"""Keyset (cursor) pagination for large listings.

Reference analog: api/pagination.py (99 LoC) — OFFSET pagination scans
and discards ``offset`` rows per page, degrading linearly; keyset
pagination seeks straight to the boundary with the composite index the
listing already uses. Cursors encode the last row's (sort timestamp,
id) as an opaque urlsafe-base64 token; id breaks timestamp ties, so
iteration is total and stable under concurrent inserts.

Timestamps here are the schema's epoch floats (db/core.py ``now()``),
not ISO datetimes — the token survives float round-tripping via
``repr``. Cursors only apply to the created_at-descending listings
(the same restriction the reference documents).
"""

from __future__ import annotations

import base64
import binascii

CURSOR_VERSION = "1"


class CursorError(ValueError):
    """Malformed or incompatible cursor token (client sends garbage)."""


def encode_cursor(ts: float, record_id: int) -> str:
    raw = f"{CURSOR_VERSION}|{ts!r}|{record_id}".encode()
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def decode_cursor(token: str) -> tuple[float, int]:
    """Returns (timestamp, id); raises CursorError on any malformation."""
    try:
        pad = "=" * (-len(token) % 4)
        raw = base64.urlsafe_b64decode(token + pad).decode()
        version, ts_s, id_s = raw.split("|")
    except (binascii.Error, UnicodeDecodeError, ValueError) as exc:
        raise CursorError("malformed cursor") from exc
    if version != CURSOR_VERSION:
        raise CursorError(f"unsupported cursor version {version!r}")
    try:
        return float(ts_s), int(id_s)
    except ValueError as exc:
        raise CursorError("malformed cursor") from exc


def keyset_clause(ts_col: str = "created_at", id_col: str = "id",
                  *, param_prefix: str = "cur") -> str:
    """WHERE fragment for a created_at-DESC, id-DESC keyset page:
    rows strictly after the cursor position. Bind ``{prefix}_ts`` and
    ``{prefix}_id``."""
    return (f"({ts_col} < :{param_prefix}_ts OR "
            f"({ts_col} = :{param_prefix}_ts AND {id_col} < :{param_prefix}_id))")


def next_cursor_from(rows: list[dict], limit: int,
                     ts_col: str = "created_at", id_col: str = "id"
                     ) -> str | None:
    """Token for the next page, or None when this page was short (the
    natural end-of-listing signal)."""
    if len(rows) < limit or not rows:
        return None
    last = rows[-1]
    return encode_cursor(float(last[ts_col]), int(last[id_col]))
