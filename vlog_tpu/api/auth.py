"""Worker API-key auth: 256-bit keys, argon2id at rest, prefix-indexed.

Reference parity: api/worker_auth.py:43-354 — keys are shown once at
registration, stored as argon2id hashes, looked up by a short indexed
prefix (so verification is one SELECT + one argon2 verify, not a table
scan), revocable, with last-used tracking. ``hash_version`` is kept in the
schema so a future hash migration can auto-rehash on use, as the
reference's v1(SHA-256)→v2(argon2id) upgrade did.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import time
from dataclasses import dataclass

from argon2 import PasswordHasher
from argon2.exceptions import VerifyMismatchError

from vlog_tpu.db.core import Database, now as db_now

KEY_PREFIX_LEN = 8
_HASHER = PasswordHasher(time_cost=2, memory_cost=65536, parallelism=1)
VERIFY_CACHE_TTL_S = 60.0
# sha256(full_key) -> (expires_monotonic, identity); bounds revocation lag
_VERIFIED_CACHE: dict[str, tuple[float, "WorkerIdentity"]] = {}


class AuthError(Exception):
    pass


@dataclass(frozen=True)
class WorkerIdentity:
    worker_name: str
    key_id: int


def generate_key() -> tuple[str, str, str]:
    """Return (full_key, prefix, secret). Key format: vlwk_<prefix><secret>."""
    prefix = secrets.token_hex(KEY_PREFIX_LEN // 2)       # 8 hex chars
    secret = secrets.token_hex(32)                        # 256-bit secret
    return f"vlwk_{prefix}{secret}", prefix, secret


async def create_worker_key(db: Database, worker_name: str) -> str:
    """Mint a key for a worker; the full key is returned exactly once."""
    full, prefix, secret = generate_key()
    await db.execute(
        """
        INSERT INTO worker_api_keys (worker_name, key_prefix, key_hash,
                                     hash_version, created_at)
        VALUES (:w, :p, :h, 2, :t)
        """,
        {"w": worker_name, "p": prefix, "h": _HASHER.hash(secret),
         "t": db_now()},
    )
    return full


def _split_key(full_key: str) -> tuple[str, str]:
    if not full_key.startswith("vlwk_") or len(full_key) < 5 + KEY_PREFIX_LEN + 8:
        raise AuthError("malformed API key")
    body = full_key[5:]
    return body[:KEY_PREFIX_LEN], body[KEY_PREFIX_LEN:]


async def verify_key(db: Database, full_key: str) -> WorkerIdentity:
    """Resolve a presented key to a worker, or raise AuthError.

    The argon2 verify runs off the event loop (it is deliberately ~100 ms
    of CPU), and successful verifications are cached for a short TTL so a
    worker streaming hundreds of segment uploads does not serialize the
    whole API behind repeated hashing. Revocation takes effect within the
    TTL window.
    """
    import asyncio

    digest = hashlib.sha256(full_key.encode()).hexdigest()
    hit = _VERIFIED_CACHE.get(digest)
    now = time.monotonic()
    if hit is not None and now < hit[0]:
        return hit[1]
    prefix, secret = _split_key(full_key)
    rows = await db.fetch_all(
        "SELECT * FROM worker_api_keys WHERE key_prefix=:p AND revoked_at IS NULL",
        {"p": prefix},
    )
    for row in rows:
        try:
            await asyncio.to_thread(_HASHER.verify, row["key_hash"], secret)
        except VerifyMismatchError:
            continue
        await db.execute(
            "UPDATE worker_api_keys SET last_used_at=:t WHERE id=:id",
            {"t": db_now(), "id": row["id"]},
        )
        ident = WorkerIdentity(worker_name=row["worker_name"],
                               key_id=row["id"])
        if len(_VERIFIED_CACHE) > 1024:
            _VERIFIED_CACHE.clear()
        _VERIFIED_CACHE[digest] = (now + VERIFY_CACHE_TTL_S, ident)
        return ident
    raise AuthError("unknown or revoked API key")


def invalidate_verify_cache() -> None:
    _VERIFIED_CACHE.clear()


async def revoke_keys(db: Database, worker_name: str) -> int:
    """Revoke every active key of a worker (reference: workers revoke
    endpoint, worker_api.py:3006). In-process verify cache is dropped
    immediately; other processes converge within VERIFY_CACHE_TTL_S."""
    _VERIFIED_CACHE.clear()
    return await db.execute(
        """
        UPDATE worker_api_keys SET revoked_at=:t
        WHERE worker_name=:w AND revoked_at IS NULL
        """,
        {"t": db_now(), "w": worker_name},
    )


def check_admin_secret(presented: str | None, expected: str) -> bool:
    """Constant-time admin-secret check; empty expected = dev mode (open)."""
    if not expected:
        return True
    return bool(presented) and hmac.compare_digest(presented, expected)
