"""Public API (:9000): browse, playback, captions, analytics.

Reference parity: api/public.py — video list/search/detail (916-1331),
transcript (1399), playback analytics session/heartbeat/end (2521-2660),
and the custom static file serving with HLS/DASH MIME types
(docs/ARCHITECTURE.md:59-62 ``HLSStaticFiles``). Read-only over the same
database the admin/worker planes write; only ready, non-deleted videos
are visible.

Run it: ``python -m vlog_tpu.api.public_api``.
"""

from __future__ import annotations

import asyncio
import logging
import secrets
from pathlib import Path

from aiohttp import web

from vlog_tpu import config
from vlog_tpu.db.core import Database, now as db_now, open_database
# MIME table lives with the delivery plane now (delivery/http.py);
# re-exported because it is part of this module's public surface.
from vlog_tpu.delivery.http import MEDIA_MIME  # noqa: F401
from vlog_tpu.jobs import videos as vids

log = logging.getLogger("vlog_tpu.public_api")

DB = web.AppKey("db", Database)
VIDEO_DIR = web.AppKey("video_dir", Path)
DELIVERY = web.AppKey("delivery", object)

_PUBLIC_VIDEO_FIELDS = ("id", "slug", "title", "description", "duration_s",
                        "width", "height", "fps", "status", "category",
                        "tags", "created_at")


def _json_error(status: int, message: str) -> web.Response:
    return web.json_response({"error": message}, status=status)


def _qnum(query, name: str, default, *, lo=None, hi=None, cast=int):
    """Parse a numeric query param; malformed input is a 400, not a 500."""
    raw = query.get(name)
    if raw is None:
        return default
    try:
        val = cast(raw)
    except (TypeError, ValueError):
        raise web.HTTPBadRequest(text=f"bad {name!r} parameter") from None
    if lo is not None:
        val = max(val, lo)
    if hi is not None:
        val = min(val, hi)
    return val


def _public_video(row: dict) -> dict:
    import json as _json

    out = {k: row[k] for k in _PUBLIC_VIDEO_FIELDS}
    out["tags"] = _json.loads(row["tags"] or "[]")
    out["stream_url"] = f"/videos/{row['slug']}/master.m3u8"
    out["dash_url"] = f"/videos/{row['slug']}/manifest.mpd"
    out["thumbnail_url"] = (f"/videos/{row['slug']}/thumbnail.jpg"
                            if row["thumbnail_path"] else None)
    out["sprites_url"] = f"/videos/{row['slug']}/sprites/sprites.vtt"
    out["captions_url"] = f"/videos/{row['slug']}/captions.vtt"
    return out


READY = "status='ready' AND deleted_at IS NULL"


async def list_videos(request: web.Request) -> web.Response:
    """Browse listing. Two pagination modes (reference pagination.py):
    classic limit/offset, and keyset via ``cursor`` (the token from a
    previous page's ``next_cursor``) — O(page) however deep, stable
    under concurrent publishes. Cursor mode ignores ``offset``."""
    from vlog_tpu.api.pagination import (
        CursorError,
        decode_cursor,
        keyset_clause,
        next_cursor_from,
    )

    db = request.app[DB]
    q = request.query
    limit = _qnum(q, "limit", 24, lo=1, hi=100)
    offset = _qnum(q, "offset", 0, lo=0)
    where = [READY]
    params: dict = {"limit": limit, "offset": offset}
    if q.get("q"):
        where.append("(title LIKE :pat OR description LIKE :pat)")
        params["pat"] = f"%{q['q']}%"
    if q.get("category"):
        where.append("category=:cat")
        params["cat"] = q["category"]
    base_where = list(where)        # total counts the whole listing,
    base_params = {k: v for k, v in params.items()
                   if k not in ("limit", "offset")}
    if q.get("cursor"):             # ...the cursor only scopes the page
        try:
            cur_ts, cur_id = decode_cursor(q["cursor"])
        except CursorError as exc:
            return _json_error(400, str(exc))
        where.append(keyset_clause("created_at", "id"))
        params.update({"cur_ts": cur_ts, "cur_id": cur_id, "offset": 0})
    rows = await db.fetch_all(
        f"""
        SELECT * FROM videos WHERE {' AND '.join(where)}
        ORDER BY created_at DESC, id DESC LIMIT :limit OFFSET :offset
        """, params)
    total = await db.fetch_val(
        f"SELECT COUNT(*) FROM videos WHERE {' AND '.join(base_where)}",
        base_params)
    return web.json_response({
        "videos": [_public_video(r) for r in rows],
        "total": total, "limit": limit, "offset": offset,
        "next_cursor": next_cursor_from(rows, limit)})


async def video_detail(request: web.Request) -> web.Response:
    db = request.app[DB]
    row = await vids.get_video_by_slug(db, request.match_info["slug"])
    if row is None or row["status"] != "ready" or row["deleted_at"]:
        return _json_error(404, "no such video")
    quals = await db.fetch_all(
        "SELECT name, width, height, video_bitrate, audio_bitrate, codec "
        "FROM video_qualities WHERE video_id=:v ORDER BY height DESC",
        {"v": row["id"]})
    chapters = await db.fetch_all(
        "SELECT start_s, title FROM chapters WHERE video_id=:v "
        "ORDER BY start_s", {"v": row["id"]})
    out = _public_video(row)
    out["qualities"] = quals
    out["chapters"] = chapters
    return web.json_response({"video": out})


async def transcript(request: web.Request) -> web.Response:
    db = request.app[DB]
    row = await vids.get_video_by_slug(db, request.match_info["slug"])
    if row is None or row["deleted_at"]:
        return _json_error(404, "no such video")
    tr = await db.fetch_one(
        "SELECT language, model, full_text, status, vtt_path "
        "FROM transcriptions WHERE video_id=:v", {"v": row["id"]})
    if tr is None or tr["status"] != "completed":
        return _json_error(404, "no transcript")
    return web.json_response({
        "language": tr["language"], "model": tr["model"],
        "text": tr["full_text"],
        "vtt_url": f"/videos/{row['slug']}/captions.vtt"})


async def categories(request: web.Request) -> web.Response:
    rows = await request.app[DB].fetch_all(
        f"""
        SELECT category, COUNT(*) AS n FROM videos
        WHERE {READY} AND category IS NOT NULL
        GROUP BY category ORDER BY n DESC
        """)
    return web.json_response({"categories": rows})


# --------------------------------------------------------------------------
# Playback analytics (public.py:2521-2660)
# --------------------------------------------------------------------------

async def start_session(request: web.Request) -> web.Response:
    db = request.app[DB]
    row = await vids.get_video_by_slug(db, request.match_info["slug"])
    if row is None:
        return _json_error(404, "no such video")
    token = secrets.token_urlsafe(24)
    t = db_now()
    await db.execute(
        """
        INSERT INTO playback_sessions (video_id, session_token, started_at,
                                       last_heartbeat_at)
        VALUES (:v, :tok, :t, :t)
        """, {"v": row["id"], "tok": token, "t": t})
    return web.json_response({"session": token}, status=201)


async def session_heartbeat(request: web.Request) -> web.Response:
    body = await request.json()
    n = await request.app[DB].execute(
        """
        UPDATE playback_sessions
        SET last_heartbeat_at=:t, watch_time_s=:w
        WHERE session_token=:tok AND ended_at IS NULL
        """,
        {"t": db_now(), "tok": str(body.get("session") or ""),
         "w": float(body.get("watch_time_s") or 0.0)})
    if not n:
        return _json_error(404, "no live session")
    return web.json_response({"ok": True})


async def end_session(request: web.Request) -> web.Response:
    body = await request.json()
    db = request.app[DB]
    n = await db.execute(
        f"""
        UPDATE playback_sessions
        SET ended_at=:t, watch_time_s={db.greatest('watch_time_s', ':w')}
        WHERE session_token=:tok AND ended_at IS NULL
        """,
        {"t": db_now(), "tok": str(body.get("session") or ""),
         "w": float(body.get("watch_time_s") or 0.0)})
    return web.json_response({"ok": True, "ended": bool(n)})


# --------------------------------------------------------------------------
# Discovery: related videos, tags, playlists (public.py:1498-1991)
# --------------------------------------------------------------------------

async def related_videos(request: web.Request) -> web.Response:
    """Same-category + shared-tag scoring, newest first within score
    (reference public.py:1498 related_videos)."""
    import json as _json

    db = request.app[DB]
    row = await vids.get_video_by_slug(db, request.match_info["slug"])
    if row is None or row["status"] != "ready" or row["deleted_at"]:
        return _json_error(404, "no such video")
    limit = _qnum(request.query, "limit", 12, lo=1, hi=50)
    tags = set(_json.loads(row["tags"] or "[]"))
    candidates = await db.fetch_all(
        f"""
        SELECT * FROM videos
        WHERE {READY} AND id != :id
        ORDER BY created_at DESC LIMIT 500
        """, {"id": row["id"]})
    scored = []
    for c in candidates:
        score = 0
        if row["category"] and c["category"] == row["category"]:
            score += 2
        score += len(tags & set(_json.loads(c["tags"] or "[]")))
        if score:
            scored.append((score, c["created_at"], c))
    scored.sort(key=lambda s: (-s[0], -s[1]))
    out = [_public_video(c) for _, _, c in scored[:limit]]
    if len(out) < limit:
        # back-fill with recency so the rail is never empty
        seen = {v["id"] for v in out} | {row["id"]}
        for c in candidates:
            if c["id"] not in seen:
                out.append(_public_video(c))
                if len(out) >= limit:
                    break
    return web.json_response({"videos": out})


async def tags(request: web.Request) -> web.Response:
    """Tag cloud: every tag on a ready video with its count
    (public.py:1636 tags browsing). Scans only the tags column of the
    newest 5000 videos — bounded work per unauthenticated request."""
    import json as _json
    from collections import Counter

    rows = await request.app[DB].fetch_all(
        f"SELECT tags FROM videos WHERE {READY} "
        "ORDER BY created_at DESC LIMIT 5000")
    counts = Counter(t for r in rows
                     for t in _json.loads(r["tags"] or "[]"))
    return web.json_response({"tags": [
        {"tag": t, "count": n} for t, n in counts.most_common(200)]})


async def videos_by_tag(request: web.Request) -> web.Response:
    import json as _json

    tag = request.match_info["tag"]
    limit = _qnum(request.query, "limit", 24, lo=1, hi=100)
    offset = _qnum(request.query, "offset", 0, lo=0)
    # SQL prefilter on the JSON text (tags are a JSON string array), then
    # exact membership in Python over a bounded candidate set
    rows = await request.app[DB].fetch_all(
        f"""
        SELECT * FROM videos WHERE {READY} AND tags LIKE :pat
        ORDER BY created_at DESC LIMIT 500
        """, {"pat": f'%"{tag}"%'})
    hits = [r for r in rows if tag in _json.loads(r["tags"] or "[]")]
    page = hits[offset:offset + limit]
    return web.json_response({
        "videos": [_public_video(r) for r in page],
        "total": len(hits), "limit": limit, "offset": offset})


async def public_playlists(request: web.Request) -> web.Response:
    rows = await request.app[DB].fetch_all(
        """
        SELECT p.slug, p.title, p.description, p.updated_at,
               COUNT(v.id) AS video_count
        FROM playlists p
        LEFT JOIN playlist_items i ON i.playlist_id = p.id
        LEFT JOIN videos v ON v.id = i.video_id
             AND v.status = 'ready' AND v.deleted_at IS NULL
        WHERE p.visibility = 'public'
        GROUP BY p.id ORDER BY p.updated_at DESC LIMIT 100
        """)
    return web.json_response({"playlists": rows})


async def public_playlist_detail(request: web.Request) -> web.Response:
    db = request.app[DB]
    row = await db.fetch_one(
        "SELECT * FROM playlists WHERE slug=:s AND visibility IN "
        "('public','unlisted')", {"s": request.match_info["plslug"]})
    if row is None:
        return _json_error(404, "no such playlist")
    items = await db.fetch_all(
        f"""
        SELECT v.* FROM playlist_items i
        JOIN videos v ON v.id = i.video_id
        WHERE i.playlist_id = :p AND {READY.replace('status', 'v.status')
                                      .replace('deleted_at', 'v.deleted_at')}
        ORDER BY i.position
        """, {"p": row["id"]})
    return web.json_response({
        "playlist": {k: row[k] for k in
                     ("slug", "title", "description", "updated_at")},
        "videos": [_public_video(v) for v in items]})


async def display_config(request: web.Request) -> web.Response:
    """Player/display knobs the SPA reads at boot (public.py:1992-2258:
    watermark + display config, served from the settings table)."""
    svc = request.app.get(SETTINGS_SVC)
    cfg = {
        "site_title": "vlog",
        "watermark": {"enabled": False, "text": "", "position":
                      "bottom-right", "opacity": 0.5},
        "player": {"autoplay": False, "default_quality": "auto",
                   "downloads_enabled": config.DOWNLOADS_ENABLED},
        "theme": {"accent": "#3b82f6"},
    }
    if svc is not None:
        for key in ("site_title",):
            v = await svc.get(f"display.{key}")
            if v is not None:
                cfg[key] = v
        for section in ("watermark", "player", "theme"):
            for k in list(cfg[section]):
                v = await svc.get(f"display.{section}.{k}")
                if v is not None:
                    cfg[section][k] = v
    return web.json_response(cfg)


SETTINGS_SVC = web.AppKey("settings_svc", object)


# --------------------------------------------------------------------------
# Media serving through the delivery plane (delivery/): publish-state
# cache + byte-bounded segment cache + single-flight + admission, with
# conditional/range/CORS semantics built from cached buffers. A steady-
# state hit performs zero DB queries and zero disk opens.
# --------------------------------------------------------------------------

def _media_error(status: int, message: str) -> web.Response:
    """Media-route errors carry CORS too: a cross-origin player must be
    able to SEE the 403/404/503, not get an opaque CORS failure."""
    from vlog_tpu.delivery.http import CORS_HEADERS

    return web.json_response({"error": message}, status=status,
                             headers=CORS_HEADERS)


async def media_preflight(request: web.Request) -> web.Response:
    from vlog_tpu.delivery import http as delivery_http

    return delivery_http.preflight_response()


async def serve_media(request: web.Request) -> web.StreamResponse:
    from vlog_tpu import delivery
    from vlog_tpu.delivery import http as delivery_http

    slug = request.match_info["slug"]
    tail = request.match_info["tail"]
    plane: delivery.DeliveryPlane = request.app[DELIVERY]
    rel = Path(tail)
    if rel.is_absolute() or ".." in rel.parts or len(rel.parts) > 4:
        return _media_error(400, "bad path")
    # Only published videos serve media: a mid-transcode tree (status
    # pending/processing) must not leak through guessable slugs. The
    # publish-state cache answers this without touching the DB.
    state = await plane.serving_state(slug)
    if state.status != "ready":
        return _media_error(404, "no such video")
    if rel.parts and rel.parts[0].startswith("original"):
        # downloads of the source are gated (reference config.py:602-616)
        if not config.DOWNLOADS_ENABLED:
            return _media_error(403, "downloads disabled")
    # a request already carrying the peer-fill header IS a peer fill
    # from another origin: answer from local tiers only, never re-enter
    # the ring (a misconfigured ring must not chase ownership in a loop)
    allow_peer = delivery.PEER_FILL_HEADER not in request.headers
    # the fill-token correlates this request with a fill already in
    # flight fleet-wide: passing it through lets the plane count the
    # coalesce (flash crowd -> one origin disk read)
    fill_token = request.headers.get(delivery.FILL_TOKEN_HEADER)
    try:
        got = await plane.fetch(slug, tail, allow_peer=allow_peer,
                                fill_token=fill_token)
    except delivery.LoadShedError as exc:
        resp = _media_error(503, "origin overloaded, retry shortly")
        resp.headers["Retry-After"] = str(exc.retry_after_s)
        return resp
    except (FileNotFoundError, delivery.MediaEscapeError):
        # a symlink escape reports like any missing file: revealing
        # "exists but refused" would leak tree shape
        return _media_error(404, "not found")
    # CacheEntry buffers from RAM; FileEntry (large-object bypass, big
    # L2 hits) streams zero-copy — one state machine for both, so all
    # four serve paths emit identical validators and bytes
    return delivery_http.entry_response(request, got)


async def delivery_gossip(request: web.Request) -> web.Response:
    """The gossip heartbeat endpoint: answering 200 from the same app
    that serves media makes 'the heartbeat answers' and 'the origin can
    serve' one fact. The response is this origin's membership snapshot
    (version + per-peer state), which the prober merges; the sender
    header marks the caller alive here in the same exchange."""
    from vlog_tpu import delivery

    plane: delivery.DeliveryPlane = request.app[DELIVERY]
    sender = request.headers.get(delivery.GOSSIP_FROM_HEADER)
    if sender:
        plane.membership.heard_from(sender)
    return web.json_response(plane.membership.snapshot())


async def metrics_endpoint(request: web.Request) -> web.Response:
    """Prometheus view of this serving process (the delivery counters
    live here, not in the admin process — scrape :9000/metrics)."""
    from vlog_tpu.obs.metrics import runtime

    return web.Response(text=runtime().render_text(),
                        content_type="text/plain")


async def healthz(request: web.Request) -> web.Response:
    return web.json_response({"ok": True, "db": request.app[DB].connected})


@web.middleware
async def error_middleware(request: web.Request, handler):
    """Unexpected exceptions become sanitized 500s (api/errors.py):
    the truth goes to the log, the client gets no paths/driver detail.
    HTTPException subclasses (the framework's own 404s etc.) pass."""
    from vlog_tpu.api.errors import sanitize_error

    try:
        return await handler(request)
    except web.HTTPException:
        raise
    except Exception as exc:   # noqa: BLE001 — boundary sanitizer
        log.exception("unhandled error rid=%s on %s %s",
                      request.get("request_id", "-"), request.method,
                      request.path)
        return _json_error(500, sanitize_error(exc))


def build_public_app(db: Database, *, video_dir: Path | None = None
                     ) -> web.Application:
    from vlog_tpu.api.settings import SettingsService
    from vlog_tpu.delivery import DeliveryPlane

    from vlog_tpu.api.errors import request_id_middleware

    app = web.Application(middlewares=[request_id_middleware,
                                       error_middleware])
    app[DB] = db
    app[VIDEO_DIR] = Path(video_dir or config.VIDEO_DIR)
    app[DELIVERY] = DeliveryPlane(db, app[VIDEO_DIR])
    app[SETTINGS_SVC] = SettingsService(db)

    async def _start_gossip(app: web.Application) -> None:
        app[DELIVERY].start_gossip()

    async def _close_delivery(app: web.Application) -> None:
        await app[DELIVERY].close()

    app.on_startup.append(_start_gossip)
    app.on_cleanup.append(_close_delivery)
    r = app.router
    r.add_get("/api/videos", list_videos)
    r.add_get("/api/videos/{slug}", video_detail)
    r.add_get("/api/videos/{slug}/transcript", transcript)
    r.add_get("/api/videos/{slug}/related", related_videos)
    r.add_get("/api/categories", categories)
    r.add_get("/api/tags", tags)
    r.add_get("/api/tags/{tag}/videos", videos_by_tag)
    r.add_get("/api/playlists", public_playlists)
    r.add_get("/api/playlists/{plslug}", public_playlist_detail)
    r.add_get("/api/config", display_config)
    r.add_post("/api/videos/{slug}/session", start_session)
    r.add_post("/api/sessions/heartbeat", session_heartbeat)
    r.add_post("/api/sessions/end", end_session)
    r.add_get("/videos/{slug}/{tail:.+}", serve_media)   # GET + HEAD
    r.add_route("OPTIONS", "/videos/{slug}/{tail:.+}", media_preflight)
    r.add_get("/api/delivery/gossip", delivery_gossip)
    r.add_get("/metrics", metrics_endpoint)
    r.add_get("/healthz", healthz)
    from vlog_tpu.web import attach_ui

    attach_ui(app, "public")
    return app


async def serve(port: int | None = None, db_url: str | None = None,
                host: str = "0.0.0.0") -> None:
    from vlog_tpu.db.schema import create_all

    config.ensure_dirs()
    db = open_database(db_url or config.DATABASE_URL)
    await db.connect()
    await create_all(db)
    app = build_public_app(db)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port or config.PUBLIC_PORT)
    await site.start()
    log.info("public API listening on %s:%d", host,
             port or config.PUBLIC_PORT)
    try:
        await asyncio.Event().wait()
    finally:
        await runner.cleanup()
        await db.disconnect()


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    asyncio.run(serve())


if __name__ == "__main__":
    main()
