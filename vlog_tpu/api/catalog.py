"""Catalog management: playlists, custom fields, thumbnails, transcripts.

Reference parity for the admin long tail VERDICT round-3 called out:

- playlists CRUD + membership/ordering (admin.py:7534-8056)
- custom metadata fields + per-video values (admin.py:6688-7533)
- thumbnail management: pick a frame time or upload an image
  (admin.py:2173-2498)
- transcript CRUD: read/replace/delete the stored transcription
  (admin.py:3568-3750)

Handlers are mounted into the admin app by
``vlog_tpu.api.admin_api.build_admin_app``; the public read side
(playlist browsing, related videos, tags) lives in public_api.py.
"""

from __future__ import annotations

import asyncio
import json
import re
from pathlib import Path

from aiohttp import web

from vlog_tpu.db.core import Database, now as db_now  # noqa: F401
# AppKeys are identity-keyed: reuse admin_api's instances (admin_api only
# imports this module inside build_admin_app, so there is no cycle)
from vlog_tpu.api.admin_api import DB, VIDEO_DIR, _path_id
from vlog_tpu.enums import JobKind, VideoStatus
from vlog_tpu.jobs import claims, qos, state as js, videos as vids


def _json_error(status: int, message: str) -> web.Response:
    return web.json_response({"error": message}, status=status)


def _slugify(title: str) -> str:
    s = re.sub(r"[^a-z0-9]+", "-", title.lower()).strip("-")
    return s or "untitled"


async def _unique_playlist_slug(db: Database, title: str) -> str:
    base = _slugify(title)
    slug, n = base, 2
    while await db.fetch_one(
            "SELECT id FROM playlists WHERE slug=:s", {"s": slug}):
        slug = f"{base}-{n}"
        n += 1
    return slug


# --------------------------------------------------------------------------
# Playlists
# --------------------------------------------------------------------------

async def list_playlists(request: web.Request) -> web.Response:
    rows = await request.app[DB].fetch_all(
        """
        SELECT p.*, COUNT(i.id) AS video_count
        FROM playlists p LEFT JOIN playlist_items i ON i.playlist_id = p.id
        GROUP BY p.id ORDER BY p.updated_at DESC
        """)
    return web.json_response({"playlists": rows})


async def create_playlist(request: web.Request) -> web.Response:
    db = request.app[DB]
    body = await request.json()
    title = str(body.get("title") or "").strip()
    if not title:
        return _json_error(400, "title required")
    visibility = body.get("visibility", "public")
    if visibility not in ("public", "unlisted", "private"):
        return _json_error(400, "bad visibility")
    t = db_now()
    pid = await db.execute(
        """
        INSERT INTO playlists (slug, title, description, visibility,
                               created_at, updated_at)
        VALUES (:s, :t, :d, :v, :now, :now)
        """,
        {"s": await _unique_playlist_slug(db, title), "t": title,
         "d": str(body.get("description") or ""), "v": visibility,
         "now": t})
    row = await db.fetch_one("SELECT * FROM playlists WHERE id=:i",
                             {"i": pid})
    return web.json_response({"playlist": row}, status=201)


async def playlist_detail(request: web.Request) -> web.Response:
    db = request.app[DB]
    pid = _path_id(request, "playlist_id")
    row = await db.fetch_one("SELECT * FROM playlists WHERE id=:i",
                             {"i": pid})
    if row is None:
        return _json_error(404, "no such playlist")
    items = await db.fetch_all(
        """
        SELECT i.position, i.added_at, v.id, v.slug, v.title, v.status,
               v.duration_s
        FROM playlist_items i JOIN videos v ON v.id = i.video_id
        WHERE i.playlist_id = :p ORDER BY i.position
        """, {"p": pid})
    return web.json_response({"playlist": row, "videos": items})


async def update_playlist(request: web.Request) -> web.Response:
    db = request.app[DB]
    pid = _path_id(request, "playlist_id")
    body = await request.json()
    sets, params = ["updated_at=:t"], {"t": db_now(), "i": pid}
    if "title" in body:
        title = str(body["title"]).strip()
        if not title:
            return _json_error(400, "title cannot be empty")
        sets.append("title=:ti")
        params["ti"] = title
    if "description" in body:
        sets.append("description=:d")
        params["d"] = str(body["description"])
    if "visibility" in body:
        if body["visibility"] not in ("public", "unlisted", "private"):
            return _json_error(400, "bad visibility")
        sets.append("visibility=:v")
        params["v"] = body["visibility"]
    n = await db.execute(
        f"UPDATE playlists SET {', '.join(sets)} WHERE id=:i", params)
    if not n:
        return _json_error(404, "no such playlist")
    return web.json_response(
        {"playlist": await db.fetch_one(
            "SELECT * FROM playlists WHERE id=:i", {"i": pid})})


async def delete_playlist(request: web.Request) -> web.Response:
    n = await request.app[DB].execute(
        "DELETE FROM playlists WHERE id=:i",
        {"i": _path_id(request, "playlist_id")})
    if not n:
        return _json_error(404, "no such playlist")
    return web.json_response({"ok": True})


async def playlist_add_video(request: web.Request) -> web.Response:
    db = request.app[DB]
    pid = _path_id(request, "playlist_id")
    body = await request.json()
    vid = body.get("video_id")
    if not isinstance(vid, int):
        return _json_error(400, "video_id (int) required")
    if await db.fetch_one("SELECT id FROM playlists WHERE id=:i",
                          {"i": pid}) is None:
        return _json_error(404, "no such playlist")
    if await db.fetch_one(
            "SELECT id FROM videos WHERE id=:v AND deleted_at IS NULL",
            {"v": vid}) is None:
        return _json_error(404, "no such video")
    t = db_now()
    async with db.transaction() as tx:
        tail = await tx.fetch_one(
            "SELECT COALESCE(MAX(position), -1) AS p FROM playlist_items "
            "WHERE playlist_id=:i", {"i": pid})
        try:
            await tx.execute(
                """
                INSERT INTO playlist_items (playlist_id, video_id,
                                            position, added_at)
                VALUES (:p, :v, :pos, :t)
                """,
                {"p": pid, "v": vid, "pos": tail["p"] + 1, "t": t})
        except Exception:  # noqa: BLE001 — UNIQUE(playlist, video)
            return _json_error(409, "video already in playlist")
        await tx.execute("UPDATE playlists SET updated_at=:t WHERE id=:i",
                         {"t": t, "i": pid})
    return web.json_response({"ok": True}, status=201)


async def playlist_remove_video(request: web.Request) -> web.Response:
    db = request.app[DB]
    pid = _path_id(request, "playlist_id")
    vid = _path_id(request, "video_id")
    n = await db.execute(
        "DELETE FROM playlist_items WHERE playlist_id=:p AND video_id=:v",
        {"p": pid, "v": vid})
    if not n:
        return _json_error(404, "video not in playlist")
    await db.execute("UPDATE playlists SET updated_at=:t WHERE id=:i",
                     {"t": db_now(), "i": pid})
    return web.json_response({"ok": True})


async def playlist_reorder(request: web.Request) -> web.Response:
    """PUT an explicit video-id order; positions are rewritten 0..n-1
    (reference admin.py reorder semantics)."""
    db = request.app[DB]
    pid = _path_id(request, "playlist_id")
    body = await request.json()
    order = body.get("video_ids")
    if (not isinstance(order, list)
            or not all(isinstance(v, int) for v in order)):
        return _json_error(400, "video_ids (list of int) required")
    if await db.fetch_one("SELECT id FROM playlists WHERE id=:i",
                          {"i": pid}) is None:
        return _json_error(404, "no such playlist")
    rows = await db.fetch_all(
        "SELECT video_id FROM playlist_items WHERE playlist_id=:p",
        {"p": pid})
    members = {r["video_id"] for r in rows}
    if members != set(order) or len(order) != len(set(order)):
        return _json_error(400, "video_ids must be a permutation of the "
                                "playlist's current members")
    async with db.transaction() as tx:
        for pos, vid in enumerate(order):
            await tx.execute(
                "UPDATE playlist_items SET position=:pos "
                "WHERE playlist_id=:p AND video_id=:v",
                {"pos": pos, "p": pid, "v": vid})
        await tx.execute("UPDATE playlists SET updated_at=:t WHERE id=:i",
                         {"t": db_now(), "i": pid})
    return web.json_response({"ok": True})


# --------------------------------------------------------------------------
# Custom fields
# --------------------------------------------------------------------------

_FIELD_TYPES = ("text", "number", "boolean", "select", "date", "url")
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]{0,63}$")


async def list_custom_fields(request: web.Request) -> web.Response:
    rows = await request.app[DB].fetch_all(
        "SELECT * FROM custom_fields ORDER BY position, id")
    for r in rows:
        r["options"] = json.loads(r["options"] or "[]")
    return web.json_response({"fields": rows})


async def create_custom_field(request: web.Request) -> web.Response:
    db = request.app[DB]
    body = await request.json()
    name = str(body.get("name") or "")
    if not _NAME_RE.match(name):
        return _json_error(400, "name must be snake_case")
    ftype = body.get("field_type", "text")
    if ftype not in _FIELD_TYPES:
        return _json_error(400, f"field_type must be one of {_FIELD_TYPES}")
    options = body.get("options") or []
    if ftype == "select" and not (
            isinstance(options, list) and options
            and all(isinstance(o, str) for o in options)):
        return _json_error(400, "select fields need a non-empty string "
                                "options list")
    if await db.fetch_one("SELECT id FROM custom_fields WHERE name=:n",
                          {"n": name}):
        return _json_error(409, "field name exists")
    fid = await db.execute(
        """
        INSERT INTO custom_fields (name, label, field_type, required,
                                   options, position, created_at)
        VALUES (:n, :l, :t, :r, :o, :p, :now)
        """,
        {"n": name, "l": str(body.get("label") or name), "t": ftype,
         "r": 1 if body.get("required") else 0,
         "o": json.dumps(options), "p": int(body.get("position") or 0),
         "now": db_now()})
    return web.json_response(
        {"field": await db.fetch_one(
            "SELECT * FROM custom_fields WHERE id=:i", {"i": fid})},
        status=201)


async def delete_custom_field(request: web.Request) -> web.Response:
    n = await request.app[DB].execute(
        "DELETE FROM custom_fields WHERE id=:i",
        {"i": _path_id(request, "field_id")})
    if not n:
        return _json_error(404, "no such field")
    return web.json_response({"ok": True})


def _validate_value(ftype: str, options: list, value) -> str | None:
    """Returns an error message, or None when the value is acceptable."""
    if value is None:
        return None
    if ftype == "number":
        try:
            float(value)
        except (TypeError, ValueError):
            return "not a number"
    elif ftype == "boolean":
        if not isinstance(value, bool) and str(value).lower() not in (
                "true", "false", "0", "1"):
            return "not a boolean"
    elif ftype == "select":
        if value not in options:
            return f"must be one of {options}"
    elif ftype == "date":
        if not re.match(r"^\d{4}-\d{2}-\d{2}$", str(value)):
            return "must be YYYY-MM-DD"
    elif ftype == "url":
        if not str(value).startswith(("http://", "https://")):
            return "must be an http(s) URL"
    return None


async def get_video_custom_values(request: web.Request) -> web.Response:
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    rows = await db.fetch_all(
        """
        SELECT f.name, f.label, f.field_type, cv.value
        FROM custom_fields f
        LEFT JOIN video_custom_values cv
               ON cv.field_id = f.id AND cv.video_id = :v
        ORDER BY f.position, f.id
        """, {"v": vid})
    return web.json_response({"values": rows})


async def put_video_custom_values(request: web.Request) -> web.Response:
    """Upsert a {field_name: value} map for one video."""
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    if await db.fetch_one("SELECT id FROM videos WHERE id=:v", {"v": vid}) \
            is None:
        return _json_error(404, "no such video")
    body = await request.json()
    if not isinstance(body, dict):
        return _json_error(400, "expected a {field: value} object")
    fields = {f["name"]: f for f in await db.fetch_all(
        "SELECT * FROM custom_fields")}
    errors = {}
    for name, value in body.items():
        f = fields.get(name)
        if f is None:
            errors[name] = "unknown field"
            continue
        err = _validate_value(f["field_type"],
                              json.loads(f["options"] or "[]"), value)
        if err:
            errors[name] = err
    if errors:
        return web.json_response({"errors": errors}, status=400)
    t = db_now()
    async with db.transaction() as tx:
        for name, value in body.items():
            f = fields[name]
            if value is None:
                await tx.execute(
                    "DELETE FROM video_custom_values "
                    "WHERE video_id=:v AND field_id=:f",
                    {"v": vid, "f": f["id"]})
                continue
            await tx.execute(
                """
                INSERT INTO video_custom_values (video_id, field_id,
                                                 value, updated_at)
                VALUES (:v, :f, :val, :t)
                ON CONFLICT (video_id, field_id)
                DO UPDATE SET value=:val, updated_at=:t
                """,
                {"v": vid, "f": f["id"], "val": json.dumps(value), "t": t})
    return web.json_response({"ok": True})


# --------------------------------------------------------------------------
# Thumbnail management (admin.py:2173-2498)
# --------------------------------------------------------------------------

async def set_thumbnail_from_time(request: web.Request) -> web.Response:
    """Re-grab the thumbnail from a timestamp of the stored source."""
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    row = await db.fetch_one("SELECT * FROM videos WHERE id=:v", {"v": vid})
    if row is None or not row["source_path"]:
        return _json_error(404, "no such video (or source dropped)")
    body = await request.json()
    try:
        at_s = float(body.get("time_s", 0.0))
    except (TypeError, ValueError):
        return _json_error(400, "bad time_s")
    src = Path(row["source_path"])
    if not src.exists():
        return _json_error(409, "source file no longer on disk")

    out_dir = request.app[VIDEO_DIR] / row["slug"]
    out_dir.mkdir(parents=True, exist_ok=True)
    dst = out_dir / "thumbnail.jpg"
    import asyncio

    def grab() -> None:
        import numpy as np

        from vlog_tpu.backends.jax_backend import JaxBackend
        from vlog_tpu.backends.source import open_source

        s = open_source(src)
        try:
            fps = (s.fps_num / s.fps_den
                   if getattr(s, "fps_den", 0) else 30.0)
            idx = max(0, min(int(at_s * fps),
                             max((s.frame_count or 1) - 1, 0)))
            for y, u, v in s.read_batches(1, idx):
                JaxBackend._write_thumbnail(
                    np.asarray(y[0]), np.asarray(u[0]), np.asarray(v[0]),
                    str(dst))
                return
            raise ValueError(f"no frame at {at_s}s")
        finally:
            s.close()

    try:
        await asyncio.to_thread(grab)
    except Exception as exc:  # noqa: BLE001 — surfaced as a 422
        return _json_error(422, f"thumbnail grab failed: {exc}")
    await db.execute(
        "UPDATE videos SET thumbnail_path=:p, updated_at=:t WHERE id=:v",
        {"p": str(dst), "t": db_now(), "v": vid})
    return web.json_response({"ok": True, "thumbnail": str(dst)})


async def get_thumbnail(request: web.Request) -> web.Response:
    """Serve the current thumbnail to the admin UI (the public plane
    serves it from the media tree; the admin plane is a different
    origin/port, so it needs its own authenticated route)."""
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    row = await db.fetch_one("SELECT * FROM videos WHERE id=:v", {"v": vid})
    if row is None or not row["thumbnail_path"]:
        return _json_error(404, "no thumbnail")
    p = Path(row["thumbnail_path"])
    if not p.is_file():
        return _json_error(404, "thumbnail file missing")
    return web.FileResponse(p, headers={
        "Content-Type": "image/jpeg", "Cache-Control": "no-cache"})


async def upload_thumbnail(request: web.Request) -> web.Response:
    """Accept a custom JPEG thumbnail body (content-type image/jpeg)."""
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    row = await db.fetch_one("SELECT * FROM videos WHERE id=:v", {"v": vid})
    if row is None:
        return _json_error(404, "no such video")
    cap = 5 * 1024 * 1024
    # reject before buffering: the app-wide client_max_size is sized for
    # video uploads, far beyond a thumbnail
    if request.content_length is not None and request.content_length > cap:
        return _json_error(413, "thumbnail too large (5 MB cap)")
    data = await request.content.read(cap + 1)
    if len(data) > cap:
        return _json_error(413, "thumbnail too large (5 MB cap)")
    if len(data) < 4 or data[:3] != b"\xff\xd8\xff":
        return _json_error(400, "body must be a JPEG image")
    from vlog_tpu.utils.fsio import atomic_write_bytes

    out_dir = request.app[VIDEO_DIR] / row["slug"]
    out_dir.mkdir(parents=True, exist_ok=True)
    dst = out_dir / "thumbnail.jpg"
    atomic_write_bytes(dst, data)
    await db.execute(
        "UPDATE videos SET thumbnail_path=:p, updated_at=:t WHERE id=:v",
        {"p": str(dst), "t": db_now(), "v": vid})
    return web.json_response({"ok": True, "thumbnail": str(dst)})


# --------------------------------------------------------------------------
# Transcript CRUD (admin.py:3568-3750)
# --------------------------------------------------------------------------

async def get_transcript_admin(request: web.Request) -> web.Response:
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    tr = await db.fetch_one(
        "SELECT * FROM transcriptions WHERE video_id=:v", {"v": vid})
    if tr is None:
        return _json_error(404, "no transcript")
    vtt = None
    if tr["vtt_path"] and Path(tr["vtt_path"]).exists():
        vtt = await asyncio.to_thread(Path(tr["vtt_path"]).read_text)
    return web.json_response({"transcript": tr, "vtt": vtt})


async def put_transcript(request: web.Request) -> web.Response:
    """Replace the transcript text/VTT (manual correction flow)."""
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    row = await db.fetch_one("SELECT * FROM videos WHERE id=:v", {"v": vid})
    if row is None:
        return _json_error(404, "no such video")
    body = await request.json()
    text = body.get("text")
    vtt = body.get("vtt")
    if not isinstance(text, str) or not text.strip():
        return _json_error(400, "text required")
    if vtt is not None and not str(vtt).startswith("WEBVTT"):
        return _json_error(400, "vtt must start with WEBVTT")
    vtt_path = None
    if vtt is not None:
        from vlog_tpu.utils.fsio import atomic_write_text

        out_dir = request.app[VIDEO_DIR] / row["slug"]
        out_dir.mkdir(parents=True, exist_ok=True)
        vtt_path = out_dir / "captions.vtt"
        atomic_write_text(vtt_path, str(vtt))
    t = db_now()
    await db.execute(
        """
        INSERT INTO transcriptions (video_id, language, model, vtt_path,
                                    full_text, status, created_at,
                                    completed_at)
        VALUES (:v, :lang, 'manual', :p, :txt, 'completed', :t, :t)
        ON CONFLICT (video_id) DO UPDATE SET
            full_text=:txt, status='completed', model='manual',
            vtt_path=COALESCE(:p, vtt_path), completed_at=:t, error=NULL
        """,
        {"v": vid, "lang": body.get("language"),
         "p": str(vtt_path) if vtt_path else None, "txt": text, "t": t})
    await db.execute(
        "UPDATE videos SET transcription_status='completed', updated_at=:t "
        "WHERE id=:v", {"t": t, "v": vid})
    return web.json_response({"ok": True})


async def delete_transcript(request: web.Request) -> web.Response:
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    n = await db.execute("DELETE FROM transcriptions WHERE video_id=:v",
                         {"v": vid})
    if not n:
        return _json_error(404, "no transcript")
    await db.execute(
        "UPDATE videos SET transcription_status='pending', updated_at=:t "
        "WHERE id=:v", {"t": db_now(), "v": vid})
    return web.json_response({"ok": True})


# --------------------------------------------------------------------------
# Bulk operations (admin.py:2883+)
# --------------------------------------------------------------------------

async def bulk_videos(request: web.Request) -> web.Response:
    """POST {action, video_ids, ...}: delete | restore | set_category."""
    db = request.app[DB]
    body = await request.json()
    ids = body.get("video_ids")
    action = body.get("action")
    if (not isinstance(ids, list) or not ids
            or not all(isinstance(i, int) for i in ids) or len(ids) > 500):
        return _json_error(400, "video_ids (1..500 ints) required")
    if action not in ("delete", "restore", "set_category", "retranscode"):
        return _json_error(400, "action must be delete | restore | "
                                "set_category | retranscode")
    t = db_now()
    done, missing = [], []
    for vid in ids:
        row = await db.fetch_one("SELECT id FROM videos WHERE id=:v",
                                 {"v": vid})
        if row is None:
            missing.append(vid)
            continue
        if action == "delete":
            await db.execute(
                "UPDATE videos SET deleted_at=:t, updated_at=:t "
                "WHERE id=:v AND deleted_at IS NULL", {"t": t, "v": vid})
        elif action == "restore":
            await db.execute(
                "UPDATE videos SET deleted_at=NULL, updated_at=:t "
                "WHERE id=:v", {"t": t, "v": vid})
        elif action == "set_category":
            await db.execute(
                "UPDATE videos SET category=:c, updated_at=:t WHERE id=:v",
                {"c": body.get("category"), "t": t, "v": vid})
        elif action == "retranscode":
            tenant = qos.normalize_tenant(body.get("tenant"))
            try:
                await claims.enqueue_job(db, vid, JobKind.TRANSCODE,
                                         force=bool(body.get("force")),
                                         tenant=tenant)
            except js.JobStateError:
                missing.append(vid)   # already queued/claimed: report it
                continue
            except qos.AdmissionError:
                # admission-capped, not lost: reported so the caller
                # retries these ids after the tenant's backlog drains
                missing.append(vid)
                continue
            await vids.set_status(db, vid, VideoStatus.PENDING)
        done.append(vid)
    return web.json_response({"ok": True, "done": done, "missing": missing})


async def get_sprites(request: web.Request) -> web.Response:
    """Sprite index for the admin preview strip: parse the WebVTT the
    sprite worker wrote (worker/sprites.py; reference sprite admin
    routes) into cue dicts the UI can lay out without a VTT parser."""
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    row = await db.fetch_one("SELECT * FROM videos WHERE id=:v", {"v": vid})
    if row is None:
        return _json_error(404, "no such video")
    vtt = (request.app[VIDEO_DIR] / row["slug"] / "sprites"
           / "sprites.vtt")
    if not vtt.is_file():
        return _json_error(404, "no sprites generated")
    cues = []
    block: list[str] = []
    text = await asyncio.to_thread(vtt.read_text)
    for line in text.splitlines() + [""]:
        if line.strip():
            block.append(line.strip())
            continue
        if len(block) >= 2 and "-->" in block[0]:
            times, target = block[0], block[1]
            a, b = [t.strip() for t in times.split("-->")]

            def secs(t):
                parts = t.split(":")
                s = float(parts[-1])
                if len(parts) > 1:
                    s += 60 * int(parts[-2])
                if len(parts) > 2:
                    s += 3600 * int(parts[-3])
                return s

            sheet, _, frag = target.partition("#xywh=")
            x, y, w, h = (int(v) for v in frag.split(",")) \
                if frag else (0, 0, 0, 0)
            cues.append({"start_s": secs(a), "end_s": secs(b),
                         "sheet": sheet, "x": x, "y": y, "w": w, "h": h})
        block = []
    return web.json_response({"cues": cues})


async def get_sprite_sheet(request: web.Request) -> web.Response:
    """Serve one sprite sheet JPEG to the admin UI (different origin
    from the public media tree, same reason as get_thumbnail)."""
    db = request.app[DB]
    vid = _path_id(request, "video_id")
    row = await db.fetch_one("SELECT * FROM videos WHERE id=:v", {"v": vid})
    if row is None:
        return _json_error(404, "no such video")
    name = request.match_info["name"]
    sdir = (request.app[VIDEO_DIR] / row["slug"] / "sprites").resolve()
    p = (sdir / name).resolve()
    # Path-boundary containment: a plain startswith() admits sibling
    # directories sharing the prefix (".../sprites-evil/x.jpg"); sheets
    # live directly in sdir, so the parent must BE sdir.
    if p.parent != sdir or p.suffix != ".jpg" or not p.is_file():
        return _json_error(404, "no such sheet")
    return web.FileResponse(p, headers={
        "Content-Type": "image/jpeg", "Cache-Control": "no-cache"})


def mount(r: web.UrlDispatcher) -> None:
    """Attach every catalog route (called by build_admin_app)."""
    r.add_get("/api/playlists", list_playlists)
    r.add_post("/api/playlists", create_playlist)
    r.add_get("/api/playlists/{playlist_id:\\d+}", playlist_detail)
    r.add_patch("/api/playlists/{playlist_id:\\d+}", update_playlist)
    r.add_delete("/api/playlists/{playlist_id:\\d+}", delete_playlist)
    r.add_post("/api/playlists/{playlist_id:\\d+}/videos",
               playlist_add_video)
    r.add_delete("/api/playlists/{playlist_id:\\d+}/videos/{video_id:\\d+}",
                 playlist_remove_video)
    r.add_put("/api/playlists/{playlist_id:\\d+}/order", playlist_reorder)
    r.add_get("/api/custom-fields", list_custom_fields)
    r.add_post("/api/custom-fields", create_custom_field)
    r.add_delete("/api/custom-fields/{field_id:\\d+}", delete_custom_field)
    r.add_get("/api/videos/{video_id:\\d+}/custom-fields",
              get_video_custom_values)
    r.add_put("/api/videos/{video_id:\\d+}/custom-fields",
              put_video_custom_values)
    r.add_post("/api/videos/{video_id:\\d+}/thumbnail/from-time",
               set_thumbnail_from_time)
    r.add_get("/api/videos/{video_id:\\d+}/thumbnail", get_thumbnail)
    r.add_put("/api/videos/{video_id:\\d+}/thumbnail", upload_thumbnail)
    r.add_get("/api/videos/{video_id:\\d+}/transcript",
              get_transcript_admin)
    r.add_put("/api/videos/{video_id:\\d+}/transcript", put_transcript)
    r.add_delete("/api/videos/{video_id:\\d+}/transcript",
                 delete_transcript)
    r.add_post("/api/videos/bulk", bulk_videos)
    r.add_get("/api/videos/{video_id:\\d+}/sprites", get_sprites)
    r.add_get("/api/videos/{video_id:\\d+}/sprites/{name}",
              get_sprite_sheet)
