"""Security audit log: append-only JSONL with size rotation.

Reference parity: api/audit.py:251 (rotating security log,
config.py:492-499) — every mutating admin request is recorded with
timestamp, method, path, result, and caller address, rotated by size so
the log is bounded. Read-only requests are not logged (noise).
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

MAX_BYTES = 10 * 1024 * 1024
KEEP_ROTATIONS = 3


class AuditLog:
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()

    def _rotate_if_needed(self) -> None:
        try:
            if self.path.stat().st_size < MAX_BYTES:
                return
        except FileNotFoundError:
            return
        for i in range(KEEP_ROTATIONS - 1, 0, -1):
            src = self.path.with_suffix(f".{i}.log")
            if src.exists():
                os.replace(src, self.path.with_suffix(f".{i + 1}.log"))
        os.replace(self.path, self.path.with_suffix(".1.log"))

    def record(self, action: str, **fields) -> None:
        entry = {"ts": round(time.time(), 3), "action": action, **fields}
        line = json.dumps(entry, separators=(",", ":")) + "\n"
        with self._lock:
            self._rotate_if_needed()
            with open(self.path, "a") as fp:
                fp.write(line)
