"""Error sanitization for API responses.

Reference analog: api/errors.py (241 LoC) — detailed errors go to logs
and the jobs table (operators need the truth); what crosses the API
boundary to clients is scrubbed of internal detail (filesystem paths,
driver/module names, stack-trace fragments) and truncated. The public
API sanitizes everything; the admin API sanitizes only 5xx bodies (an
authenticated operator gets real 4xx validation messages, but an
unexpected exception's repr still must not leak paths to a browser).
"""

from __future__ import annotations

import logging
import re

log = logging.getLogger("vlog.api.errors")

ERROR_MAX_LEN = 300

# Anything matching these marks a message as "internal" — it gets the
# generic text for its category instead of a scrubbed passthrough.
_INTERNAL_PATTERNS = [
    re.compile(p, re.I) for p in (
        r"(/[\w.\-]+){2,}",              # absolute filesystem paths
        r'File "[^"]+"',                 # traceback frames
        r"line \d+",
        r"\bsqlite3?\b",
        r"\blibpq\b|\bpostgres\b|\bsqlstate\b",
        r"\bTraceback\b",
        r"\bctypes\b|\bnumpy\b|\bjax\b",
        r"Permission denied|No such file or directory",
        r"UNIQUE constraint|FOREIGN KEY constraint",
        r"\.py:\d+",
    )
]

# category fragment -> client-safe message
_GENERIC = (
    ("decode", "The source file could not be read."),
    ("encode", "Video processing failed."),
    ("transcode", "Video processing failed."),
    ("database", "A storage error occurred. Please retry."),
    ("locked", "The service is busy. Please retry."),
    ("timeout", "The operation timed out. Please retry."),
    ("connect", "A backend service is unreachable."),
)
_FALLBACK = "An internal error occurred."


def sanitize_error(message: str | BaseException,
                   *, max_len: int = ERROR_MAX_LEN) -> str:
    """Client-safe rendering of an error: internal details replaced by
    a generic category message, everything truncated."""
    msg = str(message) or _FALLBACK
    if any(p.search(msg) for p in _INTERNAL_PATTERNS):
        low = msg.lower()
        for frag, generic in _GENERIC:
            if frag in low:
                return generic
        return _FALLBACK
    if len(msg) > max_len:
        return msg[: max_len - 1] + "…"
    return msg


def public_job_error(error: str | None) -> str | None:
    """What the public API may say about a failed job."""
    if not error:
        return None
    return sanitize_error(error)


# --------------------------------------------------------------------------
# Request-ID tracing (reference common.py X-Request-ID middleware):
# every response carries an id — caller-supplied when sane, minted
# otherwise — and unhandled errors log it, so a user-reported failure
# can be joined to its log line across all three API planes.
# --------------------------------------------------------------------------

_REQ_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")
# trace/span ids are hex (obs/trace.py new_id); anything else is junk
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,64}$")

# imported lazily at module top keeps errors.py usable without aiohttp?
# no — every consumer is an aiohttp app; import plainly.
import uuid as _uuid  # noqa: E402

from aiohttp import web as _web  # noqa: E402


@_web.middleware
async def request_id_middleware(request, handler):
    """Outermost middleware on every plane: every response (including
    framework HTTPExceptions and unhandled 500s) carries X-Request-ID,
    and an unhandled exception that reaches this tier is converted to a
    sanitized 500 WITH the id — so the one response class where log
    correlation matters most never ships without it.  Planes with their
    own error middleware log rid themselves (they sit inside this one
    and catch first)."""
    rid = request.headers.get("X-Request-ID", "")
    if not _REQ_ID_RE.match(rid):
        rid = _uuid.uuid4().hex[:16]
    request["request_id"] = rid
    # Trace propagation (obs/trace.py): honor caller-supplied trace
    # context so a worker's HTTP hop joins the job's trace — handlers
    # read request["trace_id"] / request["parent_span_id"] when they
    # record server-side spans, and every response echoes the trace id
    # so either end of the hop can be joined to the waterfall.
    tid = (request.headers.get("X-Trace-Id") or "").strip().lower()
    pid = (request.headers.get("X-Parent-Span") or "").strip().lower()
    request["trace_id"] = tid if _TRACE_ID_RE.match(tid) else None
    request["parent_span_id"] = pid if _TRACE_ID_RE.match(pid) else None
    try:
        resp = await handler(request)
    except _web.HTTPException as exc:
        exc.headers["X-Request-ID"] = rid
        if request["trace_id"]:
            exc.headers["X-Trace-Id"] = request["trace_id"]
        raise
    except Exception as exc:  # noqa: BLE001 — boundary conversion
        log.exception("unhandled error rid=%s %s %s", rid,
                      request.method, request.path)
        resp = _web.json_response(
            {"error": sanitize_error(exc)}, status=500)
    resp.headers["X-Request-ID"] = rid
    if request["trace_id"]:
        resp.headers["X-Trace-Id"] = request["trace_id"]
    return resp
