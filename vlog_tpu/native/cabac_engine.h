/* Shared CABAC arithmetic engine (H.264 9.3.4 == H.265 9.3.4).
 *
 * Used by hevc_cabac.c and h264_cabac_enc.c; the range/transition
 * tables come from the HEVC generated header (they are the same
 * normative tables in both standards). Context-count is the max of the
 * two standards (H.264's 1024); callers initialize only their range.
 */
#ifndef VT_CABAC_ENGINE_H
#define VT_CABAC_ENGINE_H

#include <stdint.h>
#include <string.h>

typedef struct {
    uint32_t low, range;
    int outstanding, first_bit;
    uint8_t *out;
    int64_t cap, nbytes;
    int cur, nbits;
    int overflow;
    uint8_t pstate[1024], mps[1024];
} Cabac;

static void cab_emit(Cabac *c, int bit) {
    c->cur = (c->cur << 1) | bit;
    if (++c->nbits == 8) {
        if (c->nbytes < c->cap) c->out[c->nbytes++] = (uint8_t)c->cur;
        else c->overflow = 1;
        c->cur = 0; c->nbits = 0;
    }
}

static void cab_put_bit(Cabac *c, int bit) {
    if (c->first_bit) c->first_bit = 0;
    else cab_emit(c, bit);
    while (c->outstanding > 0) { cab_emit(c, 1 - bit); c->outstanding--; }
}

static void cab_renorm(Cabac *c) {
    while (c->range < 256) {
        if (c->low >= 512) { cab_put_bit(c, 1); c->low -= 512; }
        else if (c->low < 256) cab_put_bit(c, 0);
        else { c->outstanding++; c->low -= 256; }
        c->low <<= 1; c->range <<= 1;
    }
}

static void cab_start(Cabac *c, uint8_t *out, int64_t cap) {
    c->low = 0; c->range = 510;
    c->outstanding = 0; c->first_bit = 1;
    c->out = out; c->cap = cap; c->nbytes = 0;
    c->cur = 0; c->nbits = 0; c->overflow = 0;
}

/* tables provided by the including .c file's generated header */
static void cab_bin(Cabac *c, int ctx, int bin) {
    int p = c->pstate[ctx];
    uint32_t rlps = HEVC_LPS[p * 4 + ((c->range >> 6) & 3)];
    c->range -= rlps;
    if (bin != c->mps[ctx]) {
        c->low += c->range; c->range = rlps;
        if (p == 0) c->mps[ctx] ^= 1;
        c->pstate[ctx] = HEVC_LPS_NEXT[p];
    } else {
        c->pstate[ctx] = HEVC_MPS_NEXT[p];
    }
    cab_renorm(c);
}

static void cab_bypass(Cabac *c, int bin) {
    c->low <<= 1;
    if (bin) c->low += c->range;
    if (c->low >= 1024) { cab_put_bit(c, 1); c->low -= 1024; }
    else if (c->low < 512) cab_put_bit(c, 0);
    else { c->outstanding++; c->low -= 512; }
}

static void cab_bypass_bits(Cabac *c, uint32_t v, int width) {
    for (int i = width - 1; i >= 0; i--) cab_bypass(c, (v >> i) & 1);
}

static void cab_terminate(Cabac *c, int bin) {
    c->range -= 2;
    if (bin) {
        c->low += c->range; c->range = 2;
        cab_renorm(c);
        cab_put_bit(c, (c->low >> 9) & 1);
        cab_emit(c, (c->low >> 8) & 1);
        cab_emit(c, 1);                  /* rbsp stop bit */
    } else {
        cab_renorm(c);
    }
}

static int64_t cab_finish(Cabac *c) {
    if (c->nbits) {
        if (c->nbytes < c->cap)
            c->out[c->nbytes++] = (uint8_t)(c->cur << (8 - c->nbits));
        else c->overflow = 1;
        c->cur = 0; c->nbits = 0;
    }
    return c->overflow ? -1 : c->nbytes;
}

/* k-th order Exp-Golomb in bypass (suffixes of UEG0/UEG3 and HEVC
 * mvd/coeff escapes share this shape) */
static void cab_eg_bypass(Cabac *c, int value, int k) {
    while (value >= (1 << k)) { cab_bypass(c, 1); value -= 1 << k; k++; }
    cab_bypass(c, 0);
    for (int i = k - 1; i >= 0; i--) cab_bypass(c, (value >> i) & 1);
}

#endif /* VT_CABAC_ENGINE_H */
