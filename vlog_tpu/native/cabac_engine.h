/* Shared CABAC arithmetic engine (H.264 9.3.4 == H.265 9.3.4).
 *
 * Used by hevc_cabac.c and h264_cabac_enc.c; the range/transition
 * tables come from the HEVC generated header (they are the same
 * normative tables in both standards). Context-count is the max of the
 * two standards (H.264's 1024); callers initialize only their range.
 *
 * Output scheme: instead of the spec's put_bit/outstanding-bits
 * bookkeeping (a function-call chain per output bit), finished bits
 * accumulate in `low` ABOVE the 10-bit arithmetic window (`queue` of
 * them, oldest highest) and drain a byte at a time. Carries from
 * low+=range ripple into the accumulated bits natively via integer
 * addition; a carry that must ripple into bytes already drained is
 * handled the standard way: the last finalized byte is held back, and
 * a run-length of 0xFF bytes (the only values a carry can pass
 * through) flips to 0x00 when one arrives. Renormalization shifts in
 * one clz step instead of a bit loop. `queue` starts at -1: the spec's
 * discarded first output bit then occupies the same position a
 * carry-out-of-stream would (bit 8 of a drained chunk), which valid
 * arithmetic coding never sets — so no special case. The emitted
 * bitstream is IDENTICAL to the spec formulation (test_h264_cabac.py /
 * test_hevc.py assert bit-exactness against the Python reference and
 * the libavcodec oracle); only the bookkeeping differs. This is the
 * production host entropy stage's hot loop — the bit-at-a-time
 * formulation it replaces was ~4x slower.
 */
#ifndef VT_CABAC_ENGINE_H
#define VT_CABAC_ENGINE_H

#include <stdint.h>
#include <string.h>

typedef struct {
    uint32_t low, range;
    int queue;            /* finished output bits held in low above bit 9
                             (-1 until the discarded first bit exists) */
    int64_t n_ff;         /* run of 0xFF bytes awaiting carry resolution */
    int pending;          /* last finalized byte not yet written (-1: none) */
    uint8_t *out;
    int64_t cap, nbytes;
    int overflow;
    uint8_t pstate[1024], mps[1024];
} Cabac;

static void cab_start(Cabac *c, uint8_t *out, int64_t cap) {
    c->low = 0; c->range = 510;
    c->queue = -1; c->n_ff = 0; c->pending = -1;
    c->out = out; c->cap = cap; c->nbytes = 0;
    c->overflow = 0;
}

static void cab_write1(Cabac *c, uint8_t b) {
    if (c->nbytes < c->cap) c->out[c->nbytes++] = b;
    else c->overflow = 1;
}

/* Finalize one 8-bit chunk; bit 8 is a carry into already-drained
 * bytes (or, on the very first chunk, the spec-discarded first bit,
 * which is always 0 there). */
static void cab_emit8(Cabac *c, uint32_t out9) {
    uint32_t carry = out9 >> 8, data = out9 & 0xFF;
    if (carry) {
        /* ripple: held byte +1, held 0xFFs wrap to 0x00, all final */
        if (c->pending >= 0) cab_write1(c, (uint8_t)(c->pending + 1));
        for (; c->n_ff > 0; c->n_ff--) cab_write1(c, 0x00);
        c->pending = (int)data;
    } else if (data == 0xFF) {
        c->n_ff++;               /* a future carry may still flip it */
    } else {
        if (c->pending >= 0) cab_write1(c, (uint8_t)c->pending);
        for (; c->n_ff > 0; c->n_ff--) cab_write1(c, 0xFF);
        c->pending = (int)data;
    }
}

static void cab_drain(Cabac *c) {
    while (c->queue >= 8) {
        int sh = c->queue + 2;   /* top 8 output bits + carry above them */
        cab_emit8(c, c->low >> sh);
        c->low &= (1u << sh) - 1;
        c->queue -= 8;
    }
}

/* tables provided by the including .c file's generated header */
static void cab_bin(Cabac *c, int ctx, int bin) {
    int p = c->pstate[ctx];
    uint32_t rlps = HEVC_LPS[p * 4 + ((c->range >> 6) & 3)];
    c->range -= rlps;
    if (bin != c->mps[ctx]) {
        c->low += c->range; c->range = rlps;
        if (p == 0) c->mps[ctx] ^= 1;
        c->pstate[ctx] = HEVC_LPS_NEXT[p];
    } else {
        c->pstate[ctx] = HEVC_MPS_NEXT[p];
    }
    /* renorm to range >= 256 in one shift (range >= 2 always) */
    int sh = __builtin_clz(c->range) - 23;
    if (sh > 0) {
        c->range <<= sh; c->low <<= sh;
        if ((c->queue += sh) >= 8) cab_drain(c);
    }
}

static void cab_bypass(Cabac *c, int bin) {
    c->low <<= 1;
    if (bin) c->low += c->range;
    if (++c->queue >= 8) cab_drain(c);
}

/* k finished bypass bits at once: per-bit low'=2*low+b*range
 * telescopes to low<<k + v*range (range is invariant in bypass). */
static void cab_bypass_bits(Cabac *c, uint32_t v, int width) {
    while (width > 8) {
        width -= 8;
        c->low = (c->low << 8) + ((v >> width) & 0xFF) * c->range;
        c->queue += 8;
        cab_drain(c);
    }
    if (width > 0) {
        c->low = (c->low << width) + (v & ((1u << width) - 1)) * c->range;
        if ((c->queue += width) >= 8) cab_drain(c);
    }
}

static void cab_terminate(Cabac *c, int bin) {
    c->range -= 2;
    if (bin) {
        c->low += c->range; c->range = 2;
        /* renorm (shift 7), then the spec flush: the window's top two
         * bits become output, then the rbsp stop bit (literal 1) */
        c->low <<= 7; c->queue += 7;
        c->low <<= 2; c->queue += 2;
        c->low <<= 1; c->queue += 1;
        c->low = (c->low & ~0x3FFu & ~(1u << 10)) | (1u << 10);
        cab_drain(c);
    } else {
        int sh = __builtin_clz(c->range) - 23;
        if (sh > 0) {
            c->range <<= sh; c->low <<= sh;
            if ((c->queue += sh) >= 8) cab_drain(c);
        }
    }
}

static int64_t cab_finish(Cabac *c) {
    /* zero-pad to a byte boundary, drain, then flush held bytes
     * (no carries can arrive after the stop bit) */
    int pad = (8 - (c->queue & 7)) & 7;
    if (pad) { c->low <<= pad; c->queue += pad; }
    cab_drain(c);
    if (c->pending >= 0) cab_write1(c, (uint8_t)c->pending);
    for (; c->n_ff > 0; c->n_ff--) cab_write1(c, 0xFF);
    c->pending = -1;
    return c->overflow ? -1 : c->nbytes;
}

/* k-th order Exp-Golomb in bypass (suffixes of UEG0/UEG3 and HEVC
 * mvd/coeff escapes share this shape) */
static void cab_eg_bypass(Cabac *c, int value, int k) {
    while (value >= (1 << k)) { cab_bypass(c, 1); value -= 1 << k; k++; }
    cab_bypass(c, 0);
    if (k > 0) cab_bypass_bits(c, (uint32_t)value, k);
}

#endif /* VT_CABAC_ENGINE_H */
