/* Baseline-JPEG scan entropy packer (T.81 F.1.2): Huffman DC/AC coding of
 * interleaved, zigzagged, quantized blocks with 0xFF byte stuffing.
 *
 * Replaces the pure-Python _BitPacker hot loop in codecs/jpeg/encoder.py,
 * which profiled at ~97 s for ONE 720p thumbnail (1.45M put() calls) and
 * made sprite sheets unusable. Bit-exact against the Python path
 * (tests/test_native.py); called via ctypes so the GIL is released.
 *
 * Table layout: codes[256]/lens[256] indexed by symbol (DC: size
 * category 0..11; AC: (run<<4)|size, 0x00=EOB, 0xF0=ZRL). lens==0 marks
 * an absent symbol (never emitted by conforming block data).
 */

#include <stdint.h>
#include <stdlib.h>

typedef struct {
    uint8_t *out;
    int64_t cap;
    int64_t pos;
    uint64_t acc;
    int nbits;
    int overflow;
} jbits;

static inline void jb_put(jbits *b, uint32_t code, int len) {
    if (len <= 0) return;
    b->acc = (b->acc << len) | (uint64_t)(code & ((1u << len) - 1u));
    b->nbits += len;
    while (b->nbits >= 8) {
        b->nbits -= 8;
        uint8_t byte = (uint8_t)((b->acc >> b->nbits) & 0xFF);
        if (b->pos + 2 > b->cap) { b->overflow = 1; return; }
        b->out[b->pos++] = byte;
        if (byte == 0xFF) b->out[b->pos++] = 0x00;
    }
}

static inline void jb_flush(jbits *b) {
    if (b->nbits) {
        int pad = 8 - b->nbits;
        jb_put(b, (1u << pad) - 1u, pad);   /* pad with 1s */
    }
}

/* size category + offset code, T.81 F.1.2.1 */
static inline void jmagnitude(int32_t v, int *size, uint32_t *code) {
    if (v == 0) { *size = 0; *code = 0; return; }
    uint32_t a = (uint32_t)(v < 0 ? -v : v);
    int s = 32 - __builtin_clz(a);
    *size = s;
    *code = (uint32_t)(v > 0 ? v : v + (1 << s) - 1);
}

extern "C" int64_t vt_jpeg_pack_scan(
    const int32_t *blocks,      /* (n_blocks, 64) zigzag, MCU-interleaved */
    const uint8_t *comp,        /* per block: 0=Y, 1=Cb, 2=Cr */
    int64_t n_blocks,
    const uint16_t *dc_codes_l, const uint8_t *dc_lens_l,
    const uint16_t *ac_codes_l, const uint8_t *ac_lens_l,
    const uint16_t *dc_codes_c, const uint8_t *dc_lens_c,
    const uint16_t *ac_codes_c, const uint8_t *ac_lens_c,
    uint8_t *out, int64_t cap)
{
    jbits b = { out, cap, 0, 0, 0, 0 };
    int32_t pred[3] = { 0, 0, 0 };
    for (int64_t bi = 0; bi < n_blocks; bi++) {
        const int32_t *zz = blocks + bi * 64;
        int c = comp[bi];
        const uint16_t *dc_codes = c ? dc_codes_c : dc_codes_l;
        const uint8_t  *dc_lens  = c ? dc_lens_c  : dc_lens_l;
        const uint16_t *ac_codes = c ? ac_codes_c : ac_codes_l;
        const uint8_t  *ac_lens  = c ? ac_lens_c  : ac_lens_l;

        int size; uint32_t code;
        int32_t dc = zz[0];
        jmagnitude(dc - pred[c], &size, &code);
        pred[c] = dc;
        jb_put(&b, dc_codes[size], dc_lens[size]);
        if (size) jb_put(&b, code, size);

        int last_nz = 0;
        for (int i = 63; i >= 1; i--) {
            if (zz[i] != 0) { last_nz = i; break; }
        }
        int run = 0;
        for (int i = 1; i <= last_nz; i++) {
            int32_t v = zz[i];
            if (v == 0) { run++; continue; }
            while (run > 15) {
                jb_put(&b, ac_codes[0xF0], ac_lens[0xF0]);  /* ZRL */
                run -= 16;
            }
            jmagnitude(v, &size, &code);
            int sym = (run << 4) | size;
            jb_put(&b, ac_codes[sym], ac_lens[sym]);
            jb_put(&b, code, size);
            run = 0;
        }
        if (last_nz < 63)
            jb_put(&b, ac_codes[0x00], ac_lens[0x00]);      /* EOB */
        if (b.overflow) return -1;
    }
    jb_flush(&b);
    return b.overflow ? -1 : b.pos;
}
