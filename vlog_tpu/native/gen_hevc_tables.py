"""Regenerate vlog_tpu/codecs/hevc/tables.py from the system libavcodec.

Same provenance policy as gen_tables.py (H.264 CAVLC) and
gen_aac_tables.py: the CABAC arithmetic tables (rangeTabLPS, state
transitions — ITU-T H.265 tables 9-46/9-47, byte-identical to H.264's
9-44/9-45), the 597 context initValues (H.265 tables 9-5..9-32,
initType-major ``[3][199]``), and the diagonal scan orders (H.265
6.5.3) are *normative constants* — every conforming codec embeds the
same numbers. Rather than hand-transcribing ~2200 values (a silent
bitstream corruption waiting to happen), this script extracts them
from the system libavcodec static archive and emits Python with the
provenance recorded.

Two extraction mechanisms:

- Exported symbols (``ff_h264_cabac_tables``, ``ff_hevc_diag_scan*``):
  compile a small dumper against the archive, as gen_aac_tables.py does.
- ``init_values`` is a *local* rodata symbol of hevc_cabac.o: extract
  the member with ``ar``, locate offset+size with ``nm -S``, slice the
  ``.rodata`` section dumped by ``objcopy``.

The per-element context offsets (CTX_OFF in the generated module) were
measured once from the disassembly of the exported
``ff_hevc_*_decode`` functions of the same hevc_cabac.o (the immediate
added to the context-state base pointer), cross-checked against each
other: sao_merge=0, split_cu=2, part_mode=13, prev_intra_luma=17,
intra_chroma=18, merge_flag=20, mvp_lx=35, no_residual=36,
cbf_cb_cr=42, sig_coeff=93, greater2=161, log2_res_scale=167,
res_scale_sign=175, cu_chroma_qp_offset=177.  The arithmetic-gap
elements between anchors follow ITU-T H.265 context counts.

Usage: python -m vlog_tpu.native.gen_hevc_tables  (rewrites tables.py)
"""

from __future__ import annotations

import subprocess
import tempfile
from pathlib import Path

_ARCHIVE = "/usr/lib/x86_64-linux-gnu/libavcodec.a"
_OUT = Path(__file__).resolve().parent.parent / "codecs" / "hevc" / "tables.py"

_DUMP_C = r"""
#include <stdio.h>
#include <stdint.h>

extern const uint8_t ff_h264_cabac_tables[512 + 4*2*64 + 2*128 + 64];
extern const uint8_t ff_hevc_diag_scan4x4_x[16];
extern const uint8_t ff_hevc_diag_scan4x4_y[16];
extern const uint8_t ff_hevc_diag_scan8x8_x[64];
extern const uint8_t ff_hevc_diag_scan8x8_y[64];

int main(void) {
    int i;
    /* layout per libavcodec/cabac.h: norm_shift @0 (512),
       lps_range @512 (4 qidx blocks x 128 packed states),
       mlps_state @1024 (256), h264 last_coeff @1280 (unused here) */
    printf("LPS_RANGE = [");
    for (i = 512; i < 1024; i++) printf("%d, ", ff_h264_cabac_tables[i]);
    printf("]\n\nMLPS_STATE = [");
    for (i = 1024; i < 1280; i++) printf("%d, ", ff_h264_cabac_tables[i]);
    printf("]\n\nDIAG4X4 = [");
    for (i = 0; i < 16; i++)
        printf("(%d, %d), ", ff_hevc_diag_scan4x4_x[i],
               ff_hevc_diag_scan4x4_y[i]);
    printf("]\n\nDIAG8X8 = [");
    for (i = 0; i < 64; i++)
        printf("(%d, %d), ", ff_hevc_diag_scan8x8_x[i],
               ff_hevc_diag_scan8x8_y[i]);
    printf("]\n");
    return 0;
}
"""


def _dump_exported() -> dict:
    with tempfile.TemporaryDirectory() as td:
        src = Path(td) / "dump.c"
        src.write_text(_DUMP_C)
        exe = Path(td) / "dump"
        subprocess.run(
            ["cc", "-O1", str(src), _ARCHIVE, "-o", str(exe)], check=True)
        out = subprocess.run([str(exe)], capture_output=True, text=True,
                             check=True).stdout
    ns: dict = {}
    exec(out, ns)  # noqa: S102 - output of our own dumper
    return ns


def _extract_init_values() -> list[int]:
    """Slice the local ``init_values`` array out of hevc_cabac.o."""
    with tempfile.TemporaryDirectory() as td:
        subprocess.run(["ar", "x", _ARCHIVE, "hevc_cabac.o"], cwd=td,
                       check=True)
        obj = Path(td) / "hevc_cabac.o"
        nm = subprocess.run(["nm", "-S", str(obj)], capture_output=True,
                            text=True, check=True).stdout
        off = size = None
        for line in nm.splitlines():
            parts = line.split()
            if len(parts) == 4 and parts[3] == "init_values":
                off, size = int(parts[0], 16), int(parts[1], 16)
        if off is None:
            raise RuntimeError("init_values symbol not found")
        rod = Path(td) / "rodata.bin"
        subprocess.run(["objcopy", "-O", "binary",
                        "--only-section=.rodata", str(obj), str(rod)],
                       check=True)
        blob = rod.read_bytes()[off:off + size]
    if len(blob) != 3 * 199:
        raise RuntimeError(f"init_values size {len(blob)} != 597")
    return list(blob)


def _spec_tables(ns: dict) -> tuple[list, list, list]:
    """Decode libavcodec's packed-state layout into the spec-shaped
    rangeTabLPS[64][4], transIdxMPS[64], transIdxLPS[64]."""
    lps = ns["LPS_RANGE"]
    mlps = ns["MLPS_STATE"]
    range_tab = []
    for p in range(64):
        row = [lps[q * 128 + 2 * p] for q in range(4)]
        for q in range(4):  # mps bit must not matter
            assert lps[q * 128 + 2 * p + 1] == row[q]
        range_tab.append(row)
    # packed state s2 = (pStateIdx<<1)|valMps.  MPS path: mlps[128+s2];
    # LPS path: mlps[127-s2] (valMps flip at p=0 is encoded in s2).
    trans_mps = [mlps[128 + (p << 1)] >> 1 for p in range(64)]
    trans_lps = []
    for p in range(64):
        s2 = mlps[127 - (p << 1)]      # from packed state (p, mps=0)
        trans_lps.append(s2 >> 1)
    # sanity: spec 9-47 invariants
    assert trans_mps[:4] == [1, 2, 3, 4] and trans_mps[62] == 62
    assert trans_lps[0] == 0
    return range_tab, trans_mps, trans_lps


# -- context layout: element -> (offset, count), measured + spec counts --
_CTX = {
    "SAO_MERGE": (0, 1), "SAO_TYPE_IDX": (1, 1),
    "SPLIT_CU": (2, 3), "CU_TRANSQUANT_BYPASS": (5, 1),
    "SKIP": (6, 3), "CU_QP_DELTA": (9, 3), "PRED_MODE": (12, 1),
    "PART_MODE": (13, 4), "PREV_INTRA_LUMA": (17, 1),
    "INTRA_CHROMA_PRED": (18, 2), "MERGE_FLAG": (20, 1),
    "MERGE_IDX": (21, 1), "INTER_PRED_IDC": (22, 5),
    "REF_IDX": (27, 4), "MVD_GREATER": (31, 4),
    "MVP_LX": (35, 1), "NO_RESIDUAL": (36, 1),
    "SPLIT_TRANSFORM": (37, 3), "CBF_LUMA": (40, 2),
    "CBF_CB_CR": (42, 5), "TRANSFORM_SKIP": (47, 2),
    "RDPCM": (49, 4),
    "LAST_X_PREFIX": (53, 18), "LAST_Y_PREFIX": (71, 18),
    "SIG_CG_FLAG": (89, 4), "SIG_COEFF": (93, 44),
    "GREATER1": (137, 24), "GREATER2": (161, 6),
    "LOG2_RES_SCALE": (167, 8), "RES_SCALE_SIGN": (175, 2),
    "CU_CHROMA_QP_OFFSET": (177, 2),
}


def generate() -> str:
    ns = _dump_exported()
    init_values = _extract_init_values()
    range_tab, trans_mps, trans_lps = _spec_tables(ns)
    for name, (off, n) in _CTX.items():
        assert 0 <= off and off + n <= 199, name

    lines = [
        '"""HEVC normative tables — generated by '
        "vlog_tpu/native/gen_hevc_tables.py; do not edit.\n",
        "\nExtracted from the system libavcodec static archive "
        f"({_ARCHIVE}):\n"
        "CABAC arithmetic tables (ITU-T H.265 9-46/9-47, shared with "
        "H.264) from\nthe exported ff_h264_cabac_tables; context "
        "initValues (H.265 9-5..9-32,\n[3 initTypes][199 contexts]) "
        "from hevc_cabac.o's rodata; diagonal scans\n(H.265 6.5.3) "
        "from ff_hevc_diag_scan*.  Context offsets measured from "
        "the\ndisassembled ff_hevc_*_decode functions — see the "
        'generator docstring.\n"""\n\n',
        "# rangeTabLPS[pStateIdx][qRangeIdx] (H.265 table 9-46)\n",
        f"RANGE_TAB_LPS = {range_tab!r}\n\n",
        "# state transitions (H.265 table 9-47)\n",
        f"TRANS_IDX_MPS = {trans_mps!r}\n",
        f"TRANS_IDX_LPS = {trans_lps!r}\n\n",
        "# initValue[initType][ctxIdx]; I slices use initType 0\n",
        "INIT_VALUES = [\n",
    ]
    for t in range(3):
        lines.append(f"    {init_values[t * 199:(t + 1) * 199]!r},\n")
    lines.append("]\n\n# ctx-state offsets: element -> (offset, count)\n")
    lines.append("CTX_OFF = {\n")
    for name, (off, n) in _CTX.items():
        lines.append(f"    {name!r}: ({off}, {n}),\n")
    lines.append("}\n\n")
    lines.append("# up-right diagonal scans (x, y) (H.265 6.5.3)\n")
    lines.append(f"DIAG_SCAN_4x4 = {ns['DIAG4X4']!r}\n")
    lines.append(f"DIAG_SCAN_8x8 = {ns['DIAG8X8']!r}\n")
    return "".join(lines)


def generate_c_header() -> str:
    """C include for native/hevc_cabac.c, from the generated tables.py
    (single source of truth, same policy as gen_tables.py)."""
    from vlog_tpu.codecs.hevc import tables as t

    def arr(name, vals, ctype="uint8_t"):
        flat = ", ".join(str(int(v)) for v in vals)
        return f"static const {ctype} {name}[{len(vals)}] = {{{flat}}};\n"

    lps = [v for row in t.RANGE_TAB_LPS for v in row]
    scan4 = [x * 16 + y for (x, y) in t.DIAG_SCAN_4x4]   # packed x<<4|y
    scan8 = [x * 16 + y for (x, y) in t.DIAG_SCAN_8x8]

    # whole-TB forward scans precomputed here (constant data, so the C
    # coder needs no lazy init — and therefore no thread-safety hazard
    # when the entropy pool fans out)
    def tb_scan(n):
        cg = t.DIAG_SCAN_8x8 if n == 32 else t.DIAG_SCAN_4x4
        out = []
        for cx, cy in cg[: (n // 4) ** 2]:
            for ix, iy in t.DIAG_SCAN_4x4:
                out.append((cy * 4 + iy) * n + (cx * 4 + ix))
        return out

    parts = [
        "/* Generated by vlog_tpu/native/gen_hevc_tables.py — do not "
        "edit. */\n#include <stdint.h>\n",
        arr("HEVC_LPS", lps), arr("HEVC_MPS_NEXT", t.TRANS_IDX_MPS),
        arr("HEVC_LPS_NEXT", t.TRANS_IDX_LPS),
        arr("HEVC_INIT_I", t.INIT_VALUES[0]),
        arr("HEVC_INIT_P", t.INIT_VALUES[1]),
        arr("HEVC_DIAG4", scan4), arr("HEVC_DIAG8", scan8),
        arr("HEVC_SCAN32", tb_scan(32), "int16_t"),
        arr("HEVC_SCAN16", tb_scan(16), "int16_t"),
    ]
    for name, (off, n) in _CTX.items():
        parts.append(f"#define HEVC_CTX_{name} {off}\n")
    return "".join(parts)


if __name__ == "__main__":
    _OUT.write_text(generate())
    print(f"wrote {_OUT}")
