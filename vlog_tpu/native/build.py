"""Build + load the native entropy-coding library (ctypes).

Compiled on demand with the system toolchain into
``vlog_tpu/native/_build/`` and cached by source mtime. No pip/pybind11
required (environment constraint); pure C ABI via ctypes. All entry
points release the GIL for the duration of the call (ctypes semantics),
so the worker's per-frame thread pool scales across cores.

Disable with VLOG_NATIVE=0 (callers fall back to the Python coders).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_DIR = Path(__file__).parent
_BUILD = _DIR / "_build"
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


class NativeBuildError(RuntimeError):
    pass


def _compile() -> Path:
    _BUILD.mkdir(exist_ok=True)
    src = _DIR / "cavlc.c"
    jpeg_src = _DIR / "jpeg_pack.c"
    hevc_src = _DIR / "hevc_cabac.c"
    h264c_src = _DIR / "h264_cabac_enc.c"
    engine_hdr = _DIR / "cabac_engine.h"
    so = _BUILD / "libvtnative.so"
    from vlog_tpu.codecs.h264 import cabac_ctx_tables, cavlc_tables
    from vlog_tpu.codecs.hevc import tables as hevc_tables

    stamp_inputs = [src, jpeg_src, hevc_src, h264c_src, engine_hdr,
                    _DIR / "gen_tables.py",
                    _DIR / "gen_hevc_tables.py",
                    _DIR / "gen_h264_cabac_tables.py",
                    Path(cavlc_tables.__file__),   # real inputs of the
                    Path(hevc_tables.__file__),    # generators
                    Path(cabac_ctx_tables.__file__)]
    if so.exists() and all(so.stat().st_mtime >= p.stat().st_mtime
                           for p in stamp_inputs):
        return so
    from vlog_tpu.native.gen_tables import generate

    # Per-process scratch names: multiple worker processes may race the
    # first build; each builds privately and os.replace publishes
    # atomically (last writer wins, all writers produce identical bits).
    from vlog_tpu.native.gen_h264_cabac_tables import (
        generate_c_header as gen_h264_hdr)
    from vlog_tpu.native.gen_hevc_tables import generate_c_header

    pid = os.getpid()
    inc = _BUILD / f"cavlc_tables.{pid}.inc"
    inc.write_text(generate())
    hevc_inc = _BUILD / f"hevc_tables.{pid}.inc"
    hevc_inc.write_text(generate_c_header())
    h264c_inc = _BUILD / f"h264_cabac_tables.{pid}.inc"
    h264c_inc.write_text(gen_h264_hdr())
    tmp_so = _BUILD / f"libvtnative.{pid}.so.tmp"
    cc = os.environ.get("CC", "g++")
    cmd = [cc, "-O3", "-fPIC", "-shared", "-x", "c++",
           f"-DVT_TABLES_INC=\"{inc.name}\"",
           f"-DVT_HEVC_TABLES_INC=\"{hevc_inc.name}\"",
           f"-DVT_H264_CABAC_INC=\"{h264c_inc.name}\"",
           str(src), str(jpeg_src), str(hevc_src), str(h264c_src),
           "-I", str(_BUILD), "-I", str(_DIR), "-o", str(tmp_so)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(f"native build failed: {proc.stderr[:2000]}")
    os.replace(tmp_so, so)
    inc.rename(_BUILD / "cavlc_tables.inc")        # for reference/debugging
    hevc_inc.rename(_BUILD / "hevc_tables.inc")
    h264c_inc.rename(_BUILD / "h264_cabac_tables.inc")
    return so


def get_lib() -> ctypes.CDLL | None:
    """The loaded library, or None (build failure / disabled)."""
    global _LIB, _TRIED
    if os.environ.get("VLOG_NATIVE", "1") in ("0", "false", "no"):
        return None
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        try:
            so = _compile()
            lib = ctypes.CDLL(str(so))
        except (NativeBuildError, OSError):
            _LIB = None
            return None
        i8 = ctypes.POINTER(ctypes.c_uint8)
        i32 = ctypes.POINTER(ctypes.c_int32)
        lib.vt_cavlc_encode_slice.restype = ctypes.c_int64
        lib.vt_cavlc_encode_slice.argtypes = [
            i32, i32, i32, i32,                      # levels arrays
            ctypes.c_int, ctypes.c_int,              # mbh, mbw
            i8, ctypes.c_int64,                      # header bytes
            ctypes.c_uint32, ctypes.c_int,           # header tail bits
            i32,                                     # nz scratch
            i8, ctypes.c_int64,                      # out buffer
        ]
        lib.vt_escape_emulation.restype = ctypes.c_int64
        lib.vt_escape_emulation.argtypes = [i8, ctypes.c_int64, i8]
        lib.vt_cavlc_encode_p_slice.restype = ctypes.c_int64
        lib.vt_cavlc_encode_p_slice.argtypes = [
            i32, i32, i32, i32,                      # luma, cdc, cac, mv
            ctypes.c_int, ctypes.c_int,              # mbh, mbw
            i8, ctypes.c_int64,                      # header bytes
            ctypes.c_uint32, ctypes.c_int,           # header tail bits
            i32,                                     # scratch
            i8, ctypes.c_int64,                      # out buffer
        ]
        lib.vt_h264_cabac_i_slice.restype = ctypes.c_int64
        lib.vt_h264_cabac_i_slice.argtypes = [
            i32, i32, i32, i32,                      # level arrays
            ctypes.c_int, ctypes.c_int,              # mbh, mbw
            ctypes.c_int,                            # slice qp
            i8, ctypes.c_int64,                      # header bytes
            i32,                                     # scratch
            i8, ctypes.c_int64,                      # out buffer
        ]
        lib.vt_h264_cabac_p_slice.restype = ctypes.c_int64
        lib.vt_h264_cabac_p_slice.argtypes = [
            i32, i32, i32, i32,                      # luma, cdc, cac, mv
            ctypes.c_int, ctypes.c_int,              # mbh, mbw
            ctypes.c_int,                            # slice qp
            i8, ctypes.c_int64,                      # header bytes
            i32,                                     # scratch
            i8, ctypes.c_int64,                      # out buffer
        ]
        i16 = ctypes.POINTER(ctypes.c_int16)
        lib.vt_hevc_encode_slice.restype = ctypes.c_int64
        lib.vt_hevc_encode_slice.argtypes = [
            i16, i16, i16,                           # luma, cb, cr levels
            ctypes.c_int32, ctypes.c_int32,          # rows, cols
            ctypes.c_int32,                          # slice qp
            i8, ctypes.c_int64,                      # out buffer
        ]
        lib.vt_hevc_encode_p_slice.restype = ctypes.c_int64
        lib.vt_hevc_encode_p_slice.argtypes = [
            i16, i16, i16,                           # luma, cb, cr levels
            i32,                                     # mv (y, x) int pels
            ctypes.c_int32, ctypes.c_int32,          # rows, cols
            ctypes.c_int32,                          # slice qp
            i32,                                     # mv scratch
            i8, ctypes.c_int64,                      # out buffer
        ]
        u16 = ctypes.POINTER(ctypes.c_uint16)
        lib.vt_jpeg_pack_scan.restype = ctypes.c_int64
        lib.vt_jpeg_pack_scan.argtypes = [
            i32, i8, ctypes.c_int64,                 # blocks, comp, n
            u16, i8, u16, i8, u16, i8, u16, i8,      # 4 Huffman tables
            i8, ctypes.c_int64,                      # out buffer
        ]
        _LIB = lib
        return _LIB
