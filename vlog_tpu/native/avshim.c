/* Foreign-upload ingest shim over the system libavformat/libavcodec.
 *
 * The reference ingests "anything ffmpeg decodes" by shelling out
 * (worker/transcoder.py:706-758, 1006). This framework's first-party
 * decoder covers its own I/P CAVLC envelope; for everything else —
 * x264/CABAC/B-frame H.264, HEVC, VP9, MKV/MOV/WebM containers — this
 * shim decodes through the same system libraries the reference's ffmpeg
 * build used, delivering I420 frames into caller buffers. The ENCODE
 * path stays first-party; this is ingest only, exactly the boundary the
 * reference drew.
 *
 * Built on demand by native/build.py when libavformat headers are
 * present; vlog_tpu degrades to the first-party envelope without it.
 */

#include <libavformat/avformat.h>
#include <libavcodec/avcodec.h>
#include <libswscale/swscale.h>
#include <libavutil/imgutils.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    AVFormatContext *fmt;
    AVCodecContext *vctx;
    AVPacket *pkt;
    AVFrame *frame;
    struct SwsContext *sws;
    int vidx;
    int w, h;
    int eof;
    int64_t next_index;     /* display index of the next frame returned */
} VtAv;

typedef struct {
    int width, height;
    double fps;
    double duration;        /* seconds, container-level */
    int64_t nb_frames;      /* container hint; -1 unknown */
    int has_audio;
    char vcodec[32];
    char acodec[32];
} VtAvInfo;

static int open_video(VtAv *av, const char *path) {
    if (avformat_open_input(&av->fmt, path, NULL, NULL) < 0) return -1;
    if (avformat_find_stream_info(av->fmt, NULL) < 0) return -2;
    av->vidx = av_find_best_stream(av->fmt, AVMEDIA_TYPE_VIDEO, -1, -1,
                                   NULL, 0);
    if (av->vidx < 0) return -3;
    AVStream *st = av->fmt->streams[av->vidx];
    const AVCodec *dec = avcodec_find_decoder(st->codecpar->codec_id);
    if (!dec) return -4;
    av->vctx = avcodec_alloc_context3(dec);
    avcodec_parameters_to_context(av->vctx, st->codecpar);
    if (avcodec_open2(av->vctx, dec, NULL) < 0) return -5;
    av->pkt = av_packet_alloc();
    av->frame = av_frame_alloc();
    av->w = st->codecpar->width;
    av->h = st->codecpar->height;
    return 0;
}

void *vt_av_open(const char *path, VtAvInfo *info) {
    VtAv *av = (VtAv *)calloc(1, sizeof(VtAv));
    if (open_video(av, path) != 0) {
        if (av->fmt) avformat_close_input(&av->fmt);
        free(av);
        return NULL;
    }
    AVStream *st = av->fmt->streams[av->vidx];
    memset(info, 0, sizeof(*info));
    info->width = av->w;
    info->height = av->h;
    AVRational fr = av_guess_frame_rate(av->fmt, st, NULL);
    info->fps = fr.num > 0 && fr.den > 0 ? (double)fr.num / fr.den : 0.0;
    info->duration = av->fmt->duration != AV_NOPTS_VALUE
        ? (double)av->fmt->duration / AV_TIME_BASE : 0.0;
    info->nb_frames = st->nb_frames > 0 ? st->nb_frames : -1;
    info->has_audio = av_find_best_stream(av->fmt, AVMEDIA_TYPE_AUDIO,
                                          -1, -1, NULL, 0) >= 0;
    const char *vn = avcodec_get_name(st->codecpar->codec_id);
    strncpy(info->vcodec, vn ? vn : "?", sizeof(info->vcodec) - 1);
    int aidx = av_find_best_stream(av->fmt, AVMEDIA_TYPE_AUDIO, -1, -1,
                                   NULL, 0);
    if (aidx >= 0) {
        const char *an = avcodec_get_name(
            av->fmt->streams[aidx]->codecpar->codec_id);
        strncpy(info->acodec, an ? an : "?", sizeof(info->acodec) - 1);
    }
    return av;
}

static void emit_i420(VtAv *av, AVFrame *f, uint8_t *dst) {
    int w = av->w, h = av->h;
    uint8_t *planes[3] = {dst, dst + (size_t)w * h,
                          dst + (size_t)w * h + (size_t)(w / 2) * (h / 2)};
    int strides[3] = {w, w / 2, w / 2};
    if (f->format == AV_PIX_FMT_YUV420P || f->format == AV_PIX_FMT_YUVJ420P) {
        for (int p = 0; p < 3; p++) {
            int ph = p ? h / 2 : h, pw = p ? w / 2 : w;
            for (int y = 0; y < ph; y++)
                memcpy(planes[p] + (size_t)y * pw,
                       f->data[p] + (size_t)y * f->linesize[p], pw);
        }
        return;
    }
    if (!av->sws)
        av->sws = sws_getContext(w, h, (enum AVPixelFormat)f->format,
                                 w, h, AV_PIX_FMT_YUV420P,
                                 SWS_BILINEAR, NULL, NULL, NULL);
    sws_scale(av->sws, (const uint8_t *const *)f->data, f->linesize,
              0, h, planes, strides);
}

/* Decode up to max_frames into buf (packed I420 per frame), with each
 * frame's presentation time (seconds; NAN-free, -1 when unknown) in
 * pts_out when non-NULL. Returns frames written; 0 at EOF; <0 on error. */
int64_t vt_av_read_pts(void *handle, uint8_t *buf, double *pts_out,
                       int64_t max_frames) {
    VtAv *av = (VtAv *)handle;
    size_t fsz = (size_t)av->w * av->h * 3 / 2;
    AVRational tb = av->fmt->streams[av->vidx]->time_base;
    int64_t got = 0;
    while (got < max_frames) {
        int r = avcodec_receive_frame(av->vctx, av->frame);
        if (r == 0) {
            emit_i420(av, av->frame, buf + (size_t)got * fsz);
            if (pts_out) {
                int64_t pts = av->frame->best_effort_timestamp;
                pts_out[got] = pts == AV_NOPTS_VALUE
                    ? -1.0 : pts * av_q2d(tb);
            }
            av_frame_unref(av->frame);
            got++;
            av->next_index++;
            continue;
        }
        if (r == AVERROR_EOF) break;
        if (r != AVERROR(EAGAIN)) return -1;
        if (av->eof) {
            if (avcodec_send_packet(av->vctx, NULL) < 0) break;
            continue;
        }
        int rr = av_read_frame(av->fmt, av->pkt);
        if (rr < 0) {
            av->eof = 1;
            avcodec_send_packet(av->vctx, NULL);
            continue;
        }
        if (av->pkt->stream_index == av->vidx)
            avcodec_send_packet(av->vctx, av->pkt);
        av_packet_unref(av->pkt);
    }
    return got;
}

int64_t vt_av_read(void *handle, uint8_t *buf, int64_t max_frames) {
    return vt_av_read_pts(handle, buf, NULL, max_frames);
}

/* Coarse seek for stride access (sprites): keyframe-accurate. Resets the
 * decoder; subsequent reads resume from the nearest prior keyframe. */
int vt_av_seek(void *handle, double seconds) {
    VtAv *av = (VtAv *)handle;
    int64_t ts = (int64_t)(seconds * AV_TIME_BASE);
    if (av_seek_frame(av->fmt, -1, ts, AVSEEK_FLAG_BACKWARD) < 0) return -1;
    avcodec_flush_buffers(av->vctx);
    av->eof = 0;
    return 0;
}

void vt_av_close(void *handle) {
    VtAv *av = (VtAv *)handle;
    if (!av) return;
    if (av->sws) sws_freeContext(av->sws);
    if (av->frame) av_frame_free(&av->frame);
    if (av->pkt) av_packet_free(&av->pkt);
    if (av->vctx) avcodec_free_context(&av->vctx);
    if (av->fmt) avformat_close_input(&av->fmt);
    free(av);
}

/* One-shot audio decode to interleaved float32 stereo-or-mono PCM written
 * as a headerless .f32 file next to a small header the caller reads.
 * Returns sample_rate<<8 | channels on success (both bounded), <0 on
 * error/no-audio. Caller passes the output path. */
int64_t vt_av_audio_to_f32(const char *path, const char *out_path) {
    AVFormatContext *fmt = NULL;
    if (avformat_open_input(&fmt, path, NULL, NULL) < 0) return -1;
    if (avformat_find_stream_info(fmt, NULL) < 0) {
        avformat_close_input(&fmt);
        return -2;
    }
    int aidx = av_find_best_stream(fmt, AVMEDIA_TYPE_AUDIO, -1, -1, NULL, 0);
    if (aidx < 0) { avformat_close_input(&fmt); return -3; }
    AVStream *st = fmt->streams[aidx];
    const AVCodec *dec = avcodec_find_decoder(st->codecpar->codec_id);
    AVCodecContext *ctx = avcodec_alloc_context3(dec);
    avcodec_parameters_to_context(ctx, st->codecpar);
    if (!dec || avcodec_open2(ctx, dec, NULL) < 0) {
        avcodec_free_context(&ctx);
        avformat_close_input(&fmt);
        return -4;
    }
    FILE *out = fopen(out_path, "wb");
    if (!out) {
        avcodec_free_context(&ctx);
        avformat_close_input(&fmt);
        return -5;
    }
    AVPacket *pkt = av_packet_alloc();
    AVFrame *frame = av_frame_alloc();
    int channels =
#if LIBAVCODEC_VERSION_MAJOR >= 59
        ctx->ch_layout.nb_channels;
#else
        ctx->channels;
#endif
    if (channels > 2) channels = 2;
    if (channels < 1) channels = 1;
    int rate = ctx->sample_rate;
    int err = 0, flushing = 0;
    while (!err) {
        int r = avcodec_receive_frame(ctx, frame);
        if (r == 0) {
            int n = frame->nb_samples;
            int fc =
#if LIBAVCODEC_VERSION_MAJOR >= 59
                frame->ch_layout.nb_channels;
#else
                frame->channels;
#endif
            for (int i = 0; i < n; i++) {
                for (int c = 0; c < channels; c++) {
                    int sc = c < fc ? c : fc - 1;
                    float v = 0.f;
                    switch (frame->format) {
                    case AV_SAMPLE_FMT_FLTP:
                        v = ((float *)frame->data[sc])[i]; break;
                    case AV_SAMPLE_FMT_FLT:
                        v = ((float *)frame->data[0])[i * fc + sc]; break;
                    case AV_SAMPLE_FMT_S16P:
                        v = ((int16_t *)frame->data[sc])[i] / 32768.f; break;
                    case AV_SAMPLE_FMT_S16:
                        v = ((int16_t *)frame->data[0])[i * fc + sc] / 32768.f;
                        break;
                    case AV_SAMPLE_FMT_S32P:
                        v = ((int32_t *)frame->data[sc])[i] / 2147483648.f;
                        break;
                    case AV_SAMPLE_FMT_S32:
                        v = ((int32_t *)frame->data[0])[i * fc + sc]
                            / 2147483648.f;
                        break;
                    case AV_SAMPLE_FMT_DBLP:
                        v = (float)((double *)frame->data[sc])[i]; break;
                    default:
                        err = 1;
                    }
                    fwrite(&v, sizeof(float), 1, out);
                }
                if (err) break;
            }
            av_frame_unref(frame);
            continue;
        }
        if (r == AVERROR_EOF) break;
        if (r != AVERROR(EAGAIN)) { err = 1; break; }
        if (flushing) { avcodec_send_packet(ctx, NULL); continue; }
        int rr = av_read_frame(fmt, pkt);
        if (rr < 0) {
            flushing = 1;
            avcodec_send_packet(ctx, NULL);
            continue;
        }
        if (pkt->stream_index == aidx) avcodec_send_packet(ctx, pkt);
        av_packet_unref(pkt);
    }
    fclose(out);
    av_frame_free(&frame);
    av_packet_free(&pkt);
    avcodec_free_context(&ctx);
    avformat_close_input(&fmt);
    if (err) return -6;
    return ((int64_t)rate << 8) | (int64_t)channels;
}
