"""Regenerate vlog_tpu/codecs/aac/tables.py from the system libavcodec.

The AAC Huffman codebooks and scalefactor-band tables are *normative
constants* of ISO/IEC 14496-3 (Tables 4.6.x and 4.A.2-4.A.12) — every
conforming codec carries byte-identical copies, the same way every H.264
codec carries the CAVLC tables (see gen_tables.py). Rather than
transcribing ~1000 numbers by hand (and risking a silent bitstream
corruption), this script extracts them from the system libavcodec
static archive's ``aactab.o`` and emits them as Python, with this
provenance recorded in the generated header.

Usage: python -m vlog_tpu.native.gen_aac_tables  (rewrites tables.py)
"""

from __future__ import annotations

import subprocess
import tempfile
from pathlib import Path

_ARCHIVE = "/usr/lib/x86_64-linux-gnu/libavcodec.a"

_DUMP_C = r"""
#include <stdio.h>
#include <stdint.h>

extern const uint8_t  ff_aac_num_swb_1024[];
extern const uint8_t  ff_aac_num_swb_128[];
extern const uint16_t * const ff_swb_offset_1024[];
extern const uint16_t * const ff_swb_offset_128[];
extern const uint32_t ff_aac_scalefactor_code[121];
extern const uint8_t  ff_aac_scalefactor_bits[121];
extern const uint16_t * const ff_aac_spectral_codes[11];
extern const uint8_t  * const ff_aac_spectral_bits[11];
extern const uint16_t ff_aac_spectral_sizes[11];
extern const uint8_t  ff_tns_max_bands_1024[];
extern const uint8_t  ff_tns_max_bands_128[];

/* satisfy aactab.o's window-init helpers (never called here) */
void ff_kbd_window_init(float *w, float a, int n) { (void)w;(void)a;(void)n; }
void ff_init_ff_sine_windows(int x) { (void)x; }

#define NUM_SR 13

int main(void) {
    int i, j;
    printf("NUM_SAMPLE_RATES = %d\n\n", NUM_SR);
    printf("NUM_SWB_1024 = [");
    for (i = 0; i < NUM_SR; i++) printf("%d, ", ff_aac_num_swb_1024[i]);
    printf("]\n\nNUM_SWB_128 = [");
    for (i = 0; i < NUM_SR; i++) printf("%d, ", ff_aac_num_swb_128[i]);
    printf("]\n\n");
    printf("SWB_OFFSET_1024 = [\n");
    for (i = 0; i < NUM_SR; i++) {
        printf("    [");
        for (j = 0; j <= ff_aac_num_swb_1024[i]; j++)
            printf("%d, ", ff_swb_offset_1024[i][j]);
        printf("],\n");
    }
    printf("]\n\nSWB_OFFSET_128 = [\n");
    for (i = 0; i < NUM_SR; i++) {
        printf("    [");
        for (j = 0; j <= ff_aac_num_swb_128[i]; j++)
            printf("%d, ", ff_swb_offset_128[i][j]);
        printf("],\n");
    }
    printf("]\n\n");
    printf("SCALEFACTOR_BITS = [");
    for (i = 0; i < 121; i++) printf("%d, ", ff_aac_scalefactor_bits[i]);
    printf("]\n\nSCALEFACTOR_CODE = [");
    for (i = 0; i < 121; i++) printf("%u, ", ff_aac_scalefactor_code[i]);
    printf("]\n\n");
    printf("SPECTRAL_SIZES = [");
    for (i = 0; i < 11; i++) printf("%d, ", ff_aac_spectral_sizes[i]);
    printf("]\n\nSPECTRAL_BITS = [\n");
    for (i = 0; i < 11; i++) {
        printf("    [");
        for (j = 0; j < ff_aac_spectral_sizes[i]; j++)
            printf("%d, ", ff_aac_spectral_bits[i][j]);
        printf("],\n");
    }
    printf("]\n\nSPECTRAL_CODES = [\n");
    for (i = 0; i < 11; i++) {
        printf("    [");
        for (j = 0; j < ff_aac_spectral_sizes[i]; j++)
            printf("%u, ", ff_aac_spectral_codes[i][j]);
        printf("],\n");
    }
    printf("]\n\n");
    printf("TNS_MAX_BANDS_1024 = [");
    for (i = 0; i < NUM_SR; i++) printf("%d, ", ff_tns_max_bands_1024[i]);
    printf("]\n\nTNS_MAX_BANDS_128 = [");
    for (i = 0; i < NUM_SR; i++) printf("%d, ", ff_tns_max_bands_128[i]);
    printf("]\n");
    return 0;
}
"""

_HEADER = '''\
"""AAC constant tables — normative ISO/IEC 14496-3 data.

Scalefactor-band offsets (Tables 4.6.x), spectral Huffman codebooks 1-11
(Tables 4.A.2-4.A.12), the scalefactor codebook (Table 4.A.1) and TNS
band limits. These are spec constants every conforming codec embeds
byte-identically; extracted from the system libavcodec archive by
vlog_tpu/native/gen_aac_tables.py (see its docstring for why). Do not
edit by hand — regenerate.
"""

# fmt: off
'''


def generate() -> str:
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        (td / "dump.c").write_text(_DUMP_C)
        subprocess.run(["ar", "x", _ARCHIVE, "aactab.o"], cwd=td, check=True)
        subprocess.run(["gcc", "-O0", "dump.c", "aactab.o", "-o", "dump"],
                       cwd=td, check=True)
        out = subprocess.run([str(td / "dump")], cwd=td, check=True,
                             capture_output=True, text=True).stdout
    return _HEADER + out


if __name__ == "__main__":
    dst = Path(__file__).resolve().parent.parent / "codecs" / "aac" / "tables.py"
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(generate())
    print(f"wrote {dst}")
