/* AV1 encode shim over the system libavcodec (libaom-av1 / SVT-AV1).
 *
 * The reference's AV1 path is DELEGATED encoding — av1_vaapi selected in
 * worker/hwaccel.py:555-646, hardware/ffmpeg doing the bits. This shim
 * is the same architectural boundary for this framework: H.264 and HEVC
 * are first-party TPU encoders (the methodology demonstrator), while
 * AV1 rides the system encoder libraries. A first-party AV1 entropy
 * coder needs the spec's default CDF tables, which this zero-egress
 * image cannot supply (libaom/libdav1d are stripped, no headers, no
 * static libs to extract from) — see COVERAGE.md row 5.
 *
 * Compiled into libvtav.so by native/avbuild.py next to the ingest shim.
 */

#include <libavcodec/avcodec.h>
#include <libavutil/opt.h>
#include <libavutil/imgutils.h>
#include <string.h>

typedef struct {
    AVCodecContext *ctx;
    AVFrame *frame;
    AVPacket *pkt;
    int w, h;
    int64_t next_pts;
    int flushed;
    int held;     /* pkt holds an undelivered packet (buffer was small) */
} VtAv1Enc;

void *vt_av1_open(int w, int h, int fps_num, int fps_den,
                  int64_t bitrate, int gop_len, int speed) {
    const char *names[] = {"libaom-av1", "libsvtav1", "librav1e", NULL};
    const AVCodec *enc = NULL;
    for (int i = 0; names[i] && !enc; i++)
        enc = avcodec_find_encoder_by_name(names[i]);
    if (!enc) return NULL;

    VtAv1Enc *e = calloc(1, sizeof(*e));
    if (!e) return NULL;
    e->ctx = avcodec_alloc_context3(enc);
    e->w = w; e->h = h;
    e->ctx->width = w;
    e->ctx->height = h;
    e->ctx->time_base = (AVRational){fps_den, fps_num};
    e->ctx->framerate = (AVRational){fps_num, fps_den};
    e->ctx->pix_fmt = AV_PIX_FMT_YUV420P;
    e->ctx->bit_rate = bitrate;
    /* Bound the one-pass VBR: without maxrate/bufsize the system
     * encoders overshoot freely on hard content and trip the product
     * plane's rate-verification cap (a miss our controller can't
     * influence). 1.5x maxrate over a ~1s window tracks the cap. */
    e->ctx->rc_max_rate = bitrate + bitrate / 2;
    e->ctx->rc_buffer_size = (int)(bitrate + bitrate / 2);
    e->ctx->gop_size = gop_len;
    e->ctx->max_b_frames = 0;
    e->ctx->thread_count = 0;
    /* no GLOBAL_HEADER: the av01 packaging relies on the sequence
     * header OBU riding in-band at every keyframe TU (av1C configOBUs
     * stay empty), so the encoder must not strip it into extradata */
    if (!strcmp(enc->name, "libaom-av1")) {
        char sp[8];
        snprintf(sp, sizeof sp, "%d", speed < 0 ? 6 : speed);
        av_opt_set(e->ctx->priv_data, "cpu-used", sp, 0);
        av_opt_set(e->ctx->priv_data, "row-mt", "1", 0);
        av_opt_set(e->ctx->priv_data, "usage", "good", 0);
        /* no alt-ref lookahead: every packet is one shown frame, so the
         * CMAF sample count tracks the frame count 1:1 */
        av_opt_set(e->ctx->priv_data, "lag-in-frames", "0", 0);
    } else if (!strcmp(enc->name, "libsvtav1")) {
        char sp[8];
        snprintf(sp, sizeof sp, "%d", speed < 0 ? 8 : speed);
        av_opt_set(e->ctx->priv_data, "preset", sp, 0);
        /* low-delay pred structure, no lookahead: packets come back in
         * presentation order with no delay, matching the muxer's
         * arrival-order CMAF packaging (same contract lag-in-frames=0
         * gives libaom above). */
        av_opt_set(e->ctx->priv_data, "svtav1-params",
                   "pred-struct=1:lookahead=0", 0);
    } else if (!strcmp(enc->name, "librav1e")) {
        av_opt_set(e->ctx->priv_data, "rav1e-params",
                   "low_latency=true", 0);
    }
    if (avcodec_open2(e->ctx, enc, NULL) < 0) {
        avcodec_free_context(&e->ctx);
        free(e);
        return NULL;
    }
    e->frame = av_frame_alloc();
    e->pkt = av_packet_alloc();
    return e;
}

/* Submit one I420 frame; 0 on success. */
int vt_av1_send(void *h, const uint8_t *y, const uint8_t *u,
                const uint8_t *v, int force_key) {
    VtAv1Enc *e = h;
    AVFrame *f = e->frame;
    f->format = AV_PIX_FMT_YUV420P;
    f->width = e->w;
    f->height = e->h;
    if (av_frame_get_buffer(f, 0) < 0) return -1;
    if (av_frame_make_writable(f) < 0) return -2;
    av_image_copy_plane(f->data[0], f->linesize[0], y, e->w, e->w, e->h);
    av_image_copy_plane(f->data[1], f->linesize[1], u, e->w / 2,
                        e->w / 2, e->h / 2);
    av_image_copy_plane(f->data[2], f->linesize[2], v, e->w / 2,
                        e->w / 2, e->h / 2);
    f->pts = e->next_pts++;
    f->pict_type = force_key ? AV_PICTURE_TYPE_I : AV_PICTURE_TYPE_NONE;
    int rc = avcodec_send_frame(e->ctx, f);
    av_frame_unref(f);
    return rc < 0 ? -3 : 0;
}

int vt_av1_flush(void *h) {
    VtAv1Enc *e = h;
    if (e->flushed) return 0;
    e->flushed = 1;
    return avcodec_send_frame(e->ctx, NULL) < 0 ? -1 : 0;
}

/* Drain one packet: >0 = bytes written (is_key/pts filled), 0 = encoder
 * needs more input, -1 = end of stream, -2 = output buffer too small
 * (the packet is HELD and re-delivered on the next call with a larger
 * buffer — never dropped), -3 = encoder error. */
int64_t vt_av1_receive(void *h, uint8_t *out, int64_t cap, int *is_key,
                       int64_t *pts) {
    VtAv1Enc *e = h;
    if (!e->held) {
        int rc = avcodec_receive_packet(e->ctx, e->pkt);
        if (rc == AVERROR(EAGAIN)) return 0;
        if (rc == AVERROR_EOF) return -1;
        if (rc < 0) return -3;
    }
    if (e->pkt->size > cap) {
        e->held = 1;
        return -2;
    }
    e->held = 0;
    memcpy(out, e->pkt->data, e->pkt->size);
    if (is_key) *is_key = (e->pkt->flags & AV_PKT_FLAG_KEY) != 0;
    if (pts) *pts = e->pkt->pts;
    int64_t n = e->pkt->size;
    av_packet_unref(e->pkt);
    return n;
}

void vt_av1_close(void *h) {
    VtAv1Enc *e = h;
    if (!e) return;
    if (e->ctx) avcodec_free_context(&e->ctx);
    if (e->frame) av_frame_free(&e->frame);
    if (e->pkt) av_packet_free(&e->pkt);
    free(e);
}
