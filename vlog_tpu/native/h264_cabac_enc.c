/* H.264 CABAC slice coders — C port of codecs/h264/cabac_enc.py.
 *
 * Same role as cavlc.c's slice coders: the production host entropy
 * stage, bit-exact with the Python reference (tests/test_h264_cabac.py
 * asserts equality and oracles against libavcodec). Covers the
 * I_16x16 / P_L0_16x16 + P_Skip envelope.
 *
 * Engine: cabac_engine.h (shared with the HEVC coder; the arithmetic
 * tables are identical in both standards). Context init pairs come
 * from the generated H264 include; zigzag/block-order tables from the
 * CAVLC generated include.
 */

#include <stdint.h>
#include <string.h>

#ifndef VT_HEVC_TABLES_INC
#define VT_HEVC_TABLES_INC "hevc_tables.inc"
#endif
#include VT_HEVC_TABLES_INC          /* engine tables (shared) */
#ifndef VT_H264_CABAC_INC
#define VT_H264_CABAC_INC "h264_cabac_tables.inc"
#endif
#include VT_H264_CABAC_INC
#ifndef VT_TABLES_INC
#define VT_TABLES_INC "cavlc_tables.inc"
#endif
#include VT_TABLES_INC               /* ZIGZAG16, LUMA_ORDER */
#include "cabac_engine.h"

static void h264_cabac_init(Cabac *c, int qp, int i_slice,
                            uint8_t *out, int64_t cap) {
    cab_start(c, out, cap);
    const int8_t *tab = i_slice ? H264_INIT_I : H264_INIT_P0;
    if (qp < 0) qp = 0; if (qp > 51) qp = 51;
    for (int i = 0; i < 1024; i++) {
        int m = tab[2 * i], n = tab[2 * i + 1];
        int pre = ((m * qp) >> 4) + n;
        if (pre < 1) pre = 1; if (pre > 126) pre = 126;
        if (pre <= 63) { c->pstate[i] = (uint8_t)(63 - pre); c->mps[i] = 0; }
        else { c->pstate[i] = (uint8_t)(pre - 64); c->mps[i] = 1; }
    }
}

/* ------------------------------------------------------- residual */

static const int CBF_CAT[5] = {0, 4, 8, 12, 16};
static const int SIGLAST_CAT[5] = {0, 15, 29, 44, 47};
static const int LVL_CAT[5] = {0, 10, 20, 30, 39};

/* coeffs: scan order, length n (<=16). Returns the cbf bit. */
static int residual_block(Cabac *c, int cat, const int32_t *coeffs, int n,
                          int cbf_inc) {
    int nz[16], nnz = 0;
    for (int i = 0; i < n; i++) if (coeffs[i]) nz[nnz++] = i;
    cab_bin(c, 85 + CBF_CAT[cat] + cbf_inc, nnz > 0);
    if (!nnz) return 0;
    int last = nz[nnz - 1];
    for (int i = 0; i < n - 1; i++) {
        int inc = (cat == 3 && i > 2) ? 2 : i;
        int sig = coeffs[i] != 0;
        cab_bin(c, 105 + SIGLAST_CAT[cat] + inc, sig);
        if (sig) {
            cab_bin(c, 166 + SIGLAST_CAT[cat] + inc, i == last);
            if (i == last) break;
        }
    }
    int num_eq1 = 0, num_gt1 = 0;
    for (int k = nnz - 1; k >= 0; k--) {
        int32_t v = coeffs[nz[k]];
        int val = (v < 0 ? -v : v) - 1;
        int base = 227 + LVL_CAT[cat];
        int inc0 = num_gt1 > 0 ? 0
                   : (1 + num_eq1 > 4 ? 4 : 1 + num_eq1);
        cab_bin(c, base + inc0, val > 0);
        if (val > 0) {
            int inc_gt = 5 + (num_gt1 > 4 ? 4 : num_gt1);
            int prefix = val < 14 ? val : 14;
            for (int j = 1; j < prefix; j++) cab_bin(c, base + inc_gt, 1);
            if (val < 14) cab_bin(c, base + inc_gt, 0);
            else cab_eg_bypass(c, val - 14, 0);
            num_gt1++;
        } else num_eq1++;
        cab_bypass(c, v < 0);
    }
    return 1;
}

/* scratch-backed neighbor grids */
typedef struct {
    int mbh, mbw;
    int32_t *cbf_lumadc;   /* (mbh, mbw) */
    int32_t *cbf_luma44;   /* (4mbh, 4mbw) */
    int32_t *cbf_chdc;     /* (2, mbh, mbw) */
    int32_t *cbf_ch44;     /* (2, 2mbh, 2mbw) */
    int32_t *cbp_chroma;   /* (mbh, mbw) */
    int32_t *mvd;          /* (mbh, mbw, 2) abs */
    int32_t *cbp8;         /* (2mbh, 2mbw) */
    int32_t *skip;         /* (mbh, mbw) */
} Grids;

static Grids grids_at(int32_t *scratch, int mbh, int mbw) {
    Grids g;
    g.mbh = mbh; g.mbw = mbw;
    int64_t mb = (int64_t)mbh * mbw;
    g.cbf_lumadc = scratch;             scratch += mb;
    g.cbf_luma44 = scratch;             scratch += mb * 16;
    g.cbf_chdc = scratch;               scratch += mb * 2;
    g.cbf_ch44 = scratch;               scratch += mb * 8;
    g.cbp_chroma = scratch;             scratch += mb;
    g.mvd = scratch;                    scratch += mb * 2;
    g.cbp8 = scratch;                   scratch += mb * 4;
    g.skip = scratch;                   scratch += mb;
    memset(g.cbf_lumadc, 0, sizeof(int32_t) * mb * 35);
    return g;
}

/* cbf ctxIdxInc per category (mirrors cabac_enc.py _cbf_inc; the
 * outside-picture default for intra MBs is condTerm=1) */
static int cbf_inc(const Grids *g, int cat, int my, int mx, int comp,
                   int by, int bx, int cur_intra, int i_slice) {
    int a, b;
    int edge = cur_intra ? 1 : 0;
    if (cat == 0) {
        a = mx > 0 ? (i_slice ? (int)g->cbf_lumadc[my * g->mbw + mx - 1]
                              : 0)
                   : edge;
        b = my > 0 ? (i_slice ? (int)g->cbf_lumadc[(my - 1) * g->mbw + mx]
                              : 0)
                   : edge;
        return a + 2 * b;
    }
    if (cat == 1 || cat == 2) {
        int y = my * 4 + by, x = mx * 4 + bx, w = g->mbw * 4;
        a = x > 0 ? (int)g->cbf_luma44[y * w + x - 1] : edge;
        b = y > 0 ? (int)g->cbf_luma44[(y - 1) * w + x] : edge;
        return a + 2 * b;
    }
    if (cat == 3) {
        a = mx > 0 ? (int)g->cbf_chdc[(comp * g->mbh + my) * g->mbw + mx - 1]
                   : edge;
        b = my > 0 ? (int)g->cbf_chdc[(comp * g->mbh + my - 1) * g->mbw + mx]
                   : edge;
        return a + 2 * b;
    }
    {
        int y = my * 2 + by, x = mx * 2 + bx, w = g->mbw * 2;
        const int32_t *grid = g->cbf_ch44 + (int64_t)comp * g->mbh * 2 * w;
        a = x > 0 ? (int)grid[y * w + x - 1] : edge;
        b = y > 0 ? (int)grid[(y - 1) * w + x] : edge;
        return a + 2 * b;
    }
}

static void scan16(const int32_t *blk, int32_t *out) {
    for (int i = 0; i < 16; i++) out[i] = blk[ZIGZAG16[i]];
}

static void qp_delta_zero(Cabac *c, int *prev_nz) {
    cab_bin(c, 60 + (*prev_nz ? 1 : 0), 0);
    *prev_nz = 0;
}

/* ------------------------------------------------------- I slices */

static int64_t encode_i_slice(
        const int32_t *luma_dc, const int32_t *luma_ac,
        const int32_t *chroma_dc, const int32_t *chroma_ac,
        int mbh, int mbw, int slice_qp,
        int32_t *scratch, uint8_t *out, int64_t out_cap)
{
    Cabac c;
    h264_cabac_init(&c, slice_qp, 1, out, out_cap);
    Grids g = grids_at(scratch, mbh, mbw);
    int prev_qp_nz = 0;
    int32_t sc[16];
    for (int my = 0; my < mbh; my++)
        for (int mx = 0; mx < mbw; mx++) {
            int mb = my * mbw + mx;
            const int32_t *dc = luma_dc + (int64_t)mb * 16;
            const int32_t *ac = luma_ac + (int64_t)mb * 256;
            int cbp_luma = 0;
            for (int i = 0; i < 256 && !cbp_luma; i++)
                if (ac[i]) cbp_luma = 15;
            int cbp_chroma = 0;
            for (int comp = 0; comp < 2 && cbp_chroma < 2; comp++) {
                const int32_t *cac = chroma_ac
                    + ((int64_t)comp * mbh * mbw + mb) * 64;
                for (int i = 0; i < 64; i++)
                    if (cac[i]) { cbp_chroma = 2; break; }
            }
            if (!cbp_chroma)
                for (int comp = 0; comp < 2 && !cbp_chroma; comp++) {
                    const int32_t *cdc = chroma_dc
                        + ((int64_t)comp * mbh * mbw + mb) * 4;
                    for (int i = 0; i < 4; i++)
                        if (cdc[i]) { cbp_chroma = 1; break; }
                }
            int luma_mode = my == 0 ? 2 : 0;
            int chroma_mode = my == 0 ? 0 : 2;

            /* mb_type: neighbors are always I16 in an I slice */
            int ca = mx > 0 ? 1 : 0, cb = my > 0 ? 1 : 0;
            cab_bin(&c, 3 + ca + cb, 1);
            cab_terminate(&c, 0);
            cab_bin(&c, 6, cbp_luma ? 1 : 0);
            cab_bin(&c, 7, cbp_chroma ? 1 : 0);
            if (cbp_chroma) cab_bin(&c, 8, cbp_chroma == 2);
            cab_bin(&c, 9, (luma_mode >> 1) & 1);
            cab_bin(&c, 10, luma_mode & 1);

            /* intra_chroma_pred_mode (neighbors' mode: row0 DC=0) */
            {
                int ia = mx > 0 && (my != 0) ? 1 : 0;  /* left mode!=0 */
                int ib = my > 1 ? 1 : 0;               /* above mode!=0 */
                cab_bin(&c, 64 + ia + ib, chroma_mode > 0);
                if (chroma_mode > 0) {
                    cab_bin(&c, 67, chroma_mode > 1);
                    if (chroma_mode > 1) cab_bin(&c, 67, chroma_mode > 2);
                }
            }
            qp_delta_zero(&c, &prev_qp_nz);

            scan16(dc, sc);
            g.cbf_lumadc[mb] = residual_block(
                &c, 0, sc, 16, cbf_inc(&g, 0, my, mx, 0, 0, 0, 1, 1));
            if (cbp_luma)
                for (int k = 0; k < 16; k++) {
                    int by = LUMA_ORDER[k] / 4, bx = LUMA_ORDER[k] % 4;
                    const int32_t *blk = ac + ((by * 4 + bx) << 4);
                    scan16(blk, sc);
                    int cbf = residual_block(
                        &c, 1, sc + 1, 15,
                        cbf_inc(&g, 1, my, mx, 0, by, bx, 1, 1));
                    g.cbf_luma44[(my * 4 + by) * mbw * 4 + mx * 4 + bx]
                        = cbf;
                }
            if (cbp_chroma > 0)
                for (int comp = 0; comp < 2; comp++) {
                    const int32_t *cdc = chroma_dc
                        + ((int64_t)comp * mbh * mbw + mb) * 4;
                    g.cbf_chdc[(comp * mbh + my) * mbw + mx]
                        = residual_block(
                            &c, 3, cdc, 4,
                            cbf_inc(&g, 3, my, mx, comp, 0, 0, 1, 1));
                }
            if (cbp_chroma == 2)
                for (int comp = 0; comp < 2; comp++)
                    for (int by = 0; by < 2; by++)
                        for (int bx = 0; bx < 2; bx++) {
                            const int32_t *blk = chroma_ac
                                + (((int64_t)comp * mbh * mbw + mb) * 4
                                   + by * 2 + bx) * 16;
                            scan16(blk, sc);
                            int cbf = residual_block(
                                &c, 4, sc + 1, 15,
                                cbf_inc(&g, 4, my, mx, comp, by, bx, 1, 1));
                            g.cbf_ch44[((int64_t)comp * mbh * 2
                                        + my * 2 + by) * mbw * 2
                                       + mx * 2 + bx] = cbf;
                        }
            g.cbp_chroma[mb] = cbp_chroma;
            cab_terminate(&c, my == mbh - 1 && mx == mbw - 1);
        }
    return cab_finish(&c);
}

extern "C" int64_t vt_h264_cabac_i_slice(
        const int32_t *luma_dc, const int32_t *luma_ac,
        const int32_t *chroma_dc, const int32_t *chroma_ac,
        int mbh, int mbw, int slice_qp,
        const uint8_t *header_bytes, int64_t n_header_bytes,
        int32_t *scratch, uint8_t *out, int64_t out_cap)
{
    if (n_header_bytes > out_cap) return -1;
    memcpy(out, header_bytes, (size_t)n_header_bytes);
    int64_t n = encode_i_slice(luma_dc, luma_ac, chroma_dc, chroma_ac,
                               mbh, mbw, slice_qp, scratch,
                               out + n_header_bytes,
                               out_cap - n_header_bytes);
    return n < 0 ? -1 : n + n_header_bytes;
}

/* ------------------------------------------------------- P slices */

static void median_pred(const int32_t *mvs, int mbh, int mbw, int my,
                        int mx, int32_t *px, int32_t *py) {
    /* same rules as cavlc.c mv_pred (8.4.1.3.1) */
    int a_ok = mx > 0, b_ok = my > 0;
    int c_ok = b_ok && mx < mbw - 1, d_ok = b_ok && mx > 0;
    int32_t ax = 0, ay = 0, bx = 0, by = 0, cx = 0, cy = 0;
    int cav = 0;
    if (a_ok) { ax = mvs[(my * mbw + mx - 1) * 2];
                ay = mvs[(my * mbw + mx - 1) * 2 + 1]; }
    if (b_ok) { bx = mvs[((my - 1) * mbw + mx) * 2];
                by = mvs[((my - 1) * mbw + mx) * 2 + 1]; }
    if (c_ok) { cav = 1; cx = mvs[((my - 1) * mbw + mx + 1) * 2];
                cy = mvs[((my - 1) * mbw + mx + 1) * 2 + 1]; }
    else if (d_ok) { cav = 1; cx = mvs[((my - 1) * mbw + mx - 1) * 2];
                     cy = mvs[((my - 1) * mbw + mx - 1) * 2 + 1]; }
    int n_avail = a_ok + b_ok + cav;
    if (n_avail == 1) {
        if (a_ok) { *px = ax; *py = ay; }
        else if (b_ok) { *px = bx; *py = by; }
        else { *px = cx; *py = cy; }
        return;
    }
#define MED3(a, b, cc) ((a) > (b) ? ((b) > (cc) ? (b) : ((a) > (cc) ? (cc) \
    : (a))) : ((a) > (cc) ? (a) : ((b) > (cc) ? (cc) : (b))))
    *px = MED3(ax, bx, cx);
    *py = MED3(ay, by, cy);
#undef MED3
}

static void skip_pred(const int32_t *mvs, int mbh, int mbw, int my,
                      int mx, int32_t *px, int32_t *py) {
    if (mx == 0 || my == 0) { *px = 0; *py = 0; return; }
    const int32_t *a = mvs + ((int64_t)my * mbw + mx - 1) * 2;
    const int32_t *b = mvs + (((int64_t)my - 1) * mbw + mx) * 2;
    if ((a[0] == 0 && a[1] == 0) || (b[0] == 0 && b[1] == 0)) {
        *px = 0; *py = 0; return;
    }
    median_pred(mvs, mbh, mbw, my, mx, px, py);
}

static void encode_mvd_comp(Cabac *c, int mvd, int amvd, int base) {
    int inc = amvd < 3 ? 0 : (amvd <= 32 ? 1 : 2);
    int val = mvd < 0 ? -mvd : mvd;
    cab_bin(c, base + inc, val > 0);
    if (val > 0) {
        int prefix = val < 9 ? val : 9;
        for (int k = 1; k < prefix; k++)
            cab_bin(c, base + 2 + (k < 4 ? k : 4), 1);
        if (val < 9)
            cab_bin(c, base + 2 + (prefix < 4 ? prefix : 4), 0);
        else cab_eg_bypass(c, val - 9, 3);
        cab_bypass(c, mvd < 0);
    }
}

extern "C" int64_t vt_h264_cabac_p_slice(
        const int32_t *luma, const int32_t *chroma_dc,
        const int32_t *chroma_ac, const int32_t *mv,
        int mbh, int mbw, int slice_qp,
        const uint8_t *header_bytes, int64_t n_header_bytes,
        int32_t *scratch, uint8_t *out, int64_t out_cap)
{
    if (n_header_bytes > out_cap) return -1;
    memcpy(out, header_bytes, (size_t)n_header_bytes);
    Cabac c;
    h264_cabac_init(&c, slice_qp, 0, out + n_header_bytes,
                    out_cap - n_header_bytes);
    Grids g = grids_at(scratch, mbh, mbw);
    int64_t mbs = (int64_t)mbh * mbw;
    int32_t *mvs = scratch + mbs * 35;   /* reconstructed (x, y) qpel */
    memset(mvs, 0, sizeof(int32_t) * mbs * 2);
    int prev_qp_nz = 0;
    int32_t sc[16];
    static const int BLK2[4][2] = {{0, 0}, {0, 1}, {1, 0}, {1, 1}};

    for (int my = 0; my < mbh; my++)
        for (int mx = 0; mx < mbw; mx++) {
            int mb = my * mbw + mx;
            const int32_t *lu = luma + (int64_t)mb * 256;
            int32_t mvx = mv[mb * 2 + 1], mvy = mv[mb * 2];
            int cbp = 0;
            for (int i8 = 0; i8 < 4; i8++) {
                int oy = BLK2[i8][0], ox = BLK2[i8][1], any = 0;
                for (int s = 0; s < 4 && !any; s++) {
                    int by = 2 * oy + BLK2[s][0], bx = 2 * ox + BLK2[s][1];
                    const int32_t *blk = lu + ((by * 4 + bx) << 4);
                    for (int i = 0; i < 16; i++)
                        if (blk[i]) { any = 1; break; }
                }
                if (any) cbp |= 1 << i8;
            }
            int cbp_chroma = 0;
            for (int comp = 0; comp < 2 && cbp_chroma < 2; comp++) {
                const int32_t *cac = chroma_ac
                    + ((int64_t)comp * mbs + mb) * 64;
                for (int i = 0; i < 64; i++)
                    if (cac[i]) { cbp_chroma = 2; break; }
            }
            if (!cbp_chroma)
                for (int comp = 0; comp < 2 && !cbp_chroma; comp++) {
                    const int32_t *cdc = chroma_dc
                        + ((int64_t)comp * mbs + mb) * 4;
                    for (int i = 0; i < 4; i++)
                        if (cdc[i]) { cbp_chroma = 1; break; }
                }
            int32_t smx, smy;
            skip_pred(mvs, mbh, mbw, my, mx, &smx, &smy);
            int skip = cbp == 0 && cbp_chroma == 0
                && mvx == smx && mvy == smy;
            int ca = mx > 0 && !g.skip[mb - 1] ? 1 : 0;
            int cb = my > 0 && !g.skip[mb - mbw] ? 1 : 0;
            cab_bin(&c, 11 + ca + cb, skip);
            if (skip) {
                mvs[mb * 2] = smx; mvs[mb * 2 + 1] = smy;
                g.skip[mb] = 1;
                cab_terminate(&c, my == mbh - 1 && mx == mbw - 1);
                continue;
            }
            cab_bin(&c, 14, 0);
            cab_bin(&c, 15, 0);
            cab_bin(&c, 16, 0);

            int32_t px, py;
            median_pred(mvs, mbh, mbw, my, mx, &px, &py);
            mvs[mb * 2] = mvx; mvs[mb * 2 + 1] = mvy;
            {
                int amvd_x = (mx > 0 ? g.mvd[(mb - 1) * 2] : 0)
                    + (my > 0 ? g.mvd[(mb - mbw) * 2] : 0);
                int amvd_y = (mx > 0 ? g.mvd[(mb - 1) * 2 + 1] : 0)
                    + (my > 0 ? g.mvd[(mb - mbw) * 2 + 1] : 0);
                int dx = mvx - px, dy = mvy - py;
                encode_mvd_comp(&c, dx, amvd_x, 40);
                encode_mvd_comp(&c, dy, amvd_y, 47);
                g.mvd[mb * 2] = dx < 0 ? -dx : dx;
                g.mvd[mb * 2 + 1] = dy < 0 ? -dy : dy;
            }

            for (int i8 = 0; i8 < 4; i8++) {
                int y8 = my * 2 + BLK2[i8][0], x8 = mx * 2 + BLK2[i8][1];
                int w8 = mbw * 2;
                int a = x8 > 0 && g.cbp8[y8 * w8 + x8 - 1] == 0 ? 1 : 0;
                int b = y8 > 0 && g.cbp8[(y8 - 1) * w8 + x8] == 0 ? 1 : 0;
                int bit = (cbp >> i8) & 1;
                cab_bin(&c, 73 + a + 2 * b, bit);
                g.cbp8[y8 * w8 + x8] = bit;
            }
            {
                int a = mx > 0 && g.cbp_chroma[mb - 1] != 0 ? 1 : 0;
                int b = my > 0 && g.cbp_chroma[mb - mbw] != 0 ? 1 : 0;
                cab_bin(&c, 77 + a + 2 * b, cbp_chroma ? 1 : 0);
                if (cbp_chroma) {
                    a = mx > 0 && g.cbp_chroma[mb - 1] == 2 ? 1 : 0;
                    b = my > 0 && g.cbp_chroma[mb - mbw] == 2 ? 1 : 0;
                    cab_bin(&c, 81 + a + 2 * b, cbp_chroma == 2);
                }
                g.cbp_chroma[mb] = cbp_chroma;
            }
            int full_cbp = cbp | (cbp_chroma << 4);
            if (full_cbp) {
                qp_delta_zero(&c, &prev_qp_nz);
                for (int i8 = 0; i8 < 4; i8++)
                    for (int s = 0; s < 4; s++) {
                        int by = 2 * BLK2[i8][0] + BLK2[s][0];
                        int bx = 2 * BLK2[i8][1] + BLK2[s][1];
                        int gy = my * 4 + by, gx = mx * 4 + bx;
                        if (!((cbp >> i8) & 1)) {
                            g.cbf_luma44[gy * mbw * 4 + gx] = 0;
                            continue;
                        }
                        const int32_t *blk = lu + ((by * 4 + bx) << 4);
                        scan16(blk, sc);
                        int cbf = residual_block(
                            &c, 2, sc, 16,
                            cbf_inc(&g, 2, my, mx, 0, by, bx, 0, 0));
                        g.cbf_luma44[gy * mbw * 4 + gx] = cbf;
                    }
                if (cbp_chroma > 0)
                    for (int comp = 0; comp < 2; comp++) {
                        const int32_t *cdc = chroma_dc
                            + ((int64_t)comp * mbs + mb) * 4;
                        g.cbf_chdc[(comp * mbh + my) * mbw + mx]
                            = residual_block(
                                &c, 3, cdc, 4,
                                cbf_inc(&g, 3, my, mx, comp, 0, 0, 0, 0));
                    }
                for (int comp = 0; comp < 2; comp++)
                    for (int by = 0; by < 2; by++)
                        for (int bx = 0; bx < 2; bx++) {
                            int64_t idx = ((int64_t)comp * mbh * 2
                                           + my * 2 + by) * mbw * 2
                                + mx * 2 + bx;
                            if (cbp_chroma != 2) {
                                g.cbf_ch44[idx] = 0;
                                continue;
                            }
                            const int32_t *blk = chroma_ac
                                + (((int64_t)comp * mbs + mb) * 4
                                   + by * 2 + bx) * 16;
                            scan16(blk, sc);
                            g.cbf_ch44[idx] = residual_block(
                                &c, 4, sc + 1, 15,
                                cbf_inc(&g, 4, my, mx, comp, by, bx, 0, 0));
                        }
            }
            cab_terminate(&c, my == mbh - 1 && mx == mbw - 1);
        }
    int64_t n = cab_finish(&c);
    return n < 0 ? -1 : n + n_header_bytes;
}
