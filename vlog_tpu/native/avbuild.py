"""Build + load the optional libav ingest shim (ctypes).

Separate from the entropy-coder build: this one links the system
libavformat/libavcodec/libswscale and is entirely optional — without the
headers/libraries, vlog_tpu keeps its first-party decode envelope and
foreign uploads are rejected at probe time, exactly like a reference
deployment without ffmpeg. Disable explicitly with VLOG_LIBAV=0.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_DIR = Path(__file__).parent
_BUILD = _DIR / "_build"
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


class VtAvInfo(ctypes.Structure):
    _fields_ = [
        ("width", ctypes.c_int),
        ("height", ctypes.c_int),
        ("fps", ctypes.c_double),
        ("duration", ctypes.c_double),
        ("nb_frames", ctypes.c_int64),
        ("has_audio", ctypes.c_int),
        ("vcodec", ctypes.c_char * 32),
        ("acodec", ctypes.c_char * 32),
    ]


def _compile() -> Path:
    _BUILD.mkdir(exist_ok=True)
    srcs = [_DIR / "avshim.c", _DIR / "av1enc.c"]
    so = _BUILD / "libvtav.so"
    if so.exists() and all(so.stat().st_mtime >= s.stat().st_mtime
                           for s in srcs):
        return so
    pid = os.getpid()
    tmp_so = _BUILD / f"libvtav.{pid}.so.tmp"
    cc = os.environ.get("CC", "gcc")
    cmd = [cc, "-O2", "-fPIC", "-shared", *map(str, srcs), "-o",
           str(tmp_so),
           "-lavformat", "-lavcodec", "-lavutil", "-lswscale"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"avshim build failed: {proc.stderr[:1000]}")
    os.replace(tmp_so, so)
    return so


def get_av_lib() -> ctypes.CDLL | None:
    """The loaded ingest shim, or None (unavailable/disabled)."""
    global _LIB, _TRIED
    if os.environ.get("VLOG_LIBAV", "1") in ("0", "false", "no"):
        return None
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        try:
            lib = ctypes.CDLL(str(_compile()))
        except (RuntimeError, OSError):
            _LIB = None
            return None
        lib.vt_av_open.restype = ctypes.c_void_p
        lib.vt_av_open.argtypes = [ctypes.c_char_p,
                                   ctypes.POINTER(VtAvInfo)]
        lib.vt_av_read.restype = ctypes.c_int64
        lib.vt_av_read.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_uint8),
                                   ctypes.c_int64]
        lib.vt_av_read_pts.restype = ctypes.c_int64
        lib.vt_av_read_pts.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint8),
                                       ctypes.POINTER(ctypes.c_double),
                                       ctypes.c_int64]
        lib.vt_av_seek.restype = ctypes.c_int
        lib.vt_av_seek.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.vt_av_close.restype = None
        lib.vt_av_close.argtypes = [ctypes.c_void_p]
        lib.vt_av_audio_to_f32.restype = ctypes.c_int64
        lib.vt_av_audio_to_f32.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.vt_av1_open.restype = ctypes.c_void_p
        lib.vt_av1_open.argtypes = [ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int64, ctypes.c_int,
                                    ctypes.c_int]
        lib.vt_av1_send.restype = ctypes.c_int
        lib.vt_av1_send.argtypes = [ctypes.c_void_p, u8p, u8p, u8p,
                                    ctypes.c_int]
        lib.vt_av1_flush.restype = ctypes.c_int
        lib.vt_av1_flush.argtypes = [ctypes.c_void_p]
        lib.vt_av1_receive.restype = ctypes.c_int64
        lib.vt_av1_receive.argtypes = [
            ctypes.c_void_p, u8p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int64)]
        lib.vt_av1_close.restype = None
        lib.vt_av1_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB
