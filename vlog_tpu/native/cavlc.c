/* CAVLC slice entropy coder — native hot path.
 *
 * Mirrors vlog_tpu/codecs/h264/cavlc.py bit-for-bit (tests assert byte
 * equality). The reference delegated entropy coding to x264 inside the
 * ffmpeg subprocess (worker/hwaccel.py:647); in this framework the DSP
 * runs on the TPU and this file packs the quantized levels the device
 * emits — the one genuinely serial, host-bound stage of the encoder.
 *
 * Built by vlog_tpu/native/build.py (g++ -O3 -shared), loaded via
 * ctypes; vlog_tpu/codecs/h264/cavlc.py falls back to its Python path
 * when the library is unavailable.
 */

#include <stdint.h>
#include <string.h>

/* Table include is parameterized so concurrent per-process builds can
 * each use a private generated copy (see build.py). */
#ifndef VT_TABLES_INC
#define VT_TABLES_INC "cavlc_tables.inc"
#endif
#include VT_TABLES_INC

#ifdef __cplusplus
extern "C" {
#endif

typedef struct {
    uint8_t *buf;
    int64_t cap;
    int64_t nbytes;     /* complete bytes written */
    uint64_t acc;       /* bit accumulator (LSB-justified) */
    int nbits;          /* bits currently in acc (< 64) */
    int overflow;
} BitWriter;

static inline void bw_flush_bytes(BitWriter *w) {
    while (w->nbits >= 8) {
        if (w->nbytes >= w->cap) { w->overflow = 1; return; }
        w->nbits -= 8;
        w->buf[w->nbytes++] = (uint8_t)((w->acc >> w->nbits) & 0xFF);
    }
}

static inline void bw_put(BitWriter *w, uint32_t bits, int n) {
    /* n <= 32. Invariant: nbits < 32 on entry (every put ends by
     * flushing when >= 32), so acc never exceeds 63 bits. */
    w->acc = (w->acc << n) | (uint64_t)(bits & ((n == 32) ? 0xFFFFFFFFu : ((1u << n) - 1u)));
    w->nbits += n;
    if (w->nbits >= 32) bw_flush_bytes(w);
}

static inline void bw_put_ue(BitWriter *w, uint32_t v) {
    uint32_t code = v + 1;
    int nbits = 32 - __builtin_clz(code);
    bw_put(w, 0, nbits - 1);
    bw_put(w, code, nbits);
}

static inline void bw_put_se(BitWriter *w, int32_t v) {
    bw_put_ue(w, v > 0 ? (uint32_t)(2 * v - 1) : (uint32_t)(-2 * v));
}

static inline int token_table(int nc) {
    if (nc < 2) return 0;
    if (nc < 4) return 1;
    if (nc < 8) return 2;
    return 3;
}

/* residual_block_cavlc (spec 9.2). coeffs in scan order. Returns
 * TotalCoeff. nc == -1 selects the chroma-DC tables. */
static int encode_residual(BitWriter *w, const int32_t *coeffs, int n,
                           int nc) {
    int nz_idx[16];
    int total = 0;
    for (int i = 0; i < n; i++)
        if (coeffs[i] != 0) nz_idx[total++] = i;

    int trailing = 0;
    for (int k = total - 1; k >= 0; k--) {
        int32_t c = coeffs[nz_idx[k]];
        if ((c == 1 || c == -1) && trailing < 3) trailing++;
        else break;
    }

    int idx = 4 * total + trailing;
    if (nc == -1) {
        bw_put(w, CHROMA_DC_COEFF_TOKEN_BITS[idx], CHROMA_DC_COEFF_TOKEN_LEN[idx]);
    } else {
        int tbl = token_table(nc);
        bw_put(w, COEFF_TOKEN_BITS[tbl][idx], COEFF_TOKEN_LEN[tbl][idx]);
    }
    if (total == 0) return 0;

    for (int k = total - 1; k >= total - trailing; k--)
        bw_put(w, coeffs[nz_idx[k]] < 0 ? 1u : 0u, 1);

    int suffix_len = (total > 10 && trailing < 3) ? 1 : 0;
    int first = 1;
    for (int k = total - trailing - 1; k >= 0; k--) {
        int32_t level = coeffs[nz_idx[k]];
        int32_t code = level > 0 ? 2 * level - 2 : -2 * level - 1;
        if (first && trailing < 3) code -= 2;
        first = 0;
        if (suffix_len == 0) {
            if (code < 14) {
                bw_put(w, 1, code + 1);
            } else if (code < 30) {
                bw_put(w, 1, 15);
                bw_put(w, (uint32_t)(code - 14), 4);
            } else {
                if (code - 30 >= (1 << 12)) { w->overflow = 2; return total; }
                bw_put(w, 1, 16);
                bw_put(w, (uint32_t)(code - 30), 12);
            }
        } else {
            if (code < (15 << suffix_len)) {
                bw_put(w, 1, (code >> suffix_len) + 1);
                bw_put(w, (uint32_t)(code & ((1 << suffix_len) - 1)), suffix_len);
            } else {
                bw_put(w, 1, 16);
                int32_t rem = code - (15 << suffix_len);
                if (rem >= (1 << 12)) { w->overflow = 2; return total; }
                bw_put(w, (uint32_t)rem, 12);
            }
        }
        if (suffix_len == 0) suffix_len = 1;
        int32_t mag = level < 0 ? -level : level;
        if (mag > (3 << (suffix_len - 1)) && suffix_len < 6) suffix_len++;
    }

    int total_zeros = nz_idx[total - 1] + 1 - total;
    if (total < n) {
        if (nc == -1)
            bw_put(w, CHROMA_DC_TOTAL_ZEROS_BITS[total - 1][total_zeros],
                   CHROMA_DC_TOTAL_ZEROS_LEN[total - 1][total_zeros]);
        else
            bw_put(w, TOTAL_ZEROS_BITS[total - 1][total_zeros],
                   TOTAL_ZEROS_LEN[total - 1][total_zeros]);
    }

    int zeros_left = total_zeros;
    for (int k = total - 1; k >= 1; k--) {
        if (zeros_left <= 0) break;
        int run = nz_idx[k] - nz_idx[k - 1] - 1;
        int tbl = (zeros_left < 7 ? zeros_left : 7) - 1;
        bw_put(w, RUN_BEFORE_BITS[tbl][run], RUN_BEFORE_LEN[tbl][run]);
        zeros_left -= run;
    }
    return total;
}

static inline int nc_of(int avail_a, int na, int avail_b, int nb) {
    if (avail_a && avail_b) return (na + nb + 1) >> 1;
    if (avail_a) return na;
    if (avail_b) return nb;
    return 0;
}

/* Encode slice_data for one frame of I_16x16 levels.
 *
 * Array layouts (C-contiguous int32), matching encoder.FrameLevels:
 *   luma_dc   (mbh, mbw, 4, 4)
 *   luma_ac   (mbh, mbw, 4, 4, 4, 4)   [block gy, gx, then 4x4]
 *   chroma_dc (2, mbh, mbw, 2, 2)
 *   chroma_ac (2, mbh, mbw, 2, 2, 4, 4)
 *
 * header_bytes/header_bits: the already-encoded slice header — copied
 * in front, with its trailing partial bits continued seamlessly.
 * nz_scratch: caller-provided int32 scratch of size
 *   mbh*4*mbw*4 + 2*mbh*2*mbw*2  (zeroed by this function).
 *
 * Returns total bytes written (header + slice_data + rbsp trailing,
 * byte-aligned), or -1 on overflow / error.
 */
int64_t vt_cavlc_encode_slice(
    const int32_t *luma_dc, const int32_t *luma_ac,
    const int32_t *chroma_dc, const int32_t *chroma_ac,
    int mbh, int mbw,
    const uint8_t *header_bytes, int64_t n_header_bytes,
    uint32_t header_tail_bits, int n_header_tail_bits,
    int32_t *nz_scratch,
    uint8_t *out, int64_t out_cap)
{
    BitWriter w = {out, out_cap, 0, 0, 0, 0};
    if (n_header_bytes > out_cap) return -1;
    memcpy(out, header_bytes, (size_t)n_header_bytes);
    w.nbytes = n_header_bytes;
    if (n_header_tail_bits > 0)
        bw_put(&w, header_tail_bits, n_header_tail_bits);

    const int gw = mbw * 4;             /* luma nz grid width  */
    const int cw = mbw * 2;             /* chroma nz grid width */
    int32_t *nz_luma = nz_scratch;                    /* (mbh*4, gw) */
    int32_t *nz_chroma = nz_scratch + (int64_t)mbh * 4 * gw; /* (2, mbh*2, cw) */
    memset(nz_scratch, 0,
           sizeof(int32_t) * ((int64_t)mbh * 4 * gw + 2 * (int64_t)mbh * 2 * cw));

    int32_t scan[16];

    for (int my = 0; my < mbh; my++) {
        for (int mx = 0; mx < mbw; mx++) {
            const int32_t *dc = luma_dc + (((int64_t)my * mbw + mx) << 4);
            const int32_t *ac = luma_ac + (((int64_t)my * mbw + mx) << 8);
            const int32_t *cdc[2], *cac[2];
            for (int comp = 0; comp < 2; comp++) {
                cdc[comp] = chroma_dc
                    + ((((int64_t)comp * mbh + my) * mbw + mx) << 2);
                cac[comp] = chroma_ac
                    + ((((int64_t)comp * mbh + my) * mbw + mx) << 6);
            }

            int cbp_luma = 0;
            for (int i = 0; i < 256 && !cbp_luma; i++)
                if (ac[i]) cbp_luma = 15;
            int any_cac = 0, any_cdc = 0;
            for (int comp = 0; comp < 2 && !any_cac; comp++)
                for (int i = 0; i < 64 && !any_cac; i++)
                    if (cac[comp][i]) any_cac = 1;
            for (int comp = 0; comp < 2 && !any_cdc; comp++)
                for (int i = 0; i < 4 && !any_cdc; i++)
                    if (cdc[comp][i]) any_cdc = 1;
            int cbp_chroma = any_cac ? 2 : (any_cdc ? 1 : 0);

            int luma_mode = my == 0 ? 2 : 0;     /* DC : Vertical */
            int chroma_mode = my == 0 ? 0 : 2;
            int mb_type = 1 + luma_mode + 4 * cbp_chroma
                        + 12 * (cbp_luma ? 1 : 0);
            bw_put_ue(&w, (uint32_t)mb_type);
            bw_put_ue(&w, (uint32_t)chroma_mode);
            bw_put_se(&w, 0);                    /* mb_qp_delta */

            int gy = my * 4, gx = mx * 4;
            int nc = nc_of(gx > 0, gx > 0 ? nz_luma[gy * gw + gx - 1] : 0,
                           gy > 0, gy > 0 ? nz_luma[(gy - 1) * gw + gx] : 0);
            for (int i = 0; i < 16; i++) scan[i] = dc[ZIGZAG16[i]];
            encode_residual(&w, scan, 16, nc);

            if (cbp_luma) {
                for (int bi = 0; bi < 16; bi++) {
                    int blk = LUMA_ORDER[bi];
                    int by = blk >> 2, bx = blk & 3;
                    int y = gy + by, x = gx + bx;
                    const int32_t *b = ac + ((by * 4 + bx) << 4);
                    nc = nc_of(x > 0, x > 0 ? nz_luma[y * gw + x - 1] : 0,
                               y > 0, y > 0 ? nz_luma[(y - 1) * gw + x] : 0);
                    for (int i = 1; i < 16; i++) scan[i - 1] = b[ZIGZAG16[i]];
                    int tc = encode_residual(&w, scan, 15, nc);
                    nz_luma[y * gw + x] = tc;
                }
            }

            if (cbp_chroma > 0) {
                for (int comp = 0; comp < 2; comp++)
                    encode_residual(&w, cdc[comp], 4, -1);  /* raster 2x2 */
            }

            if (cbp_chroma == 2) {
                int cy = my * 2, cx = mx * 2;
                for (int comp = 0; comp < 2; comp++) {
                    int32_t *grid = nz_chroma + (int64_t)comp * mbh * 2 * cw;
                    for (int by = 0; by < 2; by++) {
                        for (int bx = 0; bx < 2; bx++) {
                            int y = cy + by, x = cx + bx;
                            const int32_t *b = cac[comp] + ((by * 2 + bx) << 4);
                            nc = nc_of(x > 0, x > 0 ? grid[y * cw + x - 1] : 0,
                                       y > 0, y > 0 ? grid[(y - 1) * cw + x] : 0);
                            for (int i = 1; i < 16; i++)
                                scan[i - 1] = b[ZIGZAG16[i]];
                            int tc = encode_residual(&w, scan, 15, nc);
                            grid[y * cw + x] = tc;
                        }
                    }
                }
            }
            if (w.overflow) return -1;
        }
    }

    /* rbsp trailing: stop bit + align */
    bw_put(&w, 1, 1);
    if (w.nbits & 7) bw_put(&w, 0, 8 - (w.nbits & 7));
    bw_flush_bytes(&w);
    if (w.overflow || w.nbits != 0) return -1;
    return w.nbytes;
}

/* ---------------------------------------------------------------------
 * P slices (P_L0_16x16 / P_Skip) — mirrors cavlc.PSliceEncoder bit-for-
 * bit (tests/test_native.py asserts byte equality). P frames are the
 * bulk of every chain (GOP_LEN-1 of GOP_LEN frames), so this is the
 * steady-state host entropy path.
 * ------------------------------------------------------------------- */

/* Table 9-4 "Inter" column: coded_block_pattern -> codeNum. */
static const uint8_t CBP_INTER_CODE[48] = {
    0, 2, 3, 7, 4, 8, 17, 13, 5, 18, 9, 14, 10, 15, 16, 11,
    1, 32, 33, 36, 34, 37, 44, 40, 35, 45, 38, 41, 39, 42, 43, 19,
    6, 24, 25, 20, 26, 21, 46, 28, 27, 47, 22, 29, 23, 30, 31, 12,
};

static inline int32_t median3(int32_t a, int32_t b, int32_t c) {
    if (a > b) { int32_t t = a; a = b; b = t; }
    if (b > c) { b = c; }
    return a > b ? a : b;
}

/* Median MV predictor (8.4.1.3.1) over the quarter-pel mv grid.
 * mvs: (mbh, mbw, 2) as (x, y). */
static void mv_pred(const int32_t *mvs, int mbh, int mbw, int my, int mx,
                    int32_t *px, int32_t *py) {
    int a_ok = mx > 0;
    int b_ok = my > 0;
    int c_ok = b_ok && mx < mbw - 1;
    int d_ok = b_ok && mx > 0;
    int32_t ax = 0, ay = 0, bx = 0, by = 0, cx = 0, cy = 0;
    int c_av = 0;
    if (a_ok) {
        ax = mvs[((int64_t)my * mbw + mx - 1) * 2];
        ay = mvs[((int64_t)my * mbw + mx - 1) * 2 + 1];
    }
    if (b_ok) {
        bx = mvs[(((int64_t)my - 1) * mbw + mx) * 2];
        by = mvs[(((int64_t)my - 1) * mbw + mx) * 2 + 1];
    }
    if (c_ok) {
        c_av = 1;
        cx = mvs[(((int64_t)my - 1) * mbw + mx + 1) * 2];
        cy = mvs[(((int64_t)my - 1) * mbw + mx + 1) * 2 + 1];
    } else if (d_ok) {
        c_av = 1;
        cx = mvs[(((int64_t)my - 1) * mbw + mx - 1) * 2];
        cy = mvs[(((int64_t)my - 1) * mbw + mx - 1) * 2 + 1];
    }
    int n_avail = a_ok + b_ok + c_av;
    if (n_avail == 1) {
        if (a_ok) { *px = ax; *py = ay; }
        else if (b_ok) { *px = bx; *py = by; }
        else { *px = cx; *py = cy; }
        return;
    }
    *px = median3(ax, bx, cx);
    *py = median3(ay, by, cy);
}

/* P_Skip inferred MV (8.4.1.1). */
static void skip_mv(const int32_t *mvs, int mbh, int mbw, int my, int mx,
                    int32_t *px, int32_t *py) {
    int a_ok = mx > 0;
    int b_ok = my > 0;
    if (!a_ok || !b_ok) { *px = 0; *py = 0; return; }
    const int32_t *a = mvs + ((int64_t)my * mbw + mx - 1) * 2;
    const int32_t *b = mvs + (((int64_t)my - 1) * mbw + mx) * 2;
    if ((a[0] == 0 && a[1] == 0) || (b[0] == 0 && b[1] == 0)) {
        *px = 0; *py = 0; return;
    }
    mv_pred(mvs, mbh, mbw, my, mx, px, py);
}

/* i8x8/i4x4 coding-order offsets (quadrant zigzag). */
static const int BLK2[4][2] = {{0, 0}, {0, 1}, {1, 0}, {1, 1}};

/* Encode one P frame's slice_data.
 *
 * Layouts (C-contiguous int32):
 *   luma      (mbh, mbw, 4, 4, 4, 4)   [block by, bx, then 4x4]
 *   chroma_dc (2, mbh, mbw, 2, 2)
 *   chroma_ac (2, mbh, mbw, 2, 2, 4, 4)
 *   mv        (mbh, mbw, 2)            QUARTER pels, (y, x) — DSP order
 * scratch: int32 of size mbh*4*mbw*4 + 2*mbh*2*mbw*2 + mbh*mbw*2.
 * Returns bytes written or -1 on overflow.
 */
int64_t vt_cavlc_encode_p_slice(
    const int32_t *luma, const int32_t *chroma_dc, const int32_t *chroma_ac,
    const int32_t *mv,
    int mbh, int mbw,
    const uint8_t *header_bytes, int64_t n_header_bytes,
    uint32_t header_tail_bits, int n_header_tail_bits,
    int32_t *scratch,
    uint8_t *out, int64_t out_cap)
{
    BitWriter w = {out, out_cap, 0, 0, 0, 0};
    if (n_header_bytes > out_cap) return -1;
    memcpy(out, header_bytes, (size_t)n_header_bytes);
    w.nbytes = n_header_bytes;
    if (n_header_tail_bits > 0)
        bw_put(&w, header_tail_bits, n_header_tail_bits);

    const int gw = mbw * 4;
    const int cw = mbw * 2;
    int32_t *nz_luma = scratch;
    int32_t *nz_chroma = scratch + (int64_t)mbh * 4 * gw;
    int32_t *mvs = nz_chroma + 2 * (int64_t)mbh * 2 * cw;  /* quarter, (x,y) */
    memset(scratch, 0, sizeof(int32_t) *
           ((int64_t)mbh * 4 * gw + 2 * (int64_t)mbh * 2 * cw
            + (int64_t)mbh * mbw * 2));

    int32_t scan[16];
    uint32_t skip_run = 0;

    for (int my = 0; my < mbh; my++) {
        for (int mx = 0; mx < mbw; mx++) {
            const int64_t mb = (int64_t)my * mbw + mx;
            const int32_t *lu = luma + (mb << 8);
            const int32_t *cdc[2], *cac[2];
            for (int comp = 0; comp < 2; comp++) {
                cdc[comp] = chroma_dc + ((((int64_t)comp * mbh + my) * mbw + mx) << 2);
                cac[comp] = chroma_ac + ((((int64_t)comp * mbh + my) * mbw + mx) << 6);
            }
            /* bitstream (x, y) from DSP (y, x), both quarter-pel */
            int32_t mvx = mv[mb * 2 + 1];
            int32_t mvy = mv[mb * 2];

            /* CBP: luma bit per 8x8 quadrant + chroma 0/1/2 */
            int cbp = 0;
            for (int i8 = 0; i8 < 4; i8++) {
                int oy = BLK2[i8][0], ox = BLK2[i8][1];
                int any = 0;
                for (int s = 0; s < 4 && !any; s++) {
                    int by = 2 * oy + BLK2[s][0], bx = 2 * ox + BLK2[s][1];
                    const int32_t *b = lu + ((by * 4 + bx) << 4);
                    for (int i = 0; i < 16; i++)
                        if (b[i]) { any = 1; break; }
                }
                if (any) cbp |= 1 << i8;
            }
            int any_cac = 0, any_cdc = 0;
            for (int comp = 0; comp < 2 && !any_cac; comp++)
                for (int i = 0; i < 64; i++)
                    if (cac[comp][i]) { any_cac = 1; break; }
            for (int comp = 0; comp < 2 && !any_cdc; comp++)
                for (int i = 0; i < 4; i++)
                    if (cdc[comp][i]) { any_cdc = 1; break; }
            cbp |= (any_cac ? 2 : (any_cdc ? 1 : 0)) << 4;

            int32_t smx, smy;
            skip_mv(mvs, mbh, mbw, my, mx, &smx, &smy);
            if (cbp == 0 && mvx == smx && mvy == smy) {
                mvs[mb * 2] = smx;
                mvs[mb * 2 + 1] = smy;
                skip_run++;
                continue;
            }
            bw_put_ue(&w, skip_run);
            skip_run = 0;
            int32_t pmx, pmy;
            mv_pred(mvs, mbh, mbw, my, mx, &pmx, &pmy);
            mvs[mb * 2] = mvx;
            mvs[mb * 2 + 1] = mvy;
            bw_put_ue(&w, 0);                    /* mb_type P_L0_16x16 */
            bw_put_se(&w, mvx - pmx);
            bw_put_se(&w, mvy - pmy);
            bw_put_ue(&w, CBP_INTER_CODE[cbp]);
            if (cbp) {
                bw_put_se(&w, 0);                /* mb_qp_delta */
                int gy = my * 4, gx = mx * 4;
                for (int i8 = 0; i8 < 4; i8++) {
                    int oy = BLK2[i8][0], ox = BLK2[i8][1];
                    for (int s = 0; s < 4; s++) {
                        int by = 2 * oy + BLK2[s][0], bx = 2 * ox + BLK2[s][1];
                        int y = gy + by, x = gx + bx;
                        if (!((cbp >> i8) & 1)) {
                            nz_luma[y * gw + x] = 0;
                            continue;
                        }
                        const int32_t *b = lu + ((by * 4 + bx) << 4);
                        int nc = nc_of(x > 0, x > 0 ? nz_luma[y * gw + x - 1] : 0,
                                       y > 0, y > 0 ? nz_luma[(y - 1) * gw + x] : 0);
                        for (int i = 0; i < 16; i++) scan[i] = b[ZIGZAG16[i]];
                        int tc = encode_residual(&w, scan, 16, nc);
                        nz_luma[y * gw + x] = tc;
                    }
                }
                int cbp_chroma = cbp >> 4;
                if (cbp_chroma > 0) {
                    for (int comp = 0; comp < 2; comp++)
                        encode_residual(&w, cdc[comp], 4, -1);
                }
                int cy = my * 2, cx = mx * 2;
                for (int comp = 0; comp < 2; comp++) {
                    int32_t *grid = nz_chroma + (int64_t)comp * mbh * 2 * cw;
                    for (int by = 0; by < 2; by++) {
                        for (int bx = 0; bx < 2; bx++) {
                            int y = cy + by, x = cx + bx;
                            if (cbp_chroma != 2) {
                                grid[y * cw + x] = 0;
                                continue;
                            }
                            const int32_t *b = cac[comp] + ((by * 2 + bx) << 4);
                            int nc = nc_of(x > 0, x > 0 ? grid[y * cw + x - 1] : 0,
                                           y > 0, y > 0 ? grid[(y - 1) * cw + x] : 0);
                            for (int i = 1; i < 16; i++)
                                scan[i - 1] = b[ZIGZAG16[i]];
                            int tc = encode_residual(&w, scan, 15, nc);
                            grid[y * cw + x] = tc;
                        }
                    }
                }
            } else {
                /* nz grids for an uncoded MB: all zero */
                int gy = my * 4, gx = mx * 4;
                for (int by = 0; by < 4; by++)
                    for (int bx = 0; bx < 4; bx++)
                        nz_luma[(gy + by) * gw + gx + bx] = 0;
                int cy = my * 2, cx = mx * 2;
                for (int comp = 0; comp < 2; comp++) {
                    int32_t *grid = nz_chroma + (int64_t)comp * mbh * 2 * cw;
                    for (int by = 0; by < 2; by++)
                        for (int bx = 0; bx < 2; bx++)
                            grid[(cy + by) * cw + cx + bx] = 0;
                }
            }
            if (w.overflow) return -1;
        }
    }
    if (skip_run) bw_put_ue(&w, skip_run);      /* trailing skips */

    bw_put(&w, 1, 1);
    if (w.nbits & 7) bw_put(&w, 0, 8 - (w.nbits & 7));
    bw_flush_bytes(&w);
    if (w.overflow || w.nbits != 0) return -1;
    return w.nbytes;
}

/* Emulation-prevention escaping (H.264 7.4.1): out must have capacity
 * for worst case 3n/2. Returns escaped length. */
int64_t vt_escape_emulation(const uint8_t *in, int64_t n, uint8_t *out) {
    int64_t j = 0;
    int zeros = 0;
    for (int64_t i = 0; i < n; i++) {
        uint8_t b = in[i];
        if (zeros >= 2 && b <= 3) {
            out[j++] = 3;
            zeros = 0;
        }
        out[j++] = b;
        zeros = (b == 0) ? zeros + 1 : 0;
    }
    return j;
}

#ifdef __cplusplus
}
#endif
