"""Native (C) host runtime: entropy coding hot loops.

The TPU owns the DSP; this package owns the serial bit-packing the host
must do per frame (CAVLC slice coding, NAL escaping). See build.py for
the on-demand toolchain story.
"""

from vlog_tpu.native.build import NativeBuildError, get_lib  # noqa: F401
