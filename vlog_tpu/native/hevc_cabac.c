/* HEVC CABAC slice coder — C port of codecs/hevc/{cabac,residual,slice}.py.
 *
 * Same role as cavlc.c for the H.264 path: the device (JAX) produces
 * quantized coefficient levels per CTB; this packs one whole I-slice's
 * CABAC payload on the host at C speed.  Bit-exactness with the Python
 * reference is asserted by tests/test_hevc.py (and transitively with
 * libavcodec by the oracle tests there).
 *
 * Stream shape (see codecs/hevc/syntax.py): 32x32 CTB == CU, 2Nx2N
 * intra mode 26, one 32x32 luma TB + two 16x16 chroma TBs, no SAO/
 * deblock/transform-skip/sign-hiding, diagonal scans only.
 */

#include <stdint.h>
#include <string.h>

#ifndef VT_HEVC_TABLES_INC
#define VT_HEVC_TABLES_INC "hevc_tables.inc"
#endif
#include VT_HEVC_TABLES_INC
#include "cabac_engine.h"

/* engine lives in cabac_engine.h (shared with h264_cabac_enc.c) */
#define enc_bin cab_bin
#define enc_bypass cab_bypass
#define enc_bypass_bits cab_bypass_bits
#define enc_terminate cab_terminate
#define cabac_finish cab_finish

static void cabac_init(Cabac *c, int qp, int init_type, uint8_t *out,
                       int64_t cap) {
    cab_start(c, out, cap);
    if (qp < 0) qp = 0; if (qp > 51) qp = 51;
    for (int i = 0; i < 199; i++) {
        int init_value = init_type ? HEVC_INIT_P[i] : HEVC_INIT_I[i];
        int slope = (init_value >> 4) * 5 - 45;
        int offset = ((init_value & 15) << 3) - 16;
        int pre = ((slope * qp) >> 4) + offset;
        if (pre < 1) pre = 1; if (pre > 126) pre = 126;
        if (pre <= 63) { c->pstate[i] = (uint8_t)(63 - pre); c->mps[i] = 0; }
        else { c->pstate[i] = (uint8_t)(pre - 64); c->mps[i] = 1; }
    }
}

/* ------------------------------------------------------------- residual */

static const uint8_t GROUP_IDX[32] = {0,1,2,3,4,4,5,5,6,6,6,6,7,7,7,7,
                                      8,8,8,8,8,8,8,8,9,9,9,9,9,9,9,9};
static const uint8_t MIN_IN_GROUP[10] = {0,1,2,3,4,6,8,12,16,24};

/* whole-TB forward scans (HEVC_SCAN32/HEVC_SCAN16) come precomputed
 * from the generated header: constant data, safe under the entropy
 * thread pool with no init ordering to get wrong. */

static void write_last_prefix(Cabac *c, int group, int cmax, int base,
                              int offset, int shift) {
    for (int b = 0; b < group; b++)
        enc_bin(c, base + offset + (b >> shift), 1);
    if (group < cmax)
        enc_bin(c, base + offset + (group >> shift), 0);
}

static void write_remaining(Cabac *c, int value, int rice) {
    if (value < (3 << rice)) {
        for (int i = 0; i < (value >> rice); i++) enc_bypass(c, 1);
        enc_bypass(c, 0);
        if (rice) enc_bypass_bits(c, value & ((1 << rice) - 1), rice);
    } else {
        int length = rice;
        value -= 3 << rice;
        while (value >= (1 << length)) { value -= 1 << length; length++; }
        for (int i = 0; i < 3 + length - rice; i++) enc_bypass(c, 1);
        enc_bypass(c, 0);
        if (length) enc_bypass_bits(c, (uint32_t)value, length);
    }
}

static int sig_ctx(int x, int y, int c_idx, int prev_csbf) {
    if (x == 0 && y == 0) return c_idx == 0 ? 0 : 27;
    int xp = x & 3, yp = y & 3, s;
    if (prev_csbf == 0)      s = (xp + yp == 0) ? 2 : (xp + yp < 3 ? 1 : 0);
    else if (prev_csbf == 1) s = (yp == 0) ? 2 : (yp == 1 ? 1 : 0);
    else if (prev_csbf == 2) s = (xp == 0) ? 2 : (xp == 1 ? 1 : 0);
    else                     s = 2;
    if (c_idx == 0) {
        if ((x >> 2) || (y >> 2)) s += 3;
        return s + 21;
    }
    return 27 + s + 12;
}

/* levels: raster (N, N) int16; at least one nonzero */
static void write_residual(Cabac *c, const int16_t *lv, int log2_size,
                           int c_idx) {
    const int n = 1 << log2_size, n_cg = n >> 2;
    const int16_t *scan = (n == 32) ? HEVC_SCAN32 : HEVC_SCAN16;
    const uint8_t *cg_scan = (n_cg == 8) ? HEVC_DIAG8 : HEVC_DIAG4;

    int last_scan = -1;
    for (int i = n * n - 1; i >= 0; i--)
        if (lv[scan[i]]) { last_scan = i; break; }
    int last_x = scan[last_scan] % n, last_y = scan[last_scan] / n;

    int cmax = (log2_size << 1) - 1, offset, shift;
    if (c_idx == 0) {
        offset = 3 * (log2_size - 2) + ((log2_size - 1) >> 2);
        shift = (log2_size + 1) >> 2;
    } else { offset = 15; shift = log2_size - 2; }
    int gx = GROUP_IDX[last_x], gy = GROUP_IDX[last_y];
    write_last_prefix(c, gx, cmax, HEVC_CTX_LAST_X_PREFIX, offset, shift);
    write_last_prefix(c, gy, cmax, HEVC_CTX_LAST_Y_PREFIX, offset, shift);
    if (gx > 3)
        enc_bypass_bits(c, (uint32_t)(last_x - MIN_IN_GROUP[gx]),
                        (gx >> 1) - 1);
    if (gy > 3)
        enc_bypass_bits(c, (uint32_t)(last_y - MIN_IN_GROUP[gy]),
                        (gy >> 1) - 1);

    uint8_t csbf[64];
    for (int cy = 0; cy < n_cg; cy++)
        for (int cx = 0; cx < n_cg; cx++) {
            int any = 0;
            for (int yy = 0; yy < 4 && !any; yy++)
                for (int xx = 0; xx < 4; xx++)
                    if (lv[(cy * 4 + yy) * n + cx * 4 + xx]) { any = 1; break; }
            csbf[cy * n_cg + cx] = (uint8_t)any;
        }

    int last_cg = last_scan >> 4;
    int greater1_ctx = 1, first_cg_done = 0;
    for (int ci = last_cg; ci >= 0; ci--) {
        int cx = cg_scan[ci] >> 4, cy = cg_scan[ci] & 15;
        int coded = csbf[cy * n_cg + cx];
        int is_explicit = (ci != last_cg && ci != 0);
        int right = (cx + 1 < n_cg) && csbf[cy * n_cg + cx + 1];
        int below = (cy + 1 < n_cg) && csbf[(cy + 1) * n_cg + cx];
        if (is_explicit) {
            enc_bin(c, HEVC_CTX_SIG_CG_FLAG + (c_idx ? 2 : 0)
                       + ((right || below) ? 1 : 0), coded);
            if (!coded) continue;
        }
        int prev_csbf = right + 2 * below;

        int start = (ci == last_cg) ? (last_scan % 16) - 1 : 15;
        int infer_dc = is_explicit;
        int sig_pos[16], nsig = 0;       /* coding order (reverse scan) */
        if (ci == last_cg) sig_pos[nsig++] = scan[last_scan];
        for (int j = start; j >= 0; j--) {
            int pos = scan[(ci << 4) + j];
            int significant = lv[pos] != 0;
            if (j == 0 && infer_dc && nsig == 0) {
                sig_pos[nsig++] = pos;   /* inferred 1 */
                continue;
            }
            enc_bin(c, HEVC_CTX_SIG_COEFF
                       + sig_ctx(pos % n, pos / n, c_idx, prev_csbf),
                    significant);
            if (significant) sig_pos[nsig++] = pos;
        }
        if (!nsig) continue;             /* all-zero CG0 */

        int ctx_set = (ci > 0 && c_idx == 0) ? 2 : 0;
        if (first_cg_done && greater1_ctx == 0) ctx_set++;
        first_cg_done = 1;
        greater1_ctx = 1;
        int g1_flags[8], g2_pos = -1;
        int ng1 = nsig < 8 ? nsig : 8;
        for (int k = 0; k < ng1; k++) {
            int absl = lv[sig_pos[k]] < 0 ? -lv[sig_pos[k]] : lv[sig_pos[k]];
            int flag = absl > 1;
            int base = HEVC_CTX_GREATER1 + (c_idx ? 16 : 0);
            int c1m = greater1_ctx < 3 ? greater1_ctx : 3;
            enc_bin(c, base + ctx_set * 4 + c1m, flag);
            g1_flags[k] = flag;
            if (flag) {
                if (g2_pos < 0) g2_pos = k;
                greater1_ctx = 0;
            } else if (greater1_ctx > 0 && greater1_ctx < 3) greater1_ctx++;
        }
        int g2_flag = 0;
        if (g2_pos >= 0) {
            int absl = lv[sig_pos[g2_pos]] < 0 ? -lv[sig_pos[g2_pos]]
                                               : lv[sig_pos[g2_pos]];
            g2_flag = absl > 2;
            enc_bin(c, HEVC_CTX_GREATER2 + (c_idx ? 4 + ctx_set : ctx_set),
                    g2_flag);
        }
        for (int k = 0; k < nsig; k++)
            enc_bypass(c, lv[sig_pos[k]] < 0);
        int rice = 0;
        for (int k = 0; k < nsig; k++) {
            int absl = lv[sig_pos[k]] < 0 ? -lv[sig_pos[k]] : lv[sig_pos[k]];
            int base_level;
            if (k < 8) {
                if (!g1_flags[k]) continue;
                if (k == g2_pos) {
                    if (!g2_flag) continue;
                    base_level = 3;
                } else base_level = 2;
            } else base_level = 1;
            write_remaining(c, absl - base_level, rice);
            if (absl > (3 << rice) && rice < 4) rice++;
        }
    }
}

/* -------------------------------------------------------------- slice */

static int any_nonzero(const int16_t *lv, int count) {
    for (int i = 0; i < count; i++) if (lv[i]) return 1;
    return 0;
}

/* One 32x32 intra CTU (see slice.py for the bin-by-bin derivation). */
static void write_ctu(Cabac *c, int col, const int16_t *luma,
                      const int16_t *cb, const int16_t *cr, int last) {
    enc_bin(c, HEVC_CTX_PART_MODE, 1);          /* 2Nx2N */
    enc_bin(c, HEVC_CTX_PREV_INTRA_LUMA, 1);    /* always an MPM hit */
    if (col == 0) { enc_bypass(c, 1); enc_bypass(c, 1); }  /* mpm_idx 2 */
    else enc_bypass(c, 0);                                  /* mpm_idx 0 */
    enc_bin(c, HEVC_CTX_INTRA_CHROMA_PRED, 0);  /* DM */

    int cbf_cb = cb && any_nonzero(cb, 256);
    int cbf_cr = cr && any_nonzero(cr, 256);
    int cbf_luma = luma && any_nonzero(luma, 1024);
    enc_bin(c, HEVC_CTX_CBF_CB_CR, cbf_cb);
    enc_bin(c, HEVC_CTX_CBF_CB_CR, cbf_cr);
    enc_bin(c, HEVC_CTX_CBF_LUMA + 1, cbf_luma);
    if (cbf_luma) write_residual(c, luma, 5, 0);
    if (cbf_cb) write_residual(c, cb, 4, 1);
    if (cbf_cr) write_residual(c, cr, 4, 2);
    enc_terminate(c, last);
}

/* ----------------------------------------------------------- entry point
 * luma: rows*cols blocks of 1024 int16 (raster within block);
 * cb/cr: rows*cols blocks of 256. Returns payload size or -1 (overflow).
 */
extern "C" int64_t vt_hevc_encode_slice(
        const int16_t *luma, const int16_t *cb, const int16_t *cr,
        int32_t rows, int32_t cols, int32_t slice_qp,
        uint8_t *out, int64_t out_cap) {
    Cabac c;
    cabac_init(&c, slice_qp, 0, out, out_cap);
    for (int r = 0; r < rows; r++)
        for (int col = 0; col < cols; col++) {
            int i = r * cols + col;
            write_ctu(&c, col, luma + (int64_t)i * 1024,
                      cb + (int64_t)i * 256, cr + (int64_t)i * 256,
                      r == rows - 1 && col == cols - 1);
        }
    return cabac_finish(&c);
}

/* --------------------------------------------------------- P slices
 * Mirror of codecs/hevc/pslice.py: every CTB an inter 2Nx2N CU with an
 * explicitly coded MV (AMVP candidate 0, no merge/skip).
 * mv: (rows*cols, 2) int32 as (y, x) QUARTER luma pels (DSP order).
 */

static void write_mvd(Cabac *c, int dx, int dy) {
    int comps[2] = {dx, dy};
    int g0[2] = {dx != 0, dy != 0};
    int g1[2] = {dx > 1 || dx < -1, dy > 1 || dy < -1};
    enc_bin(c, HEVC_CTX_MVD_GREATER, g0[0]);
    enc_bin(c, HEVC_CTX_MVD_GREATER, g0[1]);
    if (g0[0]) enc_bin(c, HEVC_CTX_MVD_GREATER + 3, g1[0]);
    if (g0[1]) enc_bin(c, HEVC_CTX_MVD_GREATER + 3, g1[1]);
    for (int i = 0; i < 2; i++) {
        int v = comps[i];
        if (!g0[i]) continue;
        if (g1[i]) {
            int rem = (v < 0 ? -v : v) - 2;
            int k = 1;                       /* EG1 bypass */
            while (rem >= (1 << k)) { enc_bypass(c, 1); rem -= 1 << k; k++; }
            enc_bypass(c, 0);
            enc_bypass_bits(c, (uint32_t)rem, k);
        }
        enc_bypass(c, v < 0);
    }
}

extern "C" int64_t vt_hevc_encode_p_slice(
        const int16_t *luma, const int16_t *cb, const int16_t *cr,
        const int32_t *mv,
        int32_t rows, int32_t cols, int32_t slice_qp,
        int32_t *mv_scratch,      /* rows*cols*2, holds (x, y) qpel */
        uint8_t *out, int64_t out_cap) {
    Cabac c;
    cabac_init(&c, slice_qp, 1, out, out_cap);
    for (int r = 0; r < rows; r++)
        for (int col = 0; col < cols; col++) {
            int i = r * cols + col;
            enc_bin(&c, HEVC_CTX_SKIP, 0);          /* cu_skip_flag */
            enc_bin(&c, HEVC_CTX_PRED_MODE, 0);     /* MODE_INTER */
            enc_bin(&c, HEVC_CTX_PART_MODE, 1);     /* 2Nx2N */
            enc_bin(&c, HEVC_CTX_MERGE_FLAG, 0);
            int mvx = mv[i * 2 + 1], mvy = mv[i * 2];
            /* AMVP candidate 0: left CU, else first of B0/B1/B2
             * (every CTB here is inter, so availability is purely
             * positional — matches MvpGrid in an all-inter slice) */
            int px = 0, py = 0;
            if (col > 0) {
                px = mv_scratch[(i - 1) * 2];
                py = mv_scratch[(i - 1) * 2 + 1];
            } else if (r > 0) {
                int j = (r - 1) * cols + col + 1;   /* B0 */
                if (col + 1 >= cols) j = (r - 1) * cols + col;  /* B1 */
                px = mv_scratch[j * 2];
                py = mv_scratch[j * 2 + 1];
            }
            write_mvd(&c, mvx - px, mvy - py);
            enc_bin(&c, HEVC_CTX_MVP_LX, 0);        /* mvp_l0_flag */
            mv_scratch[i * 2] = mvx;
            mv_scratch[i * 2 + 1] = mvy;

            const int16_t *lu = luma + (int64_t)i * 1024;
            const int16_t *ub = cb + (int64_t)i * 256;
            const int16_t *vb = cr + (int64_t)i * 256;
            int cbf_l = any_nonzero(lu, 1024);
            int cbf_cb = any_nonzero(ub, 256);
            int cbf_cr = any_nonzero(vb, 256);
            int root = cbf_l || cbf_cb || cbf_cr;
            enc_bin(&c, HEVC_CTX_NO_RESIDUAL, root); /* rqt_root_cbf */
            if (root) {
                enc_bin(&c, HEVC_CTX_CBF_CB_CR, cbf_cb);
                enc_bin(&c, HEVC_CTX_CBF_CB_CR, cbf_cr);
                if (cbf_cb || cbf_cr)
                    enc_bin(&c, HEVC_CTX_CBF_LUMA + 1, cbf_l);
                /* else: cbf_luma inferred 1 */
                if (cbf_l) write_residual(&c, lu, 5, 0);
                if (cbf_cb) write_residual(&c, ub, 4, 1);
                if (cbf_cr) write_residual(&c, vb, 4, 2);
            }
            enc_terminate(&c, r == rows - 1 && col == cols - 1);
        }
    return cabac_finish(&c);
}
