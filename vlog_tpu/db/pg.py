"""PostgreSQL backend for the async database facade.

The reference's source of truth is Postgres (`databases.Database` over
asyncpg, api/database.py:11), and its claim protocol is built on
``SELECT ... FOR UPDATE SKIP LOCKED`` row locking
(worker_api.py:1494-1556). This module provides the same facade API as
:class:`vlog_tpu.db.core.Database` — ``fetch_one`` / ``fetch_all`` /
``execute`` / ``transaction()`` with ``:name`` parameters — against a
real Postgres server, so a multi-node fleet gets genuine concurrent
row-locked claims instead of sqlite's single-writer serialization.

No asyncpg/psycopg is available in this environment, so the driver is
first-party: ctypes over the system ``libpq.so.5`` (text protocol via
``PQexecParams``), with blocking calls pushed to threads. A small
connection pool backs the facade; ``transaction()`` pins one connection
for its scope, so independent transactions run on independent
connections — which is precisely what makes ``FOR UPDATE SKIP LOCKED``
meaningful (two claimants contend on row locks, not on a Python mutex).

Dialect notes handled here so callers stay single-source:

- ``:name`` parameters are rewritten to ``$n`` positionals.
- sqlite DDL is rewritten on the fly: ``INTEGER PRIMARY KEY
  AUTOINCREMENT`` -> ``BIGSERIAL PRIMARY KEY``, ``REAL`` -> ``DOUBLE
  PRECISION`` (PG ``REAL`` is float4 — too coarse for epoch-seconds
  lease math), ``BLOB`` -> ``BYTEA``.
- ``execute()`` returns the inserted ``id`` for INSERTs (the sqlite
  facade's lastrowid contract) by appending ``RETURNING id`` when the
  target table has an ``id`` column (catalog-checked, cached).
- :data:`Database.row_lock_suffix` is ``" FOR UPDATE SKIP LOCKED"``
  here and ``""`` on sqlite; the claim query appends it.
- ``greatest()``: ``GREATEST`` here, two-arg ``MAX`` on sqlite.
"""

from __future__ import annotations

import asyncio
import ctypes
import ctypes.util
import re
import threading
from collections.abc import AsyncIterator, Iterable, Mapping
from contextlib import asynccontextmanager
from typing import Any

from vlog_tpu.utils import failpoints

Row = dict[str, Any]
Params = Mapping[str, Any] | None

# -- libpq result / connection status codes (libpq-fe.h) -------------------
CONNECTION_OK = 0
PGRES_COMMAND_OK = 1
PGRES_TUPLES_OK = 2

# text-format OIDs we decode to Python types (pg_type.h)
_OID_BOOL = 16
_OID_BYTEA = 17
_OID_INT8 = 20
_OID_INT2 = 21
_OID_INT4 = 23
_OID_OID = 26
_OID_FLOAT4 = 700
_OID_FLOAT8 = 701
_OID_NUMERIC = 1700

_LIBPQ: ctypes.CDLL | None = None


def load_libpq() -> ctypes.CDLL:
    """Load and prototype the system libpq (cached)."""
    global _LIBPQ
    if _LIBPQ is not None:
        return _LIBPQ
    name = ctypes.util.find_library("pq") or "libpq.so.5"
    lib = ctypes.CDLL(name)
    c_char_pp = ctypes.POINTER(ctypes.c_char_p)
    c_int_p = ctypes.POINTER(ctypes.c_int)
    lib.PQconnectdb.restype = ctypes.c_void_p
    lib.PQconnectdb.argtypes = [ctypes.c_char_p]
    lib.PQstatus.restype = ctypes.c_int
    lib.PQstatus.argtypes = [ctypes.c_void_p]
    lib.PQfinish.restype = None
    lib.PQfinish.argtypes = [ctypes.c_void_p]
    lib.PQerrorMessage.restype = ctypes.c_char_p
    lib.PQerrorMessage.argtypes = [ctypes.c_void_p]
    lib.PQexecParams.restype = ctypes.c_void_p
    lib.PQexecParams.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_void_p,      # paramTypes (NULL: infer)
        c_char_pp,            # paramValues
        c_int_p,              # paramLengths
        c_int_p,              # paramFormats
        ctypes.c_int,         # resultFormat: 0 = text
    ]
    lib.PQresultStatus.restype = ctypes.c_int
    lib.PQresultStatus.argtypes = [ctypes.c_void_p]
    lib.PQresultErrorMessage.restype = ctypes.c_char_p
    lib.PQresultErrorMessage.argtypes = [ctypes.c_void_p]
    lib.PQresultErrorField.restype = ctypes.c_char_p
    lib.PQresultErrorField.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.PQclear.restype = None
    lib.PQclear.argtypes = [ctypes.c_void_p]
    lib.PQntuples.restype = ctypes.c_int
    lib.PQntuples.argtypes = [ctypes.c_void_p]
    lib.PQnfields.restype = ctypes.c_int
    lib.PQnfields.argtypes = [ctypes.c_void_p]
    lib.PQfname.restype = ctypes.c_char_p
    lib.PQfname.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.PQftype.restype = ctypes.c_uint
    lib.PQftype.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.PQgetvalue.restype = ctypes.c_char_p
    lib.PQgetvalue.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.PQgetisnull.restype = ctypes.c_int
    lib.PQgetisnull.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.PQgetlength.restype = ctypes.c_int
    lib.PQgetlength.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.PQcmdTuples.restype = ctypes.c_char_p
    lib.PQcmdTuples.argtypes = [ctypes.c_void_p]
    lib.PQlibVersion.restype = ctypes.c_int
    lib.PQlibVersion.argtypes = []
    # LISTEN/NOTIFY plumbing (jobs/events.py PgNotifyBus)
    lib.PQsocket.restype = ctypes.c_int
    lib.PQsocket.argtypes = [ctypes.c_void_p]
    lib.PQconsumeInput.restype = ctypes.c_int
    lib.PQconsumeInput.argtypes = [ctypes.c_void_p]
    lib.PQnotifies.restype = ctypes.POINTER(PGnotify)
    lib.PQnotifies.argtypes = [ctypes.c_void_p]
    lib.PQfreemem.restype = None
    lib.PQfreemem.argtypes = [ctypes.c_void_p]
    _LIBPQ = lib
    return lib


class PGnotify(ctypes.Structure):
    """libpq-fe.h pgNotify (public prefix; trailing private fields are
    never touched through this layout)."""
    _fields_ = [("relname", ctypes.c_char_p),
                ("be_pid", ctypes.c_int),
                ("extra", ctypes.c_char_p)]


class PgError(RuntimeError):
    def __init__(self, message: str, sqlstate: str | None = None):
        super().__init__(message)
        self.sqlstate = sqlstate


# -- SQL translation --------------------------------------------------------

# Alternation order matters: quoted regions (single-quoted literals with
# '' escapes, double-quoted identifiers, E'' strings with backslash
# escapes) match first and pass through verbatim, so a literal colon-word
# inside a string ('tag:foo', time formats) is never rewritten.
_PARAM_OR_QUOTE_RE = re.compile(
    r"""
    (?P<quote> (?<!\w)[eE]'(?:[^'\\]|''|\\.)*'   # E'' string (\ escapes;
                                          # \w guard: LIKE'x' is not E'')
             | '(?:[^']|'')*'             # standard literal ('' escapes)
             | "(?:[^"]|"")*" )           # quoted identifier
    | (?<![:\w]):(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)
    """, re.X)


def translate_params(sql: str) -> tuple[str, list[str]]:
    """Rewrite ``:name`` placeholders to ``$1..$n``; returns the ordered
    parameter-name list (repeated names reuse their positional).
    Quoted regions are skipped — ``::casts`` are already excluded by the
    lookbehind."""
    order: list[str] = []

    def sub(m: re.Match) -> str:
        if m.group("quote") is not None:
            return m.group("quote")
        name = m.group("name")
        if name not in order:
            order.append(name)
        return f"${order.index(name) + 1}"

    return _PARAM_OR_QUOTE_RE.sub(sub, sql), order


_DDL_REWRITES = [
    (re.compile(r"\bINTEGER\s+PRIMARY\s+KEY\s+AUTOINCREMENT\b", re.I),
     "BIGSERIAL PRIMARY KEY"),
    (re.compile(r"\bREAL\b", re.I), "DOUBLE PRECISION"),
    (re.compile(r"\bBLOB\b", re.I), "BYTEA"),
]


def translate_ddl(sql: str) -> str:
    """sqlite-flavored DDL -> Postgres DDL (see module docstring)."""
    head = sql.lstrip()[:30].upper()
    if not (head.startswith("CREATE TABLE")
            or head.startswith("CREATE INDEX")
            or head.startswith("ALTER TABLE")):
        return sql
    for pat, repl in _DDL_REWRITES:
        sql = pat.sub(repl, sql)
    return sql


_INSERT_TABLE_RE = re.compile(
    r"^\s*INSERT\s+INTO\s+([a-zA-Z_][a-zA-Z0-9_]*)", re.I)


def encode_value(v: Any) -> bytes | None:
    """Python value -> libpq text-format parameter (None = SQL NULL)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return b"true" if v else b"false"
    if isinstance(v, bytes):
        return b"\\x" + v.hex().encode()      # bytea hex input form
    if isinstance(v, float):
        return repr(v).encode()
    return str(v).encode()


def decode_value(raw: bytes, oid: int) -> Any:
    """libpq text-format field -> Python value by type OID."""
    if oid == _OID_BOOL:
        return raw == b"t"
    if oid in (_OID_INT2, _OID_INT4, _OID_INT8, _OID_OID):
        return int(raw)
    if oid in (_OID_FLOAT4, _OID_FLOAT8, _OID_NUMERIC):
        return float(raw)
    if oid == _OID_BYTEA:
        if raw.startswith(b"\\x"):
            return bytes.fromhex(raw[2:].decode())
        return raw
    return raw.decode()


class _PgConn:
    """One libpq connection; used by one task/thread at a time."""

    def __init__(self, dsn: str):
        self.lib = load_libpq()
        self.ptr = self.lib.PQconnectdb(dsn.encode())
        if not self.ptr or self.lib.PQstatus(self.ptr) != CONNECTION_OK:
            msg = self.lib.PQerrorMessage(self.ptr) if self.ptr else b""
            if self.ptr:
                self.lib.PQfinish(self.ptr)
                self.ptr = None
            raise PgError(f"postgres connect failed: "
                          f"{(msg or b'').decode(errors='replace').strip()}")

    def close(self) -> None:
        if self.ptr:
            self.lib.PQfinish(self.ptr)
            self.ptr = None

    def _exec(self, sql: str, args: list[bytes | None]):
        n = len(args)
        values = (ctypes.c_char_p * n)(*args) if n else None
        res = self.lib.PQexecParams(
            self.ptr, sql.encode(), n, None, values, None, None, 0)
        status = self.lib.PQresultStatus(res)
        if status not in (PGRES_COMMAND_OK, PGRES_TUPLES_OK):
            msg = (self.lib.PQresultErrorMessage(res) or b"").decode(
                errors="replace").strip()
            state = self.lib.PQresultErrorField(res, ord("C"))  # sqlstate
            self.lib.PQclear(res)
            raise PgError(msg or "postgres query failed",
                          state.decode() if state else None)
        return res

    def query(self, sql: str, params: Params) -> tuple[list[Row], int]:
        """Run one statement; returns (rows, affected_rowcount)."""
        psql, order = translate_params(sql)
        src = dict(params or {})
        args = [encode_value(src[name]) for name in order]
        res = self._exec(psql, args)
        lib = self.lib
        try:
            rows: list[Row] = []
            nt = lib.PQntuples(res)
            nf = lib.PQnfields(res)
            if nt and nf:
                names = [lib.PQfname(res, f).decode() for f in range(nf)]
                oids = [lib.PQftype(res, f) for f in range(nf)]
                for r in range(nt):
                    row: Row = {}
                    for f in range(nf):
                        if lib.PQgetisnull(res, r, f):
                            row[names[f]] = None
                        else:
                            ln = lib.PQgetlength(res, r, f)
                            raw = ctypes.string_at(
                                lib.PQgetvalue(res, r, f), ln)
                            row[names[f]] = decode_value(raw, oids[f])
                    rows.append(row)
            cmd = lib.PQcmdTuples(res) or b""
            affected = int(cmd) if cmd.strip().isdigit() else 0
            return rows, affected
        finally:
            lib.PQclear(res)


class PgDatabase:
    """Async Postgres facade with the sqlite facade's exact API.

    ``url``: a libpq DSN or URI (``postgres://user:pw@host/db`` or
    ``host=... dbname=...``).
    """

    dialect = "postgres"
    row_lock_suffix = " FOR UPDATE SKIP LOCKED"

    def __init__(self, url: str, *, pool_size: int = 8):
        self.url = url
        self.pool_size = pool_size
        self._free: asyncio.Queue[_PgConn] | None = None
        self._opened = 0
        self._connected = False
        self._id_tables: set[str] | None = None
        self._grow_lock = asyncio.Lock()
        # Same counter contract as the sqlite facade: statements issued
        # over this facade's lifetime (serving-path zero-query asserts).
        self.query_count = 0

    @staticmethod
    def greatest(*exprs: str) -> str:
        return f"GREATEST({', '.join(exprs)})"

    # -- lifecycle ---------------------------------------------------------

    async def connect(self) -> None:
        if self._connected:
            return
        self._free = asyncio.Queue()
        conn = await asyncio.to_thread(_PgConn, self.url)
        self._free.put_nowait(conn)
        self._opened = 1
        self._connected = True

    async def disconnect(self) -> None:
        if not self._connected:
            return
        self._connected = False
        while self._free is not None and not self._free.empty():
            conn = self._free.get_nowait()
            await asyncio.to_thread(conn.close)
            self._opened -= 1
        self._free = None
        self._opened = 0

    @property
    def connected(self) -> bool:
        return self._connected

    async def _acquire(self) -> _PgConn:
        if not self._connected or self._free is None:
            raise RuntimeError("Database is not connected; call connect() first")
        if self._free.empty() and self._opened < self.pool_size:
            async with self._grow_lock:
                if self._free.empty() and self._opened < self.pool_size:
                    conn = await asyncio.to_thread(_PgConn, self.url)
                    self._opened += 1
                    return conn
        return await self._free.get()

    def _release(self, conn: _PgConn) -> None:
        if self._connected and self._free is not None:
            self._free.put_nowait(conn)
        else:
            conn.close()

    # -- INSERT id contract ------------------------------------------------

    async def _tables_with_id(self, conn: _PgConn) -> set[str]:
        if self._id_tables is None:
            rows, _ = await asyncio.to_thread(
                conn.query,
                "SELECT table_name FROM information_schema.columns "
                "WHERE column_name='id' AND table_schema='public'", None)
            self._id_tables = {r["table_name"] for r in rows}
        return self._id_tables

    async def _run(self, conn: _PgConn, sql: str, params: Params) -> Any:
        """Dispatch one statement, honoring the facade's return contract:
        INSERT -> new id (when the table has one), else affected count."""
        self.query_count += 1
        verb = sql.lstrip().split(None, 1)[0].upper() if sql.strip() else ""
        if verb == "CREATE" or verb == "ALTER":
            sql = translate_ddl(sql)
            self._id_tables = None          # schema changed
        m = _INSERT_TABLE_RE.match(sql)
        if (m and "RETURNING" not in sql.upper()
                and m.group(1).lower() in await self._tables_with_id(conn)):
            rows, _ = await asyncio.to_thread(
                conn.query, sql + " RETURNING id", params)
            return rows[0]["id"] if rows else 0
        rows, affected = await asyncio.to_thread(conn.query, sql, params)
        return affected

    # -- single-statement API ----------------------------------------------

    async def execute(self, sql: str, params: Params = None) -> int:
        conn = await self._acquire()
        try:
            return await self._run(conn, sql, params)
        finally:
            self._release(conn)

    async def execute_many(self, sql: str,
                           seq: Iterable[Mapping[str, Any]]) -> None:
        conn = await self._acquire()
        try:
            # one increment per call, not per row — the sqlite facade
            # counts executemany once, and exact-delta asserts must see
            # the same number on both backends
            self.query_count += 1
            for params in seq:
                await asyncio.to_thread(conn.query, sql, params)
        finally:
            self._release(conn)

    async def fetch_one(self, sql: str, params: Params = None) -> Row | None:
        conn = await self._acquire()
        try:
            self.query_count += 1
            rows, _ = await asyncio.to_thread(conn.query, sql, params)
            return rows[0] if rows else None
        finally:
            self._release(conn)

    async def fetch_all(self, sql: str, params: Params = None) -> list[Row]:
        conn = await self._acquire()
        try:
            self.query_count += 1
            rows, _ = await asyncio.to_thread(conn.query, sql, params)
            return rows
        finally:
            self._release(conn)

    async def fetch_val(self, sql: str, params: Params = None) -> Any:
        row = await self.fetch_one(sql, params)
        if row is None:
            return None
        return next(iter(row.values()))

    # -- transactions ------------------------------------------------------

    @asynccontextmanager
    async def transaction(self, *, immediate: bool = True
                          ) -> AsyncIterator["PgTransaction"]:
        """Open a transaction on a pinned pool connection.

        ``immediate`` is accepted for sqlite-facade compatibility; on
        Postgres every transaction takes row locks as it touches rows,
        and the claim queries add ``FOR UPDATE SKIP LOCKED`` explicitly.
        """
        conn = await self._acquire()
        try:
            await asyncio.to_thread(conn.query, "BEGIN", None)
            tx = PgTransaction(self, conn)
            try:
                yield tx
                failpoints.hit("db.commit")
            except BaseException:
                await asyncio.to_thread(conn.query, "ROLLBACK", None)
                raise
            else:
                await asyncio.to_thread(conn.query, "COMMIT", None)
        finally:
            self._release(conn)


class PgTransaction:
    """Statements bound to one in-transaction connection."""

    def __init__(self, db: PgDatabase, conn: _PgConn):
        self._db = db
        self._conn = conn

    async def execute(self, sql: str, params: Params = None) -> int:
        return await self._db._run(self._conn, sql, params)

    async def execute_many(self, sql: str,
                           seq: Iterable[Mapping[str, Any]]) -> None:
        self._db.query_count += 1   # per call, matching the sqlite facade
        for params in seq:
            await asyncio.to_thread(self._conn.query, sql, params)

    async def fetch_one(self, sql: str, params: Params = None) -> Row | None:
        self._db.query_count += 1
        rows, _ = await asyncio.to_thread(self._conn.query, sql, params)
        return rows[0] if rows else None

    async def fetch_all(self, sql: str, params: Params = None) -> list[Row]:
        self._db.query_count += 1
        rows, _ = await asyncio.to_thread(self._conn.query, sql, params)
        return rows


class PgListener:
    """Dedicated LISTEN connection feeding a callback from a daemon
    thread (select on PQsocket -> PQconsumeInput -> drain PQnotifies).

    The callback fires on the listener thread; PgNotifyBus marshals
    into the event loop. A dropped connection is retried with backoff —
    wakeups are hints, so a gap only costs poll latency."""

    def __init__(self, dsn: str, channels: tuple[str, ...],
                 callback) -> None:
        self.dsn = dsn
        self.channels = channels
        self.callback = callback
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self, ready_timeout: float = 10.0) -> None:
        """Spawn the listener and block until the LISTEN statements are
        in place — a notify published right after start() must not fall
        in the subscribe gap. Timing out (server down) is non-fatal:
        the thread keeps retrying and wakeups degrade to poll latency."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="vlog-pg-listen")
        self._thread.start()
        self._ready.wait(ready_timeout)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        import select as select_mod

        backoff = 0.5
        while not self._stop.is_set():
            conn = None
            try:
                conn = _PgConn(self.dsn)
                for ch in self.channels:
                    # identifiers can't be bound parameters; channels
                    # are compile-time constants (events.py CH_*)
                    conn.query(f'LISTEN "{ch}"', None)
                self._ready.set()
                sock = conn.lib.PQsocket(conn.ptr)
                backoff = 0.5
                while not self._stop.is_set():
                    r, _, _ = select_mod.select([sock], [], [], 0.25)
                    if not r:
                        continue
                    if not conn.lib.PQconsumeInput(conn.ptr):
                        raise PgError("listen connection lost")
                    while True:
                        note = conn.lib.PQnotifies(conn.ptr)
                        if not note:
                            break
                        try:
                            ch = (note.contents.relname or b"").decode()
                            extra = (note.contents.extra or b"").decode()
                        finally:
                            conn.lib.PQfreemem(note)
                        try:
                            self.callback(ch, extra)
                        except Exception:   # noqa: BLE001
                            pass
            except Exception:               # noqa: BLE001 — reconnect
                if self._stop.wait(backoff):
                    break
                backoff = min(backoff * 2, 10.0)
            finally:
                if conn is not None:
                    conn.close()
