"""Schema DDL + migrations.

Reference parity: api/database.py:137-942 (core tables) and migrations/
(27 Alembic revisions). Here the schema is expressed as ordered DDL
migrations applied through a ``schema_migrations`` ledger, so later rounds
can evolve the schema the way the reference's Alembic history did.

Timestamps are unix-epoch REAL seconds (``vlog_tpu.db.core.now``).
JSON-valued columns are TEXT holding canonical JSON.
"""

from __future__ import annotations

from vlog_tpu.db.core import Database, now

SCHEMA_VERSION = 6

# Each entry: (version, [statements]). Append-only.
MIGRATIONS: list[tuple[int, list[str]]] = [
    (
        1,
        [
            # -- videos (reference: database.py videos table) --------------
            """
            CREATE TABLE IF NOT EXISTS videos (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                slug TEXT NOT NULL UNIQUE,
                title TEXT NOT NULL,
                description TEXT NOT NULL DEFAULT '',
                original_filename TEXT,
                source_path TEXT,
                duration_s REAL,
                width INTEGER,
                height INTEGER,
                fps REAL,
                size_bytes INTEGER,
                status TEXT NOT NULL DEFAULT 'pending',
                streaming_format TEXT NOT NULL DEFAULT 'cmaf',
                codec TEXT NOT NULL DEFAULT 'h264',
                error TEXT,
                thumbnail_path TEXT,
                transcription_status TEXT NOT NULL DEFAULT 'pending',
                category TEXT,
                tags TEXT NOT NULL DEFAULT '[]',
                created_at REAL NOT NULL,
                updated_at REAL NOT NULL,
                deleted_at REAL,
                CHECK (status IN ('pending','processing','ready','failed','deleted'))
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_videos_status ON videos(status)",
            "CREATE INDEX IF NOT EXISTS idx_videos_created ON videos(created_at)",
            # -- per-rung outputs (reference: video_qualities) --------------
            """
            CREATE TABLE IF NOT EXISTS video_qualities (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                video_id INTEGER NOT NULL REFERENCES videos(id) ON DELETE CASCADE,
                name TEXT NOT NULL,
                width INTEGER NOT NULL,
                height INTEGER NOT NULL,
                video_bitrate INTEGER,
                audio_bitrate INTEGER,
                codec TEXT NOT NULL DEFAULT 'h264',
                playlist_path TEXT,
                created_at REAL NOT NULL,
                UNIQUE (video_id, name, codec)
            )
            """,
            # -- unified job queue ------------------------------------------
            # The reference spread transcode/sprite/reencode over separate
            # tables+queues; one table with `kind` covers all of them and the
            # claim protocol (job_state.py analog) applies uniformly.
            """
            CREATE TABLE IF NOT EXISTS jobs (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                video_id INTEGER NOT NULL REFERENCES videos(id) ON DELETE CASCADE,
                kind TEXT NOT NULL DEFAULT 'transcode',
                priority INTEGER NOT NULL DEFAULT 0,
                payload TEXT NOT NULL DEFAULT '{}',
                claimed_by TEXT,
                claimed_at REAL,
                claim_expires_at REAL,
                started_at REAL,
                completed_at REAL,
                failed_at REAL,
                error TEXT,
                attempt INTEGER NOT NULL DEFAULT 0,
                max_attempts INTEGER NOT NULL DEFAULT 3,
                current_step TEXT,
                last_checkpoint TEXT NOT NULL DEFAULT '{}',
                progress REAL NOT NULL DEFAULT 0.0,
                required_accelerator TEXT,
                min_code_version TEXT,
                created_at REAL NOT NULL,
                updated_at REAL NOT NULL,
                UNIQUE (video_id, kind),
                CHECK (attempt >= 0),
                CHECK (progress >= 0.0 AND progress <= 100.0)
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_jobs_claim ON jobs(kind, completed_at, failed_at, claim_expires_at)",
            # -- per-quality checkpoint rows (reference: quality_progress) --
            """
            CREATE TABLE IF NOT EXISTS quality_progress (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                job_id INTEGER NOT NULL REFERENCES jobs(id) ON DELETE CASCADE,
                quality TEXT NOT NULL,
                status TEXT NOT NULL DEFAULT 'pending',
                progress REAL NOT NULL DEFAULT 0.0,
                updated_at REAL NOT NULL,
                UNIQUE (job_id, quality),
                CHECK (status IN ('pending','in_progress','completed','failed'))
            )
            """,
            # -- transcriptions ---------------------------------------------
            """
            CREATE TABLE IF NOT EXISTS transcriptions (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                video_id INTEGER NOT NULL UNIQUE REFERENCES videos(id) ON DELETE CASCADE,
                language TEXT,
                model TEXT,
                vtt_path TEXT,
                full_text TEXT,
                status TEXT NOT NULL DEFAULT 'pending',
                error TEXT,
                created_at REAL NOT NULL,
                completed_at REAL
            )
            """,
            # -- worker fleet -----------------------------------------------
            """
            CREATE TABLE IF NOT EXISTS workers (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL UNIQUE,
                kind TEXT NOT NULL DEFAULT 'remote',
                accelerator TEXT NOT NULL DEFAULT 'cpu',
                capabilities TEXT NOT NULL DEFAULT '{}',
                code_version TEXT,
                last_heartbeat_at REAL,
                status TEXT NOT NULL DEFAULT 'active',
                created_at REAL NOT NULL
            )
            """,
            """
            CREATE TABLE IF NOT EXISTS worker_api_keys (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                worker_name TEXT NOT NULL,
                key_prefix TEXT NOT NULL,
                key_hash TEXT NOT NULL,
                hash_version INTEGER NOT NULL DEFAULT 2,
                created_at REAL NOT NULL,
                last_used_at REAL,
                revoked_at REAL
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_api_keys_prefix ON worker_api_keys(key_prefix)",
            # -- settings (reference: settings table, settings_service) -----
            """
            CREATE TABLE IF NOT EXISTS settings (
                key TEXT PRIMARY KEY,
                value TEXT,
                value_type TEXT NOT NULL DEFAULT 'str',
                updated_at REAL NOT NULL
            )
            """,
            # -- webhooks ---------------------------------------------------
            """
            CREATE TABLE IF NOT EXISTS webhooks (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                url TEXT NOT NULL,
                secret TEXT,
                events TEXT NOT NULL DEFAULT '[]',
                active INTEGER NOT NULL DEFAULT 1,
                created_at REAL NOT NULL
            )
            """,
            """
            CREATE TABLE IF NOT EXISTS webhook_deliveries (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                webhook_id INTEGER NOT NULL REFERENCES webhooks(id) ON DELETE CASCADE,
                event TEXT NOT NULL,
                payload TEXT NOT NULL,
                status TEXT NOT NULL DEFAULT 'pending',
                attempts INTEGER NOT NULL DEFAULT 0,
                next_attempt_at REAL,
                response_code INTEGER,
                created_at REAL NOT NULL,
                delivered_at REAL
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_deliveries_pending ON webhook_deliveries(status, next_attempt_at)",
            # -- playback analytics (reference: playback_sessions) ----------
            """
            CREATE TABLE IF NOT EXISTS playback_sessions (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                video_id INTEGER NOT NULL REFERENCES videos(id) ON DELETE CASCADE,
                session_token TEXT NOT NULL UNIQUE,
                started_at REAL NOT NULL,
                last_heartbeat_at REAL NOT NULL,
                ended_at REAL,
                watch_time_s REAL NOT NULL DEFAULT 0.0
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_sessions_video ON playback_sessions(video_id, started_at)",
        ],
    ),
    (
        2,
        [
            # -- chapters (reference: chapter_detection.py + admin chapters
            #    routes, admin.py:8057-8624) --------------------------------
            """
            CREATE TABLE IF NOT EXISTS chapters (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                video_id INTEGER NOT NULL REFERENCES videos(id) ON DELETE CASCADE,
                start_s REAL NOT NULL,
                title TEXT NOT NULL,
                source TEXT NOT NULL DEFAULT 'manual',
                created_at REAL NOT NULL,
                UNIQUE (video_id, start_s),
                CHECK (source IN ('manual','container','transcript'))
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_chapters_video ON chapters(video_id, start_s)",
        ],
    ),
    (
        3,
        [
            # -- worker command channel (reference: command_listener.py over
            #    Redis pub/sub; here the shared DB is the bus — workers poll
            #    with their heartbeat) --------------------------------------
            """
            CREATE TABLE IF NOT EXISTS worker_commands (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                worker_name TEXT NOT NULL,
                command TEXT NOT NULL,
                args TEXT NOT NULL DEFAULT '{}',
                created_at REAL NOT NULL,
                picked_up_at REAL,
                completed_at REAL,
                response TEXT
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_commands_pending ON worker_commands(worker_name, picked_up_at)",
        ],
    ),
    (
        4,
        [
            # -- playlists (reference: admin.py:7534-8056 + public
            #    playlist browsing, public.py:1636-1991) ----------------
            """
            CREATE TABLE IF NOT EXISTS playlists (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                slug TEXT NOT NULL UNIQUE,
                title TEXT NOT NULL,
                description TEXT NOT NULL DEFAULT '',
                visibility TEXT NOT NULL DEFAULT 'public',
                created_at REAL NOT NULL,
                updated_at REAL NOT NULL,
                CHECK (visibility IN ('public','unlisted','private'))
            )
            """,
            """
            CREATE TABLE IF NOT EXISTS playlist_items (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                playlist_id INTEGER NOT NULL
                    REFERENCES playlists(id) ON DELETE CASCADE,
                video_id INTEGER NOT NULL
                    REFERENCES videos(id) ON DELETE CASCADE,
                position INTEGER NOT NULL,
                added_at REAL NOT NULL,
                UNIQUE (playlist_id, video_id)
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_playlist_items ON playlist_items(playlist_id, position)",
            # -- custom metadata fields (reference: admin.py:6688-7533) --
            """
            CREATE TABLE IF NOT EXISTS custom_fields (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL UNIQUE,
                label TEXT NOT NULL,
                field_type TEXT NOT NULL DEFAULT 'text',
                required INTEGER NOT NULL DEFAULT 0,
                options TEXT NOT NULL DEFAULT '[]',
                position INTEGER NOT NULL DEFAULT 0,
                created_at REAL NOT NULL,
                CHECK (field_type IN
                       ('text','number','boolean','select','date','url'))
            )
            """,
            """
            CREATE TABLE IF NOT EXISTS video_custom_values (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                video_id INTEGER NOT NULL
                    REFERENCES videos(id) ON DELETE CASCADE,
                field_id INTEGER NOT NULL
                    REFERENCES custom_fields(id) ON DELETE CASCADE,
                value TEXT,
                updated_at REAL NOT NULL,
                UNIQUE (video_id, field_id)
            )
            """,
            # -- cookie sessions for the admin UI (reference:
            #    admin.py:1088-1234 session auth + CSRF) ----------------
            """
            CREATE TABLE IF NOT EXISTS admin_sessions (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                token_hash TEXT NOT NULL UNIQUE,
                csrf_token TEXT NOT NULL,
                created_at REAL NOT NULL,
                expires_at REAL NOT NULL,
                last_used_at REAL
            )
            """,
        ],
    ),
    (
        5,
        [
            # -- failure plane (jobs/claims.py) ------------------------------
            # next_retry_at: jittered-exponential-backoff gate written by
            # fail_job; a job whose timestamp is in the future derives the
            # BACKOFF state and is skipped by SQL_CLAIMABLE, so a crashing
            # job can no longer burn its whole retry budget in seconds.
            "ALTER TABLE jobs ADD COLUMN next_retry_at REAL",
            "CREATE INDEX IF NOT EXISTS idx_jobs_next_retry"
            " ON jobs(next_retry_at)",
            # Per-attempt failure history with classification, written by
            # fail_job (transient/permanent/stalled), the expired-claim
            # sweep and daemon startup recovery (worker_crash). Surfaced in
            # the dead-letter admin view; rows outlive the retry loop so a
            # dead-lettered job carries its full post-mortem.
            """
            CREATE TABLE IF NOT EXISTS job_failures (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                job_id INTEGER NOT NULL REFERENCES jobs(id) ON DELETE CASCADE,
                attempt INTEGER NOT NULL,
                worker TEXT,
                error TEXT,
                failure_class TEXT NOT NULL DEFAULT 'transient',
                created_at REAL NOT NULL,
                CHECK (failure_class IN
                       ('transient','permanent','worker_crash','stalled'))
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_job_failures_job"
            " ON job_failures(job_id, id)",
        ],
    ),
    (
        6,
        [
            # -- trace plane (obs/) ------------------------------------------
            # One trace per job life: the root row (parent_id IS NULL,
            # name 'job') is minted at enqueue; claim/complete markers
            # (jobs/claims.py) and worker attempt/stage/rung spans
            # (worker daemon directly, remote workers via
            # POST /api/worker/jobs/{id}/spans) parent under it. Rows
            # are deleted with the other per-life tables on job
            # reset/requeue, so a fresh life gets a fresh trace.
            """
            CREATE TABLE IF NOT EXISTS job_spans (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                job_id INTEGER NOT NULL REFERENCES jobs(id) ON DELETE CASCADE,
                trace_id TEXT NOT NULL,
                span_id TEXT NOT NULL,
                parent_id TEXT,
                name TEXT NOT NULL,
                origin TEXT NOT NULL DEFAULT 'server',
                started_at REAL NOT NULL,
                duration_s REAL,
                status TEXT NOT NULL DEFAULT 'ok',
                attributes TEXT NOT NULL DEFAULT '{}',
                created_at REAL NOT NULL,
                UNIQUE (job_id, span_id),
                CHECK (origin IN ('server','worker')),
                CHECK (status IN ('ok','error'))
            )
            """,
            "CREATE INDEX IF NOT EXISTS idx_job_spans_job"
            " ON job_spans(job_id, started_at)",
            "CREATE INDEX IF NOT EXISTS idx_job_spans_trace"
            " ON job_spans(trace_id)",
            # exactly one root per job: concurrent ensure_root callers
            # (enqueue post-commit racing a fast claim) collapse onto
            # one row instead of forking the trace
            "CREATE UNIQUE INDEX IF NOT EXISTS idx_job_spans_root"
            " ON job_spans(job_id) WHERE parent_id IS NULL",
        ],
    ),
    (
        7,
        [
            # -- fault-domain isolation plane --------------------------------
            # device_fault joins the failure taxonomy (enums.FailureClass):
            # the accelerator — not the input — failed the attempt, the
            # attempt is refunded and the scheduler quarantines the slot's
            # devices. The CHECK constraint can't be altered in place on
            # sqlite, so the table rebuilds (portable on Postgres too:
            # RENAME + recreate + copy + drop). The copy deliberately does
            # NOT carry explicit ids: on Postgres the recreated BIGSERIAL
            # sequence starts at 1 and explicit-id rows would leave it
            # behind the data (the next insert would collide); re-keying
            # in ORDER BY id keeps both backends' sequences consistent and
            # preserves the only ordering anything reads (per-job history
            # is ORDER BY id; ids are never stored elsewhere).
            "ALTER TABLE job_failures RENAME TO job_failures_old",
            "DROP INDEX IF EXISTS idx_job_failures_job",
            """
            CREATE TABLE IF NOT EXISTS job_failures (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                job_id INTEGER NOT NULL REFERENCES jobs(id) ON DELETE CASCADE,
                attempt INTEGER NOT NULL,
                worker TEXT,
                error TEXT,
                failure_class TEXT NOT NULL DEFAULT 'transient',
                created_at REAL NOT NULL,
                CHECK (failure_class IN
                       ('transient','permanent','worker_crash','stalled',
                        'device_fault'))
            )
            """,
            "INSERT INTO job_failures (job_id, attempt, worker, error,"
            " failure_class, created_at)"
            " SELECT job_id, attempt, worker, error, failure_class,"
            " created_at FROM job_failures_old ORDER BY id",
            "DROP TABLE job_failures_old",
            "CREATE INDEX IF NOT EXISTS idx_job_failures_job"
            " ON job_failures(job_id, id)",
        ],
    ),
    (
        8,
        [
            # -- preemption-tolerant drain plane -----------------------------
            # preempted joins the failure taxonomy (enums.FailureClass):
            # the HOST was evicted (preemption notice / SIGTERM) and the
            # drain grace lapsed mid-attempt — refunded like device_fault,
            # no backoff, a successor resumes the uploaded partial tree.
            # Same rebuild ritual as migration 7 (CHECKs can't be altered
            # in place on sqlite; re-keying keeps Postgres sequences
            # ahead of the data).
            "ALTER TABLE job_failures RENAME TO job_failures_old",
            "DROP INDEX IF EXISTS idx_job_failures_job",
            """
            CREATE TABLE IF NOT EXISTS job_failures (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                job_id INTEGER NOT NULL REFERENCES jobs(id) ON DELETE CASCADE,
                attempt INTEGER NOT NULL,
                worker TEXT,
                error TEXT,
                failure_class TEXT NOT NULL DEFAULT 'transient',
                created_at REAL NOT NULL,
                CHECK (failure_class IN
                       ('transient','permanent','worker_crash','stalled',
                        'device_fault','preempted'))
            )
            """,
            "INSERT INTO job_failures (job_id, attempt, worker, error,"
            " failure_class, created_at)"
            " SELECT job_id, attempt, worker, error, failure_class,"
            " created_at FROM job_failures_old ORDER BY id",
            "DROP TABLE job_failures_old",
            "CREATE INDEX IF NOT EXISTS idx_job_failures_job"
            " ON job_failures(job_id, id)",
        ],
    ),
    (
        9,
        [
            # -- multi-tenant QoS plane --------------------------------------
            # Tenant identity on every job: admission control (jobs/qos.py)
            # caps per-tenant queue depth at enqueue, and the claim query
            # (jobs/claims.py) runs weighted deficit-round-robin ACROSS
            # tenants while preserving priority-then-FIFO WITHIN one.
            # Every pre-migration row (and any writer that never names a
            # tenant) lands in the 'default' tenant, so single-tenant
            # deployments keep the exact pre-QoS ordering.
            "ALTER TABLE jobs ADD COLUMN tenant TEXT NOT NULL"
            " DEFAULT 'default'",
            # Optional per-job deadline: jobs carrying one get a
            # deadline-aware boost in the fair-share order once the
            # tenant's deadline budget window opens. NULL = no deadline.
            "ALTER TABLE jobs ADD COLUMN deadline_at REAL",
            # tenant-scoped scans: admission counts, the fair-share
            # per-tenant ranking, the queue browser's tenant filter, and
            # the per-tenant /metrics gauges all GROUP/filter by tenant
            "CREATE INDEX IF NOT EXISTS idx_jobs_tenant"
            " ON jobs(tenant, completed_at, failed_at)",
        ],
    ),
]


async def create_all(db: Database) -> None:
    """Apply all pending migrations (idempotent)."""
    await db.execute(
        """
        CREATE TABLE IF NOT EXISTS schema_migrations (
            version INTEGER PRIMARY KEY,
            applied_at REAL NOT NULL
        )
        """
    )
    applied = {
        r["version"]
        for r in await db.fetch_all("SELECT version FROM schema_migrations")
    }
    for version, statements in MIGRATIONS:
        if version in applied:
            continue
        async with db.transaction() as tx:
            for stmt in statements:
                await tx.execute(stmt)
            await tx.execute(
                "INSERT INTO schema_migrations (version, applied_at) VALUES (:v, :t)",
                {"v": version, "t": now()},
            )
