"""In-process Postgres wire-protocol (v3) server backed by sqlite.

Purpose: the first-party libpq driver (db/pg.py) must be exercisable
END TO END — connect, extended-protocol query, transactions, RETURNING
id, LISTEN/NOTIFY — in an image that ships no Postgres server. This
speaks enough of the v3 protocol for libpq's ``PQconnectdb`` +
``PQexecParams`` + notification delivery, executing statements against
a shared in-memory sqlite database (per-connection sqlite handles on a
shared cache, ``BEGIN`` mapped to ``BEGIN IMMEDIATE`` so concurrent
claim transactions serialize the same way the sqlite facade does).

It is a TEST DOUBLE: PG-specific SQL is translated sqlite-ward
(``FOR UPDATE SKIP LOCKED`` stripped, ``GREATEST``→``max``, BIGSERIAL
DDL reversed, ``information_schema.columns`` served from sqlite
introspection, ``pg_notify`` fanned out as NotificationResponse
messages to listening connections). Row-lock semantics are sqlite's
single-writer model, not Postgres row locks — the live-server tests
(VLOG_TEST_PG_DSN) remain the authority there. Everything the DRIVER
does (param translation, text-format encode/decode, OID mapping,
pooled transactions, the listener thread's select/consume/notify loop)
runs for real against real wire bytes.

Reference shape: the reference tests against a live Postgres
(tests/conftest.py fixtures over asyncpg); this image cannot, hence
the fake. Protocol per the PostgreSQL Frontend/Backend documentation.
"""

from __future__ import annotations

import re
import shutil
import socket
import socketserver
import sqlite3
import struct
import tempfile
import threading
from typing import Any

# type OIDs (mirrors db/pg.py's decode table)
_OID_INT8 = 20
_OID_FLOAT8 = 701
_OID_TEXT = 25
_OID_BYTEA = 17

_STRIP_LOCK_RE = re.compile(r"\s+FOR\s+UPDATE(\s+SKIP\s+LOCKED)?", re.I)
_GREATEST_RE = re.compile(r"\bGREATEST\s*\(", re.I)
_DDL_REWRITES = [
    (re.compile(r"\bBIGSERIAL\s+PRIMARY\s+KEY\b", re.I),
     "INTEGER PRIMARY KEY AUTOINCREMENT"),
    (re.compile(r"\bDOUBLE\s+PRECISION\b", re.I), "REAL"),
    (re.compile(r"\bBYTEA\b", re.I), "BLOB"),
]
_INFO_SCHEMA_RE = re.compile(r"\binformation_schema\.columns\b", re.I)
_PG_NOTIFY_RE = re.compile(
    r"^\s*SELECT\s+pg_notify\s*\(\s*\$1\s*,\s*\$2\s*\)\s*$", re.I)
# The driver's INSERT-id contract appends "RETURNING id"; sqlite only
# grew RETURNING in 3.35, so older runtimes strip it and synthesize the
# rows from rowid arithmetic instead (see _execute).
_RETURNING_ID_RE = re.compile(r"\s+RETURNING\s+id\s*;?\s*$", re.I)
_LISTEN_RE = re.compile(r'^\s*LISTEN\s+"?([A-Za-z_][\w]*)"?\s*$', re.I)
_PARAM_RE = re.compile(r"\$(\d+)")


def _to_sqlite(sql: str) -> str:
    sql = _STRIP_LOCK_RE.sub("", sql)
    sql = _GREATEST_RE.sub("max(", sql)
    for pat, repl in _DDL_REWRITES:
        sql = pat.sub(repl, sql)
    # positional params: $n -> ?n (sqlite numbered placeholders)
    sql = _PARAM_RE.sub(r"?\1", sql)
    head = sql.lstrip()[:12].upper()
    if head.startswith("BEGIN"):
        # serialize writers up front — the same guarantee the sqlite
        # facade's BEGIN IMMEDIATE gives the claim protocol
        return "BEGIN IMMEDIATE"
    return sql


class _Wire:
    """Framed read/write over the client socket. Reads buffer partial
    data across timeouts: a socket timeout mid-message leaves every
    byte in the buffer, so the next call resumes cleanly (the handler
    uses idle timeouts to flush notifications)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = b""

    def _ensure(self, n: int) -> None:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)   # may raise socket.timeout
            if not chunk:
                raise ConnectionError("client closed")
            self._buf += chunk

    def read_startup(self) -> tuple[int, bytes]:
        self._ensure(4)
        (ln,) = struct.unpack("!i", self._buf[:4])
        self._ensure(ln)
        body = self._buf[4:ln]
        self._buf = self._buf[ln:]
        (code,) = struct.unpack("!i", body[:4])
        return code, body[4:]

    def read_message(self) -> tuple[bytes, bytes]:
        self._ensure(5)
        t = self._buf[0:1]
        (ln,) = struct.unpack("!i", self._buf[1:5])
        self._ensure(1 + ln)
        body = self._buf[5:1 + ln]
        self._buf = self._buf[1 + ln:]
        return t, body

    def send(self, t: bytes, body: bytes = b"") -> None:
        self.sock.sendall(t + struct.pack("!i", len(body) + 4) + body)


def _cstr(b: bytes, pos: int) -> tuple[bytes, int]:
    end = b.index(b"\x00", pos)
    return b[pos:end], end + 1


def _encode_field(v: Any) -> bytes | None:
    if v is None:
        return None
    if isinstance(v, bytes):
        return b"\\x" + v.hex().encode()
    if isinstance(v, float):
        return repr(v).encode()
    if isinstance(v, str):
        return v.encode()
    return str(v).encode()


def _oid_for(v: Any) -> int:
    if isinstance(v, bool) or isinstance(v, int):
        return _OID_INT8
    if isinstance(v, float):
        return _OID_FLOAT8
    if isinstance(v, bytes):
        return _OID_BYTEA
    return _OID_TEXT


class _Handler(socketserver.BaseRequestHandler):
    server: "FakePg"

    def handle(self) -> None:   # noqa: C901 — a protocol loop is a loop
        wire = _Wire(self.request)
        code, params = wire.read_startup()
        while code in (80877103, 80877104):   # SSL / GSSENC probe -> no
            self.request.sendall(b"N")
            code, params = wire.read_startup()
        if code == 80877102:            # CancelRequest — ignore politely
            return
        # AuthenticationOk + minimal parameters + ReadyForQuery
        wire.send(b"R", struct.pack("!i", 0))
        for k, v in (("server_version", "15.0 (vlog-fake)"),
                     ("client_encoding", "UTF8"),
                     ("standard_conforming_strings", "on")):
            wire.send(b"S", k.encode() + b"\x00" + v.encode() + b"\x00")
        wire.send(b"K", struct.pack("!ii", 7, 7))
        wire.send(b"Z", b"I")

        conn = self.server._sqlite_conn()
        listening: set[str] = set()
        notif_q: list[tuple[str, str]] = []
        self.server._register(listening, notif_q)
        self.request.settimeout(0.2)
        stmts: dict[bytes, str] = {}
        portals: dict[bytes, tuple[str, list[bytes | None]]] = {}
        pending_desc: list[tuple[str, str]] = []
        try:
            while True:
                # push queued notifications whenever the wire is idle
                try:
                    t, body = wire.read_message()
                except socket.timeout:
                    self._flush_notifs(wire, notif_q)
                    continue
                if t == b"X":
                    return
                if t == b"Q":           # simple query
                    self._run_and_respond(wire, conn, body[:-1].decode(),
                                          [], listening, describe=True)
                    self._flush_notifs(wire, notif_q)
                    wire.send(b"Z", b"I" if not conn.in_transaction
                              else b"T")
                elif t == b"P":         # Parse
                    name, pos = _cstr(body, 0)
                    q, pos = _cstr(body, pos)
                    stmts[name] = q.decode()
                    wire.send(b"1")
                elif t == b"B":         # Bind
                    portal, pos = _cstr(body, 0)
                    sname, pos = _cstr(body, pos)
                    (nfmt,) = struct.unpack("!h", body[pos:pos + 2])
                    pos += 2 + 2 * nfmt
                    (nparams,) = struct.unpack("!h", body[pos:pos + 2])
                    pos += 2
                    args: list[bytes | None] = []
                    for _ in range(nparams):
                        (ln,) = struct.unpack("!i", body[pos:pos + 4])
                        pos += 4
                        if ln < 0:
                            args.append(None)
                        else:
                            args.append(body[pos:pos + ln])
                            pos += ln
                    portals[portal] = (stmts.get(sname, ""), args)
                    wire.send(b"2")
                elif t == b"D":         # Describe — deferred to Execute
                    pass
                elif t == b"E":         # Execute
                    portal, _ = _cstr(body, 0)
                    q, args = portals.get(portal, ("", []))
                    self._run_and_respond(wire, conn, q, args, listening,
                                          describe=True)
                elif t == b"S":         # Sync
                    self._flush_notifs(wire, notif_q)
                    wire.send(b"Z", b"I" if not conn.in_transaction
                              else b"T")
                elif t in (b"C", b"H", b"F", b"d", b"c", b"f"):
                    pass                # close/flush/copy — unused
        except (ConnectionError, OSError):
            pass
        finally:
            self.server._unregister(listening, notif_q)
            conn.close()

    # -- execution ---------------------------------------------------------

    def _flush_notifs(self, wire: _Wire,
                      notif_q: list[tuple[str, str]]) -> None:
        while notif_q:
            ch, payload = notif_q.pop(0)
            wire.send(b"A", struct.pack("!i", 7) + ch.encode() + b"\x00"
                      + payload.encode() + b"\x00")

    def _run_and_respond(self, wire: _Wire, conn: sqlite3.Connection,
                         sql: str, args: list[bytes | None],
                         listening: set[str], *, describe: bool) -> None:
        try:
            rows, cols, tag = self._execute(conn, sql, args, listening)
        except Exception as exc:   # noqa: BLE001 — relay as ErrorResponse
            # no auto-rollback: the driver's transaction() issues its own
            # ROLLBACK after an error, and pre-empting it here would turn
            # that into "cannot rollback - no transaction is active",
            # masking the original error
            msg = str(exc)
            state = "40001" if "locked" in msg.lower() else "XX000"
            body = (b"S" + b"ERROR\x00" + b"C" + state.encode() + b"\x00"
                    + b"M" + msg.encode() + b"\x00\x00")
            wire.send(b"E", body)
            return
        if cols is not None:
            # RowDescription OIDs from the first NON-NULL value per
            # column (a NULL in row one must not demote later numeric
            # values to text on the driver side)
            def col_oid(i: int) -> int:
                for r in rows:
                    if r[i] is not None:
                        return _oid_for(r[i])
                return _OID_TEXT
            parts = [struct.pack("!h", len(cols))]
            for i, c in enumerate(cols):
                parts.append(c.encode() + b"\x00" + struct.pack(
                    "!ihihih", 0, 0, col_oid(i), -1, -1, 0))
            wire.send(b"T", b"".join(parts))
            for r in rows:
                parts = [struct.pack("!h", len(r))]
                for v in r:
                    enc = _encode_field(v)
                    if enc is None:
                        parts.append(struct.pack("!i", -1))
                    else:
                        parts.append(struct.pack("!i", len(enc)) + enc)
                wire.send(b"D", b"".join(parts))
        elif describe:
            wire.send(b"n")             # NoData
        wire.send(b"C", tag.encode() + b"\x00")

    def _execute(self, conn: sqlite3.Connection, sql: str,
                 args: list[bytes | None], listening: set[str]):
        """Returns (rows, colnames | None, command_tag)."""
        m = _LISTEN_RE.match(sql)
        if m:
            listening.add(m.group(1))
            return [], None, "LISTEN"
        if _PG_NOTIFY_RE.match(sql):
            ch = (args[0] or b"").decode()
            payload = (args[1] or b"").decode()
            self.server.notify(ch, payload)
            return [[None]], ["pg_notify"], "SELECT 1"
        if _INFO_SCHEMA_RE.search(sql):
            # serve the driver's id-column introspection from sqlite
            rows = []
            cur = conn.execute(
                "SELECT name FROM sqlite_master WHERE type='table'")
            for (tname,) in cur.fetchall():
                cols = conn.execute(f"PRAGMA table_info({tname})")
                if any(c[1] == "id" for c in cols.fetchall()):
                    rows.append([tname])
            return rows, ["table_name"], f"SELECT {len(rows)}"
        ssql = _to_sqlite(sql)
        verb0 = (ssql.lstrip().split(None, 1) or ["?"])[0].upper()
        if verb0 == "ROLLBACK" and not conn.in_transaction:
            return [], None, "ROLLBACK"   # PG tolerates; sqlite errors
        params = [None if a is None else a.decode() for a in args]
        synth_returning = False
        if verb0 == "INSERT" and sqlite3.sqlite_version_info < (3, 35, 0):
            stripped = _RETURNING_ID_RE.sub("", ssql)
            if stripped != ssql:
                ssql = stripped
                synth_returning = True
        cur = conn.execute(ssql, params)
        if synth_returning:
            # one statement's rowids are allocated in order, so the new
            # ids are the last n: [lastrowid-n+1 .. lastrowid]
            n = max(cur.rowcount, 0)
            last = cur.lastrowid or 0
            rows = ([[last - n + 1 + i] for i in range(n)]
                    if n and last else [])
            return rows, ["id"], f"INSERT 0 {len(rows)}"
        verb = (ssql.lstrip().split(None, 1) or ["?"])[0].upper()
        if cur.description is not None:
            cols = [d[0] for d in cur.description]
            rows = [list(r) for r in cur.fetchall()]
            if verb == "INSERT":        # INSERT ... RETURNING
                return rows, cols, f"INSERT 0 {len(rows)}"
            return rows, cols, f"SELECT {len(rows)}"
        n = max(cur.rowcount, 0)
        if verb in ("UPDATE", "DELETE"):
            tag = f"{verb} {n}"
        elif verb == "INSERT":
            tag = f"INSERT 0 {n}"
        elif verb in ("BEGIN",):
            tag = "BEGIN"
        elif verb == "COMMIT":
            tag = "COMMIT"
        elif verb == "ROLLBACK":
            tag = "ROLLBACK"
        else:
            tag = verb
        return [], None, tag


class FakePg(socketserver.ThreadingTCPServer):
    """Threaded fake server; one sqlite handle per client connection on
    a shared in-memory cache (the anchor handle keeps it alive)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self) -> None:
        super().__init__(("127.0.0.1", 0), _Handler)
        # File-backed WAL store (not :memory: shared cache): shared-cache
        # table locks return SQLITE_LOCKED immediately — the busy
        # handler does not apply — so concurrent BEGIN IMMEDIATE claim
        # transactions would error instead of serializing. WAL + busy
        # timeout gives the same writer-serialization semantics the
        # production sqlite facade has.
        self._tmpdir = tempfile.mkdtemp(prefix="vlog-fakepg-")
        self._dbpath = f"{self._tmpdir}/fake.db"
        self._anchor = self._sqlite_conn()
        self._listeners_lock = threading.Lock()
        self._listeners: list[tuple[set[str], list]] = []
        self._thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="vlog-fakepg")

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FakePg":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        self._anchor.close()
        shutil.rmtree(self._tmpdir, ignore_errors=True)

    @property
    def dsn(self) -> str:
        host, port = self.server_address
        # sslmode=disable skips the SSLRequest round-trip; gssencmode
        # likewise (newer libpq probes GSS first otherwise)
        return (f"host={host} port={port} dbname=fake user=fake "
                f"sslmode=disable gssencmode=disable")

    # -- shared sqlite -----------------------------------------------------

    def _sqlite_conn(self) -> sqlite3.Connection:
        conn = sqlite3.connect(
            self._dbpath, timeout=10.0, check_same_thread=False,
            isolation_level=None)
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        conn.execute("PRAGMA busy_timeout=10000")
        return conn

    # -- notifications -----------------------------------------------------

    def _register(self, listening, q) -> None:
        with self._listeners_lock:
            self._listeners.append((listening, q))

    def _unregister(self, listening, q) -> None:
        with self._listeners_lock:
            try:
                self._listeners.remove((listening, q))
            except ValueError:
                pass

    def notify(self, channel: str, payload: str) -> None:
        """Queue for listening connections; their handler threads flush
        on the next idle tick (<=0.2 s — the recv timeout)."""
        with self._listeners_lock:
            for listening, q in self._listeners:
                if channel in listening:
                    q.append((channel, payload))
