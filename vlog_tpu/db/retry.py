"""Transient-error retry tier for database operations.

Reference analog: api/db_retry.py (421 LoC) — exponential-backoff
retries around operations that can fail transiently under contention,
on both backends:

- sqlite: ``database is locked`` / ``database table is locked`` (busy
  writer past the busy_timeout, WAL checkpoint stalls);
- Postgres: deadlock (40P01), serialization failure (40001), lock
  not available (55P03), connection drops (08xxx / 57P03).

These become load-bearing exactly when the libpq driver (db/pg.py) is
used under claim contention: two claim transactions can deadlock on
row-lock order, and Postgres resolves it by killing one — which must
retry, not 500. The wrapper is deliberately only applied to operations
that are safe to re-run: whole transactions that re-read their inputs
(the claim protocol's shape) or idempotent statements. Retryable
failures surface before COMMIT, so a retried transaction never
double-applies.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Awaitable, Callable, TypeVar

log = logging.getLogger("vlog.db.retry")

T = TypeVar("T")

MAX_ATTEMPTS = 5
BASE_DELAY_S = 0.05
MAX_DELAY_S = 2.0

# sqlite message fragments (sqlite3 has no stable error codes at the
# message level; these are the documented busy/locked strings)
_SQLITE_RETRYABLE = (
    "database is locked",
    "database table is locked",
    "database schema is locked",
)

# Postgres SQLSTATEs that mean "try again" (PgError carries .sqlstate).
# Deliberately NOT here: connection-drop classes (08xxx, "server closed
# the connection") — a drop can land AFTER the server applied COMMIT,
# so re-running a non-idempotent transaction would double-apply it
# (e.g. a retried claim_job would claim a second job while the first
# sits claimed-by-nobody until lease expiry). The states below all
# surface BEFORE commit by construction: the server aborted the
# transaction itself (deadlock victim, serialization failure, lock
# unavailable) or never started it (57P03).
_PG_RETRYABLE_STATES = {
    "40001",   # serialization_failure
    "40P01",   # deadlock_detected
    "55P03",   # lock_not_available
    "57P03",   # cannot_connect_now (server starting; nothing ran)
}

_PG_RETRYABLE_FRAGMENTS = (
    "deadlock detected",
    "could not serialize access",
    "could not obtain lock",
)


# Connection-drop message shapes (libpq, sqlite-over-NFS, sockets).
# Deliberately broader than _PG_RETRYABLE_*: these are NOT safe for
# with_retries (a drop can land after COMMIT) but they ARE the signal
# the claim-loop brownout breaker paces itself on — the loop re-reads
# queue state every poll, so double-apply is not a concern there.
_CONNECTION_FRAGMENTS = (
    "connection refused",
    "connection reset",
    "connection timed out",
    "server closed the connection",
    "could not connect",
    "broken pipe",
    "connection is closed",
    "unavailable",
)


def is_transient_db_error(exc: BaseException) -> bool:
    """Is this the coordination plane flapping (vs a code/data bug)?

    Used by the worker claim loops' brownout breaker (worker/brownout.py)
    to decide between jittered backoff (transient: Postgres restarting,
    network partition, lock storms) and the generic crash-log path. Not
    used by :func:`with_retries` — see _CONNECTION_FRAGMENTS.

    Message fragments are only consulted on I/O and database-driver
    error families (same restraint as parallel/faults.py's
    RuntimeError-only matching): a code bug whose TEXT happens to say
    "unavailable" must not be routed into the brownout path, where its
    traceback would be suppressed and the worker pulled from rotation
    for the wrong reason.
    """
    if is_retryable(exc):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    if hasattr(exc, "sqlstate"):          # the PgError family
        sqlstate = exc.sqlstate
        if isinstance(sqlstate, str) and sqlstate[:2] in ("08", "57"):
            return True
    if isinstance(exc, RetriesExhausted):
        return True
    import sqlite3

    if not (isinstance(exc, (OSError, sqlite3.Error))
            or hasattr(exc, "sqlstate")):
        return False
    msg = str(exc).lower()
    return any(f in msg for f in _CONNECTION_FRAGMENTS)


class RetriesExhausted(RuntimeError):
    """All attempts failed with retryable errors; carries the last one."""

    def __init__(self, attempts: int, last: BaseException):
        super().__init__(
            f"database operation failed after {attempts} attempts: {last}")
        self.last = last


def is_retryable(exc: BaseException) -> bool:
    sqlstate = getattr(exc, "sqlstate", None)
    if sqlstate in _PG_RETRYABLE_STATES:
        return True
    msg = str(exc).lower()
    if any(f in msg for f in _SQLITE_RETRYABLE):
        return True
    return any(f in msg for f in _PG_RETRYABLE_FRAGMENTS)


async def with_retries(
    op: Callable[[], Awaitable[T]],
    *,
    max_attempts: int = MAX_ATTEMPTS,
    base_delay_s: float = BASE_DELAY_S,
    max_delay_s: float = MAX_DELAY_S,
    label: str = "db op",
) -> T:
    """Run ``op`` (a zero-arg coroutine factory — a fresh coroutine per
    attempt), retrying retryable database errors with jittered
    exponential backoff. Non-retryable errors propagate immediately."""
    last: BaseException | None = None
    for attempt in range(1, max_attempts + 1):
        try:
            return await op()
        except Exception as exc:   # noqa: BLE001 — filtered below
            # (CancelledError is BaseException and passes through)
            if not is_retryable(exc) or attempt == max_attempts:
                if last is not None and is_retryable(exc):
                    raise RetriesExhausted(attempt, exc) from exc
                raise
            last = exc
            delay = min(base_delay_s * (2 ** (attempt - 1)), max_delay_s)
            delay *= 0.5 + random.random()      # jitter: desync herds
            log.debug("%s: retryable failure (attempt %d/%d), %.0f ms: %s",
                      label, attempt, max_attempts, delay * 1000, exc)
            await asyncio.sleep(delay)
    raise AssertionError("unreachable")


def retryable(label: str | None = None, **cfg: Any):
    """Decorator form for async functions whose whole body is safe to
    re-run (transactions that re-read their inputs)."""
    def wrap(fn: Callable[..., Awaitable[T]]) -> Callable[..., Awaitable[T]]:
        async def inner(*args: Any, **kwargs: Any) -> T:
            return await with_retries(
                lambda: fn(*args, **kwargs),
                label=label or fn.__qualname__, **cfg)
        inner.__name__ = fn.__name__
        inner.__qualname__ = fn.__qualname__
        inner.__doc__ = fn.__doc__
        return inner
    return wrap
