"""Persistence layer: async database facade + schema.

Reference parity: api/database.py (SQLAlchemy Core + `databases` pool over
Postgres). Neither is available in this environment, so this is an in-house
async facade over sqlite3 (WAL mode, multi-process safe) with a driver seam a
Postgres driver can plug into later.
"""

from vlog_tpu.db.core import Database, Transaction
from vlog_tpu.db.schema import create_all, SCHEMA_VERSION

__all__ = ["Database", "Transaction", "create_all", "SCHEMA_VERSION"]
