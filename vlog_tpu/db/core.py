"""Async database facade over sqlite3.

The reference used `databases.Database` over asyncpg (api/database.py:11).
Here the same *shape* — ``fetch_one`` / ``fetch_all`` / ``execute`` /
``transaction()`` with named parameters — is provided by an in-house facade:

- One sqlite3 connection per :class:`Database`, guarded by an asyncio lock;
  blocking calls are pushed to a thread so the event loop never stalls.
- WAL journal mode + busy timeout make the file safe to share between the
  API processes and worker processes, mirroring how the reference shares
  Postgres across its services.
- ``BEGIN IMMEDIATE`` transactions give the claim protocol the same
  "row-locked claim" guarantee the reference gets from
  ``SELECT ... FOR UPDATE SKIP LOCKED`` (worker_api.py:1494-1556): sqlite has
  a single writer, so an immediate transaction *is* the lock.

Rows are returned as plain dicts.
"""

from __future__ import annotations

import asyncio
import sqlite3
import time
from collections.abc import AsyncIterator, Iterable, Mapping
from contextlib import asynccontextmanager
from pathlib import Path
from typing import Any

from vlog_tpu.utils import failpoints

Row = dict[str, Any]
Params = Mapping[str, Any] | None


def now() -> float:
    """Canonical timestamp (unix epoch seconds) used across the schema."""
    return time.time()


def _connect_sqlite(path: str) -> sqlite3.Connection:
    conn = sqlite3.connect(
        path,
        timeout=30.0,
        check_same_thread=False,
        isolation_level=None,  # autocommit; we manage BEGIN/COMMIT explicitly
    )
    conn.row_factory = sqlite3.Row
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute("PRAGMA synchronous=NORMAL")
    conn.execute("PRAGMA foreign_keys=ON")
    conn.execute("PRAGMA busy_timeout=30000")
    return conn


def parse_database_url(url: str) -> str:
    """Extract a filesystem path from ``sqlite:///path`` (or pass paths through)."""
    if url.startswith("sqlite:///"):
        return url[len("sqlite:///"):]
    if url.startswith("sqlite://"):
        return url[len("sqlite://"):]
    return url


class Transaction:
    """Handle for an open transaction; obtained via :meth:`Database.transaction`."""

    def __init__(self, db: "Database"):
        self._db = db

    async def execute(self, sql: str, params: Params = None) -> int:
        return await self._db._tx_execute(sql, params)

    async def execute_many(self, sql: str, seq: Iterable[Mapping[str, Any]]) -> None:
        await self._db._tx_execute_many(sql, seq)

    async def fetch_one(self, sql: str, params: Params = None) -> Row | None:
        return await self._db._tx_fetch_one(sql, params)

    async def fetch_all(self, sql: str, params: Params = None) -> list[Row]:
        return await self._db._tx_fetch_all(sql, params)


def open_database(url: str):
    """Facade factory: sqlite (default) or Postgres by URL scheme.

    ``postgres://`` / ``postgresql://`` URLs (and libpq keyword DSNs
    containing ``host=``/``dbname=``) return the first-party libpq-backed
    :class:`vlog_tpu.db.pg.PgDatabase` — real ``FOR UPDATE SKIP LOCKED``
    claims for multi-node fleets (reference api/database.py:11). Anything
    else is a sqlite path/URL served by :class:`Database`.
    """
    low = url.strip().lower()
    if (low.startswith(("postgres://", "postgresql://"))
            or ("dbname=" in low and not low.startswith("sqlite"))):
        from vlog_tpu.db.pg import PgDatabase

        return PgDatabase(url)
    return Database(url)


class Database:
    """Async sqlite facade; safe to share within one event loop."""

    dialect = "sqlite"
    # sqlite's single writer makes BEGIN IMMEDIATE the row lock; the PG
    # facade overrides this with " FOR UPDATE SKIP LOCKED".
    row_lock_suffix = ""

    @staticmethod
    def greatest(*exprs: str) -> str:
        # two-arg MAX is sqlite's scalar max; PG spells it GREATEST
        return f"MAX({', '.join(exprs)})"

    def __init__(self, url: str):
        self.path = parse_database_url(url)
        self._conn: sqlite3.Connection | None = None
        self._lock = asyncio.Lock()
        # Statements executed over this facade's lifetime. Serving-path
        # tests assert steady-state deltas of exactly zero (the delivery
        # plane's "a cached segment hit performs no DB queries").
        self.query_count = 0

    # -- lifecycle ---------------------------------------------------------

    async def connect(self) -> None:
        if self._conn is not None:
            return
        if self.path != ":memory:":
            Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        self._conn = await asyncio.to_thread(_connect_sqlite, self.path)

    async def disconnect(self) -> None:
        if self._conn is not None:
            conn, self._conn = self._conn, None
            await asyncio.to_thread(conn.close)

    @property
    def connected(self) -> bool:
        return self._conn is not None

    def _require_conn(self) -> sqlite3.Connection:
        if self._conn is None:
            raise RuntimeError("Database is not connected; call connect() first")
        return self._conn

    # -- single-statement API (each statement is its own transaction) ------

    async def execute(self, sql: str, params: Params = None) -> int:
        """Run a write statement; returns lastrowid (or rowcount for UPDATE)."""
        async with self._lock:
            return await asyncio.to_thread(self._run_execute, sql, params)

    async def execute_many(self, sql: str, seq: Iterable[Mapping[str, Any]]) -> None:
        async with self._lock:
            await asyncio.to_thread(self._run_execute_many, sql, list(seq))

    async def fetch_one(self, sql: str, params: Params = None) -> Row | None:
        async with self._lock:
            return await asyncio.to_thread(self._run_fetch_one, sql, params)

    async def fetch_all(self, sql: str, params: Params = None) -> list[Row]:
        async with self._lock:
            return await asyncio.to_thread(self._run_fetch_all, sql, params)

    async def fetch_val(self, sql: str, params: Params = None) -> Any:
        row = await self.fetch_one(sql, params)
        if row is None:
            return None
        return next(iter(row.values()))

    # -- transactions ------------------------------------------------------

    @asynccontextmanager
    async def transaction(self, *, immediate: bool = True) -> AsyncIterator[Transaction]:
        """Open a transaction, holding the facade lock for its duration.

        ``immediate=True`` acquires sqlite's write lock up front, which is the
        claim-protocol serialization point (see module docstring).
        """
        async with self._lock:
            conn = self._require_conn()
            begin = "BEGIN IMMEDIATE" if immediate else "BEGIN"
            await asyncio.to_thread(conn.execute, begin)
            try:
                yield Transaction(self)
                failpoints.hit("db.commit")
            except BaseException:
                await asyncio.to_thread(conn.execute, "ROLLBACK")
                raise
            else:
                await asyncio.to_thread(conn.execute, "COMMIT")

    # -- internals (thread side) -------------------------------------------

    def _run_execute(self, sql: str, params: Params) -> int:
        conn = self._require_conn()
        self.query_count += 1
        cur = conn.execute(sql, dict(params or {}))
        verb = sql.lstrip().split(None, 1)[0].upper() if sql.strip() else ""
        return cur.lastrowid if verb == "INSERT" else cur.rowcount

    def _run_execute_many(self, sql: str, seq: list[Mapping[str, Any]]) -> None:
        self.query_count += 1
        self._require_conn().executemany(sql, [dict(p) for p in seq])

    def _run_fetch_one(self, sql: str, params: Params) -> Row | None:
        self.query_count += 1
        cur = self._require_conn().execute(sql, dict(params or {}))
        row = cur.fetchone()
        return dict(row) if row is not None else None

    def _run_fetch_all(self, sql: str, params: Params) -> list[Row]:
        self.query_count += 1
        cur = self._require_conn().execute(sql, dict(params or {}))
        return [dict(r) for r in cur.fetchall()]

    # transaction-scoped variants run on the already-locked connection
    async def _tx_execute(self, sql: str, params: Params) -> int:
        return await asyncio.to_thread(self._run_execute, sql, params)

    async def _tx_execute_many(self, sql: str, seq: Iterable[Mapping[str, Any]]) -> None:
        await asyncio.to_thread(self._run_execute_many, sql, list(seq))

    async def _tx_fetch_one(self, sql: str, params: Params) -> Row | None:
        return await asyncio.to_thread(self._run_fetch_one, sql, params)

    async def _tx_fetch_all(self, sql: str, params: Params) -> list[Row]:
        return await asyncio.to_thread(self._run_fetch_all, sql, params)
