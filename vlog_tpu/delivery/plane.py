"""The delivery plane: origin-side caching + admission for serve_media.

Sits between the public API's media route and the filesystem/DB so that
steady-state playback — every 4-second ``.m4s`` of every concurrent
viewer — touches neither Postgres nor ``open()``:

- a **publish-state cache** (slug -> ready/deleted/missing, TTL +
  explicit invalidation) answers the "may this slug serve at all?"
  gate from memory, via the narrow ``get_video_serving_state`` query on
  miss instead of the old ``SELECT * FROM videos`` per segment;
- the **segment cache** (delivery/cache.py) holds response buffers
  under a byte budget, ETags seeded from the PR-2 ``outputs.json``
  manifest so revalidation compares the real published sha256;
- **single-flight** collapses N concurrent misses for one segment onto
  one disk read;
- an **admission bound** sheds distinct-key misses past
  ``VLOG_DELIVERY_MAX_INFLIGHT_READS`` with 503 + ``Retry-After``
  rather than queueing unbounded reads on the volume;
- **invalidation** — publish/re-encode/delete/restore/verify paths call
  :func:`invalidate_slug`, which fans out to every plane registered in
  this process (plus ``POST /api/delivery/invalidate`` for operators).
  Cross-process staleness of publish state and manifests is bounded by
  ``VLOG_DELIVERY_STATE_TTL`` / ``VLOG_DELIVERY_MANIFEST_TTL``; segment
  BODIES are pinned by default, so a split deployment (admin/worker
  mutating trees in another process) must set
  ``VLOG_DELIVERY_SEGMENT_TTL`` for republished segments to converge.

Counters go two places on purpose: plain ints on the plane (the admin
stats panel and tests read exact deltas) and the process-wide
``obs.metrics.runtime()`` registry (Prometheus families
``vlog_delivery_*`` — scraped via the public API's ``/metrics``).
"""

from __future__ import annotations

import asyncio
import os
import stat as stat_mod
import threading
import time
import weakref
from dataclasses import dataclass
from pathlib import Path

from vlog_tpu import config
from vlog_tpu.delivery.cache import CacheEntry, SegmentCache, SingleFlight
from vlog_tpu.delivery.http import MEDIA_MIME, MUTABLE_SUFFIXES
from vlog_tpu.obs.metrics import runtime
from vlog_tpu.utils import failpoints

# Publish-state entries (including negative "missing" ones) are tiny;
# this bound only matters under a random-slug 404 storm.
_STATE_CACHE_MAX = 16384
# Per-slug manifest digest maps are bigger (one {rel: (size, sha)} per
# published file); bound them so a long-lived process serving a huge
# catalog doesn't accumulate one map per slug ever touched.
_DIGEST_CACHE_MAX = 2048


class LoadShedError(RuntimeError):
    """Admission refused: too many origin reads in flight (HTTP 503)."""

    def __init__(self, retry_after_s: int = 1):
        super().__init__("origin overloaded; retry shortly")
        self.retry_after_s = retry_after_s


class MediaEscapeError(PermissionError):
    """A resolved path escaped the slug's tree (symlink traversal)."""


@dataclass(frozen=True)
class ServingState:
    """What the media route needs to gate a request — nothing more."""

    video_id: int | None
    status: str                 # 'ready' | 'deleted' | 'missing' | other


@dataclass(frozen=True)
class BypassFile:
    """An object too large to buffer: stream it from disk instead."""

    path: Path
    mime: str
    size: int


class DeliveryPlane:
    """One per serving process; constructed by ``build_public_app``."""

    def __init__(self, db, video_dir: str | Path, *,
                 cache_bytes: int | None = None,
                 max_inflight_reads: int | None = None,
                 manifest_ttl_s: float | None = None,
                 segment_ttl_s: float | None = None,
                 state_ttl_s: float | None = None,
                 max_entry_bytes: int | None = None):
        self.db = db
        self.video_dir = Path(video_dir)
        self.max_inflight_reads = (config.DELIVERY_MAX_INFLIGHT_READS
                                   if max_inflight_reads is None
                                   else max_inflight_reads)
        self.manifest_ttl_s = (config.DELIVERY_MANIFEST_TTL_S
                               if manifest_ttl_s is None else manifest_ttl_s)
        self.segment_ttl_s = (config.DELIVERY_SEGMENT_TTL_S
                              if segment_ttl_s is None else segment_ttl_s)
        self.state_ttl_s = (config.DELIVERY_STATE_TTL_S
                            if state_ttl_s is None else state_ttl_s)
        self.max_entry_bytes = (config.DELIVERY_MAX_ENTRY_BYTES
                                if max_entry_bytes is None
                                else max_entry_bytes)
        m = runtime()
        self.cache = SegmentCache(
            config.DELIVERY_CACHE_BYTES if cache_bytes is None
            else cache_bytes,
            on_evict=lambda _size: m.delivery_evictions.inc())
        self.flight = SingleFlight(
            on_collapse=lambda: m.delivery_collapses.inc())
        # loop-confined: _states/_fill_gen/counters are only touched
        # from event-loop coroutines, never from fill threads
        self._states: dict[str, tuple[ServingState, float]] = {}
        # slug -> (outputs.json mtime_ns | None, {rel: (size, sha256)})
        # — read AND refreshed inside _read_entry, which runs in
        # asyncio.to_thread fill workers: concurrent fills for two
        # slugs would otherwise race the dict (and the bound/clear)
        self._digest_lock = threading.Lock()
        # guarded-by: _digest_lock
        self._digests: dict[str, tuple[int | None,
                                       dict[str, tuple[int, str]]]] = {}
        self._root_resolved: Path | None = None
        self._inflight_reads = 0
        # bumped by every invalidation: a fill that straddles one must
        # not cache what it read (the tree may have been rewritten
        # between its read and its put)
        self._fill_gen = 0
        self.counters = {
            "hits": 0, "misses": 0, "bypass": 0, "shed": 0,
            "disk_reads": 0, "state_hits": 0, "state_misses": 0,
            "state_stale": 0, "invalidations": 0,
        }
        register(self)

    # -- publish-state gate ------------------------------------------------

    async def serving_state(self, slug: str) -> ServingState:
        """ready/deleted/missing for one slug, DB-free in steady state."""
        now = time.monotonic()
        cached = self._states.get(slug)
        if cached is not None and now < cached[1]:
            self.counters["state_hits"] += 1
            return cached[0]
        self.counters["state_misses"] += 1
        from vlog_tpu.jobs import videos as vids   # lazy: no import cycle

        try:
            row = await vids.get_video_serving_state(self.db, slug)
        except Exception as exc:  # noqa: BLE001 — classified below
            from vlog_tpu.db.retry import is_transient_db_error

            if cached is None or not is_transient_db_error(exc):
                raise
            # Stale-while-unavailable: the coordination plane is
            # flapping (brownout) but this slug's last known publish
            # state is in hand — keep playback alive on it rather than
            # 500 every viewer. Re-extend by one TTL so a flap costs one
            # probe per slug per TTL, not one per request.
            self.counters["state_stale"] += 1
            runtime().delivery_stale_state.inc()
            st = cached[0]
            self._states[slug] = (st, now + self.state_ttl_s)
            return st
        if row is None:
            st = ServingState(None, "missing")
        elif row["deleted_at"]:
            st = ServingState(row["id"], "deleted")
        else:
            st = ServingState(row["id"], row["status"])
        if len(self._states) >= _STATE_CACHE_MAX:
            self._states.clear()        # coarse but bounded; re-warms
        self._states[slug] = (st, now + self.state_ttl_s)
        return st

    # -- segment fetch -----------------------------------------------------

    async def fetch(self, slug: str, rel: str
                    ) -> CacheEntry | BypassFile:
        """The media body for ``slug/rel`` — cached, or read via
        single-flight under the admission bound.

        Raises FileNotFoundError (404), :class:`MediaEscapeError`
        (symlink traversal, also a 404 — don't leak tree shape),
        :class:`LoadShedError` (503), and any armed
        ``delivery.read`` failpoint error (the fill fails, nothing is
        cached, the next request retries).
        """
        entry = self.cache.get((slug, rel))
        if entry is not None:
            self.counters["hits"] += 1
            m = runtime()
            m.delivery_requests.labels("hit").inc()
            m.delivery_bytes.labels("cache").inc(entry.size)
            return entry
        return await self.flight.run((slug, rel),
                                     lambda: self._fill(slug, rel))

    async def _fill(self, slug: str, rel: str) -> CacheEntry | BypassFile:
        # a just-finished leader may have filled it while we queued
        entry = self.cache.get((slug, rel))
        if entry is not None:
            self.counters["hits"] += 1
            runtime().delivery_requests.labels("hit").inc()
            runtime().delivery_bytes.labels("cache").inc(entry.size)
            return entry
        m = runtime()
        try:
            failpoints.hit("delivery.shed")
        except failpoints.FailpointError:
            self.counters["shed"] += 1
            m.delivery_requests.labels("shed").inc()
            raise LoadShedError() from None
        if self._inflight_reads >= self.max_inflight_reads:
            self.counters["shed"] += 1
            m.delivery_requests.labels("shed").inc()
            raise LoadShedError()
        self._inflight_reads += 1
        m.delivery_inflight_reads.set(self._inflight_reads)
        gen = self._fill_gen
        try:
            got = await asyncio.to_thread(self._read_entry, slug, rel)
        finally:
            self._inflight_reads -= 1
            m.delivery_inflight_reads.set(self._inflight_reads)
        self.counters["disk_reads"] += 1
        if isinstance(got, BypassFile):
            self.counters["bypass"] += 1
            m.delivery_requests.labels("bypass").inc()
            return got
        self.counters["misses"] += 1
        m.delivery_requests.labels("miss").inc()
        m.delivery_bytes.labels("disk").inc(got.size)
        if gen == self._fill_gen:
            # an invalidation mid-read means these bytes may predate a
            # tree rewrite: serve them to the waiters, cache nothing
            self.cache.put(got)
        m.delivery_cache_bytes.set(self.cache.bytes_cached)
        return got

    # -- blocking internals (run in a thread) ------------------------------

    def _video_root(self) -> Path:
        if self._root_resolved is None:
            self._root_resolved = self.video_dir.resolve()
        return self._root_resolved

    def _read_entry(self, slug: str, rel: str) -> CacheEntry | BypassFile:
        failpoints.hit("delivery.read")
        raw = self.video_dir / slug / rel
        # ONE resolve per fill (not per hit): the lexical ".." check in
        # the route catches textual traversal; this catches a symlink
        # inside the tree pointing outside VIDEO_DIR/slug.
        resolved = raw.resolve()
        slug_root = self._video_root() / slug
        if not (resolved == slug_root
                or str(resolved).startswith(str(slug_root) + os.sep)):
            raise MediaEscapeError(f"{slug}/{rel} escapes its tree")
        try:
            st = resolved.stat()
        except OSError as exc:
            raise FileNotFoundError(str(raw)) from exc
        if not stat_mod.S_ISREG(st.st_mode):
            raise FileNotFoundError(str(raw))
        suffix = resolved.suffix.lower()
        mime = MEDIA_MIME.get(suffix, "application/octet-stream")
        if st.st_size > self.max_entry_bytes:
            return BypassFile(path=resolved, mime=mime, size=st.st_size)
        body = resolved.read_bytes()
        digest = self._digest_for(slug, rel, len(body))
        mutable = suffix in MUTABLE_SUFFIXES
        if digest is not None:
            version, etag = digest, f'"{digest}"'
        else:
            version = f"{st.st_mtime_ns:x}"
            etag = f'"{st.st_mtime_ns:x}-{len(body):x}"'
        expires = None
        if mutable:
            expires = time.monotonic() + self.manifest_ttl_s
        elif self.segment_ttl_s > 0:
            # split deployments: bound staleness of republished bodies
            expires = time.monotonic() + self.segment_ttl_s
        return CacheEntry(
            slug=slug, rel=rel, version=version, body=body, etag=etag,
            mime=mime, mtime=st.st_mtime, immutable=not mutable,
            expires_at=expires)

    def _digest_for(self, slug: str, rel: str, size: int) -> str | None:
        """The manifest sha256 for one published file, or None.

        The per-slug digest map loads from ``outputs.json`` on first
        use and revalidates by the manifest's mtime_ns per fill (a stat,
        not a re-read — fills are misses, already off the hot path). A
        size mismatch means the manifest is stale for this rel: fall
        back to the mtime ETag rather than lie about content.
        """
        from vlog_tpu.storage import integrity

        root = self.video_dir / slug
        with self._digest_lock:
            cached = self._digests.get(slug)
        try:
            current_ns = (root / integrity.MANIFEST_NAME).stat().st_mtime_ns
        except OSError:
            current_ns = None
        if cached is None or cached[0] != current_ns:
            # manifest load runs outside the lock (disk I/O); a racing
            # fill for the same slug just loads twice and the second
            # store wins — both loads saw the same manifest bytes
            cached = integrity.manifest_digests(root)
            with self._digest_lock:
                if len(self._digests) >= _DIGEST_CACHE_MAX:
                    self._digests.clear()   # coarse but bounded; re-warms
                self._digests[slug] = cached
        want = cached[1].get(rel)
        if want is None or want[0] != size:
            return None
        return want[1]

    # -- invalidation + stats ---------------------------------------------

    def invalidate_slug(self, slug: str) -> int:
        """Evict everything known about one slug; returns entries dropped."""
        n = self.cache.invalidate_slug(slug)
        self._states.pop(slug, None)
        with self._digest_lock:
            self._digests.pop(slug, None)
        self._fill_gen += 1
        self.counters["invalidations"] += 1
        runtime().delivery_cache_bytes.set(self.cache.bytes_cached)
        return n

    def invalidate_all(self) -> int:
        n = self.cache.clear()
        self._states.clear()
        with self._digest_lock:
            self._digests.clear()
        self._fill_gen += 1
        self.counters["invalidations"] += 1
        runtime().delivery_cache_bytes.set(self.cache.bytes_cached)
        return n

    def stats(self) -> dict:
        return {
            **self.counters,
            "single_flight_collapses": self.flight.collapses,
            "evictions": self.cache.evictions,
            "expirations": self.cache.expirations,
            "cache_entries": len(self.cache),
            "cache_bytes": self.cache.bytes_cached,
            "cache_budget_bytes": self.cache.max_bytes,
            "state_entries": len(self._states),
            "inflight_reads": self._inflight_reads,
            "max_inflight_reads": self.max_inflight_reads,
        }


# --------------------------------------------------------------------------
# Process-wide plane registry: the invalidation hooks in jobs/ and the
# admin API fan out here. WeakSet: a plane lives exactly as long as the
# app that built it.
# --------------------------------------------------------------------------

_PLANES: "weakref.WeakSet[DeliveryPlane]" = weakref.WeakSet()


def register(plane: DeliveryPlane) -> None:
    _PLANES.add(plane)


def has_planes() -> bool:
    """Whether this process serves media at all — lets invalidation
    hooks skip their slug lookup in worker/admin-only processes."""
    return len(_PLANES) > 0


def invalidate_slug(slug: str) -> int:
    """Evict one slug from every delivery plane in this process.

    Returns total entries dropped. Safe (a no-op) in processes that
    serve no media — workers and the admin API call it unconditionally.
    """
    return sum(p.invalidate_slug(slug) for p in list(_PLANES))


def invalidate_all() -> int:
    return sum(p.invalidate_all() for p in list(_PLANES))


def stats_snapshot() -> dict:
    """Aggregated + per-plane stats for the admin panel."""
    per_plane = [p.stats() for p in list(_PLANES)]
    totals: dict[str, int] = {}
    for s in per_plane:
        for k, v in s.items():
            if isinstance(v, int):
                totals[k] = totals.get(k, 0) + v
    return {"planes": per_plane, "totals": totals,
            "plane_count": len(per_plane)}
