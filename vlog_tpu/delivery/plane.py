"""The delivery plane: origin-side caching + admission for serve_media.

Sits between the public API's media route and the filesystem/DB so that
steady-state playback — every 4-second ``.m4s`` of every concurrent
viewer — touches neither Postgres nor ``open()``:

- a **publish-state cache** (slug -> ready/deleted/missing, TTL +
  explicit invalidation) answers the "may this slug serve at all?"
  gate from memory, via the narrow ``get_video_serving_state`` query on
  miss instead of the old ``SELECT * FROM videos`` per segment;
- the **segment cache** (delivery/cache.py) holds response buffers
  under a byte budget, ETags seeded from the PR-2 ``outputs.json``
  manifest so revalidation compares the real published sha256;
- **single-flight** collapses N concurrent misses for one segment onto
  one disk read;
- an **admission bound** sheds distinct-key misses past
  ``VLOG_DELIVERY_MAX_INFLIGHT_READS`` with 503 + ``Retry-After``
  rather than queueing unbounded reads on the volume;
- **invalidation** — publish/re-encode/delete/restore/verify paths call
  :func:`invalidate_slug`, which fans out to every plane registered in
  this process (plus ``POST /api/delivery/invalidate`` for operators).

Below and beside the RAM LRU sits the **distributed tier**:

- a **disk-backed L2** (delivery/l2.py): digest-covered entries spill
  there on fill and on L1 eviction; an L1 miss probes it before any
  origin read, and every L2 read is sha256-verified against the
  manifest digest before it can serve — corrupt spills are deleted and
  refilled, never served. Content addressing makes slug invalidation a
  no-op for the L2: a republished file gets a new digest and the old
  object simply stops being looked up.
- a **rendezvous-hash ring** (delivery/ring.py) over
  ``VLOG_DELIVERY_PEERS``: a miss on a non-owner origin fetches the
  object from its owner over the public media route (digest-verified,
  loop-guarded by the ``X-Vlog-Peer-Fill`` header) before falling back
  to local disk, so the fleet converges on one hot set instead of N.
  A failing peer gets a short cooldown and fills degrade to local.

On top of the static ring sits the **self-healing fabric**:

- **gossip membership** (delivery/gossip.py): the peer set is seeded
  from ``VLOG_DELIVERY_PEERS`` but no longer frozen by it — jittered
  heartbeat probes walk each peer through alive -> suspect -> down ->
  rejoin, the ring rebuilds from the live view on every version bump,
  and a digest-liar peer is quarantined out of ownership entirely;
- **hedged fills**: a miss routed to the owner launches a hedge to the
  next-ranked healthy peer once the primary overruns the hedge budget
  (``VLOG_DELIVERY_HEDGE_MS``, p95-adaptive from the fill-latency
  reservoir); the first digest-valid response wins, the loser is
  cancelled before it can cache anything;
- **coalesced fills**: peer fetches carry a fill-token header
  (``X-Vlog-Fill-Token``); a tokened request landing on an origin with
  the same object's fill already in flight collapses onto it, so a
  fleet-wide flash crowd produces one origin disk read;
- **failure classification**: peer-fill failures split into transport /
  timeout / status / digest. Only transport and timeout feed gossip
  suspicion; a 503 shed honors the peer's own ``Retry-After`` as the
  cooldown; a digest mismatch quarantines the liar;
- **popularity-aware L2**: per-slug exponentially-decayed heat gates
  disk-L2 admission and grants hot entries second-chance eviction
  (delivery/l2.py), so a herd-warmed working set survives the crowd.
- **publish-time prewarm**: ``finalize_ready`` schedules
  :meth:`DeliveryPlane.prewarm_slug`, pulling every init segment plus
  the first ``VLOG_DELIVERY_PREWARM_SEGMENTS`` media segments of each
  rung through the normal fetch path so a fresh publish's first viewer
  hits RAM.
- a **zero-copy path**: the ``> VLOG_DELIVERY_MAX_ENTRY_BYTES`` bypass
  and L2 hits at or above ``VLOG_DELIVERY_SENDFILE_BYTES`` return
  :class:`~vlog_tpu.delivery.cache.FileEntry`, which
  ``delivery/http.py`` serves via ``os.sendfile`` instead of buffering.

Counters go two places on purpose: the lock-guarded dict on the plane
(the admin stats panel and tests read exact deltas) and the
process-wide ``obs.metrics.runtime()`` registry (Prometheus families
``vlog_delivery_*`` — scraped via the public API's ``/metrics``).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import stat as stat_mod
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass
from email.utils import parsedate_to_datetime
from pathlib import Path

import aiohttp

from vlog_tpu import config
from vlog_tpu.delivery import gossip
from vlog_tpu.delivery.cache import CacheEntry, FileEntry, SegmentCache, \
    SingleFlight
from vlog_tpu.delivery.gossip import Membership
from vlog_tpu.delivery.http import MEDIA_MIME, MUTABLE_SUFFIXES, \
    parse_retry_after
from vlog_tpu.delivery.l2 import DiskL2
from vlog_tpu.delivery.ring import Ring
from vlog_tpu.obs.metrics import runtime
from vlog_tpu.utils import failpoints

log = logging.getLogger("vlog.delivery")

# Publish-state entries (including negative "missing" ones) are tiny;
# this bound only matters under a random-slug 404 storm.
_STATE_CACHE_MAX = 16384
# Per-slug manifest digest maps are bigger (one {rel: (size, sha)} per
# published file); bound them so a long-lived process serving a huge
# catalog doesn't accumulate one map per slug ever touched.
_DIGEST_CACHE_MAX = 2048
# Requests carrying this header are peer fills from another origin:
# they must answer from local tiers only (never re-enter the ring), or
# a misconfigured ring could chase ownership in a cycle.
PEER_FILL_HEADER = "X-Vlog-Peer-Fill"
# Cross-origin fill-correlation token: peer fetches carry the object
# digest here, so an origin that already has the same fill in flight
# coalesces the request onto it (counted) instead of starting another —
# the flash-crowd one-disk-read-fleet-wide mechanism.
FILL_TOKEN_HEADER = "X-Vlog-Fill-Token"
# Media-segment suffixes the prewarm pass considers (CMAF + TS).
_SEGMENT_SUFFIXES = (".m4s", ".ts")
# Per-slug heat records are two floats; bound the map so a random-slug
# 404 storm cannot grow it without limit.
_HEAT_MAX = 4096
# Fill-latency reservoir feeding the p95-adaptive hedge budget: sample
# count kept, and the minimum before adaptivity kicks in.
_FILL_SAMPLES = 256
_FILL_SAMPLE_MIN = 32


class LoadShedError(RuntimeError):
    """Admission refused: too many origin reads in flight (HTTP 503)."""

    def __init__(self, retry_after_s: int = 1):
        super().__init__("origin overloaded; retry shortly")
        self.retry_after_s = retry_after_s


class MediaEscapeError(PermissionError):
    """A resolved path escaped the slug's tree (symlink traversal)."""


class PeerFillError(RuntimeError):
    """A peer fetch came back unusable (status, digest, transport)."""


@dataclass(frozen=True)
class ServingState:
    """What the media route needs to gate a request — nothing more."""

    video_id: int | None
    status: str                 # 'ready' | 'deleted' | 'missing' | other


class DeliveryPlane:
    """One per serving process; constructed by ``build_public_app``."""

    def __init__(self, db, video_dir: str | Path, *,
                 cache_bytes: int | None = None,
                 max_inflight_reads: int | None = None,
                 manifest_ttl_s: float | None = None,
                 segment_ttl_s: float | None = None,
                 state_ttl_s: float | None = None,
                 max_entry_bytes: int | None = None,
                 l2_bytes: int | None = None,
                 l2_dir: str | Path | None = None,
                 peers: tuple[str, ...] | list[str] | None = None,
                 self_url: str | None = None,
                 peer_timeout_s: float | None = None,
                 prewarm_segments: int | None = None,
                 sendfile_bytes: int | None = None,
                 peer_cooldown_s: float | None = None,
                 hedge_ms: float | None = None,
                 gossip_interval_s: float | None = None,
                 heat_halflife_s: float | None = None,
                 l2_admit_heat: float | None = None,
                 l2_hot_heat: float | None = None):
        self.db = db
        self.video_dir = Path(video_dir)
        self.max_inflight_reads = (config.DELIVERY_MAX_INFLIGHT_READS
                                   if max_inflight_reads is None
                                   else max_inflight_reads)
        self.manifest_ttl_s = (config.DELIVERY_MANIFEST_TTL_S
                               if manifest_ttl_s is None else manifest_ttl_s)
        self.segment_ttl_s = (config.DELIVERY_SEGMENT_TTL_S
                              if segment_ttl_s is None else segment_ttl_s)
        self.state_ttl_s = (config.DELIVERY_STATE_TTL_S
                            if state_ttl_s is None else state_ttl_s)
        self.max_entry_bytes = (config.DELIVERY_MAX_ENTRY_BYTES
                                if max_entry_bytes is None
                                else max_entry_bytes)
        self.peer_timeout_s = (config.DELIVERY_PEER_TIMEOUT_S
                               if peer_timeout_s is None else peer_timeout_s)
        self.prewarm_segments = (config.DELIVERY_PREWARM_SEGMENTS
                                 if prewarm_segments is None
                                 else prewarm_segments)
        self.sendfile_bytes = (config.DELIVERY_SENDFILE_BYTES
                               if sendfile_bytes is None else sendfile_bytes)
        self.peer_cooldown_s = (config.DELIVERY_PEER_COOLDOWN_S
                                if peer_cooldown_s is None
                                else peer_cooldown_s)
        self.hedge_ms = (config.DELIVERY_HEDGE_MS
                         if hedge_ms is None else hedge_ms)
        self.gossip_interval_s = (config.DELIVERY_GOSSIP_INTERVAL_S
                                  if gossip_interval_s is None
                                  else gossip_interval_s)
        self.heat_halflife_s = (config.DELIVERY_HEAT_HALFLIFE_S
                                if heat_halflife_s is None
                                else heat_halflife_s)
        m = runtime()
        self.cache = SegmentCache(
            config.DELIVERY_CACHE_BYTES if cache_bytes is None
            else cache_bytes,
            on_evict=self._on_l1_evict)
        self.flight = SingleFlight(
            on_collapse=lambda: m.delivery_collapses.inc())
        self.l2 = DiskL2(
            config.DELIVERY_L2_DIR if l2_dir is None else l2_dir,
            config.DELIVERY_L2_BYTES if l2_bytes is None else l2_bytes,
            on_evict=lambda _n: runtime().delivery_l2_evictions.inc(),
            on_rescue=lambda n: runtime().delivery_l2_rescues.inc(n),
            admit_heat=(config.DELIVERY_L2_ADMIT_HEAT
                        if l2_admit_heat is None else l2_admit_heat),
            hot_heat=(config.DELIVERY_L2_HOT_HEAT
                      if l2_hot_heat is None else l2_hot_heat))
        peer_list = config.DELIVERY_PEERS if peers is None else peers
        own_url = config.DELIVERY_SELF_URL if self_url is None else self_url
        self.ring = Ring(peer_list, own_url)
        # gossip membership: the live view behind the ring. Seeded from
        # the same peer list, but transitions (death, quarantine, join,
        # rejoin) bump its version and _current_ring rebuilds.
        self.membership = Membership(
            peer_list, own_url,
            suspect_after=config.DELIVERY_GOSSIP_SUSPECT_AFTER,
            down_after_s=config.DELIVERY_GOSSIP_DOWN_S,
            quarantine_s=config.DELIVERY_GOSSIP_QUARANTINE_S)
        # loop-confined: _states/_fill_gen/_inflight_reads/_peer_down/
        # _tasks/_http are only touched from event-loop coroutines,
        # never from fill threads
        self._states: dict[str, tuple[ServingState, float]] = {}
        self._peer_down: dict[str, float] = {}      # peer -> retry-at
        self._tasks: set[asyncio.Task] = set()      # spills + prewarms
        self._http: aiohttp.ClientSession | None = None
        # fill-latency reservoir (seconds) behind the p95-adaptive
        # hedge budget; appended on the loop after each fill
        self._fill_times: deque[float] = deque(maxlen=_FILL_SAMPLES)
        # slug -> (outputs.json mtime_ns | None, {rel: (size, sha256)})
        # — read AND refreshed inside fill workers running in
        # asyncio.to_thread: concurrent fills for two slugs would
        # otherwise race the dict (and the bound/clear)
        self._digest_lock = threading.Lock()      # lock-order: 50
        # guarded-by: _digest_lock
        self._digests: dict[str, tuple[int | None,
                                       dict[str, tuple[int, str]]]] = {}
        self._root_resolved: Path | None = None
        self._inflight_reads = 0
        # bumped by every invalidation: a fill that straddles one must
        # not cache what it read (the tree may have been rewritten
        # between its read and its put)
        self._fill_gen = 0
        # hot counters are bumped from event-loop coroutines AND from
        # to_thread fill workers (spills, prewarm bookkeeping), so they
        # live behind a lock; _bump is the one write path
        self._counter_lock = threading.Lock()     # lock-order: 52
        # guarded-by: _counter_lock
        self.counters = {
            "hits": 0, "misses": 0, "bypass": 0, "shed": 0,
            "disk_reads": 0, "state_hits": 0, "state_misses": 0,
            "state_stale": 0, "invalidations": 0,
            "peer_fills": 0, "peer_errors": 0, "sendfile": 0,
            "prewarm_runs": 0, "prewarm_segments": 0, "prewarm_errors": 0,
            "hedges": 0, "hedge_wins": 0, "coalesced_fills": 0,
            "peer_quarantines": 0,
        }
        # per-slug (heat, last-touch) — bumped on the event loop per
        # request, read from to_thread spill workers at admission time
        # guarded-by: _counter_lock
        self._heat: dict[str, tuple[float, float]] = {}
        register(self)

    def _bump(self, key: str, n: int = 1) -> None:
        with self._counter_lock:
            self.counters[key] += n

    # -- publish-state gate ------------------------------------------------

    async def serving_state(self, slug: str) -> ServingState:
        """ready/deleted/missing for one slug, DB-free in steady state."""
        now = time.monotonic()
        cached = self._states.get(slug)
        if cached is not None and now < cached[1]:
            self._bump("state_hits")
            return cached[0]
        self._bump("state_misses")
        from vlog_tpu.jobs import videos as vids   # lazy: no import cycle

        try:
            row = await vids.get_video_serving_state(self.db, slug)
        except Exception as exc:  # noqa: BLE001 — classified below
            from vlog_tpu.db.retry import is_transient_db_error

            if cached is None or not is_transient_db_error(exc):
                raise
            # Stale-while-unavailable: the coordination plane is
            # flapping (brownout) but this slug's last known publish
            # state is in hand — keep playback alive on it rather than
            # 500 every viewer. Re-extend by one TTL so a flap costs one
            # probe per slug per TTL, not one per request.
            self._bump("state_stale")
            runtime().delivery_stale_state.inc()
            st = cached[0]
            self._states[slug] = (st, now + self.state_ttl_s)
            return st
        if row is None:
            st = ServingState(None, "missing")
        elif row["deleted_at"]:
            st = ServingState(row["id"], "deleted")
        else:
            st = ServingState(row["id"], row["status"])
        if len(self._states) >= _STATE_CACHE_MAX:
            self._states.clear()        # coarse but bounded; re-warms
        self._states[slug] = (st, now + self.state_ttl_s)
        return st

    # -- segment fetch -----------------------------------------------------

    async def fetch(self, slug: str, rel: str, *, allow_peer: bool = True,
                    fill_token: str | None = None
                    ) -> CacheEntry | FileEntry:
        """The media body for ``slug/rel`` — L1, then L2, then the ring
        owner (hedged), then local disk, via single-flight under the
        admission bound. ``allow_peer=False`` (requests already carrying
        the peer-fill header) answers from local tiers only.

        ``fill_token`` is the cross-origin fill-correlation token
        (:data:`FILL_TOKEN_HEADER`): a tokened request that lands while
        the same object's fill is already in flight here coalesces onto
        it and is counted — the flash-crowd one-disk-read proof.

        Raises FileNotFoundError (404), :class:`MediaEscapeError`
        (symlink traversal, also a 404 — don't leak tree shape),
        :class:`LoadShedError` (503), and any armed
        ``delivery.read`` failpoint error (the fill fails, nothing is
        cached, the next request retries).
        """
        self._touch_heat(slug)
        entry = self.cache.get((slug, rel))
        if entry is not None:
            self._bump("hits")
            m = runtime()
            m.delivery_requests.labels("hit").inc()
            m.delivery_bytes.labels("cache").inc(entry.size)
            return entry
        if fill_token is not None and self.flight.pending((slug, rel)):
            self._bump("coalesced_fills")
            runtime().delivery_coalesced_fills.inc()
        return await self.flight.run(
            (slug, rel),
            lambda: self._fill(slug, rel, allow_peer, fill_token))

    async def _fill(self, slug: str, rel: str, allow_peer: bool,
                    fill_token: str | None = None
                    ) -> CacheEntry | FileEntry:
        # a just-finished leader may have filled it while we queued
        entry = self.cache.get((slug, rel))
        if entry is not None:
            self._bump("hits")
            runtime().delivery_requests.labels("hit").inc()
            runtime().delivery_bytes.labels("cache").inc(entry.size)
            return entry
        m = runtime()
        try:
            failpoints.hit("delivery.shed")
        except failpoints.FailpointError:
            self._bump("shed")
            m.delivery_requests.labels("shed").inc()
            raise LoadShedError() from None
        if self._inflight_reads >= self.max_inflight_reads:
            self._bump("shed")
            m.delivery_requests.labels("shed").inc()
            raise LoadShedError()
        self._inflight_reads += 1
        m.delivery_inflight_reads.set(self._inflight_reads)
        gen = self._fill_gen
        source = "disk"
        t0 = time.monotonic()
        try:
            got: CacheEntry | FileEntry | None = None
            kind, meta = await asyncio.to_thread(self._pre_fill, slug, rel)
            if kind == "l2":
                digest, size, body, mtime = meta
                m.delivery_l2_requests.labels("hit").inc()
                m.delivery_bytes.labels("l2").inc(size)
                source = "l2"
                if size >= self.sendfile_bytes:
                    got = FileEntry(
                        slug=slug, rel=rel, path=self.l2.path_for(digest),
                        size=size, etag=f'"{digest}"', mime=_mime_for(rel),
                        mtime=mtime, immutable=True, digest=digest)
                else:
                    got = self._entry_from_bytes(slug, rel, digest, body,
                                                 mtime)
            else:
                if kind in ("miss", "corrupt") and self.l2.enabled:
                    m.delivery_l2_requests.labels(kind).inc()
                if meta is not None and allow_peer:
                    digest, _size = meta
                    if fill_token is None:
                        got = await self._peer_fetch(slug, rel, digest)
                    else:
                        got = await self._peer_fetch(slug, rel, digest,
                                                     fill_token)
                    if got is not None:
                        source = "peer"
                        m.delivery_bytes.labels("peer").inc(got.size)
                        self._store_l2_soon(got)
            if got is None:
                got = await asyncio.to_thread(self._read_entry, slug, rel)
                self._bump("disk_reads")
                if isinstance(got, CacheEntry):
                    m.delivery_bytes.labels("disk").inc(got.size)
                    self._store_l2_soon(got)
        finally:
            self._inflight_reads -= 1
            m.delivery_inflight_reads.set(self._inflight_reads)
        # feed the latency reservoir behind the p95-adaptive hedge
        # budget (and the fill histogram) with the winning source
        dt = time.monotonic() - t0
        self._fill_times.append(dt)
        if source in ("l2", "peer"):
            fill_label = source
        elif isinstance(got, FileEntry):
            fill_label = "bypass"
        else:
            fill_label = "disk"
        m.delivery_fill_seconds.labels(fill_label).observe(dt)
        if source == "l2":
            m.delivery_requests.labels("l2_hit").inc()
        elif source == "peer":
            self._bump("peer_fills")
            m.delivery_requests.labels("peer_fill").inc()
        elif isinstance(got, FileEntry):
            self._bump("bypass")
            m.delivery_requests.labels("bypass").inc()
        else:
            self._bump("misses")
            m.delivery_requests.labels("miss").inc()
        if isinstance(got, FileEntry):
            self._bump("sendfile")
        elif gen == self._fill_gen:
            # an invalidation mid-fill means these bytes may predate a
            # tree rewrite: serve them to the waiters, cache nothing
            self.cache.put(got)
            m.delivery_cache_bytes.set(self.cache.bytes_cached)
        return got

    # -- peer fill (event loop: aiohttp client) ----------------------------

    def _current_ring(self) -> Ring:
        """The live rendezvous view. Rebuilt from gossip membership only
        when the membership version has moved past the ring's — a ring
        installed directly (tests, static deployments with gossip off)
        keeps version 0 on both sides and is never clobbered."""
        mv = self.membership.version
        if mv and mv != self.ring.version:
            self.ring = self.membership.ring()
            runtime().delivery_ring_version.set(self.ring.version)
        return self.ring

    def _hedge_delay_s(self) -> float | None:
        """The hedge launch budget: ``hedge_ms`` until enough fill
        samples accumulate, then the observed p95 clamped to
        [hedge_ms/4, hedge_ms*4]. None disables hedging."""
        if self.hedge_ms <= 0:
            return None
        base = self.hedge_ms / 1000.0
        if len(self._fill_times) < _FILL_SAMPLE_MIN:
            return base
        ordered = sorted(self._fill_times)
        p95 = ordered[min(len(ordered) - 1, int(len(ordered) * 0.95))]
        return min(max(p95, base / 4.0), base * 4.0)

    async def _peer_fetch(self, slug: str, rel: str, digest: str,
                          fill_token: str | None = None
                          ) -> CacheEntry | None:
        """Fetch one digest-known object from the ring, hedged; None
        means 'fall back to local fill' (owner-is-us, no healthy
        candidate, or every contacted peer failed).

        Candidates are the rendezvous-ranked healthy peers for the key:
        the owner first, then the peer a hedge should try. Peers in
        cooldown or gossip-unhealthy (suspect/down/quarantined) are
        skipped outright — that is the routed-around-within-one-
        suspect-window guarantee."""
        key = f"{slug}/{rel}"
        ring = self._current_ring()
        if ring.is_local(key):
            return None
        now = time.monotonic()
        candidates: list[str] = []
        for peer in ring.ranked(key):
            if peer == ring.self_url:
                continue
            if self._peer_down.get(peer, 0.0) > now:
                continue
            state = self.membership.state_of(peer)
            if state is not None and state != gossip.ALIVE:
                continue
            candidates.append(peer)
            if len(candidates) == 2:
                break
        if not candidates:
            return None
        delay_s = self._hedge_delay_s()
        if delay_s is None or len(candidates) < 2:
            return await self._peer_fetch_one(slug, rel, digest,
                                              candidates[0], fill_token)
        return await self._peer_fetch_hedged(slug, rel, digest,
                                             candidates, delay_s,
                                             fill_token)

    async def _peer_fetch_hedged(self, slug: str, rel: str, digest: str,
                                 candidates: list[str], delay_s: float,
                                 fill_token: str | None
                                 ) -> CacheEntry | None:
        """Primary fetch to ``candidates[0]``; once it overruns the
        hedge budget, a hedge to ``candidates[1]``. First digest-valid
        response wins; the loser is cancelled (and can never cache —
        entries only exist after the full body verified)."""
        m = runtime()
        primary = asyncio.create_task(
            self._peer_fetch_one(slug, rel, digest, candidates[0],
                                 fill_token),
            name="vlog-peer-fill")
        hedge: asyncio.Task | None = None
        try:
            done, _ = await asyncio.wait({primary}, timeout=delay_s)
            if primary in done:
                entry = primary.result()
                if entry is not None:
                    return entry
                # primary failed *fast* — immediate failover to the
                # next-ranked peer (the budget never elapsed, so this
                # is not counted as a hedge)
                return await self._peer_fetch_one(
                    slug, rel, digest, candidates[1], fill_token)
            self._bump("hedges")
            m.delivery_hedges.labels("launched").inc()
            hedge = asyncio.create_task(
                self._peer_fetch_one(slug, rel, digest, candidates[1],
                                     fill_token),
                name="vlog-peer-hedge")
            pending: set[asyncio.Task] = {primary, hedge}
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for task in done:
                    entry = task.result()  # fetch-one never raises
                    if entry is None:
                        continue
                    if task is hedge:
                        self._bump("hedge_wins")
                        m.delivery_hedges.labels("win").inc()
                    else:
                        m.delivery_hedges.labels("primary_win").inc()
                    return entry
            return None
        finally:
            losers = [t for t in (primary, hedge) if t is not None]
            for task in losers:
                if not task.done():
                    task.cancel()
            await asyncio.gather(*losers, return_exceptions=True)

    async def _peer_fetch_one(self, slug: str, rel: str, digest: str,
                              peer: str, fill_token: str | None
                              ) -> CacheEntry | None:
        """One digest-verified fetch from one peer; None on any failure
        (classified and fed to cooldown/membership). Never raises except
        CancelledError (a hedge loser), which aborts before any byte
        could be cached."""
        try:
            failpoints.hit("delivery.peer")
        except failpoints.FailpointError as exc:
            self._peer_failed(peer, "transport", exc)
            return None
        try:
            failpoints.hit("delivery.hedge")
        except failpoints.FailpointError as exc:
            # chaos stall: this fetch hangs for the full peer budget,
            # exactly like a wedged-but-connected owner — the hedge to
            # the next-ranked peer is what must rescue the request
            await asyncio.sleep(self.peer_timeout_s)
            self._peer_failed(peer, "timeout", exc)
            return None
        try:
            sess = self._http_session()
            async with sess.get(
                    f"{peer}/videos/{slug}/{rel}",
                    headers={PEER_FILL_HEADER: "1",
                             FILL_TOKEN_HEADER: fill_token or digest},
                    timeout=aiohttp.ClientTimeout(total=self.peer_timeout_s),
            ) as resp:
                if resp.status != 200:
                    retry_after = None
                    if resp.status == 503:
                        # a shedding peer names its own backoff; honor
                        # it as the cooldown instead of the flat knob
                        retry_after = parse_retry_after(
                            resp.headers.get("Retry-After"))
                    self._peer_failed(
                        peer, "status",
                        PeerFillError(f"{peer} answered {resp.status}"),
                        cooldown_s=retry_after)
                    return None
                body = await resp.read()
                last_modified = resp.headers.get("Last-Modified")
        except asyncio.CancelledError:
            raise
        except asyncio.TimeoutError as exc:
            self._peer_failed(peer, "timeout", exc)
            return None
        except Exception as exc:  # noqa: BLE001 — any failure degrades
            self._peer_failed(peer, "transport", exc)
            return None
        if hashlib.sha256(body).hexdigest() != digest:
            # the peer served bytes that don't match the manifest this
            # origin published against — liveness is not trust
            self._peer_failed(peer, "digest", PeerFillError(
                f"{peer} body does not match digest {digest[:12]}…"))
            return None
        self.membership.record_success(peer)
        mtime = _parse_http_date(last_modified)
        runtime().delivery_peer_fills.labels("hit").inc()
        return self._entry_from_bytes(slug, rel, digest, body, mtime)

    def _peer_failed(self, peer: str, kind: str, exc: BaseException, *,
                     cooldown_s: float | None = None) -> None:
        """Classified peer-fill failure. Only transport/timeout feed
        gossip suspicion (the process may be unreachable); a status
        failure just cools the peer down (its own Retry-After wins over
        the knob); a digest liar is quarantined out of ownership."""
        cooldown = (self.peer_cooldown_s if cooldown_s is None
                    else cooldown_s)
        if kind == "digest":
            self.membership.quarantine(peer)
            self._bump("peer_quarantines")
            cooldown = max(cooldown, self.membership.quarantine_s)
        elif kind in ("transport", "timeout"):
            self.membership.record_failure(peer)
        self._peer_down[peer] = time.monotonic() + cooldown
        self._bump("peer_errors")
        runtime().delivery_peer_fills.labels(kind).inc()
        log.warning("peer-fill from %s failed [%s] (%.1fs cooldown): %s",
                    peer, kind, cooldown, exc)

    def _http_session(self) -> aiohttp.ClientSession:
        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession()
        return self._http

    # -- gossip membership -------------------------------------------------

    def start_gossip(self) -> bool:
        """Start the membership probe loop on the running event loop;
        False when gossip is disabled, there is no peer to probe, or no
        loop is running here. Called from the app's startup hook."""
        if self.gossip_interval_s <= 0 or not self.membership.enabled:
            return False
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return False
        m = runtime()
        t = loop.create_task(
            gossip.probe_loop(
                self.membership, self._http_session,
                interval_s=self.gossip_interval_s,
                jitter=config.DELIVERY_GOSSIP_JITTER,
                on_outcome=lambda o:
                    m.delivery_gossip_probes.labels(o).inc()),
            name="vlog-delivery-gossip")
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)
        return True

    # -- per-slug heat (popularity signal for the L2) ----------------------

    def _touch_heat(self, slug: str) -> None:
        """One request's worth of heat: +1 onto an exponential decay
        with half-life ``heat_halflife_s``. Bumped on the event loop,
        read from to_thread spill workers — hence the counter lock."""
        now = time.monotonic()
        with self._counter_lock:
            rec = self._heat.get(slug)
            if rec is None:
                if len(self._heat) >= _HEAT_MAX:
                    self._heat.clear()  # coarse but bounded; re-warms
                heat = 1.0
            else:
                heat = rec[0] * 0.5 ** ((now - rec[1])
                                        / self.heat_halflife_s) + 1.0
            self._heat[slug] = (heat, now)

    def heat_of(self, slug: str) -> float:
        """The slug's decayed heat right now (0.0 when never touched)."""
        now = time.monotonic()
        with self._counter_lock:
            rec = self._heat.get(slug)
        if rec is None:
            return 0.0
        return rec[0] * 0.5 ** ((now - rec[1]) / self.heat_halflife_s)

    def heat_top(self, n: int = 10) -> list[tuple[str, float]]:
        """Hottest slugs right now (admin fabric panel)."""
        now = time.monotonic()
        with self._counter_lock:
            items = list(self._heat.items())
        decayed = [(slug, h * 0.5 ** ((now - at) / self.heat_halflife_s))
                   for slug, (h, at) in items]
        decayed.sort(key=lambda kv: kv[1], reverse=True)
        return decayed[:n]

    async def close(self) -> None:
        """Release loop-bound resources (peer HTTP session, background
        spill/prewarm tasks). Called from the app's cleanup hook."""
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        if self._http is not None and not self._http.closed:
            await self._http.close()

    # -- L2 spill ----------------------------------------------------------

    def _on_l1_evict(self, victim: CacheEntry) -> None:
        runtime().delivery_evictions.inc()
        self._store_l2_soon(victim)

    def _store_l2_soon(self, entry: CacheEntry | FileEntry) -> None:
        """Write-through/spill one digest-covered immutable entry to the
        L2 off the serve path. On the event loop this schedules a
        thread; in loop-less (unit-test) contexts it writes inline."""
        if not self.l2.enabled or not isinstance(entry, CacheEntry):
            return
        if entry.digest is None or not entry.immutable:
            return

        digest, body, mtime = entry.digest, entry.body, entry.mtime
        heat = self.heat_of(entry.slug)     # stamp admission heat now

        def work() -> None:
            if self.l2.put(digest, body, mtime, heat=heat):
                runtime().delivery_l2_bytes.set(self.l2.stats()["bytes"])

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            work()
            return
        t = loop.create_task(asyncio.to_thread(work),
                             name="vlog-delivery-invalidate")
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    # -- publish-time prewarm ----------------------------------------------

    def schedule_prewarm(self, slug: str) -> bool:
        """Fire-and-forget prewarm of a freshly published slug; False
        when prewarm is disabled or no loop is running here."""
        if self.prewarm_segments <= 0:
            return False
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return False
        t = loop.create_task(self.prewarm_slug(slug),
                             name="vlog-delivery-prewarm")
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)
        return True

    async def prewarm_slug(self, slug: str) -> dict:
        """Pull every init segment + the first ``prewarm_segments``
        media segments of each rung through the normal fetch path (so
        single-flight, L2 write-through, and the ring all apply)."""
        self._bump("prewarm_runs")
        m = runtime()
        rels = await asyncio.to_thread(self._prewarm_targets, slug)
        warmed = errors = 0
        for rel in rels:
            try:
                await self.fetch(slug, rel)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — prewarm is best-effort
                errors += 1
                m.delivery_prewarm.labels("error").inc()
            else:
                warmed += 1
                m.delivery_prewarm.labels("warmed").inc()
        self._bump("prewarm_segments", warmed)
        self._bump("prewarm_errors", errors)
        return {"slug": slug, "targets": len(rels), "warmed": warmed,
                "errors": errors}

    def _prewarm_targets(self, slug: str) -> list[str]:
        """Init segments + first-N media segments per rung directory,
        straight from the publish manifest (no playlist parsing)."""
        from vlog_tpu.storage import integrity

        _, files = integrity.manifest_digests(self.video_dir / slug)
        inits: list[str] = []
        by_dir: dict[str, list[str]] = {}
        for rel in files:
            name = rel.rsplit("/", 1)[-1]
            if name.startswith("init"):
                inits.append(rel)
            elif Path(name).suffix.lower() in _SEGMENT_SUFFIXES:
                d = rel.rsplit("/", 1)[0] if "/" in rel else ""
                by_dir.setdefault(d, []).append(rel)
        targets = sorted(inits)
        for _, segs in sorted(by_dir.items()):
            targets.extend(sorted(segs)[:self.prewarm_segments])
        return targets

    # -- blocking internals (run in a thread) ------------------------------

    def _pre_fill(self, slug: str, rel: str):
        """L2/ring eligibility + the L2 probe, off-loop.

        Returns one of::

            ("l2",     (digest, size, body, mtime))   # verified L2 hit
            ("miss",   (digest, size))   # digest known, not in L2
            ("corrupt", (digest, size))  # was in L2, failed verify
            ("origin", None)             # mutable / uncovered / bypass
        """
        if not self.l2.enabled and not self.ring.enabled:
            return "origin", None       # single-origin: no extra stat
        if Path(rel).suffix.lower() in MUTABLE_SUFFIXES:
            return "origin", None       # playlists mutate: local + TTL
        want = self._manifest_meta(slug, rel)
        if want is None:
            return "origin", None       # no manifest coverage: local
        size, digest = want
        if size > self.max_entry_bytes:
            return "origin", None       # bypass objects stream locally
        if not self.l2.enabled:
            return "miss", (digest, size)
        outcome, body, mtime = self.l2.read(digest)
        if outcome == "hit":
            return "l2", (digest, size, body, mtime)
        return outcome, (digest, size)

    def _video_root(self) -> Path:
        if self._root_resolved is None:
            self._root_resolved = self.video_dir.resolve()
        return self._root_resolved

    def _read_entry(self, slug: str, rel: str) -> CacheEntry | FileEntry:
        failpoints.hit("delivery.read")
        raw = self.video_dir / slug / rel
        # ONE resolve per fill (not per hit): the lexical ".." check in
        # the route catches textual traversal; this catches a symlink
        # inside the tree pointing outside VIDEO_DIR/slug.
        resolved = raw.resolve()
        slug_root = self._video_root() / slug
        if not (resolved == slug_root
                or str(resolved).startswith(str(slug_root) + os.sep)):
            raise MediaEscapeError(f"{slug}/{rel} escapes its tree")
        try:
            st = resolved.stat()
        except OSError as exc:
            raise FileNotFoundError(str(raw)) from exc
        if not stat_mod.S_ISREG(st.st_mode):
            raise FileNotFoundError(str(raw))
        suffix = resolved.suffix.lower()
        mime = MEDIA_MIME.get(suffix, "application/octet-stream")
        if st.st_size > self.max_entry_bytes:
            # the bypass still carries the manifest digest when one
            # covers the file, so its validators match the buffered
            # paths (mtime-size fallback otherwise — same as below)
            digest = self._digest_for(slug, rel, st.st_size)
            etag = (f'"{digest}"' if digest is not None
                    else f'"{st.st_mtime_ns:x}-{st.st_size:x}"')
            return FileEntry(
                slug=slug, rel=rel, path=resolved, size=st.st_size,
                etag=etag, mime=mime, mtime=st.st_mtime,
                immutable=suffix not in MUTABLE_SUFFIXES, digest=digest)
        body = resolved.read_bytes()
        digest = self._digest_for(slug, rel, len(body))
        mutable = suffix in MUTABLE_SUFFIXES
        if digest is not None:
            version, etag = digest, f'"{digest}"'
        else:
            version = f"{st.st_mtime_ns:x}"
            etag = f'"{st.st_mtime_ns:x}-{len(body):x}"'
        expires = None
        if mutable:
            expires = time.monotonic() + self.manifest_ttl_s
        elif self.segment_ttl_s > 0:
            # split deployments: bound staleness of republished bodies
            expires = time.monotonic() + self.segment_ttl_s
        return CacheEntry(
            slug=slug, rel=rel, version=version, body=body, etag=etag,
            mime=mime, mtime=st.st_mtime, immutable=not mutable,
            expires_at=expires, digest=digest)

    def _entry_from_bytes(self, slug: str, rel: str, digest: str,
                          body: bytes, mtime: float) -> CacheEntry:
        """A cacheable entry for digest-verified bytes that did NOT come
        from the local origin tree (L2 promotion, peer fill)."""
        expires = None
        if self.segment_ttl_s > 0:
            expires = time.monotonic() + self.segment_ttl_s
        return CacheEntry(
            slug=slug, rel=rel, version=digest, body=body,
            etag=f'"{digest}"', mime=_mime_for(rel), mtime=mtime,
            immutable=True, expires_at=expires, digest=digest)

    def _manifest_meta(self, slug: str, rel: str
                       ) -> tuple[int, str] | None:
        """``(size, sha256)`` from the publish manifest, or None.

        The per-slug digest map loads from ``outputs.json`` on first
        use and revalidates by the manifest's mtime_ns per fill (a stat,
        not a re-read — fills are misses, already off the hot path).
        """
        from vlog_tpu.storage import integrity

        root = self.video_dir / slug
        with self._digest_lock:
            cached = self._digests.get(slug)
        try:
            current_ns = (root / integrity.MANIFEST_NAME).stat().st_mtime_ns
        except OSError:
            current_ns = None
        if cached is None or cached[0] != current_ns:
            # manifest load runs outside the lock (disk I/O); a racing
            # fill for the same slug just loads twice and the second
            # store wins — both loads saw the same manifest bytes
            cached = integrity.manifest_digests(root)
            with self._digest_lock:
                if len(self._digests) >= _DIGEST_CACHE_MAX:
                    self._digests.clear()   # coarse but bounded; re-warms
                self._digests[slug] = cached
        return cached[1].get(rel)

    def _digest_for(self, slug: str, rel: str, size: int) -> str | None:
        """The manifest sha256 for one published file, or None. A size
        mismatch means the manifest is stale for this rel: fall back to
        the mtime ETag rather than lie about content."""
        want = self._manifest_meta(slug, rel)
        if want is None or want[0] != size:
            return None
        return want[1]

    # -- invalidation + stats ---------------------------------------------

    def invalidate_slug(self, slug: str) -> int:
        """Evict everything known about one slug; returns entries
        dropped. The L2 is intentionally untouched: it is addressed by
        content digest, so a republished tree's new manifest simply
        stops resolving to the old objects and they age out by LRU."""
        n = self.cache.invalidate_slug(slug)
        self._states.pop(slug, None)
        with self._digest_lock:
            self._digests.pop(slug, None)
        self._fill_gen += 1
        self._bump("invalidations")
        runtime().delivery_cache_bytes.set(self.cache.bytes_cached)
        return n

    def invalidate_all(self) -> int:
        n = self.cache.clear()
        self._states.clear()
        with self._digest_lock:
            self._digests.clear()
        n += self.l2.clear()            # operator nuke clears disk too
        self._fill_gen += 1
        self._bump("invalidations")
        runtime().delivery_cache_bytes.set(self.cache.bytes_cached)
        runtime().delivery_l2_bytes.set(0)
        return n

    def stats(self) -> dict:
        with self._counter_lock:
            counters = dict(self.counters)
        l2 = self.l2.stats()
        return {
            **counters,
            "single_flight_collapses": self.flight.collapses,
            "evictions": self.cache.evictions,
            "expirations": self.cache.expirations,
            "cache_entries": len(self.cache),
            "cache_bytes": self.cache.bytes_cached,
            "cache_budget_bytes": self.cache.max_bytes,
            "state_entries": len(self._states),
            "inflight_reads": self._inflight_reads,
            "max_inflight_reads": self.max_inflight_reads,
            "l2_hits": l2["hits"],
            "l2_misses": l2["misses"],
            "l2_corrupt": l2["corrupt"],
            "l2_stores": l2["stores"],
            "l2_evictions": l2["evictions"],
            "l2_rescues": l2["rescues"],
            "l2_admit_skips": l2["admit_skips"],
            "l2_bytes": l2["bytes"],
            "l2_budget_bytes": l2["budget_bytes"],
            "l2_entries": l2["entries"],
            "ring": self.ring.membership(),
            "fabric": self.fabric_view(),
        }

    def fabric_view(self) -> dict:
        """The self-healing-fabric panel: live membership, ring version,
        hedge/coalesce rates, current hedge budget, heat top-N."""
        with self._counter_lock:
            hedges = self.counters["hedges"]
            hedge_wins = self.counters["hedge_wins"]
            coalesced = self.counters["coalesced_fills"]
            quarantines = self.counters["peer_quarantines"]
        delay = self._hedge_delay_s()
        return {
            "membership": self.membership.snapshot(),
            "ring_version": self.ring.version,
            "gossip_interval_s": self.gossip_interval_s,
            "hedge_delay_ms": (None if delay is None
                               else round(delay * 1000.0, 1)),
            "hedges": hedges,
            "hedge_wins": hedge_wins,
            "coalesced_fills": coalesced,
            "peer_quarantines": quarantines,
            "heat_top": [{"slug": s, "heat": round(h, 2)}
                         for s, h in self.heat_top(10)],
        }


def _mime_for(rel: str) -> str:
    return MEDIA_MIME.get(Path(rel).suffix.lower(),
                          "application/octet-stream")


def _parse_http_date(value: str | None) -> float:
    """Last-Modified from a peer response -> epoch seconds; the fetch
    time when absent/garbled (a fresh strong-ETag validator either way)."""
    if value:
        try:
            return parsedate_to_datetime(value).timestamp()
        except (TypeError, ValueError):
            pass
    return time.time()


# --------------------------------------------------------------------------
# Process-wide plane registry: the invalidation hooks in jobs/ and the
# admin API fan out here. WeakSet: a plane lives exactly as long as the
# app that built it.
# --------------------------------------------------------------------------

_PLANES: "weakref.WeakSet[DeliveryPlane]" = weakref.WeakSet()


def register(plane: DeliveryPlane) -> None:
    _PLANES.add(plane)


def has_planes() -> bool:
    """Whether this process serves media at all — lets invalidation
    hooks skip their slug lookup in worker/admin-only processes."""
    return len(_PLANES) > 0


def invalidate_slug(slug: str) -> int:
    """Evict one slug from every delivery plane in this process.

    Returns total entries dropped. Safe (a no-op) in processes that
    serve no media — workers and the admin API call it unconditionally.
    """
    return sum(p.invalidate_slug(slug) for p in list(_PLANES))


def invalidate_all() -> int:
    return sum(p.invalidate_all() for p in list(_PLANES))


def prewarm_slug(slug: str) -> int:
    """Schedule publish-time prewarm on every plane in this process;
    returns how many planes scheduled one (0 with prewarm disabled, no
    planes, or no running loop — all fine: prewarm is best-effort)."""
    return sum(1 for p in list(_PLANES) if p.schedule_prewarm(slug))


def stats_snapshot() -> dict:
    """Aggregated + per-plane stats for the admin panel."""
    per_plane = [p.stats() for p in list(_PLANES)]
    totals: dict[str, int] = {}
    for s in per_plane:
        for k, v in s.items():
            if isinstance(v, int):
                totals[k] = totals.get(k, 0) + v
    return {"planes": per_plane, "totals": totals,
            "plane_count": len(per_plane),
            "ring": per_plane[0]["ring"] if per_plane else None,
            "fabric": per_plane[0]["fabric"] if per_plane else None}
