"""Disk-backed L2 below the delivery plane's RAM LRU.

A content-addressed spill store: entries land here on L1 eviction and on
fill, named by their publish-manifest sha256 (``<digest[:2]>/<digest>``),
and byte-bounded with LRU eviction of its own. Because the name *is* the
digest, lookups are exact-content by construction — a republished
segment gets a new digest and simply stops being looked up, so slug
invalidation never has to touch the L2 at all; stale objects age out.

Trust model: the store is a cache on local disk, not a source of truth.
Every read hashes the bytes and compares against the digest name before
anything can serve or promote to L1 — a corrupt or truncated entry is
deleted and reported so the caller refills from the origin tree (or a
peer), never served.

Thread model: fills and spills run on ``asyncio.to_thread`` workers
while stats are read from the event loop, so the index is lock-guarded.
File reads/writes happen OUTSIDE the lock (only index bookkeeping is
serialized); the worst interleaving is two threads verifying the same
digest twice, which is idempotent.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from collections import OrderedDict
from pathlib import Path
from typing import Callable

log = logging.getLogger("vlog.delivery.l2")

__all__ = ["DiskL2"]

# sha256 hex: the only filenames the store creates or trusts on rescan.
_DIGEST_LEN = 64
_TMP_PREFIX = "tmp-"


def _is_digest(name: str) -> bool:
    if len(name) != _DIGEST_LEN:
        return False
    try:
        int(name, 16)
    except ValueError:
        return False
    return True


class DiskL2:
    """Byte-bounded digest-named disk store with LRU eviction."""

    def __init__(self, root: str | Path, max_bytes: int, *,
                 on_evict: Callable[[int], None] | None = None) -> None:
        self.root = Path(root)
        self.max_bytes = int(max_bytes)
        self._on_evict = on_evict
        self._lock = threading.Lock()             # lock-order: 54
        # guarded-by: _lock
        self._index: OrderedDict[str, int] = OrderedDict()  # digest -> size
        # guarded-by: _lock
        self._bytes = 0
        # guarded-by: _lock
        self.counters = {
            "hits": 0, "misses": 0, "corrupt": 0,
            "stores": 0, "evictions": 0,
        }
        if self.enabled:
            self.root.mkdir(parents=True, exist_ok=True)
            self._rescan()

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    # -- init-time rescan --------------------------------------------------

    def _rescan(self) -> None:
        """Rebuild the index from disk so the warm set survives process
        restarts. Ordered oldest-mtime-first (approximate recency: mtimes
        mirror the origin segment, not last access), then trimmed to
        budget. Stray temp files from a crashed writer are swept."""
        found: list[tuple[float, str, int]] = []
        for shard in sorted(self.root.iterdir()) if self.root.is_dir() else []:
            if not shard.is_dir():
                if shard.name.startswith(_TMP_PREFIX):
                    shard.unlink(missing_ok=True)
                continue
            for f in shard.iterdir():
                if f.name.startswith(_TMP_PREFIX):
                    f.unlink(missing_ok=True)
                    continue
                if not _is_digest(f.name):
                    continue
                try:
                    st = f.stat()
                except OSError:
                    continue
                found.append((st.st_mtime, f.name, st.st_size))
        found.sort()
        with self._lock:
            for _, digest, size in found:
                self._index[digest] = size
                self._bytes += size
            victims = self._evict_over_budget_locked()
        self._unlink_all(victims)

    # -- core --------------------------------------------------------------

    def path_for(self, digest: str) -> Path:
        return self.root / digest[:2] / digest

    def read(self, digest: str) -> tuple[str, bytes | None, float]:
        """``(outcome, body, mtime)`` — outcome one of hit/miss/corrupt.

        A hit returns the verified bytes plus the stored mtime (the
        origin segment's, preserved at store time so Last-Modified is
        identical whichever tier serves). corrupt means the bytes were
        there but failed the digest check; the entry has already been
        deleted and the caller must refill from origin.
        """
        if not self.enabled:
            return "miss", None, 0.0
        with self._lock:
            if digest in self._index:
                self._index.move_to_end(digest)
                known = True
            else:
                known = False
        path = self.path_for(digest)
        if not known:
            self._bump("misses")
            return "miss", None, 0.0
        try:
            st = path.stat()
            body = path.read_bytes()
        except OSError:
            # indexed but unreadable (crash residue, external wipe)
            self._drop(digest)
            self._bump("misses")
            return "miss", None, 0.0
        if hashlib.sha256(body).hexdigest() != digest:
            log.warning("l2 entry %s failed digest check (%d bytes); "
                        "deleting", digest[:12], len(body))
            self._drop(digest)
            path.unlink(missing_ok=True)
            self._bump("corrupt")
            return "corrupt", None, 0.0
        self._bump("hits")
        return "hit", body, st.st_mtime

    def put(self, digest: str, body: bytes, mtime: float) -> bool:
        """Store verified bytes under their digest; no-op when already
        present or when the object alone exceeds the byte budget.
        Atomic: temp write + rename, so readers never see a torn file."""
        if not self.enabled or len(body) > self.max_bytes:
            return False
        with self._lock:
            if digest in self._index:
                self._index.move_to_end(digest)
                return False
        path = self.path_for(digest)
        tmp = path.parent / f"{_TMP_PREFIX}{digest[:16]}-{os.getpid()}"
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp.write_bytes(body)
            # carry the origin segment's mtime so Last-Modified (and the
            # If-Range date match) is identical across L1/L2/sendfile
            os.utime(tmp, (mtime, mtime))
            os.replace(tmp, path)
        except OSError as exc:
            log.warning("l2 store failed for %s: %s", digest[:12], exc)
            tmp.unlink(missing_ok=True)
            return False
        with self._lock:
            if digest in self._index:       # racing writer beat us
                self._index.move_to_end(digest)
                return False
            self._index[digest] = len(body)
            self._bytes += len(body)
            self.counters["stores"] += 1
            victims = self._evict_over_budget_locked()
        self._unlink_all(victims)
        return True

    def _evict_over_budget_locked(self) -> list[str]:
        """LRU-evict index entries until under budget; returns the digests
        whose files the caller must unlink (outside the lock)."""
        victims: list[str] = []
        while self._bytes > self.max_bytes and self._index:
            digest, size = self._index.popitem(last=False)
            self._bytes -= size
            self.counters["evictions"] += 1
            victims.append(digest)
        return victims

    def _unlink_all(self, digests: list[str]) -> None:
        for digest in digests:
            self.path_for(digest).unlink(missing_ok=True)
            if self._on_evict is not None:
                self._on_evict(1)

    def _drop(self, digest: str) -> None:
        with self._lock:
            size = self._index.pop(digest, None)
            if size is not None:
                self._bytes -= size

    def _bump(self, key: str) -> None:
        with self._lock:
            self.counters[key] += 1

    def clear(self) -> int:
        """Drop every entry (admin invalidate-all); returns count.
        Not counted as evictions — a clear is an operator action, not
        budget pressure."""
        with self._lock:
            victims = list(self._index)
            self._index.clear()
            self._bytes = 0
        for digest in victims:
            self.path_for(digest).unlink(missing_ok=True)
        return len(victims)

    def stats(self) -> dict:
        with self._lock:
            return {
                "bytes": self._bytes,
                "budget_bytes": self.max_bytes,
                "entries": len(self._index),
                **self.counters,
            }
