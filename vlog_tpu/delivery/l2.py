"""Disk-backed L2 below the delivery plane's RAM LRU.

A content-addressed spill store: entries land here on L1 eviction and on
fill, named by their publish-manifest sha256 (``<digest[:2]>/<digest>``),
and byte-bounded with LRU eviction of its own. Because the name *is* the
digest, lookups are exact-content by construction — a republished
segment gets a new digest and simply stops being looked up, so slug
invalidation never has to touch the L2 at all; stale objects age out.

Trust model: the store is a cache on local disk, not a source of truth.
Every read hashes the bytes and compares against the digest name before
anything can serve or promote to L1 — a corrupt or truncated entry is
deleted and reported so the caller refills from the origin tree (or a
peer), never served.

Thread model: fills and spills run on ``asyncio.to_thread`` workers
while stats are read from the event loop, so the index is lock-guarded.
File reads/writes happen OUTSIDE the lock (only index bookkeeping is
serialized); the worst interleaving is two threads verifying the same
digest twice, which is idempotent.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from collections import OrderedDict
from pathlib import Path
from typing import Callable

log = logging.getLogger("vlog.delivery.l2")

__all__ = ["DiskL2"]

# sha256 hex: the only filenames the store creates or trusts on rescan.
_DIGEST_LEN = 64
_TMP_PREFIX = "tmp-"


def _is_digest(name: str) -> bool:
    if len(name) != _DIGEST_LEN:
        return False
    try:
        int(name, 16)
    except ValueError:
        return False
    return True


class DiskL2:
    """Byte-bounded digest-named disk store with LRU eviction.

    Optionally popularity-aware: callers may stamp each ``put`` with the
    owning slug's *heat* (the plane's exponentially-decayed per-slug
    request rate). With ``admit_heat`` set, bodies below the threshold
    bypass the spill entirely (a one-hit-wonder should not push a
    herd-warmed segment off disk); with ``hot_heat`` set, the eviction
    sweep gives entries at or above it a bounded second chance — their
    heat halves and they move to the MRU end, so colder bytes go first.
    Both default to 0 (off): pure LRU, the pre-fabric behavior.
    """

    def __init__(self, root: str | Path, max_bytes: int, *,
                 on_evict: Callable[[int], None] | None = None,
                 on_rescue: Callable[[int], None] | None = None,
                 admit_heat: float = 0.0,
                 hot_heat: float = 0.0) -> None:
        self.root = Path(root)
        self.max_bytes = int(max_bytes)
        self._on_evict = on_evict
        self._on_rescue = on_rescue
        self.admit_heat = float(admit_heat)
        self.hot_heat = float(hot_heat)
        self._lock = threading.Lock()             # lock-order: 54
        # guarded-by: _lock
        self._index: OrderedDict[str, int] = OrderedDict()  # digest -> size
        # guarded-by: _lock
        self._heat: dict[str, float] = {}   # digest -> heat at last put
        # guarded-by: _lock
        self._bytes = 0
        # guarded-by: _lock
        self.counters = {
            "hits": 0, "misses": 0, "corrupt": 0,
            "stores": 0, "evictions": 0,
            "rescues": 0, "admit_skips": 0,
        }
        if self.enabled:
            self.root.mkdir(parents=True, exist_ok=True)
            self._rescan()

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    # -- init-time rescan --------------------------------------------------

    def _rescan(self) -> None:
        """Rebuild the index from disk so the warm set survives process
        restarts. Ordered oldest-mtime-first (approximate recency: mtimes
        mirror the origin segment, not last access), then trimmed to
        budget. Stray temp files from a crashed writer are swept."""
        found: list[tuple[float, str, int]] = []
        for shard in sorted(self.root.iterdir()) if self.root.is_dir() else []:
            if not shard.is_dir():
                if shard.name.startswith(_TMP_PREFIX):
                    shard.unlink(missing_ok=True)
                continue
            for f in shard.iterdir():
                if f.name.startswith(_TMP_PREFIX):
                    f.unlink(missing_ok=True)
                    continue
                if not _is_digest(f.name):
                    continue
                try:
                    st = f.stat()
                except OSError:
                    continue
                found.append((st.st_mtime, f.name, st.st_size))
        found.sort()
        with self._lock:
            for _, digest, size in found:
                self._index[digest] = size
                self._bytes += size
            victims = self._evict_over_budget_locked()
        self._unlink_all(victims)

    # -- core --------------------------------------------------------------

    def path_for(self, digest: str) -> Path:
        return self.root / digest[:2] / digest

    def read(self, digest: str) -> tuple[str, bytes | None, float]:
        """``(outcome, body, mtime)`` — outcome one of hit/miss/corrupt.

        A hit returns the verified bytes plus the stored mtime (the
        origin segment's, preserved at store time so Last-Modified is
        identical whichever tier serves). corrupt means the bytes were
        there but failed the digest check; the entry has already been
        deleted and the caller must refill from origin.
        """
        if not self.enabled:
            return "miss", None, 0.0
        with self._lock:
            if digest in self._index:
                self._index.move_to_end(digest)
                known = True
            else:
                known = False
        path = self.path_for(digest)
        if not known:
            self._bump("misses")
            return "miss", None, 0.0
        try:
            st = path.stat()
            body = path.read_bytes()
        except OSError:
            # indexed but unreadable (crash residue, external wipe)
            self._drop(digest)
            self._bump("misses")
            return "miss", None, 0.0
        if hashlib.sha256(body).hexdigest() != digest:
            log.warning("l2 entry %s failed digest check (%d bytes); "
                        "deleting", digest[:12], len(body))
            self._drop(digest)
            path.unlink(missing_ok=True)
            self._bump("corrupt")
            return "corrupt", None, 0.0
        self._bump("hits")
        return "hit", body, st.st_mtime

    def put(self, digest: str, body: bytes, mtime: float, *,
            heat: float = 0.0) -> bool:
        """Store verified bytes under their digest; no-op when already
        present, when the object alone exceeds the byte budget, or when
        ``admit_heat`` is set and the slug's heat falls below it.
        Atomic: temp write + rename, so readers never see a torn file."""
        if not self.enabled or len(body) > self.max_bytes:
            return False
        if self.admit_heat > 0.0 and heat < self.admit_heat:
            self._bump("admit_skips")
            return False
        with self._lock:
            if digest in self._index:
                self._index.move_to_end(digest)
                self._heat[digest] = max(self._heat.get(digest, 0.0), heat)
                return False
        path = self.path_for(digest)
        tmp = path.parent / f"{_TMP_PREFIX}{digest[:16]}-{os.getpid()}"
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp.write_bytes(body)
            # carry the origin segment's mtime so Last-Modified (and the
            # If-Range date match) is identical across L1/L2/sendfile
            os.utime(tmp, (mtime, mtime))
            os.replace(tmp, path)
        except OSError as exc:
            log.warning("l2 store failed for %s: %s", digest[:12], exc)
            tmp.unlink(missing_ok=True)
            return False
        with self._lock:
            if digest in self._index:       # racing writer beat us
                self._index.move_to_end(digest)
                self._heat[digest] = max(self._heat.get(digest, 0.0), heat)
                return False
            self._index[digest] = len(body)
            self._heat[digest] = heat
            self._bytes += len(body)
            self.counters["stores"] += 1
            victims = self._evict_over_budget_locked()
        self._unlink_all(victims)
        return True

    def _evict_over_budget_locked(self) -> list[str]:
        """LRU-evict index entries until under budget; returns the digests
        whose files the caller must unlink (outside the lock).

        With ``hot_heat`` set, an LRU-front entry at or above it gets a
        second chance instead: its heat halves and it moves to the MRU
        end. Rescues are bounded to one per entry per sweep (and the
        halving converges regardless), so the sweep always terminates.
        """
        victims: list[str] = []
        rescues_left = len(self._index) if self.hot_heat > 0.0 else 0
        while self._bytes > self.max_bytes and self._index:
            digest, size = self._index.popitem(last=False)
            heat = self._heat.get(digest, 0.0)
            if rescues_left > 0 and heat >= self.hot_heat:
                rescues_left -= 1
                self._heat[digest] = heat / 2.0
                self._index[digest] = size      # reinsert at MRU end
                self.counters["rescues"] += 1
                if self._on_rescue is not None:
                    self._on_rescue(1)
                continue
            self._heat.pop(digest, None)
            self._bytes -= size
            self.counters["evictions"] += 1
            victims.append(digest)
        return victims

    def _unlink_all(self, digests: list[str]) -> None:
        for digest in digests:
            self.path_for(digest).unlink(missing_ok=True)
            if self._on_evict is not None:
                self._on_evict(1)

    def _drop(self, digest: str) -> None:
        with self._lock:
            size = self._index.pop(digest, None)
            self._heat.pop(digest, None)
            if size is not None:
                self._bytes -= size

    def _bump(self, key: str) -> None:
        with self._lock:
            self.counters[key] += 1

    def clear(self) -> int:
        """Drop every entry (admin invalidate-all); returns count.
        Not counted as evictions — a clear is an operator action, not
        budget pressure."""
        with self._lock:
            victims = list(self._index)
            self._index.clear()
            self._heat.clear()
            self._bytes = 0
        for digest in victims:
            self.path_for(digest).unlink(missing_ok=True)
        return len(victims)

    def stats(self) -> dict:
        with self._lock:
            return {
                "bytes": self._bytes,
                "budget_bytes": self.max_bytes,
                "entries": len(self._index),
                **self.counters,
            }
