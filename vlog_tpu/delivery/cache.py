"""Byte-bounded LRU segment cache + single-flight miss collapsing.

The data structures under the delivery plane (delivery/plane.py). Both
are event-loop-confined: the public API process owns one instance of
each and every touch happens on its loop, so there is no locking —
what bounds concurrency is the admission semaphore in the plane, not
these containers.

- :class:`SegmentCache` — an ``OrderedDict`` LRU over
  :class:`CacheEntry` values, bounded by TOTAL BODY BYTES (not entry
  count — a 2160p init segment and a 96-byte VTT cue are not the same
  cost). Lookup is by ``(slug, rel)``; the content *version*
  (manifest sha256, or mtime when no manifest covers the file) lives on
  the entry and becomes its ETag, so a republished tree yields a new
  ETag the moment the old entry is invalidated or expires.
- :class:`SingleFlight` — collapses N concurrent misses for one key
  onto a single fill: the first caller starts the factory in a
  detached task, everyone (including that caller) awaits it shielded,
  so a disconnecting client cancels only its own wait, never the
  shared fill. A failed fill propagates the error to every waiter and
  leaves NOTHING behind — the next request simply starts a new fill,
  so transient read errors cannot poison a key.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict
from typing import Awaitable, Callable

Key = tuple[str, str]          # (slug, rel)


class CacheEntry:
    """One cached media object: body bytes + the response metadata."""

    __slots__ = ("slug", "rel", "version", "body", "etag", "mime",
                 "mtime", "immutable", "expires_at", "digest")

    def __init__(self, *, slug: str, rel: str, version: str, body: bytes,
                 etag: str, mime: str, mtime: float, immutable: bool,
                 expires_at: float | None = None,
                 digest: str | None = None):
        self.slug = slug
        self.rel = rel
        self.version = version      # manifest sha256 or mtime-ns tag
        self.body = body
        self.etag = etag            # strong ETag, quotes included
        self.mime = mime
        self.mtime = mtime          # seconds; Last-Modified / If-Range
        self.immutable = immutable  # segments: yes; .m3u8/.mpd: no
        self.expires_at = expires_at  # monotonic deadline; None = pinned
        self.digest = digest        # manifest sha256 when covered: the
        #                             L2 spill key; None = L2-ineligible

    @property
    def size(self) -> int:
        return len(self.body)

    def fresh(self, now: float) -> bool:
        return self.expires_at is None or now < self.expires_at


class FileEntry:
    """A file-backed media object served zero-copy via ``os.sendfile``:
    the ``> VLOG_DELIVERY_MAX_ENTRY_BYTES`` bypass and L2 hits at or
    above ``VLOG_DELIVERY_SENDFILE_BYTES``. Carries the same response
    metadata (validators included) as :class:`CacheEntry` but no body —
    it is never retained in the RAM LRU, and ``delivery/http.py`` builds
    its 200/206 from the file instead of a buffer."""

    __slots__ = ("slug", "rel", "path", "size", "etag", "mime", "mtime",
                 "immutable", "digest")

    def __init__(self, *, slug: str, rel: str, path, size: int, etag: str,
                 mime: str, mtime: float, immutable: bool,
                 digest: str | None = None):
        self.slug = slug
        self.rel = rel
        self.path = path
        self.size = size
        self.etag = etag
        self.mime = mime
        self.mtime = mtime
        self.immutable = immutable
        self.digest = digest


class SegmentCache:
    """LRU over ``(slug, rel)`` bounded by total body bytes."""

    def __init__(self, max_bytes: int, *,
                 on_evict: Callable[[CacheEntry], None] | None = None):
        self.max_bytes = max_bytes
        self._entries: OrderedDict[Key, CacheEntry] = OrderedDict()
        self._bytes = 0
        self._on_evict = on_evict
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expirations = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def bytes_cached(self) -> int:
        return self._bytes

    def get(self, key: Key, *, now: float | None = None) -> CacheEntry | None:
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        if not entry.fresh(time.monotonic() if now is None else now):
            self._drop(key)
            self.expirations += 1
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return entry

    def put(self, entry: CacheEntry) -> bool:
        """Insert (replacing any same-key entry), evicting LRU entries
        until the budget holds. Returns False — and caches nothing —
        when the body alone exceeds the whole budget."""
        if self.max_bytes <= 0 or entry.size > self.max_bytes:
            return False
        key = (entry.slug, entry.rel)
        self._drop(key)
        self._entries[key] = entry
        self._bytes += entry.size
        while self._bytes > self.max_bytes and self._entries:
            _, victim = self._entries.popitem(last=False)
            self._bytes -= victim.size
            self.evictions += 1
            if self._on_evict is not None:
                # the whole entry, not just its size: the delivery
                # plane's hook spills digest-covered victims to the L2
                self._on_evict(victim)
        return True

    def invalidate_slug(self, slug: str) -> int:
        """Drop every entry under one slug; returns entries dropped."""
        doomed = [k for k in self._entries if k[0] == slug]
        for k in doomed:
            self._drop(k)
        return len(doomed)

    def clear(self) -> int:
        n = len(self._entries)
        self._entries.clear()
        self._bytes = 0
        return n

    def _drop(self, key: Key) -> None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self._bytes -= entry.size


class SingleFlight:
    """Collapse concurrent async fills of one key onto a single run."""

    def __init__(self, *, on_collapse: Callable[[], None] | None = None):
        self._inflight: dict[Key, asyncio.Task] = {}
        self._on_collapse = on_collapse
        self.collapses = 0      # followers who rode a leader's fill

    def inflight(self) -> int:
        return len(self._inflight)

    def pending(self, key: Key) -> bool:
        """Whether a fill for ``key`` is in flight right now — a caller
        about to ``run`` this key would collapse onto it."""
        return key in self._inflight

    async def run(self, key: Key, factory: Callable[[], Awaitable]):
        task = self._inflight.get(key)
        if task is not None:
            self.collapses += 1
            if self._on_collapse is not None:
                self._on_collapse()
        else:
            # The fill runs in its OWN task, not inline in the leader's
            # handler: a leader whose client disconnects gets cancelled
            # by aiohttp, and an inline fill would propagate that
            # CancelledError to every follower still connected.
            task = asyncio.get_running_loop().create_task(
                factory(), name="vlog-cache-fill")
            task.add_done_callback(self._retire(key))
            self._inflight[key] = task
        # shield: cancelling one waiter must not cancel the shared fill
        return await asyncio.shield(task)

    def _retire(self, key: Key) -> Callable[[asyncio.Task], None]:
        def done(task: asyncio.Task) -> None:
            self._inflight.pop(key, None)
            if not task.cancelled():
                task.exception()    # mark retrieved: all-waiters-gone case
        return done
