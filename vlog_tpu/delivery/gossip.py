"""Gossip/health membership for the self-healing delivery fabric.

The peer ring (delivery/ring.py) used to be frozen at construction from
``VLOG_DELIVERY_PEERS``: one dead origin kept absorbing every miss
routed to it until an operator edited an env list and bounced the
fleet. This module makes membership a *live* state machine:

- every origin runs a jittered probe loop (:func:`probe_loop`) that
  heartbeats its peers over ``GET /api/delivery/gossip`` — the same
  public app that serves media, so "the heartbeat answers" and "the
  origin can serve" are one fact;
- each peer walks ``alive -> suspect -> down -> (rejoin) alive``:
  ``VLOG_DELIVERY_GOSSIP_SUSPECT_AFTER`` consecutive transport
  failures mark it suspect (fills route around it immediately), a
  suspect that stays unreachable for ``VLOG_DELIVERY_GOSSIP_DOWN_S``
  goes down (ownership rebalances), and one successful heartbeat
  rejoins it;
- a **digest liar** — a peer that served bytes failing the manifest
  sha256 check — is *quarantined*, not merely cooled down: it leaves
  the ownership set for ``VLOG_DELIVERY_GOSSIP_QUARANTINE_S`` and only
  a successful probe after that window readmits it;
- views are **versioned**: any change to the ownership set (down,
  quarantine, rejoin, join) bumps :attr:`Membership.version`, and the
  delivery plane rebuilds its rendezvous ring from the live member set
  at the next consult — rendezvous hashing guarantees only the dead
  member's keys move;
- probe responses piggyback the sender's own view
  (:meth:`Membership.merge`): remote *suspicion* spreads (a peer the
  whole fleet can't reach is routed around fleet-wide within one
  probe round), but remote views can only make a local peer
  **suspect** — death is always confirmed by local probes, so a
  forged heartbeat (``delivery.gossip`` armed with forge semantics in
  chaos tests) cannot kill a peer this origin can still reach. Views
  may also carry peers the seed list never knew: they join as alive,
  so the fabric grows without a fleet-wide env edit.

Thread model: the state machine is consulted from event-loop
coroutines (probes, peer-fill classification) and from ``to_thread``
fill workers (ring snapshot reads), so every touch happens under one
lock (rank 48 — below the plane's digest/counter locks; nothing else
is ever acquired while it is held).
"""

from __future__ import annotations

import random
import threading
import time

from vlog_tpu.delivery.ring import Ring
from vlog_tpu.utils import failpoints

__all__ = ["Membership", "PeerView", "probe_once", "probe_loop",
           "GOSSIP_FROM_HEADER", "ALIVE", "SUSPECT", "DOWN", "QUARANTINED"]

ALIVE = "alive"
SUSPECT = "suspect"
DOWN = "down"
QUARANTINED = "quarantined"

# A probe carries its sender's identity so one heartbeat proves
# liveness in BOTH directions (the receiver marks the sender alive
# without waiting for its own next probe round).
GOSSIP_FROM_HEADER = "X-Vlog-Gossip-From"

# States that keep a peer in the rendezvous ownership set. A suspect
# peer still OWNS its keys (so a one-probe blip does not churn the
# ring) but fills route around it until it answers again.
_MEMBER_STATES = frozenset({ALIVE, SUSPECT})


class PeerView:
    """Health record for one remote peer."""

    __slots__ = ("url", "state", "fails", "since", "last_ok")

    def __init__(self, url: str):
        self.url = url
        self.state = ALIVE
        self.fails = 0          # consecutive transport/timeout failures
        self.since = time.monotonic()   # when `state` was entered
        self.last_ok = 0.0      # monotonic of last confirmed contact

    def as_dict(self, now: float) -> dict:
        return {
            "url": self.url,
            "state": self.state,
            "fails": self.fails,
            "state_age_s": round(now - self.since, 3),
            "last_ok_age_s": (round(now - self.last_ok, 3)
                              if self.last_ok else None),
        }


class Membership:
    """Versioned, self-healing view of the delivery origin set.

    Seeded from ``VLOG_DELIVERY_PEERS`` but never frozen by it: peers
    die, rejoin, and join (via gossiped views) at runtime. Every
    method is safe from any thread; none performs I/O.
    """

    def __init__(self, peers, self_url: str = "", *,
                 suspect_after: int = 2,
                 down_after_s: float = 3.0,
                 quarantine_s: float = 60.0):
        self.self_url = self_url.strip().rstrip("/")
        self.suspect_after = max(1, int(suspect_after))
        self.down_after_s = float(down_after_s)
        self.quarantine_s = float(quarantine_s)
        self._lock = threading.Lock()             # lock-order: 48
        # guarded-by: _lock
        self._peers: dict[str, PeerView] = {}
        # guarded-by: _lock
        self._version = 0
        # guarded-by: _lock
        self._ring: Ring | None = None      # cached view for _version
        for u in peers:
            u = u.strip().rstrip("/")
            if u and u != self.self_url and u not in self._peers:
                self._peers[u] = PeerView(u)

    # -- read side ---------------------------------------------------------

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    @property
    def enabled(self) -> bool:
        """Whether there is any remote peer to gossip with at all."""
        with self._lock:
            return bool(self._peers)

    def state_of(self, peer: str) -> str | None:
        with self._lock:
            pv = self._peers.get(peer)
            return pv.state if pv is not None else None

    def routable(self, peer: str) -> bool:
        """May a fill be sent to ``peer`` right now? Only fully alive
        peers take fills — suspects are routed around immediately
        (that is the 'within one suspect window' guarantee)."""
        with self._lock:
            pv = self._peers.get(peer)
            return pv is not None and pv.state == ALIVE

    def members(self) -> tuple[str, ...]:
        """The rendezvous ownership set: self + every peer not down or
        quarantined, in sorted order (deterministic across origins)."""
        with self._lock:
            live = [u for u, pv in self._peers.items()
                    if pv.state in _MEMBER_STATES]
        if self.self_url:
            live.append(self.self_url)
        return tuple(sorted(set(live)))

    def ring(self) -> Ring:
        """The current versioned rendezvous ring (cached per version)."""
        with self._lock:
            ring = self._ring
            version = self._version
        if ring is not None and ring.version == version:
            return ring
        ring = Ring(self.members(), self.self_url, version=version)
        with self._lock:
            # a racing rebuild for the same version stores the same view
            if self._version == version:
                self._ring = ring
        return ring

    def known_peers(self) -> tuple[str, ...]:
        """Every peer the fabric has ever seen (any state) — the probe
        target list. Down peers stay here so rejoin is detectable."""
        with self._lock:
            return tuple(self._peers)

    def snapshot(self) -> dict:
        """Wire/admin view: what ``GET /api/delivery/gossip`` serves."""
        now = time.monotonic()
        with self._lock:
            peers = [pv.as_dict(now) for pv in self._peers.values()]
            version = self._version
        return {"version": version, "self": self.self_url or None,
                "peers": peers}

    # -- transitions -------------------------------------------------------

    def _bump_locked(self) -> None:
        self._version += 1
        self._ring = None

    def record_failure(self, peer: str) -> str | None:
        """One transport/timeout failure against ``peer`` (probe or
        fill). Returns the peer's state after the transition. Status
        and digest failures must NOT land here — a 503 shed or a
        digest liar is not evidence the process is unreachable."""
        now = time.monotonic()
        with self._lock:
            pv = self._peers.get(peer)
            if pv is None:
                return None
            pv.fails += 1
            if pv.state == ALIVE and pv.fails >= self.suspect_after:
                pv.state, pv.since = SUSPECT, now
            elif pv.state == SUSPECT \
                    and now - pv.since >= self.down_after_s:
                pv.state, pv.since = DOWN, now
                self._bump_locked()
            return pv.state

    def record_success(self, peer: str) -> str | None:
        """Confirmed contact with ``peer`` (probe answered, fill
        served + verified). Rejoins down peers; a quarantined peer
        stays out until its window has elapsed."""
        now = time.monotonic()
        with self._lock:
            pv = self._peers.get(peer)
            if pv is None:
                if not peer or peer == self.self_url:
                    return None
                pv = self._peers[peer] = PeerView(peer)   # join
                pv.last_ok = now
                self._bump_locked()
                return pv.state
            if pv.state == QUARANTINED \
                    and now - pv.since < self.quarantine_s:
                return pv.state     # still serving its sentence
            was_member = pv.state in _MEMBER_STATES
            pv.fails = 0
            pv.last_ok = now
            if pv.state != ALIVE:
                pv.state, pv.since = ALIVE, now
                if not was_member:
                    self._bump_locked()     # rejoin: ownership returns
            return pv.state

    def heard_from(self, peer: str) -> None:
        """An inbound probe FROM ``peer`` proves it is alive — same
        evidence as our own probe succeeding (and how a never-seeded
        origin joins the fabric)."""
        self.record_success(peer)

    def quarantine(self, peer: str) -> None:
        """``peer`` served bytes that failed digest verification: it
        leaves the ownership set for ``quarantine_s`` regardless of
        reachability. Liveness is not trustworthiness."""
        now = time.monotonic()
        with self._lock:
            pv = self._peers.get(peer)
            if pv is None:
                return
            if pv.state != QUARANTINED:
                was_member = pv.state in _MEMBER_STATES
                pv.state, pv.since = QUARANTINED, now
                if was_member:
                    self._bump_locked()

    def tick(self) -> None:
        """Clock-driven transitions (called each probe round): a
        suspect that has stayed silent past the down window goes down
        even if nothing new failed in between."""
        now = time.monotonic()
        with self._lock:
            for pv in self._peers.values():
                if pv.state == SUSPECT \
                        and now - pv.since >= self.down_after_s:
                    pv.state, pv.since = DOWN, now
                    self._bump_locked()

    def merge(self, view: dict) -> None:
        """Fold a gossiped remote view in. Remote *suspicion* spreads
        (alive-here peers the sender cannot reach become suspect here,
        unless we have fresh first-hand contact); remote DOWN is still
        only suspicion here — death is confirmed by local probes.
        Unknown peers in the view join as alive."""
        peers = view.get("peers")
        if not isinstance(peers, list):
            return
        now = time.monotonic()
        with self._lock:
            for rec in peers:
                if not isinstance(rec, dict):
                    continue
                url = str(rec.get("url", "")).strip().rstrip("/")
                state = rec.get("state")
                if not url or url == self.self_url:
                    continue
                pv = self._peers.get(url)
                if pv is None:
                    if state in _MEMBER_STATES:
                        self._peers[url] = PeerView(url)    # join
                        self._bump_locked()
                    continue
                if state in (SUSPECT, DOWN) and pv.state == ALIVE \
                        and now - pv.last_ok >= self.down_after_s:
                    pv.state, pv.since = SUSPECT, now


# --------------------------------------------------------------------------
# The probe side: one jittered heartbeat round + the long-running loop.
# Network I/O lives here (event loop, aiohttp); Membership stays pure.
# --------------------------------------------------------------------------

async def probe_once(membership: Membership, session, *,
                     timeout_s: float = 1.0, on_outcome=None) -> int:
    """One heartbeat round: probe every known peer, merge what comes
    back, run clock transitions. Returns how many peers answered.
    ``on_outcome(outcome)`` (ok/fail/drop) feeds the metrics plane
    without importing it here."""
    import aiohttp

    answered = 0
    for peer in membership.known_peers():
        try:
            failpoints.hit("delivery.gossip")
        except failpoints.FailpointError:
            # the heartbeat is dropped on the floor before any network
            # I/O; silence is indistinguishable from death, so the
            # round still counts as a failed contact
            membership.record_failure(peer)
            if on_outcome is not None:
                on_outcome("drop")
            continue
        try:
            async with session.get(
                    f"{peer}/api/delivery/gossip",
                    headers=({GOSSIP_FROM_HEADER: membership.self_url}
                             if membership.self_url else {}),
                    timeout=aiohttp.ClientTimeout(total=timeout_s),
            ) as resp:
                if resp.status != 200:
                    raise OSError(f"gossip probe answered {resp.status}")
                view = await resp.json()
        except Exception:  # noqa: BLE001 — any failure is suspicion
            membership.record_failure(peer)
            if on_outcome is not None:
                on_outcome("fail")
            continue
        answered += 1
        membership.record_success(peer)
        if isinstance(view, dict):
            membership.merge(view)
        if on_outcome is not None:
            on_outcome("ok")
    membership.tick()
    return answered


async def probe_loop(membership: Membership, session_factory, *,
                     interval_s: float, jitter: float = 0.25,
                     on_outcome=None) -> None:
    """Run :func:`probe_once` forever on a bounded jittered cadence.

    Jitter desynchronizes the fleet (N origins probing in lockstep
    would make every suspect window start at once); the interval is
    the *mean*, bounded to ``[interval*(1-jitter), interval*(1+jitter)]``.
    Cancelled by ``DeliveryPlane.close()``.
    """
    import asyncio

    jitter = min(max(jitter, 0.0), 0.9)
    rng = random.Random(hash(membership.self_url) & 0xFFFF)
    timeout_s = min(max(interval_s, 0.2), 2.0)
    while True:
        await asyncio.sleep(interval_s * (1.0 + rng.uniform(-jitter,
                                                            jitter)))
        await probe_once(membership, session_factory(),
                         timeout_s=timeout_s, on_outcome=on_outcome)
