"""Rendezvous-hash ring over delivery origin peers.

Ownership routing for the distributed delivery tier: every origin
process hashes each object key against the configured peer list and the
highest score wins (highest-random-weight / rendezvous hashing, Thaler &
Ravishankar 1998). Unlike a ring of virtual nodes, HRW needs no state
beyond the member list, gives minimal disruption when a peer joins or
leaves (only the keys whose argmax moves), and every member computes the
same answer independently — no coordination plane involved.

The ring is immutable after construction and every method is pure, so
it is safe to consult from the event loop and from ``to_thread`` fill
workers without locking.
"""

from __future__ import annotations

import hashlib
from typing import Sequence

__all__ = ["Ring"]


def _score(peer: str, key: str) -> int:
    """HRW weight of ``peer`` for ``key``: big-endian sha256 of the pair.

    sha256 (vs a faster non-crypto hash) keeps the scores unarguably
    uniform and the implementation dependency-free; at one hash per
    peer per cache MISS the cost is noise next to the disk read the
    miss is about to do.
    """
    h = hashlib.sha256(f"{peer}|{key}".encode("utf-8", "surrogatepass"))
    return int.from_bytes(h.digest()[:16], "big")


class Ring:
    """The peer set plus this process's own identity within it.

    ``peers`` are base URLs (``http://host:port``); trailing slashes and
    duplicates are dropped so the hash is insensitive to spelling.
    ``self_url`` names which peer is *us* — empty means this process
    owns nothing and treats every keyed object as remotely owned.

    ``version`` stamps which membership view this ring was built from
    (gossip bumps its view version on every ownership change; the
    delivery plane rebuilds the ring only when the stamps diverge). A
    ring constructed outside the gossip plane keeps version 0 and is
    never rebuilt from under its owner.
    """

    __slots__ = ("peers", "self_url", "version")

    def __init__(self, peers: Sequence[str], self_url: str = "", *,
                 version: int = 0) -> None:
        cleaned = []
        for u in peers:
            u = u.strip().rstrip("/")
            if u and u not in cleaned:
                cleaned.append(u)
        self.peers: tuple[str, ...] = tuple(cleaned)
        self.self_url: str = self_url.strip().rstrip("/")
        self.version: int = int(version)

    @property
    def enabled(self) -> bool:
        """Peer-fill is meaningful only with at least two members (a
        one-member ring always resolves to local fill)."""
        return len(self.peers) >= 2 or (
            len(self.peers) == 1 and self.peers[0] != self.self_url)

    def owner(self, key: str) -> str | None:
        """The peer that owns ``key``, or None for an empty ring."""
        if not self.peers:
            return None
        return max(self.peers, key=lambda p: _score(p, key))

    def ranked(self, key: str) -> tuple[str, ...]:
        """All peers in descending HRW preference for ``key``: the
        owner first, then the hedge candidates in the order a fill
        should fall through them. Pure, like every other consult."""
        return tuple(sorted(self.peers,
                            key=lambda p: _score(p, key), reverse=True))

    def is_local(self, key: str) -> bool:
        """True when this process should fill ``key`` from its own disk
        (empty ring, or we are the rendezvous owner)."""
        own = self.owner(key)
        return own is None or own == self.self_url

    def membership(self) -> dict:
        """Admin-facing view of the ring: members + our identity."""
        return {
            "peers": list(self.peers),
            "self": self.self_url or None,
            "enabled": self.enabled,
        }
