"""Delivery plane: origin segment cache, single-flight, admission,
publish-keyed invalidation, plus the distributed tier — disk-backed L2,
consistent-hash peer fill, publish-time prewarm, zero-copy large-object
serving (see delivery/plane.py for the design note).

Import surface for the rest of the codebase:

- :class:`DeliveryPlane` — one per serving process (public API).
- :func:`invalidate_slug` / :func:`invalidate_all` — called by the
  publish/re-encode/delete/verify paths and the admin endpoint; fan out
  to every plane registered in this process.
- :func:`prewarm_slug` — publish-time prewarm fan-out (finalize_ready).
- :func:`stats_snapshot` — the admin stats panel's data source.
"""

from vlog_tpu.delivery.cache import (
    CacheEntry,
    FileEntry,
    SegmentCache,
    SingleFlight,
)
from vlog_tpu.delivery.gossip import (
    GOSSIP_FROM_HEADER,
    Membership,
    probe_loop,
    probe_once,
)
from vlog_tpu.delivery.l2 import DiskL2
from vlog_tpu.delivery.plane import (
    FILL_TOKEN_HEADER,
    PEER_FILL_HEADER,
    DeliveryPlane,
    LoadShedError,
    MediaEscapeError,
    PeerFillError,
    ServingState,
    has_planes,
    invalidate_all,
    invalidate_slug,
    prewarm_slug,
    register,
    stats_snapshot,
)
from vlog_tpu.delivery.ring import Ring

__all__ = [
    "CacheEntry",
    "DeliveryPlane",
    "DiskL2",
    "FILL_TOKEN_HEADER",
    "FileEntry",
    "GOSSIP_FROM_HEADER",
    "LoadShedError",
    "MediaEscapeError",
    "Membership",
    "PEER_FILL_HEADER",
    "PeerFillError",
    "Ring",
    "SegmentCache",
    "ServingState",
    "SingleFlight",
    "has_planes",
    "invalidate_all",
    "invalidate_slug",
    "prewarm_slug",
    "probe_loop",
    "probe_once",
    "register",
    "stats_snapshot",
]
