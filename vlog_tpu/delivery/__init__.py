"""Delivery plane: origin segment cache, single-flight, admission,
publish-keyed invalidation (see delivery/plane.py for the design note).

Import surface for the rest of the codebase:

- :class:`DeliveryPlane` — one per serving process (public API).
- :func:`invalidate_slug` / :func:`invalidate_all` — called by the
  publish/re-encode/delete/verify paths and the admin endpoint; fan out
  to every plane registered in this process.
- :func:`stats_snapshot` — the admin stats panel's data source.
"""

from vlog_tpu.delivery.cache import CacheEntry, SegmentCache, SingleFlight
from vlog_tpu.delivery.plane import (
    BypassFile,
    DeliveryPlane,
    LoadShedError,
    MediaEscapeError,
    ServingState,
    has_planes,
    invalidate_all,
    invalidate_slug,
    register,
    stats_snapshot,
)

__all__ = [
    "BypassFile",
    "CacheEntry",
    "DeliveryPlane",
    "LoadShedError",
    "MediaEscapeError",
    "SegmentCache",
    "ServingState",
    "SingleFlight",
    "has_planes",
    "invalidate_all",
    "invalidate_slug",
    "register",
    "stats_snapshot",
]
