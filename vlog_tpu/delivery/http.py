"""HTTP semantics for cached media: conditional, range, CORS, HEAD.

One response builder serves BOTH the cached and the uncached path — a
cache-off deployment (``VLOG_DELIVERY_CACHE_BYTES=0``) still builds its
responses from the same :class:`~vlog_tpu.delivery.cache.CacheEntry`
the fill produced, it just doesn't retain the entry. That is what makes
"cached responses are byte-identical to uncached ones" a structural
property instead of a test hope.

Implemented subset (what MSE/hls players actually send):

- strong ETags (the manifest sha256 when the tree has one), handled for
  ``If-None-Match`` (list form, ``W/`` prefixes, ``*``) -> **304**;
  ``If-Modified-Since`` -> **304** for ETag-less revalidators
  (``If-None-Match`` takes precedence when both are present)
- single-range ``Range: bytes=a-b | a- | -n`` -> **206** with
  ``Content-Range``; syntactically-valid-but-unsatisfiable -> **416**;
  multi-range requests are answered with the full **200** body (allowed
  by RFC 9110 §14.2 — no media player sends them)
- ``If-Range`` with either an ETag or an HTTP-date validator; a failed
  validator serves the full 200 body (never a stale-ranged splice)
- HEAD mirrors every header including ``Content-Length`` with an empty
  body; OPTIONS answers CORS preflight so cross-origin players can
  probe segments (the reference relies on its CDN for this tier).
"""

from __future__ import annotations

from email.utils import formatdate, parsedate_to_datetime

from aiohttp import web

from vlog_tpu.delivery.cache import CacheEntry

# The reference subclasses StaticFiles for exactly this table
# (HLSStaticFiles, docs/ARCHITECTURE.md:59-62).
MEDIA_MIME = {
    ".m3u8": "application/vnd.apple.mpegurl",
    ".mpd": "application/dash+xml",
    ".m4s": "video/iso.segment",
    ".mp4": "video/mp4",
    ".ts": "video/mp2t",
    ".vtt": "text/vtt",
    ".jpg": "image/jpeg",
    ".jpeg": "image/jpeg",
    ".png": "image/png",
    ".y4m": "application/octet-stream",
    ".aac": "audio/aac",
}

# Mutable playlist suffixes: short-TTL cache entries, no-cache clients.
MUTABLE_SUFFIXES = (".m3u8", ".mpd")

CACHE_IMMUTABLE = "public, max-age=31536000, immutable"
CACHE_MUTABLE = "no-cache"

# Cross-origin playback surface: players fetch manifests/segments with
# Range and revalidation headers and must be able to READ the range /
# validator response headers, not just receive the bytes.
CORS_HEADERS = {
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Expose-Headers":
        "Content-Length, Content-Range, Accept-Ranges, ETag, Last-Modified",
}
PREFLIGHT_HEADERS = {
    **CORS_HEADERS,
    "Access-Control-Allow-Methods": "GET, HEAD, OPTIONS",
    "Access-Control-Allow-Headers":
        "Range, If-None-Match, If-Modified-Since, If-Range",
    "Access-Control-Max-Age": "86400",
}


def preflight_response() -> web.Response:
    """CORS preflight for the media routes (OPTIONS)."""
    return web.Response(status=204, headers=PREFLIGHT_HEADERS)


def cache_control(entry: CacheEntry) -> str:
    return CACHE_IMMUTABLE if entry.immutable else CACHE_MUTABLE


def etag_matches(header: str, etag: str) -> bool:
    """RFC 9110 If-None-Match: comma list, weak prefixes, ``*``."""
    if header.strip() == "*":
        return True
    for cand in header.split(","):
        cand = cand.strip()
        if cand.startswith("W/"):
            cand = cand[2:]
        if cand == etag:
            return True
    return False


class RangeNotSatisfiable(ValueError):
    """A syntactically valid bytes range outside the representation."""


def parse_range(header: str, size: int) -> tuple[int, int] | None:
    """``(start, end_inclusive)`` for a single satisfiable bytes range.

    None means "serve the full body": absent/other units, malformed
    syntax (RFC 9110 says ignore), or multi-range. Raises
    :class:`RangeNotSatisfiable` for well-formed ranges that miss the
    representation entirely (416 + ``Content-Range: bytes */size``).
    """
    if not header or not header.startswith("bytes="):
        return None
    spec = header[len("bytes="):].strip()
    if "," in spec:             # multi-range: legal to answer with 200
        return None
    start_s, dash, end_s = spec.partition("-")
    if not dash:
        return None
    start_s, end_s = start_s.strip(), end_s.strip()
    try:
        if not start_s:                     # suffix form: last N bytes
            n = int(end_s)
            if n <= 0:
                raise RangeNotSatisfiable(header)
            if size == 0:
                raise RangeNotSatisfiable(header)
            return max(0, size - n), size - 1
        start = int(start_s)
        if start >= size:
            raise RangeNotSatisfiable(header)
        end = int(end_s) if end_s else size - 1
    except ValueError as exc:
        if isinstance(exc, RangeNotSatisfiable):
            raise
        return None                         # malformed -> full body
    if end < start:
        return None
    return start, min(end, size - 1)


def _unmodified_since(header: str | None, entry: CacheEntry) -> bool:
    """If-Modified-Since -> 304 eligibility (ETag-less revalidators —
    the header the preflight invites clients to send)."""
    if header is None:
        return False
    try:
        cut = parsedate_to_datetime(header).timestamp()
    except (TypeError, ValueError):
        return False
    return int(entry.mtime) <= cut


def _if_range_allows(header: str | None, entry: CacheEntry) -> bool:
    """True when a Range header may be honored under this If-Range."""
    if header is None:
        return True
    header = header.strip()
    if header.startswith(('"', "W/")):
        # entity-tag form; weak tags never match for ranges (RFC 9110)
        return header == entry.etag
    try:
        cut = parsedate_to_datetime(header).timestamp()
    except (TypeError, ValueError):
        return False
    # RFC 9110 §13.1.5: the date must EXACTLY match the current
    # Last-Modified ("not earlier than"-style laxity would let a tree
    # restored with an older mtime splice ranges across two bodies).
    # Last-Modified granularity is whole seconds on the wire.
    return int(entry.mtime) == int(cut)


def entry_response(request: web.Request, entry: CacheEntry,
                   ) -> web.Response:
    """The full conditional/range state machine over a cached buffer."""
    base = {
        "Content-Type": entry.mime,
        "ETag": entry.etag,
        "Last-Modified": formatdate(entry.mtime, usegmt=True),
        "Accept-Ranges": "bytes",
        "Cache-Control": cache_control(entry),
        **CORS_HEADERS,
    }
    inm = request.headers.get("If-None-Match")
    if inm is not None and etag_matches(inm, entry.etag):
        not_modified = dict(base)
        not_modified.pop("Content-Type")    # 304 carries no payload head
        return web.Response(status=304, headers=not_modified)
    if inm is None and _unmodified_since(
            request.headers.get("If-Modified-Since"), entry):
        not_modified = dict(base)
        not_modified.pop("Content-Type")
        return web.Response(status=304, headers=not_modified)

    size = len(entry.body)
    rng = None
    # RFC 9110 §13.1.5: a non-matching If-Range means IGNORE the Range
    # header outright — including its 416 path, or a resume against a
    # republished-smaller body would 416 instead of getting the new 200.
    if _if_range_allows(request.headers.get("If-Range"), entry):
        try:
            rng = parse_range(request.headers.get("Range", ""), size)
        except RangeNotSatisfiable:
            return web.Response(
                status=416,
                headers={**base, "Content-Range": f"bytes */{size}"})

    if rng is None:
        status, body = 200, entry.body
    else:
        start, end = rng
        status, body = 206, entry.body[start:end + 1]
        base["Content-Range"] = f"bytes {start}-{end}/{size}"

    if request.method == "HEAD":
        # mirror the GET headers (Content-Length included) sans body
        base["Content-Length"] = str(len(body))
        return web.Response(status=status, headers=base)
    return web.Response(status=status, body=body, headers=base)
