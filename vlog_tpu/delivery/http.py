"""HTTP semantics for cached media: conditional, range, CORS, HEAD.

One response builder serves BOTH the cached and the uncached path — a
cache-off deployment (``VLOG_DELIVERY_CACHE_BYTES=0``) still builds its
responses from the same :class:`~vlog_tpu.delivery.cache.CacheEntry`
the fill produced, it just doesn't retain the entry. That is what makes
"cached responses are byte-identical to uncached ones" a structural
property instead of a test hope.

Implemented subset (what MSE/hls players actually send):

- strong ETags (the manifest sha256 when the tree has one), handled for
  ``If-None-Match`` (list form, ``W/`` prefixes, ``*``) -> **304**;
  ``If-Modified-Since`` -> **304** for ETag-less revalidators
  (``If-None-Match`` takes precedence when both are present)
- single-range ``Range: bytes=a-b | a- | -n`` -> **206** with
  ``Content-Range``; syntactically-valid-but-unsatisfiable -> **416**;
  multi-range requests are answered with the full **200** body (allowed
  by RFC 9110 §14.2 — no media player sends them)
- ``If-Range`` with either an ETag or an HTTP-date validator; a failed
  validator serves the full 200 body (never a stale-ranged splice)
- HEAD mirrors every header including ``Content-Length`` with an empty
  body; OPTIONS answers CORS preflight so cross-origin players can
  probe segments (the reference relies on its CDN for this tier).
"""

from __future__ import annotations

import asyncio
import os
import time
from email.utils import formatdate, parsedate_to_datetime
from stat import S_ISREG

from aiohttp import web

from vlog_tpu.delivery.cache import CacheEntry, FileEntry

Entry = CacheEntry | FileEntry

# The reference subclasses StaticFiles for exactly this table
# (HLSStaticFiles, docs/ARCHITECTURE.md:59-62).
MEDIA_MIME = {
    ".m3u8": "application/vnd.apple.mpegurl",
    ".mpd": "application/dash+xml",
    ".m4s": "video/iso.segment",
    ".mp4": "video/mp4",
    ".ts": "video/mp2t",
    ".vtt": "text/vtt",
    ".jpg": "image/jpeg",
    ".jpeg": "image/jpeg",
    ".png": "image/png",
    ".y4m": "application/octet-stream",
    ".aac": "audio/aac",
}

# Mutable playlist suffixes: short-TTL cache entries, no-cache clients.
MUTABLE_SUFFIXES = (".m3u8", ".mpd")

CACHE_IMMUTABLE = "public, max-age=31536000, immutable"
CACHE_MUTABLE = "no-cache"

# Cross-origin playback surface: players fetch manifests/segments with
# Range and revalidation headers and must be able to READ the range /
# validator response headers, not just receive the bytes.
CORS_HEADERS = {
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Expose-Headers":
        "Content-Length, Content-Range, Accept-Ranges, ETag, Last-Modified",
}
PREFLIGHT_HEADERS = {
    **CORS_HEADERS,
    "Access-Control-Allow-Methods": "GET, HEAD, OPTIONS",
    "Access-Control-Allow-Headers":
        "Range, If-None-Match, If-Modified-Since, If-Range",
    "Access-Control-Max-Age": "86400",
}


def preflight_response() -> web.Response:
    """CORS preflight for the media routes (OPTIONS)."""
    return web.Response(status=204, headers=PREFLIGHT_HEADERS)


def cache_control(entry: Entry) -> str:
    return CACHE_IMMUTABLE if entry.immutable else CACHE_MUTABLE


def etag_matches(header: str, etag: str) -> bool:
    """RFC 9110 If-None-Match: comma list, weak prefixes, ``*``."""
    if header.strip() == "*":
        return True
    for cand in header.split(","):
        cand = cand.strip()
        if cand.startswith("W/"):
            cand = cand[2:]
        if cand == etag:
            return True
    return False


class RangeNotSatisfiable(ValueError):
    """A syntactically valid bytes range outside the representation."""


def parse_range(header: str, size: int) -> tuple[int, int] | None:
    """``(start, end_inclusive)`` for a single satisfiable bytes range.

    None means "serve the full body": absent/other units, malformed
    syntax (RFC 9110 says ignore), or multi-range. Raises
    :class:`RangeNotSatisfiable` for well-formed ranges that miss the
    representation entirely (416 + ``Content-Range: bytes */size``).
    """
    if not header or not header.startswith("bytes="):
        return None
    spec = header[len("bytes="):].strip()
    if "," in spec:             # multi-range: legal to answer with 200
        return None
    start_s, dash, end_s = spec.partition("-")
    if not dash:
        return None
    start_s, end_s = start_s.strip(), end_s.strip()
    try:
        if not start_s:                     # suffix form: last N bytes
            n = int(end_s)
            if n <= 0:
                raise RangeNotSatisfiable(header)
            if size == 0:
                raise RangeNotSatisfiable(header)
            return max(0, size - n), size - 1
        start = int(start_s)
        if start >= size:
            raise RangeNotSatisfiable(header)
        end = int(end_s) if end_s else size - 1
    except ValueError as exc:
        if isinstance(exc, RangeNotSatisfiable):
            raise
        return None                         # malformed -> full body
    if end < start:
        return None
    return start, min(end, size - 1)


def _unmodified_since(header: str | None, entry: Entry) -> bool:
    """If-Modified-Since -> 304 eligibility (ETag-less revalidators —
    the header the preflight invites clients to send)."""
    if header is None:
        return False
    try:
        cut = parsedate_to_datetime(header).timestamp()
    except (TypeError, ValueError):
        return False
    return int(entry.mtime) <= cut


def parse_retry_after(value: str | None) -> float | None:
    """``Retry-After`` -> seconds, or None when absent/garbled.

    Accepts both RFC 9110 forms: delta-seconds and an HTTP-date (the
    date form converts to a from-now delta, floored at 0). The peer-fill
    path uses this to honor a shedding owner's own backoff hint as the
    cooldown instead of the flat configured one.
    """
    if not value:
        return None
    value = value.strip()
    try:
        return max(0.0, float(int(value)))
    except ValueError:
        pass
    try:
        return max(0.0, parsedate_to_datetime(value).timestamp()
                   - time.time())
    except (TypeError, ValueError):
        return None


def _if_range_allows(header: str | None, entry: Entry) -> bool:
    """True when a Range header may be honored under this If-Range."""
    if header is None:
        return True
    header = header.strip()
    if header.startswith(('"', "W/")):
        # entity-tag form; weak tags never match for ranges (RFC 9110)
        return header == entry.etag
    try:
        cut = parsedate_to_datetime(header).timestamp()
    except (TypeError, ValueError):
        return False
    # RFC 9110 §13.1.5: the date must EXACTLY match the current
    # Last-Modified ("not earlier than"-style laxity would let a tree
    # restored with an older mtime splice ranges across two bodies).
    # Last-Modified granularity is whole seconds on the wire.
    return int(entry.mtime) == int(cut)


def entry_response(request: web.Request, entry: Entry,
                   ) -> web.StreamResponse:
    """The full conditional/range state machine over a delivery entry.

    Buffered entries (:class:`CacheEntry`) answer from RAM; file-backed
    entries (:class:`FileEntry` — the large-object bypass and big L2
    hits) answer 200/206 zero-copy via :class:`SendfileResponse`. Both
    kinds flow through the SAME decision tree with the SAME validators
    (the entry's digest ETag and origin mtime, never a fresh ``stat``),
    so the four serve paths — L1, L2, peer, bypass — are byte- and
    header-identical by construction.
    """
    base = {
        "Content-Type": entry.mime,
        "ETag": entry.etag,
        "Last-Modified": formatdate(entry.mtime, usegmt=True),
        "Accept-Ranges": "bytes",
        "Cache-Control": cache_control(entry),
        **CORS_HEADERS,
    }
    inm = request.headers.get("If-None-Match")
    if inm is not None and etag_matches(inm, entry.etag):
        not_modified = dict(base)
        not_modified.pop("Content-Type")    # 304 carries no payload head
        return web.Response(status=304, headers=not_modified)
    if inm is None and _unmodified_since(
            request.headers.get("If-Modified-Since"), entry):
        not_modified = dict(base)
        not_modified.pop("Content-Type")
        return web.Response(status=304, headers=not_modified)

    size = entry.size
    rng = None
    # RFC 9110 §13.1.5: a non-matching If-Range means IGNORE the Range
    # header outright — including its 416 path, or a resume against a
    # republished-smaller body would 416 instead of getting the new 200.
    if _if_range_allows(request.headers.get("If-Range"), entry):
        try:
            rng = parse_range(request.headers.get("Range", ""), size)
        except RangeNotSatisfiable:
            return web.Response(
                status=416,
                headers={**base, "Content-Range": f"bytes */{size}"})

    if rng is None:
        status, start, length = 200, 0, size
    else:
        start, end = rng
        status, length = 206, end - start + 1
        base["Content-Range"] = f"bytes {start}-{end}/{size}"

    if request.method == "HEAD":
        # mirror the GET headers (Content-Length included) sans body —
        # answered from metadata for both kinds (no file open for HEAD)
        base["Content-Length"] = str(length)
        return web.Response(status=status, headers=base)
    if isinstance(entry, FileEntry):
        return SendfileResponse(entry.path, status=status, offset=start,
                                count=length, headers=base)
    body = entry.body if rng is None else entry.body[start:start + length]
    return web.Response(status=status, body=body, headers=base)


class SendfileResponse(web.FileResponse):
    """Zero-copy body transport, nothing else.

    Every conditional/range decision — 304, 416, If-Range, the byte
    window — was already made by :func:`entry_response` against the
    delivery entry's validators, so this class must NOT re-run
    ``FileResponse``'s stat-based machinery: aiohttp computes an
    ``mtime-size`` ETag and date-only If-Range, which would diverge from
    the digest ETags the buffered paths emit. ``prepare`` is overridden
    to open + fstat the file off-loop and hand straight to
    ``FileResponse._sendfile`` (``loop.sendfile`` → ``os.sendfile``,
    with aiohttp's own chunked fallback where unavailable) using the
    precomputed offset/count and the caller's headers verbatim.
    """

    def __init__(self, path, *, status: int, offset: int, count: int,
                 headers: dict[str, str]):
        super().__init__(path, status=status, headers=headers)
        self._offset = offset
        self._count = count

    def _open_stat(self):
        fobj = open(self._path, "rb")
        try:
            st = os.fstat(fobj.fileno())
        except OSError:
            fobj.close()
            raise
        if not S_ISREG(st.st_mode):
            fobj.close()
            raise FileNotFoundError(str(self._path))
        return fobj

    async def prepare(self, request: web.BaseRequest):
        loop = asyncio.get_running_loop()
        try:
            fobj = await loop.run_in_executor(None, self._open_stat)
        except OSError:
            # the file vanished between fill and serve (republish race):
            # degrade to a clean 404 rather than a torn stream
            self.set_status(404)
            self.content_length = 0
            for name in ("ETag", "Last-Modified", "Content-Range",
                         "Cache-Control", "Content-Type"):
                self.headers.pop(name, None)
            return await web.StreamResponse.prepare(self, request)
        try:
            self.content_length = self._count
            if self._count == 0:
                return await web.StreamResponse.prepare(self, request)
            # FileResponse._sendfile: loop.sendfile over the transport,
            # falling back to chunked executor reads when unsupported
            return await self._sendfile(request, fobj, self._offset,
                                        self._count)
        finally:
            fut = loop.run_in_executor(None, fobj.close)
            _CLOSE_FUTURES.add(fut)
            fut.add_done_callback(_CLOSE_FUTURES.discard)


# strong refs to in-flight close futures (mirrors aiohttp's own pattern)
_CLOSE_FUTURES: set = set()
