"""AV1 bitstream helpers for the delegated-encode path.

Only the container-facing pieces are first-party: walking a temporal
unit's OBUs, parsing the sequence header's profile/level/tier (AV1 spec
5.5.1 — the fields the av1C record and the RFC 6381 string need), and
building the ``av01.P.LLT.DD`` codec string. The encode itself is
delegated to the system encoder libraries (backends/av1_path.py).
"""

from __future__ import annotations

OBU_SEQUENCE_HEADER = 1


class _Bits:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def f(self, n: int) -> int:
        v = 0
        for _ in range(n):
            byte = self.data[self.pos >> 3]
            v = (v << 1) | ((byte >> (7 - (self.pos & 7))) & 1)
            self.pos += 1
        return v


def _leb128(data: bytes, pos: int) -> tuple[int, int]:
    value, shift = 0, 0
    while True:
        b = data[pos]
        pos += 1
        value |= (b & 0x7F) << shift
        if not (b & 0x80):
            return value, pos
        shift += 7


def iter_obus(tu: bytes):
    """Yield (obu_type, payload) over a low-overhead temporal unit."""
    pos = 0
    n = len(tu)
    while pos < n:
        header = tu[pos]
        obu_type = (header >> 3) & 0xF
        has_ext = (header >> 2) & 1
        has_size = (header >> 1) & 1
        pos += 1 + has_ext
        if has_size:
            size, pos = _leb128(tu, pos)
        else:
            size = n - pos
        yield obu_type, tu[pos:pos + size]
        pos += size


def parse_seq_header(tu: bytes) -> tuple[int, int, int]:
    """(seq_profile, seq_level_idx[0], seq_tier[0]) from a temporal unit
    containing a sequence header OBU (keyframe TUs carry one in-band).

    Covers the field layout system encoders emit (no decoder model /
    timing info is the libaom/SVT default); falls back to safe values if
    an unusual layout defeats the walk."""
    try:
        obus = list(iter_obus(tu))
    except IndexError:      # truncated/malformed TU: safe defaults
        return 0, 8, 0
    for obu_type, payload in obus:
        if obu_type != OBU_SEQUENCE_HEADER:
            continue
        try:
            r = _Bits(payload)
            profile = r.f(3)
            r.f(1)                          # still_picture
            reduced = r.f(1)
            if reduced:
                return profile, r.f(5), 0
            timing_present = r.f(1)
            if timing_present:
                # timing_info: num_units_in_tick + time_scale +
                # equal_picture_interval (uvlc skipped -> bail to safe)
                r.f(32)
                r.f(32)
                if r.f(1):
                    return profile, 8, 0    # level 3.0, Main tier
                if r.f(1):                  # decoder_model_info_present
                    return profile, 8, 0
            r.f(1)                          # initial_display_delay_present
            r.f(5)                          # operating_points_cnt_minus_1
            r.f(12)                         # operating_point_idc[0]
            level = r.f(5)
            tier = r.f(1) if level > 7 else 0
            return profile, level, tier
        except IndexError:
            return 0, 8, 0
    return 0, 8, 0


def codec_string_from_tu(meta: dict | None) -> str:
    """RFC 6381 av01 string from parsed sequence-header fields."""
    if not meta:
        return "av01.0.08M.08"
    tier = "H" if meta.get("tier") else "M"
    return (f"av01.{meta.get('profile', 0)}."
            f"{meta.get('level', 8):02d}{tier}.08")
