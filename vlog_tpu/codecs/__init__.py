"""First-party codec implementations.

The reference shells out to ffmpeg's libx264/NVENC/VAAPI encoders
(worker/hwaccel.py:647-839); this package is their TPU-native replacement:
JAX does the DSP (prediction, transform, quantization — see vlog_tpu.ops)
and a host-side entropy layer (Python reference + C++ fast path) emits
standard bitstreams.

- ``h264``: ITU-T H.264 / ISO 14496-10 encoder (Baseline intra subset:
  I_PCM and Intra_16x16+CAVLC) and a matching decoder for verification and
  for re-ingesting our own outputs.
"""
