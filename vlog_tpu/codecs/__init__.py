"""First-party codec implementations.

The reference shells out to ffmpeg's libx264/NVENC/VAAPI encoders
(worker/hwaccel.py:647-839); this package is their TPU-native replacement:
JAX does the DSP (prediction, transform, quantization — see vlog_tpu.ops)
and a host-side entropy layer (Python reference + C++ fast path) emits
standard bitstreams.

- ``h264``: ITU-T H.264 / ISO 14496-10 encoder (Baseline intra subset:
  I_PCM and Intra_16x16+CAVLC) and a matching decoder for verification and
  for re-ingesting our own outputs.
"""

# Codecs the product plane can encode to (h264/h265 first-party on device,
# av1 via the delegated system-encoder shim). Every rejection site uses
# no_encoder_error() so operators see one canonical message.
ENCODER_CODECS = ("h264", "h265", "av1")


def no_encoder_error(codec: str) -> str:
    return (f"codec {codec!r} has no encoder "
            f"(supported: {', '.join(ENCODER_CODECS)})")


def validate_codec_format(codec: str, streaming_format: str) -> str | None:
    """One rulebook for codec/container constraints across every plane
    (admin API, local daemon, remote worker). Returns an error message,
    or None when the combination is encodable. h265/av1 are CMAF-only:
    neither has a standard MPEG-TS mapping worth carrying."""
    if codec not in ENCODER_CODECS:
        return no_encoder_error(codec)
    if codec in ("h265", "av1") and streaming_format != "cmaf":
        return f"{codec} output is CMAF-only"
    return None
