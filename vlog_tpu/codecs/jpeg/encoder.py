"""Baseline sequential JPEG (ITU-T T.81), 4:2:0, standard Annex-K tables.

Device/host split mirrors the H.264 encoder: the FDCT + quantization for
every 8x8 block of all three planes is one XLA dispatch (the DCT is two
8x8 matmuls per block — MXU work); zigzag, run-length and Huffman coding
are host-side bit packing.

Reference parity: ffmpeg mjpeg encodes in worker/transcoder.py:2247
(thumbnail ``-vframes 1``) and worker/sprite_generator.py:363-380
(sprite sheets). Output is JFIF; PIL and browsers decode it directly
(tests/test_jpeg.py uses PIL as the oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Annex K tables
# ---------------------------------------------------------------------------

QUANT_LUMA = np.array([
    [16, 11, 10, 16, 24, 40, 51, 61],
    [12, 12, 14, 19, 26, 58, 60, 55],
    [14, 13, 16, 24, 40, 57, 69, 56],
    [14, 17, 22, 29, 51, 87, 80, 62],
    [18, 22, 37, 56, 68, 109, 103, 77],
    [24, 35, 55, 64, 81, 104, 113, 92],
    [49, 64, 78, 87, 103, 121, 120, 101],
    [72, 92, 95, 98, 112, 100, 103, 99],
], np.int32)

QUANT_CHROMA = np.array([
    [17, 18, 24, 47, 99, 99, 99, 99],
    [18, 21, 26, 66, 99, 99, 99, 99],
    [24, 26, 56, 99, 99, 99, 99, 99],
    [47, 66, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
], np.int32)

# Standard Huffman specs: (BITS[1..16], HUFFVAL)
DC_LUMA_BITS = [0, 1, 5, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]
DC_LUMA_VALS = list(range(12))
DC_CHROMA_BITS = [0, 3, 1, 1, 1, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0]
DC_CHROMA_VALS = list(range(12))

AC_LUMA_BITS = [0, 2, 1, 3, 3, 2, 4, 3, 5, 5, 4, 4, 0, 0, 1, 0x7D]
AC_LUMA_VALS = [
    0x01, 0x02, 0x03, 0x00, 0x04, 0x11, 0x05, 0x12, 0x21, 0x31, 0x41, 0x06,
    0x13, 0x51, 0x61, 0x07, 0x22, 0x71, 0x14, 0x32, 0x81, 0x91, 0xA1, 0x08,
    0x23, 0x42, 0xB1, 0xC1, 0x15, 0x52, 0xD1, 0xF0, 0x24, 0x33, 0x62, 0x72,
    0x82, 0x09, 0x0A, 0x16, 0x17, 0x18, 0x19, 0x1A, 0x25, 0x26, 0x27, 0x28,
    0x29, 0x2A, 0x34, 0x35, 0x36, 0x37, 0x38, 0x39, 0x3A, 0x43, 0x44, 0x45,
    0x46, 0x47, 0x48, 0x49, 0x4A, 0x53, 0x54, 0x55, 0x56, 0x57, 0x58, 0x59,
    0x5A, 0x63, 0x64, 0x65, 0x66, 0x67, 0x68, 0x69, 0x6A, 0x73, 0x74, 0x75,
    0x76, 0x77, 0x78, 0x79, 0x7A, 0x83, 0x84, 0x85, 0x86, 0x87, 0x88, 0x89,
    0x8A, 0x92, 0x93, 0x94, 0x95, 0x96, 0x97, 0x98, 0x99, 0x9A, 0xA2, 0xA3,
    0xA4, 0xA5, 0xA6, 0xA7, 0xA8, 0xA9, 0xAA, 0xB2, 0xB3, 0xB4, 0xB5, 0xB6,
    0xB7, 0xB8, 0xB9, 0xBA, 0xC2, 0xC3, 0xC4, 0xC5, 0xC6, 0xC7, 0xC8, 0xC9,
    0xCA, 0xD2, 0xD3, 0xD4, 0xD5, 0xD6, 0xD7, 0xD8, 0xD9, 0xDA, 0xE1, 0xE2,
    0xE3, 0xE4, 0xE5, 0xE6, 0xE7, 0xE8, 0xE9, 0xEA, 0xF1, 0xF2, 0xF3, 0xF4,
    0xF5, 0xF6, 0xF7, 0xF8, 0xF9, 0xFA,
]
AC_CHROMA_BITS = [0, 2, 1, 2, 4, 4, 3, 4, 7, 5, 4, 4, 0, 1, 2, 0x77]
AC_CHROMA_VALS = [
    0x00, 0x01, 0x02, 0x03, 0x11, 0x04, 0x05, 0x21, 0x31, 0x06, 0x12, 0x41,
    0x51, 0x07, 0x61, 0x71, 0x13, 0x22, 0x32, 0x81, 0x08, 0x14, 0x42, 0x91,
    0xA1, 0xB1, 0xC1, 0x09, 0x23, 0x33, 0x52, 0xF0, 0x15, 0x62, 0x72, 0xD1,
    0x0A, 0x16, 0x24, 0x34, 0xE1, 0x25, 0xF1, 0x17, 0x18, 0x19, 0x1A, 0x26,
    0x27, 0x28, 0x29, 0x2A, 0x35, 0x36, 0x37, 0x38, 0x39, 0x3A, 0x43, 0x44,
    0x45, 0x46, 0x47, 0x48, 0x49, 0x4A, 0x53, 0x54, 0x55, 0x56, 0x57, 0x58,
    0x59, 0x5A, 0x63, 0x64, 0x65, 0x66, 0x67, 0x68, 0x69, 0x6A, 0x73, 0x74,
    0x75, 0x76, 0x77, 0x78, 0x79, 0x7A, 0x82, 0x83, 0x84, 0x85, 0x86, 0x87,
    0x88, 0x89, 0x8A, 0x92, 0x93, 0x94, 0x95, 0x96, 0x97, 0x98, 0x99, 0x9A,
    0xA2, 0xA3, 0xA4, 0xA5, 0xA6, 0xA7, 0xA8, 0xA9, 0xAA, 0xB2, 0xB3, 0xB4,
    0xB5, 0xB6, 0xB7, 0xB8, 0xB9, 0xBA, 0xC2, 0xC3, 0xC4, 0xC5, 0xC6, 0xC7,
    0xC8, 0xC9, 0xCA, 0xD2, 0xD3, 0xD4, 0xD5, 0xD6, 0xD7, 0xD8, 0xD9, 0xDA,
    0xE2, 0xE3, 0xE4, 0xE5, 0xE6, 0xE7, 0xE8, 0xE9, 0xEA, 0xF2, 0xF3, 0xF4,
    0xF5, 0xF6, 0xF7, 0xF8, 0xF9, 0xFA,
]

ZIGZAG = np.array([
    0, 1, 8, 16, 9, 2, 3, 10, 17, 24, 32, 25, 18, 11, 4, 5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6, 7, 14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63,
])


def _build_huffman(bits: list[int], vals: list[int]) -> dict[int, tuple[int, int]]:
    """BITS/HUFFVAL -> {symbol: (code, length)} (T.81 C.2 canonical codes)."""
    table: dict[int, tuple[int, int]] = {}
    code = 0
    k = 0
    for length in range(1, 17):
        for _ in range(bits[length - 1]):
            table[vals[k]] = (code, length)
            code += 1
            k += 1
        code <<= 1
    return table

_DC_LUMA = _build_huffman(DC_LUMA_BITS, DC_LUMA_VALS)
_DC_CHROMA = _build_huffman(DC_CHROMA_BITS, DC_CHROMA_VALS)
_AC_LUMA = _build_huffman(AC_LUMA_BITS, AC_LUMA_VALS)
_AC_CHROMA = _build_huffman(AC_CHROMA_BITS, AC_CHROMA_VALS)


def _table_arrays(tbl: dict[int, tuple[int, int]]
                  ) -> tuple[np.ndarray, np.ndarray]:
    """Dict table -> (codes uint16[256], lens uint8[256]) for the C packer."""
    codes = np.zeros(256, np.uint16)
    lens = np.zeros(256, np.uint8)
    for sym, (code, length) in tbl.items():
        codes[sym] = code
        lens[sym] = length
    return codes, lens


_C_TABLES = None      # lazy: ((dc_l codes, lens), (ac_l ...), (dc_c), (ac_c))


def _pack_scan_native(blocks: np.ndarray, comp: np.ndarray) -> bytes | None:
    """Entropy-code the interleaved scan in C; None -> use the Python path."""
    from vlog_tpu.native.build import get_lib

    lib = get_lib()
    if lib is None:
        return None
    global _C_TABLES
    if _C_TABLES is None:
        _C_TABLES = tuple(_table_arrays(t) for t in
                          (_DC_LUMA, _AC_LUMA, _DC_CHROMA, _AC_CHROMA))
    import ctypes

    blocks = np.ascontiguousarray(blocks, np.int32)
    comp = np.ascontiguousarray(comp, np.uint8)
    i8 = ctypes.POINTER(ctypes.c_uint8)
    i32 = ctypes.POINTER(ctypes.c_int32)
    u16 = ctypes.POINTER(ctypes.c_uint16)
    cap = blocks.shape[0] * 128 + 64
    # theoretical worst case is ~2x this (all-escape coefficients + byte
    # stuffing); retry with a doubled buffer rather than falling back to
    # the ~1000x-slower Python loop
    for _ in range(3):
        out = np.empty(cap, np.uint8)
        args = [blocks.ctypes.data_as(i32), comp.ctypes.data_as(i8),
                ctypes.c_int64(blocks.shape[0])]
        for codes, lens in _C_TABLES:
            args.append(codes.ctypes.data_as(u16))
            args.append(lens.ctypes.data_as(i8))
        args += [out.ctypes.data_as(i8), ctypes.c_int64(cap)]
        n = lib.vt_jpeg_pack_scan(*args)
        if n >= 0:
            return out[:n].tobytes()
        cap *= 2
    return None


def _pack_scan_python(blocks: np.ndarray, comp: np.ndarray) -> bytes:
    """Pure-Python scan packer — the C packer's bit-exact oracle/fallback."""
    pk = _BitPacker()
    pred = [0, 0, 0]
    for bi in range(blocks.shape[0]):
        c = int(comp[bi])
        pred[c] = _encode_block(
            pk, blocks[bi], pred[c],
            _DC_LUMA if c == 0 else _DC_CHROMA,
            _AC_LUMA if c == 0 else _AC_CHROMA)
    pk.flush()
    return bytes(pk.out)


def scaled_quant_tables(quality: int) -> tuple[np.ndarray, np.ndarray]:
    """libjpeg-compatible quality (1..100) scaling of the Annex-K tables."""
    quality = min(max(int(quality), 1), 100)
    scale = 5000 // quality if quality < 50 else 200 - 2 * quality
    out = []
    for base in (QUANT_LUMA, QUANT_CHROMA):
        t = (base * scale + 50) // 100
        out.append(np.clip(t, 1, 255).astype(np.int32))
    return out[0], out[1]


# ---------------------------------------------------------------------------
# Device half: FDCT + quantize, batched over all blocks of a plane
# ---------------------------------------------------------------------------

def _dct_matrix() -> np.ndarray:
    k = np.arange(8)
    c = np.where(k == 0, 1.0 / np.sqrt(2.0), 1.0)
    m = c[:, None] / 2.0 * np.cos((2 * np.arange(8)[None, :] + 1) * k[:, None] * np.pi / 16)
    return m.astype(np.float32)

_DCT = _dct_matrix()


def _blocks(plane: jnp.ndarray) -> jnp.ndarray:
    """(H, W) -> (H/8 * W/8, 8, 8) in raster block order."""
    h, w = plane.shape
    b = plane.reshape(h // 8, 8, w // 8, 8)
    return jnp.transpose(b, (0, 2, 1, 3)).reshape(-1, 8, 8)


@functools.partial(jax.jit, static_argnames=("quality",))
def dct_quantize_420(y, u, v, *, quality: int):
    """Planes (uint8, 8-aligned; u/v 4:2:0) -> quantized zigzag blocks.

    Returns (yq, uq, vq): int32 (n_blocks, 64) in zigzag order, raster
    block order per plane.
    """
    qy, qc = scaled_quant_tables(quality)
    d = jnp.asarray(_DCT)
    zz = jnp.asarray(ZIGZAG)

    def plane_blocks(p, qtbl):
        x = _blocks(p.astype(jnp.float32) - 128.0)
        coef = jnp.einsum("ij,njk,lk->nil", d, x, d)
        q = jnp.round(coef / qtbl.astype(jnp.float32))
        return q.astype(jnp.int32).reshape(-1, 64)[:, zz]

    return (plane_blocks(y, qy), plane_blocks(u, qc), plane_blocks(v, qc))


# ---------------------------------------------------------------------------
# Host half: Huffman entropy coding + JFIF container
# ---------------------------------------------------------------------------

class _BitPacker:
    """MSB-first packer with JPEG 0xFF byte stuffing."""

    def __init__(self) -> None:
        self.out = bytearray()
        self._acc = 0
        self._n = 0

    def put(self, code: int, length: int) -> None:
        self._acc = (self._acc << length) | (code & ((1 << length) - 1))
        self._n += length
        while self._n >= 8:
            self._n -= 8
            byte = (self._acc >> self._n) & 0xFF
            self.out.append(byte)
            if byte == 0xFF:
                self.out.append(0x00)

    def flush(self) -> None:
        if self._n:
            pad = 8 - self._n
            self.put((1 << pad) - 1, pad)  # pad with 1s


def _magnitude(v: int) -> tuple[int, int]:
    """(size category, offset code) per T.81 F.1.2.1."""
    if v == 0:
        return 0, 0
    size = int(abs(v)).bit_length()
    code = v if v > 0 else v + (1 << size) - 1
    return size, code


def _encode_block(pk: _BitPacker, zz: np.ndarray, pred_dc: int,
                  dc_tbl: dict, ac_tbl: dict) -> int:
    dc = int(zz[0])
    size, code = _magnitude(dc - pred_dc)
    hc, hl = dc_tbl[size]
    pk.put(hc, hl)
    if size:
        pk.put(code, size)
    run = 0
    last_nz = 0
    nz = np.nonzero(zz[1:])[0]
    last_nz = int(nz[-1]) + 1 if nz.size else 0
    for i in range(1, last_nz + 1):
        v = int(zz[i])
        if v == 0:
            run += 1
            continue
        while run > 15:
            hc, hl = ac_tbl[0xF0]  # ZRL
            pk.put(hc, hl)
            run -= 16
        size, code = _magnitude(v)
        hc, hl = ac_tbl[(run << 4) | size]
        pk.put(hc, hl)
        pk.put(code, size)
        run = 0
    if last_nz < 63:
        hc, hl = ac_tbl[0x00]  # EOB
        pk.put(hc, hl)
    return dc


def _marker(tag: int, payload: bytes) -> bytes:
    return bytes([0xFF, tag]) + (len(payload) + 2).to_bytes(2, "big") + payload


def _dqt(qy: np.ndarray, qc: np.ndarray) -> bytes:
    def one(tid, tbl):
        return bytes([tid]) + bytes(int(tbl.reshape(-1)[ZIGZAG[i]]) for i in range(64))
    return _marker(0xDB, one(0, qy) + one(1, qc))


def _sof0(w: int, h: int) -> bytes:
    payload = bytes([8]) + h.to_bytes(2, "big") + w.to_bytes(2, "big") + bytes([3])
    payload += bytes([1, 0x22, 0])   # Y: 2x2 sampling, qtable 0
    payload += bytes([2, 0x11, 1])   # Cb
    payload += bytes([3, 0x11, 1])   # Cr
    return _marker(0xC0, payload)


def _dht() -> bytes:
    payload = b""
    for cls, tid, bits, vals in (
        (0, 0, DC_LUMA_BITS, DC_LUMA_VALS),
        (1, 0, AC_LUMA_BITS, AC_LUMA_VALS),
        (0, 1, DC_CHROMA_BITS, DC_CHROMA_VALS),
        (1, 1, AC_CHROMA_BITS, AC_CHROMA_VALS),
    ):
        payload += bytes([(cls << 4) | tid]) + bytes(bits) + bytes(vals)
    return _marker(0xC4, payload)


def _sos() -> bytes:
    payload = bytes([3, 1, 0x00, 2, 0x11, 3, 0x11, 0, 63, 0])
    return _marker(0xDA, payload)

_APP0 = _marker(0xE0, b"JFIF\x00\x01\x01\x00\x00\x01\x00\x01\x00\x00")


def _pad8(plane: np.ndarray, align: int) -> np.ndarray:
    h, w = plane.shape
    ph, pw = (-h) % align, (-w) % align
    if ph or pw:
        plane = np.pad(plane, ((0, ph), (0, pw)), mode="edge")
    return plane


def encode_jpeg_yuv420(y: np.ndarray, u: np.ndarray, v: np.ndarray,
                       *, quality: int = 85,
                       display_size: tuple[int, int] | None = None) -> bytes:
    """Full-range YCbCr 4:2:0 planes -> baseline JFIF bytes.

    y: (H, W) uint8; u/v: (ceil(H/2), ceil(W/2)). Interleaved single scan,
    2x2 MCUs. Video-range planes must be expanded to full range first
    (JFIF is full-range BT.601 by definition). ``display_size`` (h, w)
    overrides the SOF dimensions when the caller pre-padded the planes.
    """
    h, w = display_size if display_size is not None else y.shape
    y = _pad8(np.asarray(y, np.uint8), 16)
    u = _pad8(np.asarray(u, np.uint8), 8)
    v = _pad8(np.asarray(v, np.uint8), 8)
    if u.shape[0] * 2 != y.shape[0] or u.shape[1] * 2 != y.shape[1]:
        # chroma planes for odd luma sizes: pad up to half the padded luma
        uh, uw = y.shape[0] // 2, y.shape[1] // 2
        u = np.pad(u, ((0, uh - u.shape[0]), (0, uw - u.shape[1])), mode="edge")
        v = np.pad(v, ((0, uh - v.shape[0]), (0, uw - v.shape[1])), mode="edge")

    yq, uq, vq = (np.asarray(a) for a in dct_quantize_420(y, u, v, quality=quality))
    qy, qc = scaled_quant_tables(quality)

    mcu_h, mcu_w = y.shape[0] // 16, y.shape[1] // 16
    ybw = y.shape[1] // 8                      # luma blocks per row
    cbw = u.shape[1] // 8

    # Interleave blocks in MCU scan order (Y00 Y01 Y10 Y11 Cb Cr) with a
    # component id per block; the hot entropy loop then runs in C
    # (native/jpeg_pack.c), with the Python packer as bit-exact fallback.
    n_mcu = mcu_h * mcu_w
    my, mx = np.mgrid[0:mcu_h, 0:mcu_w]
    dy, dx = np.mgrid[0:2, 0:2]
    yidx = ((my[..., None, None] * 2 + dy) * ybw
            + mx[..., None, None] * 2 + dx).reshape(n_mcu, 4)
    cidx = (my * cbw + mx).reshape(n_mcu)
    blocks = np.empty((n_mcu, 6, 64), np.int32)
    blocks[:, :4] = yq[yidx]
    blocks[:, 4] = uq[cidx]
    blocks[:, 5] = vq[cidx]
    blocks = blocks.reshape(n_mcu * 6, 64)
    comp = np.tile(np.array([0, 0, 0, 0, 1, 2], np.uint8), n_mcu)

    scan = _pack_scan_native(blocks, comp)
    if scan is None:
        scan = _pack_scan_python(blocks, comp)

    return (b"\xff\xd8" + _APP0 + _dqt(qy, qc) + _sof0(w, h) + _dht() + _sos()
            + scan + b"\xff\xd9")


def encode_jpeg_rgb(rgb: np.ndarray, *, quality: int = 85) -> bytes:
    """(H, W, 3) uint8 RGB -> JFIF bytes (full-range BT.601 conversion)."""
    from vlog_tpu.ops.colorspace import rgb_to_yuv420

    arr = np.asarray(rgb, np.uint8)
    h, w = arr.shape[:2]
    ph, pw = (-h) % 2, (-w) % 2
    if ph or pw:  # rgb_to_yuv420 needs even dims for 2x2 chroma pooling
        arr = np.pad(arr, ((0, ph), (0, pw), (0, 0)), mode="edge")
    y, u, v = rgb_to_yuv420(
        jnp.asarray(arr, jnp.float32) / 255.0, standard="bt601", full_range=True)
    return encode_jpeg_yuv420(np.asarray(y), np.asarray(u), np.asarray(v),
                              quality=quality, display_size=(h, w))
