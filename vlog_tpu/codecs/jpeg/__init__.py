"""Baseline JFIF JPEG encoder (thumbnails, sprite sheets).

The reference produced thumbnails and sprite tiles with ffmpeg's mjpeg
encoder (worker/transcoder.py:2247-2259 thumbnail, worker/
sprite_generator.py:306-421 ``tile=10x10`` sprite pass); here the DCT +
quantization run batched on the TPU and Huffman entropy coding runs on
the host.
"""

from vlog_tpu.codecs.jpeg.encoder import encode_jpeg_rgb, encode_jpeg_yuv420  # noqa: F401
