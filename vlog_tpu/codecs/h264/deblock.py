"""H.264 in-loop deblocking filter (spec 8.7) — exact, TPU-shaped.

The reference gets deblocking for free inside x264/NVENC
(worker/hwaccel.py:647); our encoder must implement it in the JAX DSP
because the filter is IN-LOOP: the deblocked picture is what a decoder
uses as the P-frame reference, so the encoder's reconstruction must be
bit-exact with spec order or prediction drifts.

**Why a wavefront.** Spec 8.7 processes macroblocks in raster order;
within an MB, the four vertical edges left-to-right, then the four
horizontal edges top-to-bottom — each filter reading the latest
partially-filtered samples. Writes of one edge overlap reads of its
neighbours (a vertical MB-boundary filter reads the 4 columns its left
neighbour's horizontal filters just wrote), so the exact computation has
a wavefront dependency structure: MB (r, c) needs (r, c-1), (r-1, c) and
(r-1, c+1). We schedule op ``idx`` (0-3 vertical, 4-7 horizontal) of MB
(r, c) at phase ``8*(r + c) + idx``: every phase runs ONE op type over a
whole anti-diagonal of MBs — ``lax.scan`` over ``mbh + mbw - 1``
diagonals with an unrolled 8-op body, each op a batched gather/filter/
scatter over the diagonal (and over the GOP batch dimension when
vmapped). Exactness is by construction: phase order is a linear
extension of the spec's read/write partial order (row skew 8 covers the
worst cross-row dependency, H(r,c,0) after V(r-1,c+1,0)).

Boundary strengths for the streams this encoder emits:

- I frames (Intra_16x16): MB-boundary edges bS=4 (strong filter),
  internal edges bS=3.
- P frames (P_L0_16x16, one MV per MB): bS=2 where either adjacent 4x4
  luma block has nonzero coefficients, else bS=1 across MB boundaries
  where the MV delta is >= 4 quarter-pel on either component, else 0
  (spec 8.7.2.1 for the P_16x16 / single-ref case).

alpha/beta/tc0 are spec Tables 8-16/8-17 (values cross-checked against
libavcodec's h264_loopfilter tables). QP is uniform per frame here
(per-frame rate control), so threshold lookups are traced scalars.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from vlog_tpu.codecs.h264.encoder import chroma_qp

# Spec Table 8-16 (alpha, beta as functions of indexA/indexB 0..51).
ALPHA = np.array([
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 4, 4, 5, 6, 7, 8,
    9, 10, 12, 13, 15, 17, 20, 22, 25, 28, 32, 36, 40, 45, 50, 56, 63,
    71, 80, 90, 101, 113, 127, 144, 162, 182, 203, 226, 255, 255,
], np.int32)
BETA = np.array([
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 2, 2, 2, 3, 3, 3,
    3, 4, 4, 4, 6, 6, 7, 7, 8, 8, 9, 9, 10, 10, 11, 11, 12, 12, 13,
    13, 14, 14, 15, 15, 16, 16, 17, 17, 18, 18,
], np.int32)
# Spec Table 8-17: tc0 by (bS-1, indexA). Row 0 is bS=1.
TC0 = np.array([
    [0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
     0, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 4, 4, 4, 5,
     6, 6, 7, 8, 9, 10, 11, 13],
    [0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 1,
     1, 1, 1, 1, 1, 1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 4, 4, 5, 5, 6, 7,
     8, 8, 10, 11, 12, 13, 15, 17],
    [0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 1, 1, 1,
     1, 1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 4, 4, 4, 5, 6, 6, 7, 8, 9,
     10, 11, 13, 14, 16, 18, 20, 23, 25],
], np.int32)


# ---------------------------------------------------------------------------
# Boundary strengths
# ---------------------------------------------------------------------------

def intra_bs(mbh: int, mbw: int):
    """(bs_v, bs_h) for an all-Intra_16x16 frame, each (mbh, mbw, 4, 4):
    [r, c, edge_idx, segment] — MB-boundary edges 4, internal 3.
    Picture-boundary edges are masked off in the scan, values unused."""
    bs = np.full((mbh, mbw, 4, 4), 3, np.int32)
    bs[:, :, 0, :] = 4
    return jnp.asarray(bs), jnp.asarray(bs)


def p_bs(nz4, mv):
    """Boundary strengths for a P frame.

    nz4: (4*mbh, 4*mbw) bool/int — 4x4 luma block has nonzero levels.
    mv: (mbh, mbw, 2) int32 quarter-pel MVs (one per MB).
    Returns (bs_v, bs_h), each (mbh, mbw, 4, 4) int32 [r, c, edge, seg].
    """
    nz4 = nz4.astype(jnp.int32)
    mbh, mbw = mv.shape[0], mv.shape[1]
    # nz per edge: either side's 4x4 block coded -> bS 2
    nzl = jnp.pad(nz4, ((0, 0), (1, 0)))[:, :-1]        # left neighbour
    nzu = jnp.pad(nz4, ((1, 0), (0, 0)))[:-1, :]        # upper neighbour
    pair_v = ((nz4 | nzl) > 0)                          # (4mbh, 4mbw)
    pair_h = ((nz4 | nzu) > 0)
    # MV-difference >= 4 qpel applies only across MB boundaries (one MV
    # per MB here, internal edges have zero delta by construction)
    dv = jnp.abs(mv - jnp.pad(mv, ((0, 0), (1, 0), (0, 0)))[:, :-1])
    dh = jnp.abs(mv - jnp.pad(mv, ((1, 0), (0, 0), (0, 0)))[:-1, :])
    mv_v = jnp.any(dv >= 4, axis=-1)                    # (mbh, mbw)
    mv_h = jnp.any(dh >= 4, axis=-1)

    def shape(p, mvd):
        # p[r, c, i, s] — edge index i, segment s — already arranged by
        # the caller; MV bS=1 applies only to MB-boundary edges (i == 0)
        bs = jnp.where(p, 2, 0)
        mvterm = jnp.where(mvd[:, :, None, None], 1, 0)
        edge0 = jnp.maximum(bs[:, :, 0:1, :], mvterm)
        return jnp.concatenate([edge0, bs[:, :, 1:, :]], axis=2)

    # vertical edge i at x=16c+4i, segment s along y (block row 4r+s):
    # pair_v[4r+s, 4c+i] -> [r, c, i, s]
    pv = pair_v.reshape(mbh, 4, mbw, 4).transpose(0, 2, 3, 1)
    # horizontal edge i at y=16r+4i, segment s along x (block col 4c+s):
    # pair_h[4r+i, 4c+s] -> [r, c, i, s]
    ph = pair_h.reshape(mbh, 4, mbw, 4).transpose(0, 2, 1, 3)
    return shape(pv, mv_v), shape(ph, mv_h)


# ---------------------------------------------------------------------------
# Line filters: win (..., 8) = [p3 p2 p1 p0 q0 q1 q2 q3] along the line
# ---------------------------------------------------------------------------

def _filter_luma_lines(win, bs, alpha, beta, tc0_row):
    """Spec 8.7.2.2 (normal, bS 1..3) + 8.7.2.3 (strong, bS 4).

    win: (..., 8) int32; bs: (...,) int32 per line; tc0_row: (3,) traced
    tc0 values for bS 1..3 at the frame QP. Returns the filtered window.
    """
    p3, p2, p1, p0 = win[..., 0], win[..., 1], win[..., 2], win[..., 3]
    q0, q1, q2, q3 = win[..., 4], win[..., 5], win[..., 6], win[..., 7]
    filt = ((bs > 0)
            & (jnp.abs(p0 - q0) < alpha)
            & (jnp.abs(p1 - p0) < beta)
            & (jnp.abs(q1 - q0) < beta))
    ap = jnp.abs(p2 - p0) < beta
    aq = jnp.abs(q2 - q0) < beta

    # ---- normal filter (bS 1..3)
    tc0 = tc0_row[jnp.clip(bs, 1, 3) - 1]
    tc = tc0 + ap.astype(jnp.int32) + aq.astype(jnp.int32)
    delta = jnp.clip(((q0 - p0) * 4 + (p1 - q1) + 4) >> 3, -tc, tc)
    p0n = jnp.clip(p0 + delta, 0, 255)
    q0n = jnp.clip(q0 - delta, 0, 255)
    p1n = p1 + jnp.clip((p2 + ((p0 + q0 + 1) >> 1) - 2 * p1) >> 1,
                        -tc0, tc0)
    q1n = q1 + jnp.clip((q2 + ((p0 + q0 + 1) >> 1) - 2 * q1) >> 1,
                        -tc0, tc0)
    p1n = jnp.where(ap, p1n, p1)
    q1n = jnp.where(aq, q1n, q1)

    # ---- strong filter (bS 4)
    strong_p = ap & (jnp.abs(p0 - q0) < ((alpha >> 2) + 2))
    strong_q = aq & (jnp.abs(p0 - q0) < ((alpha >> 2) + 2))
    p0s = jnp.where(strong_p,
                    (p2 + 2 * p1 + 2 * p0 + 2 * q0 + q1 + 4) >> 3,
                    (2 * p1 + p0 + q1 + 2) >> 2)
    p1s = jnp.where(strong_p, (p2 + p1 + p0 + q0 + 2) >> 2, p1)
    p2s = jnp.where(strong_p,
                    (2 * p3 + 3 * p2 + p1 + p0 + q0 + 4) >> 3, p2)
    q0s = jnp.where(strong_q,
                    (q2 + 2 * q1 + 2 * q0 + 2 * p0 + p1 + 4) >> 3,
                    (2 * q1 + q0 + p1 + 2) >> 2)
    q1s = jnp.where(strong_q, (q2 + q1 + q0 + p0 + 2) >> 2, q1)
    q2s = jnp.where(strong_q,
                    (2 * q3 + 3 * q2 + q1 + q0 + p0 + 4) >> 3, q2)

    is4 = bs == 4
    p2o = jnp.where(filt & is4, p2s, p2)
    p1o = jnp.where(filt, jnp.where(is4, p1s, p1n), p1)
    p0o = jnp.where(filt, jnp.where(is4, p0s, p0n), p0)
    q0o = jnp.where(filt, jnp.where(is4, q0s, q0n), q0)
    q1o = jnp.where(filt, jnp.where(is4, q1s, q1n), q1)
    q2o = jnp.where(filt & is4, q2s, q2)
    return jnp.stack([p3, p2o, p1o, p0o, q0o, q1o, q2o, q3], axis=-1)


def _filter_chroma_lines(win, bs, alpha, beta, tc0_row):
    """Chroma edge filter: win (..., 4) = [p1 p0 q0 q1]."""
    p1, p0, q0, q1 = win[..., 0], win[..., 1], win[..., 2], win[..., 3]
    filt = ((bs > 0)
            & (jnp.abs(p0 - q0) < alpha)
            & (jnp.abs(p1 - p0) < beta)
            & (jnp.abs(q1 - q0) < beta))
    # normal: tc = tc0 + 1 (spec: chroma always adds 1)
    tc = tc0_row[jnp.clip(bs, 1, 3) - 1] + 1
    delta = jnp.clip(((q0 - p0) * 4 + (p1 - q1) + 4) >> 3, -tc, tc)
    p0n = jnp.clip(p0 + delta, 0, 255)
    q0n = jnp.clip(q0 - delta, 0, 255)
    # strong (bS 4)
    p0s = (2 * p1 + p0 + q1 + 2) >> 2
    q0s = (2 * q1 + q0 + p1 + 2) >> 2
    is4 = bs == 4
    p0o = jnp.where(filt, jnp.where(is4, p0s, p0n), p0)
    q0o = jnp.where(filt, jnp.where(is4, q0s, q0n), q0)
    return jnp.stack([p1, p0o, q0o, q1], axis=-1)


# ---------------------------------------------------------------------------
# Wavefront frame filter
# ---------------------------------------------------------------------------

def _edge_pass_v(plane, r_idx, x0, seg_bs, mask, alpha, beta, tc0_row,
                 *, mb, wwin, chroma):
    """Filter the vertical edges at per-row columns ``x0`` (one edge per
    active diagonal row). plane (H, W); r_idx (n,) MB rows; x0 (n,)
    edge columns; seg_bs (n, 4) per-segment bS; mask (n,) active."""
    h, w = plane.shape
    half = wwin // 2
    rows = r_idx[:, None] * mb + jnp.arange(mb)[None, :]        # (n, mb)
    cols = jnp.clip(x0[:, None] - half + jnp.arange(wwin)[None, :],
                    0, w - 1)                                    # (n, wwin)
    win = plane[rows[:, :, None], cols[:, None, :]]              # (n,mb,wwin)
    # per-line bS: segment s covers lines 4s..4s+3 (luma) / 2s.. (chroma)
    lines_per_seg = mb // 4
    bs_l = jnp.repeat(seg_bs, lines_per_seg, axis=1)             # (n, mb)
    f = _filter_chroma_lines if chroma else _filter_luma_lines
    out = f(win, bs_l, alpha, beta, tc0_row)
    out = jnp.where(mask[:, None, None], out, win)
    return plane.at[rows[:, :, None], cols[:, None, :]].set(out)


def _edge_pass_h(plane, r_idx, c_idx, y0, seg_bs, mask, alpha, beta,
                 tc0_row, *, mb, wwin, chroma):
    """Horizontal edges: transpose roles (lines run along x)."""
    h, w = plane.shape
    half = wwin // 2
    rows = jnp.clip(y0[:, None] - half + jnp.arange(wwin)[None, :],
                    0, h - 1)                                    # (n, wwin)
    cols = c_idx[:, None] * mb + jnp.arange(mb)[None, :]         # (n, mb)
    win = plane[rows[:, :, None], cols[:, None, :]]              # (n,wwin,mb)
    win = jnp.swapaxes(win, 1, 2)                                # (n,mb,wwin)
    lines_per_seg = mb // 4
    bs_l = jnp.repeat(seg_bs, lines_per_seg, axis=1)
    f = _filter_chroma_lines if chroma else _filter_luma_lines
    out = f(win, bs_l, alpha, beta, tc0_row)
    out = jnp.where(mask[:, None, None], out, win)
    out = jnp.swapaxes(out, 1, 2)                                # (n,wwin,mb)
    return plane.at[rows[:, :, None], cols[:, None, :]].set(out)


@partial(jax.jit, static_argnames=("mbh", "mbw"))
def _deblock_wavefront(y, u, v, qp, bs_v, bs_h, *, mbh, mbw):
    ia = jnp.clip(qp, 0, 51)
    alpha = jnp.asarray(ALPHA)[ia]
    beta = jnp.asarray(BETA)[ia]
    tc0_row = jnp.asarray(TC0)[:, ia]                            # (3,)
    qpc = chroma_qp(qp)
    alpha_c = jnp.asarray(ALPHA)[jnp.clip(qpc, 0, 51)]
    beta_c = jnp.asarray(BETA)[jnp.clip(qpc, 0, 51)]
    tc0_c = jnp.asarray(TC0)[:, jnp.clip(qpc, 0, 51)]

    r_idx = jnp.arange(mbh)

    def diag(carry, k):
        yy, uu, vv = carry
        c_idx = k - r_idx                                        # (mbh,)
        valid = (c_idx >= 0) & (c_idx < mbw)
        c_cl = jnp.clip(c_idx, 0, mbw - 1)
        segs_v = bs_v[r_idx, c_cl]                               # (mbh, 4, 4)
        segs_h = bs_h[r_idx, c_cl]
        for i in range(4):                       # vertical edges, x order
            x0 = c_cl * 16 + 4 * i
            m = valid & ((c_idx > 0) | (i > 0))  # picture-left edge off
            yy = _edge_pass_v(yy, r_idx, x0, segs_v[:, i], m,
                              alpha, beta, tc0_row,
                              mb=16, wwin=8, chroma=False)
            if i % 2 == 0:                       # chroma edges at x/2
                cseg = segs_v[:, i]              # luma bS, chroma lines
                xc = c_cl * 8 + 2 * i
                uu = _edge_pass_v(uu, r_idx, xc, cseg, m, alpha_c,
                                  beta_c, tc0_c, mb=8, wwin=4,
                                  chroma=True)
                vv = _edge_pass_v(vv, r_idx, xc, cseg, m, alpha_c,
                                  beta_c, tc0_c, mb=8, wwin=4,
                                  chroma=True)
        for j in range(4):                       # horizontal edges, y order
            y0 = r_idx * 16 + 4 * j
            m = valid & ((r_idx > 0) | (j > 0))  # picture-top edge off
            yy = _edge_pass_h(yy, r_idx, c_cl, y0, segs_h[:, j], m,
                              alpha, beta, tc0_row,
                              mb=16, wwin=8, chroma=False)
            if j % 2 == 0:
                yc = r_idx * 8 + 2 * j
                uu = _edge_pass_h(uu, r_idx, c_cl, yc, segs_h[:, j], m,
                                  alpha_c, beta_c, tc0_c, mb=8,
                                  wwin=4, chroma=True)
                vv = _edge_pass_h(vv, r_idx, c_cl, yc, segs_h[:, j], m,
                                  alpha_c, beta_c, tc0_c, mb=8,
                                  wwin=4, chroma=True)
        return (yy, uu, vv), None

    (y, u, v), _ = jax.lax.scan(
        diag, (y, u, v), jnp.arange(mbh + mbw - 1))
    return y, u, v


def deblock_frame(y, u, v, *, qp, bs_v, bs_h):
    """Deblock one reconstructed frame in place of spec 8.7.

    y (H, W), u/v (H/2, W/2) integer planes (uint8 ok); ``qp`` traced or
    Python int; bS arrays from :func:`intra_bs` / :func:`p_bs`. Returns
    filtered (y, u, v) as int32 (callers cast/clip as needed — values
    stay in [0, 255] by construction).
    """
    h, w = y.shape
    mbh, mbw = h // 16, w // 16
    return _deblock_wavefront(
        y.astype(jnp.int32), u.astype(jnp.int32), v.astype(jnp.int32),
        jnp.asarray(qp, jnp.int32), bs_v, bs_h, mbh=mbh, mbw=mbw)
