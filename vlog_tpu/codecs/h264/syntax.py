"""H.264 high-level syntax: NAL units, SPS, PPS, slice headers.

Replaces the parameter-set machinery ffmpeg/x264 provided for the
reference (codec strings extracted in worker/hwaccel.py:864-981 come from
exactly these bytes). Spec: ITU-T H.264 7.3 (syntax), annex A (profiles).

We emit Constrained Baseline (profile_idc 66, constraint_set0+1) for
CAVLC streams and Main (77) for CABAC (CABAC is prohibited in Baseline,
spec A.2.1), 4:2:0, frame MBs, pic_order_cnt_type 2 (output order ==
decode order — right for all-intra and low-delay). Deblocking is
signalled per slice: chain mode runs the in-loop filter
(codecs/h264/deblock.py, disable_deblocking_filter_idc=0), intra mode
leaves it off (idc=1); either way encoder/decoder reconstructions stay
identical.
"""

from __future__ import annotations

from dataclasses import dataclass

from vlog_tpu.media.bitstream import BitWriter, escape_emulation


# NAL unit types (spec 7.4.1, table 7-1)
NAL_SLICE = 1
NAL_IDR = 5
NAL_SEI = 6
NAL_SPS = 7
NAL_PPS = 8

PROFILE_BASELINE = 66
PROFILE_MAIN = 77
PROFILE_HIGH = 100


@dataclass(frozen=True)
class NalUnit:
    nal_unit_type: int
    nal_ref_idc: int
    rbsp: bytes

    def to_bytes(self) -> bytes:
        """Header byte + emulation-protected payload."""
        header = (self.nal_ref_idc << 5) | self.nal_unit_type
        return bytes([header]) + escape_emulation(self.rbsp)


def annexb(nals: list[NalUnit]) -> bytes:
    """Annex-B byte stream (4-byte start codes)."""
    return b"".join(b"\x00\x00\x00\x01" + n.to_bytes() for n in nals)


def _level_for(width: int, height: int, fps: float) -> int:
    """Pick the smallest level_idc covering the frame size + rate.

    MB/s and frame-size limits from spec table A-1 (common subset).
    """
    mbs = ((width + 15) // 16) * ((height + 15) // 16)
    mbps = mbs * fps
    # (level_idc, max_fs_mbs, max_mbps)
    table = [
        (10, 99, 1485), (11, 396, 3000), (12, 396, 6000), (13, 396, 11880),
        (20, 396, 11880), (21, 792, 19800), (22, 1620, 20250),
        (30, 1620, 40500), (31, 3600, 108000), (32, 5120, 216000),
        (40, 8192, 245760), (41, 8192, 245760), (42, 8704, 522240),
        (50, 22080, 589824), (51, 36864, 983040), (52, 36864, 2073600),
    ]
    for level, max_fs, max_mbps in table:
        if mbs <= max_fs and mbps <= max_mbps:
            return level
    return 52


@dataclass(frozen=True)
class SpsConfig:
    width: int
    height: int
    fps_num: int = 30
    fps_den: int = 1
    profile_idc: int = PROFILE_BASELINE
    level_idc: int = 0  # 0 = auto
    max_num_ref_frames: int = 1
    log2_max_frame_num: int = 8
    full_range: bool = False
    bt709: bool = True

    @property
    def mb_width(self) -> int:
        return (self.width + 15) // 16

    @property
    def mb_height(self) -> int:
        return (self.height + 15) // 16

    @property
    def level(self) -> int:
        if self.level_idc:
            return self.level_idc
        return _level_for(self.width, self.height, self.fps_num / self.fps_den)


def make_sps(cfg: SpsConfig, sps_id: int = 0) -> NalUnit:
    """seq_parameter_set_rbsp (spec 7.3.2.1.1) with minimal VUI timing."""
    w = BitWriter()
    w.write_bits(cfg.profile_idc, 8)
    # constraint_set0..5 + reserved_zero_2bits: constrained baseline
    w.write_bits(0b11000000 if cfg.profile_idc == PROFILE_BASELINE else 0, 8)
    w.write_bits(cfg.level, 8)
    w.write_ue(sps_id)
    w.write_ue(cfg.log2_max_frame_num - 4)   # log2_max_frame_num_minus4
    w.write_ue(2)                            # pic_order_cnt_type
    w.write_ue(cfg.max_num_ref_frames)
    w.write_bit(0)                           # gaps_in_frame_num_value_allowed
    w.write_ue(cfg.mb_width - 1)
    w.write_ue(cfg.mb_height - 1)
    w.write_bit(1)                           # frame_mbs_only_flag
    w.write_bit(1)                           # direct_8x8_inference_flag
    crop_r = (cfg.mb_width * 16 - cfg.width) // 2
    crop_b = (cfg.mb_height * 16 - cfg.height) // 2
    if crop_r or crop_b:
        w.write_bit(1)
        w.write_ue(0)
        w.write_ue(crop_r)
        w.write_ue(0)
        w.write_ue(crop_b)
    else:
        w.write_bit(0)
    # VUI: colour description + timing
    w.write_bit(1)                           # vui_parameters_present_flag
    w.write_bit(0)                           # aspect_ratio_info_present
    w.write_bit(0)                           # overscan_info_present
    w.write_bit(1)                           # video_signal_type_present
    w.write_bits(5, 3)                       # video_format: unspecified
    w.write_bit(1 if cfg.full_range else 0)  # video_full_range_flag
    w.write_bit(1)                           # colour_description_present
    prim = 1 if cfg.bt709 else 6             # BT.709 / BT.601-525
    w.write_bits(prim, 8)                    # colour_primaries
    w.write_bits(1 if cfg.bt709 else 6, 8)   # transfer_characteristics
    w.write_bits(1 if cfg.bt709 else 6, 8)   # matrix_coefficients
    w.write_bit(0)                           # chroma_loc_info_present
    w.write_bit(1)                           # timing_info_present
    w.write_bits(cfg.fps_den, 32)            # num_units_in_tick
    w.write_bits(cfg.fps_num * 2, 32)        # time_scale (field rate)
    w.write_bit(1)                           # fixed_frame_rate_flag
    w.write_bit(0)                           # nal_hrd_parameters_present
    w.write_bit(0)                           # vcl_hrd_parameters_present
    w.write_bit(0)                           # pic_struct_present_flag
    w.write_bit(0)                           # bitstream_restriction_flag
    w.rbsp_trailing_bits()
    return NalUnit(NAL_SPS, 3, w.getvalue())


def make_pps(pps_id: int = 0, sps_id: int = 0, init_qp: int = 26,
             cabac: bool = False) -> NalUnit:
    """pic_parameter_set_rbsp (spec 7.3.2.2), deblock-controllable."""
    w = BitWriter()
    w.write_ue(pps_id)
    w.write_ue(sps_id)
    w.write_bit(1 if cabac else 0)   # entropy_coding_mode_flag
    w.write_bit(0)            # bottom_field_pic_order_in_frame_present
    w.write_ue(0)             # num_slice_groups_minus1
    w.write_ue(0)             # num_ref_idx_l0_default_active_minus1
    w.write_ue(0)             # num_ref_idx_l1_default_active_minus1
    w.write_bit(0)            # weighted_pred_flag
    w.write_bits(0, 2)        # weighted_bipred_idc
    w.write_se(init_qp - 26)  # pic_init_qp_minus26
    w.write_se(0)             # pic_init_qs_minus26
    w.write_se(0)             # chroma_qp_index_offset
    w.write_bit(1)            # deblocking_filter_control_present_flag
    w.write_bit(0)            # constrained_intra_pred_flag
    w.write_bit(0)            # redundant_pic_cnt_present_flag
    w.rbsp_trailing_bits()
    return NalUnit(NAL_PPS, 3, w.getvalue())


SLICE_P = 0
SLICE_I = 7   # 7 = I (and signals "all slices in picture are I")


def write_slice_header(
    w: BitWriter,
    *,
    first_mb: int,
    slice_qp: int,
    init_qp: int,
    idr: bool,
    frame_num: int,
    idr_pic_id: int = 0,
    log2_max_frame_num: int = 8,
    slice_type: int = SLICE_I,
    cabac: bool = False,
    deblock: bool = False,
) -> None:
    """slice_header (spec 7.3.3) for our stream shape.

    pic_order_cnt_type=2 and frame_mbs_only keep this short. The PPS
    sets deblocking_filter_control_present_flag, so every slice signals
    the filter explicitly: idc=0 (on, zero offsets — the in-loop filter
    in codecs/h264/deblock.py mirrors the decoder exactly) or idc=1
    (off). P slices use the PPS default single reference (no override,
    no list modification).
    """
    is_p = slice_type in (0, 5)
    w.write_ue(first_mb)
    w.write_ue(slice_type)
    w.write_ue(0)                                  # pic_parameter_set_id
    w.write_bits(frame_num % (1 << log2_max_frame_num), log2_max_frame_num)
    if idr:
        w.write_ue(idr_pic_id)
    if is_p:
        w.write_bit(0)   # num_ref_idx_active_override_flag (1 ref, PPS)
        w.write_bit(0)   # ref_pic_list_modification_flag_l0
    # dec_ref_pic_marking (nal_ref_idc != 0)
    if idr:
        w.write_bit(0)   # no_output_of_prior_pics_flag
        w.write_bit(0)   # long_term_reference_flag
    else:
        w.write_bit(0)   # adaptive_ref_pic_marking_mode_flag
    if cabac and is_p:
        w.write_ue(0)    # cabac_init_idc
    w.write_se(slice_qp - init_qp)                 # slice_qp_delta
    # disable_deblocking_filter_idc: 0 = filter on (zero offsets), 1 = off
    if deblock:
        w.write_ue(0)
        w.write_se(0)                              # slice_alpha_c0_offset_div2
        w.write_se(0)                              # slice_beta_offset_div2
    else:
        w.write_ue(1)


def avcc_config(sps: NalUnit, pps: NalUnit) -> bytes:
    """AVCDecoderConfigurationRecord (ISO 14496-15 5.3.3.1) for avc1/avcC.

    The media layer's MP4 mux embeds this; browsers derive the codecs=
    string (e.g. avc1.42C028) from bytes 1-3.
    """
    sps_b = sps.to_bytes()
    pps_b = pps.to_bytes()
    out = bytearray()
    out.append(1)                 # configurationVersion
    out += sps_b[1:4]             # profile, compat, level from SPS
    out.append(0xFC | 3)          # lengthSizeMinusOne = 3 (4-byte lengths)
    out.append(0xE0 | 1)          # numOfSequenceParameterSets
    out += len(sps_b).to_bytes(2, "big") + sps_b
    out.append(1)                 # numOfPictureParameterSets
    out += len(pps_b).to_bytes(2, "big") + pps_b
    return bytes(out)


def codec_string(sps: NalUnit) -> str:
    """RFC 6381 codecs= value, e.g. ``avc1.42C028``.

    Reference extracted this by probing ffmpeg output
    (worker/hwaccel.py:864-981); here it falls out of the SPS bytes.
    """
    b = sps.to_bytes()
    return f"avc1.{b[1]:02X}{b[2]:02X}{b[3]:02X}"


def length_prefixed(nals: list[NalUnit]) -> bytes:
    """AVCC sample format: 4-byte big-endian length before each NAL."""
    out = bytearray()
    for n in nals:
        raw = n.to_bytes()
        out += len(raw).to_bytes(4, "big") + raw
    return bytes(out)
