"""H.264/AVC encoder (Constrained Baseline intra subset), TPU-native.

Architecture (single slice per frame — see ``encoder``):

- device (JAX, vlog_tpu.ops): prediction, residual, 4x4 integer
  transform, DC Hadamards, quantization, bit-exact reconstruction. MB
  row 0 is a small ``lax.scan`` over columns (left-neighbour DC
  prediction is sequential by construction); every other MB row uses
  Intra_16x16 *vertical* prediction so the whole row vectorizes and the
  frame is one ``lax.scan`` over rows, vmapped across the GOP.
- host: CAVLC entropy coding + NAL packing (``cavlc``; numpy/python
  reference implementation, C++ fast path planned) — frames are
  independent so a GOP entropy-codes on a thread pool.

Profile/level: Constrained Baseline, 4:2:0, 8-bit, progressive, all-intra.
Correctness is enforced by decoding every test stream bit-exactly with the
system libavcodec (tests/test_h264_oracle.py).
"""

from vlog_tpu.codecs.h264.syntax import (  # noqa: F401
    NalUnit,
    make_sps,
    make_pps,
    annexb,
    avcc_config,
)
