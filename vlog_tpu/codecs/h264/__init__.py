"""H.264/AVC encoder + verification decoder (Baseline intra subset).

Architecture (one slice per macroblock row — see ``encoder``):

- device (JAX, vlog_tpu.ops): colorspace, ladder resize, residual
  computation, 4x4 integer transform, DC Hadamards, quantization, and the
  bit-exact reconstruction used for left-neighbour DC prediction via
  ``lax.scan`` along each MB row (rows/frames vmapped).
- host: CAVLC entropy coding + NAL packing (Python reference here; C++
  fast path in native/), one independent byte string per row-slice so
  rows encode in parallel.

Profile/level: Constrained Baseline, 4:2:0, 8-bit, frame (progressive)
macroblocks, all-intra GOPs. Per-row slices both bound entropy-coding
dependencies and make every row independently decodable.
"""

from vlog_tpu.codecs.h264.syntax import (  # noqa: F401
    NalUnit,
    make_sps,
    make_pps,
    annexb,
    avcc_config,
)
