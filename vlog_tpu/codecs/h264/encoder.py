"""H.264 all-intra frame encoder — the TPU compute core.

Replaces the x264/NVENC encode the reference runs as an ffmpeg subprocess
per quality rung (worker/hwaccel.py:647 builds the command,
worker/transcoder.py:426-537 runs and monitors it). Here the whole encode
is one XLA program: a ``lax.scan`` over macroblock rows with every MB in a
row processed in parallel, ``vmap``-batched over the frames of a GOP.

The design choice that makes this map onto the TPU instead of a scalar
CPU loop: H.264 intra prediction normally chains left+top reconstructed
neighbours, serializing MBs along a wavefront. We restrict the encoder to
prediction modes with *only vertical* dependence:

- MB row 0:   Intra_16x16 DC with no neighbours (pred = 128), chroma DC.
- MB rows >0: Intra_16x16 Vertical (mode 0), chroma Vertical (mode 2).

Rows then vectorize perfectly (one (mbw, ...) tensor op per row) and the
row-to-row dependence — the reconstructed bottom pixel line — is a scan
carry of shape (W,). Compression cost vs full mode search is a few percent
at ladder bitrates; throughput gain is the whole point of the port.

Everything here is bit-exact integer math (see ops/transform.py); the
decoder reconstructs the same pixels, which tests/test_h264_oracle.py
asserts by decoding our streams with the system libavcodec.

Spec: ITU-T H.264 8.3.3 (Intra_16x16 prediction), 8.3.4 (chroma), 8.5
(transform/quant). Reference parity: worker/hwaccel.py:454-552 encoder
selection — this module is the ``device=tpu`` encoder those seams select.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vlog_tpu.ops.transform import (
    core_transform,
    dequantize,
    dequantize_chroma_dc,
    dequantize_luma_dc,
    hadamard2x2,
    hadamard4,
    inverse_core_transform,
    quantize,
    quantize_chroma_dc,
    quantize_luma_dc,
)

# Table 8-15: QPc as a function of qPI (chroma_qp_index_offset = 0).
_CHROMA_QP = np.concatenate(
    [
        np.arange(30),
        np.array(
            [29, 30, 31, 32, 32, 33, 34, 34, 35, 35, 36, 36, 37, 37, 37, 38,
             38, 38, 39, 39, 39, 39],
        ),
    ]
).astype(np.int32)


def chroma_qp(qp):
    """QPc from luma QP (spec table 8-15, zero index offset).

    Accepts a Python int (returns int) or a traced int32 scalar (returns
    the traced lookup — per-frame rate-controlled QP).
    """
    if isinstance(qp, (int, np.integer)):
        return int(_CHROMA_QP[min(max(qp, 0), 51)])
    return jnp.asarray(_CHROMA_QP)[jnp.clip(qp, 0, 51)]


@dataclass
class FrameLevels:
    """Quantized levels for one frame (or a leading batch of frames).

    Shapes (without batch dims), for an mbh x mbw macroblock grid:
      luma_dc:   (mbh, mbw, 4, 4)        Hadamard-domain DC levels
      luma_ac:   (mbh, mbw, 4, 4, 4, 4)  per 4x4 block (grid y, x), (0,0)==0
      chroma_dc: (2, mbh, mbw, 2, 2)     U then V
      chroma_ac: (2, mbh, mbw, 2, 2, 4, 4)  (0,0) position zeroed
    """

    luma_dc: np.ndarray
    luma_ac: np.ndarray
    chroma_dc: np.ndarray
    chroma_ac: np.ndarray
    qp: int

    @property
    def mb_height(self) -> int:
        return self.luma_dc.shape[-4]

    @property
    def mb_width(self) -> int:
        return self.luma_dc.shape[-3]


def _luma_encode(y_row, pred, qp):
    """Encode one MB row of luma. y_row (16, W) int32, pred (16, W).

    Returns (dc_levels (mbw,4,4), ac_levels (mbw,4,4,4,4), recon (16, W)).
    """
    w = y_row.shape[-1]
    mbw = w // 16
    pred = pred.astype(jnp.int32)
    resid = y_row.astype(jnp.int32) - pred
    # (16, W) -> (mbw, 16, 16) -> 4x4 blocks (mbw, 4, 4, 4, 4)
    mb = jnp.swapaxes(resid.reshape(16, mbw, 16), 0, 1)
    blocks = jnp.swapaxes(mb.reshape(mbw, 4, 4, 4, 4), 2, 3)
    coefs = core_transform(blocks)
    dc = coefs[..., 0, 0]                        # (mbw, 4, 4)
    dc_levels = quantize_luma_dc(hadamard4(dc), qp=qp)
    ac_levels = quantize(coefs, qp=qp, intra=True)
    ac_levels = ac_levels.at[..., 0, 0].set(0)
    # Reconstruction (decoder mirror)
    dc_rec = dequantize_luma_dc(dc_levels, qp=qp)  # (mbw, 4, 4)
    ac_rec = dequantize(ac_levels, qp=qp)
    full = ac_rec.at[..., 0, 0].set(dc_rec)
    resid_rec = inverse_core_transform(full)       # (mbw, 4, 4, 4, 4)
    mb_rec = jnp.swapaxes(resid_rec, 2, 3).reshape(mbw, 16, 16)
    row_rec = jnp.swapaxes(mb_rec, 0, 1).reshape(16, w)
    recon = jnp.clip(pred + row_rec, 0, 255)
    return dc_levels, ac_levels, recon


def _chroma_encode(c_row, pred, qpc):
    """Encode one MB row of one chroma plane. c_row (8, Wc), pred (8, Wc)."""
    wc = c_row.shape[-1]
    mbw = wc // 8
    pred = pred.astype(jnp.int32)
    resid = c_row.astype(jnp.int32) - pred
    mb = jnp.swapaxes(resid.reshape(8, mbw, 8), 0, 1)       # (mbw, 8, 8)
    blocks = jnp.swapaxes(mb.reshape(mbw, 2, 4, 2, 4), 2, 3)  # (mbw,2,2,4,4)
    coefs = core_transform(blocks)
    dc = coefs[..., 0, 0]                                   # (mbw, 2, 2)
    dc_levels = quantize_chroma_dc(hadamard2x2(dc), qp=qpc)
    ac_levels = quantize(coefs, qp=qpc, intra=True)
    ac_levels = ac_levels.at[..., 0, 0].set(0)
    dc_rec = dequantize_chroma_dc(dc_levels, qp=qpc)
    ac_rec = dequantize(ac_levels, qp=qpc)
    full = ac_rec.at[..., 0, 0].set(dc_rec)
    resid_rec = inverse_core_transform(full)
    mb_rec = jnp.swapaxes(resid_rec, 2, 3).reshape(mbw, 8, 8)
    row_rec = jnp.swapaxes(mb_rec, 0, 1).reshape(8, wc)
    recon = jnp.clip(pred + row_rec, 0, 255)
    return dc_levels, ac_levels, recon


def _encode_row0(y_row, u_row, v_row, qp, qpc):
    """Encode MB row 0 as a scan over MB columns (Intra_16x16 DC mode).

    The decoder's DC prediction uses the *left* neighbour when present
    (spec 8.3.3.3: left-only pred = (sum(left_col) + 8) >> 4), so row 0 is
    inherently sequential along x. It is a tiny fraction of the frame
    (1/mbh); every other row is the fully parallel vertical-mode path.

    Chroma DC mode predicts per 4x4 quadrant (8.3.4.2): with only the left
    MB available, the top-half quadrants use left rows 0..3 and the
    bottom-half quadrants left rows 4..7.
    """
    w = y_row.shape[-1]
    mbw = w // 16
    y_mbs = jnp.swapaxes(y_row.reshape(16, mbw, 16), 0, 1)   # (mbw, 16, 16)
    u_mbs = jnp.swapaxes(u_row.reshape(8, mbw, 8), 0, 1)
    v_mbs = jnp.swapaxes(v_row.reshape(8, mbw, 8), 0, 1)
    first = jnp.zeros((mbw,), jnp.bool_).at[0].set(True)

    def chroma_dc_pred(left_col, is_first):
        top = (jnp.sum(left_col[:4]) + 2) >> 2
        bot = (jnp.sum(left_col[4:]) + 2) >> 2
        col = jnp.concatenate([jnp.full((4,), top), jnp.full((4,), bot)])
        col = jnp.where(is_first, 128, col)
        return jnp.broadcast_to(col[:, None], (8, 8))

    def step(carry, xs):
        ly, lu, lv = carry                 # left MB's recon right columns
        y_mb, u_mb, v_mb, is_first = xs
        pred_dc = jnp.where(is_first, 128, (jnp.sum(ly) + 8) >> 4)
        pred_y = jnp.full((16, 16), pred_dc)
        ydc, yac, yrec = _luma_encode(y_mb, pred_y, qp)
        udc, uac, urec = _chroma_encode(u_mb, chroma_dc_pred(lu, is_first), qpc)
        vdc, vac, vrec = _chroma_encode(v_mb, chroma_dc_pred(lv, is_first), qpc)
        carry = (yrec[:, -1], urec[:, -1], vrec[:, -1])
        out = (ydc[0], yac[0], udc[0], uac[0], vdc[0], vac[0],
               yrec, urec, vrec)
        return carry, out

    init = (jnp.full((16,), 128, jnp.int32), jnp.full((8,), 128, jnp.int32),
            jnp.full((8,), 128, jnp.int32))
    _, (ydc, yac, udc, uac, vdc, vac, yrec, urec, vrec) = jax.lax.scan(
        step, init, (y_mbs, u_mbs, v_mbs, first)
    )
    # (mbw, 16, 16) -> (16, W)
    yrec = jnp.swapaxes(yrec, 0, 1).reshape(16, w)
    urec = jnp.swapaxes(urec, 0, 1).reshape(8, w // 2)
    vrec = jnp.swapaxes(vrec, 0, 1).reshape(8, w // 2)
    return ydc, yac, udc, uac, vdc, vac, yrec, urec, vrec


@jax.jit
def encode_frame(y, u, v, *, qp):
    """Encode one 4:2:0 frame to quantized levels + reconstruction.

    y: (H, W), u/v: (H/2, W/2), integer dtypes, H and W multiples of 16
    (pad with edge replication upstream; SPS cropping trims on decode).
    ``qp`` is a *traced* int32 scalar (or Python int) — one compile
    serves every QP, so closed-loop rate control is free.

    Returns dict of levels arrays (see :class:`FrameLevels`) plus
    ``recon_y/u/v`` for PSNR and debugging. jit-compiled per shape.
    """
    h, w = y.shape
    mbh = h // 16
    qpc = chroma_qp(qp)

    y32 = y.astype(jnp.int32)
    u32 = u.astype(jnp.int32)
    v32 = v.astype(jnp.int32)

    # --- MB row 0: DC modes, sequential along x (left-neighbour pred).
    r0 = _encode_row0(y32[:16], u32[:8], v32[:8], qp, qpc)
    (ydc0, yac0, udc0, uac0, vdc0, vac0, yrec0, urec0, vrec0) = r0

    if mbh == 1:
        return {
            "luma_dc": ydc0[None], "luma_ac": yac0[None],
            "chroma_dc": jnp.stack([udc0[None], vdc0[None]]),
            "chroma_ac": jnp.stack([uac0[None], vac0[None]]),
            "recon_y": yrec0.astype(jnp.uint8),
            "recon_u": urec0.astype(jnp.uint8),
            "recon_v": vrec0.astype(jnp.uint8),
        }

    # --- MB rows 1..mbh-1: vertical modes, whole row in parallel.
    y_rows = y32[16:].reshape(mbh - 1, 16, w)
    u_rows = u32[8:].reshape(mbh - 1, 8, w // 2)
    v_rows = v32[8:].reshape(mbh - 1, 8, w // 2)

    def vert(pred_line, n):
        return jnp.broadcast_to(pred_line[None, :], (n, pred_line.shape[0]))

    def step(carry, xs):
        prev_y, prev_u, prev_v = carry
        y_row, u_row, v_row = xs
        ydc, yac, yrec = _luma_encode(y_row, vert(prev_y, 16), qp)
        udc, uac, urec = _chroma_encode(u_row, vert(prev_u, 8), qpc)
        vdc, vac, vrec = _chroma_encode(v_row, vert(prev_v, 8), qpc)
        new_carry = (yrec[-1, :], urec[-1, :], vrec[-1, :])
        return new_carry, (ydc, yac, udc, uac, vdc, vac, yrec, urec, vrec)

    init = (yrec0[-1, :], urec0[-1, :], vrec0[-1, :])
    _, (ydc, yac, udc, uac, vdc, vac, yrec, urec, vrec) = jax.lax.scan(
        step, init, (y_rows, u_rows, v_rows)
    )
    return {
        "luma_dc": jnp.concatenate([ydc0[None], ydc]),    # (mbh, mbw, 4, 4)
        "luma_ac": jnp.concatenate([yac0[None], yac]),    # (mbh, mbw, 4,4,4,4)
        "chroma_dc": jnp.stack([
            jnp.concatenate([udc0[None], udc]),
            jnp.concatenate([vdc0[None], vdc]),
        ]),                                               # (2, mbh, mbw, 2, 2)
        "chroma_ac": jnp.stack([
            jnp.concatenate([uac0[None], uac]),
            jnp.concatenate([vac0[None], vac]),
        ]),                                               # (2, mbh, mbw, 2,2,4,4)
        "recon_y": jnp.concatenate(
            [yrec0, yrec.reshape((mbh - 1) * 16, w)]).astype(jnp.uint8),
        "recon_u": jnp.concatenate(
            [urec0, urec.reshape((mbh - 1) * 8, w // 2)]).astype(jnp.uint8),
        "recon_v": jnp.concatenate(
            [vrec0, vrec.reshape((mbh - 1) * 8, w // 2)]).astype(jnp.uint8),
    }


# Batched over a GOP: (N, H, W) / (N, H/2, W/2). One dispatch per rung.
# ``qp`` may be a scalar (all frames) or a (N,) per-frame vector — the
# rate controller steps QP between frames without recompiling.
@jax.jit
def _encode_gop_vec(y, u, v, qps):
    return jax.vmap(lambda a, b, c, q: encode_frame(a, b, c, qp=q))(y, u, v, qps)


def encode_gop(y, u, v, *, qp):
    qps = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (y.shape[0],))
    return _encode_gop_vec(y, u, v, qps)


def pad_to_mb(plane: np.ndarray, mb: int = 16) -> np.ndarray:
    """Edge-replicate pad H/W up to a multiple of ``mb`` (host-side)."""
    h, w = plane.shape[-2:]
    ph = (-h) % mb
    pw = (-w) % mb
    if ph == 0 and pw == 0:
        return plane
    pad = [(0, 0)] * (plane.ndim - 2) + [(0, ph), (0, pw)]
    return np.pad(plane, pad, mode="edge")


def frame_levels(out: dict, qp: int) -> FrameLevels:
    """Device output dict -> host FrameLevels (numpy)."""
    return FrameLevels(
        luma_dc=np.asarray(out["luma_dc"]),
        luma_ac=np.asarray(out["luma_ac"]),
        chroma_dc=np.asarray(out["chroma_dc"]),
        chroma_ac=np.asarray(out["chroma_ac"]),
        qp=qp,
    )
