"""H.264 CABAC entropy coding for the I16x16 / P_L0_16x16 envelope.

CAVLC (cavlc.py) was the launch entropy coder; CABAC buys the standard
~10-15% bitrate at equal PSNR — the same step x264's default profile
takes. The arithmetic engine is byte-identical to HEVC's
(codecs/hevc/cabac.ArithEncoder — H.264 9.3.4 and H.265 9.3.4 share the
range/transition tables), so this module only adds the H.264 context
layer: the 1024 (m, n) init pairs (cabac_ctx_tables.py, extracted from
libavcodec), the per-element ctxIdx derivations with their neighbor
state grids (9.3.3.1), binarizations (9.3.2: TU, UEG0/UEG3, the joint
I_16x16 mb_type code), and the block-categorized residual coding
(coded_block_flag, significance maps, level magnitudes).

Oracle: tests/test_h264_cabac.py decodes these streams with libavcodec
and asserts byte-exact reconstruction, exactly like the CAVLC tests.
"""

from __future__ import annotations

import numpy as np

from vlog_tpu.codecs.h264 import syntax
from vlog_tpu.codecs.h264.cabac_ctx_tables import INIT_I, INIT_PB
from vlog_tpu.codecs.h264.cavlc import MvPredictor, _BLK44
from vlog_tpu.codecs.h264.cavlc_tables import LUMA_BLOCK_ORDER, ZIGZAG_4x4
from vlog_tpu.codecs.hevc.cabac import ArithEncoder
from vlog_tpu.media.bitstream import BitWriter

_ZZ16 = [r * 4 + c for r, c in ZIGZAG_4x4]


def zigzag(block: np.ndarray) -> np.ndarray:
    return np.asarray(block).reshape(-1)[_ZZ16]


def init_states_264(slice_qp: int, *, i_slice: bool,
                    cabac_init_idc: int = 0) -> tuple[list, list]:
    """H.264 context init (9.3.1.1) — shared by encoder and decoder so
    the two can never drift."""
    table = INIT_I if i_slice else INIT_PB[cabac_init_idc]
    qp = min(max(slice_qp, 0), 51)
    pstate = [0] * 1024
    mps = [0] * 1024
    for i in range(1024):
        m, n = table[2 * i], table[2 * i + 1]
        pre = min(max(((m * qp) >> 4) + n, 1), 126)
        if pre <= 63:
            pstate[i], mps[i] = 63 - pre, 0
        else:
            pstate[i], mps[i] = pre - 64, 1
    return pstate, mps


class H264Cabac(ArithEncoder):
    """The engine with H.264 context initialization (9.3.1.1)."""

    def __init__(self, slice_qp: int, *, i_slice: bool,
                 cabac_init_idc: int = 0) -> None:
        super().__init__(*init_states_264(
            slice_qp, i_slice=i_slice, cabac_init_idc=cabac_init_idc))

    def tu(self, value: int, cmax: int, ctxs: list[int]) -> None:
        """Truncated unary with a per-bin ctx list (last entry reused)."""
        for k in range(value):
            self.encode_bin(ctxs[min(k, len(ctxs) - 1)], 1)
        if value < cmax:
            self.encode_bin(ctxs[min(value, len(ctxs) - 1)], 0)

    def eg_bypass(self, value: int, k: int) -> None:
        """k-th order Exp-Golomb in bypass (9.3.2.3 suffix)."""
        while value >= (1 << k):
            self.encode_bypass(1)
            value -= 1 << k
            k += 1
        self.encode_bypass(0)
        for i in range(k - 1, -1, -1):
            self.encode_bypass((value >> i) & 1)


# block categories: (ctx offsets into cbf/sig/last/level bases, #coeffs)
#   0 Intra16 luma DC, 1 Intra16 luma AC, 2 luma 4x4, 3 chroma DC,
#   4 chroma AC
_CBF_BASE = 85
_CBF_CAT = (0, 4, 8, 12, 16)
_SIG_BASE = 105
_LAST_BASE = 166
_SIGLAST_CAT = (0, 15, 29, 44, 47)
_LVL_BASE = 227
_LVL_CAT = (0, 10, 20, 30, 39)


class _SliceState:
    """Neighbor grids shared by the ctxIdxInc derivations (9.3.3.1)."""

    def __init__(self, mbh: int, mbw: int):
        self.mbh, self.mbw = mbh, mbw
        self.skip = np.zeros((mbh, mbw), bool)
        self.intra = np.zeros((mbh, mbw), bool)
        self.i16 = np.zeros((mbh, mbw), bool)
        self.cbp_luma = np.zeros((mbh, mbw), np.int32)
        self.cbp_chroma = np.zeros((mbh, mbw), np.int32)
        self.chroma_mode = np.zeros((mbh, mbw), np.int32)
        self.cbf_lumadc = np.zeros((mbh, mbw), np.int32)
        self.cbf_luma44 = np.zeros((mbh * 4, mbw * 4), np.int32)
        self.cbf_chdc = np.zeros((2, mbh, mbw), np.int32)
        self.cbf_ch44 = np.zeros((2, mbh * 2, mbw * 2), np.int32)
        self.mvd = np.zeros((mbh, mbw, 2), np.int32)   # |mvd| (x, y)
        self.prev_qp_delta_nz = False




def cbf_ctx_inc(st: _SliceState, cat: int, my: int, mx: int, comp: int,
                by: int, bx: int, cur_intra: bool) -> int:
    """ctxIdxInc for coded_block_flag: condA + 2*condB from the
    same-category neighbor blocks (9.3.3.1.1.9). Shared by the encoder
    and the decoder (cabac_dec.py) over the same _SliceState grids."""

    def cond(n_my, n_mx, grid_val):
        if not (0 <= n_my < st.mbh and 0 <= n_mx < st.mbw):
            # neighbor MB outside the picture
            return 1 if cur_intra else 0
        return grid_val

    if cat == 0:                        # luma DC: neighbor MB's DC cbf
        a = cond(my, mx - 1,
                 int(st.cbf_lumadc[my, mx - 1]) if mx > 0 else 0)
        b = cond(my - 1, mx,
                 int(st.cbf_lumadc[my - 1, mx]) if my > 0 else 0)
        # available neighbor that is not I16x16: transBlock absent -> 0
        if mx > 0 and not st.i16[my, mx - 1]:
            a = 0
        if my > 0 and not st.i16[my - 1, mx]:
            b = 0
        return a + 2 * b
    if cat in (1, 2):                   # luma 4x4 grid neighbors
        y, x = my * 4 + by, mx * 4 + bx
        a = cond(my, mx - 1 if x % 4 == 0 else mx,
                 int(st.cbf_luma44[y, x - 1]) if x > 0 else 0)
        b = cond(my - 1 if y % 4 == 0 else my, mx,
                 int(st.cbf_luma44[y - 1, x]) if y > 0 else 0)
        return a + 2 * b
    if cat == 3:                        # chroma DC per component
        a = cond(my, mx - 1,
                 int(st.cbf_chdc[comp, my, mx - 1]) if mx > 0 else 0)
        b = cond(my - 1, mx,
                 int(st.cbf_chdc[comp, my - 1, mx]) if my > 0 else 0)
        return a + 2 * b
    y, x = my * 2 + by, mx * 2 + bx     # chroma AC 2x2 grid
    a = cond(my, mx - 1 if x % 2 == 0 else mx,
             int(st.cbf_ch44[comp, y, x - 1]) if x > 0 else 0)
    b = cond(my - 1 if y % 2 == 0 else my, mx,
             int(st.cbf_ch44[comp, y - 1, x]) if y > 0 else 0)
    return a + 2 * b

class CabacSliceCoder:
    """Shared element writers for I and P slices."""

    def __init__(self, c: H264Cabac, mbh: int, mbw: int):
        self.c = c
        self.st = _SliceState(mbh, mbw)

    # ---------------------------------------------------------- residual
    def _cbf_inc(self, cat, my, mx, comp, by, bx, cur_intra):
        return cbf_ctx_inc(self.st, cat, my, mx, comp, by, bx, cur_intra)

    def residual_block(self, cat: int, coeffs: np.ndarray, my: int,
                       mx: int, *, comp: int = 0, by: int = 0, bx: int = 0,
                       cur_intra: bool = True) -> int:
        """coded_block_flag + significance map + levels (7.3.5.3.3).
        ``coeffs`` already in scan order. Returns the cbf bit."""
        c = self.c
        cbf = int(np.any(coeffs))
        ctx = _CBF_BASE + _CBF_CAT[cat] + self._cbf_inc(
            cat, my, mx, comp, by, bx, cur_intra)
        c.encode_bin(ctx, cbf)
        if not cbf:
            return 0
        n = len(coeffs)
        nz = [i for i in range(n) if coeffs[i]]
        last = nz[-1]
        for i in range(n - 1):
            inc = min(i, 2) if cat == 3 else i
            sig = int(coeffs[i] != 0)
            c.encode_bin(_SIG_BASE + _SIGLAST_CAT[cat] + inc, sig)
            if sig:
                c.encode_bin(_LAST_BASE + _SIGLAST_CAT[cat] + inc,
                             int(i == last))
                if i == last:
                    break
        num_eq1 = 0
        num_gt1 = 0
        for i in reversed(nz):
            val = abs(int(coeffs[i])) - 1
            inc0 = 0 if num_gt1 > 0 else min(4, 1 + num_eq1)
            base = _LVL_BASE + _LVL_CAT[cat]
            c.encode_bin(base + inc0, 1 if val > 0 else 0)
            if val > 0:
                inc_gt = 5 + min(4, num_gt1)
                prefix = min(val, 14)
                for k in range(1, prefix):
                    c.encode_bin(base + inc_gt, 1)
                if val < 14:
                    c.encode_bin(base + inc_gt, 0)
                else:
                    c.eg_bypass(val - 14, 0)
                num_gt1 += 1
            else:
                num_eq1 += 1
            c.encode_bypass(1 if coeffs[i] < 0 else 0)
        return 1

    # ---------------------------------------------------------- MB layer
    def _mb_type_i16(self, my: int, mx: int, cbp_luma: int,
                     cbp_chroma: int, luma_mode: int,
                     ctx0: int, ctx_rest: int, with_inc: bool) -> None:
        """The joint I_16x16 mb_type code (9.3.2.5): '1', terminate(0),
        then cbp/pred-mode bins with positional ctx."""
        c = self.c
        st = self.st
        if with_inc:
            ca = 1 if mx > 0 and not st.skip[my, mx - 1] and \
                st.intra[my, mx - 1] and st.i16[my, mx - 1] else 0
            cb = 1 if my > 0 and not st.skip[my - 1, mx] and \
                st.intra[my - 1, mx] and st.i16[my - 1, mx] else 0
            c.encode_bin(ctx0 + ca + cb, 1)
        else:
            c.encode_bin(ctx0, 1)
        c.encode_terminate(0)                    # not I_PCM
        # fixed ctx per field (not per bin position — the chroma second
        # bin is conditionally present but later ctxs do not shift)
        c.encode_bin(ctx_rest, 1 if cbp_luma else 0)
        c.encode_bin(ctx_rest + 1, 1 if cbp_chroma else 0)
        if cbp_chroma:
            c.encode_bin(ctx_rest + 2, 1 if cbp_chroma == 2 else 0)
        c.encode_bin(ctx_rest + 3, (luma_mode >> 1) & 1)
        c.encode_bin(ctx_rest + 4, luma_mode & 1)

    def chroma_pred_mode(self, my: int, mx: int, mode: int) -> None:
        st = self.st
        ca = 1 if mx > 0 and st.intra[my, mx - 1] and \
            st.chroma_mode[my, mx - 1] != 0 else 0
        cb = 1 if my > 0 and st.intra[my - 1, mx] and \
            st.chroma_mode[my - 1, mx] != 0 else 0
        self.c.encode_bin(64 + ca + cb, 1 if mode > 0 else 0)
        if mode > 0:
            self.c.encode_bin(67, 1 if mode > 1 else 0)
            if mode > 1:
                self.c.encode_bin(67, 1 if mode > 2 else 0)

    def qp_delta(self, value: int) -> None:
        c = self.c
        inc = 1 if self.st.prev_qp_delta_nz else 0
        mapped = 2 * abs(value) - (1 if value > 0 else 0)
        c.encode_bin(60 + inc, 1 if mapped > 0 else 0)
        if mapped > 0:
            c.tu(mapped - 1, 10 ** 9, [62, 63])
        self.st.prev_qp_delta_nz = value != 0

    def i16_residual(self, levels_like: dict, my: int, mx: int,
                     cbp_luma: int, cbp_chroma: int,
                     cur_intra: bool = True) -> None:
        """The Intra16x16 residual block sequence (same order as
        CAVLC's SliceEncoder.encode_macroblock)."""
        st = self.st
        luma_dc = levels_like["luma_dc"]
        luma_ac = levels_like["luma_ac"]
        chroma_dc = levels_like["chroma_dc"]
        chroma_ac = levels_like["chroma_ac"]
        st.cbf_lumadc[my, mx] = self.residual_block(
            0, zigzag(luma_dc), my, mx, cur_intra=cur_intra)
        if cbp_luma:
            for by, bx in LUMA_BLOCK_ORDER:
                cbf = self.residual_block(
                    1, zigzag(luma_ac[by, bx])[1:], my, mx,
                    by=by, bx=bx, cur_intra=cur_intra)
                st.cbf_luma44[my * 4 + by, mx * 4 + bx] = cbf
        if cbp_chroma > 0:
            for comp in range(2):
                st.cbf_chdc[comp, my, mx] = self.residual_block(
                    3, chroma_dc[comp].reshape(-1), my, mx, comp=comp,
                    cur_intra=cur_intra)
        if cbp_chroma == 2:
            for comp in range(2):
                for by in range(2):
                    for bx in range(2):
                        cbf = self.residual_block(
                            4, zigzag(chroma_ac[comp, by, bx])[1:], my, mx,
                            comp=comp, by=by, bx=bx, cur_intra=cur_intra)
                        st.cbf_ch44[comp, my * 2 + by, mx * 2 + bx] = cbf


def _native_cabac(kind: str, arrays: list, mbh: int, mbw: int, qp: int,
                  header: bytes) -> bytes | None:
    """C fast path (native/h264_cabac_enc.c); None falls back to Python.
    Both are bit-identical (tests/test_h264_cabac.py)."""
    from vlog_tpu.native import get_lib

    lib = get_lib()
    if lib is None:
        return None
    import ctypes

    arrs = [np.ascontiguousarray(a, np.int32) for a in arrays]
    scratch = np.zeros((mbh * mbw * 37,), np.int32)
    cap = 64 + len(header) + mbh * mbw * (384 * 4)
    out = np.empty(cap, np.uint8)
    hdr = (np.frombuffer(header, np.uint8) if header
           else np.empty(0, np.uint8))

    def ptr(a, t=ctypes.c_int32):
        return a.ctypes.data_as(ctypes.POINTER(t))

    fn = (lib.vt_h264_cabac_i_slice if kind == "i"
          else lib.vt_h264_cabac_p_slice)
    n = fn(*(ptr(a) for a in arrs), mbh, mbw, qp,
           ptr(hdr, ctypes.c_uint8), len(header), ptr(scratch),
           ptr(out, ctypes.c_uint8), cap)
    if n < 0:
        return None
    return out[:n].tobytes()


def encode_p_slice_cabac(plevels: dict, *, qp: int, init_qp: int,
                         frame_num: int,
                         log2_max_frame_num: int = 8,
                         deblock: bool = False) -> syntax.NalUnit:
    """Full P-slice NAL with CABAC (counterpart of cavlc.encode_p_slice:
    P_Skip / P_L0_16x16, quarter-pel MVDs against the median predictor).

    CABAC has no skip runs — every MB codes mb_skip_flag with a
    neighbor-conditioned context."""
    luma = plevels["luma"]
    chroma_dc = plevels["chroma_dc"]
    chroma_ac = plevels["chroma_ac"]
    mv_q = plevels["mv"]
    mbh, mbw = luma.shape[:2]

    w = BitWriter()
    syntax.write_slice_header(
        w, first_mb=0, slice_qp=qp, init_qp=init_qp, idr=False,
        frame_num=frame_num, log2_max_frame_num=log2_max_frame_num,
        slice_type=syntax.SLICE_P, cabac=True, deblock=deblock)
    w.byte_align(1)
    header = w.getvalue()

    rbsp = _native_cabac("p", [luma, chroma_dc, chroma_ac, mv_q],
                         mbh, mbw, qp, header)
    if rbsp is not None:
        return syntax.NalUnit(syntax.NAL_SLICE, 3, rbsp)

    c = H264Cabac(qp, i_slice=False)
    coder = CabacSliceCoder(c, mbh, mbw)
    st = coder.st
    mvp = MvPredictor(mbh, mbw)
    cbp8 = np.zeros((mbh * 2, mbw * 2), np.int32)   # luma bit per 8x8

    def mb_cbp(my, mx):
        bits = 0
        for i8 in range(4):
            gy, gx = _BLK44[i8]
            if np.any(luma[my, mx, 2 * gy:2 * gy + 2, 2 * gx:2 * gx + 2]):
                bits |= 1 << i8
        if np.any(chroma_ac[:, my, mx]):
            return bits | (2 << 4)
        if np.any(chroma_dc[:, my, mx]):
            return bits | (1 << 4)
        return bits

    for my in range(mbh):
        for mx in range(mbw):
            mvx, mvy = int(mv_q[my, mx, 1]), int(mv_q[my, mx, 0])
            cbp = mb_cbp(my, mx)
            smx, smy = mvp.skip_mv(my, mx)
            skip = cbp == 0 and (mvx, mvy) == (smx, smy)
            ca = 1 if mx > 0 and not st.skip[my, mx - 1] else 0
            cb = 1 if my > 0 and not st.skip[my - 1, mx] else 0
            c.encode_bin(11 + ca + cb, 1 if skip else 0)
            if skip:
                mvp.mvs[my, mx] = (smx, smy)
                st.skip[my, mx] = True
                c.encode_terminate(
                    1 if my == mbh - 1 and mx == mbw - 1 else 0)
                continue

            c.encode_bin(14, 0)                 # P type
            c.encode_bin(15, 0)                 # {16x16, 8x8}
            c.encode_bin(16, 0)                 # P_L0_16x16

            pmx, pmy = mvp.mv_pred(my, mx)
            mvp.mvs[my, mx] = (mvx, mvy)
            for comp, (mvd, base) in enumerate(
                    (((mvx - pmx), 40), ((mvy - pmy), 47))):
                amvd = 0
                if mx > 0:
                    amvd += int(st.mvd[my, mx - 1, comp])
                if my > 0:
                    amvd += int(st.mvd[my - 1, mx, comp])
                inc = 0 if amvd < 3 else (1 if amvd <= 32 else 2)
                val = abs(mvd)
                c.encode_bin(base + inc, 1 if val > 0 else 0)
                if val > 0:
                    prefix = min(val, 9)
                    for k in range(1, prefix):
                        c.encode_bin(base + 2 + min(k, 4), 1)
                    if val < 9:
                        c.encode_bin(base + 2 + min(prefix, 4), 0)
                    else:
                        c.eg_bypass(val - 9, 3)
                    c.encode_bypass(1 if mvd < 0 else 0)
                st.mvd[my, mx, comp] = val

            # coded_block_pattern: 4 luma bins + up to 2 chroma bins
            for i8 in range(4):
                gy, gx = _BLK44[i8]
                y8, x8 = my * 2 + gy, mx * 2 + gx
                a = 1 if x8 > 0 and cbp8[y8, x8 - 1] == 0 else 0
                b = 1 if y8 > 0 and cbp8[y8 - 1, x8] == 0 else 0
                bit = (cbp >> i8) & 1
                c.encode_bin(73 + a + 2 * b, bit)
                cbp8[y8, x8] = bit
            cbp_chroma = cbp >> 4
            ca = 1 if mx > 0 and st.cbp_chroma[my, mx - 1] != 0 else 0
            cb = 1 if my > 0 and st.cbp_chroma[my - 1, mx] != 0 else 0
            c.encode_bin(77 + ca + 2 * cb, 1 if cbp_chroma else 0)
            if cbp_chroma:
                ca = 1 if mx > 0 and st.cbp_chroma[my, mx - 1] == 2 else 0
                cb = 1 if my > 0 and st.cbp_chroma[my - 1, mx] == 2 else 0
                c.encode_bin(81 + ca + 2 * cb,
                             1 if cbp_chroma == 2 else 0)
            st.cbp_chroma[my, mx] = cbp_chroma

            if cbp:
                coder.qp_delta(0)
                # luma 4x4 blocks in quadrant order for set cbp bits
                for i8 in range(4):
                    oy, ox = _BLK44[i8]
                    for dy, dx in _BLK44:
                        by, bx = 2 * oy + dy, 2 * ox + dx
                        if not (cbp >> i8) & 1:
                            st.cbf_luma44[my * 4 + by, mx * 4 + bx] = 0
                            continue
                        cbf = coder.residual_block(
                            2, zigzag(luma[my, mx, by, bx]), my, mx,
                            by=by, bx=bx, cur_intra=False)
                        st.cbf_luma44[my * 4 + by, mx * 4 + bx] = cbf
                if cbp_chroma > 0:
                    for comp in range(2):
                        st.cbf_chdc[comp, my, mx] = coder.residual_block(
                            3, chroma_dc[comp, my, mx].reshape(-1),
                            my, mx, comp=comp, cur_intra=False)
                if cbp_chroma == 2:
                    for comp in range(2):
                        for by in range(2):
                            for bx in range(2):
                                cbf = coder.residual_block(
                                    4, zigzag(
                                        chroma_ac[comp, my, mx, by, bx]
                                    )[1:], my, mx, comp=comp, by=by,
                                    bx=bx, cur_intra=False)
                                st.cbf_ch44[
                                    comp, my * 2 + by, mx * 2 + bx] = cbf
            c.encode_terminate(
                1 if my == mbh - 1 and mx == mbw - 1 else 0)

    return syntax.NalUnit(syntax.NAL_SLICE, 3, header + c.getvalue())


def encode_slice_cabac(levels, *, qp: int, init_qp: int,
                       frame_num: int = 0, idr: bool = True,
                       idr_pic_id: int = 0,
                       log2_max_frame_num: int = 8,
                       deblock: bool = False) -> syntax.NalUnit:
    """Full I-slice NAL with CABAC entropy (counterpart of
    cavlc.encode_slice)."""
    mbh, mbw = levels.mb_height, levels.mb_width
    w = BitWriter()
    syntax.write_slice_header(
        w, first_mb=0, slice_qp=qp, init_qp=init_qp, idr=idr,
        frame_num=frame_num, idr_pic_id=idr_pic_id,
        log2_max_frame_num=log2_max_frame_num, cabac=True, deblock=deblock)
    w.byte_align(1)                     # cabac_alignment_one_bit(s)
    header = w.getvalue()
    nal_type = syntax.NAL_IDR if idr else syntax.NAL_SLICE

    rbsp = _native_cabac(
        "i", [levels.luma_dc, levels.luma_ac, levels.chroma_dc,
              levels.chroma_ac], mbh, mbw, qp, header)
    if rbsp is not None:
        return syntax.NalUnit(nal_type, 3, rbsp)

    c = H264Cabac(qp, i_slice=True)
    coder = CabacSliceCoder(c, mbh, mbw)
    st = coder.st
    for my in range(mbh):
        for mx in range(mbw):
            luma_ac = levels.luma_ac[my, mx]
            chroma_dc = levels.chroma_dc[:, my, mx]
            chroma_ac = levels.chroma_ac[:, my, mx]
            cbp_luma = 15 if np.any(luma_ac) else 0
            cbp_chroma = (2 if np.any(chroma_ac)
                          else (1 if np.any(chroma_dc) else 0))
            luma_mode = 2 if my == 0 else 0
            chroma_mode = 0 if my == 0 else 2
            coder._mb_type_i16(my, mx, cbp_luma, cbp_chroma, luma_mode,
                               3, 6, with_inc=True)
            coder.chroma_pred_mode(my, mx, chroma_mode)
            coder.qp_delta(0)
            coder.i16_residual(
                {"luma_dc": levels.luma_dc[my, mx], "luma_ac": luma_ac,
                 "chroma_dc": chroma_dc, "chroma_ac": chroma_ac},
                my, mx, cbp_luma, cbp_chroma)
            st.intra[my, mx] = True
            st.i16[my, mx] = True
            st.cbp_luma[my, mx] = cbp_luma
            st.cbp_chroma[my, mx] = cbp_chroma
            st.chroma_mode[my, mx] = chroma_mode
            c.encode_terminate(
                1 if my == mbh - 1 and mx == mbw - 1 else 0)
    return syntax.NalUnit(nal_type, 3, header + c.getvalue())
