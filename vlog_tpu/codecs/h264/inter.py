"""P-frame DSP: motion search, motion compensation, inter residual coding.

The inter half of the TPU encoder (the piece that closes the ~11 dB
all-intra gap QUALITY.md measured against libx264). Design constraints,
TPU-first:

- **Full-search integer motion estimation as a scan over offsets**: for
  each candidate displacement the whole frame's SAD-per-MB is one shifted
  subtract + block-sum — (2s+1)^2 sequential steps of perfectly parallel
  (H, W) work, instead of a per-MB scalar search loop. A small MV-cost
  penalty biases toward short vectors (rate proxy).
- **Sub-pel refinement on device**: the three half-sample planes (b, h,
  j — spec 8.4.2.2.1 six-tap) are whole-plane shifted sums computed once
  per reference; eight half-pel then eight quarter-pel candidates around
  each MB's winner are gathers + block-SADs. Quarter positions are the
  spec's upward-rounded averages of two neighbours — expressed as one
  per-pixel select over eight gathered planes via a 16-entry (fy, fx)
  case table. MVs flow through the pipeline in QUARTER-PEL units
  ((y, x), DSP order) — the bitstream's own resolution.
- **Motion compensation as gathers**: per-MB MVs expand to per-pixel
  index maps over the edge-padded reference/half planes. Chroma follows
  H.264 8.4.2.2.2: the luma quarter-pel MV value lands on the
  eighth-chroma-pel grid directly, so chroma prediction is the 4-tap
  bilinear blend with weights 0..8 per axis.
- **Residuals**: inter 4x4 luma transform keeps all 16 coefficients per
  block (no Intra16x16 DC split); chroma keeps the 2x2 DC Hadamard.
  Quantizer rounding uses the inter offset (f = 2^qbits/6) — rounding is
  encoder freedom, dequant stays normative.

Frames chain: ``encode_p_frame`` takes the previous frame's
reconstruction (decoder mirror) as the reference, so streams survive the
libavcodec oracle bit-exactly (tests/test_h264_p.py).

Spec: ITU-T H.264 8.4 (inter prediction), 8.5 (transform). Reference
parity: this replaces x264's ME/MC inside the ffmpeg workers
(worker/hwaccel.py:647).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vlog_tpu.codecs.h264.encoder import chroma_qp
from vlog_tpu.ops.transform import (
    core_transform,
    dequantize,
    dequantize_chroma_dc,
    hadamard2x2,
    inverse_core_transform,
    quantize,
    quantize_chroma_dc,
)

# SAD penalty per quarter-pel of |MV| component — biases the search toward
# short vectors (a stand-in for the MVD rate term in RD cost).
MV_COST_LAMBDA = 4


_SIX_TAP = (1, -5, 20, 20, -5, 1)


def _six_tap_shift(x, axis):
    """Un-normalized 6-tap at half positions: out[i] sits between i and
    i+1 (taps i-2..i+3). jnp.roll wrap contamination reaches 3 (6 after
    the second pass) samples into the pad ring; callers pad by at least
    search+8 so gathered positions never touch it."""
    out = None
    for k, t in enumerate(_SIX_TAP):
        term = t * jnp.roll(x, 2 - k, axis=axis)
        out = term if out is None else out + term
    return out


def half_pel_planes(refp):
    """Edge-padded (Hp, Wp) int32 reference -> (b, h, j) planes, same
    shape/alignment (spec 8.4.2.2.1: b right-half, h down-half, j
    center; j from the un-normalized horizontal intermediates, which is
    exactly the spec's two-stage filter since no clipping intervenes)."""
    b1 = _six_tap_shift(refp, axis=1)
    h1 = _six_tap_shift(refp, axis=0)
    j1 = _six_tap_shift(b1, axis=0)
    b = jnp.clip((b1 + 16) >> 5, 0, 255)
    h = jnp.clip((h1 + 16) >> 5, 0, 255)
    j = jnp.clip((j1 + 512) >> 10, 0, 255)
    return b, h, j


# Quarter-sample derivation (spec 8.4.2.2.1): every quarter position is
# the upward-rounded average of two samples drawn from {G (integer), b,
# h, j} at offsets 0/+1.  Sample ids: 0=G(0,0) 1=G(0,+1) 2=G(+1,0)
# 3=b(0,0) 4=b(+1,0) 5=h(0,0) 6=h(0,+1) 7=j(0,0).  Indexed [fy][fx].
_QPEL_A = np.array([[0, 0, 3, 3],      # G a b c
                    [0, 3, 3, 3],      # d e f g
                    [5, 5, 7, 7],      # h i j k
                    [5, 5, 7, 6]],     # n p q r
                   np.int32)
_QPEL_B = np.array([[0, 3, 3, 1],
                    [5, 5, 7, 6],
                    [5, 7, 7, 6],
                    [2, 4, 4, 4]], np.int32)


def _gather_qpel(refp, planes, mv_q, *, pad, mb=16):
    """Luma prediction at quarter-pel MVs: eight gathers (the candidate
    neighbour samples), then one per-pixel pair-select + average."""
    bpl, hpl, jpl = planes
    hp = refp.shape[0] - 2 * pad
    wp = refp.shape[1] - 2 * pad
    dy, dx = _mv_maps(mv_q, mb)
    iy, fy = dy >> 2, dy & 3
    ix, fx = dx >> 2, dx & 3
    rows = jnp.arange(hp)[:, None] + iy + pad
    cols = jnp.arange(wp)[None, :] + ix + pad
    cand = jnp.stack([
        refp[rows, cols], refp[rows, cols + 1], refp[rows + 1, cols],
        bpl[rows, cols], bpl[rows + 1, cols],
        hpl[rows, cols], hpl[rows, cols + 1],
        jpl[rows, cols],
    ])                                              # (8, H, W)
    case = fy * 4 + fx
    ia = jnp.asarray(_QPEL_A).reshape(-1)[case]     # (H, W) sample ids
    ib = jnp.asarray(_QPEL_B).reshape(-1)[case]
    pa = jnp.take_along_axis(cand, ia[None], axis=0)[0]
    pb = jnp.take_along_axis(cand, ib[None], axis=0)[0]
    return (pa + pb + 1) >> 1


def motion_search(cur_y, ref_y, *, search: int = 8,
                  lam: int = MV_COST_LAMBDA, refp=None, planes=None):
    """Full-search integer ME + half- then quarter-pel refinement:
    (H, W) planes -> (mbh, mbw, 2) MVs in QUARTER-PEL units (y, x).

    Deterministic: ties keep the earlier candidate in raster offset
    order, with (0,0) evaluated first; each refinement stage keeps the
    previous winner on ties (its SAD seeds the stage, so the base
    candidate is never re-evaluated).  ``refp``/``planes`` may be
    precomputed by the caller (encode_p_frame shares them with motion
    compensation).
    """
    h, w = cur_y.shape
    mbh, mbw = h // 16, w // 16
    cur = cur_y.astype(jnp.int32)
    pad = search + 8
    if refp is None:
        refp = jnp.pad(ref_y.astype(jnp.int32), pad, mode="edge")

    offsets = [(0, 0)] + [
        (dy, dx)
        for dy in range(-search, search + 1)
        for dx in range(-search, search + 1)
        if (dy, dx) != (0, 0)
    ]
    offs = jnp.asarray(offsets, jnp.int32)          # (n_off, 2)

    def sad_at(off):
        shifted = jax.lax.dynamic_slice(
            refp, (pad + off[0], pad + off[1]), (h, w))
        d = jnp.abs(cur - shifted)
        sad = d.reshape(mbh, 16, mbw, 16).sum(axis=(1, 3))
        cost = lam * 4 * (jnp.abs(off[0]) + jnp.abs(off[1]))
        return sad + cost

    def step(carry, off):
        best_sad, best_mv = carry
        sad = sad_at(off)
        better = sad < best_sad
        best_sad = jnp.where(better, sad, best_sad)
        best_mv = jnp.where(better[..., None], off[None, None, :], best_mv)
        return (best_sad, best_mv), None

    init = (jnp.full((mbh, mbw), jnp.iinfo(jnp.int32).max, jnp.int32),
            jnp.zeros((mbh, mbw, 2), jnp.int32))
    (int_sad, mv_int), _ = jax.lax.scan(step, init, offs)

    # --- sub-pel refinement: eight candidates per stage around the
    # previous winner, seeded with its SAD (cost scales are commensurate
    # in quarter-pel units: lam*4*|int| == lam*|4*int|).
    if planes is None:
        planes = half_pel_planes(refp)

    neigh = jnp.asarray(
        [(dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1)
         if (dy, dx) != (0, 0)], jnp.int32)

    def refine(base_q, base_sad, step_q):
        def sad_q(cand):
            pred = _gather_qpel(refp, planes, cand, pad=pad)
            sad = jnp.abs(cur - pred).reshape(
                mbh, 16, mbw, 16).sum(axis=(1, 3))
            cost = lam * (jnp.abs(cand[..., 0]) + jnp.abs(cand[..., 1]))
            return sad + cost

        def rstep(carry, off):
            best_sad, best_mv = carry
            cand = base_q + step_q * off[None, None, :]
            sad = sad_q(cand)
            better = sad < best_sad
            best_sad = jnp.where(better, sad, best_sad)
            best_mv = jnp.where(better[..., None], cand, best_mv)
            return (best_sad, best_mv), None

        (sad, mv), _ = jax.lax.scan(rstep, (base_sad, base_q), neigh)
        return mv, sad

    mv_q, sad_q = refine(mv_int * 4, int_sad, 2)    # half-pel stage
    mv_q, _ = refine(mv_q, sad_q, 1)                # quarter-pel stage
    return mv_q


def _mv_maps(mv, mb: int):
    """(mbh, mbw, 2) -> per-pixel (H, W) dy/dx maps for a plane with
    ``mb``-sized macroblocks."""
    dy = jnp.repeat(jnp.repeat(mv[..., 0], mb, axis=0), mb, axis=1)
    dx = jnp.repeat(jnp.repeat(mv[..., 1], mb, axis=0), mb, axis=1)
    return dy, dx


def mc_luma(ref_y, mv_q, *, search: int, planes=None, refp=None):
    """Luma prediction at quarter-pel MVs (spec 8.4.2.2).

    ``planes``/``refp`` may be precomputed (encode path: the search just
    built them); the decode path passes only the reference."""
    pad = search + 8
    if refp is None:
        refp = jnp.pad(ref_y.astype(jnp.int32), pad, mode="edge")
    if planes is None:
        planes = half_pel_planes(refp)
    return _gather_qpel(refp, planes, mv_q, pad=pad)


def mc_chroma(ref_c, mv_q, *, search: int):
    """Chroma prediction per 8.4.2.2.2: the luma quarter-pel MV value is
    interpreted directly on the eighth-chroma-pel grid (integer part
    q>>3, fraction q&7), with the spec's bilinear blend."""
    hc, wc = ref_c.shape
    pad = search // 2 + 2
    refp = jnp.pad(ref_c.astype(jnp.int32), pad, mode="edge")
    dy, dx = _mv_maps(mv_q, 8)                      # quarter-luma-pel
    iy, fy = dy >> 3, dy & 7
    ix, fx = dx >> 3, dx & 7
    rows = jnp.arange(hc)[:, None] + iy + pad
    cols = jnp.arange(wc)[None, :] + ix + pad
    a = refp[rows, cols]
    b = refp[rows, cols + 1]
    c = refp[rows + 1, cols]
    d = refp[rows + 1, cols + 1]
    pred = ((8 - fx) * (8 - fy) * a + fx * (8 - fy) * b
            + (8 - fx) * fy * c + fx * fy * d + 32) >> 6
    return pred


# MB decimation weights (x264's dct_decimate idea): a macroblock whose
# quantized luma is nothing but scattered +-1s costs far more to CAVLC-
# code than the energy it restores. Weight each +-1 by how cheap it is
# to represent (low zigzag index = structurally cheap and perceptually
# load-bearing, high index = expensive trailing coefficient), and zero
# the whole MB's luma when the summed score is below threshold. Any
# |level| >= 2 vetoes. Encoder-side freedom: recon stays closed-loop.
from vlog_tpu.codecs.h264.cavlc_tables import ZIGZAG_4x4 as _ZZ

_DECIMATE_W = np.zeros((4, 4), np.int32)
for _zi, (_r, _c) in enumerate(_ZZ):
    _DECIMATE_W[_r, _c] = 3 if _zi <= 2 else (2 if _zi <= 9 else 1)
_DECIMATE_THRESHOLD = 6


def _decimate_mb_luma(levels):
    """levels (mbh, mbw, 4, 4, 4, 4) -> same, with low-score MBs zeroed."""
    absl = jnp.abs(levels)
    veto = jnp.any(absl >= 2, axis=(2, 3, 4, 5))
    score = jnp.sum((absl == 1) * jnp.asarray(_DECIMATE_W), axis=(2, 3, 4, 5))
    keep = veto | (score >= _DECIMATE_THRESHOLD)
    return levels * keep[:, :, None, None, None, None]


def _inter_luma_residual(cur, pred, qp):
    """(H, W) residual -> levels (mbh, mbw, 4, 4, 4, 4) + recon plane."""
    h, w = cur.shape
    mbh, mbw = h // 16, w // 16
    resid = cur.astype(jnp.int32) - pred
    # (H, W) -> (mbh, mbw, 4, 4, 4, 4): MB grid, 4x4 block grid, pixels
    blocks = resid.reshape(mbh, 4, 4, mbw, 4, 4)
    blocks = jnp.transpose(blocks, (0, 3, 1, 4, 2, 5))
    coefs = core_transform(blocks)
    levels = _decimate_mb_luma(quantize(coefs, qp=qp, intra=False))
    rec = inverse_core_transform(dequantize(levels, qp=qp))
    rec = jnp.transpose(rec, (0, 2, 4, 1, 3, 5)).reshape(h, w)
    recon = jnp.clip(pred + rec, 0, 255)
    return levels, recon


def _inter_chroma_residual(cur, pred, qpc):
    """(Hc, Wc) -> (dc (mbh, mbw, 2, 2), ac (mbh, mbw, 2, 2, 4, 4), recon)."""
    hc, wc = cur.shape
    mbh, mbw = hc // 8, wc // 8
    resid = cur.astype(jnp.int32) - pred
    blocks = resid.reshape(mbh, 2, 4, mbw, 2, 4)
    blocks = jnp.transpose(blocks, (0, 3, 1, 4, 2, 5))   # (mbh,mbw,2,2,4,4)
    coefs = core_transform(blocks)
    dc = coefs[..., 0, 0]
    dc_levels = quantize_chroma_dc(hadamard2x2(dc), qp=qpc)
    ac_levels = quantize(coefs, qp=qpc, intra=False)
    ac_levels = ac_levels.at[..., 0, 0].set(0)
    dc_rec = dequantize_chroma_dc(dc_levels, qp=qpc)
    full = dequantize(ac_levels, qp=qpc).at[..., 0, 0].set(dc_rec)
    rec = inverse_core_transform(full)
    rec = jnp.transpose(rec, (0, 2, 4, 1, 3, 5)).reshape(hc, wc)
    recon = jnp.clip(pred + rec, 0, 255)
    return dc_levels, ac_levels, recon


def encode_p_frame(y, u, v, ref_y, ref_u, ref_v, *, qp,
                   search: int = 8):
    """One P frame against one reference (both at the same geometry).

    All MBs are P_L0_16x16 with quarter-pel MVs (skip detection happens
    at entropy time from mv + zero levels). Returns levels, MVs
    (quarter-pel), and the reconstruction that becomes the next frame's
    reference.
    """
    qpc = chroma_qp(qp)
    pad = search + 8
    refp = jnp.pad(ref_y.astype(jnp.int32), pad, mode="edge")
    planes = half_pel_planes(refp)                  # shared search + MC
    mv = motion_search(y, ref_y, search=search, refp=refp,
                       planes=planes)               # quarter-pel units
    pred_y = mc_luma(ref_y, mv, search=search, refp=refp, planes=planes)
    pred_u = mc_chroma(ref_u, mv, search=search)
    pred_v = mc_chroma(ref_v, mv, search=search)
    luma, recon_y = _inter_luma_residual(y.astype(jnp.int32), pred_y, qp)
    udc, uac, recon_u = _inter_chroma_residual(
        u.astype(jnp.int32), pred_u, qpc)
    vdc, vac, recon_v = _inter_chroma_residual(
        v.astype(jnp.int32), pred_v, qpc)
    return {
        "luma": luma,                              # (mbh, mbw, 4,4,4,4)
        "chroma_dc": jnp.stack([udc, vdc]),        # (2, mbh, mbw, 2, 2)
        "chroma_ac": jnp.stack([uac, vac]),        # (2, mbh, mbw, 2,2,4,4)
        "mv": mv,                                  # (mbh, mbw, 2) qtr-pel
        "recon_y": recon_y.astype(jnp.uint8),
        "recon_u": recon_u.astype(jnp.uint8),
        "recon_v": recon_v.astype(jnp.uint8),
    }


def p_frame_levels(out: dict) -> dict:
    """Device output -> host numpy dict for the entropy coder."""
    return {
        "luma": np.asarray(out["luma"], np.int32),
        "chroma_dc": np.asarray(out["chroma_dc"], np.int32),
        "chroma_ac": np.asarray(out["chroma_ac"], np.int32),
        "mv": np.asarray(out["mv"], np.int32),
    }
