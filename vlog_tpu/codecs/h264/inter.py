"""P-frame DSP: motion search, motion compensation, inter residual coding.

The inter half of the TPU encoder (the piece that closes the ~11 dB
all-intra gap QUALITY.md measured against libx264). Design constraints,
TPU-first:

- **Full-search integer motion estimation as a scan over offsets**: for
  each candidate displacement the whole frame's SAD-per-MB is one shifted
  subtract + block-sum — (2s+1)^2 sequential steps of perfectly parallel
  (H, W) work, instead of a per-MB scalar search loop. A small MV-cost
  penalty biases toward short vectors (rate proxy).
- **Half-pel refinement on device**: the three half-sample planes (b, h,
  j — spec 8.4.2.2.1 six-tap) are whole-plane shifted sums computed once
  per reference; the nine candidates around each MB's integer winner are
  then gathers + block-SADs, and motion compensation selects per pixel
  among the four planes by MV fraction. MVs flow through the pipeline in
  HALF-PEL units ((y, x), DSP order).
- **Motion compensation as gathers**: per-MB MVs expand to per-pixel
  index maps over the edge-padded reference/half planes. Chroma follows
  H.264 8.4.2.2.2: luma half-pel MVs land on eighth-pel chroma
  positions, so chroma prediction is the 4-tap bilinear weighting of 4
  gathers with weights 0/2/4/6/8 per axis.
- **Residuals**: inter 4x4 luma transform keeps all 16 coefficients per
  block (no Intra16x16 DC split); chroma keeps the 2x2 DC Hadamard.
  Quantizer rounding uses the inter offset (f = 2^qbits/6) — rounding is
  encoder freedom, dequant stays normative.

Frames chain: ``encode_p_frame`` takes the previous frame's
reconstruction (decoder mirror) as the reference, so streams survive the
libavcodec oracle bit-exactly (tests/test_h264_p.py).

Spec: ITU-T H.264 8.4 (inter prediction), 8.5 (transform). Reference
parity: this replaces x264's ME/MC inside the ffmpeg workers
(worker/hwaccel.py:647).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vlog_tpu.codecs.h264.encoder import chroma_qp
from vlog_tpu.ops.transform import (
    core_transform,
    dequantize,
    dequantize_chroma_dc,
    hadamard2x2,
    inverse_core_transform,
    quantize,
    quantize_chroma_dc,
)

# SAD penalty per quarter-pel of |MV| component — biases the search toward
# short vectors (a stand-in for the MVD rate term in RD cost).
MV_COST_LAMBDA = 4


_SIX_TAP = (1, -5, 20, 20, -5, 1)


def _six_tap_shift(x, axis):
    """Un-normalized 6-tap at half positions: out[i] sits between i and
    i+1 (taps i-2..i+3). jnp.roll wrap contamination reaches 3 (6 after
    the second pass) samples into the pad ring; callers pad by at least
    search+8 so gathered positions never touch it."""
    out = None
    for k, t in enumerate(_SIX_TAP):
        term = t * jnp.roll(x, 2 - k, axis=axis)
        out = term if out is None else out + term
    return out


def half_pel_planes(refp):
    """Edge-padded (Hp, Wp) int32 reference -> (b, h, j) planes, same
    shape/alignment (spec 8.4.2.2.1: b right-half, h down-half, j
    center; j from the un-normalized horizontal intermediates, which is
    exactly the spec's two-stage filter since no clipping intervenes)."""
    b1 = _six_tap_shift(refp, axis=1)
    h1 = _six_tap_shift(refp, axis=0)
    j1 = _six_tap_shift(b1, axis=0)
    b = jnp.clip((b1 + 16) >> 5, 0, 255)
    h = jnp.clip((h1 + 16) >> 5, 0, 255)
    j = jnp.clip((j1 + 512) >> 10, 0, 255)
    return b, h, j


def _gather_halfpel(refp, planes, mv_hp, *, pad, mb=16):
    """Luma prediction at half-pel MVs: per-pixel select among the four
    sample planes by MV fraction, one gather each."""
    bpl, hpl, jpl = planes
    hp = refp.shape[0] - 2 * pad
    wp = refp.shape[1] - 2 * pad
    dy, dx = _mv_maps(mv_hp, mb)
    iy, fy = dy >> 1, dy & 1
    ix, fx = dx >> 1, dx & 1
    rows = jnp.arange(hp)[:, None] + iy + pad
    cols = jnp.arange(wp)[None, :] + ix + pad
    g = refp[rows, cols]
    return jnp.where(
        fy == 0,
        jnp.where(fx == 0, g, bpl[rows, cols]),
        jnp.where(fx == 0, hpl[rows, cols], jpl[rows, cols]))


def motion_search(cur_y, ref_y, *, search: int = 8,
                  lam: int = MV_COST_LAMBDA, refp=None, planes=None):
    """Full-search integer ME + half-pel refinement:
    (H, W) planes -> (mbh, mbw, 2) MVs in HALF-PEL units (y, x).

    Deterministic: ties keep the earlier candidate in raster offset
    order, with (0,0) evaluated first; refinement keeps the integer
    winner on ties.  ``refp``/``planes`` may be precomputed by the
    caller (encode_p_frame shares them with motion compensation).
    """
    h, w = cur_y.shape
    mbh, mbw = h // 16, w // 16
    cur = cur_y.astype(jnp.int32)
    pad = search + 8
    if refp is None:
        refp = jnp.pad(ref_y.astype(jnp.int32), pad, mode="edge")

    offsets = [(0, 0)] + [
        (dy, dx)
        for dy in range(-search, search + 1)
        for dx in range(-search, search + 1)
        if (dy, dx) != (0, 0)
    ]
    offs = jnp.asarray(offsets, jnp.int32)          # (n_off, 2)

    def sad_at(off):
        shifted = jax.lax.dynamic_slice(
            refp, (pad + off[0], pad + off[1]), (h, w))
        d = jnp.abs(cur - shifted)
        sad = d.reshape(mbh, 16, mbw, 16).sum(axis=(1, 3))
        cost = lam * 4 * (jnp.abs(off[0]) + jnp.abs(off[1]))
        return sad + cost

    def step(carry, off):
        best_sad, best_mv = carry
        sad = sad_at(off)
        better = sad < best_sad
        best_sad = jnp.where(better, sad, best_sad)
        best_mv = jnp.where(better[..., None], off[None, None, :], best_mv)
        return (best_sad, best_mv), None

    init = (jnp.full((mbh, mbw), jnp.iinfo(jnp.int32).max, jnp.int32),
            jnp.zeros((mbh, mbw, 2), jnp.int32))
    (int_sad, mv_int), _ = jax.lax.scan(step, init, offs)

    # --- half-pel refinement: eight candidates around the integer
    # winner, seeded with its SAD (the cost scales are commensurate:
    # lam*4*|off_int| == lam*2*|2*off_int|, so no re-evaluation of the
    # base candidate is needed).
    if planes is None:
        planes = half_pel_planes(refp)
    base_hp = mv_int * 2

    def sad_hp(off):
        cand = base_hp + off[None, None, :]
        pred = _gather_halfpel(refp, planes, cand, pad=pad)
        sad = jnp.abs(cur - pred).reshape(mbh, 16, mbw, 16).sum(axis=(1, 3))
        cost = lam * 2 * (jnp.abs(cand[..., 0]) + jnp.abs(cand[..., 1]))
        return sad + cost

    half_offs = jnp.asarray(
        [(dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1)
         if (dy, dx) != (0, 0)], jnp.int32)

    def hstep(carry, off):
        best_sad, best_mv = carry
        sad = sad_hp(off)
        better = sad < best_sad
        best_sad = jnp.where(better, sad, best_sad)
        cand = base_hp + off[None, None, :]
        best_mv = jnp.where(better[..., None], cand, best_mv)
        return (best_sad, best_mv), None

    (_, mv_hp), _ = jax.lax.scan(hstep, (int_sad, base_hp), half_offs)
    return mv_hp


def _mv_maps(mv, mb: int):
    """(mbh, mbw, 2) -> per-pixel (H, W) dy/dx maps for a plane with
    ``mb``-sized macroblocks."""
    dy = jnp.repeat(jnp.repeat(mv[..., 0], mb, axis=0), mb, axis=1)
    dx = jnp.repeat(jnp.repeat(mv[..., 1], mb, axis=0), mb, axis=1)
    return dy, dx


def mc_luma(ref_y, mv_hp, *, search: int, planes=None, refp=None):
    """Luma prediction at half-pel MVs (spec 8.4.2.2.1 six-tap planes).

    ``planes``/``refp`` may be precomputed (encode path: the search just
    built them); the decode path passes only the reference."""
    pad = search + 8
    if refp is None:
        refp = jnp.pad(ref_y.astype(jnp.int32), pad, mode="edge")
    if planes is None:
        planes = half_pel_planes(refp)
    return _gather_halfpel(refp, planes, mv_hp, pad=pad)


def mc_chroma(ref_c, mv_hp, *, search: int):
    """Chroma prediction per 8.4.2.2.2 for half-pel luma MVs.

    The chroma MV equals the luma quarter-pel value interpreted on the
    eighth-chroma-pel grid: q = 2*mv_hp, integer part q>>3, fraction
    q&7 in {0, 2, 4, 6} — the spec's bilinear blend."""
    hc, wc = ref_c.shape
    pad = search // 2 + 2
    refp = jnp.pad(ref_c.astype(jnp.int32), pad, mode="edge")
    dy, dx = _mv_maps(mv_hp, 8)                     # half-luma-pel units
    q_y, q_x = dy * 2, dx * 2                       # eighth-chroma-pel
    iy, fy = q_y >> 3, q_y & 7
    ix, fx = q_x >> 3, q_x & 7
    rows = jnp.arange(hc)[:, None] + iy + pad
    cols = jnp.arange(wc)[None, :] + ix + pad
    a = refp[rows, cols]
    b = refp[rows, cols + 1]
    c = refp[rows + 1, cols]
    d = refp[rows + 1, cols + 1]
    pred = ((8 - fx) * (8 - fy) * a + fx * (8 - fy) * b
            + (8 - fx) * fy * c + fx * fy * d + 32) >> 6
    return pred


def _inter_luma_residual(cur, pred, qp):
    """(H, W) residual -> levels (mbh, mbw, 4, 4, 4, 4) + recon plane."""
    h, w = cur.shape
    mbh, mbw = h // 16, w // 16
    resid = cur.astype(jnp.int32) - pred
    # (H, W) -> (mbh, mbw, 4, 4, 4, 4): MB grid, 4x4 block grid, pixels
    blocks = resid.reshape(mbh, 4, 4, mbw, 4, 4)
    blocks = jnp.transpose(blocks, (0, 3, 1, 4, 2, 5))
    coefs = core_transform(blocks)
    levels = quantize(coefs, qp=qp, intra=False)
    rec = inverse_core_transform(dequantize(levels, qp=qp))
    rec = jnp.transpose(rec, (0, 2, 4, 1, 3, 5)).reshape(h, w)
    recon = jnp.clip(pred + rec, 0, 255)
    return levels, recon


def _inter_chroma_residual(cur, pred, qpc):
    """(Hc, Wc) -> (dc (mbh, mbw, 2, 2), ac (mbh, mbw, 2, 2, 4, 4), recon)."""
    hc, wc = cur.shape
    mbh, mbw = hc // 8, wc // 8
    resid = cur.astype(jnp.int32) - pred
    blocks = resid.reshape(mbh, 2, 4, mbw, 2, 4)
    blocks = jnp.transpose(blocks, (0, 3, 1, 4, 2, 5))   # (mbh,mbw,2,2,4,4)
    coefs = core_transform(blocks)
    dc = coefs[..., 0, 0]
    dc_levels = quantize_chroma_dc(hadamard2x2(dc), qp=qpc)
    ac_levels = quantize(coefs, qp=qpc, intra=False)
    ac_levels = ac_levels.at[..., 0, 0].set(0)
    dc_rec = dequantize_chroma_dc(dc_levels, qp=qpc)
    full = dequantize(ac_levels, qp=qpc).at[..., 0, 0].set(dc_rec)
    rec = inverse_core_transform(full)
    rec = jnp.transpose(rec, (0, 2, 4, 1, 3, 5)).reshape(hc, wc)
    recon = jnp.clip(pred + rec, 0, 255)
    return dc_levels, ac_levels, recon


def encode_p_frame(y, u, v, ref_y, ref_u, ref_v, *, qp,
                   search: int = 8):
    """One P frame against one reference (both at the same geometry).

    All MBs are P_L0_16x16 with half-pel MVs (skip detection happens at
    entropy time from mv + zero levels). Returns levels, MVs (half-pel),
    and the reconstruction that becomes the next frame's reference.
    """
    qpc = chroma_qp(qp)
    pad = search + 8
    refp = jnp.pad(ref_y.astype(jnp.int32), pad, mode="edge")
    planes = half_pel_planes(refp)                  # shared search + MC
    mv = motion_search(y, ref_y, search=search, refp=refp,
                       planes=planes)               # half-pel units
    pred_y = mc_luma(ref_y, mv, search=search, refp=refp, planes=planes)
    pred_u = mc_chroma(ref_u, mv, search=search)
    pred_v = mc_chroma(ref_v, mv, search=search)
    luma, recon_y = _inter_luma_residual(y.astype(jnp.int32), pred_y, qp)
    udc, uac, recon_u = _inter_chroma_residual(
        u.astype(jnp.int32), pred_u, qpc)
    vdc, vac, recon_v = _inter_chroma_residual(
        v.astype(jnp.int32), pred_v, qpc)
    return {
        "luma": luma,                              # (mbh, mbw, 4,4,4,4)
        "chroma_dc": jnp.stack([udc, vdc]),        # (2, mbh, mbw, 2, 2)
        "chroma_ac": jnp.stack([uac, vac]),        # (2, mbh, mbw, 2,2,4,4)
        "mv": mv,                                  # (mbh, mbw, 2) half-pel
        "recon_y": recon_y.astype(jnp.uint8),
        "recon_u": recon_u.astype(jnp.uint8),
        "recon_v": recon_v.astype(jnp.uint8),
    }


def p_frame_levels(out: dict) -> dict:
    """Device output -> host numpy dict for the entropy coder."""
    return {
        "luma": np.asarray(out["luma"], np.int32),
        "chroma_dc": np.asarray(out["chroma_dc"], np.int32),
        "chroma_ac": np.asarray(out["chroma_ac"], np.int32),
        "mv": np.asarray(out["mv"], np.int32),
    }
