"""CAVLC entropy coding + slice assembly for the I_16x16 stream shape.

Host-side half of the encoder: the device (encoder.py) emits quantized
levels for every block of every MB in one XLA dispatch; this module turns
them into spec-compliant slice_data bits. The reference delegated this to
x264 inside ffmpeg (worker/hwaccel.py:647); entropy coding is inherently
sequential bit-packing, so it lives on the host — first as this
numpy/python implementation, with a C++ packer planned behind the same
interface.

Spec: ITU-T H.264 7.3.5 (macroblock layer), 7.4.5, 9.2 (CAVLC).
"""

from __future__ import annotations

import numpy as np

from vlog_tpu.media.bitstream import BitWriter
from vlog_tpu.codecs.h264 import syntax
from vlog_tpu.codecs.h264.cavlc_tables import (
    CHROMA_DC_COEFF_TOKEN_BITS,
    CHROMA_DC_COEFF_TOKEN_LEN,
    CHROMA_DC_TOTAL_ZEROS_BITS,
    CHROMA_DC_TOTAL_ZEROS_LEN,
    COEFF_TOKEN_BITS,
    COEFF_TOKEN_LEN,
    LUMA_BLOCK_ORDER,
    RUN_BEFORE_BITS,
    RUN_BEFORE_LEN,
    TOTAL_ZEROS_BITS,
    TOTAL_ZEROS_LEN,
    ZIGZAG_4x4,
    coeff_token_table,
)

_ZZ_R = np.array([r for r, _ in ZIGZAG_4x4])
_ZZ_C = np.array([c for _, c in ZIGZAG_4x4])


def zigzag(block: np.ndarray) -> np.ndarray:
    """(4,4) -> (16,) in zigzag scan order."""
    return block[_ZZ_R, _ZZ_C]


def encode_residual_block(
    w: BitWriter, coeffs: np.ndarray, nc: int
) -> int:
    """residual_block_cavlc (spec 9.2). ``coeffs`` in scan order.

    ``nc`` is the decoded-neighbour context (-1 selects the chroma DC
    table). Returns TotalCoeff (the caller records it for later nC
    derivation).
    """
    max_coeff = len(coeffs)
    nz_idx = [i for i, c in enumerate(coeffs) if c != 0]
    total_coeff = len(nz_idx)

    # Trailing ones: |1| coefficients at the high-frequency end, max 3.
    trailing = 0
    for i in reversed(nz_idx):
        if abs(int(coeffs[i])) == 1 and trailing < 3:
            trailing += 1
        else:
            break

    # coeff_token
    idx = 4 * total_coeff + trailing
    if nc == -1:
        w.write_bits(int(CHROMA_DC_COEFF_TOKEN_BITS[idx]),
                     int(CHROMA_DC_COEFF_TOKEN_LEN[idx]))
    else:
        tbl = coeff_token_table(nc)
        w.write_bits(int(COEFF_TOKEN_BITS[tbl][idx]),
                     int(COEFF_TOKEN_LEN[tbl][idx]))
    if total_coeff == 0:
        return 0

    # Trailing one signs, high frequency first.
    for i in reversed(nz_idx[total_coeff - trailing:]):
        w.write_bit(1 if coeffs[i] < 0 else 0)

    # Remaining levels, high frequency first.
    suffix_len = 1 if (total_coeff > 10 and trailing < 3) else 0
    first = True
    for i in reversed(nz_idx[: total_coeff - trailing]):
        level = int(coeffs[i])
        code = 2 * level - 2 if level > 0 else -2 * level - 1
        if first and trailing < 3:
            code -= 2
        first = False
        if suffix_len == 0:
            if code < 14:
                w.write_bits(1, code + 1)           # prefix zeros + 1
            elif code < 30:
                w.write_bits(1, 15)                 # level_prefix 14
                w.write_bits(code - 14, 4)
            else:
                w.write_bits(1, 16)                 # level_prefix 15
                w.write_bits(code - 30, 12)
        else:
            if code < (15 << suffix_len):
                w.write_bits(1, (code >> suffix_len) + 1)
                w.write_bits(code & ((1 << suffix_len) - 1), suffix_len)
            else:
                w.write_bits(1, 16)                 # level_prefix 15
                rem = code - (15 << suffix_len)
                if rem >= 1 << 12:
                    raise ValueError(f"level {level} too large for CAVLC escape")
                w.write_bits(rem, 12)
        if suffix_len == 0:
            suffix_len = 1
        if abs(level) > (3 << (suffix_len - 1)) and suffix_len < 6:
            suffix_len += 1

    # total_zeros
    total_zeros = nz_idx[-1] + 1 - total_coeff
    if total_coeff < max_coeff:
        if nc == -1:
            w.write_bits(int(CHROMA_DC_TOTAL_ZEROS_BITS[total_coeff - 1][total_zeros]),
                         int(CHROMA_DC_TOTAL_ZEROS_LEN[total_coeff - 1][total_zeros]))
        else:
            w.write_bits(int(TOTAL_ZEROS_BITS[total_coeff - 1][total_zeros]),
                         int(TOTAL_ZEROS_LEN[total_coeff - 1][total_zeros]))

    # run_before for each coefficient except the lowest-frequency one.
    zeros_left = total_zeros
    for k in range(total_coeff - 1, 0, -1):
        if zeros_left <= 0:
            break
        run = nz_idx[k] - nz_idx[k - 1] - 1
        tbl = min(zeros_left, 7) - 1
        w.write_bits(int(RUN_BEFORE_BITS[tbl][run]),
                     int(RUN_BEFORE_LEN[tbl][run]))
        zeros_left -= run
    return total_coeff


def _nc(avail_a: bool, na: int, avail_b: bool, nb: int) -> int:
    """Neighbour context (spec 9.2.1): nA left, nB above."""
    if avail_a and avail_b:
        return (na + nb + 1) >> 1
    if avail_a:
        return na
    if avail_b:
        return nb
    return 0


class SliceEncoder:
    """Encodes one frame's levels into slice_data bits (single slice).

    Tracks per-4x4-block TotalCoeff grids for nC derivation across MB
    boundaries. Designed so a batch of frames can be encoded in parallel
    host threads (no shared state between instances).
    """

    def __init__(self, mbh: int, mbw: int):
        self.mbh = mbh
        self.mbw = mbw
        # TotalCoeff per luma 4x4 block, global grid.
        self.nz_luma = np.zeros((mbh * 4, mbw * 4), np.int32)
        # Per chroma component, 2x2 blocks per MB.
        self.nz_chroma = np.zeros((2, mbh * 2, mbw * 2), np.int32)

    def encode_macroblock(
        self, w: BitWriter, levels, my: int, mx: int
    ) -> None:
        """macroblock_layer for I_16x16 (spec 7.3.5)."""
        luma_dc = levels.luma_dc[my, mx]          # (4,4) Hadamard domain
        luma_ac = levels.luma_ac[my, mx]          # (4,4,4,4)
        chroma_dc = levels.chroma_dc[:, my, mx]   # (2,2,2)
        chroma_ac = levels.chroma_ac[:, my, mx]   # (2,2,2,4,4)

        cbp_luma = 15 if np.any(luma_ac) else 0
        if np.any(chroma_ac):
            cbp_chroma = 2
        elif np.any(chroma_dc):
            cbp_chroma = 1
        else:
            cbp_chroma = 0

        # Prediction modes: row 0 DC (no neighbours), else Vertical.
        luma_mode = 2 if my == 0 else 0       # Intra_16x16: 0=V, 2=DC
        chroma_mode = 0 if my == 0 else 2     # chroma: 0=DC, 2=V

        mb_type = 1 + luma_mode + 4 * cbp_chroma + 12 * (1 if cbp_luma else 0)
        w.write_ue(mb_type)
        w.write_ue(chroma_mode)               # intra_chroma_pred_mode
        w.write_se(0)                         # mb_qp_delta (constant QP)

        # --- Intra16x16DCLevel: nC from luma 4x4 block (0,0) neighbours.
        gy, gx = my * 4, mx * 4
        nc = _nc(gx > 0, int(self.nz_luma[gy, gx - 1]),
                 gy > 0, int(self.nz_luma[gy - 1, gx]))
        encode_residual_block(w, zigzag(luma_dc), nc)

        # --- Luma AC blocks in coding order.
        if cbp_luma:
            for by, bx in LUMA_BLOCK_ORDER:
                y, x = gy + by, gx + bx
                nc = _nc(x > 0, int(self.nz_luma[y, x - 1]),
                         y > 0, int(self.nz_luma[y - 1, x]))
                tc = encode_residual_block(
                    w, zigzag(luma_ac[by, bx])[1:], nc)
                self.nz_luma[y, x] = tc
        # else: grid entries stay 0 (AC all zero).

        # --- Chroma DC (nC = -1), Cb then Cr.
        if cbp_chroma > 0:
            for comp in range(2):
                dc = chroma_dc[comp]
                encode_residual_block(
                    w, dc.reshape(-1), -1)  # 2x2 raster scan (spec 8.5.11 order)

        # --- Chroma AC, Cb then Cr, 2x2 raster block order.
        if cbp_chroma == 2:
            cy, cx = my * 2, mx * 2
            for comp in range(2):
                for by in range(2):
                    for bx in range(2):
                        y, x = cy + by, cx + bx
                        nc = _nc(x > 0, int(self.nz_chroma[comp, y, x - 1]),
                                 y > 0, int(self.nz_chroma[comp, y - 1, x]))
                        tc = encode_residual_block(
                            w, zigzag(chroma_ac[comp, by, bx])[1:], nc)
                        self.nz_chroma[comp, y, x] = tc


def encode_slice(
    levels,
    *,
    qp: int,
    init_qp: int,
    frame_num: int = 0,
    idr: bool = True,
    idr_pic_id: int = 0,
    log2_max_frame_num: int = 8,
    deblock: bool = False,
) -> syntax.NalUnit:
    """Full slice NAL (header + slice_data) for one frame's levels.

    Uses the native C coder when available (vlog_tpu/native, ~100x the
    throughput of the Python loop — it is the serial host stage of the
    encoder); both paths are bit-identical (tests/test_native.py).
    """
    mbh, mbw = levels.mb_height, levels.mb_width
    w = BitWriter()
    syntax.write_slice_header(
        w, first_mb=0, slice_qp=qp, init_qp=init_qp, idr=idr,
        frame_num=frame_num, idr_pic_id=idr_pic_id,
        log2_max_frame_num=log2_max_frame_num, deblock=deblock,
    )
    nal_type = syntax.NAL_IDR if idr else syntax.NAL_SLICE

    rbsp = _encode_slice_native(levels, w)
    if rbsp is not None:
        return syntax.NalUnit(nal_type, 3, rbsp)

    enc = SliceEncoder(mbh, mbw)
    for my in range(mbh):
        for mx in range(mbw):
            enc.encode_macroblock(w, levels, my, mx)
    w.rbsp_trailing_bits()
    return syntax.NalUnit(nal_type, 3, w.getvalue())


# --------------------------------------------------------------------------
# P slices (P_L0_16x16 / P_Skip)
# --------------------------------------------------------------------------

# Table 9-4 column "Inter": codeNum -> coded_block_pattern.
_CBP_INTER_FROM_CODE = [
    0, 16, 1, 2, 4, 8, 32, 3, 5, 10, 12, 15, 47, 7, 11, 13,
    14, 6, 9, 31, 35, 37, 42, 44, 33, 34, 36, 40, 39, 43, 45, 46,
    17, 18, 20, 24, 19, 21, 26, 28, 23, 27, 29, 30, 22, 25, 38, 41,
]
_CBP_INTER_TO_CODE = {cbp: i for i, cbp in enumerate(_CBP_INTER_FROM_CODE)}

# 4x4 luma block coding order as (i8x8, i4x4) -> (by, bx) within the MB.
_BLK44 = [(0, 0), (0, 1), (1, 0), (1, 1)]


def _median3(a: int, b: int, c: int) -> int:
    return sorted((a, b, c))[1]


class MvPredictor:
    """The spec's MV prediction state machine (8.4.1.3 + 8.4.1.1),
    shared verbatim between the P-slice encoder and decoder so the two
    can never drift. Holds reconstructed MVs in QUARTER pels, (x, y)."""

    def __init__(self, mbh: int, mbw: int):
        self.mbh = mbh
        self.mbw = mbw
        self.mvs = np.zeros((mbh, mbw, 2), np.int32)

    def _neighbor(self, my: int, mx: int):
        """(avail, mv) triplets for A (left), B (top), C (top-right with
        D top-left fallback)."""
        a_ok = mx > 0
        b_ok = my > 0
        c_ok = b_ok and mx < self.mbw - 1
        d_ok = b_ok and mx > 0
        a = self.mvs[my, mx - 1] if a_ok else np.zeros(2, np.int32)
        b = self.mvs[my - 1, mx] if b_ok else np.zeros(2, np.int32)
        if c_ok:
            c_av, c = True, self.mvs[my - 1, mx + 1]
        elif d_ok:
            c_av, c = True, self.mvs[my - 1, mx - 1]
        else:
            c_av, c = False, np.zeros(2, np.int32)
        return (a_ok, a), (b_ok, b), (c_av, c)

    def mv_pred(self, my: int, mx: int) -> tuple[int, int]:
        """Median predictor, 8.4.1.3.1 (single ref list, all-inter)."""
        (a_ok, a), (b_ok, b), (c_ok, c) = self._neighbor(my, mx)
        avail = [(a_ok, a), (b_ok, b), (c_ok, c)]
        matches = [mv for ok, mv in avail if ok]
        if len(matches) == 1:
            return int(matches[0][0]), int(matches[0][1])
        return (_median3(int(a[0]), int(b[0]), int(c[0])),
                _median3(int(a[1]), int(b[1]), int(c[1])))

    def skip_mv(self, my: int, mx: int) -> tuple[int, int]:
        """P_Skip inferred MV, 8.4.1.1."""
        (a_ok, a), (b_ok, b), _ = self._neighbor(my, mx)
        if (not a_ok or not b_ok
                or (a[0] == 0 and a[1] == 0)
                or (b[0] == 0 and b[1] == 0)):
            return 0, 0
        return self.mv_pred(my, mx)


class PSliceEncoder:
    """Encodes one P frame's device outputs into slice_data bits.

    MB modes are P_Skip or P_L0_16x16 with one reference; MVs arrive in
    QUARTER pels from the DSP and are coded as quarter-pel MVDs against
    the spec median predictor (8.4.1.3), with the P_Skip inferred-MV rule
    (8.4.1.1) deciding skippability.
    """

    def __init__(self, mbh: int, mbw: int):
        self.mbh = mbh
        self.mbw = mbw
        self.nz_luma = np.zeros((mbh * 4, mbw * 4), np.int32)
        self.nz_chroma = np.zeros((2, mbh * 2, mbw * 2), np.int32)
        self.mvp = MvPredictor(mbh, mbw)

    @property
    def mvs(self) -> np.ndarray:
        return self.mvp.mvs

    def mv_pred(self, my: int, mx: int) -> tuple[int, int]:
        return self.mvp.mv_pred(my, mx)

    def skip_mv(self, my: int, mx: int) -> tuple[int, int]:
        return self.mvp.skip_mv(my, mx)

    # -- MB layer ---------------------------------------------------------

    def _mb_cbp(self, luma, chroma_dc, chroma_ac, my, mx) -> int:
        bits = 0
        for i8 in range(4):
            gy, gx = _BLK44[i8]
            blk8 = luma[my, mx, 2 * gy:2 * gy + 2, 2 * gx:2 * gx + 2]
            if np.any(blk8):
                bits |= 1 << i8
        if np.any(chroma_ac[:, my, mx]):
            chroma = 2
        elif np.any(chroma_dc[:, my, mx]):
            chroma = 1
        else:
            chroma = 0
        return bits | (chroma << 4)

    def encode_frame(self, w: BitWriter, plevels: dict) -> None:
        """slice_data for one P frame (single slice)."""
        luma = plevels["luma"]            # (mbh, mbw, 4, 4, 4, 4)
        chroma_dc = plevels["chroma_dc"]  # (2, mbh, mbw, 2, 2)
        chroma_ac = plevels["chroma_ac"]  # (2, mbh, mbw, 2, 2, 4, 4)
        mv_q = plevels["mv"]              # (mbh, mbw, 2) quarter-pel (y, x)
        skip_run = 0
        for my in range(self.mbh):
            for mx in range(self.mbw):
                # DSP mv is (dy, dx); bitstream order is (x, y) — both
                # already in quarter pels.
                mvx, mvy = int(mv_q[my, mx, 1]), int(mv_q[my, mx, 0])
                cbp = self._mb_cbp(luma, chroma_dc, chroma_ac, my, mx)
                smx, smy = self.skip_mv(my, mx)
                if cbp == 0 and (mvx, mvy) == (smx, smy):
                    self.mvs[my, mx] = (smx, smy)
                    skip_run += 1
                    continue
                w.write_ue(skip_run)               # mb_skip_run
                skip_run = 0
                pmx, pmy = self.mv_pred(my, mx)
                self.mvs[my, mx] = (mvx, mvy)
                w.write_ue(0)                      # mb_type: P_L0_16x16
                w.write_se(mvx - pmx)              # mvd_l0 x
                w.write_se(mvy - pmy)              # mvd_l0 y
                w.write_ue(_CBP_INTER_TO_CODE[cbp])
                if cbp:
                    w.write_se(0)                  # mb_qp_delta
                    self._residuals(w, luma, chroma_dc, chroma_ac,
                                    my, mx, cbp)
        if skip_run:
            w.write_ue(skip_run)                   # trailing skips

    def _residuals(self, w: BitWriter, luma, chroma_dc, chroma_ac,
                   my, mx, cbp) -> None:
        gy, gx = my * 4, mx * 4
        for i8 in range(4):
            oy, ox = _BLK44[i8]
            for by, bx in ((2 * oy + dy, 2 * ox + dx)
                           for dy, dx in _BLK44):
                y, x = gy + by, gx + bx
                if not (cbp >> i8) & 1:
                    self.nz_luma[y, x] = 0
                    continue
                nc = _nc(x > 0, int(self.nz_luma[y, x - 1]),
                         y > 0, int(self.nz_luma[y - 1, x]))
                tc = encode_residual_block(
                    w, zigzag(luma[my, mx, by, bx]), nc)
                self.nz_luma[y, x] = tc
        cbp_chroma = cbp >> 4
        if cbp_chroma > 0:
            for comp in range(2):
                encode_residual_block(
                    w, chroma_dc[comp, my, mx].reshape(-1), -1)
        cy, cx = my * 2, mx * 2
        for comp in range(2):
            for by in range(2):
                for bx in range(2):
                    y, x = cy + by, cx + bx
                    if cbp_chroma != 2:
                        self.nz_chroma[comp, y, x] = 0
                        continue
                    nc = _nc(x > 0, int(self.nz_chroma[comp, y, x - 1]),
                             y > 0, int(self.nz_chroma[comp, y - 1, x]))
                    tc = encode_residual_block(
                        w, zigzag(chroma_ac[comp, my, mx, by, bx])[1:], nc)
                    self.nz_chroma[comp, y, x] = tc


def encode_p_slice(
    plevels: dict,
    *,
    qp: int,
    init_qp: int,
    frame_num: int,
    log2_max_frame_num: int = 8,
    deblock: bool = False,
) -> syntax.NalUnit:
    """Full P-slice NAL for one frame's inter levels.

    Native C path when available (P frames are GOP_LEN-1 of every chain,
    so this is the steady-state host entropy stage — the Python loop
    profiled ~50x slower); both paths are bit-identical
    (tests/test_native.py)."""
    mbh, mbw = plevels["luma"].shape[:2]
    w = BitWriter()
    syntax.write_slice_header(
        w, first_mb=0, slice_qp=qp, init_qp=init_qp, idr=False,
        frame_num=frame_num, log2_max_frame_num=log2_max_frame_num,
        slice_type=syntax.SLICE_P, deblock=deblock,
    )
    rbsp = _encode_p_slice_native(plevels, w)
    if rbsp is not None:
        return syntax.NalUnit(syntax.NAL_SLICE, 3, rbsp)
    enc = PSliceEncoder(mbh, mbw)
    enc.encode_frame(w, plevels)
    w.rbsp_trailing_bits()
    return syntax.NalUnit(syntax.NAL_SLICE, 3, w.getvalue())


def _encode_p_slice_native(plevels: dict, header: BitWriter) -> bytes | None:
    """C fast path: returns the complete RBSP, or None to fall back."""
    from vlog_tpu.native import get_lib

    lib = get_lib()
    if lib is None:
        return None
    import ctypes

    mbh, mbw = plevels["luma"].shape[:2]
    luma = np.ascontiguousarray(plevels["luma"], np.int32)
    chroma_dc = np.ascontiguousarray(plevels["chroma_dc"], np.int32)
    chroma_ac = np.ascontiguousarray(plevels["chroma_ac"], np.int32)
    mv = np.ascontiguousarray(plevels["mv"], np.int32)
    cap = 64 + mbh * mbw * (384 * 4)
    out = np.empty(cap, np.uint8)
    scratch = np.empty(mbh * 4 * mbw * 4 + 2 * mbh * 2 * mbw * 2
                       + mbh * mbw * 2, np.int32)
    header_bytes = bytes(header._bytes)
    hdr_arr = (np.frombuffer(header_bytes, np.uint8) if header_bytes
               else np.empty(0, np.uint8))

    def ptr(a, t=ctypes.c_int32):
        return a.ctypes.data_as(ctypes.POINTER(t))

    n = lib.vt_cavlc_encode_p_slice(
        ptr(luma), ptr(chroma_dc), ptr(chroma_ac), ptr(mv),
        mbh, mbw,
        ptr(hdr_arr, ctypes.c_uint8), len(header_bytes),
        header._cur, header._nbits,
        ptr(scratch),
        ptr(out, ctypes.c_uint8), cap,
    )
    if n < 0:
        return None
    return out[:n].tobytes()


def _encode_slice_native(levels, header: BitWriter) -> bytes | None:
    """C fast path: returns the complete RBSP, or None to fall back."""
    from vlog_tpu.native import get_lib

    lib = get_lib()
    if lib is None:
        return None
    import ctypes

    mbh, mbw = levels.mb_height, levels.mb_width
    luma_dc = np.ascontiguousarray(levels.luma_dc, np.int32)
    luma_ac = np.ascontiguousarray(levels.luma_ac, np.int32)
    chroma_dc = np.ascontiguousarray(levels.chroma_dc, np.int32)
    chroma_ac = np.ascontiguousarray(levels.chroma_ac, np.int32)
    # Generous bound: worst-case CAVLC expansion of every coefficient.
    cap = 64 + mbh * mbw * (384 * 4)
    out = np.empty(cap, np.uint8)
    scratch = np.empty(mbh * 4 * mbw * 4 + 2 * mbh * 2 * mbw * 2, np.int32)
    header_bytes = bytes(header._bytes)
    hdr_arr = np.frombuffer(header_bytes, np.uint8) if header_bytes else np.empty(0, np.uint8)

    def ptr(a, t=ctypes.c_int32):
        return a.ctypes.data_as(ctypes.POINTER(t))

    n = lib.vt_cavlc_encode_slice(
        ptr(luma_dc), ptr(luma_ac), ptr(chroma_dc), ptr(chroma_ac),
        mbh, mbw,
        ptr(hdr_arr, ctypes.c_uint8), len(header_bytes),
        header._cur, header._nbits,
        ptr(scratch),
        ptr(out, ctypes.c_uint8), cap,
    )
    if n < 0:
        return None
    return out[:n].tobytes()
