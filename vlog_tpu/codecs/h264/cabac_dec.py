"""H.264 CABAC decode for the first-party decoder (I16x16 / P_L0_16x16).

Mirror image of cabac_enc.py so the framework's own CABAC streams stay
inside the first-party decode envelope (self-transcode, sprites,
segment verification) without falling back to the libav shim. The
context derivations and neighbor grids are the same shapes as the
encoder's; the arithmetic decoder is spec 9.3.3.2.

Outputs the same levels dicts as the CAVLC decode paths, with the same
envelope validations (vertical-scan prediction layout, zero qp_delta).
"""

from __future__ import annotations

import numpy as np

from vlog_tpu.codecs.h264.cabac_enc import (
    _BLK44,
    _CBF_BASE,
    _CBF_CAT,
    _LAST_BASE,
    _LVL_BASE,
    _LVL_CAT,
    _SIG_BASE,
    _SIGLAST_CAT,
    _SliceState,
    cbf_ctx_inc,
    init_states_264,
)
from vlog_tpu.codecs.h264.cavlc import MvPredictor
from vlog_tpu.codecs.h264.cavlc_tables import LUMA_BLOCK_ORDER, ZIGZAG_4x4
from vlog_tpu.codecs.hevc.tables import (
    RANGE_TAB_LPS,
    TRANS_IDX_LPS,
    TRANS_IDX_MPS,
)

_ZZ16 = [r * 4 + c for r, c in ZIGZAG_4x4]
_UNZZ = np.argsort(_ZZ16)


def _unzigzag16(scan: np.ndarray) -> np.ndarray:
    return np.asarray(scan)[_UNZZ].reshape(4, 4)


class CabacDecodeError(ValueError):
    pass


class H264CabacDecoder:
    """Arithmetic decoding engine (9.3.3.2) over a byte buffer."""

    def __init__(self, data: bytes, slice_qp: int, *, i_slice: bool,
                 cabac_init_idc: int = 0) -> None:
        self.pstate, self.mps = init_states_264(
            slice_qp, i_slice=i_slice, cabac_init_idc=cabac_init_idc)
        self.data = data
        self.pos = 0
        self.range = 510
        self.offset = 0
        for _ in range(9):
            self.offset = (self.offset << 1) | self._bit()

    def _bit(self) -> int:
        byte = self.data[self.pos >> 3] if (self.pos >> 3) < len(
            self.data) else 0
        bit = (byte >> (7 - (self.pos & 7))) & 1
        self.pos += 1
        return bit

    def decode_bin(self, ctx: int) -> int:
        p = self.pstate[ctx]
        rlps = RANGE_TAB_LPS[p][(self.range >> 6) & 3]
        self.range -= rlps
        if self.offset >= self.range:
            bin_val = 1 - self.mps[ctx]
            self.offset -= self.range
            self.range = rlps
            if p == 0:
                self.mps[ctx] ^= 1
            self.pstate[ctx] = TRANS_IDX_LPS[p]
        else:
            bin_val = self.mps[ctx]
            self.pstate[ctx] = TRANS_IDX_MPS[p]
        while self.range < 256:
            self.range <<= 1
            self.offset = (self.offset << 1) | self._bit()
        return bin_val

    def decode_bypass(self) -> int:
        self.offset = (self.offset << 1) | self._bit()
        if self.offset >= self.range:
            self.offset -= self.range
            return 1
        return 0

    def decode_terminate(self) -> int:
        self.range -= 2
        if self.offset >= self.range:
            return 1
        while self.range < 256:
            self.range <<= 1
            self.offset = (self.offset << 1) | self._bit()
        return 0

    def eg_bypass(self, k: int) -> int:
        value = 0
        while self.decode_bypass():
            value += 1 << k
            k += 1
        for i in range(k - 1, -1, -1):
            value += self.decode_bypass() << i
        return value


class _Reader:
    """Residual + MB-layer parse, mirroring cabac_enc's derivations."""

    def __init__(self, c: H264CabacDecoder, mbh: int, mbw: int):
        self.c = c
        self.st = _SliceState(mbh, mbw)

    def cbf_inc(self, cat, my, mx, comp, by, bx, cur_intra):
        return cbf_ctx_inc(self.st, cat, my, mx, comp, by, bx, cur_intra)

    def residual_block(self, cat: int, n: int, my: int, mx: int, *,
                       comp: int = 0, by: int = 0, bx: int = 0,
                       cur_intra: bool = True) -> np.ndarray:
        c = self.c
        coeffs = np.zeros(n, np.int32)
        ctx = _CBF_BASE + _CBF_CAT[cat] + self.cbf_inc(
            cat, my, mx, comp, by, bx, cur_intra)
        if not c.decode_bin(ctx):
            return coeffs
        sig = []
        for i in range(n - 1):
            inc = min(i, 2) if cat == 3 else i
            if c.decode_bin(_SIG_BASE + _SIGLAST_CAT[cat] + inc):
                sig.append(i)
                if c.decode_bin(_LAST_BASE + _SIGLAST_CAT[cat] + inc):
                    break
        else:
            sig.append(n - 1)       # reached the end: last pos implicit
        num_eq1 = 0
        num_gt1 = 0
        for i in reversed(sig):
            base = _LVL_BASE + _LVL_CAT[cat]
            inc0 = 0 if num_gt1 > 0 else min(4, 1 + num_eq1)
            val = c.decode_bin(base + inc0)
            if val:
                inc_gt = 5 + min(4, num_gt1)
                mag = 1
                while mag < 14 and c.decode_bin(base + inc_gt):
                    mag += 1
                if mag == 14:
                    mag += c.eg_bypass(0)
                num_gt1 += 1
            else:
                mag = 0
                num_eq1 += 1
            level = mag + 1
            if c.decode_bypass():
                level = -level
            coeffs[i] = level
        return coeffs


def decode_slice_data_cabac(data: bytes, sps, header) -> dict:
    """CABAC I-slice counterpart of decoder.decode_slice_data."""
    from vlog_tpu.codecs.h264.decoder import UnsupportedStream

    mbh, mbw = sps.mb_height, sps.mb_width
    if header.first_mb != 0:
        raise UnsupportedStream("multi-slice pictures not supported")
    c = H264CabacDecoder(data, header.qp, i_slice=True)
    rd = _Reader(c, mbh, mbw)
    st = rd.st
    luma_dc = np.zeros((mbh, mbw, 4, 4), np.int32)
    luma_ac = np.zeros((mbh, mbw, 4, 4, 4, 4), np.int32)
    chroma_dc = np.zeros((2, mbh, mbw, 2, 2), np.int32)
    chroma_ac = np.zeros((2, mbh, mbw, 2, 2, 4, 4), np.int32)

    for my in range(mbh):
        for mx in range(mbw):
            ca = 1 if mx > 0 else 0
            cb = 1 if my > 0 else 0
            if not c.decode_bin(3 + ca + cb):
                raise UnsupportedStream("I_4x4 outside decode envelope")
            if c.decode_terminate():
                raise UnsupportedStream("I_PCM outside decode envelope")
            cbp_luma = 15 if c.decode_bin(6) else 0
            cbp_chroma = 0
            if c.decode_bin(7):
                cbp_chroma = 2 if c.decode_bin(8) else 1
            luma_mode = (c.decode_bin(9) << 1) | c.decode_bin(10)
            ia = 1 if mx > 0 and st.chroma_mode[my, mx - 1] != 0 else 0
            ib = 1 if my > 0 and st.chroma_mode[my - 1, mx] != 0 else 0
            chroma_mode = 0
            if c.decode_bin(64 + ia + ib):
                chroma_mode = 1
                if c.decode_bin(67):
                    chroma_mode = 2
                    if c.decode_bin(67):
                        chroma_mode = 3
            exp_luma = 2 if my == 0 else 0
            exp_chroma = 0 if my == 0 else 2
            if luma_mode != exp_luma or chroma_mode != exp_chroma:
                raise UnsupportedStream(
                    f"prediction layout mismatch at MB ({my},{mx})")
            inc = 1 if st.prev_qp_delta_nz else 0
            if c.decode_bin(60 + inc):
                raise UnsupportedStream("mb_qp_delta != 0 not supported")
            st.prev_qp_delta_nz = False

            sc = rd.residual_block(0, 16, my, mx)
            st.cbf_lumadc[my, mx] = int(np.any(sc))
            luma_dc[my, mx] = _unzigzag16(sc)
            if cbp_luma:
                for by, bx in LUMA_BLOCK_ORDER:
                    sc = rd.residual_block(1, 15, my, mx, by=by, bx=bx)
                    full = np.zeros(16, np.int32)
                    full[1:] = sc
                    luma_ac[my, mx, by, bx] = _unzigzag16(full)
                    st.cbf_luma44[my * 4 + by, mx * 4 + bx] = int(
                        np.any(sc))
            if cbp_chroma > 0:
                for comp in range(2):
                    dc = rd.residual_block(3, 4, my, mx, comp=comp)
                    chroma_dc[comp, my, mx] = dc.reshape(2, 2)
                    st.cbf_chdc[comp, my, mx] = int(np.any(dc))
            if cbp_chroma == 2:
                for comp in range(2):
                    for by in range(2):
                        for bx in range(2):
                            sc = rd.residual_block(4, 15, my, mx,
                                                   comp=comp, by=by, bx=bx)
                            full = np.zeros(16, np.int32)
                            full[1:] = sc
                            chroma_ac[comp, my, mx, by, bx] = _unzigzag16(
                                full)
                            st.cbf_ch44[comp, my * 2 + by,
                                        mx * 2 + bx] = int(np.any(sc))
            st.intra[my, mx] = True
            st.i16[my, mx] = True
            st.chroma_mode[my, mx] = chroma_mode
            last = c.decode_terminate()
            if last != (1 if my == mbh - 1 and mx == mbw - 1 else 0):
                raise UnsupportedStream("end_of_slice_flag misplaced")
    return {"luma_dc": luma_dc, "luma_ac": luma_ac,
            "chroma_dc": chroma_dc, "chroma_ac": chroma_ac}


def decode_p_slice_data_cabac(data: bytes, sps, header) -> dict:
    """CABAC P-slice counterpart of decoder.decode_p_slice_data."""
    from vlog_tpu.codecs.h264.decoder import UnsupportedStream

    mbh, mbw = sps.mb_height, sps.mb_width
    if header.first_mb != 0:
        raise UnsupportedStream("multi-slice pictures not supported")
    c = H264CabacDecoder(data, header.qp, i_slice=False)
    rd = _Reader(c, mbh, mbw)
    st = rd.st
    luma = np.zeros((mbh, mbw, 4, 4, 4, 4), np.int32)
    chroma_dc = np.zeros((2, mbh, mbw, 2, 2), np.int32)
    chroma_ac = np.zeros((2, mbh, mbw, 2, 2, 4, 4), np.int32)
    mvp = MvPredictor(mbh, mbw)
    cbp8 = np.zeros((mbh * 2, mbw * 2), np.int32)

    for my in range(mbh):
        for mx in range(mbw):
            ca = 1 if mx > 0 and not st.skip[my, mx - 1] else 0
            cb = 1 if my > 0 and not st.skip[my - 1, mx] else 0
            if c.decode_bin(11 + ca + cb):
                mvp.mvs[my, mx] = mvp.skip_mv(my, mx)
                st.skip[my, mx] = True
                if c.decode_terminate() != (
                        1 if my == mbh - 1 and mx == mbw - 1 else 0):
                    raise UnsupportedStream("end_of_slice misplaced")
                continue
            if c.decode_bin(14) or c.decode_bin(15) or c.decode_bin(16):
                raise UnsupportedStream(
                    "P mb_type outside P_L0_16x16 envelope")
            pmx, pmy = mvp.mv_pred(my, mx)
            mvd = [0, 0]
            for comp, base in ((0, 40), (1, 47)):
                amvd = 0
                if mx > 0:
                    amvd += int(st.mvd[my, mx - 1, comp])
                if my > 0:
                    amvd += int(st.mvd[my - 1, mx, comp])
                inc = 0 if amvd < 3 else (1 if amvd <= 32 else 2)
                if c.decode_bin(base + inc):
                    val = 1
                    while val < 9 and c.decode_bin(base + 2 + min(val, 4)):
                        val += 1
                    if val == 9:
                        val += c.eg_bypass(3)
                    if c.decode_bypass():
                        val = -val
                else:
                    val = 0
                mvd[comp] = val
                st.mvd[my, mx, comp] = abs(val)
            mvx, mvy = pmx + mvd[0], pmy + mvd[1]
            mvp.mvs[my, mx] = (mvx, mvy)

            cbp = 0
            for i8 in range(4):
                gy, gx = _BLK44[i8]
                y8, x8 = my * 2 + gy, mx * 2 + gx
                a = 1 if x8 > 0 and cbp8[y8, x8 - 1] == 0 else 0
                b = 1 if y8 > 0 and cbp8[y8 - 1, x8] == 0 else 0
                bit = c.decode_bin(73 + a + 2 * b)
                cbp |= bit << i8
                cbp8[y8, x8] = bit
            ca = 1 if mx > 0 and st.cbp_chroma[my, mx - 1] != 0 else 0
            cb = 1 if my > 0 and st.cbp_chroma[my - 1, mx] != 0 else 0
            cbp_chroma = 0
            if c.decode_bin(77 + ca + 2 * cb):
                ca = 1 if mx > 0 and st.cbp_chroma[my, mx - 1] == 2 else 0
                cb = 1 if my > 0 and st.cbp_chroma[my - 1, mx] == 2 else 0
                cbp_chroma = 2 if c.decode_bin(81 + ca + 2 * cb) else 1
            st.cbp_chroma[my, mx] = cbp_chroma

            if cbp or cbp_chroma:
                inc = 1 if st.prev_qp_delta_nz else 0
                if c.decode_bin(60 + inc):
                    raise UnsupportedStream("mb_qp_delta != 0")
                st.prev_qp_delta_nz = False
                for i8 in range(4):
                    oy, ox = _BLK44[i8]
                    for dy, dx in _BLK44:
                        by, bx = 2 * oy + dy, 2 * ox + dx
                        if not (cbp >> i8) & 1:
                            st.cbf_luma44[my * 4 + by, mx * 4 + bx] = 0
                            continue
                        sc = rd.residual_block(2, 16, my, mx, by=by,
                                               bx=bx, cur_intra=False)
                        luma[my, mx, by, bx] = _unzigzag16(sc)
                        st.cbf_luma44[my * 4 + by, mx * 4 + bx] = int(
                            np.any(sc))
                if cbp_chroma > 0:
                    for comp in range(2):
                        dc = rd.residual_block(3, 4, my, mx, comp=comp,
                                               cur_intra=False)
                        chroma_dc[comp, my, mx] = dc.reshape(2, 2)
                        st.cbf_chdc[comp, my, mx] = int(np.any(dc))
                for comp in range(2):
                    for by in range(2):
                        for bx in range(2):
                            if cbp_chroma != 2:
                                st.cbf_ch44[comp, my * 2 + by,
                                            mx * 2 + bx] = 0
                                continue
                            sc = rd.residual_block(4, 15, my, mx,
                                                   comp=comp, by=by,
                                                   bx=bx, cur_intra=False)
                            full = np.zeros(16, np.int32)
                            full[1:] = sc
                            chroma_ac[comp, my, mx, by, bx] = _unzigzag16(
                                full)
                            st.cbf_ch44[comp, my * 2 + by,
                                        mx * 2 + bx] = int(np.any(sc))
            if c.decode_terminate() != (
                    1 if my == mbh - 1 and mx == mbw - 1 else 0):
                raise UnsupportedStream("end_of_slice misplaced")
    return {"luma": luma, "chroma_dc": chroma_dc, "chroma_ac": chroma_ac,
            "mv_q": np.ascontiguousarray(mvp.mvs)}
