"""H.264 intra decoder: CAVLC parse on host, reconstruction in JAX.

The decode half of the transcode pipeline. The reference shells out to
ffmpeg for decode (worker/transcoder.py:1006 runs one ffmpeg per quality,
which internally decodes the source once per process); here decode is a
first-party stage: NAL/slice parsing and CAVLC entropy decode run on the
host (sequential bit work), and pixel reconstruction — dequantize, inverse
transforms, intra prediction — runs as one XLA program per frame batch,
the mirror image of ``encoder.encode_gop``.

Scope: Constrained Baseline, all-intra, CAVLC, 4:2:0, frame MBs, the
prediction-mode layout our encoder emits (MB row 0: Intra_16x16 DC +
chroma DC; rows below: Intra_16x16 Vertical + chroma Vertical), deblocking
off. Streams outside this envelope raise :class:`UnsupportedStream` — the
backend layer treats that the way the reference treats an input ffmpeg
cannot decode (transcoder.py:706-758 error path).

Spec: ITU-T H.264 7.3 (syntax), 9.1 (Exp-Golomb), 9.2 (CAVLC), 8.3
(intra prediction), 8.5 (transforms).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vlog_tpu.media.bitstream import BitReader, unescape_emulation
from vlog_tpu.codecs.h264 import syntax
from vlog_tpu.codecs.h264.cavlc_tables import (
    CHROMA_DC_COEFF_TOKEN_BITS,
    CHROMA_DC_COEFF_TOKEN_LEN,
    CHROMA_DC_TOTAL_ZEROS_BITS,
    CHROMA_DC_TOTAL_ZEROS_LEN,
    COEFF_TOKEN_BITS,
    COEFF_TOKEN_LEN,
    LUMA_BLOCK_ORDER,
    RUN_BEFORE_BITS,
    RUN_BEFORE_LEN,
    TOTAL_ZEROS_BITS,
    TOTAL_ZEROS_LEN,
    coeff_token_table,
)
from vlog_tpu.codecs.h264.cavlc import _ZZ_C, _ZZ_R, _nc
from vlog_tpu.codecs.h264.encoder import chroma_qp
from vlog_tpu.ops.transform import (
    dequantize,
    dequantize_chroma_dc,
    dequantize_luma_dc,
    inverse_core_transform,
)


class DecodeError(ValueError):
    """Malformed bitstream."""


class UnsupportedStream(DecodeError):
    """Valid H.264, but outside this decoder's envelope."""


# --------------------------------------------------------------------------
# Inverse VLC tables: {(length, bits): value}, built once at import.
# --------------------------------------------------------------------------

def _invert(bits: np.ndarray, lens: np.ndarray) -> dict[tuple[int, int], int]:
    out: dict[tuple[int, int], int] = {}
    flat_b = np.asarray(bits).reshape(-1)
    flat_l = np.asarray(lens).reshape(-1)
    for idx in range(flat_b.shape[0]):
        ln = int(flat_l[idx])
        if ln > 0:
            out[(ln, int(flat_b[idx]))] = idx
    return out

_COEFF_TOKEN_INV = [_invert(COEFF_TOKEN_BITS[t], COEFF_TOKEN_LEN[t]) for t in range(4)]
_CHROMA_DC_COEFF_TOKEN_INV = _invert(CHROMA_DC_COEFF_TOKEN_BITS, CHROMA_DC_COEFF_TOKEN_LEN)
_TOTAL_ZEROS_INV = [_invert(TOTAL_ZEROS_BITS[i], TOTAL_ZEROS_LEN[i]) for i in range(16)]
_CHROMA_DC_TOTAL_ZEROS_INV = [
    _invert(CHROMA_DC_TOTAL_ZEROS_BITS[i], CHROMA_DC_TOTAL_ZEROS_LEN[i]) for i in range(3)
]
_RUN_BEFORE_INV = [_invert(RUN_BEFORE_BITS[i], RUN_BEFORE_LEN[i]) for i in range(7)]


def _read_vlc(r: BitReader, table: dict[tuple[int, int], int], what: str,
              max_len: int = 16) -> int:
    """Read one prefix-free codeword by extending bit by bit."""
    bits = 0
    for ln in range(1, max_len + 1):
        bits = (bits << 1) | r.read_bit()
        hit = table.get((ln, bits))
        if hit is not None:
            return hit
    raise DecodeError(f"no {what} codeword within {max_len} bits")


# --------------------------------------------------------------------------
# High-level syntax parsing (inverse of syntax.py writers)
# --------------------------------------------------------------------------

def split_annexb(data: bytes) -> list[tuple[int, int, bytes]]:
    """Annex-B stream -> [(nal_type, nal_ref_idc, rbsp)] (unescaped)."""
    nals = []
    n = len(data)
    starts = []
    i = data.find(b"\x00\x00\x01")
    while i != -1:
        starts.append(i + 3)
        i = data.find(b"\x00\x00\x01", i + 3)
    for k, s in enumerate(starts):
        end = n
        if k + 1 < len(starts):
            end = starts[k + 1] - 3
            # Strip all trailing_zero_8bits before the next start code
            # (safe: rbsp_trailing_bits guarantees a nonzero final byte).
            while end > s and data[end - 1] == 0:
                end -= 1
        raw = data[s:end]
        if not raw:
            continue
        header = raw[0]
        nals.append((header & 0x1F, (header >> 5) & 3, unescape_emulation(raw[1:])))
    return nals


def split_avcc(sample: bytes, length_size: int = 4) -> list[tuple[int, int, bytes]]:
    """Length-prefixed (AVCC) sample -> [(nal_type, ref_idc, rbsp)]."""
    nals = []
    pos = 0
    n = len(sample)
    while pos + length_size <= n:
        ln = int.from_bytes(sample[pos:pos + length_size], "big")
        pos += length_size
        if ln == 0 or pos + ln > n:
            raise DecodeError("bad AVCC length field")
        raw = sample[pos:pos + ln]
        pos += ln
        header = raw[0]
        nals.append((header & 0x1F, (header >> 5) & 3, unescape_emulation(raw[1:])))
    return nals


@dataclass(frozen=True)
class Sps:
    profile_idc: int
    level_idc: int
    sps_id: int
    log2_max_frame_num: int
    pic_order_cnt_type: int
    mb_width: int
    mb_height: int
    crop_left: int
    crop_right: int
    crop_top: int
    crop_bottom: int

    @property
    def width(self) -> int:
        return self.mb_width * 16 - 2 * (self.crop_left + self.crop_right)

    @property
    def height(self) -> int:
        return self.mb_height * 16 - 2 * (self.crop_top + self.crop_bottom)


@dataclass(frozen=True)
class Pps:
    pps_id: int
    sps_id: int
    entropy_coding_mode: int
    init_qp: int
    chroma_qp_index_offset: int
    deblocking_filter_control_present: bool


def parse_sps(rbsp: bytes) -> Sps:
    r = BitReader(rbsp)
    profile = r.read_bits(8)
    r.read_bits(8)  # constraint flags + reserved
    level = r.read_bits(8)
    sps_id = r.read_ue()
    if profile in (100, 110, 122, 244, 44, 83, 86, 118, 128):
        chroma_format = r.read_ue()
        if chroma_format == 3:
            r.read_bit()
        r.read_ue()  # bit_depth_luma_minus8
        r.read_ue()  # bit_depth_chroma_minus8
        r.read_bit()  # qpprime_y_zero_transform_bypass
        if r.read_bit():  # seq_scaling_matrix_present
            raise UnsupportedStream("scaling matrices not supported")
        if chroma_format != 1:
            raise UnsupportedStream("only 4:2:0 supported")
    log2_mfn = r.read_ue() + 4
    poc_type = r.read_ue()
    if poc_type == 0:
        r.read_ue()  # log2_max_pic_order_cnt_lsb_minus4
    elif poc_type == 1:
        r.read_bit()
        r.read_se()
        r.read_se()
        for _ in range(r.read_ue()):
            r.read_se()
    r.read_ue()   # max_num_ref_frames
    r.read_bit()  # gaps_in_frame_num_value_allowed
    mbw = r.read_ue() + 1
    mbh_units = r.read_ue() + 1
    frame_mbs_only = r.read_bit()
    if not frame_mbs_only:
        raise UnsupportedStream("interlaced (field) coding not supported")
    mbh = mbh_units
    r.read_bit()  # direct_8x8_inference
    crop = [0, 0, 0, 0]
    if r.read_bit():
        crop = [r.read_ue() for _ in range(4)]  # l, r, t, b
    return Sps(profile, level, sps_id, log2_mfn, poc_type, mbw, mbh,
               crop[0], crop[1], crop[2], crop[3])


def parse_pps(rbsp: bytes) -> Pps:
    r = BitReader(rbsp)
    pps_id = r.read_ue()
    sps_id = r.read_ue()
    entropy = r.read_bit()      # 1 = CABAC (codecs/h264/cabac_dec.py)
    r.read_bit()  # bottom_field_pic_order_in_frame_present
    if r.read_ue() != 0:
        raise UnsupportedStream("slice groups not supported")
    r.read_ue()   # num_ref_idx_l0
    r.read_ue()   # num_ref_idx_l1
    r.read_bit()  # weighted_pred
    r.read_bits(2)
    init_qp = r.read_se() + 26
    r.read_se()   # pic_init_qs
    chroma_qp_off = r.read_se()
    if chroma_qp_off != 0:
        raise UnsupportedStream("chroma_qp_index_offset != 0 not supported")
    deblock_ctrl = bool(r.read_bit())
    r.read_bit()  # constrained_intra_pred_flag (no effect on all-intra)
    if r.read_bit():
        raise UnsupportedStream("redundant_pic_cnt_present_flag not supported")
    return Pps(pps_id, sps_id, entropy, init_qp, chroma_qp_off, deblock_ctrl)


@dataclass
class SliceHeader:
    first_mb: int
    slice_type: int
    pps_id: int
    frame_num: int
    idr: bool
    qp: int
    deblock: bool = False   # disable_deblocking_filter_idc == 0


def parse_slice_header(r: BitReader, sps: Sps, pps: Pps, nal_type: int,
                       nal_ref_idc: int) -> SliceHeader:
    first_mb = r.read_ue()
    slice_type = r.read_ue()
    if slice_type % 5 not in (0, 2):
        raise UnsupportedStream(
            f"only I/P slices supported (slice_type {slice_type})")
    is_p = slice_type % 5 == 0
    pps_id = r.read_ue()
    frame_num = r.read_bits(sps.log2_max_frame_num)
    idr = nal_type == syntax.NAL_IDR
    if idr:
        r.read_ue()  # idr_pic_id
    if sps.pic_order_cnt_type != 2:
        raise UnsupportedStream(
            f"pic_order_cnt_type {sps.pic_order_cnt_type} not supported")
    if is_p:
        if r.read_bit():                 # num_ref_idx_active_override_flag
            if r.read_ue() != 0:         # num_ref_idx_l0_active_minus1
                raise UnsupportedStream("multiple reference frames")
        if r.read_bit():                 # ref_pic_list_modification_flag_l0
            raise UnsupportedStream("ref pic list modification")
    if nal_ref_idc != 0:
        if idr:
            r.read_bit()  # no_output_of_prior_pics
            r.read_bit()  # long_term_reference
        else:
            if r.read_bit():
                raise UnsupportedStream("adaptive ref pic marking not supported")
    if pps.entropy_coding_mode and is_p:
        if r.read_ue() != 0:             # cabac_init_idc
            raise UnsupportedStream("cabac_init_idc != 0 not supported")
    qp = pps.init_qp + r.read_se()
    deblock = False
    if pps.deblocking_filter_control_present:
        idc = r.read_ue()
        if idc == 0:
            deblock = True
            if r.read_se() != 0 or r.read_se() != 0:
                raise UnsupportedStream(
                    "nonzero deblocking alpha/beta offsets not supported")
        elif idc != 1:
            raise UnsupportedStream(f"deblocking idc {idc} not supported")
    return SliceHeader(first_mb, slice_type, pps_id, frame_num, idr, qp,
                       deblock)


# --------------------------------------------------------------------------
# CAVLC residual decode (inverse of cavlc.encode_residual_block)
# --------------------------------------------------------------------------

def decode_residual_block(r: BitReader, nc: int, max_coeff: int) -> np.ndarray:
    """residual_block_cavlc (spec 9.2) -> coefficients in scan order."""
    coeffs = np.zeros(max_coeff, np.int32)
    if nc == -1:
        idx = _read_vlc(r, _CHROMA_DC_COEFF_TOKEN_INV, "chroma coeff_token", 8)
        total_coeff, trailing = idx >> 2, idx & 3
    else:
        tbl = coeff_token_table(nc)
        idx = _read_vlc(r, _COEFF_TOKEN_INV[tbl], "coeff_token", 16)
        total_coeff, trailing = idx >> 2, idx & 3
    if total_coeff == 0:
        return coeffs
    if total_coeff > max_coeff:
        raise DecodeError("TotalCoeff exceeds block size")

    # Values, highest frequency first: trailing ±1s then coded levels.
    values: list[int] = []
    for _ in range(trailing):
        values.append(-1 if r.read_bit() else 1)
    suffix_len = 1 if (total_coeff > 10 and trailing < 3) else 0
    for i in range(total_coeff - trailing):
        prefix = 0
        while r.read_bit() == 0:
            prefix += 1
            if prefix > 32:
                raise DecodeError("level_prefix overflow")
        if prefix <= 15:
            if suffix_len == 0:
                if prefix < 14:
                    code = prefix
                elif prefix == 14:
                    code = 14 + r.read_bits(4)
                else:
                    code = 30 + r.read_bits(12)
            else:
                if prefix < 15:
                    code = (prefix << suffix_len) + r.read_bits(suffix_len)
                else:
                    code = (15 << suffix_len) + r.read_bits(12)
        else:
            # spec 9.2.2.1: prefix >= 16 extends the escape range
            code = (15 << max(suffix_len, 1)) + r.read_bits(prefix - 3)
            code += (1 << (prefix - 3)) - 4096
        if i == 0 and trailing < 3:
            code += 2
        level = (code + 2) >> 1 if code % 2 == 0 else -((code + 1) >> 1)
        values.append(level)
        if suffix_len == 0:
            suffix_len = 1
        if abs(level) > (3 << (suffix_len - 1)) and suffix_len < 6:
            suffix_len += 1

    # Positions: total_zeros + run_before.
    if total_coeff < max_coeff:
        if nc == -1:
            total_zeros = _read_vlc(
                r, _CHROMA_DC_TOTAL_ZEROS_INV[total_coeff - 1], "chroma total_zeros", 8)
        else:
            total_zeros = _read_vlc(
                r, _TOTAL_ZEROS_INV[total_coeff - 1], "total_zeros", 9)
    else:
        total_zeros = 0

    pos = total_coeff - 1 + total_zeros          # scan index of highest-freq coeff
    zeros_left = total_zeros
    for k, val in enumerate(values):
        coeffs[pos] = val
        if k == total_coeff - 1:
            break
        if zeros_left > 0:
            run = _read_vlc(r, _RUN_BEFORE_INV[min(zeros_left, 7) - 1],
                            "run_before", 11)
        else:
            run = 0
        pos -= run + 1
        zeros_left -= run
        if pos < 0:
            raise DecodeError("run_before underflow")
    return coeffs


def _unzigzag(scan: np.ndarray) -> np.ndarray:
    block = np.zeros((4, 4), np.int32)
    block[_ZZ_R, _ZZ_C] = scan
    return block


# --------------------------------------------------------------------------
# Slice decode -> levels arrays (mirror of cavlc.SliceEncoder)
# --------------------------------------------------------------------------

# Intra16x16 pred modes by position in our layout (see encoder.py docstring)
_ROW0_LUMA_MODE, _ROW0_CHROMA_MODE = 2, 0       # DC
_BODY_LUMA_MODE, _BODY_CHROMA_MODE = 0, 2       # Vertical


def decode_slice_data(r: BitReader, sps: Sps, header: SliceHeader) -> dict:
    """Decode one full-frame I slice into levels arrays.

    Verifies the prediction-mode layout matches the vertical-scan envelope
    the JAX reconstruction implements.
    """
    mbh, mbw = sps.mb_height, sps.mb_width
    if header.first_mb != 0:
        raise UnsupportedStream("multi-slice pictures not supported")
    luma_dc = np.zeros((mbh, mbw, 4, 4), np.int32)
    luma_ac = np.zeros((mbh, mbw, 4, 4, 4, 4), np.int32)
    chroma_dc = np.zeros((2, mbh, mbw, 2, 2), np.int32)
    chroma_ac = np.zeros((2, mbh, mbw, 2, 2, 4, 4), np.int32)
    nz_luma = np.zeros((mbh * 4, mbw * 4), np.int32)
    nz_chroma = np.zeros((2, mbh * 2, mbw * 2), np.int32)
    nc_of = _nc

    for my in range(mbh):
        for mx in range(mbw):
            mb_type = r.read_ue()
            if not 1 <= mb_type <= 24:
                raise UnsupportedStream(f"mb_type {mb_type} (not I_16x16)")
            t = mb_type - 1
            luma_mode = t % 4
            cbp_chroma = (t // 4) % 3
            cbp_luma = 15 if t >= 12 else 0
            chroma_mode = r.read_ue()
            exp_luma = _ROW0_LUMA_MODE if my == 0 else _BODY_LUMA_MODE
            exp_chroma = _ROW0_CHROMA_MODE if my == 0 else _BODY_CHROMA_MODE
            if luma_mode != exp_luma or chroma_mode != exp_chroma:
                raise UnsupportedStream(
                    f"prediction layout mismatch at MB ({my},{mx}): "
                    f"luma {luma_mode}/{exp_luma} chroma {chroma_mode}/{exp_chroma}")
            if r.read_se() != 0:
                raise UnsupportedStream("mb_qp_delta != 0 not supported")

            gy, gx = my * 4, mx * 4
            nc = nc_of(gx > 0, int(nz_luma[gy, gx - 1]),
                       gy > 0, int(nz_luma[gy - 1, gx]))
            luma_dc[my, mx] = _unzigzag(decode_residual_block(r, nc, 16))

            if cbp_luma:
                for by, bx in LUMA_BLOCK_ORDER:
                    y, x = gy + by, gx + bx
                    nc = nc_of(x > 0, int(nz_luma[y, x - 1]),
                               y > 0, int(nz_luma[y - 1, x]))
                    scan15 = decode_residual_block(r, nc, 15)
                    full = np.zeros(16, np.int32)
                    full[1:] = scan15
                    luma_ac[my, mx, by, bx] = _unzigzag(full)
                    nz_luma[y, x] = int(np.count_nonzero(scan15))

            if cbp_chroma > 0:
                for comp in range(2):
                    dc = decode_residual_block(r, -1, 4)
                    chroma_dc[comp, my, mx] = dc.reshape(2, 2)

            if cbp_chroma == 2:
                cy, cx = my * 2, mx * 2
                for comp in range(2):
                    for by in range(2):
                        for bx in range(2):
                            y, x = cy + by, cx + bx
                            nc = nc_of(x > 0, int(nz_chroma[comp, y, x - 1]),
                                       y > 0, int(nz_chroma[comp, y - 1, x]))
                            scan15 = decode_residual_block(r, nc, 15)
                            full = np.zeros(16, np.int32)
                            full[1:] = scan15
                            chroma_ac[comp, my, mx, by, bx] = _unzigzag(full)
                            nz_chroma[comp, y, x] = int(np.count_nonzero(scan15))
    return {
        "luma_dc": luma_dc, "luma_ac": luma_ac,
        "chroma_dc": chroma_dc, "chroma_ac": chroma_ac,
    }


def decode_p_slice_data(r: BitReader, sps: Sps, header: SliceHeader) -> dict:
    """Decode one full-frame P slice (P_Skip / P_L0_16x16 envelope).

    MV prediction state machine is shared with the encoder
    (cavlc.PSliceEncoder.mv_pred/skip_mv), so the two can never drift.
    Returns levels + per-MB MVs in quarter pels.
    """
    from vlog_tpu.codecs.h264.cavlc import (_BLK44, _CBP_INTER_FROM_CODE,
                                            MvPredictor)

    mbh, mbw = sps.mb_height, sps.mb_width
    if header.first_mb != 0:
        raise UnsupportedStream("multi-slice pictures not supported")
    luma = np.zeros((mbh, mbw, 4, 4, 4, 4), np.int32)
    chroma_dc = np.zeros((2, mbh, mbw, 2, 2), np.int32)
    chroma_ac = np.zeros((2, mbh, mbw, 2, 2, 4, 4), np.int32)
    nz_luma = np.zeros((mbh * 4, mbw * 4), np.int32)
    nz_chroma = np.zeros((2, mbh * 2, mbw * 2), np.int32)
    mvst = MvPredictor(mbh, mbw)          # shared with the encoder

    n_mbs = mbh * mbw
    mb = 0
    skip_left = r.read_ue()               # leading mb_skip_run
    while mb < n_mbs:
        my, mx = divmod(mb, mbw)
        if skip_left > 0:
            mvst.mvs[my, mx] = mvst.skip_mv(my, mx)
            skip_left -= 1
            mb += 1
            continue
        mb_type = r.read_ue()
        if mb_type != 0:
            raise UnsupportedStream(
                f"P mb_type {mb_type} outside P_L0_16x16 envelope")
        mvd_x = r.read_se()
        mvd_y = r.read_se()
        pmx, pmy = mvst.mv_pred(my, mx)
        mvx, mvy = pmx + mvd_x, pmy + mvd_y
        mvst.mvs[my, mx] = (mvx, mvy)
        cbp = _CBP_INTER_FROM_CODE[r.read_ue()]
        if cbp:
            if r.read_se() != 0:
                raise UnsupportedStream("mb_qp_delta != 0 not supported")
            gy, gx = my * 4, mx * 4
            for i8 in range(4):
                oy, ox = _BLK44[i8]
                for dy, dx in _BLK44:
                    by, bx = 2 * oy + dy, 2 * ox + dx
                    y, x = gy + by, gx + bx
                    if not (cbp >> i8) & 1:
                        nz_luma[y, x] = 0
                        continue
                    nc = _nc(x > 0, int(nz_luma[y, x - 1]),
                             y > 0, int(nz_luma[y - 1, x]))
                    scan = decode_residual_block(r, nc, 16)
                    luma[my, mx, by, bx] = _unzigzag(scan)
                    nz_luma[y, x] = int(np.count_nonzero(scan))
            cbp_chroma = cbp >> 4
            if cbp_chroma > 0:
                for comp in range(2):
                    dc = decode_residual_block(r, -1, 4)
                    chroma_dc[comp, my, mx] = dc.reshape(2, 2)
            cy, cx = my * 2, mx * 2
            for comp in range(2):
                for by in range(2):
                    for bx in range(2):
                        y, x = cy + by, cx + bx
                        if cbp_chroma != 2:
                            nz_chroma[comp, y, x] = 0
                            continue
                        nc = _nc(x > 0, int(nz_chroma[comp, y, x - 1]),
                                 y > 0, int(nz_chroma[comp, y - 1, x]))
                        scan15 = decode_residual_block(r, nc, 15)
                        full = np.zeros(16, np.int32)
                        full[1:] = scan15
                        chroma_ac[comp, my, mx, by, bx] = _unzigzag(full)
                        nz_chroma[comp, y, x] = int(np.count_nonzero(scan15))
        mb += 1
        if mb < n_mbs:
            skip_left = r.read_ue()
    return {
        "luma": luma, "chroma_dc": chroma_dc, "chroma_ac": chroma_ac,
        "mv_q": np.ascontiguousarray(mvst.mvs),   # quarter pels, (x, y)
    }


# --------------------------------------------------------------------------
# Reconstruction (JAX) — mirror of encoder.encode_frame's recon path
# --------------------------------------------------------------------------

def _luma_resid(dc_levels, ac_levels, qp: int):
    """Levels -> spatial residual rows. dc (mbh,mbw,4,4), ac (mbh,mbw,4,4,4,4)."""
    dc_rec = dequantize_luma_dc(dc_levels, qp=qp)
    ac_rec = dequantize(ac_levels, qp=qp)
    full = ac_rec.at[..., 0, 0].set(dc_rec)
    resid = inverse_core_transform(full)               # (mbh, mbw, 4, 4, 4, 4)
    mbh, mbw = resid.shape[0], resid.shape[1]
    mb = jnp.swapaxes(resid, 3, 4).reshape(mbh, mbw, 16, 16)
    return jnp.swapaxes(mb, 1, 2).reshape(mbh, 16, mbw * 16)   # (mbh, 16, W)


def _chroma_resid(dc_levels, ac_levels, qpc: int):
    """dc (mbh,mbw,2,2), ac (mbh,mbw,2,2,4,4) -> (mbh, 8, Wc)."""
    dc_rec = dequantize_chroma_dc(dc_levels, qp=qpc)
    ac_rec = dequantize(ac_levels, qp=qpc)
    full = ac_rec.at[..., 0, 0].set(dc_rec)
    resid = inverse_core_transform(full)               # (mbh, mbw, 2, 2, 4, 4)
    mbh, mbw = resid.shape[0], resid.shape[1]
    mb = jnp.swapaxes(resid, 3, 4).reshape(mbh, mbw, 8, 8)
    return jnp.swapaxes(mb, 1, 2).reshape(mbh, 8, mbw * 8)


@functools.partial(jax.jit, static_argnames=("qp",))
def reconstruct_frame(levels: dict, *, qp: int):
    """Levels dict (numpy/jnp arrays) -> (y, u, v) uint8 planes (padded size)."""
    qpc = chroma_qp(qp)
    luma_dc = jnp.asarray(levels["luma_dc"], jnp.int32)
    luma_ac = jnp.asarray(levels["luma_ac"], jnp.int32)
    chroma_dc = jnp.asarray(levels["chroma_dc"], jnp.int32)
    chroma_ac = jnp.asarray(levels["chroma_ac"], jnp.int32)
    mbh, mbw = luma_dc.shape[0], luma_dc.shape[1]
    w = mbw * 16

    y_resid = _luma_resid(luma_dc, luma_ac, qp)                  # (mbh, 16, W)
    u_resid = _chroma_resid(chroma_dc[0], chroma_ac[0], qpc)     # (mbh, 8, W/2)
    v_resid = _chroma_resid(chroma_dc[1], chroma_ac[1], qpc)

    # --- Row 0: DC prediction with left-neighbour carry (scan over x).
    def row0_step(carry, xs):
        ly, lu, lv = carry
        yr, ur, vr, is_first = xs                 # per-MB residual slabs
        pred_dc = jnp.where(is_first, 128, (jnp.sum(ly) + 8) >> 4)
        yrec = jnp.clip(pred_dc + yr, 0, 255)
        top = (jnp.sum(lu[:4]) + 2) >> 2
        bot = (jnp.sum(lu[4:]) + 2) >> 2
        ucol = jnp.where(is_first, 128,
                         jnp.concatenate([jnp.full((4,), top), jnp.full((4,), bot)]))
        urec = jnp.clip(ucol[:, None] + ur, 0, 255)
        topv = (jnp.sum(lv[:4]) + 2) >> 2
        botv = (jnp.sum(lv[4:]) + 2) >> 2
        vcol = jnp.where(is_first, 128,
                         jnp.concatenate([jnp.full((4,), topv), jnp.full((4,), botv)]))
        vrec = jnp.clip(vcol[:, None] + vr, 0, 255)
        return (yrec[:, -1], urec[:, -1], vrec[:, -1]), (yrec, urec, vrec)

    y0_mbs = jnp.swapaxes(y_resid[0].reshape(16, mbw, 16), 0, 1)
    u0_mbs = jnp.swapaxes(u_resid[0].reshape(8, mbw, 8), 0, 1)
    v0_mbs = jnp.swapaxes(v_resid[0].reshape(8, mbw, 8), 0, 1)
    first = jnp.zeros((mbw,), jnp.bool_).at[0].set(True)
    init = (jnp.full((16,), 128, jnp.int32), jnp.full((8,), 128, jnp.int32),
            jnp.full((8,), 128, jnp.int32))
    _, (y0, u0, v0) = jax.lax.scan(row0_step, init, (y0_mbs, u0_mbs, v0_mbs, first))
    y0 = jnp.swapaxes(y0, 0, 1).reshape(16, w)
    u0 = jnp.swapaxes(u0, 0, 1).reshape(8, w // 2)
    v0 = jnp.swapaxes(v0, 0, 1).reshape(8, w // 2)

    if mbh == 1:
        return (y0.astype(jnp.uint8), u0.astype(jnp.uint8), v0.astype(jnp.uint8))

    # --- Rows 1..mbh-1: vertical prediction, scan over rows.
    def body_step(carry, xs):
        py, pu, pv = carry
        yr, ur, vr = xs
        yrec = jnp.clip(py[None, :] + yr, 0, 255)
        urec = jnp.clip(pu[None, :] + ur, 0, 255)
        vrec = jnp.clip(pv[None, :] + vr, 0, 255)
        return (yrec[-1], urec[-1], vrec[-1]), (yrec, urec, vrec)

    init = (y0[-1], u0[-1], v0[-1])
    _, (yb, ub, vb) = jax.lax.scan(
        body_step, init, (y_resid[1:], u_resid[1:], v_resid[1:]))
    y = jnp.concatenate([y0, yb.reshape((mbh - 1) * 16, w)])
    u = jnp.concatenate([u0, ub.reshape((mbh - 1) * 8, w // 2)])
    v = jnp.concatenate([v0, vb.reshape((mbh - 1) * 8, w // 2)])
    return (y.astype(jnp.uint8), u.astype(jnp.uint8), v.astype(jnp.uint8))


# Batched reconstruction over a GOP of frames (stacked levels arrays).
@functools.partial(jax.jit, static_argnames=("qp",))
def reconstruct_gop(levels: dict, *, qp: int):
    return jax.vmap(lambda l: reconstruct_frame(l, qp=qp))(levels)


@functools.partial(jax.jit, static_argnames=("qp",))
def reconstruct_p_frame(levels: dict, ref_y, ref_u, ref_v, *, qp: int):
    """P-frame recon: MC from the previous reconstruction + inter residual
    (mirror of inter.encode_p_frame's decoder loop)."""
    from vlog_tpu.codecs.h264.inter import mc_chroma, mc_luma

    qpc = chroma_qp(qp)
    mv = jnp.asarray(levels["mv_q"], jnp.int32)    # (mbh, mbw, 2) qtr-pel
    luma = jnp.asarray(levels["luma"], jnp.int32)
    chroma_dc = jnp.asarray(levels["chroma_dc"], jnp.int32)
    chroma_ac = jnp.asarray(levels["chroma_ac"], jnp.int32)
    mbh, mbw = luma.shape[0], luma.shape[1]
    h, w = mbh * 16, mbw * 16

    pred_y = mc_luma(jnp.asarray(ref_y), mv, search=_P_REF_PAD)
    pred_u = mc_chroma(jnp.asarray(ref_u), mv, search=_P_REF_PAD)
    pred_v = mc_chroma(jnp.asarray(ref_v), mv, search=_P_REF_PAD)

    rec = inverse_core_transform(dequantize(luma, qp=qp))
    y_res = jnp.transpose(rec, (0, 2, 4, 1, 3, 5)).reshape(h, w)

    def chroma_res(dc, ac):
        dc_rec = dequantize_chroma_dc(dc, qp=qpc)
        full = dequantize(ac, qp=qpc).at[..., 0, 0].set(dc_rec)
        res = inverse_core_transform(full)
        return jnp.transpose(res, (0, 2, 4, 1, 3, 5)).reshape(h // 2, w // 2)

    y = jnp.clip(pred_y + y_res, 0, 255).astype(jnp.uint8)
    u = jnp.clip(pred_u + chroma_res(chroma_dc[0], chroma_ac[0]),
                 0, 255).astype(jnp.uint8)
    v = jnp.clip(pred_v + chroma_res(chroma_dc[1], chroma_ac[1]),
                 0, 255).astype(jnp.uint8)
    return y, u, v


# MC padding for decode: covers |MV| up to this many pels (our encoder's
# search radius is <= 16; foreign streams beyond it are rejected upstream).
_P_REF_PAD = 32


# --------------------------------------------------------------------------
# Decoder object
# --------------------------------------------------------------------------

@dataclass
class DecodedFrame:
    y: np.ndarray
    u: np.ndarray
    v: np.ndarray


class H264Decoder:
    """Stateful decoder: feed NALs (AnnexB chunks or AVCC samples), get frames.

    Cropping from the SPS is applied; output planes are (h, w), (h/2, w/2).
    """

    def __init__(self, avcc_config: bytes | None = None):
        self.sps: Sps | None = None
        self.pps: Pps | None = None
        self._length_size = 4
        self._ref: tuple | None = None      # previous padded recon (y, u, v)
        if avcc_config:
            self._parse_avcc_config(avcc_config)

    def _parse_avcc_config(self, cfg: bytes) -> None:
        """AVCDecoderConfigurationRecord (ISO 14496-15 5.3.3.1)."""
        if len(cfg) < 7 or cfg[0] != 1:
            raise DecodeError("bad avcC")
        self._length_size = (cfg[4] & 3) + 1
        pos = 5
        n_sps = cfg[pos] & 0x1F
        pos += 1
        try:
            for _ in range(n_sps):
                ln = int.from_bytes(cfg[pos:pos + 2], "big")
                pos += 2
                if ln == 0 or pos + ln > len(cfg):
                    raise DecodeError("truncated avcC SPS")
                self._handle_nal(cfg[pos] & 0x1F,
                                 unescape_emulation(cfg[pos + 1:pos + ln]))
                pos += ln
            n_pps = cfg[pos]
            pos += 1
            for _ in range(n_pps):
                ln = int.from_bytes(cfg[pos:pos + 2], "big")
                pos += 2
                if ln == 0 or pos + ln > len(cfg):
                    raise DecodeError("truncated avcC PPS")
                self._handle_nal(cfg[pos] & 0x1F,
                                 unescape_emulation(cfg[pos + 1:pos + ln]))
                pos += ln
        except IndexError as exc:
            raise DecodeError("truncated avcC") from exc
        if self.sps is None or self.pps is None:
            raise DecodeError("avcC carries no SPS/PPS")

    def _handle_nal(self, nal_type: int, rbsp: bytes) -> None:
        if nal_type == syntax.NAL_SPS:
            self.sps = parse_sps(rbsp)
        elif nal_type == syntax.NAL_PPS:
            self.pps = parse_pps(rbsp)

    def _decode_slice_nal(self, nal_type: int, ref_idc: int, rbsp: bytes) -> dict:
        if self.sps is None or self.pps is None:
            raise DecodeError("slice before SPS/PPS")
        r = BitReader(rbsp)
        header = parse_slice_header(r, self.sps, self.pps, nal_type, ref_idc)
        is_p = header.slice_type % 5 == 0
        if self.pps.entropy_coding_mode:
            from vlog_tpu.codecs.h264.cabac_dec import (
                decode_p_slice_data_cabac, decode_slice_data_cabac)

            r.byte_align()               # cabac_alignment_one_bit(s)
            start = (len(rbsp) * 8 - r.bits_remaining) // 8
            data = rbsp[start:]
            levels = (decode_p_slice_data_cabac(data, self.sps, header)
                      if is_p else
                      decode_slice_data_cabac(data, self.sps, header))
        elif is_p:
            levels = decode_p_slice_data(r, self.sps, header)
        else:
            levels = decode_slice_data(r, self.sps, header)
        levels["is_p"] = is_p
        levels["qp"] = header.qp
        levels["deblock"] = header.deblock
        return levels

    def _reconstruct(self, levels: dict) -> tuple:
        """Levels -> padded planes; updates the reference picture."""
        qp = levels.pop("qp")
        deblock = levels.pop("deblock", False)
        is_p = levels.pop("is_p", False)
        if is_p:
            if self._ref is None:
                raise DecodeError("P slice with no reference picture")
            mv_q = levels.pop("mv_q")                   # (mbh, mbw, 2) (x, y)
            mv = np.stack([mv_q[..., 1], mv_q[..., 0]], axis=-1)
            # pad = _P_REF_PAD+8 in mc_luma keeps gathers safe through
            # |mv| = 32 integer pels (the historical envelope)
            if np.any(np.abs(mv) > 4 * _P_REF_PAD):
                raise UnsupportedStream("MV beyond reference padding")
            levels["mv_q"] = mv                         # DSP (y, x) order
            y, u, v = reconstruct_p_frame(levels, *self._ref, qp=qp)
        else:
            y, u, v = reconstruct_frame(levels, qp=qp)
        if deblock:
            # spec 8.7 in-loop filter — same JAX wavefront the encoder
            # runs, with bS from the decoded syntax elements
            from vlog_tpu.codecs.h264.deblock import (
                deblock_frame, intra_bs, p_bs)

            mbh, mbw = np.asarray(y).shape[0] // 16, \
                np.asarray(y).shape[1] // 16
            if is_p:
                luma = np.asarray(levels["luma"])
                nz = np.any(luma != 0, axis=(-1, -2))   # (mbh, mbw, 4, 4)
                nz4 = nz.transpose(0, 2, 1, 3).reshape(4 * mbh, 4 * mbw)
                bsv, bsh = p_bs(jnp.asarray(nz4),
                                jnp.asarray(levels["mv_q"]))
            else:
                bsv, bsh = intra_bs(mbh, mbw)
            y, u, v = deblock_frame(y, u, v, qp=qp, bs_v=bsv, bs_h=bsh)
            y, u, v = (jnp.asarray(y).astype(jnp.uint8),
                       jnp.asarray(u).astype(jnp.uint8),
                       jnp.asarray(v).astype(jnp.uint8))
        self._ref = (np.asarray(y), np.asarray(u), np.asarray(v))
        return y, u, v

    def decode_sample_levels(self, sample: bytes) -> dict | None:
        """AVCC sample -> levels dict (host arrays), or None if no slice."""
        for nal_type, ref_idc, rbsp in split_avcc(sample, self._length_size):
            if nal_type in (syntax.NAL_SLICE, syntax.NAL_IDR):
                return self._decode_slice_nal(nal_type, ref_idc, rbsp)
            self._handle_nal(nal_type, rbsp)
        return None

    def _crop(self, y, u, v) -> DecodedFrame:
        sps = self.sps
        w, h = sps.width, sps.height
        return DecodedFrame(
            np.asarray(y)[:h, :w],
            np.asarray(u)[:h // 2, :w // 2],
            np.asarray(v)[:h // 2, :w // 2],
        )

    def decode_sample(self, sample: bytes) -> DecodedFrame | None:
        levels = self.decode_sample_levels(sample)
        if levels is None:
            return None
        return self._crop(*self._reconstruct(levels))

    def decode_samples(self, samples: list[bytes]) -> list[DecodedFrame]:
        """Batched decode: CAVLC parse per sample on host, one device
        dispatch reconstructs the whole batch when the GOP is all-intra
        with a shared QP; chained (P) GOPs reconstruct sequentially."""
        all_levels = []
        for s in samples:
            lv = self.decode_sample_levels(s)
            if lv is not None:
                all_levels.append(lv)
        if not all_levels:
            return []
        qps = {lv["qp"] for lv in all_levels}
        if (len(qps) == 1
                and not any(lv.get("is_p") for lv in all_levels)
                and not any(lv.get("deblock") for lv in all_levels)):
            qp = qps.pop()
            stacked = {
                k: np.stack([lv[k] for lv in all_levels])
                for k in ("luma_dc", "luma_ac", "chroma_dc", "chroma_ac")
            }
            ys, us, vs = reconstruct_gop(stacked, qp=qp)
            self._ref = (np.asarray(ys[-1]), np.asarray(us[-1]),
                         np.asarray(vs[-1]))
            return [self._crop(ys[i], us[i], vs[i])
                    for i in range(len(all_levels))]
        return [self._crop(*self._reconstruct(lv)) for lv in all_levels]


def decode_annexb(data: bytes) -> tuple[list[DecodedFrame], Sps | None]:
    """Decode a full Annex-B elementary stream (e.g. a .h264 dump)."""
    dec = H264Decoder()
    frames: list[DecodedFrame] = []
    for nal_type, ref_idc, rbsp in split_annexb(data):
        if nal_type in (syntax.NAL_SLICE, syntax.NAL_IDR):
            levels = dec._decode_slice_nal(nal_type, ref_idc, rbsp)
            frames.append(dec._crop(*dec._reconstruct(levels)))
        else:
            dec._handle_nal(nal_type, rbsp)
    return frames, dec.sps
