"""High-level H.264 encoding API: frames in, packaged samples out.

This is the object the backend layer (vlog_tpu.backends) drives per
quality rung; it owns parameter sets and frame numbering, delegates DSP to
``encoder`` (JAX, batched per GOP) and entropy coding to ``cavlc``.

Reference parity: the (codec, width, height, bitrate) →  command-line
mapping lived in worker/hwaccel.py:647-731; here it is an encoder object
whose output plugs straight into media.fmp4 segments.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from vlog_tpu import config
from vlog_tpu.codecs.h264 import syntax
from vlog_tpu.codecs.h264.cavlc import encode_slice
from vlog_tpu.codecs.h264.encoder import (
    FrameLevels,
    encode_gop,
    pad_to_mb,
)


@dataclass
class EncodedFrame:
    """One access unit, ready for MP4/fMP4 sample tables."""

    avcc: bytes          # 4-byte-length-prefixed NALs (AVCC sample format)
    annexb: bytes        # start-code framed (for .h264 dumps / TS)
    is_idr: bool
    psnr_y: float


@dataclass
class H264Encoder:
    """Stateful per-rung encoder: call :meth:`encode` with GOP batches.

    All-intra (every frame IDR-capable); ``idr_period`` controls how often
    IDR + recovery points are marked (non-IDR frames are still I slices).
    """

    width: int
    height: int
    fps_num: int = 30
    fps_den: int = 1
    qp: int = 26
    idr_period: int = 1          # every frame IDR by default
    # None -> config.ENTROPY_THREADS (cpu-count-derived; the shared
    # executor pool is sized by the same knob)
    entropy_threads: int | None = None
    entropy: str = "cavlc"       # "cavlc" (C fast path) | "cabac"
    # In-loop deblocking (spec 8.7): the chain path enables this — the
    # DSP's reconstruction loop must apply codecs/h264/deblock.py when
    # the slice headers signal idc=0, or prediction drifts vs decoders.
    deblock: bool = False
    _frame_index: int = field(default=0, init=False)
    _idr_pic_id: int = field(default=0, init=False)

    def __post_init__(self):
        if self.entropy_threads is None:
            self.entropy_threads = config.ENTROPY_THREADS
        if self.entropy not in ("cavlc", "cabac"):
            raise ValueError(f"unknown entropy coder {self.entropy!r}")
        # CABAC is prohibited in Baseline (spec A.2.1); signal Main so
        # the SPS/avcC/RFC6381 string match the actual toolset.
        profile = (syntax.PROFILE_MAIN if self.entropy == "cabac"
                   else syntax.PROFILE_BASELINE)
        self.sps = syntax.make_sps(
            syntax.SpsConfig(
                width=self.width, height=self.height,
                fps_num=self.fps_num, fps_den=self.fps_den,
                profile_idc=profile,
            )
        )
        self.pps = syntax.make_pps(init_qp=self.qp,
                                   cabac=self.entropy == "cabac")

    def _slice_fns(self):
        from functools import partial

        if self.entropy == "cabac":
            from vlog_tpu.codecs.h264.cabac_enc import (
                encode_p_slice_cabac, encode_slice_cabac)

            i_fn, p_fn = encode_slice_cabac, encode_p_slice_cabac
        else:
            from vlog_tpu.codecs.h264.cavlc import encode_p_slice

            i_fn, p_fn = encode_slice, encode_p_slice
        return (partial(i_fn, deblock=self.deblock),
                partial(p_fn, deblock=self.deblock))

    # ---- stream metadata -------------------------------------------------
    @property
    def avcc_config(self) -> bytes:
        return syntax.avcc_config(self.sps, self.pps)

    @property
    def codec_string(self) -> str:
        return syntax.codec_string(self.sps)

    def headers_annexb(self) -> bytes:
        return syntax.annexb([self.sps, self.pps])

    # ---- encoding --------------------------------------------------------
    def _pack_one(self, frame_id: int, lv: FrameLevels, frame_qp: int,
                  psnr: float) -> EncodedFrame:
        idr = (frame_id % self.idr_period) == 0
        slice_fn, _ = self._slice_fns()
        nal = slice_fn(
            lv, qp=frame_qp, init_qp=self.qp,
            # frame_num counts reference frames since the last IDR.
            frame_num=(frame_id % self.idr_period) % 256,
            idr=idr, idr_pic_id=frame_id % 2,
        )
        raw = nal.to_bytes()
        # avc1 tracks carry parameter sets only in avcC (ISO 14496-15
        # 5.3.3); the Annex-B dump repeats them in-band at each IDR.
        prefix = [self.sps, self.pps] if idr else []
        avcc = len(raw).to_bytes(4, "big") + raw
        annexb = syntax.annexb(prefix + [nal])
        return EncodedFrame(avcc=avcc, annexb=annexb, is_idr=idr, psnr_y=psnr)

    def encode_chain(self, intra: FrameLevels, p_frames: list[dict],
                     qps: np.ndarray, psnrs: np.ndarray | None = None,
                     pool: ThreadPoolExecutor | None = None,
                     ) -> list[EncodedFrame]:
        """Entropy-code one I+P mini-GOP (GOP_MODE="p" hot path).

        ``intra`` is frame 0's levels; ``p_frames`` holds the inter level
        dicts (luma/chroma_dc/chroma_ac/mv) for frames 1..clen-1. Frames
        are slices, so they entropy-code in parallel threads — per-slice
        CAVLC state never crosses frame boundaries.
        """
        slice_fn, p_slice_fn = self._slice_fns()
        idr_pic_id = self._idr_pic_id
        self._idr_pic_id = (self._idr_pic_id + 1) % 65536
        n = 1 + len(p_frames)
        psnr = (lambda i: float(psnrs[i]) if psnrs is not None
                else float("nan"))

        def pack(i: int) -> EncodedFrame:
            if i == 0:
                nal = slice_fn(
                    intra, qp=int(qps[0]), init_qp=self.qp, frame_num=0,
                    idr=True, idr_pic_id=idr_pic_id)
                raw = nal.to_bytes()
                return EncodedFrame(
                    avcc=len(raw).to_bytes(4, "big") + raw,
                    annexb=syntax.annexb([self.sps, self.pps, nal]),
                    is_idr=True, psnr_y=psnr(0))
            nal = p_slice_fn(p_frames[i - 1], qp=int(qps[i]),
                             init_qp=self.qp, frame_num=i)
            raw = nal.to_bytes()
            return EncodedFrame(
                avcc=len(raw).to_bytes(4, "big") + raw,
                annexb=syntax.annexb([nal]), is_idr=False, psnr_y=psnr(i))

        if pool is not None:
            return list(pool.map(pack, range(n)))
        if n == 1 or self.entropy_threads <= 1:
            return [pack(i) for i in range(n)]
        with ThreadPoolExecutor(self.entropy_threads,
                                thread_name_prefix="vlog-entropy") as own:
            return list(own.map(pack, range(n)))

    def encode_levels(self, levels: dict, qps: np.ndarray,
                      psnrs: np.ndarray | None = None,
                      n: int | None = None,
                      pool: ThreadPoolExecutor | None = None
                      ) -> list[EncodedFrame]:
        """Entropy-code device outputs already on host.

        ``levels`` holds numpy ``luma_dc/luma_ac/chroma_dc/chroma_ac``
        with leading frame axis (the fused ladder program's per-rung
        output); ``qps`` is the per-frame QP the DSP actually used. The
        backend calls this while the *next* batch's dispatch is already
        in flight, so host bit-packing overlaps device compute (frames
        within the call are threaded — on ``pool`` when the caller
        shares its long-lived executor pool, else a per-call one).
        """
        total = levels["luma_dc"].shape[0]
        n = total if n is None else min(n, total)
        frame_ids = list(range(self._frame_index, self._frame_index + n))
        self._frame_index += n

        def pack(i: int) -> EncodedFrame:
            lv = FrameLevels(levels["luma_dc"][i], levels["luma_ac"][i],
                             levels["chroma_dc"][i], levels["chroma_ac"][i],
                             int(qps[i]))
            psnr = float(psnrs[i]) if psnrs is not None else float("nan")
            return self._pack_one(frame_ids[i], lv, int(qps[i]), psnr)

        if pool is not None:
            return list(pool.map(pack, range(n)))
        if n == 1 or self.entropy_threads <= 1:
            return [pack(i) for i in range(n)]
        with ThreadPoolExecutor(self.entropy_threads,
                                thread_name_prefix="vlog-entropy") as own:
            return list(own.map(pack, range(n)))

    def encode(self, y: np.ndarray, u: np.ndarray, v: np.ndarray
               ) -> list[EncodedFrame]:
        """Encode a GOP batch: y (N, H, W), u/v (N, H/2, W/2) uint8.

        One XLA dispatch for the whole batch, then entropy coding on host
        threads (one frame per task; numpy-heavy sections drop the GIL).
        """
        n = y.shape[0]
        y = pad_to_mb(y)
        u = pad_to_mb(u, 8)
        v = pad_to_mb(v, 8)
        out = encode_gop(y, u, v, qp=self.qp)
        recon_y = np.asarray(out["recon_y"])
        levels = {k: np.asarray(out[k]) for k in
                  ("luma_dc", "luma_ac", "chroma_dc", "chroma_ac")}
        vh, vw = self.height, self.width
        err = (recon_y[:, :vh, :vw].astype(np.int64)
               - y[:, :vh, :vw].astype(np.int64))
        mse = np.mean(err.astype(np.float64) ** 2, axis=(1, 2))
        psnrs = np.where(mse < 1e-9, 99.0,
                         10 * np.log10(255 ** 2 / np.maximum(mse, 1e-12)))
        return self.encode_levels(levels, np.full(n, self.qp), psnrs)
