"""AAC spectral/scalefactor Huffman coding (ISO/IEC 14496-3 4.6.3).

Codeword tables are the normative constants in ``tables.py``; this module
adds the codebook *semantics*: index <-> coefficient-tuple mapping,
sign-bit handling for the unsigned books, and the book-11 escape
sequence. Used by both the encoder (value -> bits) and the decoder
(bits -> values).

Codebook inventory (Table 4.A.1): books 1-2 quad signed LAV=1, 3-4 quad
unsigned LAV=2, 5-6 pair signed LAV=4, 7-8 pair unsigned LAV=7, 9-10
pair unsigned LAV=12, 11 pair unsigned escape LAV=16(esc).
"""

from __future__ import annotations

from vlog_tpu.codecs.aac import tables as T
from vlog_tpu.media.bitstream import BitReader, BitWriter

ZERO_HCB = 0
FIRST_PAIR_HCB = 5
ESC_HCB = 11
NOISE_HCB = 13
INTENSITY_HCB2 = 14
INTENSITY_HCB = 15

# (dimension, signed, LAV) per book 1..11
BOOK_INFO = {
    1: (4, True, 1), 2: (4, True, 1),
    3: (4, False, 2), 4: (4, False, 2),
    5: (2, True, 4), 6: (2, True, 4),
    7: (2, False, 7), 8: (2, False, 7),
    9: (2, False, 12), 10: (2, False, 12),
    11: (2, False, 16),
}


def book_index(book: int, vals: tuple[int, ...]) -> int:
    """Coefficient tuple -> codeword index (spec 4.6.3.3 ordering)."""
    dim, signed, lav = BOOK_INFO[book]
    if book <= 2:
        w, x, y, z = vals
        return 27 * (w + 1) + 9 * (x + 1) + 3 * (y + 1) + (z + 1)
    if book <= 4:
        w, x, y, z = vals
        return 27 * w + 9 * x + 3 * y + z
    if book <= 6:
        y, z = vals
        return 9 * (y + 4) + (z + 4)
    if book <= 8:
        y, z = vals
        return 8 * vals[0] + vals[1]
    if book <= 10:
        return 13 * vals[0] + vals[1]
    return 17 * vals[0] + vals[1]


def book_values(book: int, idx: int) -> tuple[int, ...]:
    """Codeword index -> coefficient tuple (inverse of book_index)."""
    if book <= 2:
        return (idx // 27 - 1, (idx // 9) % 3 - 1, (idx // 3) % 3 - 1,
                idx % 3 - 1)
    if book <= 4:
        return (idx // 27, (idx // 9) % 3, (idx // 3) % 3, idx % 3)
    if book <= 6:
        return (idx // 9 - 4, idx % 9 - 4)
    if book <= 8:
        return (idx // 8, idx % 8)
    if book <= 10:
        return (idx // 13, idx % 13)
    return (idx // 17, idx % 17)


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------

def write_scalefactor(w: BitWriter, dpcm: int) -> None:
    """dpcm in [-60, 60]; index = dpcm + 60 into the sf codebook."""
    idx = dpcm + 60
    if not 0 <= idx < 121:
        raise ValueError(f"scalefactor delta {dpcm} out of range")
    w.write_bits(T.SCALEFACTOR_CODE[idx], T.SCALEFACTOR_BITS[idx])


def scalefactor_bits(dpcm: int) -> int:
    return T.SCALEFACTOR_BITS[dpcm + 60]


def _write_escape(w: BitWriter, mag: int) -> None:
    """Book-11 escape: (n-4) ones, 0, then n LSBs of mag - 2^n."""
    n = mag.bit_length() - 1          # 2^n <= mag < 2^(n+1), n >= 4
    if n < 4 or n > 12:               # spec caps |coef| at 8191 (n <= 12)
        raise ValueError(f"escape magnitude {mag} out of range")
    w.write_bits((1 << (n - 4)) - 1, n - 4)
    w.write_bit(0)
    w.write_bits(mag - (1 << n), n)


def write_group(w: BitWriter, book: int, vals: tuple[int, ...]) -> None:
    """One codeword (+signs, +escapes) for a 2- or 4-tuple of quantized
    coefficients."""
    dim, signed, lav = BOOK_INFO[book]
    if signed:
        idx = book_index(book, vals)
        w.write_bits(T.SPECTRAL_CODES[book - 1][idx],
                     T.SPECTRAL_BITS[book - 1][idx])
        return
    mags = tuple(abs(v) for v in vals)
    coded = tuple(min(m, 16) for m in mags) if book == ESC_HCB else mags
    idx = book_index(book, coded)
    w.write_bits(T.SPECTRAL_CODES[book - 1][idx],
                 T.SPECTRAL_BITS[book - 1][idx])
    for v in vals:
        if v != 0:
            w.write_bit(1 if v < 0 else 0)
    if book == ESC_HCB:
        for m in mags:
            if m >= 16:
                _write_escape(w, m)


def group_bits(book: int, vals: tuple[int, ...]) -> int:
    """Exact bit cost of write_group (for codebook selection)."""
    dim, signed, lav = BOOK_INFO[book]
    if signed:
        return int(T.SPECTRAL_BITS[book - 1][book_index(book, vals)])
    mags = tuple(abs(v) for v in vals)
    coded = tuple(min(m, 16) for m in mags) if book == ESC_HCB else mags
    bits = int(T.SPECTRAL_BITS[book - 1][book_index(book, coded)])
    bits += sum(1 for v in vals if v != 0)
    if book == ESC_HCB:
        for m in mags:
            if m >= 16:
                bits += 2 * (m.bit_length() - 1) - 3
    return bits


def smallest_book(max_abs: int) -> int:
    """Cheapest codebook family that can represent |coef| <= max_abs."""
    if max_abs == 0:
        return ZERO_HCB
    if max_abs <= 1:
        return 2          # signed quad, LAV 1 (book 1/2 pair; 2 is 'noisy')
    if max_abs <= 2:
        return 4
    if max_abs <= 4:
        return 6
    if max_abs <= 7:
        return 8
    if max_abs <= 12:
        return 10
    return ESC_HCB


def best_book(vals: list[int]) -> tuple[int, int]:
    """(book, bits) minimizing exact cost over the usable books for a
    band's coefficients (vals length multiple of 4)."""
    vals = [int(v) for v in vals]
    m = max((abs(v) for v in vals), default=0)
    if m == 0:
        return ZERO_HCB, 0
    candidates = [b for b in (1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11)
                  if BOOK_INFO[b][2] >= min(m, 16) or b == ESC_HCB]
    best = (ESC_HCB, None)
    for b in candidates:
        dim, signed, lav = BOOK_INFO[b]
        if b != ESC_HCB and m > lav:
            continue
        total = 0
        for i in range(0, len(vals), dim):
            total += group_bits(b, tuple(vals[i:i + dim]))
        if best[1] is None or total < best[1]:
            best = (b, total)
    return best


# ---------------------------------------------------------------------------
# Decoding
# ---------------------------------------------------------------------------

class _Tree:
    """Flat prefix-decode map: (length, code) -> index."""

    __slots__ = ("by_len",)

    def __init__(self, codes, bits):
        self.by_len: dict[int, dict[int, int]] = {}
        for idx, (c, b) in enumerate(zip(codes, bits)):
            self.by_len.setdefault(b, {})[c] = idx

    def read(self, r: BitReader) -> int:
        code = 0
        length = 0
        for _ in range(20):            # max codeword length is 19 (sf book)
            code = (code << 1) | r.read_bit()
            length += 1
            hit = self.by_len.get(length)
            if hit is not None and code in hit:
                return hit[code]
        raise ValueError("bad Huffman codeword")


_SPECTRAL_TREES = [
    _Tree(T.SPECTRAL_CODES[i], T.SPECTRAL_BITS[i]) for i in range(11)
]
_SF_TREE = _Tree(T.SCALEFACTOR_CODE, T.SCALEFACTOR_BITS)


def read_scalefactor(r: BitReader) -> int:
    """Returns the dpcm value in [-60, 60]."""
    return _SF_TREE.read(r) - 60


def _read_escape(r: BitReader) -> int:
    n = 4
    while r.read_bit() == 1:
        n += 1
    return (1 << n) + r.read_bits(n)


def read_group(r: BitReader, book: int) -> tuple[int, ...]:
    """Decode one codeword (+signs, +escapes) -> coefficient tuple."""
    dim, signed, lav = BOOK_INFO[book]
    idx = _SPECTRAL_TREES[book - 1].read(r)
    vals = list(book_values(book, idx))
    if not signed:
        for i, v in enumerate(vals):
            if v != 0 and r.read_bit():
                vals[i] = -v
        if book == ESC_HCB:
            for i, v in enumerate(vals):
                if abs(v) == 16:
                    mag = _read_escape(r)
                    vals[i] = -mag if v < 0 else mag
    return tuple(vals)
