"""MDCT / IMDCT + AAC window shapes (ISO/IEC 14496-3 4.6.11).

TPU-first design: the MDCT is a dense (N/2, N) cosine-basis matmul —
for 48 kHz audio a whole 30 s chunk is a (1407, 2048) x (2048, 1024)
batched matmul, exactly the shape the MXU wants. No FFT factorization
needed at these sizes; the matrix is 8 MB and lives in HBM.

The decoder's IMDCT mirrors it host-side in numpy (ingest is not the
hot path).

Conventions (calibrated against the libavcodec AAC decoder, see
tests/test_aac.py): forward X[k] = 2 sum_n z[n] cos(2pi/N (n+n0)(k+1/2)),
inverse x[n] = (2/N) sum_k X[k] cos(...), n0 = (N/2+1)/2 — the spec's
4.6.11.1 scaling, which independent decoders assume. Sine and KBD
windows per 4.6.11.3; with OLA the pair is unity-gain (Princen-Bradley
TDAC).
"""

from __future__ import annotations

import functools

import numpy as np

LONG_N = 2048
SHORT_N = 256

ONLY_LONG_SEQUENCE = 0
LONG_START_SEQUENCE = 1
EIGHT_SHORT_SEQUENCE = 2
LONG_STOP_SEQUENCE = 3


@functools.lru_cache(maxsize=8)
def mdct_matrix(n: int) -> np.ndarray:
    """(N/2, N) forward cosine basis."""
    n0 = (n // 2 + 1) / 2.0
    k = np.arange(n // 2, dtype=np.float64)[:, None]
    t = np.arange(n, dtype=np.float64)[None, :]
    return np.cos(2.0 * np.pi / n * (t + n0) * (k + 0.5))


@functools.lru_cache(maxsize=8)
def sine_window(n: int) -> np.ndarray:
    """sin(pi/N (n + 1/2)), full length N (4.6.11.3.2)."""
    i = np.arange(n, dtype=np.float64)
    return np.sin(np.pi / n * (i + 0.5))


@functools.lru_cache(maxsize=8)
def kbd_window(n: int, alpha: float | None = None) -> np.ndarray:
    """Kaiser-Bessel-derived window (4.6.11.3.3): alpha=4 long, 6 short."""
    if alpha is None:
        alpha = 4.0 if n >= LONG_N else 6.0
    half = n // 2
    from numpy import i0

    t = np.arange(half + 1, dtype=np.float64)
    kaiser = i0(np.pi * alpha * np.sqrt(1.0 - (2.0 * t / half - 1.0) ** 2))
    cum = np.cumsum(kaiser)
    w_half = np.sqrt(cum[:half] / cum[half])
    return np.concatenate([w_half, w_half[::-1]])


def window_halves(shape: int, n: int) -> tuple[np.ndarray, np.ndarray]:
    """(rising, falling) halves for window_shape 0=sine, 1=KBD."""
    w = kbd_window(n) if shape else sine_window(n)
    return w[: n // 2], w[n // 2:]


def forward_mdct(frames: np.ndarray, basis: np.ndarray | None = None,
                 use_jax: bool = False):
    """(..., N) windowed time blocks -> (..., N/2) coefficients.

    Caller applies the window first (it varies per frame with
    transitions); this is the pure basis matmul so it can run inside a
    jit alongside the quantizer.
    """
    n = frames.shape[-1]
    m = mdct_matrix(n) if basis is None else basis
    if use_jax:
        import jax.numpy as jnp

        return 2.0 * jnp.einsum("kn,...n->...k", jnp.asarray(m, jnp.float32),
                                frames.astype(jnp.float32))
    return 2.0 * (frames.astype(np.float64) @ m.T)


def inverse_mdct(coeffs: np.ndarray) -> np.ndarray:
    """(..., N/2) coefficients -> (..., N) time aliased blocks (2/N scale)."""
    half = coeffs.shape[-1]
    n = half * 2
    m = mdct_matrix(n)
    return (2.0 / n) * (coeffs.astype(np.float64) @ m)
