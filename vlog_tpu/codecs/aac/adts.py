"""ADTS framing (ISO/IEC 14496-3 1.A.3) + AudioSpecificConfig.

ADTS is the raw-AAC transport used for test vectors and .aac dumps; MP4
carries the same raw_data_blocks with an AudioSpecificConfig in esds.
"""

from __future__ import annotations

from dataclasses import dataclass

SAMPLE_RATES = (96000, 88200, 64000, 48000, 44100, 32000, 24000, 22050,
                16000, 12000, 11025, 8000, 7350)

AOT_AAC_LC = 2


def sample_rate_index(rate: int) -> int:
    try:
        return SAMPLE_RATES.index(rate)
    except ValueError:
        raise ValueError(f"unsupported AAC sample rate {rate}") from None


@dataclass(frozen=True)
class AacConfig:
    sample_rate: int
    channels: int            # 1 or 2
    object_type: int = AOT_AAC_LC

    @property
    def sr_index(self) -> int:
        return sample_rate_index(self.sample_rate)

    def audio_specific_config(self) -> bytes:
        """2-byte ASC: 5-bit AOT, 4-bit sr index, 4-bit channel config."""
        v = (self.object_type << 11) | (self.sr_index << 7) | (self.channels << 3)
        return bytes([(v >> 8) & 0xFF, v & 0xFF])

    @classmethod
    def from_audio_specific_config(cls, asc: bytes) -> "AacConfig":
        if len(asc) < 2:
            raise ValueError("AudioSpecificConfig too short")
        v = (asc[0] << 8) | asc[1]
        aot = v >> 11
        sr_idx = (v >> 7) & 0xF
        ch = (v >> 3) & 0xF
        if sr_idx == 0xF:
            raise ValueError("explicit sample rate ASC not supported")
        return cls(sample_rate=SAMPLE_RATES[sr_idx], channels=ch,
                   object_type=aot)


def adts_header(config: AacConfig, frame_len: int) -> bytes:
    """7-byte ADTS header (no CRC) for one raw_data_block of frame_len
    payload bytes."""
    full = frame_len + 7
    profile = config.object_type - 1          # ADTS profile = AOT - 1
    h = bytearray(7)
    h[0] = 0xFF
    h[1] = 0xF1                               # MPEG-4, no CRC
    h[2] = (profile << 6) | (config.sr_index << 2) | ((config.channels >> 2) & 1)
    h[3] = ((config.channels & 3) << 6) | ((full >> 11) & 0x3)
    h[4] = (full >> 3) & 0xFF
    h[5] = ((full & 0x7) << 5) | 0x1F
    h[6] = 0xFC
    return bytes(h)


def split_adts_frames(data: bytes) -> list[bytes]:
    """ADTS stream -> whole frames WITH headers (what TS carriage needs:
    stream_type 0x0F is ADTS-framed AAC, ISO 13818-7)."""
    frames = []
    i = 0
    n = len(data)
    while i + 7 <= n:
        if data[i] != 0xFF or (data[i + 1] & 0xF0) != 0xF0:
            raise ValueError(f"bad ADTS syncword at {i}")
        full = ((data[i + 3] & 0x3) << 11) | (data[i + 4] << 3) \
            | (data[i + 5] >> 5)
        if full < 7 or i + full > n:
            raise ValueError("truncated ADTS frame")
        frames.append(data[i:i + full])
        i += full
    return frames


def split_adts(data: bytes) -> tuple[AacConfig, list[bytes]]:
    """ADTS stream -> (config, [raw_data_block payloads])."""
    frames = []
    cfg = None
    i = 0
    n = len(data)
    while i + 7 <= n:
        if data[i] != 0xFF or (data[i + 1] & 0xF0) != 0xF0:
            raise ValueError(f"bad ADTS syncword at {i}")
        crc_absent = data[i + 1] & 1
        profile = (data[i + 2] >> 6) + 1
        sr_idx = (data[i + 2] >> 2) & 0xF
        ch = ((data[i + 2] & 1) << 2) | (data[i + 3] >> 6)
        full = ((data[i + 3] & 0x3) << 11) | (data[i + 4] << 3) | (data[i + 5] >> 5)
        if full < 7 or i + full > n:
            raise ValueError("truncated ADTS frame")
        hdr = 7 if crc_absent else 9
        if cfg is None:
            cfg = AacConfig(sample_rate=SAMPLE_RATES[sr_idx], channels=ch,
                            object_type=profile)
        frames.append(data[i + hdr:i + full])
        i += full
    if cfg is None:
        raise ValueError("no ADTS frames found")
    return cfg, frames
