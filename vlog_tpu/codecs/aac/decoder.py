"""First-party AAC-LC decoder (ISO/IEC 14496-3 4.4-4.6).

The ingest half of the audio pipeline: MP4/ADTS uploads carry AAC that
must become PCM for the ladder re-encode and for transcription
(reference: ffmpeg decodes inside the transcode command,
worker/hwaccel.py:700-706; transcription.py:259-299 extracts WAV).

Host-side numpy by design: ingest decode is I/O-adjacent, not the hot
loop (the encoder's MDCT/quantization is the TPU side). Supports the
LC toolset actually seen in uploads: long/short/start/stop windows,
sine+KBD shapes, M/S, intensity stereo, PNS, TNS, pulse data. Not
supported (raise): LTP, gain control, CCE, PCE program config.

Validated against the system libavcodec decoder in tests/test_aac.py
(bit-exact spectra are not meaningful across float IMDCTs; tests assert
high SNR agreement instead).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from vlog_tpu.codecs.aac import huffman as H
from vlog_tpu.codecs.aac import tables as T
from vlog_tpu.codecs.aac.adts import AacConfig
from vlog_tpu.codecs.aac.mdct import (
    EIGHT_SHORT_SEQUENCE,
    LONG_START_SEQUENCE,
    LONG_STOP_SEQUENCE,
    ONLY_LONG_SEQUENCE,
    inverse_mdct,
    window_halves,
)
from vlog_tpu.media.bitstream import BitReader

SF_OFFSET = 100          # spec 4.6.2.3.3: gain = 2^(0.25*(sf - 100))


class AacDecodeError(ValueError):
    pass


@dataclass
class IcsInfo:
    window_sequence: int
    window_shape: int
    max_sfb: int
    num_windows: int
    num_window_groups: int
    group_len: list[int]          # windows per group
    swb_offset: list[int]
    num_swb: int


@dataclass
class ChannelData:
    """Per-channel decode intermediates for one frame."""

    ics: IcsInfo
    global_gain: int = 0
    band_books: list[int] = field(default_factory=list)     # per (group, sfb)
    scalefactors: list[int] = field(default_factory=list)   # sf / is_pos / noise
    coeffs: np.ndarray | None = None                        # (1024,) dequantized
    quant: np.ndarray | None = None                         # (1024,) raw levels
    tns: dict | None = None


def _parse_ics_info(r: BitReader, sr_index: int) -> IcsInfo:
    if r.read_bit():
        raise AacDecodeError("ics_reserved_bit set")
    seq = r.read_bits(2)
    shape = r.read_bit()
    if seq == EIGHT_SHORT_SEQUENCE:
        max_sfb = r.read_bits(4)
        grouping = r.read_bits(7)
        group_len = [1]
        for b in range(6, -1, -1):
            if (grouping >> b) & 1:
                group_len[-1] += 1
            else:
                group_len.append(1)
        swb = T.SWB_OFFSET_128[sr_index]
        num_swb = T.NUM_SWB_128[sr_index]
        return IcsInfo(seq, shape, max_sfb, 8, len(group_len), group_len,
                       swb, num_swb)
    max_sfb = r.read_bits(6)
    if r.read_bit():
        raise AacDecodeError("predictor/LTP not supported in LC")
    swb = T.SWB_OFFSET_1024[sr_index]
    num_swb = T.NUM_SWB_1024[sr_index]
    return IcsInfo(seq, shape, max_sfb, 1, 1, [1], swb, num_swb)


def _parse_section_data(r: BitReader, ics: IcsInfo) -> list[int]:
    """Per-(group, sfb) codebook list."""
    bits = 3 if ics.window_sequence == EIGHT_SHORT_SEQUENCE else 5
    esc = (1 << bits) - 1
    books: list[int] = []
    for g in range(ics.num_window_groups):
        k = 0
        while k < ics.max_sfb:
            cb = r.read_bits(4)
            length = 0
            while True:
                incr = r.read_bits(bits)
                length += incr
                if incr != esc:
                    break
            if k + length > ics.max_sfb:
                raise AacDecodeError("section overruns max_sfb")
            books.extend([cb] * length)
            k += length
    return books


def _parse_scale_factors(r: BitReader, ics: IcsInfo, books: list[int],
                         global_gain: int) -> list[int]:
    sf = global_gain
    is_pos = 0
    noise_energy = global_gain - 90
    noise_first = True
    out: list[int] = []
    for g in range(ics.num_window_groups):
        for b in range(ics.max_sfb):
            cb = books[g * ics.max_sfb + b]
            if cb == H.ZERO_HCB:
                out.append(0)
            elif cb in (H.INTENSITY_HCB, H.INTENSITY_HCB2):
                is_pos += H.read_scalefactor(r)
                out.append(is_pos)
            elif cb == H.NOISE_HCB:
                if noise_first:
                    noise_energy += r.read_bits(9) - 256
                    noise_first = False
                else:
                    noise_energy += H.read_scalefactor(r)
                out.append(noise_energy)
            else:
                sf += H.read_scalefactor(r)
                if not 0 <= sf < 256:
                    raise AacDecodeError(f"scalefactor {sf} out of range")
                out.append(sf)
    return out


def _parse_pulse(r: BitReader) -> dict:
    n = r.read_bits(2) + 1
    start_sfb = r.read_bits(6)
    offsets = []
    amps = []
    for _ in range(n):
        offsets.append(r.read_bits(5))
        amps.append(r.read_bits(4))
    return {"start_sfb": start_sfb, "offsets": offsets, "amps": amps}


def _parse_tns(r: BitReader, ics: IcsInfo) -> dict:
    short = ics.window_sequence == EIGHT_SHORT_SEQUENCE
    n_filt_bits, len_bits, order_bits = (1, 4, 3) if short else (2, 6, 5)
    windows = []
    for w in range(ics.num_windows):
        n_filt = r.read_bits(n_filt_bits)
        filters = []
        coef_res = r.read_bit() if n_filt else 0
        for _ in range(n_filt):
            length = r.read_bits(len_bits)
            order = r.read_bits(order_bits)
            f = {"length": length, "order": order}
            if order:
                f["direction"] = r.read_bit()
                compress = r.read_bit()
                bits = coef_res + 3 - compress
                f["coef_res"] = coef_res
                f["compress"] = compress
                f["coefs"] = [r.read_bits(bits) for _ in range(order)]
            filters.append(f)
        windows.append(filters)
    return {"windows": windows}


def _tns_lpc(f: dict) -> np.ndarray:
    """Quantized TNS coefficients -> direct-form LPC (spec 4.6.9.3)."""
    coef_res = f["coef_res"]
    bits = coef_res + 3 - f["compress"]
    rng = 1 << (bits - 1)
    iqfac = ((1 << (coef_res + 3 - 1)) - 0.5) / (np.pi / 2.0)
    iqfac_m = ((1 << (coef_res + 3 - 1)) + 0.5) / (np.pi / 2.0)
    refl = []
    for c in f["coefs"]:
        v = c - 2 * rng if c >= rng else c          # sign-extend
        refl.append(np.sin(v / (iqfac if v >= 0 else iqfac_m)))
    # reflection -> direct form (Levinson-Durbin style recursion)
    a = np.zeros(f["order"] + 1)
    a[0] = 1.0
    for m in range(1, f["order"] + 1):
        b = a.copy()
        for i in range(1, m):
            b[i] = a[i] + refl[m - 1] * a[m - i]
        b[m] = refl[m - 1]
        a = b
    return a


def _apply_tns(spec: np.ndarray, ics: IcsInfo, tns: dict,
               sr_index: int) -> None:
    short = ics.window_sequence == EIGHT_SHORT_SEQUENCE
    tns_max = (T.TNS_MAX_BANDS_128 if short else T.TNS_MAX_BANDS_1024)[sr_index]
    wlen = 128 if short else 1024
    for w, filters in enumerate(tns["windows"]):
        bottom = ics.num_swb
        for f in filters:
            top = bottom
            bottom = max(top - f["length"], 0)
            if not f["order"]:
                continue
            lpc = _tns_lpc(f)
            start_b = min(bottom, tns_max, ics.max_sfb)
            end_b = min(top, tns_max, ics.max_sfb)
            start = ics.swb_offset[start_b]
            end = ics.swb_offset[end_b]
            if end <= start:
                continue
            sl = spec[w * wlen + start: w * wlen + end]
            order = f["order"]
            if f.get("direction"):
                for i in range(len(sl) - 2, -1, -1):
                    acc = sl[i]
                    for k in range(1, min(order, len(sl) - 1 - i) + 1):
                        acc -= lpc[k] * sl[i + k]
                    sl[i] = acc
            else:
                for i in range(1, len(sl)):
                    acc = sl[i]
                    for k in range(1, min(order, i) + 1):
                        acc -= lpc[k] * sl[i - k]
                    sl[i] = acc


def _parse_spectral(r: BitReader, ics: IcsInfo, books: list[int]) -> np.ndarray:
    """Huffman-decode quantized levels -> (1024,) in deinterleaved
    (per-window) order."""
    quant = np.zeros(1024, np.int32)
    wlen = 128 if ics.window_sequence == EIGHT_SHORT_SEQUENCE else 1024
    win_base = 0
    for g, glen in enumerate(ics.group_len[: ics.num_window_groups]):
        for b in range(ics.max_sfb):
            cb = books[g * ics.max_sfb + b]
            lo, hi = ics.swb_offset[b], ics.swb_offset[b + 1]
            width = hi - lo
            if cb in (H.ZERO_HCB, H.NOISE_HCB, H.INTENSITY_HCB,
                      H.INTENSITY_HCB2):
                continue
            dim = H.BOOK_INFO[cb][0]
            for w in range(glen):
                dst = (win_base + w) * wlen + lo
                i = 0
                while i < width:
                    vals = H.read_group(r, cb)
                    quant[dst + i: dst + i + dim] = vals
                    i += dim
        win_base += glen
    return quant


def _dequantize(ch: ChannelData, sr_index: int) -> np.ndarray:
    ics = ch.ics
    wlen = 128 if ics.window_sequence == EIGHT_SHORT_SEQUENCE else 1024
    q = ch.quant.astype(np.float64)
    spec = np.sign(q) * np.abs(q) ** (4.0 / 3.0)
    win_base = 0
    for g, glen in enumerate(ics.group_len[: ics.num_window_groups]):
        for b in range(ics.max_sfb):
            idx = g * ics.max_sfb + b
            cb = ch.band_books[idx]
            lo, hi = ics.swb_offset[b], ics.swb_offset[b + 1]
            if cb in (H.INTENSITY_HCB, H.INTENSITY_HCB2):
                continue                       # filled from left channel later
            if cb == H.NOISE_HCB:
                continue                       # filled in PNS stage
            if cb == H.ZERO_HCB:
                continue
            gain = 2.0 ** (0.25 * (ch.scalefactors[idx] - SF_OFFSET))
            for w in range(glen):
                s = (win_base + w) * wlen
                spec[s + lo: s + hi] *= gain
        win_base += glen
    return spec


def _apply_pns(ch: ChannelData, spec: np.ndarray, rng: np.random.Generator
               ) -> None:
    ics = ch.ics
    wlen = 128 if ics.window_sequence == EIGHT_SHORT_SEQUENCE else 1024
    win_base = 0
    for g, glen in enumerate(ics.group_len[: ics.num_window_groups]):
        for b in range(ics.max_sfb):
            idx = g * ics.max_sfb + b
            if ch.band_books[idx] != H.NOISE_HCB:
                continue
            lo, hi = ics.swb_offset[b], ics.swb_offset[b + 1]
            target = 2.0 ** (0.5 * (ch.scalefactors[idx] - SF_OFFSET))
            for w in range(glen):
                s = (win_base + w) * wlen
                noise = rng.normal(0.0, 1.0, hi - lo)
                norm = np.sqrt(np.sum(noise * noise)) or 1.0
                spec[s + lo: s + hi] = noise / norm * np.sqrt(target * (hi - lo))
        win_base += glen


@dataclass
class _ChannelState:
    overlap: np.ndarray = field(default_factory=lambda: np.zeros(1024))
    prev_shape: int = 0


class AacDecoder:
    """Stateful LC decoder: feed raw_data_block payloads, get PCM."""

    def __init__(self, config: AacConfig):
        if config.object_type != 2:
            raise AacDecodeError(f"AOT {config.object_type} not supported (LC only)")
        self.config = config
        self.sr_index = config.sr_index
        self._state = [_ChannelState() for _ in range(max(config.channels, 2))]
        self._noise_rng = np.random.default_rng(0x5EED)

    # -- element parsing ---------------------------------------------------
    def _parse_ics(self, r: BitReader, common_ics: IcsInfo | None) -> ChannelData:
        global_gain = r.read_bits(8)
        ics = common_ics or _parse_ics_info(r, self.sr_index)
        ch = ChannelData(ics=ics, global_gain=global_gain)
        ch.band_books = _parse_section_data(r, ics)
        ch.scalefactors = _parse_scale_factors(r, ics, ch.band_books,
                                               global_gain)
        pulse = None
        if r.read_bit():
            if ics.window_sequence == EIGHT_SHORT_SEQUENCE:
                raise AacDecodeError("pulse data with short windows")
            pulse = _parse_pulse(r)
        ch.tns = _parse_tns(r, ics) if r.read_bit() else None
        if r.read_bit():
            raise AacDecodeError("gain_control not supported")
        ch.quant = _parse_spectral(r, ics, ch.band_books)
        if pulse:
            base = ics.swb_offset[pulse["start_sfb"]]
            k = base
            for off, amp in zip(pulse["offsets"], pulse["amps"]):
                k += off
                if k < 1024:
                    q = ch.quant[k]
                    ch.quant[k] = q + amp if q >= 0 else q - amp
        return ch

    def _finish_channel(self, ch: ChannelData, spec: np.ndarray,
                        ch_index: int) -> np.ndarray:
        if ch.tns:
            _apply_tns(spec, ch.ics, ch.tns, self.sr_index)
        return self._filterbank(spec, ch.ics, ch_index)

    # -- filterbank --------------------------------------------------------
    def _filterbank(self, spec: np.ndarray, ics: IcsInfo, ci: int
                    ) -> np.ndarray:
        st = self._state[ci]
        seq = ics.window_sequence
        shape = ics.window_shape
        prev = st.prev_shape
        out = np.zeros(1024)
        if seq in (ONLY_LONG_SEQUENCE, LONG_START_SEQUENCE,
                   LONG_STOP_SEQUENCE):
            x = inverse_mdct(spec)                      # (2048,)
            # first half window: prev frame's shape; transitions per spec
            if seq == LONG_STOP_SEQUENCE:
                rise = np.concatenate([
                    np.zeros(448), window_halves(prev, 256)[0], np.ones(576)])
            else:
                rise = window_halves(prev, 2048)[0]
            if seq == LONG_START_SEQUENCE:
                fall = np.concatenate([
                    np.ones(576), window_halves(shape, 256)[1], np.zeros(448)])
            else:
                fall = window_halves(shape, 2048)[1]
            first = x[:1024] * rise
            second = x[1024:] * fall
            out = st.overlap + first
            st.overlap = second
        elif seq == EIGHT_SHORT_SEQUENCE:
            acc = np.zeros(2048)
            rise0 = window_halves(prev, 256)[0]
            for w in range(8):
                xw = inverse_mdct(spec[w * 128:(w + 1) * 128])   # (256,)
                rise = rise0 if w == 0 else window_halves(shape, 256)[0]
                fall = window_halves(shape, 256)[1]
                start = 448 + w * 128
                acc[start:start + 256] += np.concatenate(
                    [xw[:128] * rise, xw[128:] * fall])
            out = st.overlap + acc[:1024]
            st.overlap = acc[1024:]
        else:
            raise AacDecodeError(f"bad window sequence {seq}")
        st.prev_shape = shape
        return out

    # -- public ------------------------------------------------------------
    def decode_frame(self, payload: bytes) -> np.ndarray:
        """One raw_data_block -> (channels, 1024) float PCM in [-1, 1)."""
        r = BitReader(payload)
        outs: list[np.ndarray] = []
        while True:
            ele = r.read_bits(3)
            if ele == 7:                                   # END
                break
            if ele in (0, 3):                              # SCE / LFE
                r.read_bits(4)                             # element id
                ch = self._parse_ics(r, None)
                spec = _dequantize(ch, self.sr_index)
                _apply_pns(ch, spec, self._noise_rng)
                outs.append(self._finish_channel(ch, spec, len(outs)))
            elif ele == 1:                                 # CPE
                r.read_bits(4)
                common = r.read_bit()
                ms_mask_present = 0
                ms_used: list[int] = []
                ics = None
                if common:
                    ics = _parse_ics_info(r, self.sr_index)
                    ms_mask_present = r.read_bits(2)
                    if ms_mask_present == 1:
                        nb = ics.num_window_groups * ics.max_sfb
                        ms_used = [r.read_bit() for _ in range(nb)]
                left = self._parse_ics(r, ics)
                right = self._parse_ics(r, ics)
                ls = _dequantize(left, self.sr_index)
                rs = _dequantize(right, self.sr_index)
                _apply_pns(left, ls, self._noise_rng)
                _apply_pns(right, rs, self._noise_rng)
                self._stereo_tools(left, right, ls, rs, ms_mask_present,
                                   ms_used)
                outs.append(self._finish_channel(left, ls, len(outs)))
                outs.append(self._finish_channel(right, rs, len(outs)))
            elif ele == 4:                                 # DSE
                r.read_bits(4)
                align = r.read_bit()
                cnt = r.read_bits(8)
                if cnt == 255:
                    cnt += r.read_bits(8)
                if align:
                    r.byte_align()
                for _ in range(cnt):
                    r.read_bits(8)
            elif ele == 6:                                 # FIL
                cnt = r.read_bits(4)
                if cnt == 15:
                    cnt += r.read_bits(8) - 1
                for _ in range(cnt):
                    r.read_bits(8)
            else:
                raise AacDecodeError(f"unsupported syntactic element {ele}")
        # scale to [-1, 1): spec PCM is full-scale int16-ish after /32768
        return np.stack(outs) / 32768.0 if outs else np.zeros((0, 1024))

    def _stereo_tools(self, left: ChannelData, right: ChannelData,
                      ls: np.ndarray, rs: np.ndarray, ms_mask_present: int,
                      ms_used: list[int]) -> None:
        ics = left.ics
        wlen = 128 if ics.window_sequence == EIGHT_SHORT_SEQUENCE else 1024
        win_base = 0
        for g, glen in enumerate(ics.group_len[: ics.num_window_groups]):
            for b in range(ics.max_sfb):
                idx = g * ics.max_sfb + b
                lo, hi = ics.swb_offset[b], ics.swb_offset[b + 1]
                rcb = right.band_books[idx] if idx < len(right.band_books) else 0
                is_band = rcb in (H.INTENSITY_HCB, H.INTENSITY_HCB2)
                ms_band = (ms_mask_present == 2
                           or (ms_mask_present == 1 and idx < len(ms_used)
                               and ms_used[idx]))
                for w in range(glen):
                    s = (win_base + w) * wlen
                    sl = slice(s + lo, s + hi)
                    if is_band:
                        sign = -1.0 if rcb == H.INTENSITY_HCB2 else 1.0
                        if ms_mask_present == 1 and idx < len(ms_used) \
                                and ms_used[idx]:
                            sign = -sign
                        scale = 0.5 ** (0.25 * right.scalefactors[idx])
                        rs[sl] = sign * scale * ls[sl]
                    elif ms_band:
                        m = ls[sl].copy()
                        sdiff = rs[sl].copy()
                        ls[sl] = m + sdiff
                        rs[sl] = m - sdiff
            win_base += glen


def decode_adts(data: bytes) -> tuple[AacConfig, np.ndarray]:
    """Whole ADTS stream -> (config, (channels, n_samples) float PCM)."""
    from vlog_tpu.codecs.aac.adts import split_adts

    cfg, frames = split_adts(data)
    dec = AacDecoder(cfg)
    chunks = [dec.decode_frame(f) for f in frames]
    return cfg, np.concatenate(chunks, axis=1) if chunks else np.zeros((0, 0))
