"""First-party AAC-LC encoder — TPU MDCT, host entropy coding.

Replaces the reference's ``-c:a aac`` (ffmpeg's encoder,
worker/hwaccel.py:700-706): every ladder rung gets an AAC track at the
ladder's audio bitrate (README.md:201-212). Split mirrors the video
path: the O(N^2) filterbank runs as one batched MXU matmul over a whole
chunk of frames (mdct.py), scalefactor selection + quantization are
vectorized numpy, and the serial Huffman/bitstream pack stays on host
(huffman.py) — same device/host line the H.264 encoder draws.

Toolset: long windows only (window_sequence=0, sine shape), per-band
scalefactors via a constant-SNR allocation, closed-loop bit targeting
with the shared RateController. No TNS/PNS/M-S on the encode side —
they buy quality at low rates; the ladder's 96-192 kbps targets don't
need them for transparency-adjacent output. Decodable by any LC
decoder; validated against libavcodec in tests/test_aac.py.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from vlog_tpu.codecs.aac import huffman as H
from vlog_tpu.codecs.aac import tables as T
from vlog_tpu.codecs.aac.adts import AacConfig, adts_header
from vlog_tpu.codecs.aac.decoder import SF_OFFSET
from vlog_tpu.codecs.aac.mdct import forward_mdct, mdct_matrix, sine_window
from vlog_tpu.backends.rate_control import RateController
from vlog_tpu.media.bitstream import BitWriter

MAX_QUANT = 8191                 # spec cap for escape coding
_ROUND = 0.4054                  # standard AAC quantizer rounding offset


def _frame_blocks(pcm: np.ndarray) -> np.ndarray:
    """(n_samples,) float -> (n_frames, 2048) overlapped 50% blocks.

    Prepends one priming frame of zeros (standard 1024-sample encoder
    delay) and zero-pads the tail.
    """
    n = pcm.shape[-1]
    n_frames = (n + 1024 - 1) // 1024 + 1
    padded = np.zeros((n_frames + 1) * 1024)
    padded[1024:1024 + n] = pcm
    idx = np.arange(2048)[None, :] + 1024 * np.arange(n_frames)[:, None]
    return padded[idx]


def _quantize_frame(spec: np.ndarray, sfs: np.ndarray,
                    swb: list[int], max_sfb: int) -> np.ndarray:
    """Spec coefficients + per-band scalefactors -> quantized levels."""
    q = np.zeros(1024, np.int32)
    for b in range(max_sfb):
        lo, hi = swb[b], swb[b + 1]
        gain = 2.0 ** (0.25 * (sfs[b] - SF_OFFSET))
        x = spec[lo:hi] / gain
        mag = np.floor(np.abs(x) ** 0.75 + _ROUND).astype(np.int64)
        q[lo:hi] = (np.sign(x) * np.minimum(mag, MAX_QUANT)).astype(np.int32)
    return q


@dataclass
class AacEncoder:
    """Stateful LC encoder; feed (channels, n) float PCM chunks in order."""

    sample_rate: int = 48000
    channels: int = 2
    bitrate: int = 128_000

    def __post_init__(self) -> None:
        self.config = AacConfig(sample_rate=self.sample_rate,
                                channels=self.channels)
        sr = self.config.sr_index
        self.swb = T.SWB_OFFSET_1024[sr]
        self.num_swb = T.NUM_SWB_1024[sr]
        self.max_sfb = self.num_swb
        frame_rate = self.sample_rate / 1024.0
        # Reuse the video loop: "frames" are AAC frames; bytes per frame
        # tracks the audio bitrate. Wide QP range maps to base scalefactor.
        self._rc = RateController(
            target_bps=self.bitrate, fps=frame_rate, init_qp=148,
            min_qp=80, max_qp=250, max_step=6,
            # the scalefactor rate curve is smooth across ~170 steps;
            # single-step probing (a video-cliff defense) would drag
            # undershoot recovery out 6x
            converged_down_step=6.0)
        self._window = sine_window(2048)
        self._basis = mdct_matrix(2048)

    # -- DSP ---------------------------------------------------------------
    def _mdct_all(self, pcm: np.ndarray) -> np.ndarray:
        """(channels, n) -> (channels, n_frames, 1024) via one batched
        matmul per chunk (device when JAX is initialized on one)."""
        blocks = np.stack([_frame_blocks(c * 32768.0) for c in pcm])
        windowed = blocks * self._window
        try:
            import jax

            out = forward_mdct(jax.numpy.asarray(windowed, jax.numpy.float32),
                               basis=self._basis, use_jax=True)
            return np.asarray(out, dtype=np.float64)
        except Exception:
            return forward_mdct(windowed)

    # -- per-frame coding --------------------------------------------------
    def _choose_scalefactors(self, spec: np.ndarray, base_sf: int
                             ) -> np.ndarray:
        """Constant-SNR allocation: quantizer step follows band amplitude
        (sqrt-energy), anchored at the rate-controlled base."""
        sfs = np.full(self.max_sfb, base_sf, np.int32)
        amps = np.empty(self.max_sfb)
        for b in range(self.max_sfb):
            lo, hi = self.swb[b], self.swb[b + 1]
            amps[b] = np.sqrt(np.mean(spec[lo:hi] ** 2) + 1e-9)
        ref = np.exp(np.mean(np.log(amps + 1e-9)))
        adj = np.round(2.0 * np.log2((amps + 1e-9) / ref)).astype(np.int32)
        sfs = np.clip(base_sf + adj, 1, 255)
        # Ensure escape-code range: raise sf where |q| would exceed cap.
        for b in range(self.max_sfb):
            lo, hi = self.swb[b], self.swb[b + 1]
            peak = np.max(np.abs(spec[lo:hi])) if hi > lo else 0.0
            while peak > 0:
                gain = 2.0 ** (0.25 * (sfs[b] - SF_OFFSET))
                if (peak / gain) ** 0.75 + _ROUND <= MAX_QUANT:
                    break
                sfs[b] += 4
        # DPCM deltas must fit the sf codebook (+-60): smooth the chain.
        for b in range(1, self.max_sfb):
            sfs[b] = np.clip(sfs[b], sfs[b - 1] - 60, sfs[b - 1] + 60)
        return sfs

    def _code_channel(self, w: BitWriter, spec: np.ndarray,
                      common_window: bool) -> int:
        """individual_channel_stream; returns payload bit count."""
        start_bits = w.bit_length
        sfs = self._choose_scalefactors(spec, self._rc.qp)
        quant = _quantize_frame(spec, sfs, self.swb, self.max_sfb)

        # Per-band codebooks (exact-cost best pick).
        books = []
        for b in range(self.max_sfb):
            lo, hi = self.swb[b], self.swb[b + 1]
            book, _ = H.best_book(list(quant[lo:hi]))
            books.append(book)

        # global_gain anchors the sf DPCM chain at the first coded band.
        coded = [b for b in range(self.max_sfb) if books[b] != H.ZERO_HCB]
        global_gain = int(sfs[coded[0]]) if coded else int(self._rc.qp)
        w.write_bits(global_gain, 8)

        if not common_window:
            self._write_ics_info(w)

        # section_data (5-bit length escapes, long windows)
        b = 0
        while b < self.max_sfb:
            e = b
            while e < self.max_sfb and books[e] == books[b]:
                e += 1
            w.write_bits(books[b], 4)
            length = e - b
            while length >= 31:
                w.write_bits(31, 5)
                length -= 31
            w.write_bits(length, 5)
            b = e

        # scale_factor_data (DPCM from global_gain, coded bands only)
        prev = global_gain
        for b in coded:
            H.write_scalefactor(w, int(sfs[b]) - prev)
            prev = int(sfs[b])

        w.write_bit(0)      # pulse_data_present
        w.write_bit(0)      # tns_data_present
        w.write_bit(0)      # gain_control_data_present

        # spectral_data
        for b in range(self.max_sfb):
            book = books[b]
            if book == H.ZERO_HCB:
                continue
            dim = H.BOOK_INFO[book][0]
            lo, hi = self.swb[b], self.swb[b + 1]
            for i in range(lo, hi, dim):
                H.write_group(w, book, tuple(int(v) for v in quant[i:i + dim]))
        return w.bit_length - start_bits

    def _write_ics_info(self, w: BitWriter) -> None:
        w.write_bit(0)                  # ics_reserved
        w.write_bits(0, 2)              # ONLY_LONG_SEQUENCE
        w.write_bit(0)                  # sine window
        w.write_bits(self.max_sfb, 6)
        w.write_bit(0)                  # predictor_data_present

    def encode_frames(self, pcm: np.ndarray) -> list[bytes]:
        """(channels, n_samples) float [-1,1) -> raw_data_block payloads.

        One batched MDCT for the whole chunk, then per-frame entropy
        coding with closed-loop bit targeting.
        """
        pcm = np.atleast_2d(pcm)
        if pcm.shape[0] != self.channels:
            raise ValueError(
                f"expected {self.channels} channels, got {pcm.shape[0]}")
        spec = self._mdct_all(pcm)          # (ch, frames, 1024)
        payloads = []
        for f in range(spec.shape[1]):
            w = BitWriter()
            if self.channels == 1:
                w.write_bits(0, 3)          # SCE
                w.write_bits(0, 4)
                self._code_channel(w, spec[0, f], common_window=False)
            else:
                w.write_bits(1, 3)          # CPE
                w.write_bits(0, 4)
                w.write_bit(1)              # common_window
                self._write_ics_info(w)
                w.write_bits(0, 2)          # ms_mask_present = 0
                self._code_channel(w, spec[0, f], common_window=True)
                self._code_channel(w, spec[1, f], common_window=True)
            w.write_bits(7, 3)              # END
            w.byte_align()
            payload = w.getvalue()
            self._rc.observe(len(payload), 1)
            payloads.append(payload)
        return payloads

    def encode_adts(self, pcm: np.ndarray) -> bytes:
        """Convenience: PCM -> ADTS stream (for tests / .aac dumps)."""
        out = bytearray()
        for p in self.encode_frames(pcm):
            out += adts_header(self.config, len(p)) + p
        return bytes(out)
