"""First-party AAC-LC codec: TPU-batched MDCT encoder + host decoder.

Replaces the reference's delegation of all audio to ffmpeg's aac codec
(worker/hwaccel.py:700-706 encode; transcription.py:259-299 decode).
"""

from vlog_tpu.codecs.aac.adts import AacConfig, adts_header, split_adts
from vlog_tpu.codecs.aac.decoder import AacDecoder, decode_adts
from vlog_tpu.codecs.aac.encoder import AacEncoder

__all__ = [
    "AacConfig",
    "AacDecoder",
    "AacEncoder",
    "adts_header",
    "decode_adts",
    "split_adts",
]
