"""High-level HEVC encoding API: frames in, packaged samples out.

Mirror of codecs/h264/api.py for the H.265 path: the backend drives one
``HevcEncoder`` per quality rung; DSP runs batched on the device
(jax_core), entropy coding runs on the host — the C coder
(native/hevc_cabac.c) when buildable, else the Python reference — in
parallel threads per frame.

Reference parity: hevc_nvenc / hevc_vaapi selection in
worker/hwaccel.py:509-552; re-encode codec upgrades in
worker/reencode_worker.py.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from vlog_tpu.codecs.hevc import syntax
from vlog_tpu.codecs.hevc.slice import SliceWriter

CTB = syntax.CTB


@dataclass
class EncodedFrame:
    sample: bytes        # 4-byte-length-prefixed NAL (hvc1 sample format)
    annexb: bytes
    is_idr: bool
    psnr_y: float


def _u8(v):
    return bytes([v & 0xFF])


def _u16(v):
    return v.to_bytes(2, "big")


def hvcc_config(vps: syntax.NalUnit, sps: syntax.NalUnit,
                pps: syntax.NalUnit, level_idc: int) -> bytes:
    """HEVCDecoderConfigurationRecord (ISO 14496-15 8.3.3.1) for the
    stream shape syntax.py emits (Main profile, tier 0)."""
    out = bytearray()
    out += _u8(1)                      # configurationVersion
    out += _u8(1)                      # profile_space 0, tier 0, idc Main
    out += (0x60000000).to_bytes(4, "big")   # compat: Main + Main 10
    # constraints: progressive + non-packed + frame-only (bits 7,5,4)
    out += bytes([0xB0, 0, 0, 0, 0, 0])
    out += _u8(level_idc)
    out += _u16(0xF000)                # reserved + min_spatial_seg 0
    out += _u8(0xFC)                   # reserved + parallelismType 0
    out += _u8(0xFC | 1)               # reserved + chroma 4:2:0
    out += _u8(0xF8)                   # bit_depth_luma_minus8 = 0
    out += _u8(0xF8)                   # bit_depth_chroma_minus8 = 0
    out += _u16(0)                     # avgFrameRate unknown
    out += _u8((1 << 3) | (1 << 2) | 3)  # 1 layer, nested, 4-byte lengths
    out += _u8(3)                      # numOfArrays
    for nal in (vps, sps, pps):
        raw = nal.to_bytes()
        out += _u8(0x80 | nal.nal_type)   # array_completeness | type
        out += _u16(1) + _u16(len(raw)) + raw
    return bytes(out)


@dataclass
class HevcEncoder:
    """Stateful per-rung encoder; every frame is an IDR (all-intra, the
    same GOP shape as the H.264 intra path)."""

    width: int
    height: int
    fps_num: int = 30
    fps_den: int = 1
    qp: int = 30
    entropy_threads: int = 8

    def __post_init__(self):
        self.vps = syntax.write_vps(
            syntax.level_idc_for(self.width, self.height))
        self.sps = syntax.write_sps(self.width, self.height)
        self.pps = syntax.write_pps()

    # ---- stream metadata -----------------------------------------------
    @property
    def hvcc_config(self) -> bytes:
        return hvcc_config(self.vps, self.sps, self.pps,
                           syntax.level_idc_for(self.width, self.height))

    @property
    def codec_string(self) -> str:
        """RFC 6381: hvc1.<profile>.<compat-reversed>.L<level>.<constraints>"""
        return f"hvc1.1.6.L{syntax.level_idc_for(self.width, self.height)}.B0"

    def headers_annexb(self) -> bytes:
        return syntax.annexb([self.vps, self.sps, self.pps])

    # ---- encoding -------------------------------------------------------
    def _pad(self, plane: np.ndarray, block: int) -> np.ndarray:
        b, h, w = plane.shape
        ph = (h + block - 1) // block * block
        pw = (w + block - 1) // block * block
        if (ph, pw) == (h, w):
            return plane
        return np.pad(plane, ((0, 0), (0, ph - h), (0, pw - w)),
                      mode="edge")

    def _entropy(self, ly, lu, lv, rows, cols) -> bytes:
        from vlog_tpu.native.build import get_lib

        lib = get_lib()
        if lib is not None:
            import ctypes

            la = np.ascontiguousarray(ly.reshape(-1), dtype=np.int16)
            ua = np.ascontiguousarray(lu.reshape(-1), dtype=np.int16)
            va = np.ascontiguousarray(lv.reshape(-1), dtype=np.int16)
            cap = max(1 << 16, la.size * 4)
            out = np.empty(cap, dtype=np.uint8)
            i16p = ctypes.POINTER(ctypes.c_int16)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            n = lib.vt_hevc_encode_slice(
                la.ctypes.data_as(i16p), ua.ctypes.data_as(i16p),
                va.ctypes.data_as(i16p), rows, cols, self.qp,
                out.ctypes.data_as(u8p), cap)
            if n >= 0:
                return out[:n].tobytes()
        sw = SliceWriter(self.qp)
        for r in range(rows):
            for c in range(cols):
                sw.write_ctu(c, ly[r, c], lu[r, c], lv[r, c],
                             last_in_slice=(r == rows - 1 and c == cols - 1))
        return sw.payload()

    def encode_batch(self, y: np.ndarray, u: np.ndarray, v: np.ndarray,
                     pool: ThreadPoolExecutor | None = None
                     ) -> list[EncodedFrame]:
        """Encode a batch of frames: y (B, H, W), u/v (B, H/2, W/2)
        uint8.  DSP runs as one device dispatch; entropy per frame in
        threads."""
        from vlog_tpu.codecs.hevc.jax_core import encode_batch_dsp

        y = self._pad(np.asarray(y, np.uint8), CTB)
        u = self._pad(np.asarray(u, np.uint8), CTB // 2)
        v = self._pad(np.asarray(v, np.uint8), CTB // 2)
        b, h, w = y.shape
        rows, cols = h // CTB, w // CTB
        qps = np.full((b,), self.qp, np.int32)
        (ly, lu, lv), (ry, _, _) = encode_batch_dsp(y, u, v, qps)
        ly = np.asarray(ly)
        lu = np.asarray(lu)
        lv = np.asarray(lv)
        ry = np.asarray(ry)

        def pack(i: int) -> EncodedFrame:
            payload = self._entropy(ly[i], lu[i], lv[i], rows, cols)
            nal = syntax.idr_nal(self.qp, payload)
            raw = nal.to_bytes()
            mse = np.mean(
                (ry[i, :self.height, :self.width].astype(np.float64)
                 - y[i, :self.height, :self.width].astype(np.float64)) ** 2)
            psnr = float(10 * np.log10(255.0 ** 2 / max(mse, 1e-12)))
            return EncodedFrame(
                sample=len(raw).to_bytes(4, "big") + raw,
                annexb=syntax.annexb([self.vps, self.sps, self.pps, nal]),
                is_idr=True, psnr_y=psnr)

        if pool is None:
            with ThreadPoolExecutor(self.entropy_threads) as p:
                return list(p.map(pack, range(b)))
        return list(pool.map(pack, range(b)))
