"""High-level HEVC encoding API: frames in, packaged samples out.

Mirror of codecs/h264/api.py for the H.265 path: the backend drives one
``HevcEncoder`` per quality rung; DSP runs batched on the device
(jax_core), entropy coding runs on the host — the C coder
(native/hevc_cabac.c) when buildable, else the Python reference — in
parallel threads per frame.

Reference parity: hevc_nvenc / hevc_vaapi selection in
worker/hwaccel.py:509-552; re-encode codec upgrades in
worker/reencode_worker.py.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from vlog_tpu.codecs.hevc import syntax
from vlog_tpu.codecs.hevc.slice import SliceWriter

CTB = syntax.CTB


@dataclass
class EncodedFrame:
    sample: bytes        # 4-byte-length-prefixed NAL (hvc1 sample format)
    annexb: bytes
    is_idr: bool
    psnr_y: float


def _u8(v):
    return bytes([v & 0xFF])


def _u16(v):
    return v.to_bytes(2, "big")


def hvcc_config(vps: syntax.NalUnit, sps: syntax.NalUnit,
                pps: syntax.NalUnit, level_idc: int) -> bytes:
    """HEVCDecoderConfigurationRecord (ISO 14496-15 8.3.3.1) for the
    stream shape syntax.py emits (Main profile, tier 0)."""
    out = bytearray()
    out += _u8(1)                      # configurationVersion
    out += _u8(1)                      # profile_space 0, tier 0, idc Main
    out += (0x60000000).to_bytes(4, "big")   # compat: Main + Main 10
    # constraints: progressive + non-packed + frame-only (bits 7,5,4)
    out += bytes([0xB0, 0, 0, 0, 0, 0])
    out += _u8(level_idc)
    out += _u16(0xF000)                # reserved + min_spatial_seg 0
    out += _u8(0xFC)                   # reserved + parallelismType 0
    out += _u8(0xFC | 1)               # reserved + chroma 4:2:0
    out += _u8(0xF8)                   # bit_depth_luma_minus8 = 0
    out += _u8(0xF8)                   # bit_depth_chroma_minus8 = 0
    out += _u16(0)                     # avgFrameRate unknown
    out += _u8((1 << 3) | (1 << 2) | 3)  # 1 layer, nested, 4-byte lengths
    out += _u8(3)                      # numOfArrays
    for nal in (vps, sps, pps):
        raw = nal.to_bytes()
        out += _u8(0x80 | nal.nal_type)   # array_completeness | type
        out += _u16(1) + _u16(len(raw)) + raw
    return bytes(out)


@dataclass
class HevcEncoder:
    """Stateful per-rung encoder; every frame is an IDR (all-intra, the
    same GOP shape as the H.264 intra path)."""

    width: int
    height: int
    fps_num: int = 30
    fps_den: int = 1
    qp: int = 30
    # None -> config.ENTROPY_THREADS (cpu-count-derived; the shared
    # executor pool is sized by the same knob)
    entropy_threads: int | None = None
    deblock: bool | None = None     # None -> config.HEVC_DEBLOCK

    def __post_init__(self):
        from vlog_tpu import config

        if self.entropy_threads is None:
            self.entropy_threads = config.ENTROPY_THREADS
        if self.deblock is None:
            self.deblock = config.HEVC_DEBLOCK
        self.vps = syntax.write_vps(
            syntax.level_idc_for(self.width, self.height))
        self.sps = syntax.write_sps(self.width, self.height)
        # the PPS must signal what the DSP reconstructs: a decoder runs
        # 8.7.2 iff this flag set says so, and P prediction chains on it
        self.pps = syntax.write_pps(deblock=self.deblock)

    # ---- stream metadata -----------------------------------------------
    @property
    def hvcc_config(self) -> bytes:
        return hvcc_config(self.vps, self.sps, self.pps,
                           syntax.level_idc_for(self.width, self.height))

    @property
    def codec_string(self) -> str:
        """RFC 6381: hvc1.<profile>.<compat-reversed>.L<level>.<constraints>"""
        return f"hvc1.1.6.L{syntax.level_idc_for(self.width, self.height)}.B0"

    def headers_annexb(self) -> bytes:
        return syntax.annexb([self.vps, self.sps, self.pps])

    # ---- encoding -------------------------------------------------------
    def _pad(self, plane: np.ndarray, block: int) -> np.ndarray:
        b, h, w = plane.shape
        ph = (h + block - 1) // block * block
        pw = (w + block - 1) // block * block
        if (ph, pw) == (h, w):
            return plane
        return np.pad(plane, ((0, 0), (0, ph - h), (0, pw - w)),
                      mode="edge")

    def _entropy(self, ly, lu, lv, rows, cols,
                 qp: int | None = None) -> bytes:
        from vlog_tpu.native.build import get_lib

        qp = self.qp if qp is None else qp
        lib = get_lib()
        if lib is not None:
            import ctypes

            la = np.ascontiguousarray(ly.reshape(-1), dtype=np.int16)
            ua = np.ascontiguousarray(lu.reshape(-1), dtype=np.int16)
            va = np.ascontiguousarray(lv.reshape(-1), dtype=np.int16)
            cap = max(1 << 16, la.size * 4)
            out = np.empty(cap, dtype=np.uint8)
            i16p = ctypes.POINTER(ctypes.c_int16)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            n = lib.vt_hevc_encode_slice(
                la.ctypes.data_as(i16p), ua.ctypes.data_as(i16p),
                va.ctypes.data_as(i16p), rows, cols, qp,
                out.ctypes.data_as(u8p), cap)
            if n >= 0:
                return out[:n].tobytes()
        sw = SliceWriter(qp)
        for r in range(rows):
            for c in range(cols):
                sw.write_ctu(c, ly[r, c], lu[r, c], lv[r, c],
                             last_in_slice=(r == rows - 1 and c == cols - 1))
        return sw.payload()

    def encode_chain(self, y: np.ndarray, u: np.ndarray, v: np.ndarray,
                     pool: ThreadPoolExecutor | None = None, *,
                     search: int = 16, chain_len: int | None = None,
                     partitions: bool | None = None,
                     frame_qps: np.ndarray | None = None
                     ) -> list[EncodedFrame]:
        """Encode one I + P chain: y (T, H, W), u/v (T, H/2, W/2) uint8.

        Frame 0 is an IDR coded at qp-2 (the chain-anchor offset the
        H.264 path also uses); frames 1..T-1 are P pictures with
        integer MVs against the running reconstruction
        (codecs/hevc/pslice.py). One device dispatch per chain; entropy
        per frame in threads.

        ``chain_len``: pad short tail chains (EOF) up to this length
        with replicated last frames so every dispatch reuses one
        compiled program; the padding frames are dropped from the
        output.

        ``frame_qps``: per-frame integer QPs (length >= T) realizing the
        rate controller's fractional working point (rate_control
        .frame_qps); slice_qp_delta signals each one. Defaults to a
        constant ``self.qp``."""
        from vlog_tpu.codecs.hevc.jax_core import encode_chain_dsp
        from vlog_tpu.codecs.hevc.pslice import PSliceWriter, p_nal

        y = self._pad(np.asarray(y, np.uint8), CTB)
        u = self._pad(np.asarray(u, np.uint8), CTB // 2)
        v = self._pad(np.asarray(v, np.uint8), CTB // 2)
        t_real = y.shape[0]
        if chain_len is not None and t_real < chain_len:
            reps = chain_len - t_real
            y = np.concatenate([y, np.repeat(y[-1:], reps, 0)])
            u = np.concatenate([u, np.repeat(u[-1:], reps, 0)])
            v = np.concatenate([v, np.repeat(v[-1:], reps, 0)])
        t, h, w = y.shape
        rows, cols = h // CTB, w // CTB
        if frame_qps is None:
            fqs = np.full((t,), self.qp, np.int32)
        else:
            fqs = np.asarray(frame_qps, np.int32).reshape(-1)
            if fqs.shape[0] < t:    # tail-chain padding frames
                fqs = np.concatenate(
                    [fqs, np.full((t - fqs.shape[0],), fqs[-1], np.int32)])
        qp_i = max(10, int(fqs[0]) - 2)
        qp_p_vec = (fqs[1:] if t > 1
                    else np.full((1,), self.qp, np.int32))
        if partitions is None:
            from vlog_tpu import config

            partitions = config.HEVC_PARTITIONS
        (intra, recon0), (p32, p16, parts, mvs, precons) = \
            encode_chain_dsp(y, u, v, search, np.int32(qp_i),
                             qp_p_vec, partitions, bool(self.deblock))
        recons = [recon0] + ([tuple(np.asarray(p[i]) for p in precons)
                              for i in range(t - 1)] if t > 1 else [])
        intra_np = tuple(np.asarray(a) for a in intra)
        p32_np = (tuple(np.asarray(a) for a in p32)
                  if p32 is not None else None)
        p16_np = (tuple(np.asarray(a) for a in p16)
                  if p16 is not None else None)
        parts_np = np.asarray(parts) if parts is not None else None
        mv_np = np.asarray(mvs) if mvs is not None else None

        def psnr_of(i):
            ry = np.asarray(recons[i][0])[:self.height, :self.width]
            mse = np.mean((ry.astype(np.float64)
                           - y[i, :self.height, :self.width]
                           .astype(np.float64)) ** 2)
            return float(10 * np.log10(255.0 ** 2 / max(mse, 1e-12)))

        psnrs = np.array([psnr_of(i) for i in range(t_real)])
        return self.entropy_chain(intra_np, p32_np, p16_np, parts_np,
                                  mv_np, fqs, rows, cols, psnrs,
                                  t_real=t_real, pool=pool)

    def entropy_chain(self, intra_np, p32_np, p16_np, parts_np, mv_np,
                      fqs, rows, cols, psnrs,
                      t_real: int, pool: ThreadPoolExecutor | None = None
                      ) -> list[EncodedFrame]:
        """Host entropy for one chain's device outputs.

        Shared by :meth:`encode_chain` (which ran the single-rung DSP)
        and the fused all-rungs ladder program
        (parallel/hevc_ladder.py), whose consumer calls this per chain
        with already-materialized numpy levels. ``fqs`` are the realized
        per-frame QPs, ``psnrs`` per-frame luma PSNR (display region).
        """
        from vlog_tpu.codecs.hevc.pslice import PSliceWriter, p_nal

        qp_i = max(10, int(fqs[0]) - 2)

        def p_entropy_c(ly, lu, lvv, mvg, qp) -> bytes | None:
            """C P-slice coder — all-2Nx2N slices only (its contract)."""
            from vlog_tpu.native.build import get_lib

            lib = get_lib()
            if lib is None:
                return None
            import ctypes

            la = np.ascontiguousarray(ly.reshape(-1), np.int16)
            ua = np.ascontiguousarray(lu.reshape(-1), np.int16)
            va = np.ascontiguousarray(lvv.reshape(-1), np.int16)
            # CTB MV = any of its 4 identical 16-cells
            mva = np.ascontiguousarray(
                mvg[::2, ::2].reshape(-1), np.int32)
            scratch = np.empty(rows * cols * 2, np.int32)
            cap = max(1 << 16, la.size * 4)
            out = np.empty(cap, np.uint8)
            i16p = ctypes.POINTER(ctypes.c_int16)
            i32p = ctypes.POINTER(ctypes.c_int32)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            n = lib.vt_hevc_encode_p_slice(
                la.ctypes.data_as(i16p), ua.ctypes.data_as(i16p),
                va.ctypes.data_as(i16p), mva.ctypes.data_as(i32p),
                rows, cols, qp, scratch.ctypes.data_as(i32p),
                out.ctypes.data_as(u8p), cap)
            return out[:n].tobytes() if n >= 0 else None

        def p_entropy(idx: int) -> bytes:
            """One P frame's payload: the C coder for uniform-motion
            frames, the Python writer (with 2NxN/Nx2N CUs) otherwise."""
            from vlog_tpu.codecs.hevc.jax_core import (PART_2Nx2N,
                                                       PART_Nx2N)

            l32 = tuple(a[idx] for a in p32_np)
            # parts_np is None when partitions were disabled at the DSP
            # (the fused ladder ships no all-2Nx2N partition map)
            part = parts_np[idx] if parts_np is not None else None
            mvg = mv_np[idx]                    # (2R, 2C, 2) 16-cell map
            qp = int(fqs[idx + 1])
            if part is None or not np.any(part != PART_2Nx2N):
                payload = p_entropy_c(*l32, mvg, qp)
                if payload is not None:
                    return payload
            # sub-TU codings exist only when partitions were enabled;
            # an all-2Nx2N frame (C-coder decline path) never reads them
            l16 = (tuple(a[idx] for a in p16_np)
                   if p16_np is not None else None)
            sw = PSliceWriter(qp, rows, cols)
            for r in range(rows):
                for c in range(cols):
                    last = r == rows - 1 and c == cols - 1
                    p = (PART_2Nx2N if part is None
                         else int(part[r, c]))
                    if p == PART_2Nx2N:
                        sw.write_ctu_inter(
                            r, c, tuple(int(x) for x in mvg[2 * r, 2 * c]),
                            l32[0][r, c], l32[1][r, c], l32[2][r, c],
                            last_in_slice=last)
                        continue
                    vertical = p == PART_Nx2N
                    if vertical:
                        mv0 = mvg[2 * r, 2 * c]
                        mv1 = mvg[2 * r, 2 * c + 1]
                    else:
                        mv0 = mvg[2 * r, 2 * c]
                        mv1 = mvg[2 * r + 1, 2 * c]
                    # sub-TUs in z-order from the 16-block grids
                    zs = [(2 * r, 2 * c), (2 * r, 2 * c + 1),
                          (2 * r + 1, 2 * c), (2 * r + 1, 2 * c + 1)]
                    luma_tus = [l16[0][zy, zx] for zy, zx in zs]
                    cb_tus = [l16[1][zy, zx] for zy, zx in zs]
                    cr_tus = [l16[2][zy, zx] for zy, zx in zs]
                    sw.write_ctu_inter_2part(
                        r, c, vertical=vertical,
                        mv0=tuple(int(x) for x in mv0),
                        mv1=tuple(int(x) for x in mv1),
                        luma_tus=luma_tus, cb_tus=cb_tus, cr_tus=cr_tus,
                        last_in_slice=last)
            return sw.payload()

        def pack(i: int) -> EncodedFrame:
            if i == 0:
                payload = self._entropy(*intra_np, rows, cols, qp_i)
                nal = syntax.idr_nal(qp_i, payload)
            else:
                nal = p_nal(int(fqs[i]), i, p_entropy(i - 1))
            raw = nal.to_bytes()
            return EncodedFrame(
                sample=len(raw).to_bytes(4, "big") + raw,
                annexb=syntax.annexb(
                    ([self.vps, self.sps, self.pps] if i == 0 else [])
                    + [nal]),
                is_idr=(i == 0), psnr_y=float(psnrs[i]))

        if pool is None:
            with ThreadPoolExecutor(self.entropy_threads,
                                    thread_name_prefix="vlog-entropy") as p:
                return list(p.map(pack, range(t_real)))
        return list(pool.map(pack, range(t_real)))

    def encode_batch(self, y: np.ndarray, u: np.ndarray, v: np.ndarray,
                     pool: ThreadPoolExecutor | None = None,
                     frame_qps: np.ndarray | None = None
                     ) -> list[EncodedFrame]:
        """Encode a batch of frames: y (B, H, W), u/v (B, H/2, W/2)
        uint8.  DSP runs as one device dispatch; entropy per frame in
        threads."""
        from vlog_tpu.codecs.hevc.jax_core import encode_batch_dsp

        y = self._pad(np.asarray(y, np.uint8), CTB)
        u = self._pad(np.asarray(u, np.uint8), CTB // 2)
        v = self._pad(np.asarray(v, np.uint8), CTB // 2)
        b, h, w = y.shape
        rows, cols = h // CTB, w // CTB
        if frame_qps is None:
            qps = np.full((b,), self.qp, np.int32)
        else:
            qps = np.asarray(frame_qps, np.int32).reshape(-1)[:b]
            if qps.shape[0] < b:    # same short-vector pad as encode_chain
                qps = np.concatenate(
                    [qps, np.full((b - qps.shape[0],), qps[-1] if qps.size
                                  else self.qp, np.int32)])
        (ly, lu, lv), (ry, _, _) = encode_batch_dsp(
            y, u, v, qps, deblock=bool(self.deblock))
        ly = np.asarray(ly)
        lu = np.asarray(lu)
        lv = np.asarray(lv)
        ry = np.asarray(ry)

        def pack(i: int) -> EncodedFrame:
            qp = int(qps[i])
            payload = self._entropy(ly[i], lu[i], lv[i], rows, cols, qp)
            nal = syntax.idr_nal(qp, payload)
            raw = nal.to_bytes()
            mse = np.mean(
                (ry[i, :self.height, :self.width].astype(np.float64)
                 - y[i, :self.height, :self.width].astype(np.float64)) ** 2)
            psnr = float(10 * np.log10(255.0 ** 2 / max(mse, 1e-12)))
            return EncodedFrame(
                sample=len(raw).to_bytes(4, "big") + raw,
                annexb=syntax.annexb([self.vps, self.sps, self.pps, nal]),
                is_idr=True, psnr_y=psnr)

        if pool is None:
            with ThreadPoolExecutor(self.entropy_threads,
                                    thread_name_prefix="vlog-entropy") as p:
                return list(p.map(pack, range(b)))
        return list(pool.map(pack, range(b)))
