"""HEVC residual_coding() writer (H.265 7.3.8.11 + 9.3.4.2/9.3.3.13).

Covers the TB shapes the slice writers emit: 32x32/16x16 luma, 16x16
chroma, and 8x8 chroma (the forced sub-TUs of non-2Nx2N inter CUs,
pslice.write_ctu_inter_2part). Diagonal scan throughout (the
mode-dependent horizontal/vertical scans only apply to 4x4 and
luma-8x8 TBs, which this stream shape never codes), no transform-skip,
no sign-data-hiding.

NOTE: the C port (native/hevc_cabac.c) covers the 2Nx2N shapes only
(32 luma / 16 chroma); two-part CUs entropy-code through this Python
reference until the C coder grows the sub-TU paths.

The coefficient-group machinery: the TB is scanned as 4x4 coefficient
groups in up-right diagonal order; coding runs backwards from the last
significant coefficient — last-position prefix/suffix, then per CG a
coded_sub_block_flag, significance flags with the pattern-based
context derivation, capped greater1/greater2 flags, bypass signs and
Golomb-Rice remainders with parameter adaptation.

This is the Python reference implementation; tests oracle it against
libavcodec end-to-end (tests/test_hevc.py) and the C port in
native/hevc_cabac.c must stay bit-exact with it.
"""

from __future__ import annotations

import numpy as np

from vlog_tpu.codecs.hevc.cabac import CabacEncoder
from vlog_tpu.codecs.hevc.tables import (
    CTX_OFF,
    DIAG_SCAN_4x4,
    DIAG_SCAN_8x8,
)

_LAST_X = CTX_OFF["LAST_X_PREFIX"][0]
_LAST_Y = CTX_OFF["LAST_Y_PREFIX"][0]
_SIG_CG = CTX_OFF["SIG_CG_FLAG"][0]
_SIG = CTX_OFF["SIG_COEFF"][0]
_G1 = CTX_OFF["GREATER1"][0]
_G2 = CTX_OFF["GREATER2"][0]

# last_sig_coeff_{x,y} binarization (H.265 9.3.3.12 table)
_GROUP_IDX = [0, 1, 2, 3, 4, 4, 5, 5, 6, 6, 6, 6, 7, 7, 7, 7,
              8, 8, 8, 8, 8, 8, 8, 8, 9, 9, 9, 9, 9, 9, 9, 9]
_MIN_IN_GROUP = [0, 1, 2, 3, 4, 6, 8, 12, 16, 24]


# up-right diagonal over a 2x2 CG grid (8x8 TBs)
DIAG_SCAN_2x2 = [(0, 0), (0, 1), (1, 0), (1, 1)]


def _cg_scan(n_cg: int):
    if n_cg == 8:
        return DIAG_SCAN_8x8
    if n_cg == 4:
        return DIAG_SCAN_4x4
    return DIAG_SCAN_2x2


def _scan_positions(log2_size: int) -> list[tuple[int, int]]:
    """Forward diagonal scan of the whole TB: CG-major, 4x4 inside."""
    n_cg = 1 << (log2_size - 2)
    out = []
    for cx, cy in _cg_scan(n_cg)[: n_cg * n_cg]:
        for ix, iy in DIAG_SCAN_4x4:
            out.append((cx * 4 + ix, cy * 4 + iy))
    return out


def _write_last_prefix(c: CabacEncoder, group: int, cmax: int,
                       base: int, offset: int, shift: int) -> None:
    for b in range(group):
        c.encode_bin(base + offset + (b >> shift), 1)
    if group < cmax:
        c.encode_bin(base + offset + (group >> shift), 0)


def _write_remaining(c: CabacEncoder, value: int, rice: int) -> None:
    """coeff_abs_level_remaining: Golomb-Rice with EGk escape
    (inverse of H.265 9.3.3.13)."""
    if value < (3 << rice):
        for _ in range(value >> rice):
            c.encode_bypass(1)
        c.encode_bypass(0)
        if rice:
            c.encode_bypass_bits(value & ((1 << rice) - 1), rice)
    else:
        length = rice
        value -= 3 << rice
        while value >= (1 << length):
            value -= 1 << length
            length += 1
        for _ in range(3 + length - rice):   # unary prefix: p ones + 0
            c.encode_bypass(1)
        c.encode_bypass(0)
        if length:
            c.encode_bypass_bits(value, length)


def _sig_ctx(x: int, y: int, c_idx: int, prev_csbf: int,
             chroma8: bool = False) -> int:
    """sig_coeff_flag ctxIdxInc (9.3.4.2.5): luma 16/32, chroma 16 and
    chroma 8x8 (``chroma8`` — the inter sub-TU case; 8x8 luma and the
    4x4 map cases stay outside this stream shape)."""
    if x == 0 and y == 0:
        return 0 if c_idx == 0 else 27
    xp, yp = x & 3, y & 3
    if prev_csbf == 0:
        s = 2 if xp + yp == 0 else (1 if xp + yp < 3 else 0)
    elif prev_csbf == 1:
        s = 2 if yp == 0 else (1 if yp == 1 else 0)
    elif prev_csbf == 2:
        s = 2 if xp == 0 else (1 if xp == 1 else 0)
    else:
        s = 2
    if c_idx == 0:
        if (x >> 2) or (y >> 2):    # not the first coefficient group
            s += 3
        return s + 21               # luma nTbS {16,32}
    return 27 + s + (9 if chroma8 else 12)


def write_residual(c: CabacEncoder, levels: np.ndarray, *,
                   log2_size: int, c_idx: int) -> None:
    """Emit residual_coding() for one TB. ``levels`` raster (N, N) ints,
    at least one nonzero."""
    n = 1 << log2_size
    n_cg = n >> 2
    scan = _scan_positions(log2_size)
    lv = np.asarray(levels)

    last_scan = max(i for i, (x, y) in enumerate(scan) if lv[y, x])
    last_x, last_y = scan[last_scan]

    # ---- last position (x prefix, y prefix, x suffix, y suffix)
    cmax = (log2_size << 1) - 1
    if c_idx == 0:
        offset, shift = 3 * (log2_size - 2) + ((log2_size - 1) >> 2), \
            (log2_size + 1) >> 2
    else:
        offset, shift = 15, log2_size - 2
    gx, gy = _GROUP_IDX[last_x], _GROUP_IDX[last_y]
    _write_last_prefix(c, gx, cmax, _LAST_X, offset, shift)
    _write_last_prefix(c, gy, cmax, _LAST_Y, offset, shift)
    if gx > 3:
        c.encode_bypass_bits(last_x - _MIN_IN_GROUP[gx], (gx >> 1) - 1)
    if gy > 3:
        c.encode_bypass_bits(last_y - _MIN_IN_GROUP[gy], (gy >> 1) - 1)

    # ---- per-CG coefficient data, back from the last CG
    cg_scan = _cg_scan(n_cg)[: n_cg * n_cg]
    csbf = np.zeros((n_cg, n_cg), dtype=bool)
    for cyy in range(n_cg):
        for cxx in range(n_cg):
            csbf[cyy, cxx] = bool(
                np.any(lv[cyy * 4:cyy * 4 + 4, cxx * 4:cxx * 4 + 4]))

    last_cg = last_scan >> 4
    greater1_ctx = 1            # carries across CGs (HM's c1)
    first_cg_done = False
    for ci in range(last_cg, -1, -1):
        cx, cy = cg_scan[ci]
        coded = bool(csbf[cy, cx])
        explicit = ci != last_cg and ci != 0
        right = cx + 1 < n_cg and bool(csbf[cy, cx + 1])
        below = cy + 1 < n_cg and bool(csbf[cy + 1, cx])
        if explicit:
            c.encode_bin(
                _SIG_CG + (2 if c_idx else 0) + (1 if right or below else 0),
                int(coded))
            if not coded:
                continue
        # CG0 (and the last CG) have csbf *inferred* 1: an all-zero CG0
        # still codes its 16 zero significance flags
        prev_csbf = int(right) + 2 * int(below)

        # significance flags, reverse scan; last coeff inferred
        start = (last_scan % 16) - 1 if ci == last_cg else 15
        infer_dc = explicit             # last CG is never explicit
        sigs = []                       # coding order (reverse scan)
        if ci == last_cg:
            sigs.append(scan[last_scan])
        for j in range(start, -1, -1):
            x, y = scan[(ci << 4) + j]
            significant = bool(lv[y, x])
            if j == 0 and infer_dc and not sigs:
                # every earlier flag in this CG was zero, and the coded
                # csbf==1 promises a nonzero -> DC significance inferred
                sigs.append((x, y))
                continue
            c.encode_bin(_SIG + _sig_ctx(x, y, c_idx, prev_csbf,
                                         chroma8=(log2_size == 3)),
                         int(significant))
            if significant:
                sigs.append((x, y))

        if not sigs:                    # all-zero CG0
            continue
        # greater1 (<=8), greater2 (1), signs, remainders
        ctx_set = (2 if ci > 0 and c_idx == 0 else 0)
        if first_cg_done and greater1_ctx == 0:
            ctx_set += 1
        first_cg_done = True
        greater1_ctx = 1
        g1_flags = []
        g2_pos = None
        for k, (x, y) in enumerate(sigs[:8]):
            flag = int(abs(int(lv[y, x])) > 1)
            base = _G1 + (16 if c_idx else 0)
            c.encode_bin(base + ctx_set * 4 + min(greater1_ctx, 3), flag)
            g1_flags.append(flag)
            if flag:
                if g2_pos is None:
                    g2_pos = k
                greater1_ctx = 0
            elif 0 < greater1_ctx < 3:
                greater1_ctx += 1
        g2_flag = 0
        if g2_pos is not None:
            x, y = sigs[g2_pos]
            g2_flag = int(abs(int(lv[y, x])) > 2)
            c.encode_bin(_G2 + (4 + ctx_set if c_idx else ctx_set), g2_flag)
        for x, y in sigs:               # no sign hiding
            c.encode_bypass(1 if lv[y, x] < 0 else 0)
        rice = 0
        for k, (x, y) in enumerate(sigs):
            absl = abs(int(lv[y, x]))
            if k < 8:
                if g1_flags[k] == 0:
                    continue            # level is exactly 1
                if k == g2_pos:
                    if not g2_flag:
                        continue        # level is exactly 2
                    base_level = 3
                else:
                    base_level = 2
            else:
                base_level = 1
            _write_remaining(c, absl - base_level, rice)
            if absl > (3 << rice):
                rice = min(rice + 1, 4)
