"""HEVC in-loop deblocking filter (spec 8.7.2) — exact, TPU-shaped.

The reference gets deblocking for free inside x265/NVENC/VAAPI
(worker/hwaccel.py:555-646); our first-party encoder must run it in the
JAX DSP because the filter is IN-LOOP: the deblocked picture is what a
decoder stores in the DPB, so P-frame prediction drifts unless the
encoder reconstructs through the same filter bit-exactly.

**Why two flat passes (no wavefront).** Unlike H.264's raster-order
macroblock filter (codecs/h264/deblock.py), HEVC was *designed* for
parallel deblocking: all vertical edges of the picture are filtered
first, then all horizontal edges (8.7.2.1).  Edges live on an 8x8 grid
and the filter reads 4 / writes 3 samples on each side, so no two
same-direction edge filters ever touch the same sample — each pass is
one dense batched gather/filter/scatter, exactly what the VPU wants.
Our streams are simpler still: every coded TU is >= 16x16 (jax_core
TU32 luma / TU16 chroma, TU16 luma inside partitioned CTBs), so edges
only exist on the 16-luma grid and bS is constant over each 16x16 cell.

Boundary strengths for the streams this encoder emits:

- I pictures: every TU-boundary edge has an intra CU on both sides ->
  bS = 2 (8.7.2.4).  TU boundaries sit on the 32-luma CTB grid.
- P pictures (single ref, list0): bS = 1 where either adjacent TU has
  nonzero coefficients or the MV delta is >= 4 quarter-pel on either
  component, else 0.  Edges exist at CTB boundaries, plus the interior
  16-grid of partitioned CTBs (their TU tree splits to TU16).
- Chroma is filtered only where bS = 2 -> intra pictures only, on the
  16-chroma (= CTB) grid.

beta/tc are spec Tables 8-12 (values cross-checked against
libavcodec's hevc_filter betatable/tctable).  QP is uniform per picture
(per-frame rate control), so threshold lookups are traced scalars.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# Spec Table 8-12: beta' indexed by Q = Clip3(0, 51, qp).
BETA_TBL = np.array([
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 6, 7, 8, 9, 10,
    11, 12, 13, 14, 15, 16, 17, 18, 20, 22, 24, 26, 28, 30, 32, 34,
    36, 38, 40, 42, 44, 46, 48, 50, 52, 54, 56, 58, 60, 62, 64,
], np.int32)
# Spec Table 8-12: tc' indexed by Q = Clip3(0, 53, qp + 2*(bS-1)).
TC_TBL = np.array([
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 1,
    1, 1, 1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3, 4, 4, 4, 5, 5, 6, 6,
    7, 8, 9, 10, 11, 13, 14, 16, 18, 20, 22, 24,
], np.int32)


# ---------------------------------------------------------------------------
# Boundary strengths (cell granularity: bS is constant per 16x16 cell)
# ---------------------------------------------------------------------------

def intra_bs(ctbh: int, ctbw: int):
    """(bs_v, bs_h) for an all-intra picture.

    bs_v: (Ev, H16) int32 — vertical edge k at x = 16*(k+1), per
    16-line cell row.  Only CTB boundaries carry a TU edge (TU32), so
    odd k (x a multiple of 32) gets bS 2, interior 16-columns 0.
    bs_h mirrors for horizontal edges.
    """
    h16, w16 = 2 * ctbh, 2 * ctbw
    kv = np.arange(w16 - 1)
    bs_v = np.where((kv % 2 == 1)[:, None], 2, 0).astype(np.int32)
    bs_v = np.broadcast_to(bs_v, (w16 - 1, h16))
    kh = np.arange(h16 - 1)
    bs_h = np.where((kh % 2 == 1)[:, None], 2, 0).astype(np.int32)
    bs_h = np.broadcast_to(bs_h, (h16 - 1, w16))
    return jnp.asarray(bs_v), jnp.asarray(bs_h)


def p_bs(part, cbf_cells, mv):
    """Boundary strengths for a P picture.

    part: (R, C) int32 per-CTB partition code (0 = 2Nx2N).
    cbf_cells: (2R, 2C) bool — the TU containing the cell has nonzero
    coefficients (TU32's cbf replicated over its 4 cells, or per-TU16).
    mv: (2R, 2C, 2) int32 quarter-pel MVs per 16-cell.
    Returns (bs_v, bs_h): (Ev, H16) / (Eh, W16) int32.
    """
    cbf_cells = cbf_cells.astype(jnp.int32)
    h16, w16 = cbf_cells.shape
    part_cells = jnp.repeat(jnp.repeat(part, 2, 0), 2, 1)      # (2R, 2C)

    cond_v = (((cbf_cells[:, :-1] | cbf_cells[:, 1:]) > 0)
              | jnp.any(jnp.abs(mv[:, 1:] - mv[:, :-1]) >= 4, axis=-1))
    kv = jnp.arange(w16 - 1)
    ctb_v = (kv % 2) == 1                                      # (Ev,)
    # interior edge k (even) lies inside CTB column k//2: a TU16 edge
    # exists there only when that CTB is partitioned
    inner_v = part_cells[:, (kv // 2) * 2] != 0                # (H16, Ev)
    exists_v = ctb_v[None, :] | ((~ctb_v)[None, :] & inner_v)
    bs_v = jnp.where(exists_v & cond_v, 1, 0).T                # (Ev, H16)

    cond_h = (((cbf_cells[:-1, :] | cbf_cells[1:, :]) > 0)
              | jnp.any(jnp.abs(mv[1:] - mv[:-1]) >= 4, axis=-1))
    kh = jnp.arange(h16 - 1)
    ctb_h = (kh % 2) == 1
    inner_h = part_cells[(kh // 2) * 2, :] != 0                # (Eh, W16)
    exists_h = ctb_h[:, None] | ((~ctb_h)[:, None] & inner_h)
    return bs_v.astype(jnp.int32), jnp.where(
        exists_h & cond_h, 1, 0).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Edge filters: win (..., L, 8) = [p3 p2 p1 p0 q0 q1 q2 q3] per line
# ---------------------------------------------------------------------------

def _filter_luma(win, bs_seg, qp):
    """Spec 8.7.2.5.3 (decisions) + 8.7.2.5.6/8.7.2.5.7 (filters).

    win: (E, L, 8) int32, L a multiple of 4; bs_seg: (E, L//4) int32
    per 4-line segment; qp traced scalar.  Returns filtered windows.
    """
    e, l, _ = win.shape
    s = l // 4
    w4 = win.reshape(e, s, 4, 8)
    p3, p2, p1, p0 = w4[..., 0], w4[..., 1], w4[..., 2], w4[..., 3]
    q0, q1, q2, q3 = w4[..., 4], w4[..., 5], w4[..., 6], w4[..., 7]

    beta = jnp.asarray(BETA_TBL)[jnp.clip(qp, 0, 51)]
    tc = jnp.asarray(TC_TBL)[jnp.clip(qp + 2 * (bs_seg - 1), 0, 53)]

    dp = jnp.abs(p2 - 2 * p1 + p0)                   # (E, S, 4) per line
    dq = jnp.abs(q2 - 2 * q1 + q0)
    dp03 = dp[..., 0] + dp[..., 3]                   # (E, S) lines 0+3
    dq03 = dq[..., 0] + dq[..., 3]
    d = dp03 + dq03
    filt = (bs_seg > 0) & (d < beta)                 # (E, S)

    def strong_line(i):
        return ((2 * (dp[..., i] + dq[..., i]) < (beta >> 2))
                & ((jnp.abs(p3[..., i] - p0[..., i])
                    + jnp.abs(q0[..., i] - q3[..., i])) < (beta >> 3))
                & (jnp.abs(p0[..., i] - q0[..., i])
                   < ((5 * tc + 1) >> 1)))

    strong = filt & strong_line(0) & strong_line(3)  # (E, S)

    tcl = tc[..., None]                              # broadcast to lines
    c2 = 2 * tcl
    p0s = jnp.clip((p2 + 2 * p1 + 2 * p0 + 2 * q0 + q1 + 4) >> 3,
                   p0 - c2, p0 + c2)
    p1s = jnp.clip((p2 + p1 + p0 + q0 + 2) >> 2, p1 - c2, p1 + c2)
    p2s = jnp.clip((2 * p3 + 3 * p2 + p1 + p0 + q0 + 4) >> 3,
                   p2 - c2, p2 + c2)
    q0s = jnp.clip((q2 + 2 * q1 + 2 * q0 + 2 * p0 + p1 + 4) >> 3,
                   q0 - c2, q0 + c2)
    q1s = jnp.clip((q2 + q1 + q0 + p0 + 2) >> 2, q1 - c2, q1 + c2)
    q2s = jnp.clip((2 * q3 + 3 * q2 + q1 + q0 + p0 + 4) >> 3,
                   q2 - c2, q2 + c2)

    # normal filter: per-line gate |delta| < 10*tc (8.7.2.5.7)
    d0 = (9 * (q0 - p0) - 3 * (q1 - p1) + 8) >> 4
    nf = jnp.abs(d0) < 10 * tcl
    delta = jnp.clip(d0, -tcl, tcl)
    p0n = jnp.clip(p0 + delta, 0, 255)
    q0n = jnp.clip(q0 - delta, 0, 255)
    thr_side = (beta + (beta >> 1)) >> 3
    side_p = (dp03 < thr_side)[..., None]            # per segment
    side_q = (dq03 < thr_side)[..., None]
    tch = tcl >> 1
    # sign asymmetry is spec: p0 moves by +delta, q0 by -delta, and each
    # side's p1/q1 regression term carries its own side's sign
    dp1 = jnp.clip((((p2 + p0 + 1) >> 1) - p1 + delta) >> 1, -tch, tch)
    dq1 = jnp.clip((((q2 + q0 + 1) >> 1) - q1 - delta) >> 1, -tch, tch)
    p1n = jnp.clip(p1 + dp1, 0, 255)
    q1n = jnp.clip(q1 + dq1, 0, 255)

    f = filt[..., None]
    st = strong[..., None]
    p0o = jnp.where(f & st, p0s, jnp.where(f & nf, p0n, p0))
    q0o = jnp.where(f & st, q0s, jnp.where(f & nf, q0n, q0))
    p1o = jnp.where(f & st, p1s,
                    jnp.where(f & nf & side_p, p1n, p1))
    q1o = jnp.where(f & st, q1s,
                    jnp.where(f & nf & side_q, q1n, q1))
    p2o = jnp.where(f & st, p2s, p2)
    q2o = jnp.where(f & st, q2s, q2)
    out = jnp.stack([p3, p2o, p1o, p0o, q0o, q1o, q2o, q3], axis=-1)
    return out.reshape(e, l, 8)


def _filter_chroma(win, qp):
    """Spec 8.7.2.5.5: bS-2 chroma filter, win (E, L, 4) = [p1 p0 q0 q1].

    No on/off decision beyond bS == 2 (which the caller guarantees);
    tc indexed at qp + 2 because bS is always 2 here.
    """
    p1, p0, q0, q1 = win[..., 0], win[..., 1], win[..., 2], win[..., 3]
    tc = jnp.asarray(TC_TBL)[jnp.clip(qp + 2, 0, 53)]
    delta = jnp.clip((((q0 - p0) << 2) + p1 - q1 + 4) >> 3, -tc, tc)
    p0o = jnp.clip(p0 + delta, 0, 255)
    q0o = jnp.clip(q0 - delta, 0, 255)
    return jnp.stack([p1, p0o, q0o, q1], axis=-1)


# ---------------------------------------------------------------------------
# Passes: gather non-overlapping windows, filter, scatter back
# ---------------------------------------------------------------------------

def _luma_pass_v(plane, bs_v, qp):
    """All vertical luma edges in one shot.  plane (H, W) int32;
    bs_v (Ev, H16) per-cell -> repeated to 4-line segments."""
    h, w = plane.shape
    ev = w // 16 - 1
    if ev <= 0:
        return plane
    xs = (jnp.arange(ev) + 1) * 16
    cols = xs[:, None] + jnp.arange(-4, 4)[None, :]          # (Ev, 8)
    win = jnp.swapaxes(plane[:, cols], 0, 1)                 # (Ev, H, 8)
    bs_seg = jnp.repeat(bs_v, 4, axis=1)                     # (Ev, H//4)
    out = _filter_luma(win, bs_seg, qp)
    return plane.at[:, cols].set(jnp.swapaxes(out, 0, 1))


def _luma_pass_h(plane, bs_h, qp):
    """Horizontal edges = vertical pass on the transpose (the p side is
    above the edge, which transposition maps to the left)."""
    return _luma_pass_v(plane.T, bs_h, qp).T


def _chroma_pass_v(plane, qp):
    """Intra-picture chroma: every 16-chroma column is a bS-2 CTB/TU
    boundary.  plane (Hc, Wc) int32."""
    hc, wc = plane.shape
    ev = wc // 16 - 1
    if ev <= 0:
        return plane
    xs = (jnp.arange(ev) + 1) * 16
    cols = xs[:, None] + jnp.arange(-2, 2)[None, :]          # (Ev, 4)
    win = jnp.swapaxes(plane[:, cols], 0, 1)                 # (Ev, Hc, 4)
    out = _filter_chroma(win, qp)
    return plane.at[:, cols].set(jnp.swapaxes(out, 0, 1))


def deblock_picture(y, u, v, *, qp, qpc, bs_v, bs_h, chroma: bool):
    """Deblock one reconstructed picture per spec 8.7.2.

    y (H, W), u/v (H/2, W/2) integer planes; ``qp``/``qpc`` traced
    scalars; bS arrays from :func:`intra_bs` / :func:`p_bs`; ``chroma``
    static (True only for intra pictures — chroma filters at bS 2).
    Returns (y, u, v) int32 in [0, 255].
    """
    y = jnp.asarray(y, jnp.int32)
    y = _luma_pass_v(y, bs_v, qp)
    y = _luma_pass_h(y, bs_h, qp)
    u = jnp.asarray(u, jnp.int32)
    v = jnp.asarray(v, jnp.int32)
    if chroma:
        u = _chroma_pass_v(u, qpc)
        v = _chroma_pass_v(v, qpc)
        u = _chroma_pass_v(u.T, qpc).T
        v = _chroma_pass_v(v.T, qpc).T
    return y, u, v
