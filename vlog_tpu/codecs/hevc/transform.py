"""HEVC core transform + quantization (H.265 8.6), numpy reference.

Matrix generation: every entry of the NxN integer DCT-like matrices is
one of the normative basis magnitudes at angle pi*t/64 — factor the
odd part of t and index the per-octave coefficient lists (the familiar
{83,36} / {89,75,50,18} / ... sets every HEVC text tabulates).  The
construction is validated structurally below (known 4/8-point rows
asserted at import) and end-to-end by the libavcodec oracle tests: a
wrong entry would break bit-exact reconstruction immediately.

Inverse transform and dequantization follow the spec exactly (they
must match every conforming decoder); the forward direction uses the
HM-style shifts, which is an encoder choice, not normative.
"""

from __future__ import annotations

import numpy as np

_C32 = [90, 90, 88, 85, 82, 78, 73, 67, 61, 54, 46, 38, 31, 22, 13, 4]
_C16 = [90, 87, 80, 70, 57, 43, 25, 9]
_C8 = [89, 75, 50, 18]
_C4 = [83, 36]
_LISTS = [_C32, _C16, _C8, _C4, [64]]


def _entry(t: int) -> int:
    """Matrix value at angle pi*t/64 (t already reduced mod 128)."""
    sign = -1 if 32 < t < 96 else 1
    u = t % 64
    u = min(u, 64 - u)
    if u == 0:
        return sign * 64
    e = (u & -u).bit_length() - 1         # factor-of-2 exponent
    odd = u >> e
    return sign * _LISTS[e][(odd - 1) // 2]


def _matrix(n: int) -> np.ndarray:
    step = 32 // n                         # angle scale onto the /64 grid
    m = np.empty((n, n), dtype=np.int32)
    for r in range(n):
        for c in range(n):
            m[r, c] = _entry((step * r * (2 * c + 1)) % 128)
    return m

T32 = _matrix(32)
T16 = _matrix(16)
T8 = _matrix(8)          # chroma sub-TUs of forced-split inter CUs

# structural self-check against the universally known small transforms
assert T32[0].tolist() == [64] * 32
assert _matrix(4).tolist() == [[64, 64, 64, 64], [83, 36, -36, -83],
                               [64, -64, -64, 64], [36, -83, 83, -36]]
assert _matrix(8)[3].tolist() == [75, -18, -89, -50, 50, 89, 18, -75]

# level scales (H.265 8.6.3) and HM forward quant scales
LEVEL_SCALE = np.array([40, 45, 51, 57, 64, 72], dtype=np.int64)
QUANT_SCALE = np.array([26214, 23302, 20560, 18396, 16384, 14564],
                       dtype=np.int64)

# chroma QP mapping for 4:2:0 (H.265 table 8-10)
_QPC = list(range(30)) + [29, 30, 31, 32, 33, 33, 34, 34, 35, 35, 36,
                          36, 37]


def chroma_qp(qp_y: int) -> int:
    qpi = min(max(qp_y, 0), 51)
    return _QPC[qpi] if qpi < 43 else qpi - 6


def _mat_for(n: int) -> np.ndarray:
    if n == 32:
        return T32
    if n == 16:
        return T16
    return T8


def forward_transform(res: np.ndarray) -> np.ndarray:
    """HM-style two-stage forward DCT, 8-bit input residual (N, N)."""
    n = res.shape[-1]
    m = _mat_for(n).astype(np.int64)
    log2n = n.bit_length() - 1
    s1 = log2n - 1                       # log2N + bitDepth - 9
    s2 = log2n + 6
    tmp = (m @ res.astype(np.int64) + (1 << (s1 - 1))) >> s1
    return ((tmp @ m.T + (1 << (s2 - 1))) >> s2).astype(np.int32)


def inverse_transform(coeff: np.ndarray, bit_depth: int = 8) -> np.ndarray:
    """Spec-exact inverse (8.6.4): column pass, clip to 16 bit, row pass."""
    n = coeff.shape[-1]
    m = _mat_for(n).astype(np.int64)
    e = (m.T @ coeff.astype(np.int64) + 64) >> 7   # vertical pass
    e = np.clip(e, -32768, 32767)
    s2 = 20 - bit_depth
    r = (e @ m + (1 << (s2 - 1))) >> s2            # horizontal pass
    return np.clip(r, -32768, 32767).astype(np.int32)


def quantize(coeff: np.ndarray, qp: int) -> np.ndarray:
    """HM-style forward quant with intra rounding offset (1/3)."""
    n = coeff.shape[-1]
    log2n = n.bit_length() - 1
    tr_shift = 15 - 8 - log2n
    qbits = 14 + qp // 6 + tr_shift
    f = QUANT_SCALE[qp % 6]
    offset = (1 << qbits) * 171 >> 9     # ~1/3, intra
    level = (np.abs(coeff.astype(np.int64)) * f + offset) >> qbits
    level = np.clip(level, 0, 32767)
    return (np.sign(coeff) * level).astype(np.int32)


def dequantize(level: np.ndarray, qp: int, bit_depth: int = 8) -> np.ndarray:
    """Spec 8.6.3 with flat (m=16) scaling."""
    n = level.shape[-1]
    log2n = n.bit_length() - 1
    bd_shift = bit_depth + log2n - 5
    scale = (LEVEL_SCALE[qp % 6] << (qp // 6)) * 16
    d = (level.astype(np.int64) * scale + (1 << (bd_shift - 1))) >> bd_shift
    return np.clip(d, -32768, 32767).astype(np.int32)
