"""HEVC P-slice syntax: TRAIL pictures with integer-MV inter CTBs.

Extends the all-intra envelope (slice.py) with single-reference P
slices: every CTB is either an inter 2Nx2N CU with an explicitly coded
quarter-pel MV (AMVP, mvp_l0_flag=0, no merge/skip — avoids the merge
candidate machinery entirely at a cost of a few bins per CTB) or falls
back to the intra mode-26 CU when motion fails. The device DSP
(jax_core.py) interpolates with the spec 8-tap luma / 4-tap chroma
filters — the HEVC analog of the H.264 chain design.

The AMVP predictor (8.5.3.2.6) is computed by an entropy-time state
machine over the CTB grid, mirroring what any decoder derives:
candidate A = the left CU's MV (below-left is never decoded yet at CTB
granularity), candidate B = first of above-right/above/above-left,
pruned and zero-filled. All PUs share one reference picture (the
previous frame, RPS delta=1), so no MV scaling is ever needed.

Oracle: tests/test_hevc.py decodes I+P chains with libavcodec and
asserts byte-exact reconstruction.
"""

from __future__ import annotations

import numpy as np

from vlog_tpu.codecs.hevc.cabac import CabacEncoder
from vlog_tpu.codecs.hevc.residual import write_residual
from vlog_tpu.codecs.hevc.syntax import CTB, NalUnit
from vlog_tpu.codecs.hevc.tables import CTX_OFF
from vlog_tpu.media.bitstream import BitWriter

NAL_TRAIL_R = 1

_SKIP = CTX_OFF["SKIP"][0]
_PRED_MODE = CTX_OFF["PRED_MODE"][0]
_PART = CTX_OFF["PART_MODE"][0]
_MERGE = CTX_OFF["MERGE_FLAG"][0]
_MVP = CTX_OFF["MVP_LX"][0]
_ROOT_CBF = CTX_OFF["NO_RESIDUAL"][0]
# mvd_coding contexts: greater0 at the block base, greater1 at +3
# (both measured from the hls_mvd_coding disassembly)
_MVD_G0 = CTX_OFF["MVD_GREATER"][0]
_MVD_G1 = CTX_OFF["MVD_GREATER"][0] + 3
_PREV = CTX_OFF["PREV_INTRA_LUMA"][0]
_CHROMA = CTX_OFF["INTRA_CHROMA_PRED"][0]
_CBF_LUMA = CTX_OFF["CBF_LUMA"][0]
_CBF_CHROMA = CTX_OFF["CBF_CB_CR"][0]


def p_slice_header_bits(slice_qp: int, poc_lsb: int) -> BitWriter:
    """P slice header for our stream shape (7.3.6.1): one negative ref
    at delta 1, no SAO/deblock/temporal-MVP, merge depth 1."""
    w = BitWriter()
    w.write_bit(1)            # first_slice_segment_in_pic_flag
    w.write_ue(0)             # slice_pic_parameter_set_id
    w.write_ue(1)             # slice_type = P
    w.write_bits(poc_lsb & 0xFF, 8)   # slice_pic_order_cnt_lsb
    w.write_bit(0)            # short_term_ref_pic_set_sps_flag
    w.write_ue(1)             # num_negative_pics
    w.write_ue(0)             # num_positive_pics
    w.write_ue(0)             # delta_poc_s0_minus1 (prev picture)
    w.write_bit(1)            # used_by_curr_pic_s0_flag
    w.write_bit(0)            # num_ref_idx_active_override_flag (PPS: 1)
    w.write_ue(4)             # five_minus_max_num_merge_cand -> 1
    w.write_se(slice_qp - 26)  # slice_qp_delta
    w.write_bit(1)            # alignment_bit_equal_to_one
    w.byte_align(0)
    return w


class MvpGrid:
    """AMVP over a grid of CTB-sized PUs (encoder-side mirror of
    8.5.3.2.6 for our shape). Tracks (is_inter, mv) per coded CTB."""

    def __init__(self, rows: int, cols: int) -> None:
        self.rows, self.cols = rows, cols
        self.inter = np.zeros((rows, cols), bool)
        self._coded = np.zeros((rows, cols), bool)
        self.mv = np.zeros((rows, cols, 2), np.int32)   # (x, y) qpel

    def _cand(self, r: int, c: int):
        if 0 <= r < self.rows and 0 <= c < self.cols and self.inter[r, c]:
            return tuple(int(v) for v in self.mv[r, c])
        return None

    def predictor(self, r: int, c: int) -> tuple[int, int]:
        """mvp candidate 0 for the CU at CTB (r, c).

        write_ctu_inter always signals mvp_l0_flag=0, so only the first
        list entry matters: A if available, else B, else zero (the
        spec's A==B pruning and zero-fill only reorder entry 1)."""
        a = self._cand(r, c - 1)                 # A1 (A0 is undecoded)
        if a is not None:
            return a
        for rc in ((r - 1, c + 1), (r - 1, c), (r - 1, c - 1)):  # B0 B1 B2
            b = self._cand(*rc)
            if b is not None:
                return b
        return (0, 0)

    def record(self, r: int, c: int, *, inter: bool,
               mv: tuple[int, int] = (0, 0)) -> None:
        self.inter[r, c] = inter
        self._coded[r, c] = True
        self.mv[r, c] = mv


def _write_mvd(c: CabacEncoder, dx: int, dy: int) -> None:
    """mvd_coding (7.3.8.9): greater0/1 context bins, EG1 remainder and
    sign in bypass. (dx, dy) in quarter-pel, bitstream order x then y."""
    comps = (dx, dy)
    g0 = [int(v != 0) for v in comps]
    g1 = [int(abs(v) > 1) for v in comps]
    c.encode_bin(_MVD_G0, g0[0])
    c.encode_bin(_MVD_G0, g0[1])
    if g0[0]:
        c.encode_bin(_MVD_G1, g1[0])
    if g0[1]:
        c.encode_bin(_MVD_G1, g1[1])
    for i, v in enumerate(comps):
        if not g0[i]:
            continue
        if g1[i]:
            rem = abs(v) - 2
            k = 1                               # EG1 bypass
            while rem >= (1 << k):
                c.encode_bypass(1)
                rem -= 1 << k
                k += 1
            c.encode_bypass(0)
            c.encode_bypass_bits(rem, k)
        c.encode_bypass(1 if v < 0 else 0)


class PSliceWriter:
    """Accumulates one P-slice's CABAC payload CTU by CTU.

    ``write_ctu_inter``: 2Nx2N inter CU with a quarter-pel MV
    ((y, x) DSP order — the bitstream's own resolution) and optional
    residual levels. ``write_ctu_intra``: the mode-26 intra CU, usable
    as fallback inside P slices.
    """

    def __init__(self, slice_qp: int, rows: int, cols: int) -> None:
        self.c = CabacEncoder(slice_qp, init_type=1)    # P initType
        self.grid = MvpGrid(rows, cols)

    def _common_p_prefix(self) -> None:
        # cu_skip_flag: never skipped; both neighbours are non-skip so
        # ctxInc is always 0
        self.c.encode_bin(_SKIP, 0)

    def write_ctu_inter(self, r: int, col: int, mv_q: tuple[int, int],
                        luma, cb, cr, *, last_in_slice: bool) -> None:
        """mv_q = (y, x) QUARTER luma pels (DSP order)."""
        c = self.c
        self._common_p_prefix()
        c.encode_bin(_PRED_MODE, 0)              # MODE_INTER
        c.encode_bin(_PART, 1)                   # PART_2Nx2N
        c.encode_bin(_MERGE, 0)                  # explicit AMVP
        mvq = (int(mv_q[1]), int(mv_q[0]))       # bitstream (x, y)
        pmx, pmy = self.grid.predictor(r, col)
        _write_mvd(c, mvq[0] - pmx, mvq[1] - pmy)
        c.encode_bin(_MVP, 0)                    # mvp_l0_flag = cand 0
        self.grid.record(r, col, inter=True, mv=mvq)

        def has(lv):
            return lv is not None and np.any(lv)

        cbf_l, cbf_cb, cbf_cr = has(luma), has(cb), has(cr)
        root = cbf_l or cbf_cb or cbf_cr
        c.encode_bin(_ROOT_CBF, int(root))       # rqt_root_cbf
        if not root:
            c.encode_terminate(1 if last_in_slice else 0)
            return
        # transform_tree depth 0 (no split): chroma cbfs, then luma cbf
        # — which is INFERRED 1 when both chroma are 0 (7.3.8.8)
        c.encode_bin(_CBF_CHROMA, int(cbf_cb))
        c.encode_bin(_CBF_CHROMA, int(cbf_cr))
        if cbf_cb or cbf_cr:
            c.encode_bin(_CBF_LUMA + 1, int(cbf_l))
        else:
            assert cbf_l, "rqt_root_cbf=1 with all-zero TBs"
        if cbf_l:
            write_residual(c, luma, log2_size=5, c_idx=0)
        if cbf_cb:
            write_residual(c, cb, log2_size=4, c_idx=1)
        if cbf_cr:
            write_residual(c, cr, log2_size=4, c_idx=2)
        c.encode_terminate(1 if last_in_slice else 0)

    def write_ctu_intra(self, r: int, col: int, luma, cb, cr, *,
                        last_in_slice: bool) -> None:
        """Intra fallback CU inside the P slice (mode 26, as slice.py)."""
        c = self.c
        self._common_p_prefix()
        c.encode_bin(_PRED_MODE, 1)              # MODE_INTRA
        c.encode_bin(_PART, 1)                   # 2Nx2N
        # MPM (8.4.2): candB is always DC (above PU leaves the CTB);
        # candA is 26 only when the LEFT CU exists and is itself intra
        # (inter neighbours contribute DC) — in P slices that depends on
        # per-CTB decisions, unlike the all-intra slice's static pattern:
        #   A=26, B=DC -> list {26, DC, planar} -> mpm_idx 0
        #   A=B=DC     -> list {planar, DC, 26} -> mpm_idx 2
        left_is_intra = (col > 0 and self.grid._coded[r, col - 1]
                         and not self.grid.inter[r, col - 1])
        prev_flag, mpm_idx = (1, 0) if left_is_intra else (1, 2)
        c.encode_bin(_PREV, prev_flag)
        if mpm_idx == 0:
            c.encode_bypass(0)
        else:
            c.encode_bypass(1)
            c.encode_bypass(mpm_idx - 1)
        c.encode_bin(_CHROMA, 0)                 # DM

        def has(lv):
            return lv is not None and np.any(lv)

        cbf_cb, cbf_cr, cbf_l = has(cb), has(cr), has(luma)
        c.encode_bin(_CBF_CHROMA, int(cbf_cb))
        c.encode_bin(_CBF_CHROMA, int(cbf_cr))
        c.encode_bin(_CBF_LUMA + 1, int(cbf_l))
        if cbf_l:
            write_residual(c, luma, log2_size=5, c_idx=0)
        if cbf_cb:
            write_residual(c, cb, log2_size=4, c_idx=1)
        if cbf_cr:
            write_residual(c, cr, log2_size=4, c_idx=2)
        self.grid.record(r, col, inter=False)
        c.encode_terminate(1 if last_in_slice else 0)

    def payload(self) -> bytes:
        return self.c.getvalue()


def p_nal(slice_qp: int, poc_lsb: int, payload: bytes) -> NalUnit:
    hdr = p_slice_header_bits(slice_qp, poc_lsb)
    return NalUnit(NAL_TRAIL_R, hdr.getvalue() + payload)
