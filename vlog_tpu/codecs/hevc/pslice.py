"""HEVC P-slice syntax: TRAIL pictures with integer-MV inter CTBs.

Extends the all-intra envelope (slice.py) with single-reference P
slices: every CTB is either an inter 2Nx2N CU with an explicitly coded
quarter-pel MV (AMVP, mvp_l0_flag=0, no merge/skip — avoids the merge
candidate machinery entirely at a cost of a few bins per CTB) or falls
back to the intra mode-26 CU when motion fails. The device DSP
(jax_core.py) interpolates with the spec 8-tap luma / 4-tap chroma
filters — the HEVC analog of the H.264 chain design.

The AMVP predictor (8.5.3.2.6) is computed by an entropy-time state
machine over the CTB grid, mirroring what any decoder derives:
candidate A = the left CU's MV (below-left is never decoded yet at CTB
granularity), candidate B = first of above-right/above/above-left,
pruned and zero-filled. All PUs share one reference picture (the
previous frame, RPS delta=1), so no MV scaling is ever needed.

Oracle: tests/test_hevc.py decodes I+P chains with libavcodec and
asserts byte-exact reconstruction.
"""

from __future__ import annotations

import numpy as np

from vlog_tpu.codecs.hevc.cabac import CabacEncoder
from vlog_tpu.codecs.hevc.residual import write_residual
from vlog_tpu.codecs.hevc.syntax import CTB, NalUnit
from vlog_tpu.codecs.hevc.tables import CTX_OFF
from vlog_tpu.media.bitstream import BitWriter

NAL_TRAIL_R = 1

_SKIP = CTX_OFF["SKIP"][0]
_PRED_MODE = CTX_OFF["PRED_MODE"][0]
_PART = CTX_OFF["PART_MODE"][0]
_MERGE = CTX_OFF["MERGE_FLAG"][0]
_MVP = CTX_OFF["MVP_LX"][0]
_ROOT_CBF = CTX_OFF["NO_RESIDUAL"][0]
# mvd_coding contexts: greater0 at the block base, greater1 at +3
# (both measured from the hls_mvd_coding disassembly)
_MVD_G0 = CTX_OFF["MVD_GREATER"][0]
_MVD_G1 = CTX_OFF["MVD_GREATER"][0] + 3
_PREV = CTX_OFF["PREV_INTRA_LUMA"][0]
_CHROMA = CTX_OFF["INTRA_CHROMA_PRED"][0]
_CBF_LUMA = CTX_OFF["CBF_LUMA"][0]
_CBF_CHROMA = CTX_OFF["CBF_CB_CR"][0]


def p_slice_header_bits(slice_qp: int, poc_lsb: int) -> BitWriter:
    """P slice header for our stream shape (7.3.6.1): one negative ref
    at delta 1, no SAO/deblock/temporal-MVP, merge depth 1."""
    w = BitWriter()
    w.write_bit(1)            # first_slice_segment_in_pic_flag
    w.write_ue(0)             # slice_pic_parameter_set_id
    w.write_ue(1)             # slice_type = P
    w.write_bits(poc_lsb & 0xFF, 8)   # slice_pic_order_cnt_lsb
    w.write_bit(0)            # short_term_ref_pic_set_sps_flag
    w.write_ue(1)             # num_negative_pics
    w.write_ue(0)             # num_positive_pics
    w.write_ue(0)             # delta_poc_s0_minus1 (prev picture)
    w.write_bit(1)            # used_by_curr_pic_s0_flag
    w.write_bit(0)            # num_ref_idx_active_override_flag (PPS: 1)
    w.write_ue(4)             # five_minus_max_num_merge_cand -> 1
    w.write_se(slice_qp - 26)  # slice_qp_delta
    w.write_bit(1)            # alignment_bit_equal_to_one
    w.byte_align(0)
    return w


def _has(levels) -> bool:
    return levels is not None and np.any(levels)


class MvpGrid:
    """AMVP over a 16x16-cell grid (encoder-side mirror of 8.5.3.2.6
    for our shape: CTB-sized 2Nx2N PUs or two-half 2NxN/Nx2N PUs).
    Tracks (is_inter, mv) per coded 16-cell; neighbor positions follow
    the spec's PU-bounding-box rules."""

    def __init__(self, rows: int, cols: int) -> None:
        self.rows, self.cols = rows * 2, cols * 2   # 16-cell grid
        self.inter = np.zeros((self.rows, self.cols), bool)
        self._coded = np.zeros((self.rows, self.cols), bool)
        self.mv = np.zeros((self.rows, self.cols, 2), np.int32)  # (x, y)

    def _cand(self, r: int, c: int):
        if 0 <= r < self.rows and 0 <= c < self.cols \
                and self._coded[r, c] and self.inter[r, c]:
            return tuple(int(v) for v in self.mv[r, c])
        return None

    def _predict_bbox(self, y0, y1, x0, x1) -> tuple:
        """mvp candidate 0 for a PU covering 16-cells rows y0..y1, cols
        x0..x1. Only the first list entry matters (mvp_l0_flag is always
        0): A1 if available, else the first of B0/B1/B2, else zero (the
        spec's A==B pruning and zero-fill only reorder entry 1).

        The second PU of a two-part CU may predict from the first
        (verified against libavcodec: the merge-style same-CU exclusion
        does NOT apply to AMVP), so PU0's cells — recorded before PU1
        is coded — are legitimate candidates here.

        A0 (below-left) precedes A1 in the spec scan; it is decoded
        only for the TOP PU of a 2NxN CU (where below-left is the left
        CTB's bottom half) — _cand's coded-gate makes probing it safe
        everywhere."""
        a = self._cand(y1 + 1, x0 - 1)           # A0 (below-left)
        if a is None:
            a = self._cand(y1, x0 - 1)           # A1
        if a is not None:
            return a
        for rc in ((y0 - 1, x1 + 1), (y0 - 1, x1),
                   (y0 - 1, x0 - 1)):            # B0, B1, B2
            b = self._cand(*rc)
            if b is not None:
                return b
        return (0, 0)

    def _pu_cells(self, r, c, vertical, pu):
        y0, x0 = 2 * r, 2 * c
        if vertical:                             # Nx2N: left/right 16x32
            return y0, y0 + 1, x0 + pu, x0 + pu
        return y0 + pu, y0 + pu, x0, x0 + 1      # 2NxN: top/bottom 32x16

    def predictor(self, r: int, c: int) -> tuple[int, int]:
        return self._predict_bbox(2 * r, 2 * r + 1, 2 * c, 2 * c + 1)

    def predictor_2part(self, r, c, *, vertical, pu) -> tuple[int, int]:
        return self._predict_bbox(*self._pu_cells(r, c, vertical, pu))

    def _fill(self, y0, y1, x0, x1, inter, mv):
        self.inter[y0:y1 + 1, x0:x1 + 1] = inter
        self._coded[y0:y1 + 1, x0:x1 + 1] = True
        self.mv[y0:y1 + 1, x0:x1 + 1] = mv

    def record(self, r: int, c: int, *, inter: bool,
               mv: tuple[int, int] = (0, 0)) -> None:
        self._fill(2 * r, 2 * r + 1, 2 * c, 2 * c + 1, inter, mv)

    def record_2part(self, r, c, *, vertical, pu, mv) -> None:
        self._fill(*self._pu_cells(r, c, vertical, pu), True, mv)


def _write_mvd(c: CabacEncoder, dx: int, dy: int) -> None:
    """mvd_coding (7.3.8.9): greater0/1 context bins, EG1 remainder and
    sign in bypass. (dx, dy) in quarter-pel, bitstream order x then y."""
    comps = (dx, dy)
    g0 = [int(v != 0) for v in comps]
    g1 = [int(abs(v) > 1) for v in comps]
    c.encode_bin(_MVD_G0, g0[0])
    c.encode_bin(_MVD_G0, g0[1])
    if g0[0]:
        c.encode_bin(_MVD_G1, g1[0])
    if g0[1]:
        c.encode_bin(_MVD_G1, g1[1])
    for i, v in enumerate(comps):
        if not g0[i]:
            continue
        if g1[i]:
            rem = abs(v) - 2
            k = 1                               # EG1 bypass
            while rem >= (1 << k):
                c.encode_bypass(1)
                rem -= 1 << k
                k += 1
            c.encode_bypass(0)
            c.encode_bypass_bits(rem, k)
        c.encode_bypass(1 if v < 0 else 0)


class PSliceWriter:
    """Accumulates one P-slice's CABAC payload CTU by CTU.

    ``write_ctu_inter``: 2Nx2N inter CU with a quarter-pel MV
    ((y, x) DSP order — the bitstream's own resolution) and optional
    residual levels. ``write_ctu_intra``: the mode-26 intra CU, usable
    as fallback inside P slices.
    """

    def __init__(self, slice_qp: int, rows: int, cols: int) -> None:
        self.c = CabacEncoder(slice_qp, init_type=1)    # P initType
        self.grid = MvpGrid(rows, cols)

    def _common_p_prefix(self) -> None:
        # cu_skip_flag: never skipped; both neighbours are non-skip so
        # ctxInc is always 0
        self.c.encode_bin(_SKIP, 0)

    def write_ctu_inter_2part(self, r: int, col: int, *, vertical: bool,
                              mv0, mv1, luma_tus, cb_tus, cr_tus,
                              last_in_slice: bool) -> None:
        """Inter CU split into two PUs: 2NxN (``vertical=False``, top/
        bottom 32x16) or Nx2N (left/right 16x32). ``mv0``/``mv1`` are
        (y, x) quarter-pel for the first/second PU. Residuals arrive as
        four forced sub-TUs in z-order: ``luma_tus`` four 16x16 arrays
        (or None), ``cb_tus``/``cr_tus`` four 8x8 arrays (or None) —
        max_transform_hierarchy_depth_inter=0 with a non-2Nx2N part
        forces the transform split (7.4.9.8 interSplitFlag)."""
        c = self.c
        self._common_p_prefix()
        c.encode_bin(_PRED_MODE, 0)              # MODE_INTER
        # part_mode (9.3.3.7, inter at MIN cb size — our CTB == minCB):
        # 2NxN = '01'; Nx2N = '001' (the third bin distinguishes NxN)
        c.encode_bin(_PART, 0)
        c.encode_bin(_PART + 1, 0 if vertical else 1)
        if vertical:
            c.encode_bin(_PART + 2, 1)

        # PU0 then PU1; AMVP per PU over the half-CTB (16-grid) cells
        for pu, mv in ((0, mv0), (1, mv1)):
            c.encode_bin(_MERGE, 0)
            mvq = (int(mv[1]), int(mv[0]))       # bitstream (x, y)
            pmx, pmy = self.grid.predictor_2part(
                r, col, vertical=vertical, pu=pu)
            _write_mvd(c, mvq[0] - pmx, mvq[1] - pmy)
            c.encode_bin(_MVP, 0)
            self.grid.record_2part(r, col, vertical=vertical, pu=pu,
                                   mv=mvq)

        root = any(_has(t) for tus in (luma_tus, cb_tus, cr_tus)
                   for t in tus)
        c.encode_bin(_ROOT_CBF, int(root))
        if not root:
            c.encode_terminate(1 if last_in_slice else 0)
            return
        # transform_tree depth 0: parent chroma cbfs cover the 16x16
        # chroma; the split to four TU16s is inferred (interSplitFlag)
        p_cb = any(_has(t) for t in cb_tus)
        p_cr = any(_has(t) for t in cr_tus)
        c.encode_bin(_CBF_CHROMA, int(p_cb))     # trafoDepth 0 ctx
        c.encode_bin(_CBF_CHROMA, int(p_cr))
        for i in range(4):                       # z-order sub-TUs
            cbf_l = _has(luma_tus[i])
            cbf_cb = _has(cb_tus[i])
            cbf_cr = _has(cr_tus[i])
            if p_cb:
                c.encode_bin(_CBF_CHROMA + 1, int(cbf_cb))
            if p_cr:
                c.encode_bin(_CBF_CHROMA + 1, int(cbf_cr))
            c.encode_bin(_CBF_LUMA, int(cbf_l))  # trafoDepth 1 ctx
            if cbf_l:
                write_residual(c, luma_tus[i], log2_size=4, c_idx=0)
            if cbf_cb:
                write_residual(c, cb_tus[i], log2_size=3, c_idx=1)
            if cbf_cr:
                write_residual(c, cr_tus[i], log2_size=3, c_idx=2)
        c.encode_terminate(1 if last_in_slice else 0)

    def write_ctu_inter(self, r: int, col: int, mv_q: tuple[int, int],
                        luma, cb, cr, *, last_in_slice: bool) -> None:
        """mv_q = (y, x) QUARTER luma pels (DSP order)."""
        c = self.c
        self._common_p_prefix()
        c.encode_bin(_PRED_MODE, 0)              # MODE_INTER
        c.encode_bin(_PART, 1)                   # PART_2Nx2N
        c.encode_bin(_MERGE, 0)                  # explicit AMVP
        mvq = (int(mv_q[1]), int(mv_q[0]))       # bitstream (x, y)
        pmx, pmy = self.grid.predictor(r, col)
        _write_mvd(c, mvq[0] - pmx, mvq[1] - pmy)
        c.encode_bin(_MVP, 0)                    # mvp_l0_flag = cand 0
        self.grid.record(r, col, inter=True, mv=mvq)

        cbf_l, cbf_cb, cbf_cr = _has(luma), _has(cb), _has(cr)
        root = cbf_l or cbf_cb or cbf_cr
        c.encode_bin(_ROOT_CBF, int(root))       # rqt_root_cbf
        if not root:
            c.encode_terminate(1 if last_in_slice else 0)
            return
        # transform_tree depth 0 (no split): chroma cbfs, then luma cbf
        # — which is INFERRED 1 when both chroma are 0 (7.3.8.8)
        c.encode_bin(_CBF_CHROMA, int(cbf_cb))
        c.encode_bin(_CBF_CHROMA, int(cbf_cr))
        if cbf_cb or cbf_cr:
            c.encode_bin(_CBF_LUMA + 1, int(cbf_l))
        else:
            assert cbf_l, "rqt_root_cbf=1 with all-zero TBs"
        if cbf_l:
            write_residual(c, luma, log2_size=5, c_idx=0)
        if cbf_cb:
            write_residual(c, cb, log2_size=4, c_idx=1)
        if cbf_cr:
            write_residual(c, cr, log2_size=4, c_idx=2)
        c.encode_terminate(1 if last_in_slice else 0)

    def write_ctu_intra(self, r: int, col: int, luma, cb, cr, *,
                        last_in_slice: bool) -> None:
        """Intra fallback CU inside the P slice (mode 26, as slice.py)."""
        c = self.c
        self._common_p_prefix()
        c.encode_bin(_PRED_MODE, 1)              # MODE_INTRA
        c.encode_bin(_PART, 1)                   # 2Nx2N
        # MPM (8.4.2): candB is always DC (above PU leaves the CTB);
        # candA is 26 only when the LEFT CU exists and is itself intra
        # (inter neighbours contribute DC) — in P slices that depends on
        # per-CTB decisions, unlike the all-intra slice's static pattern:
        #   A=26, B=DC -> list {26, DC, planar} -> mpm_idx 0
        #   A=B=DC     -> list {planar, DC, 26} -> mpm_idx 2
        left_is_intra = (col > 0 and self.grid._coded[2 * r, 2 * col - 1]
                         and not self.grid.inter[2 * r, 2 * col - 1])
        prev_flag, mpm_idx = (1, 0) if left_is_intra else (1, 2)
        c.encode_bin(_PREV, prev_flag)
        if mpm_idx == 0:
            c.encode_bypass(0)
        else:
            c.encode_bypass(1)
            c.encode_bypass(mpm_idx - 1)
        c.encode_bin(_CHROMA, 0)                 # DM

        cbf_cb, cbf_cr, cbf_l = _has(cb), _has(cr), _has(luma)
        c.encode_bin(_CBF_CHROMA, int(cbf_cb))
        c.encode_bin(_CBF_CHROMA, int(cbf_cr))
        c.encode_bin(_CBF_LUMA + 1, int(cbf_l))
        if cbf_l:
            write_residual(c, luma, log2_size=5, c_idx=0)
        if cbf_cb:
            write_residual(c, cb, log2_size=4, c_idx=1)
        if cbf_cr:
            write_residual(c, cr, log2_size=4, c_idx=2)
        self.grid.record(r, col, inter=False)
        c.encode_terminate(1 if last_in_slice else 0)

    def payload(self) -> bytes:
        return self.c.getvalue()


def p_nal(slice_qp: int, poc_lsb: int, payload: bytes) -> NalUnit:
    hdr = p_slice_header_bits(slice_qp, poc_lsb)
    return NalUnit(NAL_TRAIL_R, hdr.getvalue() + payload)
