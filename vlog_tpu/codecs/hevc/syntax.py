"""HEVC high-level syntax: NAL units, VPS/SPS/PPS, slice headers.

Stream shape (mirrors the constraints codecs/h264/syntax.py documents
for the H.264 path, adapted to H.265):

- Main profile, 8-bit 4:2:0, all-intra IDR frames.
- CTB = min CU = 32x32 (no coding-quadtree split bits), one 32x32 luma
  TU per CTB (no transform-tree split), 16x16 chroma TUs.
- Picture dimensions padded up to multiples of 32; the true size is
  restored by the SPS conformance window (same crop mechanism H.264's
  frame_cropping serves).
- SAO off, no tiles/WPP.  Deblocking is CONFIGURABLE (write_pps's
  ``deblock`` arg, config.HEVC_DEBLOCK, default on): when signalled on,
  the DSP runs spec 8.7.2 in-loop (codecs/hevc/deblock.py) so recon is
  pred+residual+filter; when off, recon is pred+residual exactly.  The
  PPS flag and the DSP flag must always agree — either way the
  encoder's device reconstruction matches any spec decoder
  bit-for-bit, which tests/test_hevc.py asserts against libavcodec.
- One slice per picture, entropy: CABAC (codecs/hevc/cabac.py).

Reference parity: the reference's HEVC rungs come from hevc_nvenc /
hevc_vaapi ffmpeg encoders (worker/hwaccel.py:509-552); this module is
the header layer of the TPU-native equivalent.
"""

from __future__ import annotations

from dataclasses import dataclass

from vlog_tpu.media.bitstream import BitWriter, escape_emulation

# nal_unit_type (H.265 table 7-1)
NAL_IDR_W_RADL = 19
NAL_VPS = 32
NAL_SPS = 33
NAL_PPS = 34

CTB = 32          # CtbSizeY == MinCbSizeY: no split_cu_flag in the stream

# (MaxLumaPs, level_idc) — H.265 table A.8, general_level_idc = 30*level
_LEVELS = [
    (36864, 30),        # 1
    (122880, 60),       # 2
    (245760, 63),       # 2.1
    (552960, 90),       # 3
    (983040, 93),       # 3.1
    (2228224, 120),     # 4
    (2228224, 123),     # 4.1
    (8912896, 150),     # 5
    (8912896, 153),     # 5.1
    (35651584, 180),    # 6
]


def coded_dims(width: int, height: int) -> tuple[int, int]:
    """Coded (CTB-padded) picture size for true display dimensions."""
    return ((width + CTB - 1) // CTB * CTB,
            (height + CTB - 1) // CTB * CTB)


def level_idc_for(width: int, height: int) -> int:
    """Level for the *coded* picture (pads internally, so VPS and SPS
    agree even when display dims sit just under a level threshold)."""
    cw, ch = coded_dims(width, height)
    luma_ps = cw * ch
    for max_ps, idc in _LEVELS:
        if luma_ps <= max_ps:
            return idc
    return 186  # 6.2


@dataclass
class NalUnit:
    nal_type: int
    rbsp: bytes

    def to_bytes(self) -> bytes:
        """Two-byte H.265 NAL header + emulation-protected payload."""
        b0 = (self.nal_type & 0x3F) << 1        # forbidden_zero + type
        b1 = 1                                  # layer_id 0, tid_plus1 1
        return bytes([b0, b1]) + escape_emulation(self.rbsp)


def annexb(nals: list[NalUnit]) -> bytes:
    out = bytearray()
    for n in nals:
        out += b"\x00\x00\x00\x01" + n.to_bytes()
    return bytes(out)


def _profile_tier_level(w: BitWriter, level_idc: int) -> None:
    """profile_tier_level, maxNumSubLayersMinus1 = 0 (7.3.3)."""
    w.write_bits(0, 2)       # general_profile_space
    w.write_bit(0)           # general_tier_flag
    w.write_bits(1, 5)       # general_profile_idc = Main
    for i in range(32):      # compatibility: Main (1) + Main 10 (2)
        w.write_bit(1 if i in (1, 2) else 0)
    w.write_bit(1)           # general_progressive_source_flag
    w.write_bit(0)           # general_interlaced_source_flag
    w.write_bit(1)           # general_non_packed_constraint_flag
    w.write_bit(1)           # general_frame_only_constraint_flag
    w.write_bits(0, 32)      # general_reserved_zero_44bits
    w.write_bits(0, 12)
    w.write_bits(level_idc, 8)


def write_vps(level_idc: int) -> NalUnit:
    w = BitWriter()
    w.write_bits(0, 4)       # vps_video_parameter_set_id
    w.write_bits(3, 2)       # vps_base_layer_{internal,available}_flag
    w.write_bits(0, 6)       # vps_max_layers_minus1
    w.write_bits(0, 3)       # vps_max_sub_layers_minus1
    w.write_bit(1)           # vps_temporal_id_nesting_flag
    w.write_bits(0xFFFF, 16)  # vps_reserved_0xffff_16bits
    _profile_tier_level(w, level_idc)
    w.write_bit(1)           # vps_sub_layer_ordering_info_present_flag
    w.write_ue(0)            # vps_max_dec_pic_buffering_minus1
    w.write_ue(0)            # vps_max_num_reorder_pics
    w.write_ue(0)            # vps_max_latency_increase_plus1
    w.write_bits(0, 6)       # vps_max_layer_id
    w.write_ue(0)            # vps_num_layer_sets_minus1
    w.write_bit(0)           # vps_timing_info_present_flag
    w.write_bit(0)           # vps_extension_flag
    w.rbsp_trailing_bits()
    return NalUnit(NAL_VPS, w.getvalue())


def write_sps(width: int, height: int) -> NalUnit:
    """``width``/``height`` are the true (display) dimensions; the coded
    size is padded to CTB multiples with a conformance-window crop."""
    cw, ch = coded_dims(width, height)
    w = BitWriter()
    w.write_bits(0, 4)       # sps_video_parameter_set_id
    w.write_bits(0, 3)       # sps_max_sub_layers_minus1
    w.write_bit(1)           # sps_temporal_id_nesting_flag
    _profile_tier_level(w, level_idc_for(cw, ch))
    w.write_ue(0)            # sps_seq_parameter_set_id
    w.write_ue(1)            # chroma_format_idc = 4:2:0
    w.write_ue(cw)           # pic_width_in_luma_samples
    w.write_ue(ch)           # pic_height_in_luma_samples
    if cw != width or ch != height:
        w.write_bit(1)       # conformance_window_flag
        w.write_ue(0)                          # left offset
        w.write_ue((cw - width) // 2)          # right (chroma units)
        w.write_ue(0)                          # top
        w.write_ue((ch - height) // 2)         # bottom
    else:
        w.write_bit(0)
    w.write_ue(0)            # bit_depth_luma_minus8
    w.write_ue(0)            # bit_depth_chroma_minus8
    w.write_ue(4)            # log2_max_pic_order_cnt_lsb_minus4
    w.write_bit(1)           # sps_sub_layer_ordering_info_present_flag
    w.write_ue(0)            # sps_max_dec_pic_buffering_minus1
    w.write_ue(0)            # sps_max_num_reorder_pics
    w.write_ue(0)            # sps_max_latency_increase_plus1
    w.write_ue(2)            # log2_min_luma_coding_block_size_minus3 -> 32
    w.write_ue(0)            # log2_diff_max_min_luma_coding_block_size
    w.write_ue(0)            # log2_min_luma_transform_block_size_minus2
    w.write_ue(3)            # log2_diff_max_min -> max TB 32
    w.write_ue(0)            # max_transform_hierarchy_depth_inter
    w.write_ue(0)            # max_transform_hierarchy_depth_intra
    w.write_bit(0)           # scaling_list_enabled_flag
    w.write_bit(0)           # amp_enabled_flag
    w.write_bit(0)           # sample_adaptive_offset_enabled_flag
    w.write_bit(0)           # pcm_enabled_flag
    w.write_ue(0)            # num_short_term_ref_pic_sets
    w.write_bit(0)           # long_term_ref_pics_present_flag
    w.write_bit(0)           # sps_temporal_mvp_enabled_flag
    w.write_bit(0)           # strong_intra_smoothing_enabled_flag
    w.write_bit(0)           # vui_parameters_present_flag
    w.write_bit(0)           # sps_extension_present_flag
    w.rbsp_trailing_bits()
    return NalUnit(NAL_SPS, w.getvalue())


def write_pps(deblock: bool = False) -> NalUnit:
    w = BitWriter()
    w.write_ue(0)            # pps_pic_parameter_set_id
    w.write_ue(0)            # pps_seq_parameter_set_id
    w.write_bit(0)           # dependent_slice_segments_enabled_flag
    w.write_bit(0)           # output_flag_present_flag
    w.write_bits(0, 3)       # num_extra_slice_header_bits
    w.write_bit(0)           # sign_data_hiding_enabled_flag
    w.write_bit(0)           # cabac_init_present_flag
    w.write_ue(0)            # num_ref_idx_l0_default_active_minus1
    w.write_ue(0)            # num_ref_idx_l1_default_active_minus1
    w.write_se(0)            # init_qp_minus26 (per-frame QP via slice)
    w.write_bit(0)           # constrained_intra_pred_flag
    w.write_bit(0)           # transform_skip_enabled_flag
    w.write_bit(0)           # cu_qp_delta_enabled_flag
    w.write_se(0)            # pps_cb_qp_offset
    w.write_se(0)            # pps_cr_qp_offset
    w.write_bit(0)           # pps_slice_chroma_qp_offsets_present_flag
    w.write_bit(0)           # weighted_pred_flag
    w.write_bit(0)           # weighted_bipred_flag
    w.write_bit(0)           # transquant_bypass_enabled_flag
    w.write_bit(0)           # tiles_enabled_flag
    w.write_bit(0)           # entropy_coding_sync_enabled_flag
    # across-slices off keeps slice headers free of the across-slices
    # flag when deblocking is on (7.3.6.1 gates it on this && !disabled)
    # — pictures are single-slice, so the flag is moot either way
    w.write_bit(0)           # pps_loop_filter_across_slices_enabled_flag
    if deblock:
        # control_present 0 -> 8.7.2 runs with zero beta/tc offsets and
        # no override; nothing more appears here or in slice headers
        w.write_bit(0)       # deblocking_filter_control_present_flag
    else:
        w.write_bit(1)       # deblocking_filter_control_present_flag
        w.write_bit(0)       # deblocking_filter_override_enabled_flag
        w.write_bit(1)       # pps_deblocking_filter_disabled_flag
    w.write_bit(0)           # pps_scaling_list_data_present_flag
    w.write_bit(0)           # lists_modification_present_flag
    w.write_ue(0)            # log2_parallel_merge_level_minus2
    w.write_bit(0)           # slice_segment_header_extension_present_flag
    w.write_bit(0)           # pps_extension_present_flag
    w.rbsp_trailing_bits()
    return NalUnit(NAL_PPS, w.getvalue())


def slice_header_bits(slice_qp: int) -> BitWriter:
    """I-slice IDR header; caller appends CABAC payload after the
    byte-alignment these bits end on (7.3.6.1)."""
    w = BitWriter()
    w.write_bit(1)           # first_slice_segment_in_pic_flag
    w.write_bit(0)           # no_output_of_prior_pics_flag (IDR)
    w.write_ue(0)            # slice_pic_parameter_set_id
    w.write_ue(2)            # slice_type = I
    # SAO off in SPS, IDR -> no POC/RPS fields, temporal MVP off
    w.write_se(slice_qp - 26)  # slice_qp_delta
    # deblocking: PPS disables it and override is off -> nothing here
    # loop_filter_across_slices: only when (sao||deblock) signalled -> no
    # tiles/WPP off -> no entry points
    w.write_bit(1)           # alignment_bit_equal_to_one (7.3.2.10)
    w.byte_align(0)
    return w


def idr_nal(slice_qp: int, cabac_payload: bytes) -> NalUnit:
    hdr = slice_header_bits(slice_qp)
    return NalUnit(NAL_IDR_W_RADL, hdr.getvalue() + cabac_payload)
