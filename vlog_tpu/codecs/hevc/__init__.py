"""First-party HEVC (H.265) encoder — TPU compute core + CABAC host
entropy. See syntax.py for the stream shape and encoder.py for the API."""
