"""CABAC arithmetic encoder for HEVC (ITU-T H.265 9.3).

HEVC entropy coding is CABAC-only (unlike H.264, where this framework
uses CAVLC — codecs/h264/cavlc.py), so this is the first-party binary
arithmetic coder: the standard low/range engine with outstanding-bit
carry resolution, context models as (pStateIdx, valMPS) pairs advanced
through the shared H.264/H.265 transition tables, bypass coding for
equiprobable bins, and the terminate bin that closes every CTU row and
the slice.

This Python implementation is the bit-exact reference the tests oracle
against libavcodec; the C port (native/hevc_cabac.c) mirrors it for
production throughput, the same split as cavlc.py / native/cavlc.c.

Reference parity: the reference never encodes HEVC on CPU — it shells
out to hevc_nvenc / hevc_vaapi (worker/hwaccel.py) — so this module is
the TPU-platform analog of those vendor encoders' entropy stage.
"""

from __future__ import annotations

from vlog_tpu.codecs.hevc.tables import (
    INIT_VALUES,
    RANGE_TAB_LPS,
    TRANS_IDX_LPS,
    TRANS_IDX_MPS,
)

N_CONTEXTS = 199


def init_states(slice_qp: int, init_type: int = 0) -> tuple[list, list]:
    """ContextModel init (H.265 9.3.2.2): initValue -> (pStateIdx, valMPS).

    ``init_type`` 0 is I slices; 1/2 are P/B (cabac_init_flag permuted),
    unused until an inter path exists.
    """
    qp = min(max(slice_qp, 0), 51)
    pstate = [0] * N_CONTEXTS
    mps = [0] * N_CONTEXTS
    for i, init_value in enumerate(INIT_VALUES[init_type]):
        slope = (init_value >> 4) * 5 - 45
        offset = ((init_value & 15) << 3) - 16
        pre = min(max(((slope * qp) >> 4) + offset, 1), 126)
        if pre <= 63:
            pstate[i], mps[i] = 63 - pre, 0
        else:
            pstate[i], mps[i] = pre - 64, 1
    return pstate, mps


class ArithEncoder:
    """The shared binary arithmetic engine (identical in H.264 9.3.4 and
    H.265 9.3.4 — same range/transition tables, renorm, bypass, and
    terminate/flush). Subclasses provide the context initialization."""

    def __init__(self, pstate: list, mps: list) -> None:
        self.pstate = pstate
        self.mps = mps
        self.low = 0
        self.range = 510
        self.outstanding = 0
        self.first_bit = True
        self._bytes = bytearray()
        self._cur = 0
        self._nbits = 0

    # ---------------------------------------------------------- raw bits
    def _emit(self, bit: int) -> None:
        self._cur = (self._cur << 1) | bit
        self._nbits += 1
        if self._nbits == 8:
            self._bytes.append(self._cur)
            self._cur = 0
            self._nbits = 0

    def _put_bit(self, bit: int) -> None:
        if self.first_bit:
            # the spec encoder discards the very first generated bit
            self.first_bit = False
        else:
            self._emit(bit)
        while self.outstanding > 0:
            self._emit(1 - bit)
            self.outstanding -= 1

    def _renorm(self) -> None:
        while self.range < 256:
            if self.low >= 512:
                self._put_bit(1)
                self.low -= 512
            elif self.low < 256:
                self._put_bit(0)
            else:
                self.outstanding += 1
                self.low -= 256
            self.low <<= 1
            self.range <<= 1

    # ---------------------------------------------------------- bins
    def encode_bin(self, ctx: int, bin_val: int) -> None:
        p = self.pstate[ctx]
        rlps = RANGE_TAB_LPS[p][(self.range >> 6) & 3]
        self.range -= rlps
        if bin_val != self.mps[ctx]:
            self.low += self.range
            self.range = rlps
            if p == 0:
                self.mps[ctx] ^= 1
            self.pstate[ctx] = TRANS_IDX_LPS[p]
        else:
            self.pstate[ctx] = TRANS_IDX_MPS[p]
        self._renorm()

    def encode_bypass(self, bin_val: int) -> None:
        self.low <<= 1
        if bin_val:
            self.low += self.range
        if self.low >= 1024:
            self._put_bit(1)
            self.low -= 1024
        elif self.low < 512:
            self._put_bit(0)
        else:
            self.outstanding += 1
            self.low -= 512

    def encode_bypass_bits(self, value: int, width: int) -> None:
        for i in range(width - 1, -1, -1):
            self.encode_bypass((value >> i) & 1)

    def encode_terminate(self, bin_val: int) -> None:
        """end_of_slice_segment_flag / end_of_subset (9.3.4.3.5)."""
        self.range -= 2
        if bin_val:
            self.low += self.range
            self.range = 2
            self._flush()
        else:
            self._renorm()

    def _flush(self) -> None:
        self._renorm()
        self._put_bit((self.low >> 9) & 1)
        # WriteBits(((low >> 7) & 3) | 1, 2): the trailing 1 is the
        # rbsp_stop_one_bit of the slice data
        self._emit((self.low >> 8) & 1)
        self._emit(1)

    # ---------------------------------------------------------- output
    def getvalue(self) -> bytes:
        """Byte-aligned slice payload (after encode_terminate(1), the
        stop bit is in the stream; pad with cabac_zero-safe zeros)."""
        out = bytearray(self._bytes)
        if self._nbits:
            out.append(self._cur << (8 - self._nbits))
        return bytes(out)


class CabacEncoder(ArithEncoder):
    """H.265 contexts over the shared engine (I/P initTypes)."""

    def __init__(self, slice_qp: int, init_type: int = 0) -> None:
        super().__init__(*init_states(slice_qp, init_type))
