"""HEVC intra DSP on the device: batched pred/transform/quant/recon.

The XLA program mirrors the H.264 core's shape (codecs/h264/encoder.py):
CTB row 0 is a ``lax.scan`` over columns (its prediction chains through
the left neighbour's top-right reconstructed pixel — a scalar carry),
and every later CTB row is one batched step of a ``lax.scan`` over rows
whose carry is the previous row's reconstructed bottom line.  All three
planes use exact-vertical prediction, so nothing else crosses CTBs.

The transforms are plain (32,32)/(16,16) integer matmuls — exactly what
the MXU wants — with the spec-exact inverse (stage clipping included) so
device recon equals transform.py's numpy reference bit-for-bit, which
test_hevc.py asserts, and equals any conforming decoder's output.

QP is a traced scalar (per-frame rate control can feed it without
recompiling); frames batch via ``vmap``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from vlog_tpu.codecs.hevc.transform import (
    LEVEL_SCALE,
    QUANT_SCALE,
    T8,
    T16,
    T32,
    _QPC,
)

_QPC_ARR = np.array(_QPC + [0] * 16, dtype=np.int32)  # padded; >=43 computed


def chroma_qp_traced(qp):
    qpi = jnp.clip(qp, 0, 51)
    return jnp.where(qpi < 43, jnp.asarray(_QPC_ARR)[jnp.minimum(qpi, 42)],
                     qpi - 6)


# All arithmetic below is int32 (JAX's default integer width).  Why that
# is safe: 8-bit residuals through the 32-point stages peak below 2^27
# (|m|<=90, 32 taps, stage shifts), quant products peak at ~2^30
# (|coeff|<=~2^15 x 26214), and the one genuinely 33-bit product — the
# spec's dequant ``level*16*levelScale << per`` — is decomposed into an
# int32 product plus a net shift, exactly (proof in _dequant).

def _fwd(res, mat, log2n):
    s1 = log2n - 1
    s2 = log2n + 6
    tmp = (mat @ res + (1 << (s1 - 1))) >> s1
    return (tmp @ mat.T + (1 << (s2 - 1))) >> s2


def _inv(coeff, mat):
    e = (mat.T @ coeff + 64) >> 7
    e = jnp.clip(e, -32768, 32767)
    r = (e @ mat + (1 << 11)) >> 12          # 8-bit: shift 20-8
    return jnp.clip(r, -32768, 32767)


def _quant(coeff, qp, log2n):
    tr_shift = 15 - 8 - log2n
    qbits = 14 + qp // 6 + tr_shift
    f = jnp.asarray(QUANT_SCALE, jnp.int32)[qp % 6]
    # (1<<qbits)*171 >> 9 == 171 << (qbits-9): qbits is always >= 14, and
    # the shifted form peaks at 171<<16 ~ 2^23.5 — the direct product
    # would wrap int32 at qp >= 48 (qbits 24+)
    offset = jnp.int32(171) << (qbits - 9)
    level = (jnp.abs(coeff) * f + offset) >> qbits
    return jnp.sign(coeff) * jnp.clip(level, 0, 32767)


def _dequant(level, qp, log2n):
    """Spec 8.6.3 restated int32-safely.

    d = (level*16*ls << per + 1<<(bd-1)) >> bd  with a = level*16*ls
    (|a| <= 32767*16*72 < 2^26):
      per >= bd: low ``per`` bits of a<<per are zero and the offset
        shifts to < 1, so d = a << (per-bd) exactly;
      per <  bd: divide numerator and denominator by 2^per, so
        d = (a + 1<<(bd-per-1)) >> (bd-per) exactly.
    Arithmetic right-shift floors for negatives in numpy and XLA alike.
    """
    bd = 8 + log2n - 5
    per = qp // 6
    a = level * (jnp.asarray(LEVEL_SCALE, jnp.int32)[qp % 6] * 16)
    d = jnp.where(per >= bd,
                  a << jnp.maximum(per - bd, 0),
                  (a + (jnp.int32(1) << jnp.maximum(bd - per - 1, 0)))
                  >> jnp.maximum(bd - per, 0))
    return jnp.clip(d, -32768, 32767)


def _code_blocks(src, pred, qp, mat, log2n):
    """src/pred: (..., N, N) int32 -> (levels, recon) both int32."""
    res = src - pred
    levels = _quant(_fwd(res, mat, log2n), qp, log2n)
    rec = _inv(_dequant(levels, qp, log2n), mat)
    return levels, jnp.clip(pred + rec, 0, 255)


def _encode_plane(plane, qp, mat, n):
    """One plane (H, W) uint8 -> levels (R, C, N, N) int32, recon (H, W).

    ``n``/``mat`` static; qp traced scalar (already chroma-mapped).
    """
    log2n = n.bit_length() - 1
    h, w = plane.shape
    rows, cols = h // n, w // n
    src = plane.astype(jnp.int32).reshape(rows, n, cols, n).transpose(
        0, 2, 1, 3)                       # (R, C, N, N)

    # ---- CTB row 0: scan over columns, scalar carry ------------------
    def col_step(carry, blk):
        pred = jnp.full((n, n), carry, jnp.int32)
        levels, recon = _code_blocks(blk, pred, qp, mat, log2n)
        return recon[0, n - 1], (levels, recon)

    _, (lev0, rec0) = jax.lax.scan(col_step, jnp.int32(128), src[0])

    # ---- rows 1..R-1: scan over rows, bottom-line carry --------------
    def row_step(bottom, row_blks):          # bottom: (W,), row: (C, N, N)
        pred = jnp.broadcast_to(
            bottom.reshape(cols, 1, n), (cols, n, n))
        levels, recon = _code_blocks(row_blks, pred, qp, mat, log2n)
        return recon[:, n - 1, :].reshape(w), (levels, recon)

    bottom0 = rec0[:, n - 1, :].reshape(w)
    if rows > 1:
        _, (lev_r, rec_r) = jax.lax.scan(row_step, bottom0, src[1:])
        levels = jnp.concatenate([lev0[None], lev_r], axis=0)
        recon = jnp.concatenate([rec0[None], rec_r], axis=0)
    else:
        levels, recon = lev0[None], rec0[None]
    recon_plane = recon.transpose(0, 2, 1, 3).reshape(h, w).astype(jnp.uint8)
    return levels, recon_plane


# ---------------------------------------------------------------- inter
# Quarter-pel P frames (see pslice.py — the slice syntax carries MVs at
# quarter-pel resolution regardless): luma MC is the HEVC two-stage
# 8-tap interpolation (table 8-11), chroma the 4-tap eighth-pel filter
# (table 8-32). Horizontal passes become per-fraction filtered planes
# (un-normalized, gain 64 — the spec's 8-bit path has no intermediate
# shift); the vertical pass is an 8-gather weighted sum with per-pixel
# weight rows, uniformly >>6 then rounded >>6 at the end, which matches
# the spec case-by-case because the shifts commute exactly with the
# integer convolutions. Motion search is integer offset-scan SADs plus
# half- then quarter-pel refinement, at 32x32 CTB granularity.

# luma 8-tap rows (fraction 0 is the 64-delta so every case unifies)
_LTAPS = np.array([
    [0, 0, 0, 64, 0, 0, 0, 0],
    [-1, 4, -10, 58, 17, -5, 1, 0],
    [-1, 4, -11, 40, 40, -11, 4, -1],
    [0, 1, -5, 17, 58, -10, 4, -1],
], np.int32)
# chroma 4-tap rows per eighth fraction
_CTAPS = np.array([
    [0, 64, 0, 0],
    [-2, 58, 10, -2],
    [-4, 54, 16, -2],
    [-6, 46, 28, -4],
    [-4, 36, 36, -4],
    [-4, 28, 46, -6],
    [-2, 16, 54, -4],
    [-2, 10, 58, -2],
], np.int32)


def _hfiltered_planes(refp, taps):
    """Horizontal pass: one un-normalized plane per fraction row
    (fraction 0 = ref<<6, so the stack is at uniform gain 64)."""
    planes = []
    center = taps.shape[1] // 2 - 1     # tap k applies at offset k-center
    for f in range(taps.shape[0]):
        if f == 0:
            planes.append(refp << 6)
            continue
        acc = None
        for k in range(taps.shape[1]):
            t = int(taps[f, k])
            if t == 0:
                continue
            term = t * jnp.roll(refp, center - k, axis=1)
            acc = term if acc is None else acc + term
        planes.append(acc)
    return jnp.stack(planes)            # (F, Hp, Wp)


def _mc_luma_qpel(hplanes, mv_q, *, pad, h, w, n=32):
    """Luma MC at quarter-pel MVs: per-pixel plane select (by fx) then
    the vertical 8-tap as eight gathers with per-pixel weight rows.
    ``mv_q`` is an (h/n, w/n, 2) grid — n=32 for CTB MVs, 16 for the
    partitioned motion field."""
    dy = jnp.repeat(jnp.repeat(mv_q[..., 0], n, 0), n, 1)
    dx = jnp.repeat(jnp.repeat(mv_q[..., 1], n, 0), n, 1)
    iy, fy = dy >> 2, dy & 3
    ix, fx = dx >> 2, dx & 3
    rows = jnp.arange(h)[:, None] + iy + pad
    cols = jnp.arange(w)[None, :] + ix + pad
    wtab = jnp.asarray(_LTAPS)                      # (4, 8)
    acc = jnp.zeros((h, w), jnp.int32)
    for j in range(8):
        gj = jnp.take_along_axis(
            hplanes[:, rows + (j - 3), cols], fx[None], axis=0)[0]
        acc = acc + wtab[fy, j] * gj
    pred = acc >> 6
    return jnp.clip((pred + 32) >> 6, 0, 255)


def _mc_chroma_qpel(cplanes, mv_q, *, pad, hc, wc, n=16):
    """Chroma MC: the luma quarter-pel value lands on the eighth-chroma
    grid; 4-tap vertical over the fx-selected horizontal plane. ``n``
    is the chroma block size matching the MV grid (16 per CTB MV, 8
    per 16-luma-cell MV)."""
    dy = jnp.repeat(jnp.repeat(mv_q[..., 0], n, 0), n, 1)
    dx = jnp.repeat(jnp.repeat(mv_q[..., 1], n, 0), n, 1)
    iy, fy = dy >> 3, dy & 7
    ix, fx = dx >> 3, dx & 7
    rows = jnp.arange(hc)[:, None] + iy + pad
    cols = jnp.arange(wc)[None, :] + ix + pad
    wtab = jnp.asarray(_CTAPS)                      # (8, 4)
    acc = jnp.zeros((hc, wc), jnp.int32)
    for j in range(4):
        gj = jnp.take_along_axis(
            cplanes[:, rows + (j - 1), cols], fx[None], axis=0)[0]
        acc = acc + wtab[fy, j] * gj
    pred = acc >> 6
    return jnp.clip((pred + 32) >> 6, 0, 255)


def _p_ctb_search(cur, refp, hplanes, *, search, pad, lam=2, n=32):
    """Integer offset-scan ME per nxn block, then half- and quarter-pel
    refinement through the real interpolation:
    (H, W) -> ((H/n, W/n, 2) MVs in QUARTER pels, final SADs)."""
    h, w = cur.shape
    rr, cc = h // n, w // n
    offsets = [(0, 0)] + [
        (dy, dx) for dy in range(-search, search + 1)
        for dx in range(-search, search + 1) if (dy, dx) != (0, 0)]
    offs = jnp.asarray(offsets, jnp.int32)

    def step(carry, off):
        best_sad, best_mv = carry
        shifted = jax.lax.dynamic_slice(
            refp, (pad + off[0], pad + off[1]), (h, w))
        sad = jnp.abs(cur - shifted).reshape(rr, n, cc, n).sum(
            axis=(1, 3))
        sad = sad + lam * 4 * (jnp.abs(off[0]) + jnp.abs(off[1]))
        better = sad < best_sad
        return (jnp.where(better, sad, best_sad),
                jnp.where(better[..., None], off[None, None, :],
                          best_mv)), None

    init = (jnp.full((rr, cc), jnp.iinfo(jnp.int32).max, jnp.int32),
            jnp.zeros((rr, cc, 2), jnp.int32))
    (int_sad, mv_int), _ = jax.lax.scan(step, init, offs)

    neigh = jnp.asarray(
        [(dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1)
         if (dy, dx) != (0, 0)], jnp.int32)

    def refine(base_q, base_sad, step_q):
        def rstep(carry, off):
            best_sad, best_mv = carry
            cand = base_q + step_q * off[None, None, :]
            pred = _mc_luma_qpel(hplanes, cand, pad=pad, h=h, w=w, n=n)
            sad = jnp.abs(cur - pred.astype(jnp.int32)).reshape(
                rr, n, cc, n).sum(axis=(1, 3))
            sad = sad + lam * (jnp.abs(cand[..., 0])
                               + jnp.abs(cand[..., 1]))
            better = sad < best_sad
            return (jnp.where(better, sad, best_sad),
                    jnp.where(better[..., None], cand, best_mv)), None

        (sad, mv), _ = jax.lax.scan(rstep, (base_sad, base_q), neigh)
        return mv, sad

    mv_q, sad_q = refine(mv_int * 4, int_sad, 2)
    mv_q, sad_q = refine(mv_q, sad_q, 1)
    return mv_q, sad_q


def _p_residuals_and_recon(y, u, v, cur, hplanes, mv_map, part, qp, qpc,
                           pad, search, ref_u, ref_v, partitions=True):
    """MC + both residual codings + decision-consistent recon (shared by
    the partitioned and single-MV paths of encode_p_frame_dsp)."""
    h, w = y.shape
    pred_y = _mc_luma_qpel(hplanes, mv_map, pad=pad, h=h, w=w,
                           n=16).astype(jnp.int32)
    cpad = search // 2 + 6
    hc, wc = u.shape
    cplanes_u = _hfiltered_planes(
        jnp.pad(ref_u.astype(jnp.int32), cpad, mode="edge"), _CTAPS)
    cplanes_v = _hfiltered_planes(
        jnp.pad(ref_v.astype(jnp.int32), cpad, mode="edge"), _CTAPS)
    pred_u = _mc_chroma_qpel(cplanes_u, mv_map, pad=cpad, hc=hc, wc=wc,
                             n=8).astype(jnp.int32)
    pred_v = _mc_chroma_qpel(cplanes_v, mv_map, pad=cpad, hc=hc, wc=wc,
                             n=8).astype(jnp.int32)

    # ---- both residual codings over the SAME prediction
    cu = u.astype(jnp.int32)
    cv = v.astype(jnp.int32)
    ly32, ry32 = _code_blocks(to_blocks(cur, 32), to_blocks(pred_y, 32),
                              qp, jnp.asarray(T32), 5)
    lu16, ru16 = _code_blocks(to_blocks(cu, 16), to_blocks(pred_u, 16),
                              qpc, jnp.asarray(T16), 4)
    lv16, rv16 = _code_blocks(to_blocks(cv, 16), to_blocks(pred_v, 16),
                              qpc, jnp.asarray(T16), 4)
    if not partitions:
        # single-MV path: the sub-TU codings would never be read — skip
        # the transforms and the device->host level traffic entirely
        return ((ly32, lu16, lv16), None, part, mv_map,
                (from_blocks(ry32, 32).astype(jnp.uint8),
                 from_blocks(ru16, 16).astype(jnp.uint8),
                 from_blocks(rv16, 16).astype(jnp.uint8)))
    ly16, ry16 = _code_blocks(to_blocks(cur, 16), to_blocks(pred_y, 16),
                              qp, jnp.asarray(T16), 4)
    lu8, ru8 = _code_blocks(to_blocks(cu, 8), to_blocks(pred_u, 8),
                            qpc, jnp.asarray(T8), 3)
    lv8, rv8 = _code_blocks(to_blocks(cv, 8), to_blocks(pred_v, 8),
                            qpc, jnp.asarray(T8), 3)

    # ---- recon consistent with the per-CTB transform choice
    def select(plane32, plane16, cells_per_ctb):
        mask = jnp.repeat(jnp.repeat(part == PART_2Nx2N,
                                     cells_per_ctb, 0), cells_per_ctb, 1)
        return jnp.where(mask, plane32, plane16)

    ry = select(from_blocks(ry32, 32), from_blocks(ry16, 16), 32)
    ru = select(from_blocks(ru16, 16), from_blocks(ru8, 8), 16)
    rv = select(from_blocks(rv16, 16), from_blocks(rv8, 8), 16)
    return ((ly32, lu16, lv16), (ly16, lu8, lv8), part, mv_map,
            (ry.astype(jnp.uint8), ru.astype(jnp.uint8),
             rv.astype(jnp.uint8)))



# partition codes per CTB
PART_2Nx2N, PART_2NxN, PART_Nx2N = 0, 1, 2
# mode decision penalty per extra MV (SAD units), scaled by 2^(qp/6)
_PART_PENALTY = 24


def to_blocks(plane, n):
    r2, c2 = plane.shape[0] // n, plane.shape[1] // n
    return plane.reshape(r2, n, c2, n).transpose(0, 2, 1, 3)


def from_blocks(blk, n):
    return blk.transpose(0, 2, 1, 3).reshape(blk.shape[0] * n,
                                             blk.shape[1] * n)


def encode_p_frame_dsp(y, u, v, ref_y, ref_u, ref_v, qp, *,
                       search: int = 16, partitions: bool = True,
                       deblock: bool = False):
    """One P frame against the previous reconstruction. Every CTB is
    inter; per CTB the motion field is 2Nx2N (one MV), 2NxN or Nx2N
    (two MVs) — chosen where the independently-refined 16-cell MVs
    agree per half, so partition SADs are exact without extra
    evaluations. Returns per-CTB partition codes, the 16-cell MV map,
    BOTH residual codings (TU32+chroma16 for 2Nx2N; four TU16 + 8x8
    chroma sub-TUs for two-part CTBs — entropy picks by partition), and
    the recon consistent with the decision (in-loop deblocked per
    spec 8.7.2 when ``deblock`` — the reference a decoder would hold)."""
    qp = jnp.asarray(qp, jnp.int32)
    qpc = chroma_qp_traced(qp)
    # luma pad: integer reach + 1 refinement pel + 4-tap reach + the
    # 4-sample roll-wrap contamination ring of the horizontal filters
    pad = search + 8
    h, w = y.shape
    rr, cc = h // 32, w // 32
    cur = y.astype(jnp.int32)
    refp = jnp.pad(ref_y.astype(jnp.int32), pad, mode="edge")
    hplanes = _hfiltered_planes(refp, _LTAPS)
    mv32, sad32 = _p_ctb_search(cur, refp, hplanes, search=search,
                                pad=pad, n=32)
    if not partitions:
        # single-MV CTBs only: skip the 16-cell search and hypothesis
        # evaluations entirely (this is the production default until the
        # mode-decision penalty is calibrated and the C entropy coder
        # covers two-part CUs)
        part = jnp.zeros((rr, cc), jnp.int32)
        mv_map = jnp.repeat(jnp.repeat(mv32, 2, 0), 2, 1)
        out = _p_residuals_and_recon(
            y, u, v, cur, hplanes, mv_map, part, qp, qpc, pad, search,
            ref_u, ref_v, partitions=False)
        return _deblock_p(out, qp, qpc) if deblock else out
    mv16, _ = _p_ctb_search(cur, refp, hplanes, search=search,
                            pad=pad, n=16)

    # ---- partition decision. Each half of a two-part CTB must share
    # ONE MV; candidates are the half's two refined 16-cell MVs, and
    # each candidate is evaluated exactly (one MC pass per variant, the
    # SADs summed per half), so the costs compared below are real.
    def _sad16_under(mv_cells):
        pred = _mc_luma_qpel(hplanes, mv_cells, pad=pad, h=h, w=w, n=16)
        return jnp.abs(cur - pred.astype(jnp.int32)).reshape(
            rr, 2, 16, cc, 2, 16).sum(axis=(2, 5))   # (R, ry, C, rx)

    m = mv16.reshape(rr, 2, cc, 2, 2)            # (R, ry, C, rx, yx)

    def half_costs(horizontal):
        """Exact per-CTB cost + per-half MVs for 2NxN (horizontal=True,
        halves are cell ROWS) or Nx2N (halves are cell COLUMNS)."""
        if horizontal:
            # candidate per (CTB row, half-row): the half's two cells
            cand_a = m[:, :, :, 0]               # (R, ry, C, 2)
            cand_b = m[:, :, :, 1]
            expand = lambda cm: jnp.repeat(      # noqa: E731
                cm.reshape(rr * 2, cc, 2), 2, 1)
        else:
            # candidate per (CTB row, half-col): transpose rx to front
            mt = m.transpose(0, 3, 2, 1, 4)      # (R, rx, C, ry, yx)
            cand_a = mt[:, :, :, 0]              # (R, rx, C, 2)
            cand_b = mt[:, :, :, 1]
            expand = lambda cm: jnp.repeat(      # noqa: E731
                cm.transpose(0, 2, 1, 3).reshape(rr, cc * 2, 2), 2, 0)
        s_a = _sad16_under(expand(cand_a))       # (R, ry, C, rx)
        s_b = _sad16_under(expand(cand_b))
        if horizontal:
            ha = s_a.sum(axis=3)                 # (R, ry, C)
            hb = s_b.sum(axis=3)
        else:
            ha = s_a.sum(axis=1).transpose(0, 2, 1)   # (R, rx, C)
            hb = s_b.sum(axis=1).transpose(0, 2, 1)
        best = jnp.minimum(ha, hb)
        mv_best = jnp.where((hb < ha)[..., None], cand_b, cand_a)
        return best.sum(axis=1), mv_best         # (R, C), (R, half, C, 2)

    c_2nxn_raw, mv_h = half_costs(True)
    c_nx2n_raw, mv_v = half_costs(False)
    pen = _PART_PENALTY * (jnp.int32(1) << jnp.clip(qp // 6, 0, 8))
    costs = jnp.stack([sad32, c_2nxn_raw + pen, c_nx2n_raw + pen])
    part = jnp.argmin(costs, axis=0).astype(jnp.int32)   # (R, C)

    # ---- the unified 16-cell MV map realizes every partition
    mv32_cells = jnp.repeat(jnp.repeat(mv32, 2, 0), 2, 1)
    mvh_cells = jnp.repeat(mv_h.reshape(rr * 2, cc, 2), 2, 1)
    mvv_cells = jnp.repeat(
        mv_v.transpose(0, 2, 1, 3).reshape(rr, cc * 2, 2), 2, 0)
    part_cells = jnp.repeat(jnp.repeat(part, 2, 0), 2, 1)[..., None]
    mv_map = jnp.where(part_cells == PART_2Nx2N, mv32_cells,
                       jnp.where(part_cells == PART_2NxN, mvh_cells,
                                 mvv_cells))

    out = _p_residuals_and_recon(
        y, u, v, cur, hplanes, mv_map, part, qp, qpc, pad, search,
        ref_u, ref_v)
    return _deblock_p(out, qp, qpc) if deblock else out


def _deblock_p(out, qp, qpc):
    """Apply spec 8.7.2 to a P recon.  Luma-TB cbf drives the bS-1
    condition (what libavcodec's boundary-strength pass reads); the TU
    grid is TU32 for 2Nx2N CTBs and TU16 inside partitioned ones, so
    per-16-cell cbf selects by partition.  Chroma needs bS 2 (intra) —
    never on P pictures — so only luma is filtered."""
    from vlog_tpu.codecs.hevc import deblock as dbk

    (lv32, lv16, part, mv_map, (ry, ru, rv)) = out
    cbf32 = jnp.any(lv32[0] != 0, axis=(-1, -2))          # (R, C)
    cell_cbf = jnp.repeat(jnp.repeat(cbf32, 2, 0), 2, 1)  # (2R, 2C)
    if lv16 is not None:
        cbf16 = jnp.any(lv16[0] != 0, axis=(-1, -2))      # (2R, 2C)
        part_cells = jnp.repeat(jnp.repeat(part, 2, 0), 2, 1)
        cell_cbf = jnp.where(part_cells == PART_2Nx2N, cell_cbf, cbf16)
    bs_v, bs_h = dbk.p_bs(part, cell_cbf, mv_map)
    dy, du, dv = dbk.deblock_picture(
        ry, ru, rv, qp=qp, qpc=qpc, bs_v=bs_v, bs_h=bs_h, chroma=False)
    return (lv32, lv16, part, mv_map,
            (dy.astype(jnp.uint8), du.astype(jnp.uint8),
             dv.astype(jnp.uint8)))



from vlog_tpu.ops.bitproxy import cost_proxy as _cost_proxy  # noqa: E402


@partial(jax.jit, static_argnums=(3, 6, 7))
def encode_chain_dsp(y, u, v, search, qp_i, qp_p, partitions=False,
                     deblock=False, rc=None):
    """I + P chain: frame 0 intra (row-scan), frames 1.. inter against
    the running reconstruction (lax.scan carry). Inputs (T, H, W) padded
    planes; returns intra levels, per-P levels/MVs, and recons.

    ``qp_i`` is typically qp_p-2: a finer anchor pays off down the whole
    chain (same offset the H.264 chain path ships, +0.3-0.4 dB).
    ``qp_p`` may be a scalar or a (T-1,) per-frame vector — the rate
    controller's fractional working point is realized by dithering
    integer QPs across the chain (rate_control.frame_qps), so it rides
    the scan as a per-step input.

    ``rc`` (optional {"budget": f32 bytes/frame, "alpha": f32 bytes per
    proxy unit}) enables device-side in-chain rate adaptation — the same
    cascade the H.264 ladder runs (parallel/ladder.py): the scan carries
    a byte balance fed by a per-frame bits proxy, and each P frame's QP
    moves trunc(balance/(3*budget)) in [-1, +8] relative to plan.
    alpha==0 disables adjustment.  With ``rc`` the return gains a third
    element {"qp_eff": (T-1,) int32, "cost": (T,) f32} — the entropy
    stage must signal qp_eff."""
    qp_i = jnp.asarray(qp_i, jnp.int32)
    t = y.shape[0]
    qp_p = jnp.broadcast_to(jnp.asarray(qp_p, jnp.int32).reshape(-1),
                            (max(t - 1, 1),))
    (li, lui, lvi), (ry, ru, rv) = encode_frame_dsp(
        y[0], u[0], v[0], qp_i, deblock=deblock)
    if rc is not None:
        budget = jnp.maximum(jnp.asarray(rc["budget"], jnp.float32), 1.0)
        alpha = jnp.asarray(rc["alpha"], jnp.float32)
        cost0 = _cost_proxy(li, lui, lvi)

    def step(carry, frame):
        if rc is None:
            refs = carry
        else:
            refs, bal = carry
        fy, fu, fv, qpf = frame
        if rc is not None:
            adj = jnp.clip(jnp.trunc(bal / (3.0 * budget)),
                           -1.0, 8.0).astype(jnp.int32)
            qpf = jnp.clip(qpf + adj, 10, 51)
        lv32, lv16, part, mv_map, recon = encode_p_frame_dsp(
            fy, fu, fv, *refs, qpf, search=search,
            partitions=partitions, deblock=deblock)
        if rc is None:
            return recon, (lv32, lv16, part, mv_map, recon)
        cost = _cost_proxy(*lv32)
        # anti-windup mirrors parallel/ladder.py: credit bottoms at 3
        # frames of budget, debt tops at what +8 QP can repay; the
        # intra frame's planned overspend is NOT charged (bal starts 0)
        bal = jnp.clip(
            bal + jnp.where(alpha > 0, cost * alpha - budget, 0.0),
            -3.0 * budget, 30.0 * budget)
        return ((recon, bal),
                (lv32, lv16, part, mv_map, recon, qpf, cost))

    if t > 1:
        init = ((ry, ru, rv) if rc is None
                else ((ry, ru, rv), jnp.float32(0.0)))
        _, ys = jax.lax.scan(step, init, (y[1:], u[1:], v[1:], qp_p))
        if rc is None:
            p32, p16, parts, mvs, precons = ys
        else:
            p32, p16, parts, mvs, precons, qp_eff, costs = ys
    else:
        p32 = p16 = parts = mvs = precons = None
        qp_eff = jnp.zeros((0,), jnp.int32)
        costs = jnp.zeros((0,), jnp.float32)
    base = (((li, lui, lvi), (ry, ru, rv)),
            (p32, p16, parts, mvs, precons))
    if rc is None:
        return base
    return base + ({"qp_eff": qp_eff,
                    "cost": jnp.concatenate([cost0[None], costs])},)


@partial(jax.jit, static_argnames=("deblock",))
def encode_frame_dsp(y, u, v, qp, *, deblock=False):
    """Device pass for one padded frame: returns per-CTB quantized levels
    and the bit-exact reconstruction for all three planes (spec-8.7.2
    deblocked when ``deblock`` — intra pictures filter luma AND chroma,
    every TU edge at bS 2)."""
    from vlog_tpu.codecs.hevc import deblock as dbk

    qp = jnp.asarray(qp, jnp.int32)
    qpc = chroma_qp_traced(qp)
    ly, ry = _encode_plane(y, qp, jnp.asarray(T32), 32)
    lu, ru = _encode_plane(u, qpc, jnp.asarray(T16), 16)
    lv, rv = _encode_plane(v, qpc, jnp.asarray(T16), 16)
    if deblock:
        h, w = y.shape
        bs_v, bs_h = dbk.intra_bs(h // 32, w // 32)
        dy, du, dv = dbk.deblock_picture(
            ry, ru, rv, qp=qp, qpc=qpc, bs_v=bs_v, bs_h=bs_h,
            chroma=True)
        ry, ru, rv = (dy.astype(jnp.uint8), du.astype(jnp.uint8),
                      dv.astype(jnp.uint8))
    return (ly, lu, lv), (ry, ru, rv)


@partial(jax.jit, static_argnames=("deblock",))
def encode_batch_dsp(y, u, v, qps, deblock=False):
    return jax.vmap(
        lambda a, b, c, q: encode_frame_dsp(a, b, c, q, deblock=deblock)
    )(y, u, v, qps)
