"""HEVC intra DSP on the device: batched pred/transform/quant/recon.

The XLA program mirrors the H.264 core's shape (codecs/h264/encoder.py):
CTB row 0 is a ``lax.scan`` over columns (its prediction chains through
the left neighbour's top-right reconstructed pixel — a scalar carry),
and every later CTB row is one batched step of a ``lax.scan`` over rows
whose carry is the previous row's reconstructed bottom line.  All three
planes use exact-vertical prediction, so nothing else crosses CTBs.

The transforms are plain (32,32)/(16,16) integer matmuls — exactly what
the MXU wants — with the spec-exact inverse (stage clipping included) so
device recon equals transform.py's numpy reference bit-for-bit, which
test_hevc.py asserts, and equals any conforming decoder's output.

QP is a traced scalar (per-frame rate control can feed it without
recompiling); frames batch via ``vmap``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from vlog_tpu.codecs.hevc.transform import (
    LEVEL_SCALE,
    QUANT_SCALE,
    T16,
    T32,
    _QPC,
)

_QPC_ARR = np.array(_QPC + [0] * 16, dtype=np.int32)  # padded; >=43 computed


def chroma_qp_traced(qp):
    qpi = jnp.clip(qp, 0, 51)
    return jnp.where(qpi < 43, jnp.asarray(_QPC_ARR)[jnp.minimum(qpi, 42)],
                     qpi - 6)


# All arithmetic below is int32 (JAX's default integer width).  Why that
# is safe: 8-bit residuals through the 32-point stages peak below 2^27
# (|m|<=90, 32 taps, stage shifts), quant products peak at ~2^30
# (|coeff|<=~2^15 x 26214), and the one genuinely 33-bit product — the
# spec's dequant ``level*16*levelScale << per`` — is decomposed into an
# int32 product plus a net shift, exactly (proof in _dequant).

def _fwd(res, mat, log2n):
    s1 = log2n - 1
    s2 = log2n + 6
    tmp = (mat @ res + (1 << (s1 - 1))) >> s1
    return (tmp @ mat.T + (1 << (s2 - 1))) >> s2


def _inv(coeff, mat):
    e = (mat.T @ coeff + 64) >> 7
    e = jnp.clip(e, -32768, 32767)
    r = (e @ mat + (1 << 11)) >> 12          # 8-bit: shift 20-8
    return jnp.clip(r, -32768, 32767)


def _quant(coeff, qp, log2n):
    tr_shift = 15 - 8 - log2n
    qbits = 14 + qp // 6 + tr_shift
    f = jnp.asarray(QUANT_SCALE, jnp.int32)[qp % 6]
    # (1<<qbits)*171 >> 9 == 171 << (qbits-9): qbits is always >= 14, and
    # the shifted form peaks at 171<<16 ~ 2^23.5 — the direct product
    # would wrap int32 at qp >= 48 (qbits 24+)
    offset = jnp.int32(171) << (qbits - 9)
    level = (jnp.abs(coeff) * f + offset) >> qbits
    return jnp.sign(coeff) * jnp.clip(level, 0, 32767)


def _dequant(level, qp, log2n):
    """Spec 8.6.3 restated int32-safely.

    d = (level*16*ls << per + 1<<(bd-1)) >> bd  with a = level*16*ls
    (|a| <= 32767*16*72 < 2^26):
      per >= bd: low ``per`` bits of a<<per are zero and the offset
        shifts to < 1, so d = a << (per-bd) exactly;
      per <  bd: divide numerator and denominator by 2^per, so
        d = (a + 1<<(bd-per-1)) >> (bd-per) exactly.
    Arithmetic right-shift floors for negatives in numpy and XLA alike.
    """
    bd = 8 + log2n - 5
    per = qp // 6
    a = level * (jnp.asarray(LEVEL_SCALE, jnp.int32)[qp % 6] * 16)
    d = jnp.where(per >= bd,
                  a << jnp.maximum(per - bd, 0),
                  (a + (jnp.int32(1) << jnp.maximum(bd - per - 1, 0)))
                  >> jnp.maximum(bd - per, 0))
    return jnp.clip(d, -32768, 32767)


def _code_blocks(src, pred, qp, mat, log2n):
    """src/pred: (..., N, N) int32 -> (levels, recon) both int32."""
    res = src - pred
    levels = _quant(_fwd(res, mat, log2n), qp, log2n)
    rec = _inv(_dequant(levels, qp, log2n), mat)
    return levels, jnp.clip(pred + rec, 0, 255)


def _encode_plane(plane, qp, mat, n):
    """One plane (H, W) uint8 -> levels (R, C, N, N) int32, recon (H, W).

    ``n``/``mat`` static; qp traced scalar (already chroma-mapped).
    """
    log2n = n.bit_length() - 1
    h, w = plane.shape
    rows, cols = h // n, w // n
    src = plane.astype(jnp.int32).reshape(rows, n, cols, n).transpose(
        0, 2, 1, 3)                       # (R, C, N, N)

    # ---- CTB row 0: scan over columns, scalar carry ------------------
    def col_step(carry, blk):
        pred = jnp.full((n, n), carry, jnp.int32)
        levels, recon = _code_blocks(blk, pred, qp, mat, log2n)
        return recon[0, n - 1], (levels, recon)

    _, (lev0, rec0) = jax.lax.scan(col_step, jnp.int32(128), src[0])

    # ---- rows 1..R-1: scan over rows, bottom-line carry --------------
    def row_step(bottom, row_blks):          # bottom: (W,), row: (C, N, N)
        pred = jnp.broadcast_to(
            bottom.reshape(cols, 1, n), (cols, n, n))
        levels, recon = _code_blocks(row_blks, pred, qp, mat, log2n)
        return recon[:, n - 1, :].reshape(w), (levels, recon)

    bottom0 = rec0[:, n - 1, :].reshape(w)
    if rows > 1:
        _, (lev_r, rec_r) = jax.lax.scan(row_step, bottom0, src[1:])
        levels = jnp.concatenate([lev0[None], lev_r], axis=0)
        recon = jnp.concatenate([rec0[None], rec_r], axis=0)
    else:
        levels, recon = lev0[None], rec0[None]
    recon_plane = recon.transpose(0, 2, 1, 3).reshape(h, w).astype(jnp.uint8)
    return levels, recon_plane


# ---------------------------------------------------------------- inter
# Integer-MV P frames (see pslice.py): luma MC is a shifted gather from
# the previous reconstruction; chroma lands on {0, 1/2} positions, so
# the HEVC 4-tap filter at fraction 4 yields three derived planes and MC
# selects among them per MV parity. Motion search is the same
# offset-scan SAD pattern as the H.264 core, at 32x32 CTB granularity.

_CTAP = (-4, 36, 36, -4)      # HEVC chroma filter, fraction 4 (table 8-32)


def _chroma_frac_planes(refp):
    """Edge-padded chroma plane -> (copy<<6, H, V, HV) at the uniform
    'predSample' scale (gain 64); final pred = (sel + 32) >> 6."""
    def tap(x, axis):
        out = None
        for k, t in enumerate(_CTAP):
            term = t * jnp.roll(x, 1 - k, axis=axis)
            out = term if out is None else out + term
        return out

    h1 = tap(refp, 1)
    v1 = tap(refp, 0)
    hv = tap(h1, 0) >> 6
    return refp << 6, h1, v1, hv


def _p_ctb_search(cur, refp, *, search, pad, lam=2):
    """Full-search integer ME per 32x32 CTB: (H, W) -> (R, C, 2) MVs
    ((y, x), integer luma pels)."""
    h, w = cur.shape
    rr, cc = h // 32, w // 32
    offsets = [(0, 0)] + [
        (dy, dx) for dy in range(-search, search + 1)
        for dx in range(-search, search + 1) if (dy, dx) != (0, 0)]
    offs = jnp.asarray(offsets, jnp.int32)

    def step(carry, off):
        best_sad, best_mv = carry
        shifted = jax.lax.dynamic_slice(
            refp, (pad + off[0], pad + off[1]), (h, w))
        sad = jnp.abs(cur - shifted).reshape(rr, 32, cc, 32).sum(
            axis=(1, 3))
        sad = sad + lam * 4 * (jnp.abs(off[0]) + jnp.abs(off[1]))
        better = sad < best_sad
        return (jnp.where(better, sad, best_sad),
                jnp.where(better[..., None], off[None, None, :],
                          best_mv)), None

    init = (jnp.full((rr, cc), jnp.iinfo(jnp.int32).max, jnp.int32),
            jnp.zeros((rr, cc, 2), jnp.int32))
    (_, mv), _ = jax.lax.scan(step, init, offs)
    return mv


def _mc_luma_int(refp, mv, *, pad, n=32):
    h = refp.shape[0] - 2 * pad
    w = refp.shape[1] - 2 * pad
    dy = jnp.repeat(jnp.repeat(mv[..., 0], n, 0), n, 1)
    dx = jnp.repeat(jnp.repeat(mv[..., 1], n, 0), n, 1)
    rows = jnp.arange(h)[:, None] + dy + pad
    cols = jnp.arange(w)[None, :] + dx + pad
    return refp[rows, cols]


def _mc_chroma_frac4(ref_c, mv, *, pad):
    """Chroma MC for integer luma MVs: parity picks copy/H/V/HV."""
    refp = jnp.pad(ref_c.astype(jnp.int32), pad, mode="edge")
    planes = jnp.stack(_chroma_frac_planes(refp))   # (4, Hp, Wp)
    hc = ref_c.shape[0]
    wc = ref_c.shape[1]
    dy = jnp.repeat(jnp.repeat(mv[..., 0], 16, 0), 16, 1)
    dx = jnp.repeat(jnp.repeat(mv[..., 1], 16, 0), 16, 1)
    iy, fy = dy >> 1, dy & 1
    ix, fx = dx >> 1, dx & 1
    rows = jnp.arange(hc)[:, None] + iy + pad
    cols = jnp.arange(wc)[None, :] + ix + pad
    sel = fy * 2 + fx                               # 0=copy 1=H 2=V 3=HV
    gathered = planes[:, rows, cols]                # (4, hc, wc)
    ps = jnp.take_along_axis(gathered, sel[None], axis=0)[0]
    return jnp.clip((ps + 32) >> 6, 0, 255)


def encode_p_frame_dsp(y, u, v, ref_y, ref_u, ref_v, qp, *,
                       search: int = 16):
    """One P frame against the previous reconstruction. All CTBs inter
    with integer MVs (pslice.py codes them); returns levels, MVs, recon.
    Everything is ref-relative, so the whole frame is one parallel pass
    — no intra row-scan needed."""
    qp = jnp.asarray(qp, jnp.int32)
    qpc = chroma_qp_traced(qp)
    pad = search + 1
    h, w = y.shape
    rr, cc = h // 32, w // 32
    cur = y.astype(jnp.int32)
    refp = jnp.pad(ref_y.astype(jnp.int32), pad, mode="edge")
    mv = _p_ctb_search(cur, refp, search=search, pad=pad)

    pred_y = _mc_luma_int(refp, mv, pad=pad)
    # chroma pad: mv/2 reach + 2 taps + 4 roll-wrap contamination ring
    cpad = search // 2 + 6
    pred_u = _mc_chroma_frac4(ref_u, mv, pad=cpad)
    pred_v = _mc_chroma_frac4(ref_v, mv, pad=cpad)

    def to_blocks(plane, n):
        r2, c2 = plane.shape[0] // n, plane.shape[1] // n
        return plane.reshape(r2, n, c2, n).transpose(0, 2, 1, 3)

    def from_blocks(blk, n):
        return blk.transpose(0, 2, 1, 3).reshape(blk.shape[0] * n,
                                                 blk.shape[1] * n)

    ly, ry = _code_blocks(to_blocks(cur, 32), to_blocks(pred_y, 32), qp,
                          jnp.asarray(T32), 5)
    lu, ru = _code_blocks(to_blocks(u.astype(jnp.int32), 16),
                          to_blocks(pred_u, 16), qpc, jnp.asarray(T16), 4)
    lv, rv = _code_blocks(to_blocks(v.astype(jnp.int32), 16),
                          to_blocks(pred_v, 16), qpc, jnp.asarray(T16), 4)
    return ((ly, lu, lv), mv,
            (from_blocks(ry, 32).astype(jnp.uint8),
             from_blocks(ru, 16).astype(jnp.uint8),
             from_blocks(rv, 16).astype(jnp.uint8)))


@partial(jax.jit, static_argnums=(3,))
def encode_chain_dsp(y, u, v, search, qp_i, qp_p):
    """I + P chain: frame 0 intra (row-scan), frames 1.. inter against
    the running reconstruction (lax.scan carry). Inputs (T, H, W) padded
    planes; returns intra levels, per-P levels/MVs, and recons.

    ``qp_i`` is typically qp_p-2: a finer anchor pays off down the whole
    chain (same offset the H.264 chain path ships, +0.3-0.4 dB)."""
    qp_i = jnp.asarray(qp_i, jnp.int32)
    qp_p = jnp.asarray(qp_p, jnp.int32)
    (li, lui, lvi), (ry, ru, rv) = encode_frame_dsp(y[0], u[0], v[0], qp_i)

    def step(carry, frame):
        fy, fu, fv = frame
        levels, mv, recon = encode_p_frame_dsp(
            fy, fu, fv, *carry, qp_p, search=search)
        return recon, (levels, mv, recon)

    if y.shape[0] > 1:
        _, (plevels, mvs, precons) = jax.lax.scan(
            step, (ry, ru, rv), (y[1:], u[1:], v[1:]))
    else:
        plevels, mvs, precons = None, None, None
    return ((li, lui, lvi), (ry, ru, rv)), (plevels, mvs, precons)


@partial(jax.jit, static_argnums=())
def encode_frame_dsp(y, u, v, qp):
    """Device pass for one padded frame: returns per-CTB quantized levels
    and the bit-exact reconstruction for all three planes."""
    qp = jnp.asarray(qp, jnp.int32)
    qpc = chroma_qp_traced(qp)
    ly, ry = _encode_plane(y, qp, jnp.asarray(T32), 32)
    lu, ru = _encode_plane(u, qpc, jnp.asarray(T16), 16)
    lv, rv = _encode_plane(v, qpc, jnp.asarray(T16), 16)
    return (ly, lu, lv), (ry, ru, rv)


encode_batch_dsp = jax.jit(jax.vmap(encode_frame_dsp, in_axes=(0, 0, 0, 0)))
