"""HEVC intra DSP on the device: batched pred/transform/quant/recon.

The XLA program mirrors the H.264 core's shape (codecs/h264/encoder.py):
CTB row 0 is a ``lax.scan`` over columns (its prediction chains through
the left neighbour's top-right reconstructed pixel — a scalar carry),
and every later CTB row is one batched step of a ``lax.scan`` over rows
whose carry is the previous row's reconstructed bottom line.  All three
planes use exact-vertical prediction, so nothing else crosses CTBs.

The transforms are plain (32,32)/(16,16) integer matmuls — exactly what
the MXU wants — with the spec-exact inverse (stage clipping included) so
device recon equals transform.py's numpy reference bit-for-bit, which
test_hevc.py asserts, and equals any conforming decoder's output.

QP is a traced scalar (per-frame rate control can feed it without
recompiling); frames batch via ``vmap``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from vlog_tpu.codecs.hevc.transform import (
    LEVEL_SCALE,
    QUANT_SCALE,
    T16,
    T32,
    _QPC,
)

_QPC_ARR = np.array(_QPC + [0] * 16, dtype=np.int32)  # padded; >=43 computed


def chroma_qp_traced(qp):
    qpi = jnp.clip(qp, 0, 51)
    return jnp.where(qpi < 43, jnp.asarray(_QPC_ARR)[jnp.minimum(qpi, 42)],
                     qpi - 6)


# All arithmetic below is int32 (JAX's default integer width).  Why that
# is safe: 8-bit residuals through the 32-point stages peak below 2^27
# (|m|<=90, 32 taps, stage shifts), quant products peak at ~2^30
# (|coeff|<=~2^15 x 26214), and the one genuinely 33-bit product — the
# spec's dequant ``level*16*levelScale << per`` — is decomposed into an
# int32 product plus a net shift, exactly (proof in _dequant).

def _fwd(res, mat, log2n):
    s1 = log2n - 1
    s2 = log2n + 6
    tmp = (mat @ res + (1 << (s1 - 1))) >> s1
    return (tmp @ mat.T + (1 << (s2 - 1))) >> s2


def _inv(coeff, mat):
    e = (mat.T @ coeff + 64) >> 7
    e = jnp.clip(e, -32768, 32767)
    r = (e @ mat + (1 << 11)) >> 12          # 8-bit: shift 20-8
    return jnp.clip(r, -32768, 32767)


def _quant(coeff, qp, log2n):
    tr_shift = 15 - 8 - log2n
    qbits = 14 + qp // 6 + tr_shift
    f = jnp.asarray(QUANT_SCALE, jnp.int32)[qp % 6]
    # (1<<qbits)*171 >> 9 == 171 << (qbits-9): qbits is always >= 14, and
    # the shifted form peaks at 171<<16 ~ 2^23.5 — the direct product
    # would wrap int32 at qp >= 48 (qbits 24+)
    offset = jnp.int32(171) << (qbits - 9)
    level = (jnp.abs(coeff) * f + offset) >> qbits
    return jnp.sign(coeff) * jnp.clip(level, 0, 32767)


def _dequant(level, qp, log2n):
    """Spec 8.6.3 restated int32-safely.

    d = (level*16*ls << per + 1<<(bd-1)) >> bd  with a = level*16*ls
    (|a| <= 32767*16*72 < 2^26):
      per >= bd: low ``per`` bits of a<<per are zero and the offset
        shifts to < 1, so d = a << (per-bd) exactly;
      per <  bd: divide numerator and denominator by 2^per, so
        d = (a + 1<<(bd-per-1)) >> (bd-per) exactly.
    Arithmetic right-shift floors for negatives in numpy and XLA alike.
    """
    bd = 8 + log2n - 5
    per = qp // 6
    a = level * (jnp.asarray(LEVEL_SCALE, jnp.int32)[qp % 6] * 16)
    d = jnp.where(per >= bd,
                  a << jnp.maximum(per - bd, 0),
                  (a + (jnp.int32(1) << jnp.maximum(bd - per - 1, 0)))
                  >> jnp.maximum(bd - per, 0))
    return jnp.clip(d, -32768, 32767)


def _code_blocks(src, pred, qp, mat, log2n):
    """src/pred: (..., N, N) int32 -> (levels, recon) both int32."""
    res = src - pred
    levels = _quant(_fwd(res, mat, log2n), qp, log2n)
    rec = _inv(_dequant(levels, qp, log2n), mat)
    return levels, jnp.clip(pred + rec, 0, 255)


def _encode_plane(plane, qp, mat, n):
    """One plane (H, W) uint8 -> levels (R, C, N, N) int32, recon (H, W).

    ``n``/``mat`` static; qp traced scalar (already chroma-mapped).
    """
    log2n = n.bit_length() - 1
    h, w = plane.shape
    rows, cols = h // n, w // n
    src = plane.astype(jnp.int32).reshape(rows, n, cols, n).transpose(
        0, 2, 1, 3)                       # (R, C, N, N)

    # ---- CTB row 0: scan over columns, scalar carry ------------------
    def col_step(carry, blk):
        pred = jnp.full((n, n), carry, jnp.int32)
        levels, recon = _code_blocks(blk, pred, qp, mat, log2n)
        return recon[0, n - 1], (levels, recon)

    _, (lev0, rec0) = jax.lax.scan(col_step, jnp.int32(128), src[0])

    # ---- rows 1..R-1: scan over rows, bottom-line carry --------------
    def row_step(bottom, row_blks):          # bottom: (W,), row: (C, N, N)
        pred = jnp.broadcast_to(
            bottom.reshape(cols, 1, n), (cols, n, n))
        levels, recon = _code_blocks(row_blks, pred, qp, mat, log2n)
        return recon[:, n - 1, :].reshape(w), (levels, recon)

    bottom0 = rec0[:, n - 1, :].reshape(w)
    if rows > 1:
        _, (lev_r, rec_r) = jax.lax.scan(row_step, bottom0, src[1:])
        levels = jnp.concatenate([lev0[None], lev_r], axis=0)
        recon = jnp.concatenate([rec0[None], rec_r], axis=0)
    else:
        levels, recon = lev0[None], rec0[None]
    recon_plane = recon.transpose(0, 2, 1, 3).reshape(h, w).astype(jnp.uint8)
    return levels, recon_plane


@partial(jax.jit, static_argnums=())
def encode_frame_dsp(y, u, v, qp):
    """Device pass for one padded frame: returns per-CTB quantized levels
    and the bit-exact reconstruction for all three planes."""
    qp = jnp.asarray(qp, jnp.int32)
    qpc = chroma_qp_traced(qp)
    ly, ry = _encode_plane(y, qp, jnp.asarray(T32), 32)
    lu, ru = _encode_plane(u, qpc, jnp.asarray(T16), 16)
    lv, rv = _encode_plane(v, qpc, jnp.asarray(T16), 16)
    return (ly, lu, lv), (ry, ru, rv)


encode_batch_dsp = jax.jit(jax.vmap(encode_frame_dsp, in_axes=(0, 0, 0, 0)))
