"""HEVC slice-data writer: CTU/CU/TU syntax over the CABAC engine.

Stream shape (see syntax.py): every CTB is one 32x32 intra CU, part
2Nx2N, luma mode 26 (exact vertical), chroma DM, one 32x32 luma TU +
two 16x16 chroma TUs, SAO/deblocking off.  What remains per CTU is:
part_mode, the MPM-coded luma mode, the chroma DM bin, three cbf bits,
up to three residual_coding() blocks, and the end_of_slice terminate
bin (H.265 7.3.8.2-7.3.8.11).

Why mode 26 everywhere: with 32x32 TBs the spec applies *no* intra
boundary filtering and exact-vertical reads only the top reference
row, so reconstruction depends on the row above alone — that is what
lets encoder.py vectorize whole CTB rows on the TPU the same way the
H.264 core does (codecs/h264/encoder.py module docstring).  The MPM
derivation below exploits the same shape: the above neighbour is
always outside the current CTB (PUs are CTB-sized), so
candIntraPredModeB is always INTRA_DC (H.265 8.4.2).
"""

from __future__ import annotations

import numpy as np

from vlog_tpu.codecs.hevc.cabac import CabacEncoder
from vlog_tpu.codecs.hevc.residual import write_residual
from vlog_tpu.codecs.hevc.tables import CTX_OFF

_PART = CTX_OFF["PART_MODE"][0]
_PREV = CTX_OFF["PREV_INTRA_LUMA"][0]
_CHROMA = CTX_OFF["INTRA_CHROMA_PRED"][0]
_CBF_LUMA = CTX_OFF["CBF_LUMA"][0]
_CBF_CHROMA = CTX_OFF["CBF_CB_CR"][0]

MODE_VERT = 26


def mpm_bins(col: int) -> tuple[int, int]:
    """(prev_intra_luma_pred_flag, mpm_idx) encoding luma mode 26.

    H.265 8.4.2 with our shape: candB = DC always (above PU leaves the
    CTB); candA = DC at column 0 (left unavailable) else 26.
      col 0:  A==B==DC (<2)  -> list {planar, DC, 26} -> mpm_idx 2
      col>0:  A=26, B=DC     -> list {26, DC, planar} -> mpm_idx 0
    """
    return (1, 2) if col == 0 else (1, 0)


class SliceWriter:
    """Accumulates one I-slice's CABAC payload CTU by CTU."""

    def __init__(self, slice_qp: int) -> None:
        self.c = CabacEncoder(slice_qp)

    def write_ctu(
        self,
        col: int,
        luma_levels: np.ndarray | None,
        cb_levels: np.ndarray | None,
        cr_levels: np.ndarray | None,
        *,
        last_in_slice: bool,
    ) -> None:
        """One CTB: 32x32 intra CU.  ``*_levels`` are quantized
        coefficient arrays in raster order (32x32 luma, 16x16 chroma),
        or None / all-zero for cbf=0."""
        c = self.c

        def has(levels):
            return levels is not None and np.any(levels)

        # coding_quadtree: CTB==MinCb -> no split_cu_flag
        # coding_unit: I slice -> no transquant bypass/skip/pred_mode
        c.encode_bin(_PART, 1)                      # part_mode = 2Nx2N
        prev_flag, mpm_idx = mpm_bins(col)
        c.encode_bin(_PREV, prev_flag)
        if mpm_idx == 0:
            c.encode_bypass(0)
        else:                                       # TR cMax=2
            c.encode_bypass(1)
            c.encode_bypass(mpm_idx - 1)
        c.encode_bin(_CHROMA, 0)                    # chroma mode = DM

        # transform_tree depth 0 (split inferred 0, MaxTrafoDepth=0)
        cbf_cb, cbf_cr, cbf_luma = has(cb_levels), has(cr_levels), has(luma_levels)
        c.encode_bin(_CBF_CHROMA, int(cbf_cb))
        c.encode_bin(_CBF_CHROMA, int(cbf_cr))
        c.encode_bin(_CBF_LUMA + 1, int(cbf_luma))  # ctx 1: trafoDepth==0
        if cbf_luma:
            write_residual(c, luma_levels, log2_size=5, c_idx=0)
        if cbf_cb:
            write_residual(c, cb_levels, log2_size=4, c_idx=1)
        if cbf_cr:
            write_residual(c, cr_levels, log2_size=4, c_idx=2)

        c.encode_terminate(1 if last_in_slice else 0)

    def payload(self) -> bytes:
        return self.c.getvalue()
