"""HEVC all-intra frame encoder — numpy reference implementation.

Pipeline per 32x32 CTB: exact-vertical (mode 26) prediction, forward
transform, quantization, spec-exact dequant + inverse transform, and
reconstruction — so the recon here equals what any conforming decoder
produces (loop filters are disabled; tests/test_hevc.py decodes
our streams with libavcodec and asserts byte equality).

Dependency shape (the point of mode 26 — see slice.py): a CTB row
depends only on the reconstructed bottom line of the row above, except
CTB row 0 where each CTB's prediction is a flat fill of its *left*
neighbour's top-right reconstructed pixel (H.265 8.4.4.2.2 reference
substitution with no row above).  jax_core.py vectorizes rows >0 across
the width and scans row 0, mirroring codecs/h264/encoder.py.

Reference parity: hevc_nvenc / hevc_vaapi encode in the reference's
re-encode worker (worker/hwaccel.py:509, reencode_worker.py); this is
the TPU-platform equivalent those jobs select via codec="h265".
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from vlog_tpu.codecs.hevc import syntax
from vlog_tpu.codecs.hevc.slice import SliceWriter
from vlog_tpu.codecs.hevc.transform import (
    chroma_qp,
    dequantize,
    forward_transform,
    inverse_transform,
    quantize,
)

CTB = 32


def _pad(plane: np.ndarray, block: int) -> np.ndarray:
    h, w = plane.shape
    ph = (h + block - 1) // block * block
    pw = (w + block - 1) // block * block
    if (ph, pw) == (h, w):
        return plane
    return np.pad(plane, ((0, ph - h), (0, pw - w)), mode="edge")


def _code_block(src: np.ndarray, pred: np.ndarray, qp: int
                ) -> tuple[np.ndarray | None, np.ndarray]:
    """One TB: returns (levels or None, recon)."""
    res = src.astype(np.int32) - pred.astype(np.int32)
    levels = quantize(forward_transform(res), qp)
    if not np.any(levels):
        return None, pred.astype(np.uint8)
    rec = inverse_transform(dequantize(levels, qp))
    return levels, np.clip(pred.astype(np.int32) + rec, 0, 255).astype(
        np.uint8)


@dataclass
class FrameResult:
    nal: syntax.NalUnit
    recon_y: np.ndarray
    recon_u: np.ndarray
    recon_v: np.ndarray


def encode_frame(y: np.ndarray, u: np.ndarray, v: np.ndarray, qp: int
                 ) -> FrameResult:
    """Encode one IDR frame; planes are uint8, true (display) size.

    Returns the slice NAL plus the (padded-size) reconstruction the
    decoder will produce.
    """
    yp = _pad(np.asarray(y, dtype=np.uint8), CTB)
    up = _pad(np.asarray(u, dtype=np.uint8), CTB // 2)
    vp = _pad(np.asarray(v, dtype=np.uint8), CTB // 2)
    h, w = yp.shape
    rows, cols = h // CTB, w // CTB
    qpc = chroma_qp(qp)

    ry = np.zeros_like(yp)
    ru = np.zeros_like(up)
    rv = np.zeros_like(vp)
    sw = SliceWriter(qp)

    for r in range(rows):
        for c in range(cols):
            y0, x0 = r * CTB, c * CTB
            cy0, cx0 = y0 // 2, x0 // 2
            if r == 0:
                # substituted refs: flat fill of the left neighbour's
                # top-right recon pixel (128 at the frame corner)
                pl = int(ry[0, x0 - 1]) if c else 128
                pu_ = int(ru[0, cx0 - 1]) if c else 128
                pv_ = int(rv[0, cx0 - 1]) if c else 128
                pred_y = np.full((CTB, CTB), pl, np.int32)
                pred_u = np.full((16, 16), pu_, np.int32)
                pred_v = np.full((16, 16), pv_, np.int32)
            else:
                pred_y = np.broadcast_to(ry[y0 - 1, x0:x0 + CTB],
                                         (CTB, CTB)).astype(np.int32)
                pred_u = np.broadcast_to(ru[cy0 - 1, cx0:cx0 + 16],
                                         (16, 16)).astype(np.int32)
                pred_v = np.broadcast_to(rv[cy0 - 1, cx0:cx0 + 16],
                                         (16, 16)).astype(np.int32)

            ll, rec = _code_block(yp[y0:y0 + CTB, x0:x0 + CTB], pred_y, qp)
            ry[y0:y0 + CTB, x0:x0 + CTB] = rec
            lu, rec = _code_block(up[cy0:cy0 + 16, cx0:cx0 + 16], pred_u,
                                  qpc)
            ru[cy0:cy0 + 16, cx0:cx0 + 16] = rec
            lvv, rec = _code_block(vp[cy0:cy0 + 16, cx0:cx0 + 16], pred_v,
                                   qpc)
            rv[cy0:cy0 + 16, cx0:cx0 + 16] = rec

            sw.write_ctu(c, ll, lu, lvv,
                         last_in_slice=(r == rows - 1 and c == cols - 1))

    return FrameResult(syntax.idr_nal(qp, sw.payload()), ry, ru, rv)


def encode_stream(frames, width: int, height: int, qp: int
                  ) -> tuple[bytes, list]:
    """All-IDR annex-B stream for an iterable of (y, u, v) frames."""
    nals = [syntax.write_vps(syntax.level_idc_for(width, height)),
            syntax.write_sps(width, height), syntax.write_pps()]
    recons = []
    for (fy, fu, fv) in frames:
        fr = encode_frame(fy, fu, fv, qp)
        nals.append(fr.nal)
        recons.append((fr.recon_y, fr.recon_u, fr.recon_v))
    return syntax.annexb(nals), recons
