"""Frame sources: uniform GOP-batch iteration over supported containers.

The decode stage of the pipeline. The reference leaves decode to ffmpeg
inside each transcode subprocess (worker/transcoder.py:1006 — every rung
re-decodes the source); here the source is decoded ONCE per frame batch
and every rung is scaled/encoded from that single in-memory copy.

Supported inputs: Y4M (raw 4:2:0) and progressive MP4 with our H.264
intra envelope (see codecs/h264/decoder.py). Anything else raises
UnsupportedSource, the analog of the reference's ffprobe-failure path
(transcoder.py:706-758).
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator

import numpy as np

from vlog_tpu.codecs.h264.decoder import H264Decoder, UnsupportedStream
from vlog_tpu.media import mp4 as mp4mod
from vlog_tpu.media import y4m
from vlog_tpu.media.probe import VideoInfo, get_video_info, sniff_container


class UnsupportedSource(ValueError):
    """Container/codec outside the first-party decode envelope."""


class FrameSource:
    """Iterate (y, u, v) uint8 numpy batches of up to ``batch`` frames."""

    info: VideoInfo
    frame_count: int
    fps_num: int
    fps_den: int
    # True: start_frame addressing is frame-exact and frame_count is
    # authoritative (our containers). False: libav fallback — counts are
    # container estimates and mid-stream starts are keyframe-coarse, so
    # the backend disables segment resume.
    exact_seek: bool = True

    def read_batches(self, batch: int, start_frame: int = 0
                     ) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray]]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Y4mFrameSource(FrameSource):
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.info = get_video_info(path)
        self._reader = y4m.Y4mReader(path)
        self.frame_count = self._reader.info.frame_count
        self.fps_num = self._reader.info.fps_num
        self.fps_den = self._reader.info.fps_den

    def read_batches(self, batch: int, start_frame: int = 0):
        n = self.frame_count
        i = start_frame
        while i < n:
            count = min(batch, n - i)
            ys, us, vs = [], [], []
            for j in range(i, i + count):
                y, u, v = self._reader.read_frame(j)
                ys.append(y)
                us.append(u)
                vs.append(v)
            yield np.stack(ys), np.stack(us), np.stack(vs)
            i += count

    def close(self):
        self._reader.close()


class Mp4H264FrameSource(FrameSource):
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.info = get_video_info(path)
        movie = mp4mod.parse_mp4(path)
        track = movie.video
        if track is None:
            raise UnsupportedSource(f"{path}: no video track")
        if track.codec != "h264":
            raise UnsupportedSource(
                f"{path}: codec {track.codec!r} has no first-party decoder")
        self._track = track
        self._reader = mp4mod.SampleReader(path, track)
        self._decoder = H264Decoder(avcc_config=track.codec_config)
        self.frame_count = track.samples.count
        fps = track.fps or 30.0
        self.fps_num, self.fps_den = y4m.fps_to_fraction(fps)

    def read_batches(self, batch: int, start_frame: int = 0):
        n = self.frame_count
        i = start_frame
        while i < n:
            count = min(batch, n - i)
            samples = self._reader.read_range(i, count)
            try:
                frames = self._decoder.decode_samples(samples)
            except UnsupportedStream as exc:
                raise UnsupportedSource(f"{self.path}: {exc}") from exc
            if len(frames) != count:
                raise UnsupportedSource(
                    f"{self.path}: sample {i}+ produced no frame")
            yield (np.stack([f.y for f in frames]),
                   np.stack([f.u for f in frames]),
                   np.stack([f.v for f in frames]))
            i += count

    def close(self):
        self._reader.close()


class LibavFrameSource(FrameSource):
    """Foreign-upload decode through the system libav shim.

    The ingest half of the reference's "anything ffmpeg decodes" contract
    (transcoder.py:706-758): CABAC/B-frame H.264, HEVC, VP9, MKV/MOV/...
    decode into the same (y, u, v) batch stream the first-party sources
    produce. Encode stays first-party; ``exact_seek`` is False (container
    frame counts are estimates; mid-stream starts are keyframe-coarse).
    """

    exact_seek = False

    def __init__(self, path: str | Path):
        import ctypes

        from vlog_tpu.native.avbuild import VtAvInfo, get_av_lib

        lib = get_av_lib()
        if lib is None:
            raise UnsupportedSource(
                f"{path}: outside the first-party decode envelope and the "
                "libav ingest shim is unavailable")
        self._lib = lib
        self.path = Path(path)
        self._avinfo = VtAvInfo()
        self._handle = lib.vt_av_open(str(path).encode(),
                                      ctypes.byref(self._avinfo))
        if not self._handle:
            raise UnsupportedSource(f"{path}: libav cannot open this input")
        ai = self._avinfo
        if ai.width <= 0 or ai.height <= 0:
            self.close()
            raise UnsupportedSource(f"{path}: no decodable video stream")
        if ai.width % 2 or ai.height % 2:
            # Reject at PROBE time, not mid-transcode: 4:2:0 needs even
            # dimensions end to end.
            self.close()
            raise UnsupportedSource(
                f"{path}: odd frame dimensions "
                f"{ai.width}x{ai.height} unsupported")
        fps = ai.fps if ai.fps > 0 else 30.0
        from vlog_tpu.media.y4m import fps_to_fraction

        self.fps_num, self.fps_den = fps_to_fraction(fps)
        n = int(ai.nb_frames) if ai.nb_frames > 0 else int(
            round(ai.duration * fps))
        self.frame_count = max(n, 1)
        self.info = VideoInfo(
            container="libav", path=str(path),
            duration_s=float(ai.duration), width=int(ai.width),
            height=int(ai.height), fps=round(fps, 3),
            frame_count=self.frame_count,
            video_codec=ai.vcodec.decode(errors="replace"),
            audio_codec=(ai.acodec.decode(errors="replace")
                         if ai.has_audio else None),
            size_bytes=self.path.stat().st_size,
        )
        self._pos = 0

    def _seek_to(self, start_frame: int) -> None:
        """Seek to the prior keyframe, then decode-and-discard forward
        until the stream's PTS reaches the target time (bounded)."""
        import ctypes

        fps = self.fps_num / self.fps_den
        target_t = start_frame / fps
        if self._lib.vt_av_seek(self._handle, target_t) != 0 \
                and start_frame < self._pos:
            raise UnsupportedSource(f"{self.path}: seek failed")
        h, w = self.info.height, self.info.width
        fsz = w * h * 3 // 2
        buf = np.empty(fsz, np.uint8)
        pts = ctypes.c_double(-1.0)
        # budget bounds pathological streams (e.g. keyframe-free)
        for _ in range(2000):
            # Peek one frame; stop once its pts reaches target (within
            # half a frame). The peeked frame is the NEXT one yielded —
            # stash it.
            got = self._lib.vt_av_read_pts(
                self._handle,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.byref(pts), 1)
            if got <= 0:
                self._stash = None
                break
            if pts.value < 0 or pts.value >= target_t - 0.5 / fps:
                self._stash = buf.copy()
                break
        else:
            self._stash = None
        self._pos = start_frame

    def read_batches(self, batch: int, start_frame: int = 0):
        import ctypes

        if start_frame != self._pos:
            self._seek_to(start_frame)
        h, w = self.info.height, self.info.width
        fsz = w * h * 3 // 2

        def emit(frames: np.ndarray):
            n = frames.shape[0]
            ys = frames[:, : h * w].reshape(n, h, w).copy()
            us = frames[:, h * w: h * w + (h // 2) * (w // 2)].reshape(
                n, h // 2, w // 2).copy()
            vs = frames[:, h * w + (h // 2) * (w // 2):].reshape(
                n, h // 2, w // 2).copy()
            return ys, us, vs

        stash = getattr(self, "_stash", None)
        self._stash = None
        if stash is not None:
            self._pos += 1
            yield emit(stash[None, :])
        buf = np.empty(batch * fsz, np.uint8)
        while True:
            got = self._lib.vt_av_read(
                self._handle,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                batch)
            if got < 0:
                raise UnsupportedSource(f"{self.path}: libav decode error")
            if got == 0:
                return
            self._pos += int(got)
            yield emit(buf[: got * fsz].reshape(int(got), fsz))
            if got < batch:
                return

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.vt_av_close(self._handle)
            self._handle = None


def _trial_decode(src: Mp4H264FrameSource) -> None:
    """Decode the first sample so envelope violations (CABAC at the PPS,
    foreign slice features at the first slice) surface at OPEN time,
    letting open_source fall back to libav before any work happens."""
    samples = src._reader.read_range(0, 1)
    if samples:
        from vlog_tpu.codecs.h264.decoder import H264Decoder

        probe_dec = H264Decoder(avcc_config=src._track.codec_config)
        probe_dec.decode_sample_levels(samples[0])


def open_source(path: str | Path) -> FrameSource:
    """Sniff the container and return the right FrameSource.

    First-party decoders are preferred (frame-exact, resume-capable);
    anything outside their envelope falls back to the libav ingest shim
    when it is available.
    """
    try:
        kind = sniff_container(path)
    except Exception:
        kind = "libav"
    if kind == "y4m":
        return Y4mFrameSource(path)
    if kind == "mp4":
        from vlog_tpu.codecs.h264.decoder import DecodeError

        src = None
        try:
            src = Mp4H264FrameSource(path)
            _trial_decode(src)
            return src
        except (UnsupportedSource, UnsupportedStream, DecodeError,
                ValueError):
            # outside the first-party envelope; try libav — without
            # leaking the half-open first-party reader
            if src is not None:
                src.close()
    return LibavFrameSource(path)
