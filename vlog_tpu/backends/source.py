"""Frame sources: uniform GOP-batch iteration over supported containers.

The decode stage of the pipeline. The reference leaves decode to ffmpeg
inside each transcode subprocess (worker/transcoder.py:1006 — every rung
re-decodes the source); here the source is decoded ONCE per frame batch
and every rung is scaled/encoded from that single in-memory copy.

Supported inputs: Y4M (raw 4:2:0) and progressive MP4 with our H.264
intra envelope (see codecs/h264/decoder.py). Anything else raises
UnsupportedSource, the analog of the reference's ffprobe-failure path
(transcoder.py:706-758).
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator

import numpy as np

from vlog_tpu.codecs.h264.decoder import H264Decoder, UnsupportedStream
from vlog_tpu.media import mp4 as mp4mod
from vlog_tpu.media import y4m
from vlog_tpu.media.probe import VideoInfo, get_video_info, sniff_container


class UnsupportedSource(ValueError):
    """Container/codec outside the first-party decode envelope."""


class FrameSource:
    """Iterate (y, u, v) uint8 numpy batches of up to ``batch`` frames."""

    info: VideoInfo
    frame_count: int
    fps_num: int
    fps_den: int

    def read_batches(self, batch: int, start_frame: int = 0
                     ) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray]]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Y4mFrameSource(FrameSource):
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.info = get_video_info(path)
        self._reader = y4m.Y4mReader(path)
        self.frame_count = self._reader.info.frame_count
        self.fps_num = self._reader.info.fps_num
        self.fps_den = self._reader.info.fps_den

    def read_batches(self, batch: int, start_frame: int = 0):
        n = self.frame_count
        i = start_frame
        while i < n:
            count = min(batch, n - i)
            ys, us, vs = [], [], []
            for j in range(i, i + count):
                y, u, v = self._reader.read_frame(j)
                ys.append(y)
                us.append(u)
                vs.append(v)
            yield np.stack(ys), np.stack(us), np.stack(vs)
            i += count

    def close(self):
        self._reader.close()


class Mp4H264FrameSource(FrameSource):
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.info = get_video_info(path)
        movie = mp4mod.parse_mp4(path)
        track = movie.video
        if track is None:
            raise UnsupportedSource(f"{path}: no video track")
        if track.codec != "h264":
            raise UnsupportedSource(
                f"{path}: codec {track.codec!r} has no first-party decoder")
        self._track = track
        self._reader = mp4mod.SampleReader(path, track)
        self._decoder = H264Decoder(avcc_config=track.codec_config)
        self.frame_count = track.samples.count
        fps = track.fps or 30.0
        self.fps_num, self.fps_den = y4m.fps_to_fraction(fps)

    def read_batches(self, batch: int, start_frame: int = 0):
        n = self.frame_count
        i = start_frame
        while i < n:
            count = min(batch, n - i)
            samples = self._reader.read_range(i, count)
            try:
                frames = self._decoder.decode_samples(samples)
            except UnsupportedStream as exc:
                raise UnsupportedSource(f"{self.path}: {exc}") from exc
            if len(frames) != count:
                raise UnsupportedSource(
                    f"{self.path}: sample {i}+ produced no frame")
            yield (np.stack([f.y for f in frames]),
                   np.stack([f.u for f in frames]),
                   np.stack([f.v for f in frames]))
            i += count

    def close(self):
        self._reader.close()


def open_source(path: str | Path) -> FrameSource:
    """Sniff the container and return the right FrameSource."""
    kind = sniff_container(path)
    if kind == "y4m":
        return Y4mFrameSource(path)
    if kind == "mp4":
        return Mp4H264FrameSource(path)
    raise UnsupportedSource(f"{path}: unsupported container {kind!r}")
