"""The JAX/XLA ladder backend — decode once, emit every rung in one pass.

This is the ``device=tpu`` encoder the accelerator boundary selects,
replacing the reference's one-ffmpeg-process-per-rung scheme
(worker/transcoder.py:2528-2559 parallel batches; worker/hwaccel.py:647
command builder). Pipeline per frame batch:

  host decode (source.py) -> device: ladder resize (MXU matmuls,
  ops/resize.py) -> device: per-rung intra encode (encoder.encode_gop)
  -> host: CAVLC entropy + fMP4 packaging (threads, overlapped with the
  next batch's device work)

Segments are cut at whole-second boundaries (all frames are IDR-capable,
so any boundary is a valid CMAF chunk start). Output layout per rung:

    {out}/{rung}/init.mp4
    {out}/{rung}/segment_%05d.m4s
    {out}/{rung}/playlist.m3u8

matching what media.hls.dash_manifest expects and what the reference's
validate_hls_playlist checks (transcoder.py:816-947).

Resume: an interrupted run restarts at the first segment index any rung
is missing (quality_progress semantics, reference database.py:209-248) —
GOP-chunked execution keeps checkpoint granularity even though a single
XLA dispatch is not interruptible (SURVEY.md section 7 hard part #3).
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from pathlib import Path

import numpy as np

from vlog_tpu import config
from vlog_tpu.backends.base import (
    Capabilities,
    ExecutionPlan,
    PlannedRung,
    ProgressFn,
    RungResult,
    RunResult,
    plan_rung_geometry,
    register_backend,
)
from vlog_tpu.backends.rate_control import RateController
from vlog_tpu.backends.source import open_source
from vlog_tpu.codecs.h264.api import H264Encoder
from vlog_tpu.codecs.jpeg import encode_jpeg_yuv420
from vlog_tpu.media import hls
from vlog_tpu.media.fmp4 import Sample, TrackConfig, avc1_sample_entry, init_segment, media_segment
from vlog_tpu.media.probe import VideoInfo
from vlog_tpu.utils.fsio import atomic_write_bytes, atomic_write_text, prepare_init_segment
from vlog_tpu.ops.colorspace import yuv420_to_rgb
from vlog_tpu.ops.resize import resize_yuv420


def _enable_persistent_compile_cache() -> None:
    """Back-compat alias: the cache logic moved to
    parallel/compile_cache.py so all three codec backends and the ASR
    engine share one arming point (and the compile-seconds meter)."""
    from vlog_tpu.parallel.compile_cache import ensure_compile_cache

    ensure_compile_cache()


class JaxBackend:
    """Runs the one-pass ladder on whatever devices JAX exposes."""

    name = "jax"

    def detect(self) -> Capabilities:
        import jax

        _enable_persistent_compile_cache()

        devices = jax.devices()
        kind = devices[0].platform if devices else "cpu"
        if kind not in ("cpu", "gpu", "tpu"):
            # experimental platform names (e.g. the axon TPU tunnel) still
            # expose TPU-class devices
            kind = "tpu" if "tpu" in str(devices[0]).lower() else kind
        mem = None
        try:
            stats = devices[0].memory_stats()
            if stats:
                mem = stats.get("bytes_limit")
        except Exception:
            pass
        return Capabilities(
            backend=self.name,
            device_kind=kind,
            device_count=len(devices),
            codecs=("h264",),
            decode_codecs=("h264", "raw"),
            max_parallel_jobs=1,
            memory_bytes=mem,
            details={"devices": [str(d) for d in devices]},
        )

    # ------------------------------------------------------------------
    def plan(self, source: VideoInfo, rungs=None, out_dir: Path | str = ".",
             **opts) -> ExecutionPlan:
        if rungs is None:
            rungs = config.ladder_for_source(source.height)
        planned = tuple(
            plan_rung_geometry(source.width, source.height, r) for r in rungs
        )
        codec = opts.get("codec", "h264")
        if codec == "hevc":
            codec = "h265"
        if codec in ("h265", "av1"):
            from dataclasses import replace

            planned = tuple(replace(r, codec=codec) for r in planned)
        elif codec != "h264":
            raise ValueError(f"unknown codec {codec!r}")
        from vlog_tpu.media.y4m import fps_to_fraction

        fps_num, fps_den = fps_to_fraction(source.fps or 30.0)
        seg_s = opts.get("segment_duration_s", config.SEGMENT_DURATION_S)
        fps = fps_num / fps_den
        frames_per_seg = max(1, round(seg_s * fps))
        gop_len = 1
        gop_mode = opts.get("gop_mode", config.GOP_MODE)
        if gop_mode == "p":
            # Pick the divisor of frames-per-segment closest to GOP_LEN
            # (segments must start on chain boundaries = IDRs). Divisors
            # somewhat above the target are allowed so awkward frame
            # rates (e.g. 25fps/1s segments) still get long chains.
            cap = min(frames_per_seg, 2 * config.GOP_LEN)
            divisors = [d for d in range(1, cap + 1)
                        if frames_per_seg % d == 0]
            gop_len = min(divisors,
                          key=lambda d: (abs(d - config.GOP_LEN), -d))
            if gop_len <= max(2, config.GOP_LEN // 3):
                import logging

                logging.getLogger("vlog_tpu.backend").warning(
                    "gop_mode=p degraded to %d-frame chains "
                    "(frames/segment=%d has no divisor near GOP_LEN=%d); "
                    "bitrate efficiency suffers — consider adjusting "
                    "VLOG_SEGMENT_DURATION", gop_len, frames_per_seg,
                    config.GOP_LEN)
        return ExecutionPlan(
            source=source,
            rungs=planned,
            out_dir=Path(out_dir),
            segment_duration_s=seg_s,
            frame_batch=opts.get("frame_batch", config.TPU_FRAME_BATCH),
            fps_num=fps_num,
            fps_den=fps_den,
            total_frames=source.frame_count,
            thumbnail=opts.get("thumbnail", True),
            gop_len=gop_len,
            streaming_format=opts.get("streaming_format",
                                      config.STREAMING_FORMAT),
        )

    # ------------------------------------------------------------------
    def run(self, plan: ExecutionPlan, progress_cb: ProgressFn | None = None,
            *, resume: bool = True) -> RunResult:
        from vlog_tpu.utils import failpoints

        failpoints.hit("backend.encode")    # chaos: simulated device fault
        _enable_persistent_compile_cache()
        t0 = time.monotonic()
        if any(r.codec == "h265" for r in plan.rungs):
            from vlog_tpu.backends.hevc_path import run_hevc

            return run_hevc(self, plan, progress_cb, resume, t0)
        if any(r.codec == "av1" for r in plan.rungs):
            from vlog_tpu.backends.av1_path import run_av1

            return run_av1(self, plan, progress_cb, resume, t0)
        out = plan.out_dir
        out.mkdir(parents=True, exist_ok=True)

        fps = plan.fps_num / plan.fps_den
        frames_per_seg = max(1, round(plan.segment_duration_s * fps))
        timescale = plan.fps_num * 1000
        frame_dur = plan.fps_den * 1000
        # Legacy HLS: MPEG-TS segments with muxed audio, no init/DASH.
        ts_mode = plan.streaming_format == "hls_ts"
        seg_ext = "ts" if ts_mode else "m4s"

        encoders: dict[str, H264Encoder] = {}
        tracks: dict[str, TrackConfig] = {}
        seg_counts: dict[str, int] = {}
        seg_durs: dict[str, list[float]] = {}
        bytes_written: dict[str, int] = {}
        psnr_acc: dict[str, list[float]] = {}
        init_matched: dict[str, bool] = {}
        for rung in plan.rungs:
            # Chain mode runs the in-loop deblocking filter (the DSP and
            # the slice headers' idc must agree — ladder_chain_program
            # gets the same flag below); intra mode leaves it off.
            enc = H264Encoder(width=rung.width, height=rung.height,
                              fps_num=plan.fps_num, fps_den=plan.fps_den,
                              qp=rung.qp, entropy=config.H264_ENTROPY,
                              deblock=(config.H264_DEBLOCK
                                       and plan.gop_len > 1))
            encoders[rung.name] = enc
            tracks[rung.name] = TrackConfig(
                track_id=1, handler="vide", timescale=timescale,
                sample_entry=avc1_sample_entry(rung.width, rung.height,
                                               enc.avcc_config),
                width=rung.width, height=rung.height,
            )
            rdir = out / rung.name
            rdir.mkdir(parents=True, exist_ok=True)
            if not ts_mode:
                init_matched[rung.name] = prepare_init_segment(
                    rdir, init_segment(tracks[rung.name]),
                    config_tag=(f"h264:{config.H264_ENTROPY}"
                                f":deblock={int(enc.deblock)}"
                                f":gop={plan.gop_len}"))
            seg_counts[rung.name] = 0
            seg_durs[rung.name] = []
            bytes_written[rung.name] = 0
            psnr_acc[rung.name] = []

        # --- resume point: first segment index any rung is missing.
        # (TS mode restarts from 0: continuity counters span the whole
        # playlist, so a fresh muxer cannot append mid-stream.)
        src = open_source(plan.source.path)
        total = src.frame_count
        start_segment = 0
        # (any failure between here and the decode loop must not leak
        # the source — see the except below)
        # Foreign (libav) sources have keyframe-coarse seeking only, so
        # mid-stream segment resume would misalign frames: restart clean.
        try:
            return self._run_with_source(
                plan, progress_cb, resume, t0, src, total, out, fps,
                frames_per_seg, timescale, frame_dur, ts_mode, seg_ext,
                encoders, tracks, seg_counts, seg_durs, bytes_written,
                psnr_acc, init_matched)
        except BaseException:
            src.close()
            raise

    def _run_with_source(self, plan, progress_cb, resume, t0, src, total,
                         out, fps, frames_per_seg, timescale, frame_dur,
                         ts_mode, seg_ext, encoders, tracks, seg_counts,
                         seg_durs, bytes_written, psnr_acc,
                         init_matched) -> RunResult:
        # Resume CANDIDATE from the on-disk segment scan. The definitive
        # resume point is fixed below once the dispatch batch size is
        # known: byte-identical resume must land on a batch boundary the
        # rate-control journal can replay (backends/rc_journal.py), so
        # the candidate may be clamped down — or to zero (cold restart,
        # still deterministic) when the journal is missing or from a
        # differently-configured run.
        start_segment = 0
        resume_per_rung: dict[str, list[int]] | None = None
        if resume and not ts_mode and src.exact_seek:
            resume_per_rung = self._scan_resume_candidates(plan, out,
                                                           init_matched)
            start_segment = min(len(d) for d in resume_per_rung.values())
        start_frame = start_segment * frames_per_seg

        pending: dict[str, list[Sample]] = {r.name: [] for r in plan.rungs}
        frames_done = start_frame
        thumb_path = None

        # --- TS-mode segment writer state (muxers persist across
        # segments for playlist-wide continuity counters).
        from vlog_tpu.media.ts import TsMuxer, TsSample

        audio_by_rate = plan.audio_adts or {}
        ts_muxers: dict[str, TsMuxer] = {}
        ts_frame_idx = {r.name: start_frame for r in plan.rungs}
        ts_audio_idx = {r.name: 0 for r in plan.rungs}

        # Exact 90 kHz timestamps: multiply BEFORE dividing, per index —
        # a pre-truncated per-frame tick drifts A/V apart on non-integer
        # rates (23.976 fps / 44.1 kHz) by ~1 s/hour.
        def vpts(idx: int) -> int:
            return idx * 90000 * plan.fps_den // plan.fps_num

        def apts(idx: int, sr: int) -> int:
            return idx * 90000 * 1024 // sr

        def write_segment(rung: PlannedRung, chunk: list[Sample]) -> None:
            name = rung.name
            if not ts_mode:
                self._write_segment(out, rung, tracks[name], seg_counts,
                                    seg_durs, bytes_written, chunk,
                                    timescale)
                return
            audio = audio_by_rate.get(rung.audio_bitrate)
            mux = ts_muxers.get(name)
            if mux is None:
                mux = ts_muxers[name] = TsMuxer(has_video=True,
                                                has_audio=audio is not None)
            i0 = ts_frame_idx[name]
            vsamples = [TsSample(s.data, pts=vpts(i0 + k), is_idr=s.is_sync)
                        for k, s in enumerate(chunk)]
            ts_frame_idx[name] = i0 + len(chunk)
            asamples = []
            if audio is not None:
                frames, sr = audio
                t_end = vpts(ts_frame_idx[name])
                j = ts_audio_idx[name]
                while j < len(frames) and apts(j, sr) < t_end:
                    asamples.append(TsSample(frames[j], pts=apts(j, sr)))
                    j += 1
                ts_audio_idx[name] = j
            data = mux.mux_segment(video=vsamples, audio=asamples or None)
            idx = seg_counts[name]
            path = out / name / f"segment_{idx + 1:05d}.ts"
            atomic_write_bytes(path, data)
            seg_counts[name] = idx + 1
            seg_durs[name].append(sum(s.duration for s in chunk) / timescale)
            bytes_written[name] += len(data)

        # --- the one-pass ladder program: ONE dispatch per GOP batch
        # emits quantized levels for EVERY rung (SURVEY §2d.2); over >1
        # chip the ladder lays out as a 2-D (data × rung) grid — frames
        # shard the data axis, rung columns split the ladder — resolved
        # by grid_for_run() (slot submesh devices under the scheduler,
        # every visible device otherwise; VLOG_TPU_MESH picks the
        # shape). All batch math keys off the grid's DATA-axis width
        # only, so every shape whose data width divides the frame batch
        # stages identical batches — the cross-shape byte-identity
        # contract tests/test_mesh_equivalence.py asserts.
        import jax

        from vlog_tpu.parallel.ladder import (ladder_chain_grid,
                                              ladder_encode_grid)
        from vlog_tpu.parallel.scheduler import (grid_for_run,
                                                 host_pool_for_run)

        src_h, src_w = plan.source.height, plan.source.width
        rungs_spec = tuple((r.name, r.height, r.width, r.qp)
                           for r in plan.rungs)
        chain_mode = plan.gop_len > 1
        if chain_mode:
            # Chains are independent mini-GOPs, so the grid shards the
            # chain axis; enough chains per dispatch to honor frame_batch
            # (amortizing host overhead), rounded to the data-axis width
            # (NOT the device count: a 2x4 grid pads a small batch to 2
            # chains where the 1-D mesh padded it to 8).
            clen = plan.gop_len
            hint = max(1, -(-plan.frame_batch // clen))
            grid = grid_for_run(rungs_spec, batch_hint=hint)
            prog = ladder_chain_grid(
                rungs_spec, src_h, src_w,
                search=config.MOTION_SEARCH_RADIUS, grid=grid,
                deblock=config.H264_DEBLOCK)
            chains_per = max(prog.data, hint + (-hint) % prog.data)
            batch_n = clen * chains_per
        else:
            grid = grid_for_run(rungs_spec, batch_hint=plan.frame_batch)
            prog = ladder_encode_grid(rungs_spec, src_h, src_w, grid)
            # Fixed staged batch size (single compile; data-divisible).
            batch_n = max(plan.frame_batch, prog.data)
            batch_n += (-batch_n) % prog.data

        # Closed-loop VBR toward each rung's ladder bitrate.
        controllers = {
            r.name: RateController(target_bps=r.video_bitrate, fps=fps,
                                   init_qp=r.qp)
            for r in plan.rungs
        }
        npix = {r.name: r.height * r.width for r in plan.rungs}

        # Stage accounting: decode_wait = blocked on the prefetch fifo;
        # compute_wait = block_until_ready on the dispatch outputs (pure
        # device compute, since dispatch is async); device_pull =
        # np.asarray AFTER readiness (pure d2h transfer — without the
        # split, the pull absorbed the XLA compute and the profile
        # could not distinguish the two, VERDICT r4 weak #3); entropy =
        # host slice coding; package = segment mux + fsync. All five are
        # cumulative BUSY seconds per stage; the executor adds the
        # overlap gauges (pipeline_depth / max_in_flight / host_busy_s /
        # host_wall_s / host_occupancy) on top.
        prof = {"decode_wait_s": 0.0, "compute_wait_s": 0.0,
                "device_pull_s": 0.0, "entropy_s": 0.0, "package_s": 0.0}

        def dispatch(by, bu, bv):
            n_real = by.shape[0]
            if n_real < batch_n:   # tail: replicate last frame, drop later
                reps = batch_n - n_real
                by = np.concatenate([by, np.repeat(by[-1:], reps, axis=0)])
                bu = np.concatenate([bu, np.repeat(bu[-1:], reps, axis=0)])
                bv = np.concatenate([bv, np.repeat(bv[-1:], reps, axis=0)])
            pipe.note_pad_waste(n_real, batch_n)
            if chain_mode:
                chain = lambda p: p.reshape((chains_per, clen) + p.shape[1:])
                by, bu, bv = chain(by), chain(bu), chain(bv)
                # I frames carry the whole chain as its reference: spend
                # ~2 QP more on them than on the P frames they anchor
                # (standard I/P offset; the rate controller sees the
                # blended chain bytes either way).
                qps = {}
                for r in plan.rungs:
                    # fractional working point -> per-frame dither
                    q = controllers[r.name].frame_qps(
                        chains_per * clen).reshape(chains_per, clen)
                    q[:, 0] = np.maximum(q[:, 0] - 2, 0)
                    qps[r.name] = q
                # per-rung device RC params; zero-target rungs keep
                # alpha 0 (calibrate_proxy no-ops), disabling adjustment
                rc = {r.name: controllers[r.name].device_rc_params()
                      for r in plan.rungs}
                # the grid stages per column (frames replicated along
                # the rung axis, each rung's QP/RC routed to its owning
                # column) and leaves each rung's outputs on that column
                return prog.dispatch(by, bu, bv, qps, rc), n_real, qps
            qps = {r.name: controllers[r.name].frame_qps(batch_n)
                   for r in plan.rungs}
            return prog.dispatch(by, bu, bv, qps), n_real, qps

        # --- stage-decoupled consume side (parallel/executor.py): rungs
        # pull + entropy-code concurrently on per-rung ordered threads,
        # frame-level work fans onto one shared cpu-count-sized pool,
        # and up to VLOG_PIPELINE_DEPTH batches are in flight.
        from vlog_tpu.parallel.executor import (LaggedRateControl,
                                                PipelineExecutor)

        rungs_by_name = {r.name: r for r in plan.rungs}
        rc = LaggedRateControl(controllers)

        # --- definitive resume point + rate-control journal. The scan
        # candidate is clamped to a segment boundary that is ALSO a
        # dispatch-batch boundary with a complete journal prefix; the
        # journal then replays the original run's rate-control schedule
        # so the resumed segments encode byte-identically (the
        # cross-worker hand-off contract — a successor must continue
        # the tree the uploaded digests already describe).
        from vlog_tpu.backends import rc_journal as rcj

        journal = None
        depth = config.PIPELINE_DEPTH
        start_batch = 0
        if not ts_mode:
            jpath = out / rcj.RC_JOURNAL_NAME
            header = rcj.make_header(
                batch_n=batch_n, depth=depth,
                frames_per_seg=frames_per_seg, gop_len=plan.gop_len,
                rungs=[r.name for r in plan.rungs],
                tag=(f"h264:{config.H264_ENTROPY}"
                     f":deblock={int(config.H264_DEBLOCK and plan.gop_len > 1)}"))
            if start_segment > 0:
                loaded = rcj.load_journal(jpath)
                entries = (loaded[1] if loaded is not None
                           and loaded[0] == header else {})
                a_seg, a_batch = rcj.aligned_resume_point(
                    start_segment, frames_per_seg=frames_per_seg,
                    batch_n=batch_n, entries=entries,
                    rungs=header["rungs"])
                if a_batch > 0:
                    # byte-identical resume: replay the journal so the
                    # controllers continue the original timeline
                    start_segment, start_batch = a_seg, a_batch
                    rc.replay(entries, start_batch, header["depth"])
                else:
                    # no replayable aligned point (journal missing, or
                    # batch padding outruns the tree): legacy resume —
                    # completed segments still skip re-encoding, but the
                    # controllers start cold, so the remaining segments
                    # are valid-not-identical. The journal is stamped
                    # with the resumed frame origin so a later run can
                    # never mistake it for the original timeline.
                    header = {**header,
                              "origin_frame": start_segment * frames_per_seg}
                self._apply_resume_state(
                    plan, resume_per_rung, start_segment, timescale,
                    seg_counts, seg_durs, bytes_written)
            journal = rcj.RCJournal(jpath, header, keep_batches=start_batch)
            start_frame = start_segment * frames_per_seg
            frames_done = start_frame
        if plan.thumbnail and start_segment > 0 \
                and (out / "thumbnail.jpg").exists():
            # resumed run: keep the original first-batch thumbnail — a
            # mid-stream frame would break tree byte-identity
            thumb_path = str(out / "thumbnail.jpg")

        def wait_device(batch):
            jax.block_until_ready(batch.outs)   # device compute, all rungs

        def pull_chain(name, batch):
            ro = batch.outs[name]
            return {k: np.asarray(ro[k]) for k in
                    ("i_luma_dc", "i_luma_ac", "i_chroma_dc",
                     "i_chroma_ac", "p_luma", "p_chroma_dc",
                     "p_chroma_ac", "mv", "sse_y", "qp_eff", "cost")}

        def process_chain(name, batch, host):
            """Entropy-code one rung of one dispatch of I+P chains
            (display order is chain-major, matching how frames were
            batched)."""
            from vlog_tpu.codecs.h264.encoder import FrameLevels

            i32 = lambda a: np.ascontiguousarray(a, np.int32)
            rung = rungs_by_name[name]
            n_real = batch.n_real
            te = time.perf_counter()
            sse = host["sse_y"]                       # (nc, clen)
            # the QPs the device ACTUALLY encoded at (plan + in-chain
            # adjustment) — slice headers must signal these
            qarr = host["qp_eff"]                     # (nc, clen)
            cost = host["cost"]                       # (nc, clen)
            batch_bytes = 0
            n_frames = 0
            cost_sum = 0.0
            rc_qs = []   # P-frame dither values: the working-point
            #              mix the controller must attribute to (the
            #              I frames carry the -2 anchor, excluded)
            plan_q = np.asarray(batch.qps[name])      # (nc, clen)
            for ci in range(chains_per):
                base = ci * clen
                if base >= n_real:
                    break
                keep = min(clen, n_real - base)
                # attribute to the PLAN (outer-loop) working point,
                # not qp_eff: the device's in-chain bumps are the
                # inner loop of a cascade — if the host attributed
                # to the realized QPs, its own corrective step would
                # cancel against the attribution shift and the plan
                # would never converge (measured: stuck 28% under)
                rc_qs.append(plan_q[ci, 1:keep])
                cost_sum += float(cost[ci, :keep].sum())
                lv0 = FrameLevels(
                    luma_dc=i32(host["i_luma_dc"][ci]),
                    luma_ac=i32(host["i_luma_ac"][ci]),
                    chroma_dc=i32(host["i_chroma_dc"][ci]),
                    chroma_ac=i32(host["i_chroma_ac"][ci]),
                    qp=int(qarr[ci, 0]))
                p_list = [
                    {"luma": i32(host["p_luma"][ci, fi]),
                     "chroma_dc": i32(host["p_chroma_dc"][ci, fi]),
                     "chroma_ac": i32(host["p_chroma_ac"][ci, fi]),
                     "mv": i32(host["mv"][ci, fi])}
                    for fi in range(keep - 1)
                ]
                mse = np.maximum(sse[ci, :keep] / npix[name], 1e-12)
                psnrs = np.where(mse < 1e-9, 99.0,
                                 10 * np.log10(255 ** 2 / mse))
                efs = encoders[name].encode_chain(
                    lv0, p_list, qarr[ci, :keep], psnrs,
                    pool=pipe.host_pool)
                for ef in efs:
                    pending[name].append(
                        Sample(data=ef.annexb if ts_mode else ef.avcc,
                               duration=frame_dur, is_sync=ef.is_idr))
                    psnr_acc[name].append(ef.psnr_y)
                    batch_bytes += len(ef.avcc)
                n_frames += keep
            rc_mix = (np.concatenate(rc_qs) if rc_qs else None)
            if rc_mix is not None and rc_mix.size == 0:
                rc_mix = None
            # posted here, applied in batch order on the dispatch
            # thread (observe + the device-RC bytes-per-proxy
            # calibration) — see LaggedRateControl
            rc.post(name, batch.index, nbytes=batch_bytes,
                    frames=max(n_frames, 1), frame_qps=rc_mix,
                    cost=cost_sum)
            if journal is not None:
                journal.record(batch.index, name, nbytes=batch_bytes,
                               frames=max(n_frames, 1), qps=rc_mix,
                               cost=cost_sum)
            pipe.prof_add("entropy_s", time.perf_counter() - te)
            tw = time.perf_counter()
            while len(pending[name]) >= frames_per_seg:
                chunk = pending[name][:frames_per_seg]
                pending[name] = pending[name][frames_per_seg:]
                write_segment(rung, chunk)
            pipe.prof_add("package_s", time.perf_counter() - tw)

        def pull_intra(name, batch):
            ro = batch.outs[name]
            n_real = batch.n_real
            # device ships int16 (halves the transfer); the CAVLC
            # coders (C + Python) work on int32
            levels = {
                k: np.ascontiguousarray(np.asarray(ro[k])[:n_real],
                                        np.int32)
                for k in ("luma_dc", "luma_ac", "chroma_dc", "chroma_ac")}
            sse = np.asarray(ro["sse_y"])[:n_real]
            return levels, sse

        def process_intra(name, batch, host):
            levels, sse = host
            rung = rungs_by_name[name]
            n_real = batch.n_real
            te = time.perf_counter()
            mse = np.maximum(sse / npix[name], 1e-12)
            psnrs = np.where(mse < 1e-9, 99.0,
                             10 * np.log10(255 ** 2 / mse))
            q_used = np.asarray(batch.qps[name])[:n_real]
            frames = encoders[name].encode_levels(levels, q_used, psnrs,
                                                  pool=pipe.host_pool)
            batch_bytes = 0
            for ef in frames:
                pending[name].append(
                    Sample(data=ef.annexb if ts_mode else ef.avcc,
                           duration=frame_dur, is_sync=ef.is_idr))
                psnr_acc[name].append(ef.psnr_y)
                batch_bytes += len(ef.avcc)
            rc.post(name, batch.index, nbytes=batch_bytes, frames=n_real,
                    frame_qps=q_used)
            if journal is not None:
                journal.record(batch.index, name, nbytes=batch_bytes,
                               frames=n_real, qps=q_used, cost=None)
            pipe.prof_add("entropy_s", time.perf_counter() - te)
            tw = time.perf_counter()
            while len(pending[name]) >= frames_per_seg:
                chunk = pending[name][:frames_per_seg]
                pending[name] = pending[name][frames_per_seg:]
                write_segment(rung, chunk)
            pipe.prof_add("package_s", time.perf_counter() - tw)

        def on_batch_done(batch):
            # serialized + batch-ordered by the executor's contract
            nonlocal frames_done
            frames_done += batch.n_real
            if progress_cb:
                # total is an estimate for foreign sources; never report
                # done > total
                t = max(total, frames_done)
                progress_cb(frames_done, t,
                            f"encoded {frames_done}/{t} frames")

        pipe = PipelineExecutor(
            [r.name for r in plan.rungs],
            pull=pull_chain if chain_mode else pull_intra,
            process=process_chain if chain_mode else process_intra,
            ready=wait_device, on_batch_done=on_batch_done,
            host_pool=host_pool_for_run(),   # shared across slot executors
            prof=prof, name="vlog-pipe")

        # Decode prefetch: a producer thread reads/decodes the NEXT batches
        # while the device computes and the host entropy-codes — the
        # decode ∥ transfer ∥ compute ∥ package overlap SURVEY §7 hard
        # part 5 calls mandatory at 4K rates. Bounded queue so decode can
        # run at most 2 batches ahead of the device.
        eof = object()
        fifo: queue_mod.Queue = queue_mod.Queue(maxsize=2)
        stop_decode = threading.Event()

        def producer() -> None:
            try:
                for item in src.read_batches(batch_n, start_frame):
                    while not stop_decode.is_set():
                        try:
                            fifo.put(item, timeout=0.5)
                            break
                        except queue_mod.Full:
                            continue
                    if stop_decode.is_set():
                        return
                fifo.put(eof)
            except BaseException as exc:  # noqa: BLE001 — relayed to consumer
                fifo.put(exc)

        decode_thread = threading.Thread(target=producer, daemon=True,
                                         name="vlog-decode-prefetch")
        decode_thread.start()

        batch_idx = 0
        try:
            while True:
                td = time.perf_counter()
                item = fifo.get()
                prof["decode_wait_s"] += time.perf_counter() - td
                if item is eof:
                    break
                if isinstance(item, BaseException):
                    raise item
                by, bu, bv = item
                # Thumbnail from the first batch (reference grabs an early
                # frame, transcoder.py:2247) — a 4K JPEG encode, so it
                # rides the executor's host pool, not the dispatch thread.
                if plan.thumbnail and thumb_path is None:
                    thumb_path = str(out / "thumbnail.jpg")
                    pipe.submit_aux(self._write_thumbnail, by[0], bu[0],
                                    bv[0], thumb_path)
                # Backpressure BEFORE planning: with a free slot secured,
                # batches <= N-depth are fully consumed, so applying
                # their observations here gives every depth (and every
                # thread interleaving) the same deterministic QP plan.
                pipe.reserve()
                rc.apply_upto(batch_idx - pipe.depth)
                outs, n_real, qps = dispatch(by, bu, bv)
                pipe.submit(outs, n_real, qps)
                batch_idx += 1
                if rc.hunting():
                    # Calibration/cliff hunt: drain the window to depth 0
                    # and apply every correction before the next batch is
                    # staged — with batches in flight each QP move lags
                    # extra batches, multiplying any overshoot burn.
                    pipe.drain()
                    rc.apply_upto(batch_idx - 1)
            pipe.drain()
            # Flush trailing partial segments.
            for rung in plan.rungs:
                if pending[rung.name]:
                    write_segment(rung, pending[rung.name])
                    pending[rung.name] = []
        finally:
            stop_decode.set()
            while True:     # unblock a producer stuck on a full queue
                try:
                    fifo.get_nowait()
                except queue_mod.Empty:
                    break
            decode_thread.join(timeout=10)
            pipe.close()
            src.close()
            if journal is not None:
                journal.close()

        # Inexact (libav) sources: the container's frame count is an
        # estimate — trust the frames actually decoded.
        true_total = total if src.exact_seek else frames_done
        duration_s = true_total / fps if fps else 0.0
        results = []
        variants = []
        for rung in plan.rungs:
            name = rung.name
            enc = encoders[name]
            playlist = hls.media_playlist(
                [hls.SegmentRef(uri=f"segment_{i + 1:05d}.{seg_ext}",
                                duration_s=seg_durs[name][i])
                 for i in range(seg_counts[name])],
                target_duration_s=plan.segment_duration_s,
                init_uri=None if ts_mode else "init.mp4",
            )
            ppath = out / name / "playlist.m3u8"
            atomic_write_text(ppath, playlist)
            total_dur = sum(seg_durs[name])
            achieved = int(bytes_written[name] * 8 / total_dur) if total_dur else 0
            results.append(RungResult(
                name=name, width=rung.width, height=rung.height,
                codec_string=enc.codec_string,
                segment_count=seg_counts[name],
                bytes_written=bytes_written[name],
                mean_psnr_y=float(np.mean(psnr_acc[name])) if psnr_acc[name] else None,
                achieved_bitrate=achieved,
                playlist_path=str(ppath),
                target_bitrate=rung.video_bitrate,
            ))
            # TS variants carry muxed AAC: CODECS must list every format
            # present (RFC 8216) and BANDWIDTH must include the audio.
            muxed = ts_mode and rung.audio_bitrate in audio_by_rate
            variants.append(hls.VariantRef(
                name=name, uri=f"{name}/playlist.m3u8",
                bandwidth=max(achieved, 1)
                + (rung.audio_bitrate if muxed else 0),
                width=rung.width,
                height=rung.height,
                codecs=(enc.codec_string + ",mp4a.40.2" if muxed
                        else enc.codec_string),
                frame_rate=fps,
                audio_group=("" if ts_mode else
                             (f"aud{rung.audio_bitrate // 1000}"
                              if rung.audio_bitrate else "")),
            ))
        atomic_write_text(out / "master.m3u8", hls.master_playlist(variants))
        if not ts_mode:      # DASH is CMAF-only; legacy TS serves HLS alone
            atomic_write_text(out / "manifest.mpd", hls.dash_manifest(
                variants, duration_s=duration_s,
                segment_duration_s=plan.segment_duration_s))

        return RunResult(
            rungs=results, frames_processed=frames_done,
            duration_s=duration_s, thumbnail_path=thumb_path,
            wall_s=time.monotonic() - t0,
            variants=variants, fps=fps,
            segment_duration_s=plan.segment_duration_s,
            stage_s={k: round(v, 3) for k, v in prof.items()}
            | pipe.gauges(),
            gop_len=plan.gop_len,
            resumed_segments=start_segment * len(plan.rungs),
        )

    # ------------------------------------------------------------------
    def _resume_scan(self, plan, out, timescale, seg_counts, seg_durs,
                     bytes_written, init_matched) -> int:
        """Reconstruct per-rung segment state from disk; returns the
        first segment index every rung still needs (shared by the H.264
        and HEVC paths — both emit the same CMAF tree).

        ``init_matched``: rung name -> True when the init segment on
        disk before this run matched the one this run writes. Segments
        from a run with a different init (entropy mode, QP base, SPS
        shape changed between runs) cannot be appended to — they
        reference another PPS — so such rungs restart from segment 0."""
        per_rung = self._scan_resume_candidates(plan, out, init_matched)
        start_segment = min(len(d) for d in per_rung.values())
        self._apply_resume_state(plan, per_rung, start_segment, timescale,
                                 seg_counts, seg_durs, bytes_written)
        return start_segment

    def _scan_resume_candidates(self, plan, out, init_matched
                                ) -> dict[str, list[int]]:
        """Per-rung timescale durations of the contiguous valid segments
        on disk (the scan half of :meth:`_resume_scan`; the H.264 path
        applies state separately so the resume point can first be
        clamped to a journal-replayable batch boundary)."""
        per_rung = {}
        for r in plan.rungs:
            existing = self._existing_segments(out / r.name)
            if existing and not init_matched.get(r.name, False):
                existing = []
            per_rung[r.name] = existing
        return per_rung

    @staticmethod
    def _apply_resume_state(plan, per_rung, start_segment, timescale,
                            seg_counts, seg_durs, bytes_written) -> None:
        """Install the resumed prefix into the run's per-rung state."""
        for rung in plan.rungs:
            durs = per_rung[rung.name][:start_segment]
            seg_counts[rung.name] = start_segment
            seg_durs[rung.name] = [d / timescale for d in durs]
            for i in range(start_segment):
                seg = plan.out_dir / rung.name / f"segment_{i + 1:05d}.m4s"
                bytes_written[rung.name] += seg.stat().st_size

    @staticmethod
    def _existing_segments(rdir: Path) -> list[int]:
        """Timescale durations of contiguous valid segments (resume state).

        A segment counts only if its moof parses and carries samples —
        the on-disk-validation analog of validate_hls_playlist's fMP4
        ``moof`` check (reference transcoder.py:930-941).
        """
        from vlog_tpu.media.boxes import parse_box_tree

        durations: list[int] = []
        if not (rdir / "init.mp4").exists():
            return durations
        i = 0
        while True:
            seg = rdir / f"segment_{i + 1:05d}.m4s"
            if not seg.exists() or seg.stat().st_size < 16:
                break
            try:
                with open(seg, "rb") as fp:
                    tree = parse_box_tree(fp)
                moof = next(b for b in tree if b.type == "moof")
                trun = moof.find("traf", "trun")
                n = int.from_bytes(trun.payload[4:8], "big")
                if n == 0:
                    break
                # trun payload: ver/flags, count, data_offset, then
                # (duration, size, flags, cts) per sample
                dur = sum(
                    int.from_bytes(trun.payload[12 + 16 * k:16 + 16 * k], "big")
                    for k in range(n)
                )
            except (StopIteration, AttributeError, ValueError, IndexError):
                break  # torn write
            durations.append(dur)
            i += 1
        return durations

    def _write_segment(self, out, rung: PlannedRung, track: TrackConfig,
                       seg_counts, seg_durs, bytes_written,
                       samples: list[Sample], timescale: int) -> None:
        name = rung.name
        idx = seg_counts[name]
        # base decode time = sum of durations of all prior segments
        base_time = int(round(sum(seg_durs[name]) * timescale))
        data = media_segment(track, idx + 1, base_time, samples)
        path = out / name / f"segment_{idx + 1:05d}.m4s"
        tmp = path.with_suffix(".m4s.tmp")
        tmp.write_bytes(data)
        tmp.rename(path)           # atomic publish (sprite_generator parity)
        seg_counts[name] = idx + 1
        seg_durs[name].append(sum(s.duration for s in samples) / timescale)
        bytes_written[name] += len(data)

    @staticmethod
    def _write_thumbnail(y, u, v, path: str, max_width: int = 1280) -> None:
        h, w = y.shape
        if w > max_width:
            th = max(2, round(h * max_width / w / 2) * 2)
            y, u, v = resize_yuv420(y[None], u[None], v[None], th, max_width)
            y, u, v = np.asarray(y[0]), np.asarray(u[0]), np.asarray(v[0])
        rgb = np.asarray(yuv420_to_rgb(y, u, v, standard="bt709"))
        from vlog_tpu.codecs.jpeg import encode_jpeg_rgb

        atomic_write_bytes(Path(path), encode_jpeg_rgb(
            (rgb * 255).astype(np.uint8), quality=85))


register_backend("jax", JaxBackend)
