"""Closed-loop rate control: per-batch QP adaptation toward a bitrate.

The reference hits ladder bitrate targets by delegating VBR to
x264/NVENC (`-b:v`/`-maxrate`, worker/hwaccel.py:660-731). Here the
control loop is explicit: observe achieved bits after each GOP batch,
step QP toward the target. The DSP takes QP as a *traced* per-frame
value (ops/transform.py), so stepping costs no recompile.

The plant model is the standard H.264 rule of thumb: bits halve per +6
QP, i.e. log2(bits) is linear in QP with slope -1/6. A damped
proportional step on that log scale converges in a few batches and
cannot oscillate for damping <= 1.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


@dataclass
class RateController:
    """One per rung. ``observe()`` after each batch; read ``qp`` before
    the next."""

    target_bps: int            # 0 = constant-QP mode (no adaptation)
    fps: float
    init_qp: int
    min_qp: int = 10
    max_qp: int = 48
    damping: float = 0.6       # fraction of the full log-domain correction
    max_step: int = 4          # per-batch QP step clamp
    ema_alpha: float = 0.6     # weight of the newest batch in the bpf EMA

    qp: int = field(init=False)
    _ema_bpf: float | None = field(default=None, init=False)
    _calibrating: bool = field(default=True, init=False)
    _last_sign: int = field(default=0, init=False)
    _sign_run: int = field(default=0, init=False)

    def __post_init__(self) -> None:
        self.qp = self.init_qp

    @property
    def target_bytes_per_frame(self) -> float:
        return self.target_bps / 8.0 / self.fps if self.fps else 0.0

    def observe(self, bytes_out: int, n_frames: int) -> int:
        """Feed achieved bytes for ``n_frames`` frames; returns next QP."""
        if self.target_bps <= 0 or n_frames <= 0 or self.fps <= 0:
            return self.qp
        bpf = bytes_out / n_frames
        if self._ema_bpf is None:
            self._ema_bpf = bpf
        else:
            self._ema_bpf += self.ema_alpha * (bpf - self._ema_bpf)
        ratio = max(self._ema_bpf, 1e-9) / max(self.target_bytes_per_frame, 1e-9)
        # +6 QP ~ half the bits -> full correction is 6*log2(ratio).
        if self._calibrating:
            # First real observation: jump the whole way (the init QP is a
            # ladder-wide default, often far off for this content).
            self._calibrating = False
            step = round(6.0 * math.log2(ratio))
        else:
            full = 6.0 * math.log2(ratio)
            sign = (full > 0) - (full < 0)
            # Damping guards against oscillation — but an error that keeps
            # the same sign across batches is bias, not noise; drop the
            # damping so short encodes still converge (few observations).
            self._sign_run = self._sign_run + 1 if sign == self._last_sign \
                else 1
            self._last_sign = sign
            damp = 1.0 if self._sign_run >= 2 else self.damping
            step = max(-self.max_step,
                       min(self.max_step, round(full * damp)))
        if step:
            self.qp = max(self.min_qp, min(self.max_qp, self.qp + step))
            # A QP move invalidates the EMA's operating point; restart it
            # so stale samples don't fight the next correction.
            self._ema_bpf = None
        return self.qp
