"""Closed-loop rate control: per-batch QP adaptation toward a bitrate.

The reference hits ladder bitrate targets by delegating VBR to
x264/NVENC (`-b:v`/`-maxrate`, worker/hwaccel.py:660-731). Here the
control loop is explicit: observe achieved bits after each GOP batch,
pick the next QP. The DSP takes QP as a *traced* per-frame value
(ops/transform.py), so stepping costs no recompile.

Design (round 4, replacing the log-bracket search): the rate curve of a
real encoder is NOT smooth — MB decimation, skip thresholds, and dead
zones produce CLIFFS where bits drop several-fold across one QP step
(measured: 64k -> 8k bytes/frame between QP 27 and 28 on noisy content).
Two structural choices make the controller exact there:

- **Integer-QP rate estimates.** ``frame_qps(n)`` realizes a fractional
  working point q as a Bresenham mix of floor(q) and floor(q)+1 frames,
  so the achieved rate is a LINEAR blend of the two integers' rates.
  The controller therefore estimates bytes/frame per INTEGER QP (EMA,
  updated by attributing each batch observation to the two integers in
  proportion to their mix), instead of curve-fitting fractional points.
- **Analytic dither fraction.** Once adjacent integers (qa, qa+1)
  bracket the target, the mix fraction is solved directly:
  f = (r(qa) - target) / (r(qa) - r(qa+1)), and q = qa + f. One step
  lands ON target even when the target sits inside a cliff no single QP
  can reach. Non-adjacent brackets bisect at integers; no bracket
  extrapolates on the textbook bits-halve-per-6-QP slope, clamped to
  ±2*max_step per batch — calibration included, so a cliff can cost at
  most one bounded-error batch, never a 5x overshoot burn.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np


@dataclass
class RateController:
    """One per rung. ``observe()`` after each batch; read ``qp`` (or
    ``frame_qps``) before the next."""

    target_bps: int            # 0 = constant-QP mode (no adaptation)
    fps: float
    init_qp: int
    min_qp: int = 10
    max_qp: int = 48
    damping: float = 0.6       # kept for API compat (unused)
    max_step: int = 4          # extrapolation clamp (x2 applied)
    ema_alpha: float = 0.5     # per-QP estimate update weight
    band: float = 0.15         # +-15% of target counts as converged
    # Debt payback horizon: overspend from a scene cut / noise burst is
    # recovered over this many frames by steering the working setpoint
    # below nominal (and vice versa for undershoot). Without it the
    # loop re-converges to NOMINAL after every spike, so bursty content
    # averages 25-60% hot even though each quiet batch sits in-band —
    # x264's VBR pays its debt back the same way.
    payback_horizon_frames: float = 96.0
    # Converged-phase downward probe size. 1 for integer-QP video codecs
    # (cliffs sit between adjacent QPs; one step either converges or
    # forms an adjacent bracket for the analytic dither). Controllers on
    # finer, smoother scales (AAC scalefactors span ~170 steps) raise it.
    converged_down_step: float = 1.0

    _q: float = field(init=False)
    _obs: dict = field(default_factory=dict, init=False)   # int qp -> bpf
    _order: list = field(default_factory=list, init=False)
    _calibrating: bool = field(default=True, init=False)
    _hunting: bool = field(default=True, init=False)
    _debt_bytes: float = field(default=0.0, init=False)
    _proxy_alpha: float = field(default=0.0, init=False)

    def __post_init__(self) -> None:
        self._q = float(self.init_qp)

    @property
    def hunting(self) -> bool:
        """True until an observation lands within 1.5x of target. While
        hunting, the backend consumes batches SYNCHRONOUSLY (no
        one-batch-in-flight overlap): with a batch in flight every
        correction lags one extra batch, and a calibration jump past a
        rate cliff would burn two 5x batches instead of one."""
        return self.target_bps > 0 and self._hunting

    @property
    def qp(self) -> int:
        return int(round(self._q))

    @qp.setter
    def qp(self, value: int) -> None:
        self._q = float(value)

    @property
    def target_bytes_per_frame(self) -> float:
        return self.target_bps / 8.0 / self.fps if self.fps else 0.0

    # ---- device-side in-chain cascade (ops/bitproxy.py) --------------
    # The chain programs adapt QP per FRAME on device from a bits proxy;
    # this controller is the outer loop and owns the bytes-per-proxy
    # calibration both backends share.

    def device_rc_params(self) -> dict:
        """The rc pytree a chain-ladder dispatch takes (alpha 0 until
        the first batch calibrates -> device runs open-loop)."""
        return {"budget": np.float32(
                    max(self.target_bytes_per_frame, 1.0)),
                "alpha": np.float32(self._proxy_alpha)}

    def calibrate_proxy(self, batch_bytes: float, cost_sum: float) -> None:
        """EMA the realized bytes-per-proxy-unit from one chain batch.
        No-op for constant-QP rungs (no target) or empty batches."""
        if self.target_bps <= 0 or cost_sum <= 0:
            return
        a = batch_bytes / cost_sum
        self._proxy_alpha = (a if self._proxy_alpha == 0
                             else 0.5 * self._proxy_alpha + 0.5 * a)

    def frame_qps(self, n: int) -> np.ndarray:
        """Per-frame integer QPs whose mix realizes the fractional
        working point (evenly interleaved)."""
        lo = math.floor(self._q)
        frac = self._q - lo
        i = np.arange(n)
        bump = ((i + 1) * frac).astype(np.int64) - (i * frac).astype(
            np.int64)
        return np.clip(lo + bump, self.min_qp, self.max_qp).astype(
            np.int32)

    # ------------------------------------------------------------------
    def _touch(self, q: int) -> None:
        if q in self._order:
            self._order.remove(q)
        self._order.append(q)
        while len(self._order) > 12:          # bounded, recency-kept
            self._obs.pop(self._order.pop(0), None)

    def _upd(self, q: int, bpf: float, weight: float = 1.0) -> None:
        bpf = max(bpf, 1.0)
        if q in self._obs:
            self._obs[q] += self.ema_alpha * weight * (bpf - self._obs[q])
        else:
            self._obs[q] = bpf
        self._touch(q)

    def _attribute(self, bpf: float, lo: int, f: float) -> None:
        """Fold one batch observation into the integer estimates for the
        realized (lo, lo+1) mix with fraction ``f`` of frames at lo+1."""
        lo = int(min(max(lo, self.min_qp), self.max_qp))
        hi = int(min(lo + 1, self.max_qp))
        if f < 1e-6 or hi == lo:
            self._upd(lo, bpf)
            return
        rlo, rhi = self._obs.get(lo), self._obs.get(hi)
        if rlo is None and rhi is None:
            self._upd(lo, bpf)
            self._upd(hi, bpf)
            return
        if rlo is None:
            if f < 0.85:       # enough mass at lo to imply its rate
                self._upd(lo, (bpf - f * rhi) / (1.0 - f))
            else:              # nearly all frames ran at hi
                self._upd(hi, bpf)
            return
        if rhi is None:
            if f > 0.15:       # enough mass at hi to imply its rate
                self._upd(hi, (bpf - (1.0 - f) * rlo) / f)
            else:
                self._upd(lo, bpf)
            return
        # both known: distribute the prediction error by mix share
        pred = (1.0 - f) * rlo + f * rhi
        err = bpf - pred
        self._upd(lo, rlo + (1.0 - f) * err)
        self._upd(hi, rhi + f * err)

    def _predicted(self) -> float | None:
        lo = math.floor(self._q)
        f = self._q - lo
        rlo, rhi = self._obs.get(lo), self._obs.get(lo + 1)
        if f < 1e-6:
            return rlo
        if rlo is None or rhi is None:
            return None
        return (1.0 - f) * rlo + f * rhi

    def observe(self, bytes_out: int, n_frames: int,
                frame_qps: np.ndarray | None = None) -> int:
        """Feed achieved bytes for ``n_frames`` frames; returns next QP.

        ``frame_qps``: the integer QPs the batch was ACTUALLY encoded at
        (the array ``frame_qps()`` returned when the batch was staged).
        The backend runs one batch in flight, so by observe time the
        working point has already moved — attributing to ``self._q``
        would mislabel every observation by one batch (the failure mode
        ADVICE round-3 flagged on the HEVC path). Without it the current
        working point is assumed."""
        if self.target_bps <= 0 or n_frames <= 0 or self.fps <= 0:
            return self.qp
        bpf = bytes_out / n_frames
        if frame_qps is not None and len(frame_qps) > 0:
            qs = np.asarray(frame_qps).reshape(-1)[:n_frames]
            lo = int(qs.min())
            f = float(np.mean(qs > lo))
            q_real = lo + f
        else:
            lo = math.floor(self._q)
            f = self._q - lo
            q_real = self._q
        self._attribute(bpf, lo, f)
        nominal = max(self.target_bytes_per_frame, 1e-9)
        # Anti-windup, two layers: a single batch can book at most 3x
        # its nominal budget of debt/credit (one cliff batch must not
        # dominate the integral), and the integral itself is clamped to
        # what the (clamped) setpoint offset can actually pay back —
        # a long stretch of un-payable credit/debt (content pinned at a
        # QP rail) cannot bank thousands of frames of rail-riding.
        batch_budget = nominal * int(n_frames)
        # credit is inherently <= 1x budget (bytes_out >= 0); no
        # per-batch clamp needed on that side
        per_batch = min(float(bytes_out) - batch_budget,
                        3.0 * batch_budget)
        # integral caps mirror the setpoint clamp below: debt pays back
        # at up to 0.5x nominal/frame, credit spends at only 0.15x —
        # each side bounded by what one horizon can actually recover
        self._debt_bytes += per_batch
        self._debt_bytes = min(
            max(self._debt_bytes,
                -0.15 * nominal * self.payback_horizon_frames),
            0.5 * nominal * self.payback_horizon_frames)
        calibrating, self._calibrating = self._calibrating, False
        self._hunting = (abs(math.log2(max(bpf, 1.0) / nominal))
                         > math.log2(1.5))
        # Steady-state setpoint = nominal minus accumulated debt
        # amortized over the payback horizon, clamped to [0.5, 1.5]x
        # nominal so a giant spike can't spiral QP to the rails. Debt
        # accrues always (calibration bits were really spent) and
        # steers every post-calibration batch — a scene-cut batch that
        # blows past 1.5x nominal is exactly when payback must engage,
        # not pause. Only the calibration batch itself is exempt (its
        # step math is the direction-asymmetric transient logic).
        if calibrating:
            target = nominal
        else:
            # Asymmetric setpoint clamp (the integral sibling of the
            # asymmetric step rule): paying back overshoot pushes the
            # setpoint down to 0.5x freely — raising QP is always safe —
            # but banked credit raises it at most 15%, because SPENDING
            # credit means stepping down toward rate cliffs, and a
            # cliff batch costs more than the credit was worth.
            target = min(max(
                nominal - self._debt_bytes / self.payback_horizon_frames,
                0.5 * nominal), 1.15 * nominal)

        # converged: the just-measured rate sits inside the band
        if abs(math.log2(max(bpf, 1.0) / target)) <= math.log2(
                1 + self.band):
            return self.qp

        over = {q: r for q, r in self._obs.items() if r > target}
        under = {q: r for q, r in self._obs.items() if r <= target}
        if over and under:
            qa = max(over)                     # highest QP still over
            qb = min(under)                    # lowest QP at/under
            if qa >= qb:
                # contradicts bits-decrease-with-QP: the content moved;
                # keep only what this batch just taught us — the
                # REALIZED (lo, lo+1) pair, which with a batch in flight
                # is not floor(self._q)
                keep = {q: self._obs[q]
                        for q in (lo, lo + 1) if q in self._obs}
                self._obs = dict(keep)
                self._order = list(keep)
            elif qb - qa == 1:
                # adjacent bracket: rate mixes linearly in the dither
                # fraction — solve it exactly (cliff-proof)
                f = (over[qa] - target) / max(over[qa] - under[qb], 1e-9)
                self._q = qa + min(max(f, 0.0), 0.999)
                return self.qp
            else:
                # wide bracket: log-rate interpolation, snapped to an
                # INTEGER probe strictly inside (smooth content lands
                # near the answer in one step; cliffs degenerate toward
                # bisection, and every probe tightens the bracket)
                l_lo = math.log2(max(over[qa], 1.0))
                l_hi = math.log2(max(under[qb], 1.0))
                t = (math.log2(target) - l_lo) / min(l_hi - l_lo, -1e-9)
                probe = round(qa + t * (qb - qa))
                self._q = float(min(max(probe, qa + 1), qb - 1))
                return self.qp

        # No (usable) bracket: textbook slope, ASYMMETRICALLY capped.
        # Downward moves (spending more bits) walk at most max_step per
        # batch: a rate cliff below costs a mildly-under batch instead
        # of a 5x overshoot burn (each step lands a bracket point, so
        # the analytic dither takes over the moment the target is
        # straddled). Upward moves (cutting bits) jump the whole way
        # while calibrating — overshoot recovery must be immediate.
        ratio = max(bpf, 1.0) / target
        step = 6.0 * math.log2(ratio)
        if step < 0:
            # halve the remaining distance on bracketless downward moves
            # while far from target: any target is reached in O(log)
            # batches of cheap UNDER-target encodes, and a cliff at the
            # far end is approached, never leapt onto (the 5x-burn batch
            # a full jump used to cost). CONVERGED operation probes one
            # QP at a time: near the working point the rate curve's
            # cliffs are exactly where a -3 model step lands 5x hot, and
            # a single step either converges or forms an adjacent
            # bracket for the analytic dither to solve.
            step = step / 2.0 if self._hunting or calibrating \
                else max(step, -self.converged_down_step)
        elif not calibrating:
            step = min(step, 2.0 * self.max_step)
        base = q_real if frame_qps is not None else self._q
        self._q = float(int(round(
            min(max(base + step, float(self.min_qp)),
                float(self.max_qp)))))
        return self.qp
