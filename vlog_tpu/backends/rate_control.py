"""Closed-loop rate control: per-batch QP adaptation toward a bitrate.

The reference hits ladder bitrate targets by delegating VBR to
x264/NVENC (`-b:v`/`-maxrate`, worker/hwaccel.py:660-731). Here the
control loop is explicit: observe achieved bits after each GOP batch,
pick the next QP. The DSP takes QP as a *traced* per-frame value
(ops/transform.py), so stepping costs no recompile.

Two structural choices make this robust where slope controllers fail:

- **Bracketing search** over the observed (QP -> bytes/frame) points.
  The textbook "bits halve per +6 QP" rule only extrapolates while no
  bracket exists (including the first calibration jump); once
  observations straddle the target, the next QP interpolates between
  the bracketing points in log-bit space, so response cliffs and
  temporal drift cannot produce limit cycles.
- **Fractional QP via frame dithering.** The working QP is continuous;
  ``frame_qps(n)`` assigns each frame floor or ceil in a Bresenham
  pattern matching the fraction. Rate mixes linearly in the frame
  count, so targets BETWEEN two integer QPs' rates — exactly the cliff
  case where no single QP lands near the target — are reachable. This
  is the frame-level analog of x264's adaptive quantization.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np


@dataclass
class RateController:
    """One per rung. ``observe()`` after each batch; read ``qp`` (or
    ``frame_qps``) before the next."""

    target_bps: int            # 0 = constant-QP mode (no adaptation)
    fps: float
    init_qp: int
    min_qp: int = 10
    max_qp: int = 48
    damping: float = 0.6       # kept for API compat (unused by search)
    max_step: int = 4          # extrapolation step clamp (x2 applied)
    ema_alpha: float = 0.5     # per-QP estimate update weight
    band: float = 0.15         # +-15% of target counts as converged

    _q: float = field(init=False)
    _obs: dict = field(default_factory=dict, init=False)  # q -> bpf EMA
    _order: list = field(default_factory=list, init=False)
    _calibrating: bool = field(default=True, init=False)

    def __post_init__(self) -> None:
        self._q = float(self.init_qp)

    @property
    def qp(self) -> int:
        return int(round(self._q))

    @qp.setter
    def qp(self, value: int) -> None:
        self._q = float(value)

    @property
    def target_bytes_per_frame(self) -> float:
        return self.target_bps / 8.0 / self.fps if self.fps else 0.0

    def frame_qps(self, n: int) -> np.ndarray:
        """Per-frame integer QPs whose mix realizes the fractional
        working point (evenly interleaved)."""
        lo = math.floor(self._q)
        frac = self._q - lo
        i = np.arange(n)
        bump = ((i + 1) * frac).astype(np.int64) - (i * frac).astype(
            np.int64)
        return np.clip(lo + bump, self.min_qp, self.max_qp).astype(
            np.int32)

    # ------------------------------------------------------------------
    def _record(self, q: float, bpf: float) -> None:
        key = round(q, 2)
        if key in self._obs:
            self._obs[key] += self.ema_alpha * (bpf - self._obs[key])
            self._order.remove(key)
        else:
            self._obs[key] = bpf
        self._order.append(key)
        while len(self._order) > 8:            # bounded, recency-kept
            self._obs.pop(self._order.pop(0))

    def observe(self, bytes_out: int, n_frames: int) -> int:
        """Feed achieved bytes for ``n_frames`` frames; returns next QP."""
        if self.target_bps <= 0 or n_frames <= 0 or self.fps <= 0:
            return self.qp
        bpf = bytes_out / n_frames
        self._record(self._q, bpf)
        target = max(self.target_bytes_per_frame, 1e-9)

        est = self._obs[round(self._q, 2)]
        ratio = max(est, 1e-9) / target
        calibrating, self._calibrating = self._calibrating, False
        if abs(math.log2(ratio)) <= math.log2(1 + self.band):
            return self.qp                      # converged: hold

        over = {q: b for q, b in self._obs.items() if b > target}
        under = {q: b for q, b in self._obs.items() if b <= target}
        nxt = None
        if over and under:
            q_lo = max(over)                    # highest QP still over
            q_hi = min(under)                   # lowest QP at/under
            if q_lo >= q_hi:
                # contradicts bits-decrease-with-QP: the content moved;
                # trust only what we just measured
                self._obs = {round(self._q, 2): est}
                self._order = [round(self._q, 2)]
            else:
                # interpolate in log-bit space inside the bracket; the
                # fractional result is realized by frame dithering
                l_lo = math.log2(max(over[q_lo], 1e-9))
                l_hi = math.log2(max(under[q_hi], 1e-9))
                t = (math.log2(target) - l_lo) / (l_hi - l_lo)
                nxt = q_lo + t * (q_hi - q_lo)
                span = q_hi - q_lo
                nxt = min(max(nxt, q_lo + 0.05 * span),
                          q_hi - 0.05 * span)
        if nxt is None:
            # no (usable) bracket: extrapolate on the textbook slope;
            # the calibration jump goes the whole way (the init QP is a
            # ladder-wide default, often far off), later ones clamp. If
            # the jump lands past a response cliff, that one batch is
            # the unavoidable probe cost — the bracket formed from it
            # pulls the very next batch onto the interpolated point.
            step = 6.0 * math.log2(ratio)
            if not calibrating:
                cap = 2.0 * self.max_step
                step = max(-cap, min(cap, step))
            nxt = self._q + step
        self._q = min(max(nxt, float(self.min_qp)), float(self.max_qp))
        return self.qp
