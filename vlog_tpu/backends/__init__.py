"""Accelerator backends (the hwaccel.py analog, SURVEY.md section 7 step 3).

Importing this package registers the built-in JAX backend; additional
backends register themselves via :func:`register_backend`.
"""

from vlog_tpu.backends.base import (  # noqa: F401
    Backend,
    Capabilities,
    ExecutionPlan,
    PlannedRung,
    RungResult,
    RunResult,
    available_backends,
    get_backend,
    plan_rung_geometry,
    register_backend,
    select_backend,
)
from vlog_tpu.backends.source import (  # noqa: F401
    FrameSource,
    Mp4H264FrameSource,
    UnsupportedSource,
    Y4mFrameSource,
    open_source,
)
from vlog_tpu.backends import jax_backend  # noqa: F401  (registers "jax")
