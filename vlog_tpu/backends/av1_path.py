"""AV1 ladder execution path (codec="av1" re-encodes) — delegated encode.

Reference parity: AV1 in the reference is hardware-delegated encoding
(av1_vaapi selection, worker/hwaccel.py:555-646). This path draws the
same boundary: resize runs on the device (matmul lanczos), the AV1 bits
come from the system encoder libraries through the native shim
(native/av1enc.c — libaom-av1/SVT-AV1 via libavcodec), and the product
plane (CMAF av01 segments, playlists, resume validation, re-encode
flips) is all first-party and identical in shape to the H.264/HEVC
paths. H.264 and HEVC remain first-party TPU encoders; a first-party
AV1 entropy coder is descoped in this environment (COVERAGE.md row 5:
the spec's default CDF tables cannot be sourced from the stripped
system libraries with zero egress).

The delegated encoder owns its own rate control (bitrate target per
rung, VBR); keyframes are forced at segment boundaries so the CMAF tree
stays chain-aligned and resumable.
"""

from __future__ import annotations

import ctypes
import queue as queue_mod
import threading
import time
from pathlib import Path

import numpy as np

from vlog_tpu import config
from vlog_tpu.backends.base import RungResult, RunResult
from vlog_tpu.backends.source import open_source
from vlog_tpu.codecs.av1 import codec_string_from_tu, parse_seq_header
from vlog_tpu.media import hls
from vlog_tpu.media.fmp4 import (
    Sample,
    TrackConfig,
    av01_sample_entry,
    av1c_record,
    init_segment,
)
from vlog_tpu.utils.fsio import atomic_write_text, prepare_init_segment


class Av1Unavailable(RuntimeError):
    """No system AV1 encoder (shim unbuildable or encoders absent)."""


class _ShimEncoder:
    """One delegated AV1 encoder instance (one per rung)."""

    def __init__(self, lib, w: int, h: int, fps_num: int, fps_den: int,
                 bitrate: int, gop_len: int):
        self.lib = lib
        self.w, self.h = w, h
        self.handle = lib.vt_av1_open(
            w, h, fps_num, fps_den,
            bitrate or 2_000_000, max(gop_len, 1),
            int(config.AV1_SPEED))
        if not self.handle:
            raise Av1Unavailable("vt_av1_open failed (no AV1 encoder)")
        self._out = np.empty(max(1 << 20, w * h * 2), np.uint8)
        self._u8p = ctypes.POINTER(ctypes.c_uint8)
        self._closed = False

    def send(self, y: np.ndarray, u: np.ndarray, v: np.ndarray,
             force_key: bool) -> None:
        p = self._u8p
        ya = np.ascontiguousarray(y, np.uint8)
        ua = np.ascontiguousarray(u, np.uint8)
        va = np.ascontiguousarray(v, np.uint8)
        rc = self.lib.vt_av1_send(
            self.handle, ya.ctypes.data_as(p), ua.ctypes.data_as(p),
            va.ctypes.data_as(p), 1 if force_key else 0)
        if rc != 0:
            raise RuntimeError(f"av1 send failed rc={rc}")

    def receive(self) -> list[tuple[bytes, bool, int]]:
        out = []
        is_key = ctypes.c_int()
        pts = ctypes.c_int64()
        while True:
            n = self.lib.vt_av1_receive(
                self.handle, self._out.ctypes.data_as(self._u8p),
                self._out.size, ctypes.byref(is_key), ctypes.byref(pts))
            if n == -2:    # grow and retry
                self._out = np.empty(self._out.size * 2, np.uint8)
                continue
            if n <= 0:
                if n == -3:
                    raise RuntimeError("av1 encoder error")
                return out
            out.append((self._out[:n].tobytes(), bool(is_key.value),
                        int(pts.value)))

    def flush(self) -> list[tuple[bytes, bool, int]]:
        self.lib.vt_av1_flush(self.handle)
        return self.receive()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self.lib.vt_av1_close(self.handle)


def run_av1(backend, plan, progress_cb, resume: bool, t0: float
            ) -> RunResult:
    if plan.streaming_format != "cmaf":
        raise ValueError("av1 output is CMAF-only")
    from vlog_tpu.native.avbuild import get_av_lib

    lib = get_av_lib()
    if lib is None:
        raise Av1Unavailable(
            "AV1 re-encode needs the libav shim (system libavcodec with "
            "an AV1 encoder); it is unavailable or disabled")

    out = Path(plan.out_dir)
    out.mkdir(parents=True, exist_ok=True)
    fps = plan.fps_num / plan.fps_den
    frames_per_seg = max(1, round(plan.segment_duration_s * fps))
    timescale = plan.fps_num * 1000
    frame_dur = plan.fps_den * 1000

    encoders: dict[str, _ShimEncoder] = {}
    tracks: dict[str, TrackConfig] = {}
    meta: dict[str, dict] = {}        # rung -> {profile, level, tier}
    seg_counts: dict[str, int] = {}
    seg_durs: dict[str, list[float]] = {}
    bytes_written: dict[str, int] = {}
    pending: dict[str, list[Sample]] = {}
    frame_idx: dict[str, int] = {}

    def _close_all() -> None:
        for enc in encoders.values():
            enc.close()

    try:
        for rung in plan.rungs:
            encoders[rung.name] = _ShimEncoder(
                lib, rung.width, rung.height, plan.fps_num, plan.fps_den,
                rung.video_bitrate, frames_per_seg)
            seg_counts[rung.name] = 0
            seg_durs[rung.name] = []
            bytes_written[rung.name] = 0
            pending[rung.name] = []
            frame_idx[rung.name] = 0
        src = open_source(plan.source.path)
    except BaseException:
        _close_all()
        raise
    try:
        total = src.frame_count
        # resume: AV1 tracks are written by a third-party encoder whose
        # bitstream state we cannot reconstruct mid-stream — restart
        # clean (the tree is still atomically replaced per segment)
        start_frame = 0

        from vlog_tpu.ops.resize import resize_yuv420
        from vlog_tpu.parallel.compile_cache import ensure_compile_cache
        from vlog_tpu.parallel.executor import PipelineExecutor
        from vlog_tpu.parallel.mesh import pad_batch, shard_frames
        from vlog_tpu.parallel.scheduler import (grid_for_run,
                                                 host_pool_for_run)

        ensure_compile_cache()

        # Mesh parity with the first-party paths: rungs are partitioned
        # into cost-balanced columns of the 2-D (data x rung) grid and
        # each rung's device resize runs on its owning column (slot
        # submesh under the scheduler, all devices otherwise), so AV1
        # jobs can be placed on narrow slots too and per-rung resizes
        # land on distinct devices. Frames are independent, so sharded
        # and unsharded resizes are identical; pad_batch rounds the
        # batch up to the column's data width and the pull trims.
        rungs_spec = tuple((r.name, r.height, r.width, 0)
                           for r in plan.rungs)
        grid = grid_for_run(rungs_spec, batch_hint=plan.frame_batch)

        fifo: queue_mod.Queue = queue_mod.Queue(maxsize=1)
        eof = object()
        stop = threading.Event()
        batch_n = max(1, plan.frame_batch)
        # same stage fields as the first-party paths: device_pull is the
        # device resize + d2h, entropy the delegated encoder, package
        # the fMP4 segment writes (compute_wait stays 0 — the delegated
        # encoder has no separate async device stage)
        prof = {"decode_wait_s": 0.0, "compute_wait_s": 0.0,
                "device_pull_s": 0.0, "entropy_s": 0.0, "package_s": 0.0}

        def producer() -> None:
            try:
                for item in src.read_batches(batch_n, start_frame):
                    while not stop.is_set():
                        try:
                            fifo.put(item, timeout=0.5)
                            break
                        except queue_mod.Full:
                            continue
                    if stop.is_set():
                        return
                fifo.put(eof)
            except BaseException as exc:  # noqa: BLE001 — relayed below
                fifo.put(exc)

        threading.Thread(target=producer, daemon=True,
                         name="vlog-av1-decode").start()

        frames_done = 0
        thumb_path = None

        def ensure_track(rung, first_tu: bytes) -> None:
            """Build the av01 track from the first keyframe TU's
            sequence header (libaom leaves extradata to the bitstream)."""
            if rung.name in tracks:
                return
            prof, level, tier = parse_seq_header(first_tu)
            meta[rung.name] = {"profile": prof, "level": level,
                               "tier": tier}
            tracks[rung.name] = TrackConfig(
                track_id=1, handler="vide", timescale=timescale,
                sample_entry=av01_sample_entry(
                    rung.width, rung.height,
                    av1c_record(prof, level, tier)),
                width=rung.width, height=rung.height)
            rdir = out / rung.name
            rdir.mkdir(parents=True, exist_ok=True)
            # AV1 never resumes (a third-party encoder's mid-stream
            # state is unreconstructable): always purge stale segments
            for seg in rdir.glob("segment_*.m4s"):
                seg.unlink(missing_ok=True)
            prepare_init_segment(
                rdir, init_segment(tracks[rung.name]),
                config_tag=f"av1:delegated:gop={frames_per_seg}")

        next_pts: dict[str, int] = {r.name: 0 for r in plan.rungs}

        def drain(rung, pkts) -> None:
            for data, is_key, pts in pkts:
                # The muxer packages packets in arrival order with
                # uniform durations, and segment boundaries assume the
                # forced keyframes land where they were asked. Both
                # break silently if the encoder reorders or delays
                # output, so every encoder is opened low-delay
                # (av1enc.c) and this asserts the contract held.
                if pts != next_pts[rung.name]:
                    raise RuntimeError(
                        f"{rung.name}: delegated AV1 encoder emitted "
                        f"pts {pts}, expected {next_pts[rung.name]} — "
                        "out-of-order/delayed output breaks CMAF "
                        "timing (encoder not in low-delay mode?)")
                next_pts[rung.name] = pts + 1
                ensure_track(rung, data)
                pending[rung.name].append(
                    Sample(data=data, duration=frame_dur, is_sync=is_key))
            tw = time.perf_counter()
            while len(pending[rung.name]) >= frames_per_seg:
                chunk = pending[rung.name][:frames_per_seg]
                pending[rung.name] = pending[rung.name][frames_per_seg:]
                backend._write_segment(out, rung, tracks[rung.name],
                                       seg_counts, seg_durs,
                                       bytes_written, chunk, timescale)
            pipe.prof_add("package_s", time.perf_counter() - tw)

        # --- consume side on the shared stage-decoupled executor: the
        # delegated encoders are stateful and order-sensitive per rung
        # (the pts contract above), which is exactly the executor's
        # per-rung-ordered guarantee; rungs encode concurrently and up
        # to VLOG_PIPELINE_DEPTH decoded batches stay in flight.
        rungs_by_name = {r.name: r for r in plan.rungs}

        def pull(name, batch):
            rung = rungs_by_name[name]
            by, bu, bv = batch.extra
            if (rung.height, rung.width) == (by.shape[1], by.shape[2]):
                return by, bu, bv
            n = by.shape[0]
            if grid is not None:
                col = grid.column_of(name)
                (by, bu, bv), _ = pad_batch(grid.data, by, bu, bv)
                pipe.note_pad_waste(n, by.shape[0])
                by, bu, bv = shard_frames(col.mesh, by, bu, bv)
            ry, ru, rv = resize_yuv420(by, bu, bv, rung.height,
                                       rung.width)
            return (np.asarray(ry)[:n], np.asarray(ru)[:n],
                    np.asarray(rv)[:n])

        def process(name, batch, host):
            rung = rungs_by_name[name]
            ry, ru, rv = host
            enc = encoders[name]
            te = time.perf_counter()
            for i in range(batch.n_real):
                fi = frame_idx[name]
                enc.send(ry[i], ru[i], rv[i],
                         force_key=(fi % frames_per_seg == 0))
                frame_idx[name] = fi + 1
                drain(rung, enc.receive())
            pipe.prof_add("entropy_s", time.perf_counter() - te)

        def on_batch_done(batch):
            # serialized + batch-ordered by the executor's contract
            nonlocal frames_done
            frames_done += batch.n_real
            if progress_cb is not None:
                progress_cb(frames_done, max(total, frames_done),
                            "av1 ladder")

        pipe = PipelineExecutor(
            [r.name for r in plan.rungs], pull=pull, process=process,
            on_batch_done=on_batch_done,
            host_pool=host_pool_for_run(),   # shared across slot executors
            prof=prof, name="vlog-pipe")

        try:
            while True:
                td = time.perf_counter()
                item = fifo.get()
                prof["decode_wait_s"] += time.perf_counter() - td
                if item is eof:
                    break
                if isinstance(item, BaseException):
                    raise item
                by, bu, bv = item
                if plan.thumbnail and thumb_path is None:
                    thumb_path = str(out / "thumbnail.jpg")
                    pipe.submit_aux(backend._write_thumbnail, by[0],
                                    bu[0], bv[0], thumb_path)
                pipe.reserve()
                pipe.submit(None, by.shape[0], extra=(by, bu, bv))
            pipe.drain()
            for rung in plan.rungs:
                drain(rung, encoders[rung.name].flush())
                if pending[rung.name]:
                    backend._write_segment(out, rung, tracks[rung.name],
                                           seg_counts, seg_durs,
                                           bytes_written,
                                           pending[rung.name], timescale)
                    pending[rung.name] = []
        finally:
            stop.set()
            while True:
                try:
                    fifo.get_nowait()
                except queue_mod.Empty:
                    break
            pipe.close()
            for enc in encoders.values():
                enc.close()
    finally:
        src.close()

    true_total = total if src.exact_seek else frames_done
    duration_s = true_total / fps if fps else 0.0
    results, variants = [], []
    for rung in plan.rungs:
        name = rung.name
        cstr = codec_string_from_tu(meta.get(name))
        playlist = hls.media_playlist(
            [hls.SegmentRef(uri=f"segment_{i + 1:05d}.m4s",
                            duration_s=seg_durs[name][i])
             for i in range(seg_counts[name])],
            target_duration_s=plan.segment_duration_s,
            init_uri="init.mp4")
        ppath = out / name / "playlist.m3u8"
        atomic_write_text(ppath, playlist)
        total_dur = sum(seg_durs[name])
        achieved = (int(bytes_written[name] * 8 / total_dur)
                    if total_dur else 0)
        results.append(RungResult(
            name=name, width=rung.width, height=rung.height,
            codec_string=cstr, segment_count=seg_counts[name],
            bytes_written=bytes_written[name], mean_psnr_y=None,
            achieved_bitrate=achieved, playlist_path=str(ppath),
            target_bitrate=rung.video_bitrate))
        variants.append(hls.VariantRef(
            name=name, uri=f"{name}/playlist.m3u8",
            bandwidth=max(achieved, 1), width=rung.width,
            height=rung.height, codecs=cstr, frame_rate=fps,
            audio_group=(f"aud{rung.audio_bitrate // 1000}"
                         if rung.audio_bitrate else "")))
    atomic_write_text(out / "master.m3u8", hls.master_playlist(variants))
    atomic_write_text(out / "manifest.mpd", hls.dash_manifest(
        variants, duration_s=duration_s,
        segment_duration_s=plan.segment_duration_s))
    return RunResult(
        rungs=results, frames_processed=frames_done,
        duration_s=duration_s, thumbnail_path=thumb_path,
        wall_s=time.monotonic() - t0, variants=variants, fps=fps,
        segment_duration_s=plan.segment_duration_s,
        stage_s={k: round(v, 3) for k, v in prof.items()} | pipe.gauges(),
        gop_len=frames_per_seg)
