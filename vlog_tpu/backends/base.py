"""Accelerator boundary: backend protocol, capability model, registry.

This is the keystone seam of the framework — the analog of the
reference's ``worker/hwaccel.py`` (detect_gpu_capabilities:412,
select_encoder:454, build_transcode_command:647). Where the reference
maps (codec, resolution) to an ffmpeg command line for NVENC/VAAPI/CPU,
here a :class:`Backend` maps a source + ladder to an executable plan and
runs it. Registering a new accelerator is one ``register_backend`` call;
the worker runtime, job gating, and APIs never import a concrete backend.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Protocol

from vlog_tpu import config
from vlog_tpu.media.probe import VideoInfo


@dataclass(frozen=True)
class Capabilities:
    """What an accelerator can do (reference: GPUCapabilities hwaccel.py:67
    + get_worker_capabilities:1050)."""

    backend: str                       # registry name, e.g. "jax"
    device_kind: str                   # "tpu" | "cpu" | "gpu"
    device_count: int
    codecs: tuple[str, ...]            # encodeable codecs
    decode_codecs: tuple[str, ...]     # decodeable codecs
    max_parallel_jobs: int = 1
    memory_bytes: int | None = None
    details: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "backend": self.backend,
            "device_kind": self.device_kind,
            "device_count": self.device_count,
            "codecs": list(self.codecs),
            "decode_codecs": list(self.decode_codecs),
            "max_parallel_jobs": self.max_parallel_jobs,
            "memory_bytes": self.memory_bytes,
            **self.details,
        }


@dataclass(frozen=True)
class PlannedRung:
    """One ladder rung with resolved output geometry."""

    name: str
    width: int
    height: int
    video_bitrate: int
    qp: int
    codec: str = "h264"
    audio_bitrate: int = 0     # paired AAC rendition rate (0 = video-only)


@dataclass
class ExecutionPlan:
    """Everything the backend needs to run one transcode job.

    The analog of the ffmpeg command lines built by
    build_cmaf_transcode_command (hwaccel.py:732) — but as data, so it can
    be inspected, checkpointed, and resumed.
    """

    source: VideoInfo
    rungs: tuple[PlannedRung, ...]
    out_dir: Path
    segment_duration_s: float = 6.0
    frame_batch: int = 8
    fps_num: int = 30
    fps_den: int = 1
    total_frames: int = 0
    streaming_format: str = "cmaf"     # "cmaf" (fMP4) for now
    thumbnail: bool = True
    # I+P chain length; 1 = all-intra. Always divides frames-per-segment
    # so every CMAF segment starts on an IDR.
    gop_len: int = 1
    # hls_ts mode: {audio_bitrate: (list_of_adts_frames, sample_rate)} —
    # classic HLS muxes audio INTO each variant's TS segments, so the
    # pipeline pre-encodes ADTS and the backend interleaves per segment.
    audio_adts: dict | None = None


@dataclass
class RungResult:
    name: str
    width: int
    height: int
    codec_string: str
    segment_count: int
    bytes_written: int
    # None = not measured this run (e.g. fully-resumed run encoded nothing),
    # never a fabricated 0.0.
    mean_psnr_y: float | None
    achieved_bitrate: int
    playlist_path: str
    target_bitrate: int = 0      # the ladder's ask; 0 = constant-QP run


@dataclass
class RunResult:
    rungs: list[RungResult]
    frames_processed: int
    duration_s: float
    thumbnail_path: str | None = None
    wall_s: float = 0.0
    # master-playlist variant refs (media.hls.VariantRef) so the pipeline
    # can re-emit manifests once audio renditions exist
    variants: list = field(default_factory=list)
    fps: float = 0.0
    segment_duration_s: float = 0.0
    # wall-clock accounting per pipeline stage (decode_wait_s /
    # compute_wait_s / device_pull_s / entropy_s / package_s): where the
    # e2e time went, so benches can report which stage bounds
    # throughput. compute_wait = block_until_ready on the async
    # dispatch (pure device compute); device_pull = np.asarray AFTER
    # readiness (pure device->host transfer). Each field is cumulative
    # BUSY seconds for its stage; since the stage-decoupled executor
    # (parallel/executor.py) runs rungs concurrently, busy sums can
    # exceed wall clock — the overlap gauges it adds (pipeline_depth,
    # max_in_flight, host_busy_s, host_wall_s, host_occupancy) say how
    # much actually overlapped.
    stage_s: dict = field(default_factory=dict)
    # chain length the run actually used (plan_for's segment-divisor
    # logic may pick a different value than config.GOP_LEN; 1 = intra)
    gop_len: int = 1
    # segments (summed across rungs) this run accepted from disk via
    # digest/structure-verified resume instead of re-encoding — the
    # bounded-loss accounting preemption-tolerant workers assert on
    # (vlog_resume_segments_skipped_total)
    resumed_segments: int = 0


# progress_cb(frames_done, frames_total, message)
ProgressFn = Callable[[int, int, str], None]


class Backend(Protocol):
    """Accelerator backend protocol (hwaccel.py:412-839 analog)."""

    name: str

    def detect(self) -> Capabilities: ...

    def plan(self, source: VideoInfo, rungs, out_dir: Path, **opts) -> ExecutionPlan: ...

    def run(self, plan: ExecutionPlan, progress_cb: ProgressFn | None = None,
            *, resume: bool = True) -> RunResult: ...


# --------------------------------------------------------------------------
# Registry (hwaccel.py:454 select_encoder analog)
# --------------------------------------------------------------------------

_REGISTRY: dict[str, Callable[[], Backend]] = {}


def register_backend(name: str, factory: Callable[[], Backend]) -> None:
    _REGISTRY[name] = factory


def available_backends() -> list[str]:
    return list(_REGISTRY)


def get_backend(name: str) -> Backend:
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown backend {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None
    return factory()


_SELECTED: Backend | None = None


def select_backend(preference: str | None = None) -> Backend:
    """Pick the best available backend.

    Preference order mirrors the reference's GPU-over-CPU encoder
    selection (hwaccel.py:454-481): explicit preference, then whichever
    registered backend reports TPU devices, then anything. The choice is
    cached per process — probing instantiates backends (and may open
    accelerators), which must happen once, not per job.
    """
    global _SELECTED
    if preference:
        return get_backend(preference)
    if _SELECTED is not None:
        return _SELECTED
    best = None
    for name in _REGISTRY:
        b = get_backend(name)
        try:
            caps = b.detect()
        except Exception:       # noqa: BLE001 — a broken backend is
            continue            # skipped, not fatal to selection
        if caps.device_kind == "tpu":
            _SELECTED = b
            return b
        if best is None:
            best = b
    if best is None:
        raise RuntimeError("no backends registered (or none detectable)")
    _SELECTED = best
    return best


def plan_rung_geometry(src_w: int, src_h: int, rung: config.QualityRung,
                      codec: str = "h264") -> PlannedRung:
    """Resolve output geometry for one rung: height from the ladder, width
    follows the source aspect ratio, rounded to even (mod-2, as the
    reference's scale filters do)."""
    h = min(rung.height, src_h if src_h % 2 == 0 else src_h - 1)
    h = h - (h % 2)
    w = round(src_w * h / src_h / 2) * 2 if src_h else h * 16 // 9
    return PlannedRung(
        name=rung.name, width=max(w, 2), height=max(h, 2),
        video_bitrate=rung.video_bitrate, qp=rung.base_qp, codec=codec,
        audio_bitrate=getattr(rung, "audio_bitrate", 0),
    )
