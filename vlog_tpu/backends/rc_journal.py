"""Persistent rate-control journal: byte-identical mid-stream resume.

The ladder's output bytes after segment K depend on more than the
pixels: the rate controllers carry cross-segment state (per-QP rate
estimates, the debt integral, proxy calibration) and the pipeline
applies their observations on a fixed lag schedule
(parallel/executor.py LaggedRateControl). A resumed run that restarts
the controllers cold therefore re-encodes the remaining segments with
*different* QP plans — valid output, but not the bytes the
uninterrupted run would have produced, which breaks the cross-worker
hand-off contract (a successor must continue the tree the manifest
digests already describe).

This journal closes that gap. The backend appends one canonical JSON
line per *dispatch batch* recording exactly what each rung's consumer
posted to the rate controller (achieved bytes, frame count, the plan-QP
mix, the device bit-proxy cost sum). On resume,
``LaggedRateControl.replay`` re-runs the dispatch schedule against the
journal — same lag, same hunting drains — so the controllers reach the
exact state the original run had when planning the first resumed batch,
and every subsequent segment encodes byte-identically.

Canonical format (order-independent of consumer-thread interleaving —
the file itself must be byte-reproducible so published trees stay
digest-comparable):

- line 1: the header — run parameters that must match for a replay to
  be meaningful (batch size, pipeline depth, frames per segment, GOP
  length, rung names, encoder config tag). A mismatch (config changed
  between runs) discards the journal and the run restarts cold, which
  is still deterministic.
- line N+2: batch N's observations for every rung, written only once
  ALL rungs have posted for that batch, rung keys sorted.

A torn tail line (host died mid-append) is detected by the JSON parse
and dropped; the contiguous prefix is what resume may use. The journal
rides the output tree, so the remote streaming uploader ships it with
the segments and a successor on a different machine can prefetch it.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path

# Canonical name lives with the manifest-exclusion rule: the journal is
# run state (depth/mesh-shaped bytes), never a published artifact, so
# build_manifest skips it and tree byte-identity contracts ignore it.
from vlog_tpu.storage.integrity import RC_JOURNAL_NAME

__all__ = ["RC_JOURNAL_NAME", "RCJournal", "aligned_resume_point",
           "load_journal", "make_header"]


def make_header(*, batch_n: int, depth: int, frames_per_seg: int,
                gop_len: int, rungs: list[str], tag: str) -> dict:
    """The run-parameter fingerprint a resume must match exactly.

    ``origin_frame`` 0 marks the original timeline; a legacy
    (non-batch-aligned) resume stamps the frame it restarted from, so a
    later resume can never replay its entries as if they were the
    uninterrupted run's."""
    return {"v": 1, "batch_n": int(batch_n), "depth": int(depth),
            "frames_per_seg": int(frames_per_seg), "gop_len": int(gop_len),
            "rungs": list(rungs), "tag": tag, "origin_frame": 0}


def _dump(obj: dict) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class RCJournal:
    """Append-side of the journal (one per run; consumer threads call
    :meth:`record`, batches flush in index order once complete)."""

    def __init__(self, path: Path, header: dict, *, keep_batches: int = 0):
        self.path = Path(path)
        self.header = header
        # a resumed run's pipeline re-indexes batches from 0; the
        # journal keeps the ORIGINAL timeline so a third resume (or a
        # digest comparison against an uninterrupted run) lines up
        self.index_offset = int(keep_batches)
        self._lock = threading.Lock()             # lock-order: 60
        # out-of-order completion buffer: batch index -> {rung: obs}
        self._buf: dict[int, dict] = {}          # guarded-by: _lock
        self._next = int(keep_batches)           # guarded-by: _lock
        self._fp = None                          # guarded-by: _lock
        self._rewrite(keep_batches)

    def _rewrite(self, keep_batches: int) -> None:
        """Start (or truncate) the on-disk journal: header plus the
        replayed prefix — entries past the resume point belong to a
        timeline the resumed run is about to re-encode."""
        prefix: list[str] = []
        if keep_batches > 0:
            loaded = load_journal(self.path)
            if loaded is not None and loaded[0] == self.header:
                entries = loaded[1]
                for k in range(keep_batches):
                    prefix.append(_dump({"k": k, "obs": entries[k]}))
        tmp = self.path.with_suffix(".jsonl.tmp")
        with open(tmp, "w") as fp:
            fp.write(_dump(self.header) + "\n")
            for line in prefix:
                fp.write(line + "\n")
        tmp.rename(self.path)

    def record(self, batch_index: int, rung: str, *, nbytes: int,
               frames: int, qps, cost: float | None) -> None:
        """Mirror one ``LaggedRateControl.post`` call (consumer thread).
        ``qps`` is the plan-QP mix array/list or None."""
        obs = {"bytes": int(nbytes), "frames": int(frames),
               "qps": None if qps is None else [int(q) for q in qps],
               "cost": None if cost is None else float(cost)}
        want = set(self.header["rungs"])
        batch_index += self.index_offset
        with self._lock:
            if batch_index < self._next:
                return          # replayed prefix: already on disk
            self._buf.setdefault(batch_index, {})[rung] = obs
            # The append stays under the lock on purpose: the file's
            # byte-reproducibility contract is "lines in batch-index
            # order", and the only thing serializing competing consumer
            # threads' drains IS this lock. Lines are ~100 buffered
            # bytes — the hold is microseconds, and the alternative (a
            # second writer lock held across the same write) is the
            # same blocking with more states.
            while set(self._buf.get(self._next, ())) >= want:
                line = _dump({"k": self._next,
                              "obs": self._buf.pop(self._next)})
                if self._fp is None:
                    self._fp = open(self.path, "a")    # holds-ok: canonical append order needs the drain serialized
                self._fp.write(line + "\n")            # holds-ok: canonical append order needs the drain serialized
                self._fp.flush()                       # holds-ok: canonical append order needs the drain serialized
                self._next += 1

    def close(self) -> None:
        with self._lock:
            if self._fp is not None:
                self._fp.close()
                self._fp = None


def _clean_entry(obj) -> tuple[int, dict] | None:
    """Shape-validate one batch line; None rejects it (corrupt journals
    must degrade to a shorter replayable prefix / cold restart, never
    crash the resumed attempt — the prefetch path deliberately skips
    digest verification on the strength of this parser)."""
    if not isinstance(obj, dict) or not isinstance(obj.get("k"), int) \
            or not isinstance(obj.get("obs"), dict):
        return None
    for rung, ob in obj["obs"].items():
        if not isinstance(rung, str) or not isinstance(ob, dict):
            return None
        if not isinstance(ob.get("bytes"), int) \
                or not isinstance(ob.get("frames"), int):
            return None
        if ob.get("qps") is not None and not isinstance(ob["qps"], list):
            return None
        if ob.get("cost") is not None \
                and not isinstance(ob["cost"], (int, float)):
            return None
    return obj["k"], obj["obs"]


def load_journal(path: Path) -> tuple[dict, dict[int, dict]] | None:
    """Parse a journal: ``(header, {batch_index: {rung: obs}})`` or None.
    A torn/garbled/malformed tail is dropped; only lines before it count."""
    path = Path(path)
    if not path.is_file():
        return None
    header: dict | None = None
    entries: dict[int, dict] = {}
    try:
        with open(path) as fp:
            for line in fp:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    break       # torn tail: stop at the last clean line
                if header is None:
                    if not isinstance(obj, dict) or obj.get("v") != 1:
                        return None
                    header = obj
                else:
                    cleaned = _clean_entry(obj)
                    if cleaned is None:
                        break   # malformed tail: same verdict as torn
                    entries[cleaned[0]] = cleaned[1]
    except OSError:
        return None
    if header is None:
        return None
    return header, entries


def aligned_resume_point(start_segment: int, *, frames_per_seg: int,
                         batch_n: int, entries: dict[int, dict],
                         rungs: list[str]) -> tuple[int, int]:
    """Clamp a segment-scan resume candidate to the nearest point the
    journal can actually replay: the resume frame must sit on BOTH a
    segment and a dispatch-batch boundary (the controllers' state is
    only well-defined between batches), and the journal must hold a
    complete observation record for every prior batch. Returns
    ``(start_segment, start_batch)``; ``(0, 0)`` restarts cold."""
    want = set(rungs)
    # contiguous complete journal prefix, in batches
    complete = 0
    while set(entries.get(complete, ())) >= want:
        complete += 1
    while start_segment > 0:
        frames = start_segment * frames_per_seg
        if frames % batch_n == 0 and frames // batch_n <= complete:
            return start_segment, frames // batch_n
        start_segment -= 1
    return 0, 0
