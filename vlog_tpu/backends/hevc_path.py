"""HEVC ladder execution path (codec="h265" re-encodes).

The H.264 path runs a fused all-rungs XLA ladder program
(parallel/ladder.py); this HEVC path trades that last fusion step for
simplicity: per batch it resizes on device (matmul lanczos,
ops/resize.py), runs the HEVC DSP (codecs/hevc/jax_core.py — I+P
chains when the plan's GOP mode asks for them, intra otherwise; one
dispatch per rung per chain), and entropy-codes on the host,
overlapping decode with a one-batch prefetch thread. Segments,
playlists, and manifests come out identical in shape to the H.264 path
(hvc1 sample entries, hvc1.* CODECS strings), so the whole product
plane — players, resume validation, re-encode flips — works unchanged.

Reference parity: reencode_worker.py codec upgrades via hevc_nvenc /
hevc_vaapi (worker/hwaccel.py:509-552).
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from pathlib import Path

import numpy as np

from vlog_tpu import config
from vlog_tpu.backends.base import RungResult, RunResult
from vlog_tpu.utils.fsio import prepare_init_segment
from vlog_tpu.backends.rate_control import RateController
from vlog_tpu.backends.source import open_source
from vlog_tpu.codecs.hevc.api import HevcEncoder
from vlog_tpu.media import hls
from vlog_tpu.media.fmp4 import (
    Sample,
    TrackConfig,
    hvc1_sample_entry,
    init_segment,
)
from vlog_tpu.utils.fsio import atomic_write_bytes, atomic_write_text


def run_hevc(backend, plan, progress_cb, resume: bool, t0: float
             ) -> RunResult:
    if plan.streaming_format != "cmaf":
        raise ValueError("h265 output is CMAF-only (hls_ts carries H.264)")
    out = Path(plan.out_dir)
    out.mkdir(parents=True, exist_ok=True)
    fps = plan.fps_num / plan.fps_den
    frames_per_seg = max(1, round(plan.segment_duration_s * fps))
    timescale = plan.fps_num * 1000
    frame_dur = plan.fps_den * 1000

    encoders: dict[str, HevcEncoder] = {}
    tracks: dict[str, TrackConfig] = {}
    seg_counts: dict[str, int] = {}
    seg_durs: dict[str, list[float]] = {}
    bytes_written: dict[str, int] = {}
    psnr_acc: dict[str, list[float]] = {}
    init_matched: dict[str, bool] = {}
    for rung in plan.rungs:
        enc = HevcEncoder(width=rung.width, height=rung.height,
                          fps_num=plan.fps_num, fps_den=plan.fps_den,
                          qp=rung.qp)
        encoders[rung.name] = enc
        tracks[rung.name] = TrackConfig(
            track_id=1, handler="vide", timescale=timescale,
            sample_entry=hvc1_sample_entry(rung.width, rung.height,
                                           enc.hvcc_config),
            width=rung.width, height=rung.height)
        rdir = out / rung.name
        rdir.mkdir(parents=True, exist_ok=True)
        init_matched[rung.name] = prepare_init_segment(
            rdir, init_segment(tracks[rung.name]),
            config_tag=(f"hevc:partitions={int(config.HEVC_PARTITIONS)}"
                        f":gop={plan.gop_len}"))
        seg_counts[rung.name] = 0
        seg_durs[rung.name] = []
        bytes_written[rung.name] = 0
        psnr_acc[rung.name] = []

    src = open_source(plan.source.path)
    try:
        total = src.frame_count
        start_segment = 0
        if resume and src.exact_seek:
            start_segment = backend._resume_scan(plan, out, timescale,
                                                 seg_counts, seg_durs,
                                                 bytes_written,
                                                 init_matched)
        start_frame = start_segment * frames_per_seg

        import jax

        from vlog_tpu.parallel.compile_cache import ensure_compile_cache
        from vlog_tpu.parallel.executor import (LaggedRateControl,
                                                PipelineExecutor)
        from vlog_tpu.parallel.hevc_ladder import hevc_chain_ladder_grid
        from vlog_tpu.parallel.scheduler import (grid_for_run,
                                                 host_pool_for_run)

        ensure_compile_cache()

        # closed-loop VBR toward each rung's ladder bitrate, same
        # controller the H.264 path uses (per-frame QP is traced, so
        # stepping never recompiles)
        controllers = {
            r.name: RateController(target_bps=r.video_bitrate, fps=fps,
                                   init_qp=r.qp)
            for r in plan.rungs
        }
        pending: dict[str, list[Sample]] = {r.name: [] for r in plan.rungs}
        frames_done = start_frame
        thumb_path = None
        # same five stage fields as the H.264 path (cumulative busy
        # seconds), plus the executor's overlap gauges at the end
        prof = {"decode_wait_s": 0.0, "compute_wait_s": 0.0,
                "device_pull_s": 0.0, "entropy_s": 0.0, "package_s": 0.0}

        # one-batch decode prefetch (same shape as the H.264 loop)
        fifo: queue_mod.Queue = queue_mod.Queue(maxsize=1)
        eof = object()
        stop = threading.Event()

        # --- fused all-rungs chain ladder (parallel/hevc_ladder.py): one
        # dispatch per batch emits every hvc1 rung; over >1 device the
        # ladder lays out as a 2-D (data × rung) grid — chains shard the
        # data axis, rung columns split the ladder (SURVEY §2d.2/§2d.5
        # applied to HEVC). grid_for_run() resolves the shape over the
        # job's slot devices (all devices without a lease); batch math
        # keys off the DATA-axis width only, keeping batches (and trees)
        # identical across grid shapes.
        src_h, src_w = plan.source.height, plan.source.width
        rungs_spec = tuple((r.name, r.height, r.width, r.qp)
                           for r in plan.rungs)
        clen = max(1, plan.gop_len)
        hint = max(1, -(-plan.frame_batch // clen))
        grid = grid_for_run(rungs_spec, batch_hint=hint)
        prog = hevc_chain_ladder_grid(
            rungs_spec, src_h, src_w,
            search=config.MOTION_SEARCH_RADIUS, grid=grid,
            deblock=config.HEVC_DEBLOCK)
        chains_per = max(prog.data, hint + (-hint) % prog.data)
        batch_n = clen * chains_per
        npix = {r.name: r.height * r.width for r in plan.rungs}
        rows_cols = {r.name: ((r.height + 31) // 32, (r.width + 31) // 32)
                     for r in plan.rungs}

        def producer() -> None:
            try:
                for item in src.read_batches(batch_n, start_frame):
                    while not stop.is_set():
                        try:
                            fifo.put(item, timeout=0.5)
                            break
                        except queue_mod.Full:
                            continue
                    if stop.is_set():
                        return
                fifo.put(eof)
            except BaseException as exc:  # noqa: BLE001 — relayed below
                fifo.put(exc)

        threading.Thread(target=producer, daemon=True,
                         name="vlog-hevc-decode").start()

        def dispatch(by, bu, bv):
            n_real = by.shape[0]
            if n_real < batch_n:   # tail: replicate last frame, drop later
                reps = batch_n - n_real
                by = np.concatenate([by, np.repeat(by[-1:], reps, axis=0)])
                bu = np.concatenate([bu, np.repeat(bu[-1:], reps, axis=0)])
                bv = np.concatenate([bv, np.repeat(bv[-1:], reps, axis=0)])
            pipe.note_pad_waste(n_real, batch_n)
            chain = lambda p: p.reshape((chains_per, clen) + p.shape[1:])
            by, bu, bv = chain(by), chain(bu), chain(bv)
            qps = {}
            for r in plan.rungs:
                q = controllers[r.name].frame_qps(
                    chains_per * clen).reshape(chains_per, clen)
                qps[r.name] = q       # the program applies the I -2 anchor
            rc = {r.name: controllers[r.name].device_rc_params()
                  for r in plan.rungs}
            # per-column staging: frames replicate along the rung axis,
            # each rung's outputs stay on its owning column for the pull
            return prog.dispatch(by, bu, bv, qps, rc), n_real, qps

        # --- stage-decoupled consume side: the same PipelineExecutor
        # the H.264 path uses (per-rung ordered threads, shared host
        # pool, VLOG_PIPELINE_DEPTH batches in flight, deterministic
        # lag-applied rate feedback).
        rungs_by_name = {r.name: r for r in plan.rungs}
        rc = LaggedRateControl(controllers)

        def wait_device(batch):
            jax.block_until_ready(batch.outs)

        def pull(name, batch):
            ro = batch.outs[name]
            return {k: np.asarray(ro[k]) for k in
                    ("i_luma", "i_cb", "i_cr", "p_luma", "p_cb",
                     "p_cr", "mv", "sse_y", "qp_eff", "cost")}

        def process(name, batch, host):
            rung = rungs_by_name[name]
            rows, cols = rows_cols[name]
            n_real = batch.n_real
            te = time.perf_counter()
            sse = host["sse_y"]                      # (nc, clen)
            plan_q = np.asarray(batch.qps[name])
            # the QPs the device ACTUALLY encoded at (plan + in-chain
            # adjustment) — slice headers must signal these; the
            # controller still attributes to PLAN (cascade outer loop)
            qarr = host["qp_eff"]
            cost = host["cost"]
            batch_bytes = 0
            n_frames = 0
            cost_sum = 0.0
            rc_qs = []   # plan working-point dither (the HEVC
            #              program applies its I -2 anchor internally)
            for ci in range(chains_per):
                base = ci * clen
                if base >= n_real:
                    break
                keep = min(clen, n_real - base)
                rc_qs.append(plan_q[ci, :keep])
                cost_sum += float(cost[ci, :keep].sum())
                mse = np.maximum(sse[ci, :keep] / npix[name], 1e-12)
                psnrs = np.where(mse < 1e-9, 99.0,
                                 10 * np.log10(255.0 ** 2 / mse))
                frames = encoders[name].entropy_chain(
                    (host["i_luma"][ci], host["i_cb"][ci],
                     host["i_cr"][ci]),
                    (host["p_luma"][ci], host["p_cb"][ci],
                     host["p_cr"][ci]) if clen > 1 else None,
                    None, None,
                    host["mv"][ci] if clen > 1 else None,
                    qarr[ci], rows, cols, psnrs,
                    t_real=keep, pool=pipe.host_pool)
                for f in frames:
                    psnr_acc[name].append(f.psnr_y)
                    pending[name].append(
                        Sample(data=f.sample, duration=frame_dur,
                               is_sync=f.is_idr))
                    batch_bytes += len(f.sample)
                n_frames += keep
            rc.post(name, batch.index, nbytes=batch_bytes,
                    frames=max(n_frames, 1),
                    frame_qps=(np.concatenate(rc_qs) if rc_qs else None),
                    cost=cost_sum)
            pipe.prof_add("entropy_s", time.perf_counter() - te)
            tw = time.perf_counter()
            while len(pending[name]) >= frames_per_seg:
                chunk = pending[name][:frames_per_seg]
                pending[name] = pending[name][frames_per_seg:]
                backend._write_segment(out, rung, tracks[name],
                                       seg_counts, seg_durs,
                                       bytes_written, chunk,
                                       timescale)
            pipe.prof_add("package_s", time.perf_counter() - tw)

        def on_batch_done(batch):
            # serialized + batch-ordered by the executor's contract
            nonlocal frames_done
            frames_done += batch.n_real
            if progress_cb is not None:
                progress_cb(frames_done, total, "hevc ladder")

        pipe = PipelineExecutor(
            [r.name for r in plan.rungs], pull=pull, process=process,
            ready=wait_device, on_batch_done=on_batch_done,
            host_pool=host_pool_for_run(),   # shared across slot executors
            prof=prof, name="vlog-pipe")

        batch_idx = 0
        try:
            while True:
                td = time.perf_counter()
                item = fifo.get()
                prof["decode_wait_s"] += time.perf_counter() - td
                if item is eof:
                    break
                if isinstance(item, BaseException):
                    raise item
                by, bu, bv = item
                if plan.thumbnail and thumb_path is None:
                    thumb_path = str(out / "thumbnail.jpg")
                    pipe.submit_aux(backend._write_thumbnail, by[0],
                                    bu[0], bv[0], thumb_path)
                # backpressure before planning, then deterministic lagged
                # feedback — same schedule as jax_backend
                pipe.reserve()
                rc.apply_upto(batch_idx - pipe.depth)
                outs, n_real, qps = dispatch(by, bu, bv)
                pipe.submit(outs, n_real, qps)
                batch_idx += 1
                if rc.hunting():
                    # calibration/cliff hunt: drain to depth 0 so
                    # corrections land before the next batch stages
                    # (same shape as jax_backend)
                    pipe.drain()
                    rc.apply_upto(batch_idx - 1)
            pipe.drain()
            for rung in plan.rungs:
                if pending[rung.name]:
                    backend._write_segment(out, rung, tracks[rung.name],
                                           seg_counts, seg_durs,
                                           bytes_written,
                                           pending[rung.name], timescale)
                    pending[rung.name] = []
        finally:
            stop.set()
            while True:
                try:
                    fifo.get_nowait()
                except queue_mod.Empty:
                    break
            pipe.close()
    finally:
        src.close()

    true_total = total if src.exact_seek else frames_done
    duration_s = true_total / fps if fps else 0.0
    results = []
    variants = []
    for rung in plan.rungs:
        name = rung.name
        enc = encoders[name]
        playlist = hls.media_playlist(
            [hls.SegmentRef(uri=f"segment_{i + 1:05d}.m4s",
                            duration_s=seg_durs[name][i])
             for i in range(seg_counts[name])],
            target_duration_s=plan.segment_duration_s,
            init_uri="init.mp4")
        ppath = out / name / "playlist.m3u8"
        atomic_write_text(ppath, playlist)
        total_dur = sum(seg_durs[name])
        achieved = int(bytes_written[name] * 8 / total_dur) if total_dur else 0
        results.append(RungResult(
            name=name, width=rung.width, height=rung.height,
            codec_string=enc.codec_string,
            segment_count=seg_counts[name],
            bytes_written=bytes_written[name],
            mean_psnr_y=(float(np.mean(psnr_acc[name]))
                         if psnr_acc[name] else None),
            achieved_bitrate=achieved,
            playlist_path=str(ppath),
            target_bitrate=rung.video_bitrate))
        variants.append(hls.VariantRef(
            name=name, uri=f"{name}/playlist.m3u8",
            bandwidth=max(achieved, 1),
            width=rung.width, height=rung.height,
            codecs=enc.codec_string, frame_rate=fps,
            audio_group=(f"aud{rung.audio_bitrate // 1000}"
                         if rung.audio_bitrate else "")))
    atomic_write_text(out / "master.m3u8", hls.master_playlist(variants))
    atomic_write_text(out / "manifest.mpd", hls.dash_manifest(
        variants, duration_s=duration_s,
        segment_duration_s=plan.segment_duration_s))

    return RunResult(
        rungs=results, frames_processed=frames_done, duration_s=duration_s,
        thumbnail_path=thumb_path, wall_s=time.monotonic() - t0,
        variants=variants, fps=fps,
        segment_duration_s=plan.segment_duration_s,
        stage_s={k: round(v, 3) for k, v in prof.items()} | pipe.gauges(),
        gop_len=plan.gop_len,
        resumed_segments=start_segment * len(plan.rungs))
