"""HEVC ladder execution path (codec="h265" re-encodes).

The H.264 path runs a fused all-rungs XLA ladder program
(parallel/ladder.py); this HEVC path trades that last fusion step for
simplicity: per batch it resizes on device (matmul lanczos,
ops/resize.py), runs the HEVC DSP (codecs/hevc/jax_core.py — I+P
chains when the plan's GOP mode asks for them, intra otherwise; one
dispatch per rung per chain), and entropy-codes on the host,
overlapping decode with a one-batch prefetch thread. Segments,
playlists, and manifests come out identical in shape to the H.264 path
(hvc1 sample entries, hvc1.* CODECS strings), so the whole product
plane — players, resume validation, re-encode flips — works unchanged.

Reference parity: reencode_worker.py codec upgrades via hevc_nvenc /
hevc_vaapi (worker/hwaccel.py:509-552).
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from pathlib import Path

import numpy as np

from vlog_tpu import config
from vlog_tpu.backends.base import RungResult, RunResult
from vlog_tpu.utils.fsio import prepare_init_segment
from vlog_tpu.backends.rate_control import RateController
from vlog_tpu.backends.source import open_source
from vlog_tpu.codecs.hevc.api import HevcEncoder
from vlog_tpu.media import hls
from vlog_tpu.media.fmp4 import (
    Sample,
    TrackConfig,
    hvc1_sample_entry,
    init_segment,
)
from vlog_tpu.utils.fsio import atomic_write_bytes, atomic_write_text


def run_hevc(backend, plan, progress_cb, resume: bool, t0: float
             ) -> RunResult:
    if plan.streaming_format != "cmaf":
        raise ValueError("h265 output is CMAF-only (hls_ts carries H.264)")
    out = Path(plan.out_dir)
    out.mkdir(parents=True, exist_ok=True)
    fps = plan.fps_num / plan.fps_den
    frames_per_seg = max(1, round(plan.segment_duration_s * fps))
    timescale = plan.fps_num * 1000
    frame_dur = plan.fps_den * 1000

    encoders: dict[str, HevcEncoder] = {}
    tracks: dict[str, TrackConfig] = {}
    seg_counts: dict[str, int] = {}
    seg_durs: dict[str, list[float]] = {}
    bytes_written: dict[str, int] = {}
    psnr_acc: dict[str, list[float]] = {}
    init_matched: dict[str, bool] = {}
    for rung in plan.rungs:
        enc = HevcEncoder(width=rung.width, height=rung.height,
                          fps_num=plan.fps_num, fps_den=plan.fps_den,
                          qp=rung.qp)
        encoders[rung.name] = enc
        tracks[rung.name] = TrackConfig(
            track_id=1, handler="vide", timescale=timescale,
            sample_entry=hvc1_sample_entry(rung.width, rung.height,
                                           enc.hvcc_config),
            width=rung.width, height=rung.height)
        rdir = out / rung.name
        rdir.mkdir(parents=True, exist_ok=True)
        init_matched[rung.name] = prepare_init_segment(
            rdir, init_segment(tracks[rung.name]))
        seg_counts[rung.name] = 0
        seg_durs[rung.name] = []
        bytes_written[rung.name] = 0
        psnr_acc[rung.name] = []

    src = open_source(plan.source.path)
    try:
        total = src.frame_count
        start_segment = 0
        if resume and src.exact_seek:
            start_segment = backend._resume_scan(plan, out, timescale,
                                                 seg_counts, seg_durs,
                                                 bytes_written,
                                                 init_matched)
        start_frame = start_segment * frames_per_seg

        from concurrent.futures import ThreadPoolExecutor

        from vlog_tpu.ops.resize import resize_yuv420

        # one long-lived entropy pool shared by every (rung, batch) call
        # — per-call pools would churn threads (same reason as the H.264
        # loop's pool)
        entropy_pool = ThreadPoolExecutor(max_workers=8)
        # closed-loop VBR toward each rung's ladder bitrate, same
        # controller the H.264 path uses (per-frame QP is traced, so
        # stepping never recompiles)
        controllers = {
            r.name: RateController(target_bps=r.video_bitrate, fps=fps,
                                   init_qp=r.qp)
            for r in plan.rungs
        }
        pending: dict[str, list[Sample]] = {r.name: [] for r in plan.rungs}
        frames_done = start_frame
        thumb_path = None

        # one-batch decode prefetch (same shape as the H.264 loop)
        fifo: queue_mod.Queue = queue_mod.Queue(maxsize=1)
        eof = object()
        stop = threading.Event()

        # chain-aligned batches: segments are gop_len multiples, so each
        # batch holds whole chains (the last may be short at EOF)
        clen = max(1, plan.gop_len)
        batch_n = clen * max(1, plan.frame_batch // clen)

        def producer() -> None:
            try:
                for item in src.read_batches(batch_n, start_frame):
                    while not stop.is_set():
                        try:
                            fifo.put(item, timeout=0.5)
                            break
                        except queue_mod.Full:
                            continue
                    if stop.is_set():
                        return
                fifo.put(eof)
            except BaseException as exc:  # noqa: BLE001 — relayed below
                fifo.put(exc)

        threading.Thread(target=producer, daemon=True,
                         name="vlog-hevc-decode").start()

        try:
            while True:
                item = fifo.get()
                if item is eof:
                    break
                if isinstance(item, BaseException):
                    raise item
                by, bu, bv = item
                if plan.thumbnail and thumb_path is None:
                    thumb_path = str(out / "thumbnail.jpg")
                    backend._write_thumbnail(by[0], bu[0], bv[0], thumb_path)
                for rung in plan.rungs:
                    if (rung.height, rung.width) == (by.shape[1],
                                                     by.shape[2]):
                        ry, ru, rv = by, bu, bv
                    else:
                        ry, ru, rv = resize_yuv420(by, bu, bv, rung.height,
                                                   rung.width)
                        ry, ru, rv = (np.asarray(ry), np.asarray(ru),
                                      np.asarray(rv))
                    enc = encoders[rung.name]
                    enc.qp = controllers[rung.name].qp
                    # dithered integer QPs realizing the controller's
                    # fractional working point, so observe() is keyed to
                    # what was actually encoded (per-frame slice_qp_delta)
                    qps = controllers[rung.name].frame_qps(ry.shape[0])
                    if clen > 1:
                        frames = []
                        for c0 in range(0, ry.shape[0], clen):
                            frames.extend(enc.encode_chain(
                                ry[c0:c0 + clen], ru[c0:c0 + clen],
                                rv[c0:c0 + clen], pool=entropy_pool,
                                search=config.MOTION_SEARCH_RADIUS,
                                chain_len=clen,
                                frame_qps=qps[c0:c0 + clen]))
                    else:
                        frames = enc.encode_batch(ry, ru, rv,
                                                  pool=entropy_pool,
                                                  frame_qps=qps)
                    controllers[rung.name].observe(
                        sum(len(f.sample) for f in frames), len(frames))
                    for f in frames:
                        psnr_acc[rung.name].append(f.psnr_y)
                        pending[rung.name].append(
                            Sample(data=f.sample, duration=frame_dur,
                                   is_sync=f.is_idr))
                    while len(pending[rung.name]) >= frames_per_seg:
                        chunk = pending[rung.name][:frames_per_seg]
                        pending[rung.name] = pending[rung.name][
                            frames_per_seg:]
                        backend._write_segment(out, rung, tracks[rung.name],
                                               seg_counts, seg_durs,
                                               bytes_written, chunk,
                                               timescale)
                frames_done += by.shape[0]
                if progress_cb is not None:
                    progress_cb(frames_done, total, "hevc ladder")
            for rung in plan.rungs:
                if pending[rung.name]:
                    backend._write_segment(out, rung, tracks[rung.name],
                                           seg_counts, seg_durs,
                                           bytes_written,
                                           pending[rung.name], timescale)
                    pending[rung.name] = []
        finally:
            stop.set()
            while True:
                try:
                    fifo.get_nowait()
                except queue_mod.Empty:
                    break
            entropy_pool.shutdown(wait=True)
    finally:
        src.close()

    true_total = total if src.exact_seek else frames_done
    duration_s = true_total / fps if fps else 0.0
    results = []
    variants = []
    for rung in plan.rungs:
        name = rung.name
        enc = encoders[name]
        playlist = hls.media_playlist(
            [hls.SegmentRef(uri=f"segment_{i + 1:05d}.m4s",
                            duration_s=seg_durs[name][i])
             for i in range(seg_counts[name])],
            target_duration_s=plan.segment_duration_s,
            init_uri="init.mp4")
        ppath = out / name / "playlist.m3u8"
        atomic_write_text(ppath, playlist)
        total_dur = sum(seg_durs[name])
        achieved = int(bytes_written[name] * 8 / total_dur) if total_dur else 0
        results.append(RungResult(
            name=name, width=rung.width, height=rung.height,
            codec_string=enc.codec_string,
            segment_count=seg_counts[name],
            bytes_written=bytes_written[name],
            mean_psnr_y=(float(np.mean(psnr_acc[name]))
                         if psnr_acc[name] else None),
            achieved_bitrate=achieved,
            playlist_path=str(ppath),
            target_bitrate=rung.video_bitrate))
        variants.append(hls.VariantRef(
            name=name, uri=f"{name}/playlist.m3u8",
            bandwidth=max(achieved, 1),
            width=rung.width, height=rung.height,
            codecs=enc.codec_string, frame_rate=fps,
            audio_group=(f"aud{rung.audio_bitrate // 1000}"
                         if rung.audio_bitrate else "")))
    atomic_write_text(out / "master.m3u8", hls.master_playlist(variants))
    atomic_write_text(out / "manifest.mpd", hls.dash_manifest(
        variants, duration_s=duration_s,
        segment_duration_s=plan.segment_duration_s))

    return RunResult(
        rungs=results, frames_processed=frames_done, duration_s=duration_s,
        thumbnail_path=thumb_path, wall_s=time.monotonic() - t0,
        variants=variants, fps=fps,
        segment_duration_s=plan.segment_duration_s,
        gop_len=plan.gop_len)
