"""SLO plane: declarative objectives evaluated as multi-window burn rates.

The metrics plane (obs/metrics.py) records everything and interprets
nothing. This module is the Monarch-style rollup on top: a small,
declarative registry of service-level objectives — one per plane's
user-visible promise — each evaluated as an **error ratio** (fraction
of events outside the objective's threshold) over two sliding windows,
and turned into a **burn rate** (error ratio over the error budget
``1 - target``). A burn rate of 1.0 means the plane is spending its
budget exactly at the sustainable rate; an objective *alerts* while
both windows burn at or above ``VLOG_SLO_BURN_ALERT`` — the classic
multi-window multi-burn rule, so a 10-second blip (fast window only)
and a slow background bleed (slow window only) both stay quiet while a
sustained acute burn pages.

Three source kinds cover every objective without new instrumentation:

- ``histogram`` — a cumulative runtime-registry histogram. Good events
  are observations at or under the threshold (read from the bucket
  counts; the threshold snaps to the nearest bucket bound at or above
  the requested value). Windowing comes from a bounded ring of
  cumulative snapshots taken at each evaluation tick.
- ``counter`` — a labeled runtime-registry counter where some label
  values are failures (e.g. ``vlog_delivery_requests_total`` outcome
  ``shed``). Same snapshot-delta windowing.
- ``span`` — named ``job_spans`` rows (obs/store.py), windowed directly
  in SQL over ``started_at``. Span objectives are also the exemplar
  source: rows over the threshold carry a ``trace_id`` that resolves
  through ``GET /api/jobs/{id}/trace``, so a burning objective links
  straight to the waterfall of a job that burned it.

Evaluation results are exported as the ``vlog_slo_*`` gauge families,
served by ``GET /api/slo`` (admin + worker APIs), and read back by the
fleet autoscale signal: :func:`alerting_objectives` is sync and cheap,
and ``jobs/qos.fleet_snapshot`` floors the scale hint at +1 while any
objective alerts (a burning SLO means the fleet is behind even if the
instantaneous backlog looks small).

Everything here is best-effort observability: evaluation never raises
into callers, the exemplar ring is bounded (``VLOG_SLO_EXEMPLARS``),
and the snapshot ring is pruned past the slow window.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from vlog_tpu import config

log = logging.getLogger("vlog_tpu.slo")

WINDOWS = ("fast", "slow")


def _window_s(window: str) -> float:
    return (config.SLO_FAST_WINDOW_S if window == "fast"
            else config.SLO_SLOW_WINDOW_S)


@dataclass(frozen=True)
class Objective:
    """One declarative objective. ``target`` is the good-event fraction
    the plane promises (error budget = ``1 - target``)."""

    name: str                 # e.g. "jobs.queue_wait" (stable label)
    plane: str                # jobs | asr | delivery | ...
    description: str
    target: float             # e.g. 0.99
    kind: str                 # histogram | counter | gauge | span
    family: str = ""          # runtime() attribute (histogram/counter/gauge)
    threshold_s: float = 0.0  # latency bound (histogram/span kinds)
    bad_values: tuple[str, ...] = ()   # failing label values (counter kind)
    low: float | None = None  # gauge kind: bad while sampled value < low
    span_name: str = ""       # span kind: job_spans name

    @property
    def budget(self) -> float:
        return max(1e-6, 1.0 - self.target)


# The fleet's promises, one per plane surface. Latency thresholds are
# chosen to sit on existing histogram bucket bounds (obs/metrics.py)
# so bucket-count arithmetic is exact, and span thresholds reuse the
# QoS starvation bound — the SLO plane must agree with the claim
# scheduler about what "too slow" means.
OBJECTIVES: tuple[Objective, ...] = (
    Objective(
        name="jobs.enqueue_ready",
        plane="jobs",
        description="Jobs reach a terminal state within 30 minutes of "
                    "enqueue (root-span duration)",
        target=0.95, kind="span", span_name="__root__",
        threshold_s=1800.0),
    Objective(
        name="jobs.queue_wait",
        plane="jobs",
        description="Claimable jobs wait under the starvation bound "
                    "before a worker claims them (queue.wait spans)",
        target=0.99, kind="span", span_name="queue.wait",
        threshold_s=config.QOS_STARVATION_S),
    Objective(
        name="jobs.claim_wait",
        plane="jobs",
        description="Enqueue-to-claim wait stays under 10 s across "
                    "tenants (vlog_tenant_claim_wait_seconds)",
        target=0.99, kind="histogram", family="tenant_claim_wait",
        threshold_s=10.0),
    Objective(
        name="asr.throughput",
        plane="asr",
        description="The ASR engine sustains at least 0.5 windows/s "
                    "while batches are flowing",
        target=0.90, kind="gauge", family="asr_windows_per_second",
        low=0.5),
    Objective(
        name="asr.occupancy",
        plane="asr",
        description="ASR batches stay at least half-packed with real "
                    "windows while batches are flowing",
        target=0.90, kind="gauge", family="asr_batch_occupancy",
        low=0.5),
    Objective(
        name="delivery.latency",
        plane="delivery",
        description="Cache fills complete within 250 ms "
                    "(vlog_delivery_fill_seconds, all sources)",
        target=0.99, kind="histogram", family="delivery_fill_seconds",
        threshold_s=0.25),
    Objective(
        name="delivery.errors",
        plane="delivery",
        description="Delivery requests are served, not shed "
                    "(vlog_delivery_requests_total outcome=shed)",
        target=0.999, kind="counter", family="delivery_requests",
        bad_values=("shed",)),
)


# --------------------------------------------------------------------------
# Cumulative (good, total) extraction from the runtime registry
# --------------------------------------------------------------------------

def _collect_samples(metric: Any) -> list:
    try:
        families = list(metric.collect())
    except Exception:   # noqa: BLE001 — noop metrics under no prometheus
        return []
    out = []
    for fam in families:
        out.extend(getattr(fam, "samples", ()))
    return out


def _histogram_cum(metric: Any, threshold_s: float) -> tuple[float, float]:
    """(good, total) from cumulative bucket counts across all label
    sets: good = observations in buckets with le >= threshold (the
    first bound at or above the requested threshold), total = +Inf."""
    good = total = 0.0
    best_le: float | None = None
    buckets: list[tuple[float, float]] = []
    for s in _collect_samples(metric):
        if not s.name.endswith("_bucket"):
            continue
        le = s.labels.get("le", "")
        if le in ("+Inf", "inf"):
            total += s.value
            continue
        try:
            bound = float(le)
        except ValueError:
            continue
        buckets.append((bound, s.value))
        if bound >= threshold_s and (best_le is None or bound < best_le):
            best_le = bound
    if best_le is None:       # threshold above every finite bucket
        return total, total
    good = sum(v for bound, v in buckets if bound == best_le)
    return good, total


def _counter_cum(metric: Any, bad_values: tuple[str, ...]) \
        -> tuple[float, float]:
    """(good, total) from a labeled counter: any first-label value in
    ``bad_values`` is a failure."""
    bad = total = 0.0
    for s in _collect_samples(metric):
        if not s.name.endswith("_total"):
            continue
        total += s.value
        if any(v in bad_values for v in s.labels.values()):
            bad += s.value
    return total - bad, total


# --------------------------------------------------------------------------
# The plane
# --------------------------------------------------------------------------

@dataclass
class Exemplar:
    objective: str
    trace_id: str
    job_id: int | None
    value_s: float
    at: float
    attrs: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"objective": self.objective, "trace_id": self.trace_id,
                "job_id": self.job_id, "value_s": round(self.value_s, 3),
                "at": self.at, "attrs": self.attrs}


def _exemplar_ring() -> "deque[Exemplar]":
    return deque(maxlen=config.SLO_EXEMPLARS)


class SloPlane:
    """Snapshot ring + evaluation; one per process (see :func:`plane`)."""

    def __init__(self, objectives: tuple[Objective, ...] = OBJECTIVES):
        self.objectives = objectives
        self._lock = threading.Lock()             # lock-order: 38
        # ring of (wall_time, {objective: (good_cum, total_cum)});
        # guarded-by: _lock
        self._ring: deque[tuple[float, dict[str, tuple[float, float]]]] = \
            deque()
        # bounded exemplar ring (maxlen=config.SLO_EXEMPLARS)
        self._exemplars: deque[Exemplar] = _exemplar_ring()  # guarded-by: _lock
        self._exemplar_seen: deque[str] = deque(maxlen=256)
        self._last_report: dict | None = None     # guarded-by: _lock
        # gauge kinds accumulate their own good/total tick counts so
        # they window exactly like cumulative counters
        self._gauge_counts: dict[str, tuple[float, float]] = {}

    # ---- sampling ----------------------------------------------------

    def _registry_cum(self) -> dict[str, tuple[float, float]]:
        from vlog_tpu.obs.metrics import runtime

        reg = runtime()
        out: dict[str, tuple[float, float]] = {}
        for obj in self.objectives:
            metric = getattr(reg, obj.family, None) if obj.family else None
            if obj.kind == "histogram":
                out[obj.name] = _histogram_cum(metric, obj.threshold_s)
            elif obj.kind == "counter":
                out[obj.name] = _counter_cum(metric, obj.bad_values)
            elif obj.kind == "gauge":
                good, total = self._gauge_counts.get(obj.name, (0.0, 0.0))
                value = self._gauge_value(metric)
                # value 0.0 = no batch has flowed (gauges are
                # last-batch observations) — vacuously good, skip
                if value is not None and value > 0.0:
                    total += 1.0
                    if obj.low is None or value >= obj.low:
                        good += 1.0
                self._gauge_counts[obj.name] = (good, total)
                out[obj.name] = (good, total)
        return out

    @staticmethod
    def _gauge_value(metric: Any) -> float | None:
        for s in _collect_samples(metric):
            return float(s.value)
        return None

    def tick(self) -> None:
        """Take one cumulative snapshot (sync; registry only)."""
        cum = self._registry_cum()
        now = time.time()
        keep_after = now - config.SLO_SLOW_WINDOW_S - 2 * max(
            1.0, config.SLO_EVAL_S)
        with self._lock:
            self._ring.append((now, cum))
            while self._ring and self._ring[0][0] < keep_after:
                self._ring.popleft()
            while len(self._ring) > 512:
                self._ring.popleft()

    def _window_delta(self, name: str, now: float, window_s: float) \
            -> tuple[float, float, float]:
        """(good_delta, total_delta, actual_window_s) vs the snapshot
        closest to ``now - window_s`` (oldest available if none that
        old — a fresh process reports over its own lifetime)."""
        with self._lock:
            ring = list(self._ring)
        if not ring:
            return 0.0, 0.0, 0.0
        cutoff = now - window_s
        base_t, base = ring[0]
        for t, cum in ring:
            if t <= cutoff:
                base_t, base = t, cum
            else:
                break
        cur = ring[-1][1]
        g0, t0 = base.get(name, (0.0, 0.0))
        g1, t1 = cur.get(name, (0.0, 0.0))
        # registry restarts (tests resetting the singleton) read as
        # negative deltas; clamp to the current cumulative value
        dg, dt = g1 - g0, t1 - t0
        if dt < 0 or dg < 0:
            dg, dt = g1, t1
        return dg, dt, max(0.0, now - base_t)

    # ---- span-kind SQL -----------------------------------------------

    async def _span_window(self, db: Any, obj: Objective, now: float,
                           window_s: float) -> tuple[float, float]:
        """(good, total) for a span objective over one SQL window.
        ``__root__`` selects root spans (parent IS NULL) — the
        enqueue→terminal duration close_root stamps."""
        if obj.span_name == "__root__":
            where = "parent_id IS NULL"
            params: dict = {}
        else:
            where = "name = :name"
            params = {"name": obj.span_name}
        row = await db.fetch_one(
            f"""
            SELECT COUNT(*) AS total,
                   SUM(CASE WHEN duration_s <= :thr THEN 1 ELSE 0 END)
                       AS good
            FROM job_spans
            WHERE {where} AND duration_s IS NOT NULL
              AND started_at > :cut
            """,
            {**params, "thr": obj.threshold_s, "cut": now - window_s})
        total = float(row["total"] or 0)
        good = float(row["good"] or 0)
        return good, total

    async def _capture_exemplars(self, db: Any, obj: Objective,
                                 now: float) -> None:
        """Pull a few slow outliers (rows over the threshold) into the
        bounded ring; each links to /api/jobs/{id}/trace."""
        if obj.span_name == "__root__":
            where = "parent_id IS NULL"
            params: dict = {}
        else:
            where = "name = :name"
            params = {"name": obj.span_name}
        rows = await db.fetch_all(
            f"""
            SELECT trace_id, job_id, duration_s, started_at, attributes
            FROM job_spans
            WHERE {where} AND duration_s > :thr
              AND started_at > :cut
            ORDER BY duration_s DESC LIMIT 4
            """,
            {**params, "thr": obj.threshold_s,
             "cut": now - config.SLO_FAST_WINDOW_S})
        from vlog_tpu.obs.metrics import runtime
        import json as _json

        for r in rows:
            key = f"{obj.name}:{r['trace_id']}"
            with self._lock:
                if key in self._exemplar_seen:
                    continue
                self._exemplar_seen.append(key)
                try:
                    attrs = _json.loads(r["attributes"] or "{}")
                except ValueError:
                    attrs = {}
                self._exemplars.append(Exemplar(
                    objective=obj.name, trace_id=r["trace_id"],
                    job_id=r["job_id"], value_s=float(r["duration_s"]),
                    at=float(r["started_at"]), attrs=attrs))
            runtime().slo_exemplars.labels(obj.name).inc()

    # ---- evaluation --------------------------------------------------

    async def evaluate(self, db: Any) -> dict:
        """One full evaluation: tick, window every objective, export
        the vlog_slo_* gauges, and return the report dict
        (``GET /api/slo``'s body)."""
        from vlog_tpu.obs.metrics import runtime

        self.tick()
        reg = runtime()
        now = time.time()
        out = []
        for obj in self.objectives:
            per_window: dict[str, dict] = {}
            alerting = True
            for window in WINDOWS:
                w = _window_s(window)
                if obj.kind == "span":
                    try:
                        good, total = await self._span_window(
                            db, obj, now, w)
                    except Exception:   # noqa: BLE001 — table may not
                        # exist yet on an embedder's partial schema
                        log.debug("span window failed for %s",
                                  obj.name, exc_info=True)
                        good = total = 0.0
                    actual_w = w
                else:
                    good, total, actual_w = self._window_delta(
                        obj.name, now, w)
                err = (1.0 - good / total) if total > 0 else 0.0
                burn = err / obj.budget
                per_window[window] = {
                    "window_s": w,
                    "observed_window_s": round(actual_w, 1),
                    "events": int(total),
                    "error_ratio": round(err, 6),
                    "burn_rate": round(burn, 4),
                }
                reg.slo_error_ratio.labels(obj.name, window).set(err)
                reg.slo_burn_rate.labels(obj.name, window).set(burn)
                if burn < config.SLO_BURN_ALERT or total <= 0:
                    alerting = False
            reg.slo_alert.labels(obj.name).set(1.0 if alerting else 0.0)
            if obj.kind == "span":
                try:
                    await self._capture_exemplars(db, obj, now)
                except Exception:   # noqa: BLE001 — exemplars are garnish
                    log.debug("exemplar capture failed for %s",
                              obj.name, exc_info=True)
            out.append({
                "name": obj.name,
                "plane": obj.plane,
                "description": obj.description,
                "target": obj.target,
                "kind": obj.kind,
                "threshold_s": obj.threshold_s or None,
                "windows": per_window,
                "alerting": alerting,
            })
        with self._lock:
            exemplars = [e.as_dict() for e in self._exemplars]
        report = {
            "computed_at": now,
            "burn_alert_threshold": config.SLO_BURN_ALERT,
            "windows": {"fast": config.SLO_FAST_WINDOW_S,
                        "slow": config.SLO_SLOW_WINDOW_S},
            "objectives": out,
            "exemplars": exemplars,
        }
        with self._lock:
            self._last_report = report
        return report

    def last_report(self) -> dict | None:
        with self._lock:
            return self._last_report

    def alerting(self) -> list[str]:
        """Objective names alerting as of the last evaluation (sync —
        the scale-hint path must not re-evaluate)."""
        with self._lock:
            report = self._last_report
        if not report:
            return []
        return [o["name"] for o in report["objectives"] if o["alerting"]]


_plane: SloPlane | None = None
_plane_lock = threading.Lock()


def plane() -> SloPlane:
    """The process-wide SLO plane (lazy singleton, runtime() idiom)."""
    global _plane
    if _plane is None:
        with _plane_lock:
            if _plane is None:
                _plane = SloPlane()
    return _plane


def reset_plane() -> None:
    """Test hook: drop the singleton (fresh ring + exemplars)."""
    global _plane
    with _plane_lock:
        _plane = None


def alerting_objectives() -> list[str]:
    """Sync view of alerting objectives for the scale-hint path; never
    raises and never touches the database."""
    try:
        return plane().alerting()
    except Exception:   # noqa: BLE001 — observability must not break qos
        return []


async def eval_loop(db: Any, sink: Any = None) -> None:
    """Background evaluation (admin process): keeps the burn windows
    populated between scrapes and fires one rate-limited webhook per
    alerting objective. ``VLOG_SLO_EVAL_S=0`` disables the loop;
    ``GET /api/slo`` still evaluates on demand."""
    interval = config.SLO_EVAL_S
    if interval <= 0:
        return
    while True:
        await asyncio.sleep(interval)
        try:
            report = await plane().evaluate(db)
            if sink is not None:
                for o in report["objectives"]:
                    if not o["alerting"]:
                        continue
                    fast = o["windows"]["fast"]["burn_rate"]
                    slow = o["windows"]["slow"]["burn_rate"]
                    await sink.send(
                        "slo_burn",
                        f"objective {o['name']} burning error budget at "
                        f"{fast}x (fast) / {slow}x (slow)",
                        {"objective": o["name"], "plane": o["plane"],
                         "target": o["target"],
                         "burn_fast": fast, "burn_slow": slow},
                        key=f"slo_burn:{o['name']}")
        except asyncio.CancelledError:
            raise
        except Exception:   # noqa: BLE001 — the loop must survive
            log.warning("slo evaluation failed", exc_info=True)
