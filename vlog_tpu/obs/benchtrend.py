"""Bench-trend regression gate over the committed BENCH/MULTICHIP files.

Every perf PR appends labeled records to the repo's append-only
trajectories (BENCH_*.json, MULTICHIP*.json); nothing ever re-reads
them, so a regression only surfaces if a human rereads JSON. This
module parses every committed file into one unified trajectory keyed by
``(metric, step, identity-config)``, then flags any series whose latest
gated point fell beyond tolerance below the best prior point (or rose
above it, for lower-is-better metrics).

File shapes handled (all present at HEAD and round-tripped by
tests/test_benchtrend.py so schema drift breaks the tier-1 lane, not
the gate):

- labeled record lists (``[{metric, value|rps|fps|..., step?, config?,
  gate?, platform?}, ...]``) — BENCH_asr/compile/coord/delivery.json,
  MULTICHIP.json;
- one legacy unlabeled first record in BENCH_delivery.json
  ({metric, hot_cache_rps, cold_origin_rps, ...});
- runner wrappers (``{n, cmd, rc, tail, parsed?}`` /
  ``{n_devices, rc, ok, skipped, tail}``) — BENCH_r0N.json,
  MULTICHIP_r0N.json — whose ``parsed`` record and any JSON lines
  embedded in ``tail`` are recovered.

Gating rules:

- records labeled ``gate: tpu_only`` count only when produced on a TPU
  (``platform`` absent or "tpu"); CPU-fallback records (explicit
  ``fallback_reason``, a ``*_cpu_fallback`` metric name, or the
  bench-failed sentinel unit) chart but never gate;
- direction comes from an explicit per-metric table plus name
  heuristics (``*_p99_s``/``*_wait_s``/``*pad_waste*``/``warm_ratio``
  are lower-is-better);
- tolerance is ``VLOG_BENCHTREND_TOL`` (relative, default 0.5 — these
  series mix machines and VM generations, so only large cliffs gate)
  with per-metric overrides, and latencies additionally get an absolute
  floor so microsecond jitter on a sub-ms p99 cannot fail CI.

CLI: ``python -m vlog_tpu.obs.benchtrend [--check] [--root DIR]
[--json]`` — ``--check`` exits 1 on any regression (the tier-1
agreement test runs exactly this against HEAD); bench.py stamps
:func:`summary_line` into every record it emits.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable

from vlog_tpu import config

# metric names where smaller is better; everything else defaults to
# larger-is-better unless a name heuristic (below) says otherwise
_LOWER_IS_BETTER = {
    "compile_cache_warm_ratio",
    "enqueue_to_claim_p99_s",
}
_LOWER_SUFFIXES = ("_p99_s", "_p95_s", "_p50_s", "_wait_s", "_latency_s",
                   "_seconds")
_LOWER_SUBSTRINGS = ("pad_waste", "warm_ratio")

# per-metric relative tolerance overrides (fraction of the best prior
# value the latest may fall short by before gating). The default,
# config.BENCHTREND_TOL, is deliberately loose: the committed series
# span different machines, VM generations, and contended CI hosts.
_TOL_OVERRIDES = {
    # soak numbers swing ~2x run-to-run with cache temperature
    "fabric_soak_rps": 0.75,
    "ram_hit_rps": 0.6,
}

# lower-is-better latencies additionally need an absolute floor: the
# committed enqueue_to_claim_p99_s series is 1.5ms vs 3.1ms — a 2.07x
# "regression" that is pure scheduler jitter. Below the floor, absolute
# values gate instead of ratios.
_ABS_FLOOR_S = 0.05

# config keys that distinguish otherwise same-named series (a batched
# claim at max_jobs=16 is not comparable to max_jobs=8)
_IDENTITY_KEYS = ("max_jobs", "workload", "mesh_shape", "db", "quant",
                  "platform", "devices")
_IDENTITY_TOP_KEYS = ("killed_origin", "platform")

_VALUE_KEYS = ("value", "rps", "fps", "win_x", "speedup_x",
               "realtime_x", "ratio")

_FALLBACK_UNIT = "bench_failed_all_platforms"


@dataclass
class Point:
    """One labeled bench record flattened into the trajectory."""

    file: str
    index: int                      # position within the file
    metric: str
    value: float
    step: str = ""
    unit: str = ""
    timestamp: float = 0.0
    gate: str = ""                  # "" or "tpu_only"
    platform: str = ""              # "" (assume native), "cpu", "tpu"
    fallback: bool = False
    config: dict = field(default_factory=dict)
    raw: dict = field(default_factory=dict)

    @property
    def series_key(self) -> str:
        ident = []
        for k in _IDENTITY_KEYS:
            v = self.config.get(k)
            if v is not None:
                ident.append(f"{k}={v}")
        for k in _IDENTITY_TOP_KEYS:
            v = self.raw.get(k)
            if v is not None:
                ident.append(f"{k}={v}")
        base = f"{self.metric}|{self.step}" if self.step else self.metric
        return f"{base}|{','.join(ident)}" if ident else base

    @property
    def gated(self) -> bool:
        """Does this point participate in regression gating?"""
        if self.fallback:
            return False
        if self.gate == "tpu_only" and self.platform == "cpu":
            return False
        return True


def _is_lower_better(metric: str) -> bool:
    if metric in _LOWER_IS_BETTER:
        return True
    if any(metric.endswith(s) for s in _LOWER_SUFFIXES):
        return True
    return any(s in metric for s in _LOWER_SUBSTRINGS)


def _tolerance(metric: str) -> float:
    return _TOL_OVERRIDES.get(metric, config.BENCHTREND_TOL)


def _record_value(rec: dict) -> float | None:
    for k in _VALUE_KEYS:
        v = rec.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and math.isfinite(float(v)):
            return float(v)
    return None


def _ts(v: Any) -> float:
    """Epoch seconds from a numeric or ISO-8601 timestamp (the
    committed files use ``2026-08-05T03:32:25Z`` strings); 0.0 when
    absent or unparseable (append order then decides)."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    if isinstance(v, str) and v:
        from datetime import datetime

        try:
            return datetime.fromisoformat(v.replace("Z", "+00:00")) \
                .timestamp()
        except ValueError:
            return 0.0
    return 0.0


def _is_fallback(rec: dict) -> bool:
    if rec.get("fallback_reason"):
        return True
    if "cpu_fallback" in str(rec.get("metric", "")):
        return True
    return rec.get("unit") == _FALLBACK_UNIT


def _point_from_record(rec: dict, file: str, index: int) -> Point | None:
    metric = rec.get("metric")
    if not isinstance(metric, str) or not metric:
        return None
    value = _record_value(rec)
    if value is None:
        return None
    cfg = rec.get("config") if isinstance(rec.get("config"), dict) else {}
    return Point(
        file=file, index=index, metric=metric, value=value,
        step=str(rec.get("step", "") or ""),
        unit=str(rec.get("unit", "") or ""),
        timestamp=_ts(rec.get("timestamp")),
        gate=str(rec.get("gate", "") or ""),
        platform=str(rec.get("platform", "")
                     or cfg.get("platform", "") or ""),
        fallback=_is_fallback(rec),
        config=cfg, raw=rec)


def _tail_records(tail: Any) -> Iterable[dict]:
    """Recover labeled JSON-line records embedded in a runner wrapper's
    captured ``tail`` text (BENCH_r02.json carries its result only
    there)."""
    if isinstance(tail, list):
        lines: Iterable[str] = [str(x) for x in tail]
    elif isinstance(tail, str):
        lines = tail.splitlines()
    else:
        return
    for line in lines:
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            yield rec


def parse_file(path: Path, rel: str | None = None) -> list[Point]:
    """Every labeled point recoverable from one committed bench file.
    Unparseable files raise — a corrupt committed trajectory should
    fail the agreement test loudly, not chart as empty."""
    rel = rel or path.name
    data = json.loads(path.read_text())
    points: list[Point] = []
    if isinstance(data, dict):
        # runner wrapper: {n, cmd, rc, tail, parsed?} or
        # {n_devices, rc, ok, skipped, tail}
        recs: list[dict] = []
        if isinstance(data.get("parsed"), dict):
            recs.append(data["parsed"])
        seen = {id(r) for r in recs}
        for rec in _tail_records(data.get("tail")):
            if id(rec) not in seen:
                recs.append(rec)
        # de-dup parsed vs tail copies of the same record
        uniq: list[dict] = []
        for rec in recs:
            if all(rec != u for u in uniq):
                uniq.append(rec)
        for i, rec in enumerate(uniq):
            p = _point_from_record(rec, rel, i)
            if p is not None:
                points.append(p)
        return points
    if not isinstance(data, list):
        raise ValueError(f"{rel}: expected list or wrapper dict, "
                         f"got {type(data).__name__}")
    for i, rec in enumerate(data):
        if not isinstance(rec, dict):
            continue
        p = _point_from_record(rec, rel, i)
        if p is not None:
            points.append(p)
        # legacy multi-facet shape (BENCH_delivery.json record 0):
        # {metric, hot_cache_rps, cold_origin_rps, speedup_x, ...} —
        # additionally expand each named *_rps facet into its own
        # point ("rps" itself is the labeled single-value key)
        metric = rec.get("metric")
        if isinstance(metric, str) and metric and "rps" not in rec:
            for k, v in rec.items():
                if not k.endswith("_rps") or k == "rps":
                    continue
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    points.append(Point(
                        file=rel, index=i, metric=f"{metric}_{k}",
                        value=float(v), timestamp=_ts(rec.get("timestamp")),
                        fallback=_is_fallback(rec), raw=rec))
    return points


def bench_files(root: Path) -> list[Path]:
    return sorted([*root.glob("BENCH_*.json"), *root.glob("MULTICHIP*.json")])


def load_trajectory(root: Path) -> list[Point]:
    points: list[Point] = []
    for path in bench_files(root):
        points.extend(parse_file(path, path.name))
    return points


@dataclass
class Regression:
    series: str
    metric: str
    file: str
    best: float
    latest: float
    ratio: float
    tolerance: float
    lower_is_better: bool

    def describe(self) -> str:
        direction = "rose" if self.lower_is_better else "fell"
        return (f"{self.series} [{self.file}]: latest {self.latest:g} "
                f"{direction} vs best {self.best:g} "
                f"(ratio {self.ratio:.2f}, tolerance {self.tolerance:g})")


def find_regressions(points: list[Point]) -> list[Regression]:
    """Latest gated point of every multi-point series vs the best gated
    prior point, beyond per-metric tolerance."""
    series: dict[str, list[Point]] = {}
    for p in points:
        if p.gated:
            series.setdefault(p.series_key, []).append(p)
    out: list[Regression] = []
    for key, pts in sorted(series.items()):
        if len(pts) < 2:
            continue
        # committed order is append order; fall back to timestamps when
        # a series spans files
        pts = sorted(pts, key=lambda p: (p.timestamp or 0.0, p.file,
                                         p.index))
        latest, prior = pts[-1], pts[:-1]
        lower = _is_lower_better(latest.metric)
        tol = _tolerance(latest.metric)
        if lower:
            best = min(p.value for p in prior)
            if best < _ABS_FLOOR_S and latest.value < _ABS_FLOOR_S:
                continue    # sub-floor latency jitter never gates
            if best <= 0:
                continue
            ratio = latest.value / best
            bad = ratio > 1.0 + tol
        else:
            best = max(p.value for p in prior)
            if best <= 0:
                continue
            ratio = latest.value / best
            bad = ratio < 1.0 - tol
        if bad:
            out.append(Regression(
                series=key, metric=latest.metric, file=latest.file,
                best=best, latest=latest.value, ratio=ratio,
                tolerance=tol, lower_is_better=lower))
    return out


def trend_report(root: Path | str | None = None) -> dict:
    """The full machine-readable report (CLI ``--json`` body)."""
    root = Path(root) if root is not None else _repo_root()
    points = load_trajectory(root)
    regressions = find_regressions(points)
    n_series = len({p.series_key for p in points if p.gated})
    return {
        "root": str(root),
        "files": [p.name for p in bench_files(root)],
        "points": len(points),
        "gated_points": sum(1 for p in points if p.gated),
        "series": n_series,
        "tolerance_default": config.BENCHTREND_TOL,
        "regressions": [vars(r) for r in regressions],
        "ok": not regressions,
    }


def summary_line(root: Path | str | None = None) -> str:
    """One-line trend stamp for bench.py records, e.g.
    ``trend ok: 61 points / 34 series, 0 regressions``. Never raises —
    a bench run must not die because the trend gate can't read a file."""
    try:
        rep = trend_report(root)
    except Exception as exc:   # noqa: BLE001 — stamp is garnish
        return f"trend unavailable: {exc}"
    state = "ok" if rep["ok"] else "REGRESSED"
    return (f"trend {state}: {rep['gated_points']} points / "
            f"{rep['series']} series, {len(rep['regressions'])} "
            f"regressions")


def _repo_root() -> Path:
    """The committed trajectory lives next to bench.py at the repo
    root (two levels up from this package module)."""
    return Path(__file__).resolve().parents[2]


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m vlog_tpu.obs.benchtrend",
        description="Bench-trend regression gate over committed "
                    "BENCH_*.json / MULTICHIP*.json trajectories.")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any series regressed")
    ap.add_argument("--root", default=None,
                    help="directory holding the bench files "
                         "(default: repo root)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the full machine-readable report")
    args = ap.parse_args(argv)
    rep = trend_report(args.root)
    if args.as_json:
        print(json.dumps(rep, indent=2))
    else:
        print(f"{rep['points']} points ({rep['gated_points']} gated) in "
              f"{len(rep['files'])} files, {rep['series']} series")
        for r in rep["regressions"]:
            print("REGRESSION: " + Regression(**r).describe())
        if rep["ok"]:
            print("no regressions beyond tolerance")
    return 1 if (args.check and not rep["ok"]) else 0


if __name__ == "__main__":
    raise SystemExit(main())
