"""Unified metrics: per-app HTTP registry + process-wide runtime registry.

Two registries on purpose:

- :class:`Metrics` — the HTTP-plane families (request counts, claim /
  complete / fail counters, upload integrity counters), one instance
  per aiohttp app so tests get a fresh registry per server. This is the
  class that used to live inside ``api/worker_api.py``; it now also
  carries stage-duration histograms and appends the runtime registry
  when rendering, so one scrape of the server ``/metrics`` sees both.
- :func:`runtime` — ONE registry per process for everything that is not
  an HTTP handler: stage-duration histograms, pipeline overlap gauges,
  circuit-breaker transitions, retry-backoff entries, GC totals, alert
  outcomes, failpoint fires, and worker job-lifecycle counts. The
  worker daemon and remote worker have no HTTP app; this registry is
  what their health server's ``/metrics`` route exposes, and what
  previously write-only surfaces (``AlertMetrics``, ``DaemonStats``,
  ``storage.gc.TOTALS``, ``failpoints.counters()``) now feed.

Scrape cost: the DB-derived gauges in :meth:`Metrics.render` aggregate
in SQL (``GROUP BY`` over the derived-state CASE, jobs/state.py) — one
O(states) query per scrape, never a full-table read into Python — and
the whole DB block is reused for ``VLOG_METRICS_DB_TTL_S`` seconds, so
a tight scrape interval cannot become DB load.
"""

from __future__ import annotations

import threading
import time
from typing import Any

try:
    from prometheus_client import (CollectorRegistry, Counter, Gauge,
                                   Histogram, generate_latest)
    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover — exercised only in minimal envs
    # This module is imported by the whole job plane (claims, workers,
    # CLI); prometheus-client must stay optional there. Without it,
    # metric objects are no-ops and renders are empty — tracing and the
    # job plane work unchanged.
    HAVE_PROMETHEUS = False

    class CollectorRegistry:                       # type: ignore[no-redef]
        def collect(self):
            return []

    class _NoopMetric:
        def __init__(self, *args, **kwargs):
            pass

        def labels(self, *args, **kwargs):
            return self

        def inc(self, *args):
            pass

        def observe(self, *args):
            pass

        def set(self, *args):
            pass

    Counter = Gauge = Histogram = _NoopMetric      # type: ignore[misc]

    def generate_latest(_registry) -> bytes:       # type: ignore[no-redef]
        return b""

from vlog_tpu import config
from vlog_tpu.obs.trace import STAGE_KEYS
from vlog_tpu.utils import failpoints

# Transcode stages run minutes at ladder scale; sub-second buckets catch
# the sprite/transcription tail.
STAGE_BUCKETS = (0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0)

_BREAKER_STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}


class RuntimeMetrics:
    """Process-wide registry (one per process; see :func:`runtime`)."""

    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        self.stage_seconds = Histogram(
            "vlog_stage_duration_seconds",
            "Per-stage busy seconds of one transcode run "
            "(RunResult.stage_s fields)",
            ["stage"], buckets=STAGE_BUCKETS, registry=self.registry)
        self.rung_seconds = Histogram(
            "vlog_rung_duration_seconds",
            "Per-rung consume busy seconds of one transcode run",
            ["rung"], buckets=STAGE_BUCKETS, registry=self.registry)
        # The server's ingested view of worker-REPORTED spans is a
        # separate family from the worker's own observations: a remote
        # run lands in vlog_stage_* on its worker's health port and in
        # vlog_fleet_stage_* on the server, so a Prometheus setup
        # scraping both endpoints never double-counts a run inside one
        # family's sum().
        self.fleet_stage_seconds = Histogram(
            "vlog_fleet_stage_duration_seconds",
            "Per-stage busy seconds ingested from worker span reports",
            ["stage"], buckets=STAGE_BUCKETS, registry=self.registry)
        self.fleet_rung_seconds = Histogram(
            "vlog_fleet_rung_duration_seconds",
            "Per-rung consume busy seconds ingested from worker span reports",
            ["rung"], buckets=STAGE_BUCKETS, registry=self.registry)
        # Lock-sanitizer witness (utils/locktrace.py): per-lock
        # wait/hold profiles, labeled by the static lock-order name.
        # Only populated on sanitized builds (VLOG_LOCK_SANITIZER=1);
        # contention lives well under the transcode-stage scale, so
        # the buckets start at microseconds.
        _lock_buckets = (1e-5, 1e-4, 1e-3, 1e-2, 0.1, 0.5, 2.0, 10.0)
        self.lock_wait_seconds = Histogram(
            "vlog_lock_wait_seconds",
            "Seconds spent waiting to acquire a sanitized lock",
            ["lock"], buckets=_lock_buckets, registry=self.registry)
        self.lock_hold_seconds = Histogram(
            "vlog_lock_hold_seconds",
            "Seconds a sanitized lock was held per acquisition",
            ["lock"], buckets=_lock_buckets, registry=self.registry)
        self.pipeline_gauges = Gauge(
            "vlog_pipeline_gauge",
            "Last run's pipeline overlap gauges (pipeline_depth, "
            "max_in_flight, host_busy_s, host_wall_s, host_occupancy)",
            ["name"], registry=self.registry)
        self.breaker_transitions = Counter(
            "vlog_breaker_transitions_total",
            "Circuit-breaker state transitions", ["state"],
            registry=self.registry)
        self.breaker_state = Gauge(
            "vlog_breaker_state",
            "Current breaker state (0 closed, 1 half-open, 2 open)",
            registry=self.registry)
        self.job_backoff = Counter(
            "vlog_job_backoff_total",
            "Failed attempts stamped with retry backoff (next_retry_at)",
            registry=self.registry)
        self.worker_jobs = Counter(
            "vlog_worker_jobs_total",
            "Worker job lifecycle events (DaemonStats fields)",
            ["event"], registry=self.registry)
        self.gc_runs = Counter(
            "vlog_gc_runs_total", "Orphan-GC sweeps run",
            registry=self.registry)
        self.gc_files_removed = Counter(
            "vlog_gc_files_removed_total", "Entries reclaimed by GC sweeps",
            registry=self.registry)
        self.gc_bytes_reclaimed = Counter(
            "vlog_gc_bytes_reclaimed_total", "Bytes reclaimed by GC sweeps",
            registry=self.registry)
        self.gc_errors = Counter(
            "vlog_gc_errors_total", "Errors hit during GC sweeps",
            registry=self.registry)
        self.alerts = Counter(
            "vlog_alerts_total", "Alert webhook outcomes (AlertMetrics)",
            ["outcome"], registry=self.registry)
        self.failpoint_fires = Counter(
            "vlog_failpoint_fires_total", "Armed failpoint fires by site",
            ["site"], registry=self.registry)
        self.spans_recorded = Counter(
            "vlog_spans_recorded_total", "Spans persisted to job_spans",
            ["origin"], registry=self.registry)
        # Delivery plane (delivery/): origin segment cache + admission.
        self.delivery_requests = Counter(
            "vlog_delivery_requests_total",
            "Delivery-plane media request outcomes "
            "(hit, l2_hit, peer_fill, miss, bypass, shed)",
            ["outcome"], registry=self.registry)
        self.delivery_bytes = Counter(
            "vlog_delivery_bytes_total",
            "Payload bytes produced by the delivery plane, by source "
            "(cache, l2, peer, disk)",
            ["source"], registry=self.registry)
        self.delivery_evictions = Counter(
            "vlog_delivery_evictions_total",
            "Segment-cache entries evicted to stay under the byte budget",
            registry=self.registry)
        self.delivery_collapses = Counter(
            "vlog_delivery_collapses_total",
            "Concurrent same-key misses collapsed onto one disk read",
            registry=self.registry)
        self.delivery_cache_bytes = Gauge(
            "vlog_delivery_cache_bytes",
            "Bytes currently held by the delivery segment cache",
            registry=self.registry)
        self.delivery_inflight_reads = Gauge(
            "vlog_delivery_inflight_reads",
            "Cache-fill disk reads currently in flight",
            registry=self.registry)
        # Distributed delivery tier: disk-backed L2, consistent-hash
        # peer fill, publish-time prewarm (delivery/{l2,ring,plane}.py).
        self.delivery_l2_requests = Counter(
            "vlog_delivery_l2_requests_total",
            "Disk L2 probe outcomes on L1 miss "
            "(hit, miss, corrupt — corrupt entries are deleted and "
            "refilled, never served)",
            ["outcome"], registry=self.registry)
        self.delivery_l2_bytes = Gauge(
            "vlog_delivery_l2_bytes",
            "Bytes currently held by the disk-backed delivery L2",
            registry=self.registry)
        self.delivery_l2_evictions = Counter(
            "vlog_delivery_l2_evictions_total",
            "Disk L2 entries evicted to stay under the byte budget",
            registry=self.registry)
        self.delivery_peer_fills = Counter(
            "vlog_delivery_peer_fills_total",
            "Consistent-hash peer fill outcomes (hit = digest-verified "
            "body from a ring peer; failures classified as transport / "
            "timeout / status / digest — only transport and timeout "
            "feed gossip suspicion, digest quarantines the liar; every "
            "failure degrades the fill to local disk)",
            ["outcome"], registry=self.registry)
        self.delivery_prewarm = Counter(
            "vlog_delivery_prewarm_total",
            "Publish-time prewarm segment outcomes (warmed, error)",
            ["outcome"], registry=self.registry)
        # Self-healing fabric: gossip membership, hedged fills, heat.
        self.delivery_fill_seconds = Histogram(
            "vlog_delivery_fill_seconds",
            "Cache-fill latency by winning source (l2, peer, disk, "
            "bypass) — the reservoir behind the p95-adaptive hedge "
            "budget",
            ["source"],
            buckets=(0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.0, 5.0),
            registry=self.registry)
        self.delivery_hedges = Counter(
            "vlog_delivery_hedges_total",
            "Hedged peer-fill outcomes (launched = primary overran the "
            "hedge budget, win = hedge beat the primary, primary_win = "
            "primary finished first anyway; losers are cancelled and "
            "never cached)",
            ["outcome"], registry=self.registry)
        self.delivery_coalesced_fills = Counter(
            "vlog_delivery_coalesced_fills_total",
            "Cross-origin fill requests (carrying the fill-token "
            "header) that coalesced onto an already-in-flight local "
            "fill — the flash-crowd one-disk-read-fleet-wide proof",
            registry=self.registry)
        self.delivery_gossip_probes = Counter(
            "vlog_delivery_gossip_probes_total",
            "Gossip heartbeat probe outcomes (ok, fail, drop — drop is "
            "the delivery.gossip failpoint eating the heartbeat)",
            ["outcome"], registry=self.registry)
        self.delivery_ring_version = Gauge(
            "vlog_delivery_ring_version",
            "Version of the membership view the delivery ring was last "
            "rebuilt from (bumps on peer death, quarantine, join, "
            "rejoin)",
            registry=self.registry)
        self.delivery_l2_rescues = Counter(
            "vlog_delivery_l2_rescues_total",
            "Disk L2 eviction second-chances granted to entries of hot "
            "slugs (heat-aware eviction spill)",
            registry=self.registry)
        # Mesh job scheduler (parallel/scheduler.py): slot arbitration
        # over the process's device mesh.
        self.mesh_slots = Gauge(
            "vlog_mesh_slots",
            "Configured mesh job slots (VLOG_MESH_SLOTS, clamped to the "
            "device count)",
            registry=self.registry)
        self.mesh_slot_occupancy = Gauge(
            "vlog_mesh_slot_occupancy",
            "Mesh slot leases currently held by running jobs",
            registry=self.registry)
        self.mesh_slot_width = Gauge(
            "vlog_mesh_slot_width",
            "Devices held by each active slot lease (0 = slot free; "
            "slot label \"full\" is the work-conserving full-mesh lease)",
            ["slot"], registry=self.registry)
        self.mesh_slot_wait = Histogram(
            "vlog_mesh_slot_wait_seconds",
            "Seconds a claimed job waited for a mesh slot lease "
            "(queue-wait-for-slot)",
            buckets=(0.001, 0.01, 0.1, 0.5, 2.0, 10.0, 60.0, 300.0),
            registry=self.registry)
        self.ladder_pad_waste = Gauge(
            "vlog_ladder_pad_waste",
            "Padded fraction of the last ladder dispatch's staged frames "
            "(pad_batch rounds batches to the grid's data-axis width; the "
            "2-D (data x rung) layout narrows that width on small batches)",
            registry=self.registry)
        # Fault-domain isolation plane: device quarantine + claim-loop
        # brownout (parallel/scheduler.py, worker/brownout.py).
        self.slot_quarantined = Counter(
            "vlog_slot_quarantined_total",
            "Slot quarantine events (device-fault classified failures "
            "that took the lease's devices out of rotation)",
            ["slot"], registry=self.registry)
        self.device_quarantined = Gauge(
            "vlog_device_quarantined",
            "Devices currently quarantined (awaiting a passing probe)",
            registry=self.registry)
        self.device_probe = Counter(
            "vlog_device_probe_total",
            "Quarantined-device reinstatement probe outcomes",
            ["outcome"], registry=self.registry)
        self.claim_errors = Counter(
            "vlog_claim_errors_total",
            "Transient coordination-plane (DB/API) errors hit by worker "
            "claim loops", ["source"], registry=self.registry)
        self.claim_breaker_open = Gauge(
            "vlog_claim_breaker_open",
            "1 while the worker's coordination-plane brownout breaker "
            "is open", registry=self.registry)
        self.delivery_stale_state = Counter(
            "vlog_delivery_stale_state_total",
            "Publish-state answers served stale because the database "
            "was unavailable (coordination-plane brownout)",
            registry=self.registry)
        # Preemption-tolerant drain plane (worker/drain.py).
        self.worker_draining = Gauge(
            "vlog_worker_draining",
            "1 while this worker is draining (preemption notice, "
            "SIGTERM, or admin drain)", registry=self.registry)
        self.drain_seconds = Histogram(
            "vlog_drain_seconds",
            "Seconds from drain start until every in-flight claim "
            "resolved (completed, flushed + requeued, or released)",
            buckets=(0.5, 2.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0),
            registry=self.registry)
        self.resume_segments_skipped = Counter(
            "vlog_resume_segments_skipped_total",
            "Ladder segments accepted from a verified partial tree by "
            "resume instead of re-encoded (summed across rungs)",
            registry=self.registry)
        # Continuous-batching ASR plane (asr/engine.py): one shared
        # Whisper engine serving every transcription job on the worker.
        self.asr_batches = Counter(
            "vlog_asr_batches_total",
            "Batched decode forwards run by the ASR engine",
            ["result"], registry=self.registry)
        self.asr_windows = Counter(
            "vlog_asr_windows_total",
            "Windows through the ASR plane (decoded = engine forward; "
            "resumed = restored from a checkpoint without re-decoding; "
            "failed = lost to a batch failure)",
            ["result"], registry=self.registry)
        self.asr_batch_occupancy = Gauge(
            "vlog_asr_batch_occupancy",
            "Real windows / batch rows in the last engine batch (1.0 = "
            "perfectly packed)", registry=self.registry)
        self.asr_pad_waste = Gauge(
            "vlog_asr_pad_waste",
            "Zero-padded fraction of the last engine batch's rows",
            registry=self.registry)
        self.asr_windows_per_second = Gauge(
            "vlog_asr_windows_per_second",
            "Decode throughput of the last engine batch",
            registry=self.registry)
        self.asr_queue_wait = Histogram(
            "vlog_asr_queue_wait_seconds",
            "Seconds a window waited in the cross-job queue before its "
            "batch completed",
            buckets=(0.01, 0.05, 0.2, 1.0, 5.0, 20.0, 60.0, 300.0),
            registry=self.registry)
        # Multi-tenant QoS plane (jobs/qos.py, jobs/claims.py): the
        # claim-side wait distribution per tenant — this is the
        # starvation bound's observable (p99 must stay under
        # VLOG_QOS_STARVATION_S) — and the fleet autoscale hint.
        self.tenant_claim_wait = Histogram(
            "vlog_tenant_claim_wait_seconds",
            "Seconds between a job becoming claimable and its claim, "
            "by tenant (enqueue-to-claim wait)",
            ["tenant"],
            buckets=(0.01, 0.1, 0.5, 2.0, 10.0, 30.0, 120.0, 600.0),
            registry=self.registry)
        self.fleet_scale_hint = Gauge(
            "vlog_fleet_scale_hint",
            "Suggested worker-count delta from the fleet snapshot "
            "(positive = scale out; negative = safe to shrink)",
            registry=self.registry)
        # Perf observatory (obs/slo.py, obs/profiler.py): always-on
        # device-time attribution next to the host-occupancy gauges, the
        # SLO burn-rate rollup, and the on-demand profiler's outcomes.
        self.device_seconds = Counter(
            "vlog_device_seconds",
            "Accelerator-attributed busy seconds per batch by plane and "
            "rung (ladder: rung='compute' = shared device compute wait, "
            "rung=<name> = that rung's d2h pull; asr: rung='forward') — "
            "read next to host_busy_s/host_occupancy for the d2h-vs-"
            "compute split",
            ["plane", "rung"], registry=self.registry)
        self.slo_error_ratio = Gauge(
            "vlog_slo_error_ratio",
            "Fraction of an objective's events outside its threshold "
            "over each burn window (0 = budget untouched)",
            ["objective", "window"], registry=self.registry)
        self.slo_burn_rate = Gauge(
            "vlog_slo_burn_rate",
            "Error ratio over the objective's error budget per window "
            "(1.0 = burning budget exactly at the sustainable rate)",
            ["objective", "window"], registry=self.registry)
        self.slo_alert = Gauge(
            "vlog_slo_alert",
            "1 while an objective burns past VLOG_SLO_BURN_ALERT on "
            "BOTH windows (the multi-window page condition)",
            ["objective"], registry=self.registry)
        self.slo_exemplars = Counter(
            "vlog_slo_exemplars_total",
            "Slow-outlier exemplars captured by the SLO plane "
            "(each carries a trace_id resolvable via the job trace API)",
            ["objective"], registry=self.registry)
        self.profile_sessions = Counter(
            "vlog_profile_sessions_total",
            "On-demand device profiler session outcomes "
            "(started, completed, rejected, error)",
            ["outcome"], registry=self.registry)
        # the fires counter must see every fire in the process, wherever
        # the site lives — failpoints stays dependency-free, we observe
        failpoints.add_observer(
            lambda site: self.failpoint_fires.labels(site).inc())

    def observe_run(self, stage_s: dict | None) -> None:
        """Feed one RunResult.stage_s into histograms + overlap gauges."""
        if not stage_s:
            return
        for key, val in stage_s.items():
            try:
                num = float(val)
            except (TypeError, ValueError):
                continue
            if key in STAGE_KEYS:
                self.stage_seconds.labels(key[:-2]).observe(num)
            elif key.startswith("rung_") and key.endswith("_s"):
                self.rung_seconds.labels(key[5:-2]).observe(num)
            else:
                self.pipeline_gauges.labels(key).set(num)

    def observe_breaker(self, state: str) -> None:
        """Record a breaker transition (worker/breaker.py calls this)."""
        self.breaker_transitions.labels(state).inc()
        self.breaker_state.set(_BREAKER_STATE_VALUES.get(state, -1))

    def render_text(self) -> str:
        return generate_latest(self.registry).decode()


_runtime: RuntimeMetrics | None = None
_runtime_lock = threading.Lock()


def runtime() -> RuntimeMetrics:
    """The process-wide runtime registry (lazy singleton)."""
    global _runtime
    if _runtime is None:
        with _runtime_lock:
            if _runtime is None:
                _runtime = RuntimeMetrics()
    return _runtime


class Metrics:
    """HTTP-plane Prometheus registry (one per app, test-safe)."""

    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        self.http_requests = Counter(
            "vlog_http_requests_total", "HTTP requests",
            ["method", "route", "status"], registry=self.registry)
        self.jobs_claimed = Counter(
            "vlog_jobs_claimed_total", "Jobs claimed over HTTP",
            ["kind"], registry=self.registry)
        self.jobs_completed = Counter(
            "vlog_jobs_completed_total", "Jobs completed over HTTP",
            ["kind"], registry=self.registry)
        self.jobs_failed = Counter(
            "vlog_jobs_failed_total", "Job failures reported over HTTP",
            ["kind"], registry=self.registry)
        self.bytes_uploaded = Counter(
            "vlog_upload_bytes_total", "Output bytes uploaded by workers",
            registry=self.registry)
        self.upload_digest_mismatch = Counter(
            "vlog_upload_digest_mismatch_total",
            "Uploads rejected for an X-Content-SHA256 mismatch (422)",
            registry=self.registry)
        self.upload_disk_rejected = Counter(
            "vlog_upload_disk_rejected_total",
            "Uploads rejected under disk pressure (507)",
            registry=self.registry)
        self.manifest_rejects = Counter(
            "vlog_manifest_verify_failures_total",
            "Completions rejected by outputs.json tree verification (422)",
            registry=self.registry)
        # DB-derived gauge block cache (VLOG_METRICS_DB_TTL_S): the
        # GROUP-BYs below are O(states)/O(tenants), but a 1 s scrape
        # interval across several scrapers still multiplies them — the
        # app registry and runtime registry stay live every scrape,
        # only the SQL block is reused inside the TTL.
        self._db_block: str | None = None
        self._db_block_expires = 0.0

    async def render(self, db: Any) -> str:
        """One scrape: app registry + DB gauges + the runtime registry.

        The job-state gauges aggregate in SQL (GROUP BY over the
        derived-state CASE) so scrape cost is O(states), not O(jobs) —
        and the whole DB block is additionally cached for
        ``VLOG_METRICS_DB_TTL_S`` so tight scrape intervals cannot
        become DB load.
        """
        text = generate_latest(self.registry).decode()
        now_mono = time.monotonic()
        if self._db_block is None or now_mono >= self._db_block_expires:
            self._db_block = await self._render_db_block(db)
            self._db_block_expires = now_mono + config.METRICS_DB_TTL_S
        return text + self._db_block + runtime().render_text()

    async def _render_db_block(self, db: Any) -> str:
        """The SQL-derived gauge families of one scrape (cacheable)."""
        # lazy: jobs/claims imports this module, so a module-level
        # jobs.state import would be circular when obs loads first
        from vlog_tpu.db.core import now as db_now
        from vlog_tpu.jobs import state as js

        t = db_now()
        state_rows = await db.fetch_all(
            f"SELECT {js.sql_state_case()} AS state, COUNT(*) AS n "
            "FROM jobs GROUP BY state", {"now": t})
        counts = {r["state"]: int(r["n"] or 0) for r in state_rows}
        lines = ["# HELP vlog_jobs Jobs by derived state",
                 "# TYPE vlog_jobs gauge"]
        for st, n in sorted(counts.items()):
            lines.append(f'vlog_jobs{{state="{st}"}} {n}')
        # flat queue-depth gauge: what the worker HPA scales on
        # (deploy/k8s/worker-autoscaling.yaml) — claimable work only;
        # jobs waiting out retry backoff are deliberately excluded (they
        # cannot be claimed yet, so they must not trigger scale-up)
        queued = (counts.get("unclaimed", 0) + counts.get("retrying", 0)
                  + counts.get("expired", 0))
        lines.append("# HELP vlog_jobs_queued Jobs waiting for a worker")
        lines.append("# TYPE vlog_jobs_queued gauge")
        lines.append(f"vlog_jobs_queued {queued}")
        online = await db.fetch_val(
            "SELECT COUNT(*) FROM workers WHERE last_heartbeat_at > :cut",
            {"cut": t - config.WORKER_OFFLINE_THRESHOLD_S})
        lines.append("# HELP vlog_workers_online Workers with a fresh heartbeat")
        lines.append("# TYPE vlog_workers_online gauge")
        lines.append(f"vlog_workers_online {online or 0}")
        # per-tenant queue pressure: one GROUP BY over tenant (the QoS
        # plane's admission + fair-share inputs, made scrapeable)
        tenant_rows = await db.fetch_all(
            f"""
            SELECT tenant,
                   SUM(CASE WHEN {js.SQL_CLAIMABLE} THEN 1 ELSE 0 END)
                       AS queued,
                   SUM(CASE WHEN {js.SQL_ACTIVELY_CLAIMED} THEN 1 ELSE 0 END)
                       AS inflight
            FROM jobs WHERE {js.SQL_NOT_TERMINAL}
            GROUP BY tenant ORDER BY tenant
            """, {"now": t})
        lines.append("# HELP vlog_tenant_queued Claimable jobs by tenant")
        lines.append("# TYPE vlog_tenant_queued gauge")
        for r in tenant_rows:
            lines.append(f'vlog_tenant_queued{{tenant="{r["tenant"]}"}} '
                         f'{int(r["queued"] or 0)}')
        lines.append("# HELP vlog_tenant_inflight Actively claimed jobs "
                     "by tenant")
        lines.append("# TYPE vlog_tenant_inflight gauge")
        for r in tenant_rows:
            lines.append(f'vlog_tenant_inflight{{tenant="{r["tenant"]}"}} '
                         f'{int(r["inflight"] or 0)}')
        return "\n".join(lines) + "\n"
